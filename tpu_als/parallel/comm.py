"""Gather strategies: full ``all_gather`` vs ring ``ppermute`` streaming.

The reference stack moves factor messages with a sort-based shuffle over
netty TCP (SURVEY.md §2.C2).  The TPU-native replacements (§5.7/§5.8):

- **all_gather** (tpu_als.parallel.trainer): each half-step gathers the full
  opposite factor matrix over ICI.  Simplest and fastest while
  ``N_opposite × rank`` fits per-device HBM.
- **ring** (this module): the opposite factors are never materialized in
  full.  Each device keeps only its own factor shard; shards rotate around
  the mesh with ``ppermute`` while normal-equation accumulators stay
  stationary — the same dataflow as ring attention (stationary queries =
  the accumulators, streaming keys/values = the factor shards).

Peak-HBM model (the reason ring exists, config 3 of BASELINE.json —
rank 256, ~570M ratings on a v5e-32 mesh):

  extra HBM per device = O(row_tile · r²)   (one tile's A accumulators)
                       + O(N_opposite/D · r) (the resident factor shard)

The solved rows are processed in **row tiles**: the ring pass runs once per
tile, so only that tile's ``A [tile, r, r]`` is ever alive — never a
full-shard ``[num_rows, r, r]`` accumulator (at rank 256 and 1M solved
rows/device that naive accumulator would be ~262 GB; a 1024-row tile is
256 MB).  ``trainer_chunk`` bounds ``tile · r · max(w, r)`` by 2²⁸ elements
(1 GiB f32).  The price is communication: each tile re-streams every
opposite shard, so ring traffic = n_tiles × one all_gather's bytes — a
deliberate HBM-for-ICI trade; ICI bandwidth is the cheap resource and the
``ppermute`` chain overlaps with each tile's einsum work.

Data layout: ratings are blocked on a 2-D (owner device × source shard)
grid — the TPU analog of Spark's ``numUserBlocks × numItemBlocks`` rating
grid — with column ids local to the source shard, so each ring step's
gather indexes only the currently-held shard.  Crucially all S source
shards share ONE row position per entity (bucketing by max-per-source
degree), so a row tile accumulates coherently across the whole ring pass.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tpu_als.core.ratings import (
    Bucket,
    entity_widths,
    scan_chunk,
    trainer_chunk,
)
from tpu_als.ops.ring_buffer import prefetch_stream, rotate_stream
from tpu_als.ops.solve import solve_cg, solve_nnls, solve_spd
from tpu_als.parallel.mesh import AXIS


@dataclass
class RingCsr:
    """Bucketed (owner device × source shard) grid for one side.

    Bucket arrays: rows [D, nb] (entity per row — shared across source
    shards), cols/vals/mask [D, S, nb, w] (shard-local column ids).
    """

    buckets: list  # list[Bucket]
    rows_per_shard: int
    chunk_elems: int
    nnz: int
    # None = full grid; a tuple = this process's mesh positions only
    # (multi-host: blocking is replicated, placement is local — the grid
    # exists to bound device HBM, not host memory)
    positions: tuple = None

    def device_buckets(self):
        return list(self.buckets)

    @property
    def padded_nnz(self):
        return sum(b.mask.size for b in self.buckets)

    def local_slice(self, positions):
        """This process's owner rows of the grid, for
        ``jax.make_array_from_process_local_data`` assembly (leading axis
        ``len(positions)``, in the given order)."""
        pos = list(positions)
        return RingCsr(
            buckets=[Bucket(rows=b.rows[pos], cols=b.cols[pos],
                            vals=b.vals[pos], mask=b.mask[pos])
                     for b in self.buckets],
            rows_per_shard=self.rows_per_shard,
            chunk_elems=self.chunk_elems,
            nnz=self.nnz,
            positions=tuple(pos),
        )


def shard_csr_grid(row_part, col_part, row_idx, col_idx, vals,
                   min_width=8, chunk_elems=1 << 19, positions=None):
    """Build the grid with a row space SHARED across source shards.

    Every source shard stores entity u's ratings at the same (bucket, row)
    position — required by the row-tiled ring pass, which accumulates one
    tile's normal equations from all S shards before solving it.  Entities
    are bucketed by their **max-per-source** degree (each shard's slice of
    a row pads to that bucket's width), trading some extra padding for the
    tile-coherent layout.

    ``positions``: allocate and fill ONLY these owner devices' grid rows
    (multi-host — the layout itself is still computed globally so every
    host agrees on shapes; grid HBM/host memory drops D/len(positions)×).
    The result equals slicing a full build at ``positions``.
    """
    D = row_part.n_shards
    S = col_part.n_shards
    row_idx = np.asarray(row_idx)
    col_idx = np.asarray(col_idx)
    vals = np.asarray(vals, dtype=np.float32)
    owner = row_part.owner[row_idx].astype(np.int64)
    local_rows = row_part.local[row_idx].astype(np.int64)
    src = col_part.owner[col_idx].astype(np.int64)
    local_cols = col_part.local[col_idx].astype(np.int64)
    num_rows = row_part.rows_per_shard
    n = len(row_idx)

    # per-entry offset within its (owner, row, source-shard) group
    key = (owner * num_rows + local_rows) * S + src
    order = np.argsort(key, kind="stable")
    uniq_k, starts, kcounts = np.unique(
        key[order], return_index=True, return_counts=True)
    off = np.arange(n) - starts[np.repeat(np.arange(len(uniq_k)), kcounts)]

    # bucket width per (device, entity): max degree over source shards
    k_du = uniq_k // S
    maxdeg = np.zeros(D * num_rows, dtype=np.int64)
    np.maximum.at(maxdeg, k_du, kcounts)
    rated = np.zeros(D * num_rows, dtype=bool)
    rated[k_du] = True
    widths_all = entity_widths(maxdeg, min_width)

    bucket_widths = sorted(set(widths_all[rated].tolist()))
    local_pos = np.full(D * num_rows, -1, dtype=np.int64)
    nb_pads = []
    selections = {}  # (w, d) -> row indices, reused by the fill loop below
    for w in bucket_widths:
        nb_need = 0
        for d in range(D):
            lo = d * num_rows
            sel = np.flatnonzero(
                rated[lo:lo + num_rows]
                & (widths_all[lo:lo + num_rows] == w))
            selections[w, d] = sel
            local_pos[lo + sel] = np.arange(len(sel))
            nb_need = max(nb_need, len(sel))
        chunk = scan_chunk(nb_need, w, chunk_elems)
        nb_pads.append(-(-nb_need // chunk) * chunk)

    e_owner = owner[order]
    e_rows = local_rows[order]
    e_src = src[order]
    e_cols = local_cols[order]
    e_vals = vals[order]
    flat = e_owner * num_rows + e_rows
    e_w = widths_all[flat]
    e_pos = local_pos[flat]

    local = positions is not None
    pos_list = list(positions) if local else list(range(D))
    L = len(pos_list)
    # owner device id -> leading-axis index (or -1 for remote owners)
    owner_to_li = np.full(D, -1, dtype=np.int64)
    owner_to_li[pos_list] = np.arange(L)

    buckets = []
    for w, nb in zip(bucket_widths, nb_pads):
        rows = np.full((L, nb), num_rows, dtype=np.int32)
        for li, d in enumerate(pos_list):
            sel = selections[w, d]
            rows[li, :len(sel)] = sel
        cols = np.zeros((L, S, nb, w), dtype=np.int32)
        v = np.zeros((L, S, nb, w), dtype=np.float32)
        m = np.zeros((L, S, nb, w), dtype=np.float32)
        esel = (e_w == w) & (owner_to_li[e_owner] >= 0)
        dd = owner_to_li[e_owner[esel]]
        ss = e_src[esel]
        pp, oo = e_pos[esel], off[esel]
        cols[dd, ss, pp, oo] = e_cols[esel]
        v[dd, ss, pp, oo] = e_vals[esel]
        m[dd, ss, pp, oo] = 1.0
        buckets.append(Bucket(rows=rows, cols=cols, vals=v, mask=m))
    return RingCsr(buckets=buckets, rows_per_shard=num_rows,
                   chunk_elems=chunk_elems, nnz=n,
                   positions=tuple(pos_list) if local else None)


def ring_fused_half_step(V_shard, ring_buckets, num_rows, n_shards, cfg,
                         YtY=None, interpret=False):
    """One half-step as ONE Pallas kernel call per bucket (inside
    ``shard_map``): ``solve_backend='gather_fused_ring'`` moves the ring
    rotation itself into the whole-iteration fused kernel — the factor
    shard streams to the right neighbor via ``make_async_remote_copy``
    INSIDE the kernel, tile-by-tile into the same HBM landing buffers
    that feed the gather/Gram/solve panels, overlapped with the compute
    (tpu_als.ops.pallas_gather_ne.gather_solve_ring).  No ``ppermute``
    traces; no per-tile XLA loop (the kernel grid does the row tiling);
    the per-row counts come from the in-kernel ``cw`` accumulation, so no
    ``counts`` lookup either.  Off-TPU pass ``interpret=True`` — the
    forced-host-device CPU mesh runs the identical schedule.

    Solver precedence matches ``ring_half_step``'s tail (AlsConfig doc:
    nonnegative > forced fused backends > cg): the CALLER routes
    ``cfg.nonnegative`` to the XLA ring before dispatching here.
    """
    from tpu_als.ops.pallas_gather_ne import (
        gather_fused_ring_explicit,
        gather_fused_ring_implicit,
    )

    r = V_shard.shape[-1]
    cdt = jnp.dtype(cfg.compute_dtype)
    V_c = V_shard.astype(cdt)
    out = jnp.zeros((num_rows, r), dtype=jnp.float32)
    for b in ring_buckets:
        with jax.named_scope("gather_fused_ring"):
            if cfg.implicit_prefs:
                x = gather_fused_ring_implicit(
                    V_c, b.cols, b.vals.astype(cdt), b.mask.astype(cdt),
                    cfg.reg_param, cfg.alpha, YtY.astype(jnp.float32),
                    axis_name=AXIS, jitter=cfg.jitter,
                    interpret=interpret)
            else:
                x = gather_fused_ring_explicit(
                    V_c, b.cols, b.vals.astype(cdt), b.mask.astype(cdt),
                    cfg.reg_param, axis_name=AXIS, jitter=cfg.jitter,
                    interpret=interpret)
        out = out.at[b.rows].set(x, mode="drop", unique_indices=True)
    return out


def ring_half_step(V_shard, ring_buckets, counts, num_rows, n_shards, cfg,
                   chunk_elems, YtY=None, prev=None, overlap=False,
                   fused=False, interpret=False):
    """One half-step with streaming factor shards (inside ``shard_map``).

    V_shard [per_opposite, r]: this device's shard of the opposite factors.
    ring_buckets: this device's slice of a RingCsr — rows [nb],
    cols/vals/mask [S, nb, w].
    counts [num_rows]: per-row rating counts (for the λ·n ridge; for
    implicit feedback, the positive-rating counts).
    prev [num_rows, r]: the solved side's current local factors — the CG
    warm start when ``cfg.cg_iters > 0``.

    Rows are processed in tiles (``trainer_chunk``): per tile, one full
    ring pass of ``n_shards`` ppermute rotations accumulates
    ``A [tile, r, r]`` / ``b [tile, r]``, then the tile is solved and
    scattered.  Each pass performs all ``n_shards`` rotations, so the
    factor shard is back home when the next tile starts.  See the module
    docstring for the peak-HBM model this enforces.

    ``overlap=True`` double-buffers the rotation: the ``ppermute`` sending
    shard k+1 is issued *before* shard k's normal-equation contribution is
    accumulated, so XLA's latency-hiding scheduler can keep one async
    collective-permute in flight under the einsum.  The extra cost is one
    shard-sized buffer (the in-flight slot); bytes moved, rotation count
    and numerics are identical to ``overlap=False`` — both variants'
    traffic is modeled by the same ``comm_bytes_per_iter('ring', ...)``
    closed form and verified against the traced jaxpr in
    tests/test_comm_audit.py.

    ``fused=True`` dispatches to :func:`ring_fused_half_step` — the
    in-kernel remote-DMA ring (``solve_backend='gather_fused_ring'``) —
    unless ``cfg.nonnegative`` demands the NNLS sweep tail, which has no
    fused kernel (same precedence rule as the local path).  The caller
    (``trainer.make_ring_step``) decides ``fused`` at build time from the
    knob + availability probe; ``interpret`` follows ``not on_tpu()``.
    """
    if fused and not cfg.nonnegative:
        return ring_fused_half_step(V_shard, ring_buckets, num_rows,
                                    n_shards, cfg, YtY=YtY,
                                    interpret=interpret)
    r = V_shard.shape[-1]
    cdt = jnp.dtype(cfg.compute_dtype)
    me = jax.lax.axis_index(AXIS)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    eye = jnp.eye(r, dtype=jnp.float32)
    out = jnp.zeros((num_rows, r), dtype=jnp.float32)

    def rotate(V_c):
        # the substrate's in-flight slot (overlap=True): rotate_stream
        # issues this permute for shard t+1 BEFORE shard t's accumulate,
        # so XLA's latency-hiding scheduler keeps one async
        # collective-permute under the einsum (V_c stays readable, the
        # permute result is the in-flight slot)
        if overlap:
            with jax.named_scope("ring_prefetch"):
                return jax.lax.ppermute(V_c, AXIS, perm)
        return jax.lax.ppermute(V_c, AXIS, perm)

    def tile_pass(V_c, rows, cols, vals, mask):
        """rows [tile]; cols/vals/mask [S, tile, w] -> (V_c, x [tile, r])"""
        tile = rows.shape[0]

        def accumulate(t, V_c, carry):
            A, bb = carry
            src = (me - t) % n_shards  # shard held after t rotations
            with jax.named_scope("ring_gather"):
                c = jax.lax.dynamic_index_in_dim(cols, src, 0, False)
                v = jax.lax.dynamic_index_in_dim(vals, src, 0, False)
                m = jax.lax.dynamic_index_in_dim(mask, src, 0, False)
                Vg = V_c[c].astype(cdt)
            with jax.named_scope("ring_normal_eq"):
                if cfg.implicit_prefs:
                    conf_m1 = cfg.alpha * jnp.abs(v) * m
                    pref = (v > 0).astype(cdt)
                    A = A + jnp.einsum(
                        "nw,nwr,nws->nrs", conf_m1.astype(cdt), Vg, Vg,
                        preferred_element_type=jnp.float32)
                    bb = bb + jnp.einsum(
                        "nw,nwr->nr",
                        ((1.0 + conf_m1) * pref * m).astype(cdt), Vg,
                        preferred_element_type=jnp.float32)
                else:
                    Vm = Vg * m[..., None].astype(cdt)
                    A = A + jnp.einsum(
                        "nwr,nws->nrs", Vm, Vm,
                        preferred_element_type=jnp.float32)
                    bb = bb + jnp.einsum(
                        "nw,nwr->nr", (v * m).astype(cdt), Vg,
                        preferred_element_type=jnp.float32)
            return A, bb

        # rotate every step: after n_shards rotations the shard is home
        V_c, (A, bb) = rotate_stream(
            n_shards, rotate, accumulate, V_c,
            (jnp.zeros((tile, r, r), dtype=jnp.float32),
             jnp.zeros((tile, r), dtype=jnp.float32)),
            overlap=overlap)
        # padding rows (rows == num_rows) read an arbitrary count; their
        # b is 0 so x solves to 0 and the scatter drops them anyway
        cnt = counts[jnp.clip(rows, 0, num_rows - 1)]
        A = A + (cfg.reg_param * cnt)[:, None, None] * eye
        if cfg.implicit_prefs:
            A = A + YtY[None]
        with jax.named_scope("ring_solve"):
            if cfg.nonnegative:
                x = solve_nnls(A, bb, cnt, sweeps=cfg.nnls_sweeps,
                               jitter=cfg.jitter)
            elif (cfg.cg_iters > 0
                  and cfg.solve_backend not in ("gather_fused_solve",
                                                "gather_fused_ring")):
                # same precedence as local_half_step (AlsConfig doc:
                # nonnegative > forced fused backends > cg) so one config
                # means one solver across every gatherStrategy; when the
                # forced fusion cannot run here (no availability probe
                # pass — ``fused=False`` above) it degrades to the exact
                # solve, never to cg
                x0 = (prev[jnp.clip(rows, 0, num_rows - 1)]
                      if prev is not None else None)
                x = solve_cg(A, bb, cnt, x0=x0, iters=cfg.cg_iters,
                             jitter=cfg.jitter)
            else:
                x = solve_spd(A, bb, cnt, jitter=cfg.jitter,
                              adaptive=cfg.adaptive_solve)
        return V_c, x

    for b in ring_buckets:
        S, nb, w = b.cols.shape
        tile = trainer_chunk(nb, w, r, chunk_elems)
        ntiles = nb // tile
        if ntiles == 1:
            V_shard, x = tile_pass(V_shard, b.rows, b.cols, b.vals, b.mask)
            out = out.at[b.rows].set(x, mode="drop", unique_indices=True)
        else:
            def body(ti, carry, b=b, tile=tile):
                V_c, out = carry
                s0 = ti * tile
                rows = jax.lax.dynamic_slice_in_dim(b.rows, s0, tile, 0)
                cols = jax.lax.dynamic_slice_in_dim(b.cols, s0, tile, 1)
                vals = jax.lax.dynamic_slice_in_dim(b.vals, s0, tile, 1)
                mask = jax.lax.dynamic_slice_in_dim(b.mask, s0, tile, 1)
                V_c, x = tile_pass(V_c, rows, cols, vals, mask)
                out = out.at[rows].set(x, mode="drop", unique_indices=True)
                return (V_c, out)

            V_shard, out = jax.lax.fori_loop(
                0, ntiles, body, (V_shard, out))
    return out


def gather_block_plan(per, n_blocks):
    """Column-block decomposition of a ``rows_per_shard``-row factor shard.

    Returns ``(sub, starts, widths)``: block c covers local rows
    ``[starts[c], starts[c] + widths[c])`` of every device's shard;
    ``sub = ceil(per / n_blocks)`` and the last block may be ragged, so
    any ``1 <= n_blocks`` works for any ``per`` and the blocks always
    partition the shard exactly (``sum(widths) == per`` — the byte model
    depends on this)."""
    per = int(per)
    sub = -(-per // max(1, int(n_blocks)))
    starts = list(range(0, per, sub))
    widths = [min(sub, per - s) for s in starts]
    return sub, starts, widths


def chunked_gather_half_step(V_shard, buckets, num_rows, n_shards, cfg,
                             chunk_elems, n_blocks=4, YtY=None, prev=None):
    """One half-step gathering the opposite factors in column blocks
    (inside ``shard_map``) — the streamed variant of the plain
    ``all_gather`` strategy.

    V_shard [per, r]: this device's shard of the opposite factors.
    buckets: this device's slice of a ShardedCsr — rows [nb],
    cols/vals/mask [nb, w] with cols in GLOBAL SLOT space
    (``slot = owner * per + local``), i.e. the same containers the plain
    all_gather step consumes.

    Instead of materializing the full ``[D·per, r]`` opposite table, each
    row tile runs a static loop over ``n_blocks`` column blocks: block c
    is ``all_gather(V_shard[start_c : start_c+w_c])`` — a ``[D·w_c, r]``
    slice of the table — and only the entries whose column falls in that
    block contribute to the tile's normal equations.  The blocks
    partition the slot space exactly, so A/b/count accumulate to the same
    sums as the one-shot gather (within f32 reduction order), while peak
    HBM drops from ``D·per·r`` to ``row_tile·r² + 2·D·ceil(per/C)·r``
    (the live block plus one in flight) — this is what unlocks rank-256
    all_gather layouts that BASELINE's HBM table rules out today.

    Double buffering: block c+1's ``all_gather`` is issued before block
    c's einsum, keeping one async gather in flight under the compute.
    Per tile pass the gathers move ``(D−1)·per·r·4`` bytes — identical to
    one full all_gather — so total traffic is that times the row-tile
    count (``comm_bytes_per_iter('all_gather_chunked', ...)``; traced
    jaxpr equality in tests/test_comm_audit.py).

    Ridge/YtY/solver-precedence semantics mirror ``ring_half_step``: the
    per-row count is accumulated in-step from the mask (explicit: rated
    entries; implicit: positive entries), then ``A += λ·count·I`` (+YtY
    implicit) and nonnegative > cg > exact solve with ``prev`` as the CG
    warm start.
    """
    r = V_shard.shape[-1]
    per = V_shard.shape[0]
    cdt = jnp.dtype(cfg.compute_dtype)
    eye = jnp.eye(r, dtype=jnp.float32)
    out = jnp.zeros((num_rows, r), dtype=jnp.float32)
    sub, starts, widths = gather_block_plan(per, n_blocks)
    C = len(starts)

    def gather_block(c):
        with jax.named_scope("gchunk_gather"):
            blk = jax.lax.slice_in_dim(
                V_shard, starts[c], starts[c] + widths[c], axis=0)
            # tiled gather is device-major: slot (d, l) of block c lands
            # at row d*widths[c] + (l - starts[c])
            return jax.lax.all_gather(blk, AXIS, axis=0, tiled=True)

    def tile_pass(rows, cols, vals, mask):
        """rows [tile]; cols/vals/mask [tile, w] -> x [tile, r]"""
        tile = rows.shape[0]
        d = cols // per
        loc = cols % per
        # ragged last block: every local row >= starts[-1] belongs to it
        blkid = jnp.minimum(loc // sub, C - 1)

        def accumulate(c, G, carry):
            A, bb, cnt = carry
            m_c = mask * (blkid == c)
            # clip keeps masked-out entries' indices in bounds; their
            # contribution is zeroed by m_c
            idx = jnp.clip(d * widths[c] + (loc - starts[c]),
                           0, n_shards * widths[c] - 1)
            with jax.named_scope("gchunk_normal_eq"):
                Vg = G[idx].astype(cdt)
                if cfg.implicit_prefs:
                    conf_m1 = cfg.alpha * jnp.abs(vals) * m_c
                    pref = (vals > 0).astype(cdt)
                    A = A + jnp.einsum(
                        "nw,nwr,nws->nrs", conf_m1.astype(cdt), Vg, Vg,
                        preferred_element_type=jnp.float32)
                    bb = bb + jnp.einsum(
                        "nw,nwr->nr",
                        ((1.0 + conf_m1) * pref * m_c).astype(cdt), Vg,
                        preferred_element_type=jnp.float32)
                    cnt = cnt + ((vals > 0) * m_c).sum(axis=-1)
                else:
                    Vm = Vg * m_c[..., None].astype(cdt)
                    A = A + jnp.einsum(
                        "nwr,nws->nrs", Vm, Vm,
                        preferred_element_type=jnp.float32)
                    bb = bb + jnp.einsum(
                        "nw,nwr->nr", (vals * m_c).astype(cdt), Vg,
                        preferred_element_type=jnp.float32)
                    cnt = cnt + m_c.sum(axis=-1)
            return A, bb, cnt

        # block c+1's all_gather goes in flight under block c's einsum —
        # the substrate's indexed-prefetch schedule
        A, bb, cnt = prefetch_stream(
            C, gather_block, accumulate,
            (jnp.zeros((tile, r, r), dtype=jnp.float32),
             jnp.zeros((tile, r), dtype=jnp.float32),
             jnp.zeros((tile,), dtype=jnp.float32)))
        A = A + (cfg.reg_param * cnt)[:, None, None] * eye
        if cfg.implicit_prefs:
            A = A + YtY[None]
        with jax.named_scope("gchunk_solve"):
            if cfg.nonnegative:
                x = solve_nnls(A, bb, cnt, sweeps=cfg.nnls_sweeps,
                               jitter=cfg.jitter)
            elif (cfg.cg_iters > 0
                  and cfg.solve_backend not in ("gather_fused_solve",
                                                "gather_fused_ring")):
                x0 = (prev[jnp.clip(rows, 0, num_rows - 1)]
                      if prev is not None else None)
                x = solve_cg(A, bb, cnt, x0=x0, iters=cfg.cg_iters,
                             jitter=cfg.jitter)
            else:
                x = solve_spd(A, bb, cnt, jitter=cfg.jitter,
                              adaptive=cfg.adaptive_solve)
        return x

    for b in buckets:
        nb, w = b.cols.shape
        tile = trainer_chunk(nb, w, r, chunk_elems)
        ntiles = nb // tile
        if ntiles == 1:
            x = tile_pass(b.rows, b.cols, b.vals, b.mask)
            out = out.at[b.rows].set(x, mode="drop", unique_indices=True)
        else:
            def body(ti, out, b=b, tile=tile):
                s0 = ti * tile
                rows = jax.lax.dynamic_slice_in_dim(b.rows, s0, tile, 0)
                cols = jax.lax.dynamic_slice_in_dim(b.cols, s0, tile, 0)
                vals = jax.lax.dynamic_slice_in_dim(b.vals, s0, tile, 0)
                mask = jax.lax.dynamic_slice_in_dim(b.mask, s0, tile, 0)
                x = tile_pass(rows, cols, vals, mask)
                return out.at[rows].set(x, mode="drop", unique_indices=True)

            out = jax.lax.fori_loop(0, ntiles, body, out)
    return out
