"""Gather strategies: full ``all_gather`` vs ring ``ppermute`` streaming.

The reference stack moves factor messages with a sort-based shuffle over
netty TCP (SURVEY.md §2.C2).  The TPU-native replacements (§5.7/§5.8):

- **all_gather** (tpu_als.parallel.trainer): each half-step gathers the full
  opposite factor matrix over ICI.  Simplest and fastest while
  ``N_opposite × rank`` fits per-device HBM.
- **ring** (this module): the opposite factors are never materialized in
  full.  Each device keeps only its own factor shard; shards rotate around
  the mesh with ``ppermute`` while per-row normal-equation accumulators stay
  stationary — the same dataflow as ring attention (stationary queries =
  the accumulators, streaming keys/values = the factor shards).  Total
  bytes moved equal one all_gather, but peak HBM drops from
  ``N_opposite × rank`` to ``N_opposite/D × rank``.

Data layout for the ring: ratings are blocked on a 2-D (owner device ×
source shard) grid — the TPU analog of Spark's ``numUserBlocks ×
numItemBlocks`` rating grid — with column ids local to the source shard, so
each ring step's gather indexes only the currently-held shard.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tpu_als.core.ratings import Bucket, build_csr_buckets, trainer_chunk
from tpu_als.ops.solve import solve_nnls, solve_spd
from tpu_als.parallel.data import stack_shards
from tpu_als.parallel.mesh import AXIS


@dataclass
class RingCsr:
    """[D, S, ...] bucketed grid for one side (uniform shapes over both the
    device axis D and the source-shard axis S)."""

    buckets: list  # list[Bucket]; arrays are [D, S, nb, w]
    rows_per_shard: int
    chunk_elems: int
    nnz: int

    def device_buckets(self):
        return list(self.buckets)


def shard_csr_grid(row_part, col_part, row_idx, col_idx, vals,
                   min_width=8, chunk_elems=1 << 19):
    """Build the (owner device × source shard) grid with shard-local cols."""
    D = row_part.n_shards
    S = col_part.n_shards
    owner = row_part.owner[row_idx]
    local_rows = row_part.local[row_idx]
    src = col_part.owner[col_idx]
    local_cols = col_part.local[col_idx]

    vals = np.asarray(vals)
    # per (d, s): a CsrBuckets; then unify across d for each s, then across s
    per_s = []
    for s in range(S):
        shards = []
        for d in range(D):
            sel = (owner == d) & (src == s)
            shards.append(build_csr_buckets(
                local_rows[sel], local_cols[sel], vals[sel],
                num_rows=row_part.rows_per_shard,
                min_width=min_width, chunk_elems=chunk_elems,
            ))
        per_s.append(stack_shards(shards, chunk_elems))  # [D, nb_s, w]

    # unify bucket shapes across the S axis so a traced shard index can
    # dynamic-slice into a single stacked array
    widths = sorted({b.width for sh in per_s for b in sh.buckets})
    stacked = []
    num_rows = row_part.rows_per_shard
    for w in widths:
        per = [next((b for b in sh.buckets if b.width == w), None)
               for sh in per_s]
        nb_max = max(b.rows.shape[1] for b in per if b is not None)
        rows = np.full((D, S, nb_max), num_rows, dtype=np.int32)
        cols = np.zeros((D, S, nb_max, w), dtype=np.int32)
        v = np.zeros((D, S, nb_max, w), dtype=np.float32)
        m = np.zeros((D, S, nb_max, w), dtype=np.float32)
        for s, b in enumerate(per):
            if b is None:
                continue
            nb = b.rows.shape[1]
            rows[:, s, :nb] = b.rows
            cols[:, s, :nb] = b.cols
            v[:, s, :nb] = b.vals
            m[:, s, :nb] = b.mask
        stacked.append(Bucket(rows=rows, cols=cols, vals=v, mask=m))
    return RingCsr(buckets=stacked, rows_per_shard=num_rows,
                   chunk_elems=chunk_elems, nnz=len(row_idx))


def _accumulate_shard(V_shard, buckets, shard_sel, num_rows, cfg, chunk_elems,
                      A_acc, b_acc):
    """Add one source shard's normal-equation contributions.

    ``buckets`` arrays are [S, nb, w]; ``shard_sel`` is the traced source
    shard index currently held by this device.  Raw sums only — the λ·n·I
    ridge (and implicit YᵀY) are added once at solve time.
    """
    r = V_shard.shape[-1]
    cdt = jnp.dtype(cfg.compute_dtype)
    for b in buckets:
        _, nb, w = b.cols.shape
        rows = jax.lax.dynamic_index_in_dim(b.rows, shard_sel, 0, False)
        cols = jax.lax.dynamic_index_in_dim(b.cols, shard_sel, 0, False)
        vals = jax.lax.dynamic_index_in_dim(b.vals, shard_sel, 0, False)
        mask = jax.lax.dynamic_index_in_dim(b.mask, shard_sel, 0, False)
        chunk = trainer_chunk(nb, w, r, chunk_elems)
        nchunks = nb // chunk

        def contrib(args):
            c, v, m = args
            Vg = V_shard[c].astype(cdt)
            if cfg.implicit_prefs:
                conf_m1 = cfg.alpha * jnp.abs(v) * m
                pref = (v > 0).astype(cdt)
                A = jnp.einsum("nw,nwr,nws->nrs", conf_m1.astype(cdt), Vg, Vg,
                               preferred_element_type=jnp.float32)
                bb = jnp.einsum("nw,nwr->nr",
                                ((1.0 + conf_m1) * pref * m).astype(cdt), Vg,
                                preferred_element_type=jnp.float32)
            else:
                Vm = Vg * m[..., None].astype(cdt)
                A = jnp.einsum("nwr,nws->nrs", Vm, Vm,
                               preferred_element_type=jnp.float32)
                bb = jnp.einsum("nw,nwr->nr", (v * m).astype(cdt), Vg,
                                preferred_element_type=jnp.float32)
            return A, bb

        if nchunks == 1:
            A, bb = contrib((cols, vals, mask))
        else:
            A, bb = jax.lax.map(
                contrib,
                (cols.reshape(nchunks, chunk, w),
                 vals.reshape(nchunks, chunk, w),
                 mask.reshape(nchunks, chunk, w)),
            )
            A = A.reshape(nb, r, r)
            bb = bb.reshape(nb, r)
        A_acc = A_acc.at[rows].add(A, mode="drop")
        b_acc = b_acc.at[rows].add(bb, mode="drop")
    return A_acc, b_acc


def ring_half_step(V_shard, ring_buckets, counts, num_rows, n_shards, cfg,
                   chunk_elems, YtY=None):
    """One half-step with streaming factor shards (inside ``shard_map``).

    V_shard [per_opposite, r]: this device's shard of the opposite factors.
    ring_buckets: [S, ...] bucket arrays (this device's slice of a RingCsr).
    counts [num_rows]: per-row rating counts (for the λ·n ridge; for
    implicit feedback, the positive-rating counts).
    """
    r = V_shard.shape[-1]
    me = jax.lax.axis_index(AXIS)
    A = jnp.zeros((num_rows, r, r), dtype=jnp.float32)
    b = jnp.zeros((num_rows, r), dtype=jnp.float32)

    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    V_cur = V_shard
    for t in range(n_shards):
        src = (me - t) % n_shards  # shard currently held after t rotations
        A, b = _accumulate_shard(V_cur, ring_buckets, src, num_rows, cfg,
                                 chunk_elems, A, b)
        if t + 1 < n_shards:
            V_cur = jax.lax.ppermute(V_cur, AXIS, perm)

    eye = jnp.eye(r, dtype=jnp.float32)
    A = A + (cfg.reg_param * counts)[:, None, None] * eye
    if cfg.implicit_prefs:
        A = A + YtY[None]
    if cfg.nonnegative:
        return solve_nnls(A, b, counts, sweeps=cfg.nnls_sweeps)
    return solve_spd(A, b, counts)
