"""Ragged ``all_to_all`` gather strategy (the Ulysses-style exchange).

Third member of the gather-strategy triad (SURVEY.md §5.7/§5.8, build plan
M6) next to ``all_gather`` (tpu_als.parallel.trainer) and ``ring``
(tpu_als.parallel.comm):

- **all_gather** moves ``N_opposite × rank`` floats to every device and
  peaks HBM at the full opposite factor matrix.
- **ring** moves the same bytes but never materializes the full matrix.
- **all_to_all** (this module) moves only the factor rows each device
  actually references: device d receives, from each source shard s, exactly
  the rows its rating block touches.  When interactions are clustered (each
  user block rates a small item subset — the regime where Spark's OutBlock
  "send only active rows" optimization wins, SURVEY.md §2.B4), both bytes
  moved AND peak HBM drop below the gather/ring strategies.

Mechanics: the request lists are computed host-side once (they depend only
on the rating layout), padded to a uniform per-(src,dst) budget ``R`` so the
exchange is one static-shape ``jax.lax.all_to_all`` over the mesh axis.
Column ids in the rating shards are pre-remapped to **compact** ids
``s·R + position`` indexing the received ``[D·R, rank]`` table, so after the
exchange the half-step is the unchanged ``local_half_step``.  This is the
TPU analog of Spark ALS's OutBlock machinery: the reference stack computes,
per user block, which factor rows each item block needs and ships only
those through the shuffle — here the "shuffle" is a single XLA collective
and the routing tables are baked into the compiled step.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax

from tpu_als.core.als import local_half_step
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.parallel.data import stack_shards
from tpu_als.parallel.mesh import AXIS


@dataclass
class A2aCsr:
    """Rating shards + routing tables for one side's half-step.

    buckets arrays are [D, nb, w] (cols hold compact recv-table ids);
    send_idx [D_src, D_dst, R]: local factor-row indices on the source
    shard requested by each destination (0-padded; padding rows are never
    referenced by any compact col id).
    """

    buckets: list
    send_idx: np.ndarray
    rows_per_shard: int
    request_budget: int  # R
    chunk_elems: int
    nnz: int
    # the budget is the max over (src, dst) pairs, so one hot pair inflates
    # the whole [D, D, R] exchange (ADVICE r1): these fields let callers see
    # and escape that degeneration
    padding_ratio: float = 1.0  # D²·R / true request-list entries
    degenerate: bool = False    # True when exchanged rows >= all_gather's
    # None = full build; a tuple = this process's mesh positions only
    positions: tuple = None

    def device_buckets(self):
        return list(self.buckets)

    def local_slice(self, positions):
        """This process's source rows of the shards + send tables, for
        ``jax.make_array_from_process_local_data`` assembly (the exchange
        plan itself is computed globally — every host agrees on R and the
        recv-table layout)."""
        import dataclasses

        from tpu_als.core.ratings import Bucket

        pos = list(positions)
        return dataclasses.replace(
            self,
            buckets=[Bucket(rows=b.rows[pos], cols=b.cols[pos],
                            vals=b.vals[pos], mask=b.mask[pos])
                     for b in self.buckets],
            send_idx=self.send_idx[pos],
            positions=tuple(pos),
        )


def build_a2a(row_part, col_part, row_idx, col_idx, vals,
              min_width=8, chunk_elems=1 << 19, on_degenerate="build",
              positions=None):
    """Build rating shards with compact column ids + the exchange plan.

    row_part/col_part: Partition for the solved side / the gathered side
    (tpu_als.parallel.data).  Requires ``row_part.n_shards ==
    col_part.n_shards`` (one mesh axis drives the exchange).

    on_degenerate: what to do when the uniform budget R reaches the
    opposite side's rows/shard (exchange bytes >= all_gather's).
    'build' (default) warns but still builds a working plan — fine at
    small scale.  'stub' returns immediately with ``degenerate=True`` and
    NO shard/table arrays (a [D, D, R] table at R ≈ 10⁶ rows is a
    terabyte-class host allocation — the caller must check the flag and
    fall back before anything that size is materialized); a stub plan is
    not trainable.

    ``positions``: allocate and fill ONLY these mesh positions' source
    rows of the shards and send tables (multi-host; the exchange plan —
    R, recv layout, degeneration — is still computed globally so every
    host agrees).  Equals slicing a full build at ``positions``.
    """
    D = row_part.n_shards
    if col_part.n_shards != D:
        raise ValueError("all_to_all requires equal shard counts per side")
    row_idx = np.asarray(row_idx)
    col_idx = np.asarray(col_idx)
    vals = np.asarray(vals)
    owner_r = row_part.owner[row_idx]
    local_r = row_part.local[row_idx]
    owner_c = col_part.owner[col_idx]
    local_c = col_part.local[col_idx].astype(np.int64)
    rps = col_part.rows_per_shard

    # unique (dst, src, local_col) triples, sorted — positions within each
    # (dst, src) group become the slot in that destination's request list
    key = (owner_r.astype(np.int64) * D + owner_c) * rps + local_c
    uniq, inv = np.unique(key, return_inverse=True)
    grp = (uniq // rps).astype(np.int64)            # dst*D + src, sorted
    loc = (uniq % rps).astype(np.int64)
    starts = np.searchsorted(grp, np.arange(D * D))
    pos = np.arange(len(uniq)) - starts[grp]
    # uniform request budget, padded to a sublane multiple
    R_true = int(pos.max()) + 1 if len(uniq) else 1
    R = max(8, -(-R_true // 8) * 8)

    # budget accounting: R is the max over all (src, dst) pairs, so a
    # single dense pair pads every other pair's list up to it.  When the
    # per-device exchanged rows (D·R) reach the opposite side's full shard
    # rows (D·rows_per_shard ≥ what all_gather would move), the strategy
    # has lost its reason to exist — callers should fall back.  Compare
    # the pre-floor demand so the sublane rounding of tiny budgets doesn't
    # read as degeneration.
    true_requests = max(1, len(uniq))
    padding_ratio = (D * D * R) / true_requests
    degenerate = R_true >= rps
    if degenerate:
        import warnings

        warnings.warn(
            f"all_to_all request budget R={R_true} >= opposite rows/shard "
            f"{rps}: the exchange moves at least as many bytes as "
            "all_gather (clustered-skew rating layout); prefer "
            "gatherStrategy='all_gather' or 'ring'", stacklevel=2)
        if on_degenerate == "stub":
            # detected BEFORE the [D, D, R] table / shard arrays exist —
            # at the scale the fallback matters those allocations would
            # OOM the host long before the caller could check the flag
            return A2aCsr(
                buckets=[], send_idx=np.zeros((D, D, 0), dtype=np.int32),
                rows_per_shard=row_part.rows_per_shard, request_budget=R,
                chunk_elems=chunk_elems, nnz=len(row_idx),
                padding_ratio=padding_ratio, degenerate=True,
            )

    local = positions is not None
    pos_list = list(positions) if local else list(range(D))
    L = len(pos_list)
    pos_of = np.full(D, -1, dtype=np.int64)
    pos_of[pos_list] = np.arange(L)

    dst = grp // D
    src = grp % D
    send_idx = np.zeros((L, D, R), dtype=np.int32)
    ssel = pos_of[src] >= 0
    send_idx[pos_of[src[ssel]], dst[ssel], pos[ssel]] = loc[ssel]

    # compact col id per rating: src_shard * R + request position
    compact = (owner_c.astype(np.int64) * R + pos[inv]).astype(np.int64)

    shards = []
    for d in pos_list:
        sel = owner_r == d
        shards.append(build_csr_buckets(
            local_r[sel], compact[sel], vals[sel],
            num_rows=row_part.rows_per_shard,
            min_width=min_width, chunk_elems=chunk_elems,
        ))
    # globally-agreed layout: counts per (device, local row) slot feed the
    # same arithmetic stack_shards would derive from a full build, so a
    # positions build matches a slice of the full one exactly
    from tpu_als.parallel.data import Partition, shard_layout

    rps_row = row_part.rows_per_shard
    flat_counts = np.bincount(
        owner_r.astype(np.int64) * rps_row + local_r,
        minlength=D * rps_row)
    slot_part = Partition(
        owner=np.repeat(np.arange(D, dtype=np.int32), rps_row),
        local=np.tile(np.arange(rps_row, dtype=np.int32), D),
        rows_per_shard=rps_row, n_shards=D)
    layout = shard_layout(slot_part, flat_counts, min_width, chunk_elems)
    stacked = stack_shards(shards, chunk_elems, layout=layout,
                           positions=tuple(pos_list) if local else None)
    return A2aCsr(
        buckets=stacked.buckets,
        send_idx=send_idx,
        rows_per_shard=row_part.rows_per_shard,
        request_budget=R,
        chunk_elems=chunk_elems,
        nnz=len(row_idx),
        padding_ratio=padding_ratio,
        degenerate=degenerate,
        positions=tuple(pos_list) if local else None,
    )


def a2a_half_step(V_loc, send_idx, buckets, num_rows, cfg, chunk_elems,
                  YtY=None, prev=None):
    """One half-step with the ragged exchange (inside ``shard_map``).

    V_loc [per_opposite, r]: this device's shard of the opposite factors.
    send_idx [D, R]: this device's outgoing request lists (one per dst).
    The exchange builds the compact [D·R, r] recv table the rating shards'
    col ids index; the solve is the shared ``local_half_step`` (``prev`` =
    the solved side's current shard, its CG warm start).
    """
    Vsend = V_loc[send_idx]                                    # [D, R, r]
    Vrecv = jax.lax.all_to_all(Vsend, AXIS, split_axis=0, concat_axis=0)
    V_compact = Vrecv.reshape(-1, V_loc.shape[-1])             # [D*R, r]
    return local_half_step(V_compact, buckets, num_rows, cfg, YtY,
                           chunk_elems, prev=prev)
