"""Multi-host bring-up — the analog of Spark's cluster boot.

The reference stack scales past one machine with Spark's driver/executor
runtime: executors register with the driver over netty RPC and each holds
its partitions (SURVEY.md §2.B8).  The TPU-native equivalent is JAX's
multi-controller model: every host runs this same program,
``jax.distributed.initialize`` rendezvouses them over DCN, and afterwards
``jax.devices()`` spans the whole deployment, so
:func:`tpu_als.parallel.mesh.make_mesh` builds one global (slice-major)
mesh and the ``shard_map`` trainer is unchanged — XLA routes each
collective over ICI within a slice and DCN across (SURVEY.md §5.8).

What IS per-host is the data: at Amazon-2023 scale (~570M ratings,
BASELINE.json config 3) no host should materialize the full rating set.
:func:`local_positions` + :func:`local_rating_mask` give each process the
mesh-axis positions its devices own and the subset of COO ratings that
land there, so blocking (`build_csr_buckets` / `build_a2a`) runs on the
local shard only — the analog of executors building only their own
``InBlock``s.

Scope: three multi-process entry tiers, all exercised by REAL spawned
two-process gloo tests in ``tests/test_multihost.py``:

1. ``ALS(mesh=...).fit(frame)`` — every host fits the same replicated
   frame (``dataMode='replicated'``, the default) or its own disjoint
   split (``dataMode='per_host'``: id maps are agreed via
   :func:`global_id_union`, triples exchanged inside
   :func:`train_multihost`); factors match the single-process mesh fit
   exactly (same partitions/init/layout).  All runtime knobs are wired:
   gatherStrategy, checkpoint/resume, and ``fitCallback`` (entity-space
   gather every ``fitCallbackInterval`` iterations, invoked on process 0).
2. ``tpu_als.cli train`` — same convention, plus holdout eval and model
   save on process 0.
3. :func:`train_multihost` — per-host rating splits (redistributed or
   ``replicated=True``), for custom loops; built on
   ``data.shard_csr(positions=...)`` blocking into the globally-agreed
   ``data.shard_layout`` shapes and
   ``jax.make_array_from_process_local_data`` placement.
"""

from __future__ import annotations

import os

import numpy as np

import jax


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, retry_policy=None):
    """Connect this process to the deployment (no-op when single-process).

    Resolution order: explicit args → the standard JAX env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``, also set by TPU pod launchers) → single-process
    no-op.  Must run before first JAX use, like Spark's ``SparkContext``
    construction must precede any job.

    The rendezvous is retried under ``tpu_als.resilience.retry``
    (default: 5 attempts, 1s base exponential backoff) — a coordinator
    that is still binding its port, or a DCN blip, is the single most
    common pod-launch flake and must not kill the whole deployment.
    Fault point ``multihost.init`` fires inside each rendezvous attempt.
    Returns (process_index, process_count).
    """
    from tpu_als.resilience import faults
    from tpu_als.resilience.retry import RetryPolicy, retry_call

    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address and _already_initialized():
        # idempotent: a launcher (or test worker) may have rendezvoused
        # before handing control to code that also calls this — a second
        # jax.distributed.initialize would raise (the backend is up)
        coordinator_address = None

    def _rendezvous():
        # the fault point lives INSIDE the retried closure so chaos
        # tests exercise the retry loop even on the single-process path
        faults.check("multihost.init")
        if coordinator_address and not _already_initialized():
            kw = {"coordinator_address": coordinator_address}
            np_ = num_processes or os.environ.get("JAX_NUM_PROCESSES")
            pid = process_id if process_id is not None else \
                os.environ.get("JAX_PROCESS_ID")
            if np_ is not None:
                kw["num_processes"] = int(np_)
            if pid is not None:
                kw["process_id"] = int(pid)
            jax.distributed.initialize(**kw)

    policy = retry_policy if retry_policy is not None else \
        RetryPolicy(max_attempts=5, base_delay=1.0, max_delay=15.0,
                    retry_on=(OSError, TimeoutError, RuntimeError))
    retry_call(_rendezvous, policy=policy, what="multihost.init")
    return jax.process_index(), jax.process_count()


def rejoin(coordinator_address=None, num_processes=None, process_id=None,
           retry_policy=None):
    """Re-run the deployment rendezvous after an elastic mesh
    reformation (resilience.elastic → api.fitting recovery).

    Single-process deployments (every CPU test, and the single-host
    mesh path the elastic recovery currently drives) are a no-op —
    there is no cross-host barrier to re-form.  Multi-process: tear
    down the distributed client and rendezvous again with the
    survivors' coordinates, under the same retried
    :func:`init_distributed` discipline (the coordinator may itself be
    restarting).  Returns ``(process_index, process_count)``.
    """
    if jax.process_count() <= 1 and coordinator_address is None \
            and os.environ.get("JAX_COORDINATOR_ADDRESS") is None:
        return jax.process_index(), jax.process_count()
    try:
        jax.distributed.shutdown()
    except Exception:
        pass  # a dead peer may have already torn the client down
    return init_distributed(coordinator_address=coordinator_address,
                            num_processes=num_processes,
                            process_id=process_id,
                            retry_policy=retry_policy)


def _triples_digest(u, i, r):
    """Order-independent int64 digest of (u, i, r) triples: blake2b over
    the lexicographically sorted rows.  Used to detect identical per-host
    inputs without false positives on coincidentally-equal summary stats."""
    import hashlib

    order = np.lexsort((np.asarray(r), np.asarray(i), np.asarray(u)))
    buf = np.concatenate([
        np.asarray(u, dtype=np.int64)[order].view(np.uint8),
        np.asarray(i, dtype=np.int64)[order].view(np.uint8),
        np.asarray(r, dtype=np.float32)[order].view(np.uint8),
    ])
    h = hashlib.blake2b(buf.tobytes(), digest_size=8).digest()
    return int(np.frombuffer(h, dtype=np.int64)[0])


def _split_signatures_duplicated(sig):
    """True when any TWO non-empty per-process (len, digest) rows match —
    the duplicated-load mistake.  Pairwise, not all-equal: with P > 2
    processes, two hosts reading the same file must still be rejected
    even when the others differ (advisor r3).  Empty splits are excluded
    (several hosts legitimately holding no data share the empty digest)."""
    sig = np.asarray(sig)
    nonempty = sig[sig[:, 0] > 0]
    return len(nonempty) != len(np.unique(nonempty, axis=0))


def _ragged_allgather(arr, fill=0):
    """Concatenate every process's 1-D array (ragged lengths allowed).

    The shared collective idiom of this module: lengths are agreed first,
    locals are padded to the max, one ``process_allgather`` moves the
    data, padding is dropped.  O(P · max_len) host memory.
    """
    from jax.experimental import multihost_utils as mhu

    arr = np.asarray(arr)
    lens = np.asarray(mhu.process_allgather(
        np.array([len(arr)], dtype=np.int64))).ravel()
    pad = int(lens.max())
    buf = np.full(pad, fill, dtype=arr.dtype)
    buf[: len(arr)] = arr
    g = np.asarray(mhu.process_allgather(buf))
    keep = np.arange(pad)[None, :] < lens[:, None]
    return g[keep]


def _already_initialized():
    """True when this process has an active jax.distributed client."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:  # fallback for jax versions without the public probe
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        return False


def train_multihost(u, i, r, num_users, num_items, cfg, mesh=None,
                    min_width=8, chunk_elems=1 << 19, replicated=False,
                    strategy="all_gather", init=None, start_iter=0,
                    callback=None):
    """Multi-process ALS training: every process calls this with its OWN
    rating triples (global dense ids) — the analog of Spark executors each
    reading their input split and ``partitionRatings`` shuffling blocks to
    owners (SURVEY.md §3.1).

    Pipeline: (1) redistribute triples so each host sees the ratings its
    entities own — implemented with ``process_allgather`` (O(total nnz)
    per host; pass ``replicated=True`` when every host already loaded the
    FULL dataset to skip the exchange, or at pod scale feed pre-sharded
    inputs through :func:`local_rating_mask`); (2) global
    counts → partitions → per-host blocking into the agreed
    :func:`tpu_als.parallel.data.shard_layout` shapes; (3) global-array
    assembly via ``jax.make_array_from_process_local_data``; (4) the
    ``shard_map`` trainer over the global mesh — collectives cross hosts
    over DCN (gloo on the CPU test mesh).

    Returns ``(U, V, user_part, item_part)``: slot-space global
    ``jax.Array`` factors sharded over the mesh.  Exercised end-to-end by
    ``tests/test_multihost.py`` (two spawned processes, result equal to
    the single-process run).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_als.core.als import init_factors
    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.mesh import AXIS, make_mesh
    from tpu_als.parallel.trainer import make_sharded_step

    if mesh is None:
        mesh = make_mesh()
    # pin dtypes BEFORE the cross-process gather: per-host divergence
    # (e.g. one host's empty split arriving as float64) would feed gloo
    # mismatched buffers
    u = np.asarray(u, dtype=np.int64)
    i = np.asarray(i, dtype=np.int64)
    r = np.asarray(r, dtype=np.float32)

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils as mhu

        # cross-host agreement check: divergent entity spaces would fail
        # far away (mismatched global shapes inside gloo) or silently
        # corrupt factors if shapes happened to coincide; divergent
        # iteration windows would have one host exit the training loop
        # while peers keep issuing collectives — a silent hang
        dims = np.asarray(mhu.process_allgather(np.array(
            [num_users, num_items, int(start_iter), int(cfg.max_iter)],
            dtype=np.int64)))
        if not (dims == dims[0]).all():
            raise ValueError(
                "hosts disagree on (num_users, num_items, start_iter, "
                f"max_iter): {dims.tolist()}; all hosts must share one "
                "id mapping and one iteration window (same resumeFrom "
                "checkpoint, same maxIter)")

        if replicated:
            # every host already holds the FULL triples (e.g. all loaded
            # the same file): skip the O(total nnz) exchange — but check
            # CONTENT agreement, not just length (same-length divergent
            # inputs would give hosts divergent partitions and corrupt
            # training far from here)
            sig = np.asarray(mhu.process_allgather(np.array(
                [len(u), int(u.sum()), int(i.sum()),
                 np.float64(r.astype(np.float64).sum()).view(np.int64)],
                dtype=np.int64)))
            if not (sig == sig[0]).all():
                raise ValueError(
                    "replicated=True but per-host rating data differ "
                    f"(len/Σu/Σi/Σr signatures: {sig.tolist()}) — every "
                    "host must load the SAME dataset, or pass each "
                    "host's own split with replicated=False")
    if jax.process_count() > 1 and not replicated:
        from jax.experimental import multihost_utils as mhu

        # catch the duplicated-load mistake BEFORE the exchange doubles
        # every rating: per-host splits with identical content mean every
        # host read the SAME file (replicated=False would then train on P
        # copies of each rating — effective regularization silently
        # divided by P).  Content = an order-independent 64-bit digest of
        # the sorted triples, not summary stats (equal sums on genuinely
        # disjoint splits would false-positive; a hash collision is
        # ~2^-64)
        sig = np.asarray(mhu.process_allgather(np.array(
            [len(u), _triples_digest(u, i, r)], dtype=np.int64)))
        if _split_signatures_duplicated(sig):
            raise ValueError(
                "replicated=False but two or more processes passed "
                "IDENTICAL rating triples — each host must pass its OWN "
                "disjoint split (per-host input files), or pass "
                "replicated=True for a shared load")
        u = _ragged_allgather(u)
        i = _ragged_allgather(i)
        r = _ragged_allgather(r)

    D = mesh.devices.size
    ucounts = np.bincount(u, minlength=num_users)
    icounts = np.bincount(i, minlength=num_items)
    upart = partition_balanced(ucounts, D)
    ipart = partition_balanced(icounts, D)
    positions = local_positions(mesh)

    leading = NamedSharding(mesh, P(AXIS))

    def assemble(local):
        return jax.make_array_from_process_local_data(leading, local)

    if strategy in ("ring", "ring_overlap"):
        # ring exists to bound DEVICE HBM (opposite factors never
        # materialize in full); its grid layout is computed globally
        # (every host holds the full triples at this point) but only the
        # local owner rows are allocated, filled, and placed
        from tpu_als.parallel.comm import shard_csr_grid
        from tpu_als.parallel.trainer import make_ring_step, stacked_counts

        ush = shard_csr_grid(upart, ipart, u, i, r, min_width=min_width,
                             chunk_elems=chunk_elems, positions=positions)
        ish = shard_csr_grid(ipart, upart, i, u, r, min_width=min_width,
                             chunk_elems=chunk_elems, positions=positions)
        pos_only = cfg.implicit_prefs
        extra = (
            assemble(stacked_counts(upart, u, r,
                                    positive_only=pos_only)[positions]),
            assemble(stacked_counts(ipart, i, r,
                                    positive_only=pos_only)[positions]),
        )
        if strategy == "ring_overlap":
            def step_factory(mesh, ush, ish, cfg):
                return make_ring_step(mesh, ush, ish, cfg, overlap=True)
        else:
            step_factory = make_ring_step
    elif strategy in ("all_gather", "all_gather_chunked"):
        umask = local_rating_mask(upart, u, positions=positions)
        imask = local_rating_mask(ipart, i, positions=positions)
        ush = shard_csr(upart, ipart, u[umask], i[umask], r[umask],
                        min_width=min_width, chunk_elems=chunk_elems,
                        positions=positions, row_counts=ucounts)
        ish = shard_csr(ipart, upart, i[imask], u[imask], r[imask],
                        min_width=min_width, chunk_elems=chunk_elems,
                        positions=positions, row_counts=icounts)
        extra = ()
        if strategy == "all_gather_chunked":
            from tpu_als.parallel.trainer import make_chunked_gather_step

            step_factory = make_chunked_gather_step
        else:
            step_factory = make_sharded_step
    elif strategy == "all_to_all":
        # exchange plan computed globally (full triples are present),
        # only the local source rows placed; degenerate plans (one hot
        # (src, dst) pair pushing the uniform budget past all_gather
        # bytes) fall back to all_gather, same as single-process fit
        from tpu_als.parallel.a2a import build_a2a
        from tpu_als.parallel.trainer import make_a2a_step

        ush = build_a2a(upart, ipart, u, i, r, min_width=min_width,
                        chunk_elems=chunk_elems, on_degenerate="stub",
                        positions=positions)
        ish = build_a2a(ipart, upart, i, u, r, min_width=min_width,
                        chunk_elems=chunk_elems, on_degenerate="stub",
                        positions=positions)
        if ush.degenerate or ish.degenerate:
            return train_multihost(
                u, i, r, num_users, num_items, cfg, mesh=mesh,
                min_width=min_width, chunk_elems=chunk_elems,
                replicated=True, strategy="all_gather",
                init=init, start_iter=start_iter, callback=callback)
        extra = (assemble(ush.send_idx), assemble(ish.send_idx))
        step_factory = make_a2a_step
    else:
        from tpu_als.parallel.trainer import EXECUTABLE_STRATEGIES

        raise ValueError(
            f"unknown strategy {strategy!r} for multi-host training "
            f"(expected one of {EXECUTABLE_STRATEGIES} — the table in "
            "parallel.trainer.GATHER_STRATEGIES)")

    ub = jax.tree.map(assemble, ush.device_buckets())
    ib = jax.tree.map(assemble, ish.device_buckets())

    U0 = np.zeros((upart.padded_rows, cfg.rank), np.float32)
    V0 = np.zeros((ipart.padded_rows, cfg.rank), np.float32)
    if init is not None:
        # entity-space warm start (checkpoint resume): scatter to slots
        U0[upart.slot] = np.asarray(init[0], dtype=np.float32)
        V0[ipart.slot] = np.asarray(init[1], dtype=np.float32)
    else:
        key = jax.random.PRNGKey(cfg.seed)
        ku, kv = jax.random.split(key)
        U0[upart.slot] = np.asarray(init_factors(ku, num_users, cfg.rank))
        V0[ipart.slot] = np.asarray(init_factors(kv, num_items, cfg.rank))
    rps_u, rps_i = upart.rows_per_shard, ipart.rows_per_shard
    U = assemble(np.concatenate(
        [U0[p * rps_u:(p + 1) * rps_u] for p in positions]))
    V = assemble(np.concatenate(
        [V0[p * rps_i:(p + 1) * rps_i] for p in positions]))

    step = step_factory(mesh, ush, ish, cfg)
    for it in range(start_iter, cfg.max_iter):
        U, V = step(U, V, ub, ib, *extra)
        if callback is not None:
            # slot-space global arrays + the partitions to unscatter them;
            # collective work inside the callback (e.g. a
            # gather_entity_factors for checkpointing) must run on EVERY
            # process
            callback(it + 1, U, V, upart, ipart)
    return U, V, upart, ipart


def save_checkpoint_sharded(path, Us, Vs, upart, ipart, user_map, item_map,
                            mesh, params=None, iteration=None):
    """Shard-per-process checkpoint: each process writes ONLY the factor
    shards its devices own — the SURVEY §5.4 design ("flat-array
    shard-per-device checkpoint with a JSON manifest").

    A replicated checkpoint costs an O(N_entities · rank) cross-host
    gather per checkpoint (the most expensive collective in the loop);
    here factor bytes never cross hosts: process-local ``np.savez`` per
    mesh position, process 0 adds ids/slots + manifest, one barrier, then
    process 0 runs the same old-aside/install/cleanup swap as
    ``io.checkpoint.save_factors`` so a complete checkpoint exists at
    ``path`` or ``path + '.old'`` at every instant.  The saved slot maps
    make the directory self-contained: ``io.checkpoint.load_factors``
    reassembles entity-space factors with the same return contract as
    the replicated format, so every resume/load path works unchanged.
    """
    import shutil

    from jax.experimental import multihost_utils as mhu

    from tpu_als.io.checkpoint import SHARDED_FORMAT, atomic_install

    Us.block_until_ready()
    Vs.block_until_ready()
    pidx = jax.process_index()
    tmp = path + ".tmp"
    # clear stale leftovers from a crashed attempt BEFORE anyone writes
    # (a dead run with a different shard count would otherwise leave
    # wrong-generation shard files inside the installed directory)
    if pidx == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)
    if jax.process_count() > 1:
        mhu.sync_global_devices(f"tpu_als_ckpt_clear_{iteration}")
    os.makedirs(tmp, exist_ok=True)
    positions = local_positions(mesh)

    def write_side(arr, name):
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        for pos, sh in zip(positions, shards):
            np.savez(os.path.join(tmp, f"{name}_shard_{pos:05d}.npz"),
                     factors=np.asarray(sh.data))

    write_side(Us, "user")
    write_side(Vs, "item")
    if pidx == 0:
        np.savez(os.path.join(tmp, "slots.npz"),
                 user_ids=np.asarray(user_map.ids),
                 item_ids=np.asarray(item_map.ids),
                 user_slot=np.asarray(upart.slot),
                 item_slot=np.asarray(ipart.slot))
        manifest = {
            "format_version": SHARDED_FORMAT,
            "sharded": True,
            "n_shards": int(upart.n_shards),
            "rows_per_shard_user": int(upart.rows_per_shard),
            "rows_per_shard_item": int(ipart.rows_per_shard),
            "rank": int(Us.shape[-1]),
            "num_users": int(len(user_map)),
            "num_items": int(len(item_map)),
            "iteration": iteration,
            "params": params or {},
            "extra": {},
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            import json

            json.dump(manifest, f, indent=2)
    if jax.process_count() > 1:
        mhu.sync_global_devices(f"tpu_als_ckpt_write_{iteration}")
    if pidx == 0:
        atomic_install(tmp, path)
    if jax.process_count() > 1:
        # peers must not race into the next iteration's tmp dir (or a
        # resume) while the swap is mid-flight
        mhu.sync_global_devices(f"tpu_als_ckpt_swap_{iteration}")


def global_id_union(local_ids):
    """Sorted union of every process's id set — the agreed entity space of
    a per-host-split fit (``ALS(dataMode='per_host')``).

    The reference analog is ``partitionRatings`` seeing the global id space
    through the shuffle (SURVEY.md §3.1); here each host contributes only
    its O(local unique) ids, so no host materializes the remote *ratings*
    to agree on the *entities*.  Deterministic (sorted) on every host, so
    the resulting ``IdMap`` — and everything downstream: partitions,
    layouts, init — is identical across processes.  Single-process: plain
    ``np.unique``.
    """
    uniq = np.unique(np.asarray(local_ids))
    if jax.process_count() == 1:
        return uniq
    return np.unique(_ragged_allgather(uniq.astype(np.int64)))


def global_vocab_union(labels):
    """Sorted union of every process's STRING vocabulary — the entity
    agreement for per-host streaming ingest (io/stream.py) whose raw ids
    are strings (config 3's Amazon-2023 schema, SURVEY.md §6 row 3).

    Same contract as :func:`global_id_union` but over an ``S``-dtype
    label array: each host contributes O(local distinct) label bytes,
    never its ratings.  Labels are padded to the globally-agreed width,
    moved as uint8 rows through the ragged allgather, and uniqued —
    deterministic (lexicographic) on every process.  Labels must not
    contain NUL bytes (the padding alphabet).  Single-process: plain
    ``np.unique``.  The local->global remap is
    ``np.searchsorted(global, local)``.
    """
    labels = np.asarray(labels, dtype="S")
    if jax.process_count() == 1:
        return np.unique(labels)
    from jax.experimental import multihost_utils as mhu

    w = int(np.asarray(mhu.process_allgather(
        np.array([max(labels.dtype.itemsize, 1)], dtype=np.int64))).max())
    rows = np.zeros((len(labels), w), dtype=np.uint8)
    if len(labels):
        loc_w = labels.dtype.itemsize
        rows[:, :loc_w] = (labels.view(np.uint8)
                           .reshape(len(labels), loc_w))
    flat = _ragged_allgather(rows.ravel())
    gathered = np.ascontiguousarray(
        flat.reshape(-1, w)).view(f"S{w}").ravel()
    return np.unique(gathered)


def gather_entity_factors(arr, part, mesh):
    """Host-replicated entity-space factors from a slot-space global array.

    Small-model convenience for the serving/persistence boundary (the
    reference's ``ALSModel`` is a driver-side object too); at pod scale
    keep factors sharded and serve from device.  Works single- and
    multi-process (one ``process_allgather`` of the local rows).
    """
    rps = part.rows_per_shard
    rank = arr.shape[-1]
    shards = sorted(arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    local = np.concatenate([np.asarray(s.data) for s in shards])
    positions = np.asarray(local_positions(mesh), dtype=np.int64)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils as mhu

        g_rows = np.asarray(mhu.process_allgather(local))      # [P, L*rps, r]
        g_pos = np.asarray(mhu.process_allgather(positions))   # [P, L]
        slotspace = np.zeros((part.padded_rows, rank), np.float32)
        for p in range(g_rows.shape[0]):
            for li, pos in enumerate(g_pos[p]):
                slotspace[pos * rps:(pos + 1) * rps] = \
                    g_rows[p, li * rps:(li + 1) * rps]
    else:
        slotspace = local
    return slotspace[part.slot]


def local_positions(mesh):
    """Mesh-axis positions (0..D-1) owned by this process's devices.

    The sharded trainer lays factors and rating shards out device-major
    along the 1-D mesh axis; these are the leading-axis indices this host
    must have data for."""
    local = {d.id for d in jax.local_devices()}
    flat = list(mesh.devices.flat)
    return [k for k, d in enumerate(flat) if d.id in local]


def local_rating_mask(part, row_idx, mesh=None, positions=None):
    """Boolean mask over COO ratings: True where the solved-side entity is
    owned by one of this process's mesh positions.  Feed the masked
    triples to the blocking builders so each host blocks only its shard —
    O(local nnz) host memory instead of O(total nnz).

    ``positions`` overrides the mesh-derived ownership (tests / custom
    placement); exactly one of ``mesh`` / ``positions`` is required."""
    if positions is None:
        if mesh is None:
            raise ValueError("pass mesh or positions")
        positions = local_positions(mesh)
    own = np.zeros(part.n_shards, dtype=bool)
    own[list(positions)] = True
    return own[part.owner[np.asarray(row_idx)]]
