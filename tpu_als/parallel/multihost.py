"""Multi-host bring-up — the analog of Spark's cluster boot.

The reference stack scales past one machine with Spark's driver/executor
runtime: executors register with the driver over netty RPC and each holds
its partitions (SURVEY.md §2.B8).  The TPU-native equivalent is JAX's
multi-controller model: every host runs this same program,
``jax.distributed.initialize`` rendezvouses them over DCN, and afterwards
``jax.devices()`` spans the whole deployment, so
:func:`tpu_als.parallel.mesh.make_mesh` builds one global (slice-major)
mesh and the ``shard_map`` trainer is unchanged — XLA routes each
collective over ICI within a slice and DCN across (SURVEY.md §5.8).

What IS per-host is the data: at Amazon-2023 scale (~570M ratings,
BASELINE.json config 3) no host should materialize the full rating set.
:func:`local_positions` + :func:`local_rating_mask` give each process the
mesh-axis positions its devices own and the subset of COO ratings that
land there, so blocking (`build_csr_buckets` / `build_a2a`) runs on the
local shard only — the analog of executors building only their own
``InBlock``s.

Scope (honest contract): the high-level Estimator is single-controller —
it materializes full factor matrices host-side and raises a clear error
under multi-process JAX rather than failing inside a collective.  The
multi-host surface is the trainer level: these helpers + per-host rating
shards (``data.shard_csr(positions=...)`` building only the local shards
into the globally-agreed ``data.shard_layout`` shapes) +
``jax.make_array_from_process_local_data`` for the factor/bucket
placement.  This path is exercised END-TO-END by
``tests/test_multihost.py::test_two_process_sharded_step_matches_single_process``:
two spawned processes, gloo collectives over a 4-device global CPU mesh,
per-host blocking, one sharded ALS step — asserted equal to the
single-process result.  Wiring the Estimator itself for multi-process is
future work; nothing in the sharded math (shard_map steps, collectives)
is single-process-specific.
"""

from __future__ import annotations

import os

import numpy as np

import jax


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Connect this process to the deployment (no-op when single-process).

    Resolution order: explicit args → the standard JAX env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``, also set by TPU pod launchers) → single-process
    no-op.  Must run before first JAX use, like Spark's ``SparkContext``
    construction must precede any job.
    Returns (process_index, process_count).
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address:
        kw = {"coordinator_address": coordinator_address}
        num_processes = num_processes or os.environ.get("JAX_NUM_PROCESSES")
        process_id = process_id if process_id is not None else \
            os.environ.get("JAX_PROCESS_ID")
        if num_processes is not None:
            kw["num_processes"] = int(num_processes)
        if process_id is not None:
            kw["process_id"] = int(process_id)
        jax.distributed.initialize(**kw)
    return jax.process_index(), jax.process_count()


def local_positions(mesh):
    """Mesh-axis positions (0..D-1) owned by this process's devices.

    The sharded trainer lays factors and rating shards out device-major
    along the 1-D mesh axis; these are the leading-axis indices this host
    must have data for."""
    local = {d.id for d in jax.local_devices()}
    flat = list(mesh.devices.flat)
    return [k for k, d in enumerate(flat) if d.id in local]


def local_rating_mask(part, row_idx, mesh=None, positions=None):
    """Boolean mask over COO ratings: True where the solved-side entity is
    owned by one of this process's mesh positions.  Feed the masked
    triples to the blocking builders so each host blocks only its shard —
    O(local nnz) host memory instead of O(total nnz).

    ``positions`` overrides the mesh-derived ownership (tests / custom
    placement); exactly one of ``mesh`` / ``positions`` is required."""
    if positions is None:
        if mesh is None:
            raise ValueError("pass mesh or positions")
        positions = local_positions(mesh)
    own = np.zeros(part.n_shards, dtype=bool)
    own[list(positions)] = True
    return own[part.owner[np.asarray(row_idx)]]
