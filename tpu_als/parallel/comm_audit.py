"""Collective-traffic audit: count the bytes the *traced computation*
actually moves, straight from the jaxpr.

``trainer.comm_bytes_per_iter`` is a closed-form model (container-derived
arithmetic).  This module derives the same per-device quantity from the
step function's jaxpr — every ``all_gather`` / ``ppermute`` / ``psum`` /
``all_to_all`` equation, scaled by the trip counts of enclosing ``scan``s
— so a divergence between what the step *compiles* and what the model
*claims* fails a test instead of silently mis-reporting the CLI traffic
line (VERDICT r3 weak #7: the model was only ever checked against its own
inputs).  The jaxpr is what XLA lowers, so this is the strongest
validation available without an on-chip profiler trace; the byte
conventions per primitive mirror the model's documented ones
(trainer.comm_bytes_per_iter docstring):

- ``all_gather``  → received bytes, ``(S−1)/S × |out|``
- ``ppermute``    → received bytes, ``|out|`` per rotation
- ``psum``        → bidirectional-ring all-reduce, ``2·(S−1)/S × |out|``
- ``all_to_all``  → sent + received minus the self slice,
  ``2·(S−1)/S × |out|``
- ``cond``        → one branch executes per call: branches moving equal
  totals count once; disagreeing branches raise (data-dependent traffic)
- ``while``       → a collective in the body OR the predicate raises
  (unbounded trip count cannot be scaled)
"""

from __future__ import annotations

import numpy as np

import jax


def _aval_bytes(aval):
    return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize


def _out_bytes(eqn):
    return sum(_aval_bytes(v.aval) for v in eqn.outvars
               if getattr(v, "aval", None) is not None)


def collective_bytes(fn, *args, axis_size):
    """Per-device collective bytes of one call of ``fn(*args)``.

    ``axis_size``: size of the (single) mesh axis the collectives run
    over — needed because psum/all_gather byte formulas depend on it and
    the jaxpr does not carry the mesh.

    Returns ``(total_bytes, breakdown)`` where breakdown maps primitive
    name -> bytes.  Raises on a collective inside a ``while`` whose trip
    count the jaxpr cannot bound (none exist in this codebase: the tile
    loops are static-bound ``fori_loop``s, which lower to ``scan``).
    """
    closed = jax.make_jaxpr(fn)(*args)
    breakdown = {}
    # one name set for both the byte counter and the while-loop guard —
    # a primitive recognized by one but not the other would let a
    # collective hide inside a while body uncounted
    COLLECTIVES = ("all_gather", "ppermute", "psum", "psum2",
                   "psum_invariant", "all_to_all")

    S = int(axis_size)

    def walk(jaxpr, mult, out):
        def add(name, nbytes):
            out[name] = out.get(name, 0) + int(nbytes)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "all_gather":
                gsize = int(eqn.params.get("axis_size", S))
                add(name, mult * (gsize - 1) / gsize * _out_bytes(eqn))
            elif name == "ppermute":
                add(name, mult * _out_bytes(eqn))
            elif name in ("psum", "psum2", "psum_invariant"):
                add("psum", mult * 2 * (S - 1) / S * _out_bytes(eqn))
            elif name == "all_to_all":
                add(name, mult * 2 * (S - 1) / S * _out_bytes(eqn))
            elif name == "scan":
                walk(eqn.params["jaxpr"].jaxpr,
                     mult * int(eqn.params["length"]), out)
            elif name == "while":
                # both sub-jaxprs run an unbounded number of times —
                # a collective in EITHER (a converged-everywhere psum
                # predicate is the classic case) is unscalable here
                if (_has_collective(eqn.params["body_jaxpr"].jaxpr)
                        or _has_collective(eqn.params["cond_jaxpr"].jaxpr)):
                    raise ValueError(
                        "collective inside a while loop with unbounded "
                        "trip count — the audit cannot scale it; use a "
                        "static-bound fori_loop/scan")
            elif name == "cond":
                # exactly one branch executes per call: counting all
                # branches would over-report.  Branches that move the
                # same total are counted once; disagreeing branches make
                # the per-iteration traffic data-dependent, which the
                # closed-form model cannot represent — raise.
                per_branch = []
                for br in eqn.params["branches"]:
                    sub = {}
                    walk(br.jaxpr, mult, sub)
                    per_branch.append(sub)
                # full per-primitive dicts, not grand totals: branches
                # moving the same bytes through DIFFERENT primitives
                # would make the breakdown's attribution data-dependent
                if any(d != per_branch[0] for d in per_branch[1:]):
                    raise ValueError(
                        "cond branches move different collective "
                        f"traffic {per_branch} — per-iteration traffic "
                        "is data-dependent and unauditable")
                for k, v in per_branch[0].items():
                    add(k, v)
            else:
                for p in ("jaxpr", "call_jaxpr"):
                    inner = eqn.params.get(p) if eqn.params else None
                    if inner is not None:
                        walk(getattr(inner, "jaxpr", inner), mult, out)

    def _has_collective(jaxpr):
        found = []

        def probe(jp):
            for eqn in jp.eqns:
                if eqn.primitive.name in COLLECTIVES:
                    found.append(eqn.primitive.name)
                for p in ("jaxpr", "call_jaxpr", "body_jaxpr",
                          "cond_jaxpr"):
                    inner = eqn.params.get(p) if eqn.params else None
                    if inner is not None:
                        probe(getattr(inner, "jaxpr", inner))
                for br in (eqn.params.get("branches", ())
                           if eqn.params else ()):
                    probe(getattr(br, "jaxpr", br))
        probe(jaxpr)
        return bool(found)

    walk(closed.jaxpr, 1, breakdown)
    # the jaxpr is per-program; under shard_map the collectives are
    # per-device ops already, so no further division
    return int(sum(breakdown.values())), breakdown
