"""Collective-traffic audit: count the bytes the *traced computation*
actually moves, straight from the jaxpr.

``trainer.comm_bytes_per_iter`` is a closed-form model (container-derived
arithmetic).  This module derives the same per-device quantity from the
step function's jaxpr — every ``all_gather`` / ``ppermute`` / ``psum`` /
``all_to_all`` equation, scaled by the trip counts of enclosing ``scan``s
— so a divergence between what the step *compiles* and what the model
*claims* fails a test instead of silently mis-reporting the CLI traffic
line (VERDICT r3 weak #7: the model was only ever checked against its own
inputs).  The jaxpr is what XLA lowers, so this is the strongest
validation available without an on-chip profiler trace; the byte
conventions per primitive mirror the model's documented ones
(trainer.comm_bytes_per_iter docstring):

- ``all_gather``  → received bytes, ``(S−1)/S × |out|``
- ``ppermute``    → received bytes, ``|out|`` per rotation
- ``psum``        → bidirectional-ring all-reduce, ``2·(S−1)/S × |out|``
- ``all_to_all``  → sent + received minus the self slice,
  ``2·(S−1)/S × |out|``
- ``cond``        → one branch executes per call: branches moving equal
  totals count once; disagreeing branches raise (data-dependent traffic)
- ``while``       → a collective in the body OR the predicate raises
  (unbounded trip count cannot be scaled)

:func:`remote_dma_bytes` extends the audit to traffic NO collective
primitive represents: the fused-comm ring kernel
(``solve_backend='gather_fused_ring'``) moves its inter-chip bytes with
``make_async_remote_copy`` *inside* a ``pallas_call``, visible only as
``dma_start`` equations in the kernel jaxpr.  The ``comm_audit`` contract
(analysis/contracts.py) pins both counters to
``trainer.comm_bytes_per_iter``'s closed forms.
"""

from __future__ import annotations

import numpy as np

import jax


def _aval_bytes(aval):
    return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize


def _out_bytes(eqn):
    return sum(_aval_bytes(v.aval) for v in eqn.outvars
               if getattr(v, "aval", None) is not None)


def collective_bytes(fn, *args, axis_size):
    """Per-device collective bytes of one call of ``fn(*args)``.

    ``axis_size``: size of the (single) mesh axis the collectives run
    over — needed because psum/all_gather byte formulas depend on it and
    the jaxpr does not carry the mesh.

    Returns ``(total_bytes, breakdown)`` where breakdown maps primitive
    name -> bytes.  Raises on a collective inside a ``while`` whose trip
    count the jaxpr cannot bound (none exist in this codebase: the tile
    loops are static-bound ``fori_loop``s, which lower to ``scan``).
    """
    closed = jax.make_jaxpr(fn)(*args)
    breakdown = {}
    # one name set for both the byte counter and the while-loop guard —
    # a primitive recognized by one but not the other would let a
    # collective hide inside a while body uncounted
    COLLECTIVES = ("all_gather", "ppermute", "psum", "psum2",
                   "psum_invariant", "all_to_all")

    S = int(axis_size)

    def walk(jaxpr, mult, out):
        def add(name, nbytes):
            out[name] = out.get(name, 0) + int(nbytes)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "all_gather":
                gsize = int(eqn.params.get("axis_size", S))
                add(name, mult * (gsize - 1) / gsize * _out_bytes(eqn))
            elif name == "ppermute":
                add(name, mult * _out_bytes(eqn))
            elif name in ("psum", "psum2", "psum_invariant"):
                add("psum", mult * 2 * (S - 1) / S * _out_bytes(eqn))
            elif name == "all_to_all":
                add(name, mult * 2 * (S - 1) / S * _out_bytes(eqn))
            elif name == "scan":
                walk(eqn.params["jaxpr"].jaxpr,
                     mult * int(eqn.params["length"]), out)
            elif name == "while":
                # both sub-jaxprs run an unbounded number of times —
                # a collective in EITHER (a converged-everywhere psum
                # predicate is the classic case) is unscalable here
                if (_has_collective(eqn.params["body_jaxpr"].jaxpr)
                        or _has_collective(eqn.params["cond_jaxpr"].jaxpr)):
                    raise ValueError(
                        "collective inside a while loop with unbounded "
                        "trip count — the audit cannot scale it; use a "
                        "static-bound fori_loop/scan")
            elif name == "cond":
                # exactly one branch executes per call: counting all
                # branches would over-report.  Branches that move the
                # same total are counted once; disagreeing branches make
                # the per-iteration traffic data-dependent, which the
                # closed-form model cannot represent — raise.
                per_branch = []
                for br in eqn.params["branches"]:
                    sub = {}
                    walk(br.jaxpr, mult, sub)
                    per_branch.append(sub)
                # full per-primitive dicts, not grand totals: branches
                # moving the same bytes through DIFFERENT primitives
                # would make the breakdown's attribution data-dependent
                if any(d != per_branch[0] for d in per_branch[1:]):
                    raise ValueError(
                        "cond branches move different collective "
                        f"traffic {per_branch} — per-iteration traffic "
                        "is data-dependent and unauditable")
                for k, v in per_branch[0].items():
                    add(k, v)
            else:
                for p in ("jaxpr", "call_jaxpr"):
                    inner = eqn.params.get(p) if eqn.params else None
                    if inner is not None:
                        walk(getattr(inner, "jaxpr", inner), mult, out)

    def _has_collective(jaxpr):
        found = []

        def probe(jp):
            for eqn in jp.eqns:
                if eqn.primitive.name in COLLECTIVES:
                    found.append(eqn.primitive.name)
                for p in ("jaxpr", "call_jaxpr", "body_jaxpr",
                          "cond_jaxpr"):
                    inner = eqn.params.get(p) if eqn.params else None
                    if inner is not None:
                        probe(getattr(inner, "jaxpr", inner))
                for br in (eqn.params.get("branches", ())
                           if eqn.params else ()):
                    probe(getattr(br, "jaxpr", br))
        probe(jaxpr)
        return bool(found)

    walk(closed.jaxpr, 1, breakdown)
    # the jaxpr is per-program; under shard_map the collectives are
    # per-device ops already, so no further division
    return int(sum(breakdown.values())), breakdown


def remote_dma_bytes(fn, *args, fires=None):
    """Per-device IN-KERNEL inter-chip bytes of one call of ``fn(*args)``:
    the remote-DMA payloads a Pallas kernel moves with
    ``make_async_remote_copy`` (ops.ring_buffer.remote_copy), which
    :func:`collective_bytes` cannot see — no collective primitive traces;
    the transfer is a ``dma_start`` equation inside the ``pallas_call``.

    A ``dma_start`` is REMOTE iff it carries a send/recv semaphore PAIR
    (local copies have exactly one DMA semaphore); its payload is the
    source ref's aval.  Multiplicity is a SCHEDULE, not derivable from
    the jaxpr alone; the default is the fused-comm ring's contract
    (ops.pallas_gather_ne._gather_solve_ring_kernel): grid ``(row_tiles,
    ring_steps, width_chunks)``, ONE transfer per (row tile, step ``t <=
    S-2``) — the parity-variant ``dma_start``s are mutually exclusive
    ``cond`` arms of that one transfer, so the audit requires them to
    move identical payloads and counts ``grid[0] * (grid[1] - 1)`` fires
    per kernel call, refusing any other grid arity.  A kernel with a
    different schedule passes ``fires``, a callable mapping the kernel's
    grid tuple to its fire count (the serving merge ring
    — ops.pallas_topk._topk_merge_ring_kernel, grid ``(user_tiles,
    score_phases + S)``, one transfer per (user tile, hop) — passes
    ``lambda g: g[0] * (S - 1)`` from the ``serve_comm_audit`` contract).
    The identical-payload rule applies either way: a kernel whose remote
    arms disagree on payload is data-dependent traffic → raise, same
    policy as :func:`collective_bytes`'s ``cond`` rule.

    Returns ``(total_bytes, per_call)`` where ``per_call`` lists each
    ``pallas_call``'s contribution (scan-scaled).
    """
    closed = jax.make_jaxpr(fn)(*args)
    per_call = []

    def payload_bytes(eqn):
        # the transferred extent, not the full source ref: a send from a
        # dynamically-indexed slot (``ref.at[slot]`` — the serving merge
        # ring's collect buffer) carries the ref WHOLE in invars[0] with
        # the indexer in params['tree']; reconstruct it and price the
        # indexer shape.  Refs sent whole have no transform and fall
        # through to the full aval (the fused-comm ring's landing
        # buffers — byte-identical to the pre-extension audit).
        aval = eqn.invars[0].aval
        try:
            unflat = jax.tree_util.tree_unflatten(
                eqn.params["tree"], list(eqn.invars))
            transforms = unflat[1]
            if transforms:
                shape = tuple(transforms[-1].get_indexer_shape())
                return (int(np.prod(shape))
                        * np.dtype(aval.dtype).itemsize)
        except Exception:
            pass
        return _aval_bytes(aval)

    def kernel_remote_payloads(jaxpr, out):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dma_start":
                sems = [v for v in eqn.invars
                        if "semaphore" in str(getattr(v, "aval", ""))]
                if len(sems) >= 2:
                    out.append(payload_bytes(eqn))
            for p in ("jaxpr", "call_jaxpr", "body_jaxpr", "cond_jaxpr"):
                inner = eqn.params.get(p) if eqn.params else None
                if inner is not None:
                    kernel_remote_payloads(
                        getattr(inner, "jaxpr", inner), out)
            for br in (eqn.params.get("branches", ())
                       if eqn.params else ()):
                kernel_remote_payloads(getattr(br, "jaxpr", br), out)

    def walk(jaxpr, mult):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "pallas_call":
                payloads = []
                kernel_remote_payloads(eqn.params["jaxpr"], payloads)
                if not payloads:
                    continue
                if len(set(payloads)) != 1:
                    raise ValueError(
                        "remote-DMA arms move different payloads "
                        f"{sorted(set(payloads))} — data-dependent "
                        "traffic is unauditable")
                grid = tuple(eqn.params["grid_mapping"].grid)
                if fires is not None:
                    n_fires = int(fires(grid))
                else:
                    if len(grid) != 3:
                        raise ValueError(
                            f"remote-DMA kernel with grid {grid}: the "
                            "default audit only knows the fused-comm "
                            "ring schedule (row_tiles, ring_steps, "
                            "width_chunks) — pass ``fires`` for other "
                            "schedules")
                    n_fires = grid[0] * max(0, grid[1] - 1)
                per_call.append(mult * payloads[0] * n_fires)
            elif name == "scan":
                walk(eqn.params["jaxpr"].jaxpr,
                     mult * int(eqn.params["length"]))
            elif name == "cond":
                for br in eqn.params["branches"]:
                    walk(br.jaxpr, mult)
            else:
                for p in ("jaxpr", "call_jaxpr"):
                    inner = eqn.params.get(p) if eqn.params else None
                    if inner is not None:
                        walk(getattr(inner, "jaxpr", inner), mult)

    walk(closed.jaxpr, 1)
    return int(sum(per_call)), per_call
