"""Device mesh helpers — the substrate for the sharded trainer.

The reference stack's scale-out substrate is Spark's cluster runtime
(executors + netty RPC + sort shuffle, SURVEY.md §2.B8/§2.C2).  Here the
substrate is a 1-D ``jax.sharding.Mesh`` with a single ``"d"`` axis: user
factors, item factors, and rating shards are all partitioned along it, and
each ALS half-step all-gathers the opposite factor shard over ICI (ring
``ppermute`` streaming at the scale where a full gather no longer fits —
tpu_als.parallel.comm).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

AXIS = "d"


def make_mesh(n_devices=None, devices=None, axis=AXIS):
    """1-D mesh over the first ``n_devices`` (default: all) devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def shard_leading(mesh, axis=AXIS):
    """NamedSharding that splits the leading array axis over the mesh."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh):
    return NamedSharding(mesh, P())
