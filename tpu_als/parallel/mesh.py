"""Device mesh helpers — the substrate for the sharded trainer.

The reference stack's scale-out substrate is Spark's cluster runtime
(executors + netty RPC + sort shuffle, SURVEY.md §2.B8/§2.C2).  Here the
substrate is a 1-D ``jax.sharding.Mesh`` with a single ``"d"`` axis: user
factors, item factors, and rating shards are all partitioned along it, and
each ALS half-step either all-gathers the opposite factor shard, streams it
around a ``ppermute`` ring, or exchanges referenced rows with
``all_to_all`` (tpu_als.parallel.{trainer,comm,a2a}).

Multi-slice (DCN) awareness: on a multi-slice deployment the devices of one
slice share ICI while slices talk over the much slower data-center network.
All three gather strategies move data between *neighboring* positions of
the 1-D axis (a ring permute, or the segment layout of an all_gather), so
the whole DCN story reduces to **device order**: :func:`make_mesh` orders
devices slice-major (all of slice 0, then slice 1, …), which makes ring
neighbors ICI-local with exactly one DCN hop per slice boundary and lets
XLA schedule the intra-slice part of each collective on ICI.  This mirrors the
scaling-book recipe: pick the mesh so collectives ride ICI, not DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

AXIS = "d"

# jax moved shard_map from jax.experimental into the top-level namespace
# (and renamed check_rep -> check_vma on the way); resolve whichever this
# jax has so trainer/serve import on both sides of the move (one
# definition — both consumers alias this)
try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


def _default_slice_of(device):
    """The platform's slice assignment: ``device.slice_index`` on
    multi-slice TPU deployments, None elsewhere (single slice, CPU)."""
    return getattr(device, "slice_index", None)


def simulated_slice_of(n_slices, all_devices=None):
    """A ``slice_of`` callable that partitions ``all_devices`` (default:
    ``jax.devices()``) into ``n_slices`` equal contiguous-by-id groups.

    CPU devices carry no ``slice_index``, so the multi-slice code path —
    slice-major ordering, boundary accounting, collectives whose device
    order crosses a slice boundary — could otherwise never be exercised
    without pod hardware.  Tests and the driver dryrun pass this to
    :func:`make_mesh` to pin that path on the forced-host-device CPU
    backend (SURVEY.md §5.8 "DCN across slices").
    """
    devices = sorted(all_devices or jax.devices(), key=lambda d: d.id)
    per = max(1, (len(devices) + n_slices - 1) // n_slices)
    assignment = {d.id: k // per for k, d in enumerate(devices)}
    return lambda d: assignment[d.id]


def order_devices_slice_major(devices, slice_of=None):
    """Sort devices so same-slice devices are contiguous.

    ``slice_of`` maps a device to its slice index; the default reads
    ``device.slice_index`` where the platform exposes it (multi-slice
    TPU deployments; single-slice and CPU devices don't have it and keep
    their given order).  The sort is stable on the slice index alone, so
    a caller-chosen intra-slice order (e.g. a custom ring) is preserved.
    """
    slice_of = slice_of or _default_slice_of
    devices = list(devices)
    if any(slice_of(d) is not None for d in devices):
        devices.sort(key=lambda d: slice_of(d) or 0)
    return devices


def make_mesh(n_devices=None, devices=None, axis=AXIS, slice_of=None):
    """1-D mesh over ``n_devices`` (default: all) devices, slice-major
    ordered.  Ordering happens BEFORE truncation, so asking for one slice's
    worth of devices on a multi-slice deployment yields ICI-connected
    devices of the first slice, not an interleaved sample crossing DCN.
    ``slice_of`` overrides the platform slice assignment (see
    :func:`simulated_slice_of`)."""
    if devices is None:
        devices = order_devices_slice_major(jax.devices(), slice_of)
        if n_devices is not None:
            if n_devices > len(devices):
                # fixed at depth (advisor r4): every caller — CLI train,
                # CLI recommend, library users — must get an error, not
                # a silently smaller mesh than requested
                raise ValueError(
                    f"requested a {n_devices}-device mesh but only "
                    f"{len(devices)} devices are visible; refusing to "
                    "build a silently smaller mesh")
            devices = devices[:n_devices]
    else:
        devices = order_devices_slice_major(devices, slice_of)
    return Mesh(np.asarray(devices), (axis,))


def slice_boundaries(devices, slice_of=None):
    """Positions in the 1-D (slice-major) order where a DCN hop occurs —
    observability helper for the ring strategy's cost model: bytes moved
    over DCN per iteration = boundary_count × shard_bytes."""
    slice_of = slice_of or _default_slice_of
    devices = order_devices_slice_major(devices, slice_of)
    slices = [slice_of(d) or 0 for d in devices]
    return [k for k in range(1, len(slices)) if slices[k] != slices[k - 1]]


def shard_leading(mesh, axis=AXIS):
    """NamedSharding that splits the leading array axis over the mesh."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh):
    return NamedSharding(mesh, P())
