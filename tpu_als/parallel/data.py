"""Sharded ratings layout: balanced entity partitioning + stacked CSR shards.

This replaces the reference stack's ``partitionRatings`` / ``makeBlocks``
grid (hash-partitioned ``numUserBlocks × numItemBlocks`` rating blocks with
``LocalIndexEncoder``-packed ids — SURVEY.md §2.B4) with:

- a **count-balanced entity partition**: entities are dealt round-robin in
  descending rating-count order, so power-law degree skew does not serialize
  the mesh behind one hot shard — the analog of Spark's hash partitioner but
  load-aware;
- a **slot space**: entity e lives at ``slot[e] = owner*rows_per_shard +
  local_idx``, so the device-major ``all_gather`` of factor shards is
  directly indexable by slot ids (no shuffle, no index encoder);
- **stacked, shape-unified buckets**: every device's CSR buckets are padded
  to common shapes and stacked on a leading mesh axis, ready for
  ``shard_map``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_als.core.ratings import Bucket, build_csr_buckets, scan_chunk


@dataclass
class Partition:
    """Entity → (owner device, local slot) assignment for one side."""

    owner: np.ndarray  # [n] device id per entity
    local: np.ndarray  # [n] local row index on the owner
    rows_per_shard: int
    n_shards: int

    @property
    def slot(self):
        """Global position in the device-major gathered factor array."""
        return self.owner.astype(np.int64) * self.rows_per_shard + self.local

    @property
    def padded_rows(self):
        return self.n_shards * self.rows_per_shard


def partition_balanced(counts, n_shards):
    """Count-balanced partition: deal entities round-robin in descending
    rating-count order.

    With power-law rating counts a contiguous split would be dominated by
    the head of the distribution; the sorted round-robin deal keeps
    per-device half-step work near-uniform (within one entity's count of
    optimal per deal round) and is fully vectorized — O(n log n) host time
    at hundreds of millions of entities.
    """
    counts = np.asarray(counts)
    n = len(counts)
    order = np.argsort(-counts, kind="stable")
    owner = np.empty(n, dtype=np.int32)
    local = np.empty(n, dtype=np.int32)
    k = np.arange(n)
    owner[order] = (k % n_shards).astype(np.int32)
    local[order] = (k // n_shards).astype(np.int32)
    rows_per_shard = -(-n // n_shards)
    return Partition(owner=owner, local=local,
                     rows_per_shard=rows_per_shard, n_shards=n_shards)


@dataclass
class ShardedCsr:
    """Shape-unified, stacked CSR shards for one side.

    ``buckets[k]`` arrays have a leading [n_shards] axis; inside ``shard_map``
    each device sees its own [nb, w] block.  Row ids are device-local; col
    ids are opposite-side **slot** ids (index the gathered factor array).
    """

    buckets: list  # list[Bucket] with leading shard axis
    rows_per_shard: int
    chunk_elems: int
    nnz: int

    def device_buckets(self):
        return list(self.buckets)


def shard_csr(row_part, col_part, row_idx, col_idx, vals,
              min_width=8, chunk_elems=1 << 19):
    """Build per-device CSR buckets in slot space and stack them.

    row_part/col_part: Partition for the solved side / the gathered side.
    """
    D = row_part.n_shards
    owner = row_part.owner[row_idx]
    local_rows = row_part.local[row_idx]
    slot_cols = col_part.slot[col_idx]

    shards = []
    for d in range(D):
        sel = owner == d
        shards.append(
            build_csr_buckets(
                local_rows[sel], slot_cols[sel], np.asarray(vals)[sel],
                num_rows=row_part.rows_per_shard,
                min_width=min_width, chunk_elems=chunk_elems,
            )
        )
    return stack_shards(shards, chunk_elems)


def stack_shards(shards, chunk_elems):
    """Unify bucket shapes across shards and stack on a leading axis."""
    D = len(shards)
    num_rows = shards[0].num_rows
    widths = sorted({b.width for s in shards for b in s.buckets})
    stacked = []
    for w in widths:
        per = []
        for s in shards:
            match = [b for b in s.buckets if b.width == w]
            per.append(match[0] if match else None)
        nb_max = max(b.rows.shape[0] for b in per if b is not None)
        # keep row padding aligned to the scan chunk all shards will use
        chunk = scan_chunk(nb_max, w, chunk_elems)
        nb_max = -(-nb_max // chunk) * chunk
        rows = np.full((D, nb_max), num_rows, dtype=np.int32)
        cols = np.zeros((D, nb_max, w), dtype=np.int32)
        vals = np.zeros((D, nb_max, w), dtype=np.float32)
        mask = np.zeros((D, nb_max, w), dtype=np.float32)
        for d, b in enumerate(per):
            if b is None:
                continue
            nb = b.rows.shape[0]
            rows[d, :nb] = b.rows
            cols[d, :nb] = b.cols
            vals[d, :nb] = b.vals
            mask[d, :nb] = b.mask
        stacked.append(Bucket(rows=rows, cols=cols, vals=vals, mask=mask))
    return ShardedCsr(
        buckets=stacked,
        rows_per_shard=num_rows,
        chunk_elems=chunk_elems,
        nnz=sum(s.nnz for s in shards),
    )
