"""Sharded ratings layout: balanced entity partitioning + stacked CSR shards.

This replaces the reference stack's ``partitionRatings`` / ``makeBlocks``
grid (hash-partitioned ``numUserBlocks × numItemBlocks`` rating blocks with
``LocalIndexEncoder``-packed ids — SURVEY.md §2.B4) with:

- a **count-balanced entity partition**: entities are dealt round-robin in
  descending rating-count order, so power-law degree skew does not serialize
  the mesh behind one hot shard — the analog of Spark's hash partitioner but
  load-aware;
- a **slot space**: entity e lives at ``slot[e] = owner*rows_per_shard +
  local_idx``, so the device-major ``all_gather`` of factor shards is
  directly indexable by slot ids (no shuffle, no index encoder);
- **stacked, shape-unified buckets**: every device's CSR buckets are padded
  to common shapes and stacked on a leading mesh axis, ready for
  ``shard_map``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_als.core.ratings import (
    Bucket,
    build_csr_buckets,
    entity_widths,
    padded_bucket_rows,
)


@dataclass
class Partition:
    """Entity → (owner device, local slot) assignment for one side."""

    owner: np.ndarray  # [n] device id per entity
    local: np.ndarray  # [n] local row index on the owner
    rows_per_shard: int
    n_shards: int

    @property
    def slot(self):
        """Global position in the device-major gathered factor array."""
        return self.owner.astype(np.int64) * self.rows_per_shard + self.local

    @property
    def padded_rows(self):
        return self.n_shards * self.rows_per_shard


def partition_balanced(counts, n_shards):
    """Count-balanced partition: deal entities round-robin in descending
    rating-count order.

    With power-law rating counts a contiguous split would be dominated by
    the head of the distribution; the sorted round-robin deal keeps
    per-device half-step work near-uniform (within one entity's count of
    optimal per deal round) and is fully vectorized — O(n log n) host time
    at hundreds of millions of entities.
    """
    counts = np.asarray(counts)
    n = len(counts)
    order = np.argsort(-counts, kind="stable")
    owner = np.empty(n, dtype=np.int32)
    local = np.empty(n, dtype=np.int32)
    k = np.arange(n)
    owner[order] = (k % n_shards).astype(np.int32)
    local[order] = (k // n_shards).astype(np.int32)
    rows_per_shard = -(-n // n_shards)
    return Partition(owner=owner, local=local,
                     rows_per_shard=rows_per_shard, n_shards=n_shards)


@dataclass
class ShardedCsr:
    """Shape-unified, stacked CSR shards for one side.

    ``buckets[k]`` arrays have a leading [n_shards] axis; inside ``shard_map``
    each device sees its own [nb, w] block.  Row ids are device-local; col
    ids are opposite-side **slot** ids (index the gathered factor array).
    """

    buckets: list  # list[Bucket] with leading shard axis
    rows_per_shard: int
    chunk_elems: int
    nnz: int
    # None = full build (leading axis spans every mesh position); a tuple
    # = process-local build holding exactly these positions, in order
    # (data for jax.make_array_from_process_local_data assembly)
    positions: tuple = None

    def device_buckets(self):
        return list(self.buckets)


def shard_layout(row_part, row_counts, min_width=8, chunk_elems=1 << 19,
                 width_growth=2.0):
    """The globally-agreed stacked-bucket layout: ``[(width, padded_nb)]``.

    Computable on EVERY host from the per-entity rating counts alone
    (O(num_entities), no rating data) — the agreement step of multi-host
    blocking: each process builds only its own shards
    (:func:`shard_csr` ``positions=``) into identical global shapes, so
    ``jax.make_array_from_process_local_data`` can assemble one global
    array per bucket leaf.  Multi-host deployments obtain global counts
    with one count exchange (each host bincounts its local ratings; sum) —
    O(num_entities) traffic, vs the O(nnz) rating set that never leaves
    its host.  Mirrors the arithmetic of the full build exactly (per-shard
    chunk padding, then cross-shard max, then re-pad to the common chunk).
    """
    counts = np.asarray(row_counts)
    D = row_part.n_shards
    rated = counts > 0
    w_all = entity_widths(counts, min_width, width_growth)
    layout = []
    for w in sorted(set(w_all[rated].tolist())):
        sel = rated & (w_all == w)
        nb_d = np.bincount(row_part.owner[sel], minlength=D)
        nb_max = max(padded_bucket_rows(int(nb), w, chunk_elems)
                     for nb in nb_d if nb)
        layout.append((w, padded_bucket_rows(nb_max, w, chunk_elems)))
    return layout


def shard_csr(row_part, col_part, row_idx, col_idx, vals,
              min_width=8, chunk_elems=1 << 19, positions=None,
              row_counts=None):
    """Build per-device CSR buckets in slot space and stack them.

    row_part/col_part: Partition for the solved side / the gathered side.

    ``positions``: build ONLY these mesh positions' shards (multi-host —
    the caller feeds just its local ratings, ``multihost.local_rating_mask``)
    laid out in the global shapes from :func:`shard_layout`; requires
    ``row_counts`` = GLOBAL per-entity counts of the solved side.  The
    resulting leading axis is ``len(positions)`` in the given order, and
    slicing a full build at ``positions`` yields bit-identical arrays.
    """
    D = row_part.n_shards
    row_idx = np.asarray(row_idx)
    owner = row_part.owner[row_idx]
    local_rows = row_part.local[row_idx]
    slot_cols = col_part.slot[np.asarray(col_idx)]

    local = positions is not None
    if positions is None:
        positions = range(D)
    elif row_counts is None:
        # local ratings cannot derive the GLOBAL layout: silently using
        # them would give this host different bucket shapes than its peers
        raise ValueError(
            "positions= requires row_counts (global per-entity counts of "
            "the solved side; multi-host deployments sum per-host "
            "bincounts — see shard_layout)")
    if row_counts is None:
        if len(row_idx):
            row_counts = np.bincount(row_idx, minlength=len(row_part.owner))
        else:
            row_counts = np.zeros(len(row_part.owner), np.int64)
    layout = shard_layout(row_part, row_counts, min_width, chunk_elems)

    shards = []
    for d in positions:
        sel = owner == d
        shards.append(build_csr_buckets(
            local_rows[sel], slot_cols[sel], np.asarray(vals)[sel],
            num_rows=row_part.rows_per_shard,
            min_width=min_width, chunk_elems=chunk_elems,
        ))
    return stack_shards(shards, chunk_elems, layout=layout,
                        positions=(tuple(positions) if local else None))


def stack_shards(shards, chunk_elems, layout=None, positions=None):
    """Unify bucket shapes across shards and stack on a leading axis.

    ``layout``: optional precomputed ``[(width, padded_nb)]`` (the
    multi-host agreement from :func:`shard_layout`); default = derive it
    from the shards themselves (single-host path — same arithmetic).
    Every built width must appear in the layout: a mismatch means the
    ``row_counts`` the layout came from disagree with the actual triples,
    and dropping the bucket would silently lose ratings.
    """
    num_rows = shards[0].num_rows
    built_widths = sorted({b.width for s in shards for b in s.buckets})
    if layout is None:
        layout = []
        for w in built_widths:
            nb_max = max(b.rows.shape[0] for s in shards for b in s.buckets
                         if b.width == w)
            # keep row padding aligned to the scan chunk all shards use
            layout.append((w, padded_bucket_rows(nb_max, w, chunk_elems)))
    missing = set(built_widths) - {w for w, _ in layout}
    if missing:
        raise ValueError(
            f"built buckets of widths {sorted(missing)} have no layout "
            "entry — row_counts disagree with the rating triples "
            "(stale counts?); refusing to silently drop ratings")
    D = len(shards)
    stacked = []
    for w, nb_max in layout:
        rows = np.full((D, nb_max), num_rows, dtype=np.int32)
        cols = np.zeros((D, nb_max, w), dtype=np.int32)
        vals = np.zeros((D, nb_max, w), dtype=np.float32)
        mask = np.zeros((D, nb_max, w), dtype=np.float32)
        for d, s in enumerate(shards):
            match = [b for b in s.buckets if b.width == w]
            if not match:
                continue
            b = match[0]
            nb = b.rows.shape[0]
            rows[d, :nb] = b.rows
            cols[d, :nb] = b.cols
            vals[d, :nb] = b.vals
            mask[d, :nb] = b.mask
        stacked.append(Bucket(rows=rows, cols=cols, vals=vals, mask=mask))
    return ShardedCsr(
        buckets=stacked,
        rows_per_shard=num_rows,
        chunk_elems=chunk_elems,
        nnz=sum(s.nnz for s in shards),
        positions=positions,
    )
