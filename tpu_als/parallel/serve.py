"""Sharded top-k serving: ``recommendForAll*`` over a device mesh.

The reference serves recommendations with the same machinery it trains
with — blockified factor RDDs, cross-join GEMMs, and a shuffle-merged
``BoundedPriorityQueue`` per user (``MatrixFactorizationModel.
recommendProductsForUsers`` / ``ALSModel.recommendForAllUsers``,
SURVEY.md §3.3).  At config-3 scale (SURVEY.md §6: ~48M items × rank 256)
the opposite factor table no longer fits one device for SERVING any more
than it does for training, so this module gives the serving path the same
two scale-out strategies the trainer has (``parallel/trainer.py``):

- ``all_gather``: query rows stay sharded; each device gathers the full
  item table once and runs the single-device chunked GEMM + running
  ``lax.top_k`` scan (``ops/topk.py``).  One collective, full-table HBM.
- ``ring``: the item-factor shards stream around the mesh via
  ``ppermute`` (the training ring's dataflow re-used for serving); each
  device folds one shard's local top-k into its running (scores, ids)
  per step.  The full table never materializes — peak HBM is two shards
  + the [n, k] running state, and the cross-device traffic is the item
  table once around the ring plus nothing else (the [n, 2k] merge is
  local).
- ``merge_ring``: the in-kernel fused path (ops.pallas_topk.
  topk_merge_ring): queries replicate, each device scores its OWN
  resident shard inside one Pallas kernel, and the per-shard candidate
  sets rotate as ``make_async_remote_copy`` hops on the ring substrate,
  merged in VMEM — no XLA gather collective traces, no per-shard
  candidate list ever lands in HBM, and the wire bytes per query are
  independent of catalog size (perf.roofline.serve_merge_remote_bytes;
  pinned by the ``serve_comm_audit`` contract).  On TPU it is adopted
  only after the live-mesh probe ``pallas_topk.merge_ring_available``
  passes for THIS shard count — banked verdicts never steer collectives
  — and degrades to ``ring`` when the probe fails or ``k > 128``;
  off-TPU the interpret-mode kernel is dispatched unconditionally
  (tests/contracts; CPU serving engines prefer the compiled XLA
  strategies for throughput).

Tie-breaking note: with equal scores the selected index can differ
between ``all_gather`` and ``ring`` (merge order is shard-rotation
order, which differs per device); scores are always identical.
``merge_ring`` is stronger: its stable in-kernel merge reproduces the
single-device ``chunked_topk_scores`` tie-break bitwise (ids included)
whenever the score values themselves agree across contraction shapes.
"""

from __future__ import annotations

import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpu_als import obs
from tpu_als.ops.topk import NEG_INF, chunked_topk_scores
from tpu_als.parallel.mesh import AXIS, shard_map
from tpu_als.resilience import faults

STRATEGIES = ("all_gather", "ring", "merge_ring")


class ServeShardLost(RuntimeError):
    """A sharded top-k gather failed (lost/stale factor shard) and no
    last-good catalog is cached to degrade onto — the request cannot be
    answered.  Callers that can shed load should catch this; the first
    successful request after recovery repopulates the cache."""


# (V, valid) REFERENCES from the last successful single-process sharded
# serve, keyed by mesh device ids ONLY — the degraded path answers from
# this host-side catalog when a gather fails.  Keyed, not a single
# global: two meshes in one process (a pod host serving two slices, the
# test harness) must never answer each other's requests from the wrong
# catalog.  Bounded to ONE entry per mesh — the newest publish replaces
# whatever any strategy served before (an answer from catalog
# generation g is correct for every strategy, so per-strategy entries
# only multiplied full-catalog retention by len(STRATEGIES)) — and the
# entry shares the caller's arrays instead of copying (``np.asarray``
# on the already-converted serving arrays is a view).  One catalog
# reference per mesh is the availability price — see
# docs/resilience.md.  The lock guards the dict against concurrent
# serving threads (the engine loop plus direct callers).
_last_good = {}
_last_good_lock = threading.Lock()


def _cache_key(mesh):
    return tuple(int(d.id) for d in mesh.devices.flat)


def reset_last_good():
    """Drop the degraded-serving cache (tests; memory pressure)."""
    with _last_good_lock:
        _last_good.clear()


def _serve_degraded(U, k, Nu, mesh, strategy, reason, record):
    """Answer from the last-good catalog on ONE device.  Slower and
    possibly stale — but an answer, which beats a crash for a
    recommender (the scores were approximate to begin with)."""
    with _last_good_lock:
        entry = _last_good.get(_cache_key(mesh))
    if entry is None:
        raise ServeShardLost(
            f"sharded top-k failed ({reason}) and no last-good factors "
            "are cached for this mesh to serve degraded from")
    Vg, validg = entry
    kk = min(k, Vg.shape[0])
    obs.counter("serve.degraded")
    obs.emit("serve_degraded", strategy=strategy, reason=reason)
    s, ix = chunked_topk_scores(jnp.asarray(U), jnp.asarray(Vg),
                                jnp.asarray(validg), kk)
    out = (np.asarray(s)[:Nu], np.asarray(ix)[:Nu].astype(np.int32))
    record(Nu)
    return out


def _merge_topk(s1, i1, s2, i2, k):
    """Fold (s2, i2) into the running (s1, i1): one [n, k1+k2] top_k."""
    cat_s = jnp.concatenate([s1, s2], axis=1)
    cat_i = jnp.concatenate([i1, i2], axis=1)
    new_s, sel = jax.lax.top_k(cat_s, k)
    return new_s, jnp.take_along_axis(cat_i, sel, axis=1)


@functools.lru_cache(maxsize=32)
def _build(mesh, ni_loc, k, k_loc, strategy, item_chunk,
           tile_u=256, tile_i=512, interpret=False):
    """Compiled sharded top-k for one (mesh, shapes, k, strategy) tuple.

    ``jax.sharding.Mesh`` is hashable, so the cache key is exact; without
    the cache every serving call would rebuild the shard_map closure and
    recompile.  ``tile_u``/``tile_i``/``interpret`` only shape the
    ``merge_ring`` kernel instantiation (the XLA strategies ignore them;
    the defaults keep their cache keys unchanged).
    """
    D = mesh.devices.size

    def body_all_gather(U_loc, V_loc, valid_loc):
        V_full = jax.lax.all_gather(V_loc, AXIS, axis=0, tiled=True)
        valid_full = jax.lax.all_gather(valid_loc, AXIS, axis=0,
                                        tiled=True)
        return chunked_topk_scores(U_loc, V_full, valid_full, k,
                                   item_chunk=item_chunk)

    def body_ring(U_loc, V_loc, valid_loc):
        me = jax.lax.axis_index(AXIS)
        perm = [(i, (i + 1) % D) for i in range(D)]
        n = U_loc.shape[0]

        def step(t, carry):
            V_cur, valid_cur, s, ix = carry
            # device i starts with its own shard and receives from i-1:
            # after t permutes it holds shard (i - t) mod D
            owner = jax.lax.rem(me - t + D, D)
            sc_t, ix_t = chunked_topk_scores(U_loc, V_cur, valid_cur,
                                             k_loc,
                                             item_chunk=item_chunk)
            s, ix = _merge_topk(s, ix, sc_t,
                                owner.astype(jnp.int32) * ni_loc + ix_t,
                                k)
            return (jax.lax.ppermute(V_cur, AXIS, perm),
                    jax.lax.ppermute(valid_cur, AXIS, perm), s, ix)

        s0 = jnp.full((n, k), NEG_INF, dtype=jnp.float32)
        i0 = jnp.zeros((n, k), dtype=jnp.int32)
        _, _, s, ix = jax.lax.fori_loop(
            0, D, step, (V_loc, valid_loc, s0, i0))
        return s, ix

    def body_merge_ring(U_full, V_loc, valid_loc):
        from tpu_als.ops.pallas_topk import topk_merge_ring

        return topk_merge_ring(
            U_full, V_loc, valid_loc, k, axis_name=AXIS, n_shards=D,
            ni_loc=ni_loc, tile_u=tile_u, tile_i=tile_i,
            interpret=interpret)

    if strategy == "merge_ring":
        # queries replicate (serving batches are tiny next to the
        # catalog); the merged result is identical on every device, so
        # the replicated out_specs are sound under check_vma=False
        return jax.jit(shard_map(
            body_merge_ring, mesh=mesh,
            in_specs=(P(), P(AXIS), P(AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        ))
    body = body_all_gather if strategy == "all_gather" else body_ring
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    ))


def topk_sharded(U, V, k, mesh, strategy="all_gather", item_valid=None,
                 item_chunk=8192, return_info=False):
    """Top-k over a mesh: ``U`` rows sharded as queries, ``V`` rows
    sharded as the catalog.  Identical (up to tie-breaking) to
    ``chunked_topk_scores(U, V, valid, k')`` on one device, with
    ``k' = min(k, len(V))``.

    Return contract depends on the deployment: single-process → host
    numpy ``(scores [Nu, k'], indices [Nu, k'])``; multi-process
    (``jax.process_count() > 1``) → GLOBAL jax.Arrays whose row shards
    live across hosts — read ``.addressable_shards`` for this host's
    rows (``shard.index[0].start`` is the global row offset).  The
    higher-level ``ALSModel.recommendFor*`` surfaces refuse the
    multi-process case rather than crash mid-assembly.

    Degraded mode (single-process only): when the sharded execute fails
    — a lost/stale factor shard, a device error, or the ``serve.gather``
    fault point — the request is answered from the last catalog this
    SAME mesh successfully served (any strategy — newest publish wins;
    the cache holds one catalog reference per mesh) on one device
    instead of crashing
    (``serve.degraded`` counter + ``serve_degraded`` event); with no
    last-good catalog cached, the typed :class:`ServeShardLost` raises.
    ``return_info=True`` appends ``{"degraded": bool, "reason": ...}``
    to the return tuple so callers can surface staleness.
    """
    if strategy not in STRATEGIES:
        raise ValueError(f"unknown serving strategy {strategy!r} "
                         f"(expected one of {STRATEGIES})")
    t0 = time.perf_counter()

    def _record(nrows):
        # latency histogram + throughput counters: dict writes under a
        # lock, so instrumentation stays in the noise on the serve path
        obs.histogram("serve.request_seconds",
                      time.perf_counter() - t0, strategy=strategy)
        obs.counter("serve.requests")
        obs.counter("serve.rows", nrows)

    def _info(out, degraded, reason=None):
        return out + ({"degraded": degraded, "reason": reason},) \
            if return_info else out

    U = np.asarray(U, dtype=np.float32)
    V = np.asarray(V, dtype=np.float32)
    Nu, r = U.shape
    Ni = V.shape[0]
    if Ni == 0 or Nu == 0:
        kk = min(k, Ni)
        _record(Nu)
        return _info((np.zeros((Nu, kk), np.float32),
                      np.zeros((Nu, kk), np.int32)), False)
    valid = (np.ones(Ni, dtype=bool) if item_valid is None
             else np.asarray(item_valid, dtype=bool))
    D = mesh.devices.size
    k_eff = min(k, Ni)
    nu_loc = -(-Nu // D)
    ni_loc = -(-Ni // D)
    if strategy == "merge_ring":
        from tpu_als.utils.platform import on_tpu

        interpret = not on_tpu()
        if k_eff > 128:
            # one lane tile carries the in-kernel candidate set
            strategy = "ring"
        elif not interpret:
            # live-mesh probe, THIS shard count — a banked verdict for a
            # different mesh is a cache miss, never a steer (the
            # gather_fused_ring rule); a failed probe degrades to the
            # XLA ring instead of crashing serving
            from tpu_als.ops.pallas_topk import merge_ring_available

            if not merge_ring_available(r, k_eff, D):
                strategy = "ring"
    Vp = np.pad(V, ((0, D * ni_loc - Ni), (0, 0)))
    validp = np.pad(valid, (0, D * ni_loc - Ni))  # pad rows never win
    k_loc = min(k_eff, ni_loc)
    if strategy == "merge_ring":
        f = _build(mesh, ni_loc, k_eff, k_loc, strategy, item_chunk,
                   tile_u=min(256, -(-Nu // 8) * 8),
                   tile_i=min(512, -(-ni_loc // 128) * 128),
                   interpret=interpret)
        Up = U  # replicated queries; the kernel wrapper pads internally
    else:
        f = _build(mesh, ni_loc, k_eff, k_loc, strategy,
                   min(item_chunk, ni_loc if strategy == "ring"
                       else D * ni_loc))
        Up = np.pad(U, ((0, D * nu_loc - Nu), (0, 0)))
    # place shard-wise (NOT jnp.asarray, which would commit the FULL
    # padded catalog to one device before resharding — the exact OOM the
    # ring strategy exists to avoid at 48M-item scale)
    from tpu_als.parallel.mesh import replicated, shard_leading

    spec = shard_leading(mesh)
    u_spec = replicated(mesh) if strategy == "merge_ring" else spec
    multiproc = jax.process_count() > 1
    try:
        with obs.span("serve.topk", strategy=strategy):
            # fault point: raise = failed gather collective; corrupt =
            # a shard is stale/lost (nothing sane to execute against)
            if faults.check("serve.gather") == "corrupt":
                raise ServeShardLost("stale/lost factor shard")
            s, ix = f(jax.device_put(Up, u_spec),
                      jax.device_put(Vp, spec),
                      jax.device_put(validp, spec))
            if multiproc:
                # multi-process mesh: the result is a GLOBAL array whose
                # shards live across hosts — np.asarray would fail on
                # non-addressable shards.  Trim the query padding on
                # device (every process executes the same op) and hand
                # the global arrays back; the caller reads
                # .addressable_shards for its own rows.
                _record(Nu)
                return _info((s[:Nu], ix[:Nu]), False)
            out = np.asarray(s)[:Nu], np.asarray(ix)[:Nu]
    except (OSError, RuntimeError) as e:
        if multiproc:
            # every process must degrade identically for the fallback to
            # be coherent; with no way to agree on that here, fail loud
            raise
        reason = f"{type(e).__name__}: {e}"
        return _info(_serve_degraded(U, k, Nu, mesh, strategy, reason,
                                     _record), True, reason)
    with _last_good_lock:
        _last_good[_cache_key(mesh)] = (V, valid)
    _record(Nu)
    return _info(out, False)
