"""Sharded ALS trainer: ``shard_map`` half-steps with on-device collectives.

This is the replacement for the reference stack's distributed hot loop
(SURVEY.md §3.1): where Spark's ``computeFactors`` runs an executor↔executor
sort shuffle of factor messages twice per iteration, here each half-step is

    1. ``all_gather`` the opposite factor shard over the mesh (ICI), and
    2. a purely local bucketed solve for the rows this device owns,

inside one jitted ``shard_map`` — the exact design the north-star names
("every iteration runs on-device with an ``all_gather`` instead of a Spark
shuffle", BASELINE.json).  For implicit feedback the YᵀY precompute is a
``psum`` of per-shard partials — the analog of Spark's ``treeAggregate``.

Factor layout: slot space (tpu_als.parallel.data) — entity e's row lives at
``slot[e]`` in a ``[D*rows_per_shard, r]`` array sharded on the leading axis,
so the device-major gather is directly indexable by the slot ids stored in
the rating shards.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_als.core.als import AlsConfig, init_factors, local_half_step
from tpu_als.ops.solve import compute_yty
from tpu_als.parallel.mesh import AXIS

shard_map = jax.shard_map


def _squeeze0(tree):
    return jax.tree.map(lambda x: x[0], tree)


def make_sharded_step(mesh, user_sharded, item_sharded, cfg: AlsConfig):
    """Jitted full ALS iteration over the mesh.

    user_sharded/item_sharded: ShardedCsr (stacked host arrays; placed on
    device by the caller with a leading-axis sharding).
    Returns ``step(U, V) -> (U, V)`` on slot-space factor arrays sharded
    over ``mesh``.
    """
    n_shards = user_sharded.buckets[0].rows.shape[0]
    if mesh.devices.size != n_shards:
        raise ValueError(
            f"mesh has {mesh.devices.size} devices but the rating shards were "
            f"built for {n_shards}; a mismatch would silently drop shards"
        )
    per_u = user_sharded.rows_per_shard
    per_i = item_sharded.rows_per_shard
    u_chunk = user_sharded.chunk_elems
    i_chunk = item_sharded.chunk_elems

    def step_body(U_loc, V_loc, ubuckets, ibuckets):
        ubuckets = _squeeze0(ubuckets)
        ibuckets = _squeeze0(ibuckets)
        # --- item half-step: gather U, solve owned item rows ---
        U_full = jax.lax.all_gather(U_loc, AXIS, axis=0, tiled=True)
        if cfg.implicit_prefs:
            YtY_u = jax.lax.psum(compute_yty(U_loc), AXIS)
            V_new = local_half_step(U_full, ibuckets, per_i, cfg, YtY_u, i_chunk)
        else:
            V_new = local_half_step(U_full, ibuckets, per_i, cfg,
                                    chunk_elems=i_chunk)
        # --- user half-step: gather V, solve owned user rows ---
        V_full = jax.lax.all_gather(V_new, AXIS, axis=0, tiled=True)
        if cfg.implicit_prefs:
            YtY_v = jax.lax.psum(compute_yty(V_new), AXIS)
            U_new = local_half_step(V_full, ubuckets, per_u, cfg, YtY_v, u_chunk)
        else:
            U_new = local_half_step(V_full, ubuckets, per_u, cfg,
                                    chunk_elems=u_chunk)
        return U_new, V_new

    sharded = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def train_sharded(mesh, user_part, item_part, user_sharded, item_sharded,
                  cfg: AlsConfig, callback=None):
    """Distributed ALS training loop.  Returns slot-space (U, V) jax.Arrays
    sharded over ``mesh``; index with ``Partition.slot`` to get entity rows.
    """
    leading = NamedSharding(mesh, P(AXIS))
    ub = jax.device_put(user_sharded.device_buckets(), leading)
    ib = jax.device_put(item_sharded.device_buckets(), leading)

    key = jax.random.PRNGKey(cfg.seed)
    ku, kv = jax.random.split(key)
    # init in slot space: entity e's initial row is a function of its slot;
    # padding slots start at zero and stay zero (count==0 rows solve to 0)
    U = jax.device_put(
        _slot_init(ku, user_part, cfg.rank), leading
    )
    V = jax.device_put(
        _slot_init(kv, item_part, cfg.rank), leading
    )

    step = make_sharded_step(mesh, user_sharded, item_sharded, cfg)
    for it in range(cfg.max_iter):
        U, V = step(U, V, ub, ib)
        if callback is not None:
            callback(it + 1, U, V)
    return U, V


def _slot_init(key, part, rank):
    """Unit-norm gaussian rows scattered into slot positions.

    Row e of the dense init lands at slot[e], so a sharded run and a
    single-device run started from the same seed see identical per-entity
    initial factors (the equivalence tests rely on this).
    """
    import numpy as np

    n = len(part.owner)
    dense = init_factors(key, n, rank)
    out = np.zeros((part.padded_rows, rank), dtype=np.float32)
    out[np.asarray(part.slot)] = np.asarray(dense)
    return out
