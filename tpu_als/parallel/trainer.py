"""Sharded ALS trainer: ``shard_map`` half-steps with on-device collectives.

This is the replacement for the reference stack's distributed hot loop
(SURVEY.md §3.1): where Spark's ``computeFactors`` runs an executor↔executor
sort shuffle of factor messages twice per iteration, here each half-step is

    1. ``all_gather`` the opposite factor shard over the mesh (ICI), and
    2. a purely local bucketed solve for the rows this device owns,

inside one jitted ``shard_map`` — the exact design the north-star names
("every iteration runs on-device with an ``all_gather`` instead of a Spark
shuffle", BASELINE.json).  For implicit feedback the YᵀY precompute is a
``psum`` of per-shard partials — the analog of Spark's ``treeAggregate``.

Factor layout: slot space (tpu_als.parallel.data) — entity e's row lives at
``slot[e]`` in a ``[D*rows_per_shard, r]`` array sharded on the leading axis,
so the device-major gather is directly indexable by the slot ids stored in
the rating shards.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_als import obs
from tpu_als.core.als import AlsConfig, init_factors, local_half_step
from tpu_als.core.ratings import trainer_chunk
from tpu_als.ops.solve import compute_yty
from tpu_als.parallel.mesh import AXIS, shard_map
from tpu_als.resilience import faults
from tpu_als.resilience.elastic import DeviceLost


#: THE authoritative gather-strategy table.  The CLI's
#: ``--gather-strategy`` help, the :class:`tpu_als.api.estimator.ALS`
#: ``gatherStrategy`` docs and :func:`train_sharded` all render or
#: validate against THIS dict instead of restating it — three
#: hand-copied variants drifted apart once already (PR 15).  ``auto``
#: is a front-end name only (the CLI/estimator resolve it via the
#: execution planner before :func:`train_sharded` runs); the ring rows
#: additionally accept ``AlsConfig.solve_backend='gather_fused_ring'``,
#: which moves the rotation itself into the gather-solve kernel as
#: in-kernel remote DMAs (ops/pallas_gather_ne; one kernel per
#: half-step — identical traffic model, see :func:`comm_bytes_per_iter`).
GATHER_STRATEGIES = {
    "auto": "the execution planner's comm-model pick (tpu_als.plan; "
            "single-process mesh fits only)",
    "all_gather": "full opposite-factor gather per half-step "
                  "(the default)",
    "all_gather_chunked": "column-block gathers per row tile — the "
                          "full opposite table never materializes",
    "ring": "ppermute streaming: shards rotate around the mesh, "
            "accumulators stay put; opposite factors never "
            "materialize in full",
    "ring_overlap": "ring with the double-buffered "
                    "ppermute-under-einsum schedule — identical bytes, "
                    "the collective flies under the compute",
    "all_to_all": "ragged exchange of only the referenced rows "
                  "(needs the built A2aCsr request plans)",
}

#: The strategy names train_sharded actually executes ('auto' resolves
#: to one of these upstream).
EXECUTABLE_STRATEGIES = tuple(k for k in GATHER_STRATEGIES
                              if k != "auto")


def strategy_help(include_auto=True):
    """One-line rendering of :data:`GATHER_STRATEGIES` for CLI help /
    error messages — so callers print the table instead of copying it."""
    keys = GATHER_STRATEGIES if include_auto else EXECUTABLE_STRATEGIES
    return "; ".join(f"{k} = {GATHER_STRATEGIES[k]}" for k in keys)


class FactorsCorrupt(RuntimeError):
    """Non-finite factors detected after a collective step — the sharded
    equivalent of a torn message (a bad DMA, a poisoned reduction).  ALS
    cannot recover by iterating (NaN is a fixed point of the solve), so
    the loop must stop and resume from the last checkpoint."""


def _squeeze0(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _chaos_wrap_step(step):
    """Host-level ``comm.ring_step`` fault wrapper.

    Only installed when the point is ARMED (chaos runs): the disarmed
    builder returns the raw jitted step, so traced jaxprs — and the
    comm-audit byte models derived from them — are byte-identical to a
    build without fault injection, and the steady-state hot loop carries
    zero extra dispatch work.

    raise mode surfaces :class:`~tpu_als.resilience.faults.InjectedFault`
    before the step runs (a failed collective); corrupt mode poisons the
    user factors with NaN after it, which the armed-path finiteness check
    converts into the typed :class:`FactorsCorrupt`.
    """
    import jax.numpy as jnp

    def chaos_step(U, V, *args):
        mode = faults.check("comm.ring_step")
        U, V = step(U, V, *args)
        if mode == "corrupt":
            U = U * jnp.float32(jnp.nan)
        if not bool(jnp.isfinite(jnp.sum(U)) & jnp.isfinite(jnp.sum(V))):
            raise FactorsCorrupt(
                "non-finite factors after ring step — resume from the "
                "last checkpoint")
        return U, V

    return chaos_step


def _check_shard_containers(mesh, user_sharded, item_sharded):
    """Shared guard for every step builder: host containers hold either
    every mesh position's shard (single process) or exactly this
    process's (multi-host, ``positions`` metadata) — anything else would
    silently drop shards or scatter them onto the wrong devices."""
    for side, sharded in (("user", user_sharded), ("item", item_sharded)):
        n_shards = sharded.buckets[0].rows.shape[0]
        positions = getattr(sharded, "positions", None)
        if positions is not None:
            from tpu_als.parallel.multihost import local_positions

            if list(positions) != local_positions(mesh):
                raise ValueError(
                    f"{side} rating shards were built for mesh positions "
                    f"{list(positions)} but this process owns "
                    f"{local_positions(mesh)}; a mismatch would scatter "
                    "shards onto the wrong devices"
                )
        elif mesh.devices.size != n_shards:
            raise ValueError(
                f"mesh has {mesh.devices.size} devices but the {side} "
                f"rating shards were built for {n_shards}; a mismatch "
                "would silently drop shards"
            )


def _prewarm(cfg: AlsConfig, matfree_capable=True):
    """Probe the solve kernels EAGERLY in every step *builder*: a probe
    firing inside the shard_map jit trace cannot run, and the jit cache
    would pin the XLA fallback path for the compiled step's lifetime
    (tpu_als.utils.platform.probe_kernel).  Lives here — not only in
    train_sharded — so callers driving the builders directly get the
    same guarantee.  ``matfree_capable=False`` = the ring builder, whose
    solve cannot run matrix-free (attribution resolves to dense CG).

    This also covers the DMA-gather NE kernel's availability + timing
    probes (tpu_als.ops.pallas_gather_ne): under solve_backend='auto'
    the gather-fused upgrade inside local_half_step reads the cached
    outcomes this eager resolve populates — the all_gather and
    all_to_all builders route through local_half_step and inherit the
    kernel; the ring/chunked builders keep the einsum build (their
    normal equations accumulate across streamed shards in tpu_als.
    parallel.comm, which the per-bucket kernel does not model)."""
    from tpu_als.core.als import resolve_solve_path

    resolve_solve_path(cfg, cfg.rank, matfree_capable=matfree_capable)


def make_sharded_step(mesh, user_sharded, item_sharded, cfg: AlsConfig):
    """Jitted full ALS iteration over the mesh.

    user_sharded/item_sharded: ShardedCsr (stacked host arrays; placed on
    device by the caller with a leading-axis sharding).
    Returns ``step(U, V) -> (U, V)`` on slot-space factor arrays sharded
    over ``mesh``.
    """
    _check_shard_containers(mesh, user_sharded, item_sharded)
    _prewarm(cfg)
    per_u = user_sharded.rows_per_shard
    per_i = item_sharded.rows_per_shard
    u_chunk = user_sharded.chunk_elems
    i_chunk = item_sharded.chunk_elems

    def step_body(U_loc, V_loc, ubuckets, ibuckets):
        ubuckets = _squeeze0(ubuckets)
        ibuckets = _squeeze0(ibuckets)
        # --- item half-step: gather U, solve owned item rows ---
        with jax.named_scope("item_half_step"):
            U_full = jax.lax.all_gather(U_loc, AXIS, axis=0, tiled=True)
            if cfg.implicit_prefs:
                YtY_u = jax.lax.psum(compute_yty(U_loc), AXIS)
                V_new = local_half_step(U_full, ibuckets, per_i, cfg,
                                        YtY_u, i_chunk, prev=V_loc)
            else:
                V_new = local_half_step(U_full, ibuckets, per_i, cfg,
                                        chunk_elems=i_chunk, prev=V_loc)
        # --- user half-step: gather V, solve owned user rows ---
        with jax.named_scope("user_half_step"):
            V_full = jax.lax.all_gather(V_new, AXIS, axis=0, tiled=True)
            if cfg.implicit_prefs:
                YtY_v = jax.lax.psum(compute_yty(V_new), AXIS)
                U_new = local_half_step(V_full, ubuckets, per_u, cfg,
                                        YtY_v, u_chunk, prev=U_loc)
            else:
                U_new = local_half_step(V_full, ubuckets, per_u, cfg,
                                        chunk_elems=u_chunk, prev=U_loc)
        return U_new, V_new

    sharded = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def make_ring_step(mesh, user_ring, item_ring, cfg: AlsConfig,
                   overlap=False):
    """Jitted full ALS iteration with the ring (``ppermute``) strategy:
    factor shards stream around the mesh, normal-equation accumulators stay
    stationary, the full opposite factor matrix is never materialized
    (tpu_als.parallel.comm).  Signature: ``step(U, V, ub, ib, uc, ic)``.

    ``overlap=True`` is the double-buffered schedule (strategy name
    ``'ring_overlap'``): each rotation's ``ppermute`` is issued before the
    held shard's normal-equation accumulation so the collective-permute
    flies under the einsum.  Identical bytes and numerics-within-f32 to
    ``overlap=False``.
    """
    from tpu_als.parallel.comm import ring_half_step
    from tpu_als.utils.platform import on_tpu

    D = mesh.devices.size
    _check_shard_containers(mesh, user_ring, item_ring)
    per_u = user_ring.rows_per_shard
    per_i = item_ring.rows_per_shard
    u_chunk = user_ring.chunk_elems
    i_chunk = item_ring.chunk_elems
    _prewarm(cfg, matfree_capable=False)

    # fused-comm dispatch is decided HERE, at build time (the trace needs
    # a static branch): the explicit knob, minus nonnegative (NNLS has no
    # fused kernel — same precedence as everywhere), gated ON THE LIVE
    # MESH by the availability probe when compiled (a banked or stale
    # verdict must never steer a collective schedule — the multi-host
    # safety rule).  Off-TPU the kernel runs in interpret mode, no gate.
    interpret = not on_tpu()
    fused_ring = (cfg.solve_backend == "gather_fused_ring"
                  and not cfg.nonnegative)
    if fused_ring and not interpret:
        from tpu_als.ops import pallas_gather_ne

        if not pallas_gather_ne.ring_available(
                cfg.rank, cfg.compute_dtype, D):
            obs.event("ring_fused_unavailable", rank=cfg.rank,
                      n_shards=D, fallback="xla_ring")
            fused_ring = False

    def step_body(U_loc, V_loc, ubuckets, ibuckets, ucounts, icounts):
        ubuckets = _squeeze0(ubuckets)
        ibuckets = _squeeze0(ibuckets)
        ucounts = ucounts[0]
        icounts = icounts[0]
        with jax.named_scope("item_half_step"):
            YtY_u = (jax.lax.psum(compute_yty(U_loc), AXIS)
                     if cfg.implicit_prefs else None)
            V_new = ring_half_step(U_loc, ibuckets, icounts, per_i, D,
                                   cfg, i_chunk, YtY_u, prev=V_loc,
                                   overlap=overlap, fused=fused_ring,
                                   interpret=interpret)
        with jax.named_scope("user_half_step"):
            YtY_v = (jax.lax.psum(compute_yty(V_new), AXIS)
                     if cfg.implicit_prefs else None)
            U_new = ring_half_step(V_new, ubuckets, ucounts, per_u, D,
                                   cfg, u_chunk, YtY_v, prev=U_loc,
                                   overlap=overlap, fused=fused_ring,
                                   interpret=interpret)
        return U_new, V_new

    sharded = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(AXIS),) * 6,
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    jitted = jax.jit(sharded, donate_argnums=(0, 1))
    if faults.armed("comm.ring_step"):
        return _chaos_wrap_step(jitted)
    return jitted


def make_chunked_gather_step(mesh, user_sharded, item_sharded,
                             cfg: AlsConfig, n_blocks=4):
    """Jitted full ALS iteration with the chunked all_gather strategy
    (``'all_gather_chunked'``): the opposite factors are gathered in
    ``n_blocks`` column blocks per row tile and the ``[n, r, r]`` normal
    equations accumulate incrementally — the full opposite table is never
    materialized (tpu_als.parallel.comm.chunked_gather_half_step).
    Consumes the same ShardedCsr containers as the plain all_gather step;
    signature ``step(U, V, ub, ib)``.
    """
    from tpu_als.parallel.comm import chunked_gather_half_step

    D = mesh.devices.size
    _check_shard_containers(mesh, user_sharded, item_sharded)
    per_u = user_sharded.rows_per_shard
    per_i = item_sharded.rows_per_shard
    u_chunk = user_sharded.chunk_elems
    i_chunk = item_sharded.chunk_elems
    # same capability envelope as ring: the blockwise solve has no
    # matrix-free path (it never holds the full gathered table)
    _prewarm(cfg, matfree_capable=False)

    def step_body(U_loc, V_loc, ubuckets, ibuckets):
        ubuckets = _squeeze0(ubuckets)
        ibuckets = _squeeze0(ibuckets)
        with jax.named_scope("item_half_step"):
            YtY_u = (jax.lax.psum(compute_yty(U_loc), AXIS)
                     if cfg.implicit_prefs else None)
            V_new = chunked_gather_half_step(
                U_loc, ibuckets, per_i, D, cfg, i_chunk,
                n_blocks=n_blocks, YtY=YtY_u, prev=V_loc)
        with jax.named_scope("user_half_step"):
            YtY_v = (jax.lax.psum(compute_yty(V_new), AXIS)
                     if cfg.implicit_prefs else None)
            U_new = chunked_gather_half_step(
                V_new, ubuckets, per_u, D, cfg, u_chunk,
                n_blocks=n_blocks, YtY=YtY_v, prev=U_loc)
        return U_new, V_new

    sharded = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def make_a2a_step(mesh, user_a2a, item_a2a, cfg: AlsConfig):
    """Jitted full ALS iteration with the ragged ``all_to_all`` strategy:
    each device receives only the opposite-factor rows its rating shard
    references (tpu_als.parallel.a2a).  Signature: ``step(U, V, ub, ib,
    u_send, i_send)`` where u_send/i_send are the [D, D, R] request tables.
    """
    from tpu_als.parallel.a2a import a2a_half_step

    D = mesh.devices.size
    _check_shard_containers(mesh, user_a2a, item_a2a)
    per_u = user_a2a.rows_per_shard
    per_i = item_a2a.rows_per_shard
    u_chunk = user_a2a.chunk_elems
    i_chunk = item_a2a.chunk_elems
    _prewarm(cfg)

    def step_body(U_loc, V_loc, ubuckets, ibuckets, u_send, i_send):
        ubuckets = _squeeze0(ubuckets)
        ibuckets = _squeeze0(ibuckets)
        # each device's slice of a [D_src, D_dst, R] table = its OUTGOING
        # request lists; the item-side plan routes U rows and vice versa
        u_send = u_send[0]              # serves the U half-step (V rows)
        i_send = i_send[0]              # serves the V half-step (U rows)
        with jax.named_scope("item_half_step"):
            YtY_u = (jax.lax.psum(compute_yty(U_loc), AXIS)
                     if cfg.implicit_prefs else None)
            V_new = a2a_half_step(U_loc, i_send, ibuckets, per_i, cfg,
                                  i_chunk, YtY_u, prev=V_loc)
        with jax.named_scope("user_half_step"):
            YtY_v = (jax.lax.psum(compute_yty(V_new), AXIS)
                     if cfg.implicit_prefs else None)
            U_new = a2a_half_step(V_new, u_send, ubuckets, per_u, cfg,
                                  u_chunk, YtY_v, prev=U_loc)
        return U_new, V_new

    sharded = shard_map(
        step_body,
        mesh=mesh,
        in_specs=(P(AXIS),) * 6,
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def comm_bytes_per_iter(strategy, user_part, item_part, rank,
                        user_container=None, item_container=None,
                        implicit=False, compute_dtype="float32",
                        panel=16):
    """Per-device collective traffic for ONE full ALS iteration, in bytes
    — the "gather bytes" line of the observability spec (SURVEY.md §5.5).

    Model (f32 factors; per half-step the solved side receives the
    opposite side's rows):

    - ``all_gather``: the full opposite table minus the resident shard,
      ``(D−1)·rows_per_shard·r·4``.
    - ``ring`` / ``ring_overlap``: ``D·rows_per_shard·r·4`` per tile pass
      (every tile runs ALL ``D`` ppermute rotations so the shard ends
      home — no resident-shard discount), times the row-tile count read
      from the built ``RingCsr`` containers when given, else assumed 1.
      The double-buffered schedule reorders the same rotations, so its
      traffic is identical.
    - ``all_gather_chunked``: the column blocks of one tile pass sum to
      exactly one full gather, ``(D−1)·rows_per_shard·r·4`` — per row
      tile (unlike plain all_gather, which gathers once per half-step
      regardless of tiling), times the row-tile count from the built
      ``ShardedCsr`` containers when given, else assumed 1.
    - ``all_to_all``: only the requested rows move, ``(D−1)/D · D·R·r·4``
      received (+ the same sent); needs the built ``A2aCsr`` plans for R.
    - ``gather_fused_ring``: the in-kernel remote-DMA ring — see the
      branch comment below; ``compute_dtype``/``panel`` only matter here
      (the payload is the kernel's lane-padded shard in the compute
      dtype; ``panel`` sets the kernel row-tile size).
    - implicit adds one ``psum(YtY)`` per half-step: ``2·(D−1)/D·r²·4``
      with a bidirectional-ring cost model.
    """
    from tpu_als.perf.roofline import ring_remote_bytes

    D = user_part.n_shards
    fb = 4 * rank
    _db = jax.numpy.dtype(compute_dtype).itemsize

    def _r_pad(r):
        return max(128, -(-r // 128) * 128)

    def tiles(container):
        if container is None or not getattr(container, "buckets", None):
            return 1
        n = 0
        for b in container.buckets:
            S, nb, w = b.cols.shape[-3:]
            chunk = trainer_chunk(nb, w, rank, container.chunk_elems)
            n += nb // chunk
        return max(1, n)

    def _ring_tiles(container, r):
        # KERNEL row tiles: the fused ring tiles rows by _tiles_solve's
        # TN (the grid does the chunking — trainer_chunk never applies)
        from tpu_als.ops.pallas_gather_ne import _tiles_solve

        if container is None or not getattr(container, "buckets", None):
            return 1
        n = 0
        for b in container.buckets:
            S, nb, w = b.cols.shape[-3:]
            tn, _, _ = _tiles_solve(_r_pad(r), -(-w // 8) * 8, panel=panel)
            n += -(-nb // tn)
        return max(1, n)

    if strategy == "all_gather":
        half_u = (D - 1) * item_part.rows_per_shard * fb   # gathers V
        half_v = (D - 1) * user_part.rows_per_shard * fb   # gathers U
    elif strategy in ("ring", "ring_overlap"):
        half_u = D * item_part.rows_per_shard * fb * tiles(user_container)
        half_v = D * user_part.rows_per_shard * fb * tiles(item_container)
    elif strategy == "all_gather_chunked":
        half_u = ((D - 1) * item_part.rows_per_shard * fb
                  * tiles(user_container))
        half_v = ((D - 1) * user_part.rows_per_shard * fb
                  * tiles(item_container))
    elif strategy == "all_to_all":
        if user_container is None or item_container is None:
            raise ValueError("all_to_all traffic needs the built A2aCsr "
                             "plans (request budgets R)")
        # recv + send, excluding the self-shard slice
        half_u = 2 * (D - 1) * user_container.request_budget * fb
        half_v = 2 * (D - 1) * item_container.request_budget * fb
    elif strategy == "gather_fused_ring":
        # the in-kernel remote-DMA ring (solve_backend='gather_fused_ring'
        # under 'ring'/'ring_overlap'): every KERNEL row tile runs its own
        # (D−1)-rotation pass over the [rows_per_shard, r_pad] shard in
        # the compute dtype — no homecoming rotation (the kernel
        # re-streams from its immutable HBM copy), hence D−1 where the
        # XLA ring pays D; the payload is rank-PADDED because the kernel
        # ships its lane-padded V.  Same closed form as
        # perf.roofline.ring_remote_bytes, summed over both half-steps —
        # the extended comm_audit contract pins the traced in-kernel
        # remote-copy bytes to exactly this.
        half_u = (ring_remote_bytes(
            _ring_tiles(user_container, rank), D,
            item_part.rows_per_shard, _r_pad(rank), _db))
        half_v = (ring_remote_bytes(
            _ring_tiles(item_container, rank), D,
            user_part.rows_per_shard, _r_pad(rank), _db))
    else:
        raise ValueError(f"unknown strategy {strategy!r}")
    total = half_u + half_v
    if implicit:
        total += 2 * 2 * (D - 1) * rank * rank * 4 // D
    return int(total)


def stacked_counts(part, row_idx, vals=None, positive_only=False):
    """Per-row rating counts in [D, rows_per_shard] layout (for the ring
    strategy's λ·n ridge; ``positive_only`` mirrors the implicit-feedback
    ``numExplicits`` semantic)."""
    if positive_only and vals is None:
        raise ValueError("vals is required when positive_only=True")
    sel = (np.asarray(vals) > 0) if positive_only else slice(None)
    rows = np.asarray(row_idx)[sel] if positive_only else np.asarray(row_idx)
    out = np.zeros((part.n_shards, part.rows_per_shard), dtype=np.float32)
    np.add.at(out, (part.owner[rows], part.local[rows]), 1.0)
    return out


def train_sharded(mesh, user_part, item_part, user_sharded, item_sharded,
                  cfg: AlsConfig, callback=None, strategy="all_gather",
                  ring_counts=None, init=None, start_iter=0,
                  gather_blocks=4, elastic=False):
    """Distributed ALS training loop.  Returns slot-space (U, V) jax.Arrays
    sharded over ``mesh``; index with ``Partition.slot`` to get entity rows.

    strategy: any :data:`EXECUTABLE_STRATEGIES` row — the semantics live
    in :data:`GATHER_STRATEGIES` (the one authoritative table; 'auto' is
    resolved by the CLI/estimator before this runs).  Container
    contract per family: the gather strategies take ShardedCsr
    ('all_gather_chunked' reads ``gather_blocks``), the ring family
    takes RingCsr plus ``ring_counts=(user_counts, item_counts)`` from
    :func:`stacked_counts`, and 'all_to_all' takes A2aCsr from
    tpu_als.parallel.a2a.build_a2a.

    ``init``: optional entity-space ``(U0, V0)`` warm start (checkpoint
    resume, SURVEY.md §5.3); rows are scattered into slot space here.
    Resumes at ``start_iter``, running the remaining iterations.

    ``elastic=True`` wraps the jitted step with the host-level device-
    loss detector (resilience.elastic.wrap_step): a failed step is
    health-probed into transient-retry-in-place vs the typed
    ``DeviceLost`` (stamped with the failing iteration), which
    ``api.fitting.fit_sharded`` converts into mesh re-formation.  The
    wrapper never enters the traced graph, so the step jaxpr is
    byte-identical either way (the ``elastic_disarmed`` contract).
    """
    leading = NamedSharding(mesh, P(AXIS))
    with obs.span("train.stage", strategy=strategy):
        ub = jax.device_put(user_sharded.device_buckets(), leading)
        ib = jax.device_put(item_sharded.device_buckets(), leading)

    if init is not None:
        U0 = np.zeros((user_part.padded_rows, cfg.rank), dtype=np.float32)
        U0[np.asarray(user_part.slot)] = np.asarray(init[0])
        V0 = np.zeros((item_part.padded_rows, cfg.rank), dtype=np.float32)
        V0[np.asarray(item_part.slot)] = np.asarray(init[1])
        U = jax.device_put(U0, leading)
        V = jax.device_put(V0, leading)
    else:
        key = jax.random.PRNGKey(cfg.seed)
        ku, kv = jax.random.split(key)
        # init in slot space: entity e's initial row is a function of its
        # slot; padding slots start at zero and stay zero (count==0 rows
        # solve to 0)
        U = jax.device_put(
            _slot_init(ku, user_part, cfg.rank), leading
        )
        V = jax.device_put(
            _slot_init(kv, item_part, cfg.rank), leading
        )

    if strategy not in EXECUTABLE_STRATEGIES:
        raise ValueError(f"unknown strategy {strategy!r} (expected one "
                         f"of {EXECUTABLE_STRATEGIES}; "
                         f"{strategy_help(include_auto=False)})")
    with obs.span("train.build_step", strategy=strategy):
        if strategy == "all_to_all":
            us = jax.device_put(user_sharded.send_idx, leading)
            is_ = jax.device_put(item_sharded.send_idx, leading)
            step = make_a2a_step(mesh, user_sharded, item_sharded, cfg)
            args = (ub, ib, us, is_)
        elif strategy in ("ring", "ring_overlap"):
            if ring_counts is None:
                raise ValueError(
                    f"strategy={strategy!r} requires ring_counts="
                    "(user_counts, item_counts) from stacked_counts")
            uc, ic = ring_counts
            uc = jax.device_put(uc, leading)
            ic = jax.device_put(ic, leading)
            step = make_ring_step(mesh, user_sharded, item_sharded, cfg,
                                  overlap=(strategy == "ring_overlap"))
            args = (ub, ib, uc, ic)
        elif strategy == "all_gather_chunked":
            step = make_chunked_gather_step(
                mesh, user_sharded, item_sharded, cfg,
                n_blocks=gather_blocks)
            args = (ub, ib)
        else:
            step = make_sharded_step(mesh, user_sharded, item_sharded, cfg)
            args = (ub, ib)
    if elastic:
        from tpu_als.resilience import elastic as _elastic
        step = _elastic.wrap_step(step, mesh)
    for it in range(start_iter, cfg.max_iter):
        # dispatch time unless the callback (or donation pressure)
        # blocks — the per-iteration wall clock lives in the CLI's
        # iteration events; this span pins compile+dispatch outliers
        with obs.span("train.iteration", iteration=it + 1,
                      strategy=strategy):
            try:
                U, V = step(U, V, *args)
            except DeviceLost as e:
                if e.iteration is None:
                    e.iteration = it + 1   # stamp the failing iteration
                raise
            if callback is not None:
                callback(it + 1, U, V)
    return U, V


def _slot_init(key, part, rank):
    """Unit-norm gaussian rows scattered into slot positions.

    Row e of the dense init lands at slot[e], so a sharded run and a
    single-device run started from the same seed see identical per-entity
    initial factors (the equivalence tests rely on this).
    """
    n = len(part.owner)
    dense = init_factors(key, n, rank)
    out = np.zeros((part.padded_rows, rank), dtype=np.float32)
    out[np.asarray(part.slot)] = np.asarray(dense)
    return out
