"""Execution-mode orchestration behind ``ALS.fit``.

``fit`` (api/estimator.py) validates params, extracts columns, resolves id
maps and resume state, then dispatches here.  One function per execution
mode (SURVEY.md §2.E lanes):

- :func:`check_multiprocess_gate` — the FIRST collective of every
  multi-process fit: agree on every knob that decides which collectives
  follow, so a divergence raises instead of pairing mismatched
  collectives (a distributed hang).
- :func:`fit_multiprocess` — N processes × local devices, gloo/ICI
  collectives, replicated or per-host data (``parallel.multihost``).
- :func:`fit_sharded` — single process over a device mesh
  (``parallel.trainer``), all three gather strategies with the
  degenerate-a2a fallback.

Extracted from ``ALS.fit`` when it reached ~280 lines across four modes
(VERDICT r3 weak #8); behavior-preserving, pinned by the existing fit
equivalence tests (tests/test_sharded.py, tests/test_multihost.py).
"""

from __future__ import annotations

import numpy as np


def check_multiprocess_gate(est):
    """Allgather + compare the fit knobs every process must share.

    gatherStrategy decides WHICH collectives the compiled step issues
    (ring=ppermute, a2a=all_to_all, default=all_gather) and cgIters/cgMode
    decide the solver — a cross-process divergence in any of them pairs
    mismatched collectives or trains shards with different numerics.
    dataMode picks the id-map path; the observer knobs gate the
    fitCallback gathers.  With sharded checkpoints every peer's
    checkpointDir is load-bearing (each writes its own shard files), so a
    digest of the resolved dir rides along.
    """
    from jax.experimental import multihost_utils as mhu

    interval = est.getCheckpointInterval()
    ckpt_on = est.checkpointDir is not None and interval >= 1
    ckdir_digest = 0
    if est.checkpointSharded and ckpt_on and est.checkpointDir:
        import hashlib
        import os

        h = hashlib.blake2b(
            os.path.abspath(est.checkpointDir).encode(),
            digest_size=8).digest()
        ckdir_digest = int(np.frombuffer(h, dtype=np.int64)[0])
    if est.gatherStrategy == "auto":
        # the planner's model is deterministic, but the knob gate below
        # compares the REQUESTED strategy across hosts before shapes are
        # agreed — resolving after the gate would let a cache-divergent
        # host pair mismatched collectives.  Require explicitness here.
        raise ValueError(
            "gatherStrategy='auto' is not supported in multi-process "
            "fits — resolve it up front (tpu_als plan warm shows the "
            "modeled pick) and pass the same explicit strategy on every "
            "process")
    strat_code = ("all_gather", "ring",
                  "all_to_all").index(est.gatherStrategy)
    gate = np.asarray(mhu.process_allgather(np.array(
        [int(est.dataMode == "per_host"),
         int(est.fitCallback is not None),
         est.fitCallbackInterval,
         int(ckpt_on), interval,
         int(est.checkpointSharded), ckdir_digest,
         est.getMaxIter(),
         strat_code, est.cgIters,
         ("matfree", "dense").index(est.cgMode)],
        dtype=np.int64)))
    if not (gate == gate[0]).all():
        raise ValueError(
            "processes disagree on multi-process fit config "
            "(dataMode, fitCallback present, fitCallbackInterval, "
            "checkpointing, checkpointInterval, checkpointSharded, "
            "checkpointDir digest, maxIter, gatherStrategy, cgIters, "
            f"cgMode): {gate.tolist()} — pass the SAME knobs on every "
            "process (peers may use an inert callback; only process 0's "
            "is invoked)")


def check_finite_ratings_collective(local_nonfinite, rating_col):
    """Raise ON EVERY PROCESS when any host's ratings contain nan/inf.

    The single-process path raises immediately in ``fit``; here the
    decision must be collective — a one-host abort before the data
    collectives would strand the peers inside them (code-review r4).
    """
    from jax.experimental import multihost_utils as mhu

    counts = np.asarray(mhu.process_allgather(
        np.array([local_nonfinite], dtype=np.int64)))
    if counts.sum() > 0:
        raise ValueError(
            f"ratingCol {rating_col!r} contains non-finite values "
            f"(nan/inf) — per-process counts {counts.ravel().tolist()}; "
            "clean the input before fit")


def fit_multiprocess(est, u_idx, i_idx, r, user_map, item_map, cfg,
                     init, start_iter):
    """Multi-process fit: processes pass the SAME dataset
    (dataMode='replicated') or each its own disjoint split ('per_host';
    id maps agreed via global_id_union, triples redistributed inside
    train_multihost); blocking is per-host, training crosses hosts via
    collectives, and the fitted factors are re-replicated for the
    (driver-side) model object.  Same init/partitions/layout as the
    single-process mesh path -> identical factors (pinned by the
    two-process tests).  Checkpoint gathers are collective, writes
    process-0-only; fitCallback gathers entity-space factors every
    fitCallbackInterval iterations and is invoked on process 0 (the
    gather is the cost, the interval amortizes it).

    Returns entity-space ``(U, V)``.
    """
    import jax

    from tpu_als.parallel.multihost import (
        gather_entity_factors,
        train_multihost,
    )

    callback = est._checkpoint_callback(user_map, item_map)
    # observer/dataMode agreement was checked by the gate at the top of
    # fit — the FIRST collective on every path — so mp_cb's collectives
    # below fire in lockstep
    mp_cb = None
    last_gather = {}  # iteration -> (Ue, Ve); reused below so a
    # final-iteration gather isn't repeated after training (the most
    # expensive end-of-training collective)
    if callback is not None:
        def mp_cb(iteration, Us, Vs, up, ip):
            from jax.experimental import multihost_utils as mhu

            from tpu_als.resilience import preempt

            due_cb, due_ck = est._due(iteration)
            # preemption must be a COLLECTIVE decision: the signal lands
            # on one host, but every process must take the same save +
            # stop path or the survivors hang in the next collective
            stopping = bool(np.asarray(mhu.process_allgather(np.array(
                [int(preempt.pending(iteration))],
                dtype=np.int64))).sum() > 0)
            if stopping and est.checkpointDir is not None:
                due_ck = True  # force a resume point at this boundary
            if due_ck and est.checkpointSharded:
                # factor bytes never cross hosts: each process writes
                # its own shards (barriers inside); the gather below
                # then happens only when the callback needs it
                import os

                from tpu_als.parallel.multihost import (
                    save_checkpoint_sharded,
                )

                save_checkpoint_sharded(
                    os.path.join(est.checkpointDir, "als_checkpoint"),
                    Us, Vs, up, ip, user_map, item_map,
                    est.mesh, params=est._ckpt_params(),
                    iteration=iteration)
                due_ck = False
            if not (due_cb or due_ck or stopping):
                return
            # the gathers are collective: EVERY process runs them; only
            # process 0 observes the result
            Ue = gather_entity_factors(Us, up, est.mesh)
            Ve = gather_entity_factors(Vs, ip, est.mesh)
            last_gather.clear()
            last_gather[iteration] = (Ue, Ve)
            if jax.process_index() == 0:
                # same primitives the single-process callback composes,
                # gated by the shared _due rule
                if due_cb and est.fitCallback is not None:
                    est.fitCallback(iteration, Ue, Ve)
                if due_ck:
                    est._save_checkpoint(
                        user_map, item_map, iteration, Ue, Ve)
            if stopping:
                import os

                from tpu_als import obs

                path = (os.path.join(est.checkpointDir, "als_checkpoint")
                        if est.checkpointDir is not None else None)
                g = preempt.installed()
                signum = g.signum if g is not None else None
                obs.emit("preempted", iteration=iteration, signum=signum)
                raise preempt.Preempted(iteration, path, signum)

    Us, Vs, upart, ipart = train_multihost(
        u_idx, i_idx, r, len(user_map), len(item_map), cfg,
        mesh=est.mesh,
        replicated=est.dataMode == "replicated",
        strategy=est.gatherStrategy,
        init=init, start_iter=start_iter, callback=mp_cb)
    if cfg.max_iter in last_gather:
        return last_gather[cfg.max_iter]
    U = gather_entity_factors(Us, upart, est.mesh)
    V = gather_entity_factors(Vs, ipart, est.mesh)
    return U, V


def fit_sharded(est, u_idx, i_idx, r, user_map, item_map, cfg,
                init, start_iter):
    """Single-process fit over a device mesh, with elastic recovery.

    The happy path is one :func:`_fit_sharded_once` pass over
    ``est.mesh``.  With ``est.elastic`` on, a mid-fit device loss (the
    typed ``DeviceLost`` from the resilience.elastic detector) becomes a
    rescheduling event instead of a crash: the epoch since the last
    checkpoint is quarantined, the mesh re-forms on the surviving
    devices, partitions/containers/shard plan are re-derived for the new
    device count (the plan key carries it), and training re-enters the
    shrunk ring from the last atomic checkpoint — or from the original
    init when no checkpoint exists yet.  Each pass is deterministic
    given (mesh size, init, start_iter), so the recovered run is
    bitwise-identical to a fresh fit on the shrunk mesh resumed from the
    same checkpoint (the device-loss scenario pins this).

    Returns entity-space ``(U, V)``.
    """
    from tpu_als.resilience.elastic import DeviceLost

    mesh = est.mesh
    reforms = 0
    max_reforms = int(mesh.devices.size) - 1  # can't shrink below 1
    while True:
        try:
            return _fit_sharded_once(est, mesh, u_idx, i_idx, r,
                                     user_map, item_map, cfg, init,
                                     start_iter)
        except DeviceLost as e:
            if reforms >= max_reforms:
                raise
            reforms += 1
            mesh, init, start_iter = _reform_and_resume(
                est, mesh, e, cfg, user_map, item_map, init, start_iter)


def _reform_and_resume(est, mesh, exc, cfg, user_map, item_map,
                       orig_init, orig_start):
    """One elastic recovery: emit the device-loss record, rebuild the
    mesh from the survivors, and pick the resume point (last atomic
    checkpoint if one matches this fit, else the original init — the
    quarantined epoch is re-run in full).  Returns
    ``(new_mesh, init, start_iter)`` for the next training pass.  The
    event trail (``device_lost`` → ``mesh_reformed`` →
    ``elastic_resume`` + the ``elastic.*`` trace spans) is the recovery
    tree ``observe explain`` reconstructs from events.jsonl alone."""
    from tpu_als import obs
    from tpu_als.io.checkpoint import discover_resume, load_factors
    from tpu_als.obs import tracing
    from tpu_als.parallel.mesh import make_mesh

    lost = sorted(set(exc.lost))
    old = list(mesh.devices.flat)
    surviving = [d for d in old if int(d.id) not in set(lost)]
    if not surviving:
        raise exc
    obs.counter("train.reformations")
    obs.emit("device_lost", iteration=exc.iteration, lost=lost,
             surviving=len(surviving))
    ctx = tracing.start_trace("elastic.detect", iteration=exc.iteration,
                              lost=lost)
    import jax

    if jax.process_count() > 1:
        # the cross-host barrier must re-form before any collective on
        # the shrunk mesh (no-op single-process — every CPU test)
        from tpu_als.parallel.multihost import rejoin

        rejoin()
    new_mesh = make_mesh(devices=surviving)
    obs.emit("mesh_reformed", old_devices=len(old),
             new_devices=len(surviving), lost=lost)
    ctx = tracing.record_span(ctx, "elastic.reform",
                              old_devices=len(old),
                              new_devices=len(surviving))
    init, start_iter, source, path = orig_init, orig_start, "scratch", None
    if est.checkpointDir is not None:
        path = discover_resume(est.checkpointDir)
    if path is not None:
        manifest, c_uids, c_U, c_iids, c_V = load_factors(path)
        if (manifest.get("rank") == cfg.rank
                and np.array_equal(c_uids, user_map.ids)
                and np.array_equal(c_iids, item_map.ids)):
            init = (c_U, c_V)
            start_iter = int(manifest.get("iteration") or 0)
            source = "checkpoint"
        else:
            path = None  # a foreign checkpoint is not this fit's state
    extra = {"path": path} if source == "checkpoint" else {}
    obs.emit("elastic_resume", iteration=start_iter, source=source,
             devices=len(surviving), **extra)
    tracing.record_span(ctx, "elastic.resume", iteration=start_iter,
                        source=source)
    return new_mesh, init, start_iter


def _fit_sharded_once(est, mesh, u_idx, i_idx, r, user_map, item_map,
                      cfg, init, start_iter):
    """One training pass over ``mesh``: balanced entity partitions,
    per-strategy rating containers (with the degenerate-a2a -> all_gather
    fallback), traffic model bookkeeping, then ``train_sharded``.

    Returns entity-space ``(U, V)``.
    """
    from tpu_als import obs
    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.trainer import (
        comm_bytes_per_iter,
        stacked_counts,
        train_sharded,
    )

    callback = est._checkpoint_callback(user_map, item_map)
    D = mesh.devices.size
    obs.update_manifest(mesh_shape=list(mesh.devices.shape),
                        mesh_devices=int(D))
    with obs.span("train.partition"):
        upart = partition_balanced(
            np.bincount(u_idx, minlength=len(user_map)), D)
        ipart = partition_balanced(
            np.bincount(i_idx, minlength=len(item_map)), D)
    strategy = est.gatherStrategy
    if strategy == "auto":
        # planner resolve BEFORE container building (the container
        # layout is strategy-specific); deterministic given shapes —
        # tpu_als.plan.resolve_gather_strategy never takes the verdict
        # from the cache, only banks it for provenance
        from tpu_als import plan as _plan

        strategy = _plan.resolve_gather_strategy(
            requested="auto", n_users=len(user_map),
            n_items=len(item_map), rank=cfg.rank, n_devices=int(D),
            implicit=cfg.implicit_prefs)
    ring_counts = None
    with obs.span("train.block", strategy=strategy):
        if strategy in ("ring", "ring_overlap"):
            from tpu_als.parallel.comm import shard_csr_grid

            ush = shard_csr_grid(upart, ipart, u_idx, i_idx, r)
            ish = shard_csr_grid(ipart, upart, i_idx, u_idx, r)
            pos = cfg.implicit_prefs
            ring_counts = (
                stacked_counts(upart, u_idx, r, positive_only=pos),
                stacked_counts(ipart, i_idx, r, positive_only=pos))
        elif strategy == "all_to_all":
            from tpu_als.parallel.a2a import build_a2a

            ush = build_a2a(upart, ipart, u_idx, i_idx, r,
                            on_degenerate="stub")
            ish = build_a2a(ipart, upart, i_idx, u_idx, r,
                            on_degenerate="stub")
            if ush.degenerate or ish.degenerate:
                # one hot (src, dst) pair inflated the uniform request
                # budget to >= all_gather traffic — use the strategy that
                # actually bounds the bytes (build_a2a warned)
                strategy = "all_gather"
                ush = shard_csr(upart, ipart, u_idx, i_idx, r)
                ish = shard_csr(ipart, upart, i_idx, u_idx, r)
        else:
            ush = shard_csr(upart, ipart, u_idx, i_idx, r)
            ish = shard_csr(ipart, upart, i_idx, u_idx, r)

    # observability (SURVEY §5.5 "gather bytes"): per-device collective
    # traffic of the chosen strategy, readable after fit (the CLI prints
    # it).  `strategy` is the EFFECTIVE one (a degenerate a2a plan fell
    # back to all_gather above) — report that, not the request.
    est.lastFitCommBytes = comm_bytes_per_iter(
        strategy, upart, ipart, cfg.rank,
        user_container=ush, item_container=ish,
        implicit=cfg.implicit_prefs)
    est.lastFitStrategy = strategy
    obs.gauge("train.comm_bytes_per_iter", est.lastFitCommBytes,
              strategy=strategy)
    if strategy == "all_gather_chunked":
        # record the column-block plan the step will run with (trainer
        # default): bytes are block-count-invariant, resident gathered
        # slice is not — this is the number the rank-256 layout math uses
        from tpu_als.parallel.comm import gather_block_plan

        sub_u, _, _ = gather_block_plan(ipart.rows_per_shard, 4)
        obs.gauge("train.gather_block_rows", sub_u, n_blocks=4,
                  side="user_half")

    sharded_cb = None
    if callback is not None:
        def sharded_cb(iteration, U, V):  # slot space -> entity space
            if not est._callback_due(iteration):
                return  # nothing due: skip the full-factor fetch
            with obs.span("train.fetch_factors"):
                Ue = np.asarray(U)[upart.slot]
                Ve = np.asarray(V)[ipart.slot]
            callback(iteration, Ue, Ve)
    with obs.span("train.fit", strategy=strategy):
        Us, Vs = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                               callback=sharded_cb, init=init,
                               start_iter=start_iter, strategy=strategy,
                               ring_counts=ring_counts,
                               elastic=bool(getattr(est, "elastic",
                                                    False)))
        U = np.asarray(Us)[upart.slot]
        V = np.asarray(Vs)[ipart.slot]
    return U, V
