"""Legacy RDD-style API: ``ALS.train`` / ``MatrixFactorizationModel``.

Mirrors ``pyspark.mllib.recommendation`` (canonical upstream
``python/pyspark/mllib/recommendation.py`` — SURVEY.md §2.B2/§2.B6): the
functional ``train``/``trainImplicit`` entry points, the ``Rating`` tuple,
and the ``MatrixFactorizationModel`` method set.  In the reference these
delegate to the very same Scala ALS as the DataFrame API (SURVEY.md §3.4);
here they delegate to the same ``tpu_als.api.estimator.ALS`` core.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from tpu_als.api.estimator import ALS as _ALS, ALSModel
from tpu_als.utils.frame import ColumnarFrame


class Rating(NamedTuple):
    user: int
    product: int
    rating: float


def _to_frame(ratings):
    arr = [Rating(int(u), int(p), float(r)) for (u, p, r) in ratings]
    return ColumnarFrame({
        "user": np.asarray([a.user for a in arr], dtype=np.int64),
        "product": np.asarray([a.product for a in arr], dtype=np.int64),
        "rating": np.asarray([a.rating for a in arr], dtype=np.float32),
    })


class MatrixFactorizationModel:
    """Wraps the fitted factors with the legacy method names."""

    def __init__(self, model: ALSModel):
        self._model = model
        self.rank = model.rank

    # -- prediction -----------------------------------------------------
    def predict(self, user, product):
        return self._model.predict(user, product)

    def predictAll(self, user_product):
        """[(user, product)] -> [Rating] (prediction as the rating)."""
        pairs = list(user_product)
        frame = ColumnarFrame({
            "user": np.asarray([u for u, _ in pairs], dtype=np.int64),
            "product": np.asarray([p for _, p in pairs], dtype=np.int64),
        })
        out = self._model.transform(frame)
        return [
            Rating(int(u), int(p), float(s))
            for u, p, s in zip(out["user"], out["product"], out["prediction"])
        ]

    # -- recommendation -------------------------------------------------
    def recommendProducts(self, user, num):
        frame = ColumnarFrame({"user": np.asarray([user])})
        recs = self._model.recommendForUserSubset(frame, num)
        if len(recs) == 0:
            raise ValueError(f"user {user} not in the model")
        return [Rating(int(user), int(p), float(s))
                for p, s in recs["recommendations"][0]]

    def recommendUsers(self, product, num):
        frame = ColumnarFrame({"product": np.asarray([product])})
        recs = self._model.recommendForItemSubset(frame, num)
        if len(recs) == 0:
            raise ValueError(f"product {product} not in the model")
        return [Rating(int(u), int(product), float(s))
                for u, s in recs["recommendations"][0]]

    def recommendProductsForUsers(self, num):
        recs = self._model.recommendForAllUsers(num)
        return [
            (int(u), [Rating(int(u), int(p), float(s)) for p, s in rs])
            for u, rs in zip(recs[recs.columns[0]], recs["recommendations"])
        ]

    def recommendUsersForProducts(self, num):
        recs = self._model.recommendForAllItems(num)
        return [
            (int(p), [Rating(int(u), int(p), float(s)) for u, s in rs])
            for p, rs in zip(recs[recs.columns[0]], recs["recommendations"])
        ]

    # -- factor access ---------------------------------------------------
    def userFeatures(self):
        uf = self._model.userFactors
        return [(int(i), np.asarray(f)) for i, f in zip(uf["id"], uf["features"])]

    def productFeatures(self):
        itf = self._model.itemFactors
        return [(int(i), np.asarray(f)) for i, f in zip(itf["id"], itf["features"])]

    # -- persistence ------------------------------------------------------
    def save(self, path):
        self._model.save(path)

    @classmethod
    def load(cls, path):
        return cls(ALSModel.load(path))


class ALS:
    """Legacy functional entry points (``pyspark.mllib.recommendation.ALS``)."""

    @classmethod
    def train(cls, ratings, rank, iterations=5, lambda_=0.01, blocks=-1,
              nonnegative=False, seed=None):
        est = _ALS(
            rank=rank, maxIter=iterations, regParam=lambda_,
            nonnegative=nonnegative, seed=seed if seed is not None else 0,
            userCol="user", itemCol="product", ratingCol="rating",
        )
        if blocks > 0:
            est.setNumUserBlocks(blocks).setNumItemBlocks(blocks)
        return MatrixFactorizationModel(est.fit(_to_frame(ratings)))

    @classmethod
    def trainImplicit(cls, ratings, rank, iterations=5, lambda_=0.01,
                      blocks=-1, alpha=0.01, nonnegative=False, seed=None):
        est = _ALS(
            rank=rank, maxIter=iterations, regParam=lambda_, alpha=alpha,
            implicitPrefs=True, nonnegative=nonnegative,
            seed=seed if seed is not None else 0,
            userCol="user", itemCol="product", ratingCol="rating",
        )
        if blocks > 0:
            est.setNumUserBlocks(blocks).setNumItemBlocks(blocks)
        return MatrixFactorizationModel(est.fit(_to_frame(ratings)))
