"""Hyper-parameter tuning: ParamGridBuilder, CrossValidator,
TrainValidationSplit.

Mirrors the reference stack's ``pyspark.ml.tuning`` (SURVEY.md §2.B12): grid
construction keyed on Param objects, k-fold cross validation and a single
train/validation split, each refitting the estimator per param map and
scoring with an evaluator.  Fits within one host process — each inner fit is
itself a TPU training run.
"""

from __future__ import annotations

import itertools
import json
import os

import numpy as np

from tpu_als.utils.frame import as_frame


def _save_tuned(model, path, metrics_payload):
    """Shared persistence: best model via its own save + JSON metrics
    (the analog of ``DefaultParamsWriter`` metadata, SURVEY.md §2.B11).
    The best model's class is recorded so load restores the right type."""
    os.makedirs(path, exist_ok=True)
    best = model.bestModel
    if hasattr(best, "write"):  # inner replace is atomic (save_factors)
        best.write().overwrite().save(os.path.join(path, "bestModel"))
    else:
        best.save(os.path.join(path, "bestModel"))
    cls = type(best)
    metrics_payload["modelClass"] = f"{cls.__module__}.{cls.__qualname__}"
    tmp = os.path.join(path, "tuning.json.tmp")
    with open(tmp, "w") as f:
        json.dump(metrics_payload, f)
    os.replace(tmp, os.path.join(path, "tuning.json"))


def _load_tuned(path, kind):
    import importlib

    with open(os.path.join(path, "tuning.json")) as f:
        meta = json.load(f)
    if meta.get("kind") != kind:
        raise ValueError(
            f"{path} holds a {meta.get('kind')!r} tuning save, not {kind!r}")
    cls_path = meta.get("modelClass", "tpu_als.api.estimator.ALSModel")
    # tuning.json may come from an untrusted directory — never import an
    # arbitrary dotted path from it
    if not cls_path.startswith("tpu_als."):
        raise ValueError(
            f"refusing to load model class {cls_path!r} from {path}: "
            "only tpu_als.* model classes are loadable")
    mod, _, name = cls_path.rpartition(".")
    model_cls = getattr(importlib.import_module(mod), name)
    best = model_cls.load(os.path.join(path, "bestModel"))
    return best, meta


class ParamGridBuilder:
    def __init__(self):
        self._grid = {}

    def addGrid(self, param, values):
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args):
        base = {}
        for a in args:
            if isinstance(a, dict):
                base.update(a)
            else:
                k, v = a
                base[k] = v
        for k, v in base.items():
            self._grid[k] = [v]
        return self

    def build(self):
        keys = list(self._grid)
        combos = itertools.product(*(self._grid[k] for k in keys))
        return [dict(zip(keys, c)) for c in combos]


class _ValidatorBase:
    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 seed=None):
        self.estimator = estimator
        self.estimatorParamMaps = estimatorParamMaps
        self.evaluator = evaluator
        self.seed = seed

    def _fit_score(self, train, val):
        scores = []
        for pm in self.estimatorParamMaps:
            model = self.estimator.copy(pm).fit(train)
            scores.append(self.evaluator.evaluate(model.transform(val)))
        return scores

    def _best_index(self, avg):
        avg = np.asarray(avg)
        return int(np.nanargmax(avg) if self.evaluator.isLargerBetter()
                   else np.nanargmin(avg))


class CrossValidator(_ValidatorBase):
    """k-fold CV over the param grid; refits the best map on all data."""

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 numFolds=3, seed=None, collectSubModels=False):
        super().__init__(estimator, estimatorParamMaps, evaluator, seed)
        if numFolds < 2:
            raise ValueError("numFolds must be >= 2")
        self.numFolds = numFolds
        self.collectSubModels = collectSubModels

    def fit(self, dataset):
        frame = as_frame(dataset)
        rng = np.random.default_rng(self.seed)
        fold = rng.integers(0, self.numFolds, len(frame))
        metrics = np.zeros((len(self.estimatorParamMaps), self.numFolds))
        for f in range(self.numFolds):
            train = frame.filter(fold != f)
            val = frame.filter(fold == f)
            metrics[:, f] = self._fit_score(train, val)
        avg = metrics.mean(axis=1)
        best = self._best_index(avg)
        best_model = self.estimator.copy(self.estimatorParamMaps[best]).fit(frame)
        return CrossValidatorModel(best_model, avg.tolist(), metrics.tolist())


class CrossValidatorModel:
    def __init__(self, bestModel, avgMetrics, foldMetrics=None):
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics
        self.foldMetrics = foldMetrics

    def transform(self, dataset):
        return self.bestModel.transform(dataset)

    def write(self):
        from tpu_als.api.estimator import MLWriter

        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        _save_tuned(self, path, {"kind": "cv", "avgMetrics": self.avgMetrics,
                                 "foldMetrics": self.foldMetrics})

    @classmethod
    def load(cls, path):
        best, meta = _load_tuned(path, "cv")
        return cls(best, meta["avgMetrics"], meta.get("foldMetrics"))


class TrainValidationSplit(_ValidatorBase):
    """Single split tuning — ``trainRatio`` of the data trains, the rest
    validates; refits the best map on all data."""

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 trainRatio=0.75, seed=None):
        super().__init__(estimator, estimatorParamMaps, evaluator, seed)
        if not 0 < trainRatio < 1:
            raise ValueError("trainRatio must be in (0, 1)")
        self.trainRatio = trainRatio

    def fit(self, dataset):
        frame = as_frame(dataset)
        train, val = frame.randomSplit(
            [self.trainRatio, 1 - self.trainRatio], seed=self.seed)
        scores = self._fit_score(train, val)
        best = self._best_index(scores)
        best_model = self.estimator.copy(self.estimatorParamMaps[best]).fit(frame)
        return TrainValidationSplitModel(best_model, list(scores))


class TrainValidationSplitModel:
    def __init__(self, bestModel, validationMetrics):
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics

    def transform(self, dataset):
        return self.bestModel.transform(dataset)

    def write(self):
        from tpu_als.api.estimator import MLWriter

        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        _save_tuned(self, path,
                    {"kind": "tvs", "validationMetrics":
                     self.validationMetrics})

    @classmethod
    def load(cls, path):
        best, meta = _load_tuned(path, "tvs")
        return cls(best, meta["validationMetrics"])
