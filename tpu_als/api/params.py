"""The Param/Params system — an exact replica of the reference API's shape.

The reference stack's algorithm configuration layer is ``pyspark.ml.param``
(``Param`` descriptors + a ``Params`` mixin with default/user param maps;
canonical upstream ``python/pyspark/ml/param/__init__.py`` — SURVEY.md
§2.B1/§5.6).  The north-star freezes this surface ("the Pipeline/DataFrame
surface is unchanged"), so names and semantics here mirror it: ``getOrDefault``
precedence (user-set over default), ``copy(extra)``, ``extractParamMap``,
``hasDefault``/``isSet``/``isDefined``, ``explainParams``, and param
objects usable as ``ParamGridBuilder`` keys.
"""

from __future__ import annotations

import copy as _copy


class Param:
    """A named parameter attached to a Params instance."""

    def __init__(self, parent, name, doc, typeConverter=None):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or (lambda x: x)

    def __repr__(self):
        return f"{type(self.parent).__name__}__{self.name}"

    def __hash__(self):
        return hash((type(self.parent), self.name))

    def __eq__(self, other):
        return (
            isinstance(other, Param)
            and type(self.parent) is type(other.parent)
            and self.name == other.name
        )


# -- type converters (subset of pyspark.ml.param.TypeConverters) ----------
class TypeConverters:
    @staticmethod
    def toInt(v):
        if isinstance(v, bool) or int(v) != v:
            raise TypeError(f"could not convert {v!r} to int")
        return int(v)

    @staticmethod
    def toFloat(v):
        return float(v)

    @staticmethod
    def toBoolean(v):
        if not isinstance(v, (bool,)):
            raise TypeError(f"boolean param got {v!r}")
        return bool(v)

    @staticmethod
    def toString(v):
        return str(v)


class Params:
    """Mixin holding a default param map and a user-set param map."""

    def __init__(self):
        self._paramMap = {}
        self._defaultParamMap = {}

    # -- declaration helpers ------------------------------------------
    def _declareParam(self, name, doc, typeConverter=None, default=None):
        p = Param(self, name, doc, typeConverter)
        setattr(self, name, p)
        if default is not None:
            self._defaultParamMap[p] = default
        return p

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            self._defaultParamMap[self.getParam(name)] = value
        return self

    # -- the pyspark.ml surface ---------------------------------------
    @property
    def params(self):
        # Param descriptors are instance attributes (set by _declareParam);
        # scanning dir()/getattr here would re-enter this property forever.
        return sorted(
            (v for v in self.__dict__.values() if isinstance(v, Param)),
            key=lambda p: p.name,
        )

    def getParam(self, name):
        p = getattr(self, name, None)
        if not isinstance(p, Param):
            raise ValueError(f"no param named {name!r}")
        return p

    def hasParam(self, name):
        return isinstance(getattr(self, name, None), Param)

    def isSet(self, param):
        return self._resolve(param) in self._paramMap

    def hasDefault(self, param):
        return self._resolve(param) in self._defaultParamMap

    def isDefined(self, param):
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param):
        p = self._resolve(param)
        if p in self._paramMap:
            return self._paramMap[p]
        if p in self._defaultParamMap:
            return self._defaultParamMap[p]
        raise KeyError(f"param {p.name} is not set and has no default")

    def set(self, param, value):
        p = self._resolve(param)
        self._paramMap[p] = p.typeConverter(value)
        return self

    def _set(self, **kwargs):
        for name, value in kwargs.items():
            if value is not None:
                self.set(self.getParam(name), value)
        return self

    def clear(self, param):
        self._paramMap.pop(self._resolve(param), None)
        return self

    def extractParamMap(self, extra=None):
        m = dict(self._defaultParamMap)
        m.update(self._paramMap)
        if extra:
            m.update({self._resolve(k): v for k, v in extra.items()})
        return m

    def explainParam(self, param):
        p = self._resolve(param)
        parts = [f"default: {self._defaultParamMap.get(p)}"]
        if p in self._paramMap:
            parts.append(f"current: {self._paramMap[p]}")
        return f"{p.name}: {p.doc} ({', '.join(parts)})"

    def explainParams(self):
        return "\n".join(self.explainParam(p) for p in self.params)

    def copy(self, extra=None):
        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        # re-bind Param descriptors to the copy so grids keyed on the
        # original's params still resolve (matching pyspark semantics of
        # resolving by parent type + name)
        if extra:
            for k, v in extra.items():
                that.set(k, v)
        return that

    def _resolve(self, param):
        """Accept this instance's Param, a same-shaped Param from a copy,
        or a param name."""
        if isinstance(param, str):
            return self.getParam(param)
        if isinstance(param, Param):
            own = getattr(self, param.name, None)
            if isinstance(own, Param):
                return own
            raise ValueError(f"{type(self).__name__} has no param {param.name}")
        raise TypeError(f"expected Param or str, got {param!r}")


class Estimator(Params):
    """Shared ``fit``/``fitMultiple`` param-map overloads (reference
    ``python/pyspark/ml/base.py``): subclasses implement ``_fit(dataset)``
    and inherit the whole overload surface, so the TypeError contract and
    the fitMultiple snapshot semantics exist in exactly one place."""

    def fit(self, dataset, params=None):
        if isinstance(params, (list, tuple)):
            models = [None] * len(params)
            for i, m in self.fitMultiple(dataset, params):
                models[i] = m
            return models
        if params is None or isinstance(params, dict):
            est = self.copy(params) if params else self
            return est._fit(dataset)
        raise TypeError(
            "params must be either a param map (dict) or a list/tuple "
            f"of param maps, got {type(params).__name__}")

    def fitMultiple(self, dataset, paramMaps):
        """Thread-safe iterator of ``(index, model)`` — one per param
        map, fit against a SNAPSHOT of this estimator taken now (later
        mutations of ``self`` do not leak into pending fits, per the
        reference contract).  Index allocation is locked; the fits
        themselves run outside the lock so callers may drain the
        iterator from several threads."""
        import threading

        est = self.copy()
        maps = list(paramMaps)
        lock = threading.Lock()
        counter = {"i": 0}

        class _FitIter:
            def __iter__(self):
                return self

            def __next__(self):
                with lock:
                    i = counter["i"]
                    if i >= len(maps):
                        raise StopIteration
                    counter["i"] = i + 1
                return i, est.copy(maps[i])._fit(dataset)

        return _FitIter()
