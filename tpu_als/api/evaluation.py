"""Evaluators: regression metrics + ranking metrics.

Mirrors the reference stack's evaluation layer (SURVEY.md §2.B7):
``pyspark.ml.evaluation.RegressionEvaluator`` (rmse/mse/mae/r2/var),
``pyspark.mllib.evaluation.RankingMetrics`` (precision@k, MAP, NDCG@k,
recall@k) and ``pyspark.ml.evaluation.RankingEvaluator``.  Metric math is
plain numpy on host — these run once per evaluation, not in the hot loop.
"""

from __future__ import annotations

import numpy as np

from tpu_als.api.params import Params, TypeConverters
from tpu_als.utils.frame import as_frame


class RegressionEvaluator(Params):
    """rmse (default) | mse | mae | r2 | var, NaN predictions excluded the
    way the reference evaluator sees them after coldStartStrategy='drop'."""

    def __init__(self, **kwargs):
        super().__init__()
        self._declareParam("predictionCol", "prediction column",
                           TypeConverters.toString, "prediction")
        self._declareParam("labelCol", "label column",
                           TypeConverters.toString, "label")
        self._declareParam("metricName", "rmse|mse|mae|r2|var",
                           TypeConverters.toString, "rmse")
        self._declareParam("throughOrigin", "r2 through origin",
                           TypeConverters.toBoolean, False)
        self._set(**kwargs)

    def setParams(self, **kwargs):
        return self._set(**kwargs)

    def evaluate(self, dataset, params=None):
        if params:
            return self.copy(params).evaluate(dataset)
        frame = as_frame(dataset)
        pred = np.asarray(frame[self.getOrDefault("predictionCol")], np.float64)
        label = np.asarray(frame[self.getOrDefault("labelCol")], np.float64)
        ok = ~(np.isnan(pred) | np.isnan(label))
        pred, label = pred[ok], label[ok]
        if len(pred) == 0:
            return float("nan")
        err = pred - label
        metric = self.getOrDefault("metricName")
        if metric == "rmse":
            return float(np.sqrt(np.mean(err**2)))
        if metric == "mse":
            return float(np.mean(err**2))
        if metric == "mae":
            return float(np.mean(np.abs(err)))
        if metric == "r2":
            if self.getOrDefault("throughOrigin"):
                ss_tot = np.sum(label**2)
            else:
                ss_tot = np.sum((label - label.mean()) ** 2)
            return float(1.0 - np.sum(err**2) / ss_tot)
        if metric == "var":
            return float(np.var(err))
        raise ValueError(f"unknown metricName {metric!r}")

    def isLargerBetter(self):
        return self.getOrDefault("metricName") in ("r2",)


class RegressionMetrics:
    """Legacy ``pyspark.mllib.evaluation.RegressionMetrics`` surface:
    constructed from (prediction, observation) pairs, exposing the five
    metric properties (canonical upstream
    ``mllib/.../evaluation/RegressionMetrics.scala`` — SURVEY.md §2.B7).
    The DataFrame-era equivalent is :class:`RegressionEvaluator`."""

    def __init__(self, pred_and_obs):
        arr = np.asarray([(float(p), float(o)) for p, o in pred_and_obs],
                         dtype=np.float64)
        if arr.size == 0:
            raise ValueError("RegressionMetrics needs at least one "
                             "(prediction, observation) pair")
        self._pred = arr[:, 0]
        self._obs = arr[:, 1]

    @property
    def meanSquaredError(self):
        return float(np.mean((self._pred - self._obs) ** 2))

    @property
    def rootMeanSquaredError(self):
        return float(np.sqrt(self.meanSquaredError))

    @property
    def meanAbsoluteError(self):
        return float(np.mean(np.abs(self._pred - self._obs)))

    @property
    def r2(self):
        ss_res = float(np.sum((self._obs - self._pred) ** 2))
        ss_tot = float(np.sum((self._obs - np.mean(self._obs)) ** 2))
        return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0

    @property
    def explainedVariance(self):
        # reference semantics: SSreg/n = E[(pred - E[obs])^2] (the
        # mllib summarizer's definition — always >= 0), NOT the
        # var(obs) - var(residuals) form, which coincides only for
        # unbiased OLS-style fits
        return float(np.mean((self._pred - np.mean(self._obs)) ** 2))


class RankingMetrics:
    """Ranking quality over (predicted ranking, ground-truth set) pairs.

    ``pred_and_labels``: iterable of (predicted_ids_in_rank_order,
    relevant_ids) — the exact input shape of the reference's
    ``mllib.evaluation.RankingMetrics`` (SURVEY.md §4 'Ranking metrics').
    """

    def __init__(self, pred_and_labels):
        self._pairs = [
            (list(p), set(l)) for p, l in pred_and_labels  # noqa: E741
        ]

    def precisionAt(self, k):
        if k <= 0:
            raise ValueError("k must be > 0")
        vals = []
        for pred, rel in self._pairs:
            if not rel:
                vals.append(0.0)
                continue
            topk = pred[:k]
            vals.append(sum(1 for p in topk if p in rel) / k)
        return float(np.mean(vals)) if vals else 0.0

    def recallAt(self, k):
        if k <= 0:
            raise ValueError("k must be > 0")
        vals = []
        for pred, rel in self._pairs:
            if not rel:
                vals.append(0.0)
                continue
            topk = pred[:k]
            vals.append(sum(1 for p in topk if p in rel) / len(rel))
        return float(np.mean(vals)) if vals else 0.0

    @property
    def meanAveragePrecision(self):
        return self._map(None)

    def meanAveragePrecisionAt(self, k):
        return self._map(k)

    def _map(self, k):
        vals = []
        for pred, rel in self._pairs:
            if not rel:
                vals.append(0.0)
                continue
            cut = pred if k is None else pred[:k]
            hits, s = 0, 0.0
            for rank, p in enumerate(cut, start=1):
                if p in rel:
                    hits += 1
                    s += hits / rank
            denom = len(rel) if k is None else min(len(rel), k)
            vals.append(s / denom)
        return float(np.mean(vals)) if vals else 0.0

    def ndcgAt(self, k):
        if k <= 0:
            raise ValueError("k must be > 0")
        vals = []
        for pred, rel in self._pairs:
            if not rel:
                vals.append(0.0)
                continue
            dcg = sum(
                1.0 / np.log2(rank + 1)
                for rank, p in enumerate(pred[:k], start=1) if p in rel
            )
            ideal = sum(
                1.0 / np.log2(rank + 1)
                for rank in range(1, min(len(rel), k) + 1)
            )
            vals.append(dcg / ideal)
        return float(np.mean(vals)) if vals else 0.0


class RankingEvaluator(Params):
    """DataFrame-style wrapper over RankingMetrics, like
    ``pyspark.ml.evaluation.RankingEvaluator``: expects a prediction column
    of id arrays (rank order) and a label column of relevant-id arrays."""

    def __init__(self, **kwargs):
        super().__init__()
        self._declareParam("predictionCol", "ranked prediction id arrays",
                           TypeConverters.toString, "prediction")
        self._declareParam("labelCol", "relevant id arrays",
                           TypeConverters.toString, "label")
        self._declareParam(
            "metricName",
            "meanAveragePrecision|meanAveragePrecisionAtK|precisionAtK|"
            "ndcgAtK|recallAtK", TypeConverters.toString,
            "meanAveragePrecision")
        self._declareParam("k", "cutoff for @K metrics",
                           TypeConverters.toInt, 10)
        self._set(**kwargs)

    def evaluate(self, dataset, params=None):
        if params:
            return self.copy(params).evaluate(dataset)
        frame = as_frame(dataset)
        pairs = list(zip(frame[self.getOrDefault("predictionCol")],
                         frame[self.getOrDefault("labelCol")]))
        m = RankingMetrics(pairs)
        k = self.getOrDefault("k")
        name = self.getOrDefault("metricName")
        if name == "meanAveragePrecision":
            return m.meanAveragePrecision
        if name == "meanAveragePrecisionAtK":
            return m.meanAveragePrecisionAt(k)
        if name == "precisionAtK":
            return m.precisionAt(k)
        if name == "ndcgAtK":
            return m.ndcgAt(k)
        if name == "recallAtK":
            return m.recallAt(k)
        raise ValueError(f"unknown metricName {name!r}")

    def isLargerBetter(self):
        return True
