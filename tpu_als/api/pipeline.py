"""Pipeline composition + string-id feature stages.

The reference workflow (SURVEY.md §1 L2, §2.A) is rarely a bare ALS call:
the canonical `pyspark.ml` recommender chains ``StringIndexer`` stages (raw
string/arbitrary ids → dense integer ids) into a ``Pipeline`` with the ALS
estimator, cross-validates the whole pipeline, and maps predictions back
with ``IndexToString``.  Canonical upstream surfaces replicated here:

- ``pyspark.ml.Pipeline`` / ``PipelineModel``
  (``python/pyspark/ml/pipeline.py``): ordered stages, fit = fold over
  stages (transformers apply, estimators fit then their model applies),
  transform = apply every stage model in order, MLWritable persistence.
- ``pyspark.ml.feature.StringIndexer`` / ``StringIndexerModel`` /
  ``IndexToString`` (``python/pyspark/ml/feature.py``): frequency- or
  alphabet-ordered label vocabulary, ``handleInvalid`` in
  ``{'error','skip','keep'}`` (keep maps unseen values to index
  ``len(labels)``), and the inverse mapping transformer.

Deviations (documented, TPU-first): the indexer emits **int64** indices
(not pyspark's DoubleType) because every downstream consumer here — the
ALS estimator's id columns, CSR blocking, device gathers — is integer-
indexed; emitting doubles to then re-cast on device would be pure waste.
Values are indexed by their string form, matching pyspark's cast-to-string
behavior on non-string columns.

Stages duck-type: anything with ``fit`` is an estimator, anything with
``transform`` is a transformer (the reference distinguishes by abstract
base class; the call contract is identical).
"""

from __future__ import annotations

import json
import os

import numpy as np

from tpu_als.api.estimator import MLWriter, recover_interrupted_overwrite
from tpu_als.api.params import Estimator, Params, TypeConverters
from tpu_als.utils.frame import as_frame

_ORDER_TYPES = ("frequencyDesc", "frequencyAsc", "alphabetDesc",
                "alphabetAsc")
_INVALID_POLICIES = ("error", "skip", "keep")


class StringIndexer(Estimator):
    """Estimator mapping a column of arbitrary values to dense int64
    indices ordered by ``stringOrderType`` (reference default
    ``frequencyDesc``: most frequent value gets index 0; ties break
    alphabetically ascending so the fit is deterministic)."""

    def __init__(self, *, inputCol=None, outputCol=None,
                 handleInvalid="error", stringOrderType="frequencyDesc"):
        super().__init__()
        self._declareParam("inputCol", "input column name",
                           TypeConverters.toString)
        self._declareParam("outputCol", "output column name",
                           TypeConverters.toString)
        self._declareParam("handleInvalid",
                           "how to handle unseen labels at transform time: "
                           "'error', 'skip' (drop rows) or 'keep' (map to "
                           "index len(labels))",
                           TypeConverters.toString, default="error")
        self._declareParam("stringOrderType",
                           "label ordering: frequencyDesc | frequencyAsc | "
                           "alphabetDesc | alphabetAsc",
                           TypeConverters.toString, default="frequencyDesc")
        self.setParams(inputCol=inputCol, outputCol=outputCol,
                       handleInvalid=handleInvalid,
                       stringOrderType=stringOrderType)

    def setParams(self, **kwargs):
        self._set(**kwargs)
        for name in ("handleInvalid", "stringOrderType"):
            allowed = (_INVALID_POLICIES if name == "handleInvalid"
                       else _ORDER_TYPES)
            if self.isDefined(self.getParam(name)) and \
                    self.getOrDefault(self.getParam(name)) not in allowed:
                raise ValueError(
                    f"{name} must be one of {allowed}, got "
                    f"{self.getOrDefault(self.getParam(name))!r}")
        return self

    def _fit(self, dataset):
        df = as_frame(dataset)
        col = self.getOrDefault(self.getParam("inputCol"))
        if col not in df:
            raise ValueError(f"inputCol {col!r} not in {df.columns}")
        values = np.asarray(df[col]).astype(str)
        uniq, counts = np.unique(values, return_counts=True)
        order = self.getOrDefault(self.getParam("stringOrderType"))
        if order == "frequencyDesc":
            # np.unique returns uniq ascending; stable sort on -counts
            # keeps the alphabetical tiebreak
            idx = np.argsort(-counts, kind="stable")
        elif order == "frequencyAsc":
            idx = np.argsort(counts, kind="stable")
        elif order == "alphabetAsc":
            idx = np.arange(len(uniq))
        else:  # alphabetDesc
            idx = np.arange(len(uniq))[::-1]
        model = StringIndexerModel(labels=[str(v) for v in uniq[idx]])
        model._copy_config_from(self)
        return model

    # -- estimator persistence (DefaultParamsWritable parity) -----------
    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        os.makedirs(path, exist_ok=True)
        payload = {
            "class": "tpu_als.api.pipeline.StringIndexer",
            "paramMap": {p.name: v for p, v in self._paramMap.items()},
        }
        tmp = os.path.join(path, "indexer.json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, "indexer.json"))

    @classmethod
    def load(cls, path):
        recover_interrupted_overwrite(path)
        with open(os.path.join(path, "indexer.json")) as f:
            meta = json.load(f)
        if meta.get("class") != "tpu_als.api.pipeline.StringIndexer":
            raise ValueError(f"{path} holds {meta.get('class')!r}, not a "
                             "StringIndexer")
        est = cls()
        est._set(**meta.get("paramMap", {}))
        return est


class StringIndexerModel(Params):
    """Fitted vocabulary: ``labels[i]`` is the value mapped to index i."""

    def __init__(self, *, labels=None, inputCol=None, outputCol=None,
                 handleInvalid="error"):
        super().__init__()
        self._declareParam("inputCol", "input column name",
                           TypeConverters.toString)
        self._declareParam("outputCol", "output column name",
                           TypeConverters.toString)
        self._declareParam("handleInvalid",
                           "'error' | 'skip' | 'keep'",
                           TypeConverters.toString, default="error")
        if handleInvalid not in _INVALID_POLICIES:
            raise ValueError(f"handleInvalid must be one of "
                             f"{_INVALID_POLICIES}, got {handleInvalid!r}")
        self.labels = list(labels or [])
        self._set(inputCol=inputCol, outputCol=outputCol,
                  handleInvalid=handleInvalid)

    @classmethod
    def from_labels(cls, labels, inputCol=None, outputCol=None,
                    handleInvalid="error"):
        """Reference's ``StringIndexerModel.from_labels``."""
        return cls(labels=labels, inputCol=inputCol, outputCol=outputCol,
                   handleInvalid=handleInvalid)

    def _copy_config_from(self, est):
        self._set(inputCol=est.getOrDefault(est.getParam("inputCol")),
                  outputCol=est.getOrDefault(est.getParam("outputCol")),
                  handleInvalid=est.getOrDefault(
                      est.getParam("handleInvalid")))

    def setHandleInvalid(self, value):
        if value not in _INVALID_POLICIES:
            raise ValueError(f"handleInvalid must be one of "
                             f"{_INVALID_POLICIES}, got {value!r}")
        return self._set(handleInvalid=value)

    def transform(self, dataset):
        df = as_frame(dataset)
        in_col = self.getOrDefault(self.getParam("inputCol"))
        out_col = self.getOrDefault(self.getParam("outputCol"))
        if in_col not in df:
            raise ValueError(f"inputCol {in_col!r} not in {df.columns}")
        values = np.asarray(df[in_col]).astype(str)
        lut = {v: i for i, v in enumerate(self.labels)}
        idx = np.fromiter((lut.get(v, -1) for v in values),
                          dtype=np.int64, count=len(values))
        unseen = idx < 0
        if unseen.any():
            policy = self.getOrDefault(self.getParam("handleInvalid"))
            if policy == "error":
                examples = sorted(set(values[unseen]))[:5]
                raise ValueError(
                    f"StringIndexerModel({out_col}): unseen labels "
                    f"{examples} (and possibly more); set "
                    "handleInvalid='skip' or 'keep' to accept them")
            if policy == "skip":
                df = df.filter(~unseen)
                idx = idx[~unseen]
            else:  # keep — the reference maps all unseen to one bucket
                idx = np.where(unseen, len(self.labels), idx)
        return df.withColumn(out_col, idx)

    # -- persistence ----------------------------------------------------
    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        os.makedirs(path, exist_ok=True)
        payload = {
            "class": "tpu_als.api.pipeline.StringIndexerModel",
            "labels": self.labels,
            "paramMap": {p.name: v for p, v in self._paramMap.items()},
        }
        tmp = os.path.join(path, "indexer.json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, "indexer.json"))

    @classmethod
    def load(cls, path):
        recover_interrupted_overwrite(path)
        with open(os.path.join(path, "indexer.json")) as f:
            meta = json.load(f)
        if meta.get("class") != "tpu_als.api.pipeline.StringIndexerModel":
            raise ValueError(f"{path} holds {meta.get('class')!r}, not a "
                             "StringIndexerModel")
        m = cls(labels=meta["labels"])
        m._set(**meta.get("paramMap", {}))
        return m


class IndexToString(Params):
    """Inverse of ``StringIndexerModel``: int indices → original labels
    (reference ``pyspark.ml.feature.IndexToString``)."""

    def __init__(self, *, inputCol=None, outputCol=None, labels=None):
        super().__init__()
        self._declareParam("inputCol", "input column name",
                           TypeConverters.toString)
        self._declareParam("outputCol", "output column name",
                           TypeConverters.toString)
        self.labels = list(labels or [])
        self._set(inputCol=inputCol, outputCol=outputCol)

    # -- persistence (a pipeline ending in IndexToString must save) -----
    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        os.makedirs(path, exist_ok=True)
        payload = {
            "class": "tpu_als.api.pipeline.IndexToString",
            "labels": self.labels,
            "paramMap": {p.name: v for p, v in self._paramMap.items()},
        }
        tmp = os.path.join(path, "index_to_string.json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, "index_to_string.json"))

    @classmethod
    def load(cls, path):
        recover_interrupted_overwrite(path)
        with open(os.path.join(path, "index_to_string.json")) as f:
            meta = json.load(f)
        if meta.get("class") != "tpu_als.api.pipeline.IndexToString":
            raise ValueError(f"{path} holds {meta.get('class')!r}, not an "
                             "IndexToString")
        t = cls(labels=meta["labels"])
        t._set(**meta.get("paramMap", {}))
        return t

    def transform(self, dataset):
        df = as_frame(dataset)
        in_col = self.getOrDefault(self.getParam("inputCol"))
        out_col = self.getOrDefault(self.getParam("outputCol"))
        if not self.labels:
            raise ValueError("IndexToString needs labels (pass labels= or "
                             "use StringIndexerModel.labels)")
        idx = np.asarray(df[in_col])
        if not np.issubdtype(idx.dtype, np.integer):
            if np.issubdtype(idx.dtype, np.floating) and \
                    np.all(np.isfinite(idx)) and np.all(idx == idx.astype(np.int64)):
                idx = idx.astype(np.int64)
            else:
                raise ValueError(
                    f"IndexToString inputCol {in_col!r} must hold integer "
                    f"indices, got dtype {idx.dtype}")
        if idx.size and (idx.min() < 0 or idx.max() >= len(self.labels)):
            raise ValueError(
                f"index out of range for {len(self.labels)} labels: "
                f"[{idx.min()}, {idx.max()}]")
        arr = np.asarray(self.labels, dtype=object)
        return df.withColumn(out_col, arr[idx])


class Pipeline(Estimator):
    """Ordered composition of transformers and estimators (reference
    ``pyspark.ml.Pipeline``).  ``fit`` folds the dataset through the
    stages: a transformer stage applies; an estimator stage fits on the
    current dataset and its model applies; the result is a
    ``PipelineModel`` of the materialized transformer chain."""

    def __init__(self, *, stages=None):
        super().__init__()
        self._declareParam("stages", "pipeline stages")
        if stages is not None:
            self.setStages(stages)

    def setStages(self, stages):
        stages = list(stages)
        for s in stages:
            if not (hasattr(s, "fit") or hasattr(s, "transform")):
                raise TypeError(
                    f"pipeline stage {s!r} is neither an estimator "
                    "(has .fit) nor a transformer (has .transform)")
        self._paramMap[self.getParam("stages")] = stages
        return self

    def getStages(self):
        return list(self.getOrDefault(self.getParam("stages")))

    def _fit(self, dataset):
        df = as_frame(dataset)
        stages = self.getStages()
        last_est = max((i for i, s in enumerate(stages)
                        if hasattr(s, "fit")), default=-1)
        fitted = []
        for i, stage in enumerate(stages):
            model = stage.fit(df) if hasattr(stage, "fit") else stage
            fitted.append(model)
            # nothing after the last estimator consumes the dataset
            # during fit — in particular the fitted model must not score
            # the whole training set just to feed discarded output
            if i < last_est:
                df = model.transform(df)
        return PipelineModel(fitted)

    def copy(self, extra=None):
        """Stage-aware copy: grid params (``extra`` keyed by Param) are
        routed to the stage that declares them — this is what lets a
        ``CrossValidator`` grid over ALS params drive a whole Pipeline
        (``estimator.copy(paramMap).fit`` in tuning.py).

        Routing prefers *instance* identity (``param.parent is stage`` —
        the reference's uid semantics): a grid built from ``als.rank``
        drives exactly the ``als`` stage even when a sibling stage has
        the same class.  Class+name routing is the fallback (grids built
        against a detached instance), but it REFUSES to fan one param
        out to multiple same-class stages — silently configuring both
        ``StringIndexer``s with one ``inputCol`` would corrupt the fit.
        """
        extra = extra or {}
        stages = self.getStages()
        per_stage = [dict() for _ in stages]
        for k, v in extra.items():
            if not hasattr(k, "name"):
                raise TypeError(f"expected Param keys in extra, got {k!r}")
            owner = [i for i, s in enumerate(stages)
                     if getattr(k, "parent", None) is s]
            if not owner:
                owner = [i for i, s in enumerate(stages)
                         if s.hasParam(k.name)
                         and type(k.parent) is type(s)]
            if not owner:
                raise ValueError(
                    f"grid param {k.name!r} (declared by "
                    f"{type(k.parent).__name__}) matches no pipeline "
                    "stage (params resolve by declaring instance, then "
                    "class + name)")
            if len(owner) > 1:
                raise ValueError(
                    f"grid param {k.name!r} matches "
                    f"{len(owner)} {type(k.parent).__name__} stages — "
                    "ambiguous; key the grid with the stage instance's "
                    "own Param (e.g. pipeline.getStages()[i].paramName)")
            per_stage[owner[0]][k] = v
        # copy EVERY copyable stage, not only param-receiving ones: the
        # Estimator.fitMultiple snapshot contract relies on copy()
        # isolating later mutations of the original stages (advisor r4);
        # duck-typed transformer stages without copy() pass through
        return Pipeline(stages=[
            stage.copy(own or None) if hasattr(stage, "copy") else stage
            for stage, own in zip(stages, per_stage)])

    # -- persistence ----------------------------------------------------
    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        _save_stages(path, "tpu_als.api.pipeline.Pipeline",
                     self.getStages())

    @classmethod
    def load(cls, path):
        return cls(stages=_load_stages(
            path, "tpu_als.api.pipeline.Pipeline"))


class PipelineModel:
    """Fitted pipeline: every stage is now a transformer; ``transform``
    applies them in order.  ``stages[i]`` exposes the fitted stage models
    (e.g. the ``ALSModel`` for ``recommendForAllUsers``)."""

    def __init__(self, stages):
        self.stages = list(stages)

    def transform(self, dataset):
        df = as_frame(dataset)
        for stage in self.stages:
            df = stage.transform(df)
        return df

    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)

    def _save_to(self, path):
        _save_stages(path, "tpu_als.api.pipeline.PipelineModel",
                     self.stages)

    @classmethod
    def load(cls, path):
        return cls(stages=_load_stages(
            path, "tpu_als.api.pipeline.PipelineModel"))


# -- shared stage persistence ---------------------------------------------

def _stage_class_path(stage):
    cls = type(stage)
    return f"{cls.__module__}.{cls.__qualname__}"


def _import_stage_class(path):
    if not path.startswith("tpu_als."):
        raise ValueError(
            f"refusing to load stage class {path!r}: only tpu_als.* "
            "stages are loadable (same rule as tuning._load_tuned)")
    mod_name, _, cls_name = path.rpartition(".")
    import importlib

    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name)


def _save_stages(path, class_path, stages):
    for s in stages:
        if not hasattr(s, "_save_to"):
            raise ValueError(
                f"pipeline stage {type(s).__name__} is not persistable "
                "(no _save_to); fit the pipeline or drop the stage "
                "before saving")
        if not _stage_class_path(s).startswith("tpu_als."):
            # the load side only imports tpu_als.* classes — refusing
            # here turns a save that could never be read back into an
            # immediate error instead of a latent one
            raise ValueError(
                f"pipeline stage class {_stage_class_path(s)!r} is "
                "outside tpu_als.*; it would be unloadable "
                "(_import_stage_class refuses non-tpu_als stages)")
    os.makedirs(path, exist_ok=True)
    meta = {"class": class_path,
            "stages": [_stage_class_path(s) for s in stages]}
    for i, s in enumerate(stages):
        s._save_to(os.path.join(path, f"stage_{i:02d}"))
    tmp = os.path.join(path, "pipeline.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(path, "pipeline.json"))


def _load_stages(path, expect_class):
    recover_interrupted_overwrite(path)
    with open(os.path.join(path, "pipeline.json")) as f:
        meta = json.load(f)
    if meta.get("class") != expect_class:
        raise ValueError(f"{path} holds {meta.get('class')!r}, not "
                         f"{expect_class}")
    stages = []
    for i, cls_path in enumerate(meta["stages"]):
        cls = _import_stage_class(cls_path)
        stages.append(cls.load(os.path.join(path, f"stage_{i:02d}")))
    return stages
