"""The ALS Estimator / ALSModel — the frozen API surface of the reference.

Mirrors ``pyspark.ml.recommendation.{ALS, ALSModel}`` (canonical upstream
``python/pyspark/ml/recommendation.py`` — SURVEY.md §2.B1/§2.D): same param
names, defaults, and method surface (``fit``, ``transform``,
``recommendForAllUsers/Items``, ``recommendForUserSubset/ItemSubset``,
``save/load``), plus the north-star's ``solver`` param (``'jax_tpu'``,
BASELINE.json).  Instead of delegating over Py4J to a JVM, ``fit`` drives the
TPU-native core: remap ids → bucketed CSR shards → jitted batched-Cholesky
half-steps (single device or a mesh).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from tpu_als.api.params import Estimator, Params, TypeConverters
from tpu_als.core.als import AlsConfig, predict as _predict_kernel, train as _train
from tpu_als.core.ratings import IdMap, build_csr_buckets, remap_ids
from tpu_als.io.checkpoint import load_factors, save_factors
from tpu_als.ops.topk import topk_scores
from tpu_als.utils.frame import ColumnarFrame, as_frame

_STORAGE_LEVELS = {
    "NONE", "DISK_ONLY", "MEMORY_ONLY", "MEMORY_AND_DISK",
    "MEMORY_ONLY_SER", "MEMORY_AND_DISK_SER", "OFF_HEAP",
}

# (name, doc, converter, default) — names/defaults per SURVEY.md §2.D
_ALS_PARAMS = [
    ("rank", "rank of the factorization", TypeConverters.toInt, 10),
    ("maxIter", "max number of iterations (>= 0)", TypeConverters.toInt, 10),
    ("regParam", "regularization parameter (>= 0)", TypeConverters.toFloat, 0.1),
    ("numUserBlocks", "number of user blocks", TypeConverters.toInt, 10),
    ("numItemBlocks", "number of item blocks", TypeConverters.toInt, 10),
    ("implicitPrefs", "whether to use implicit preference",
     TypeConverters.toBoolean, False),
    ("alpha", "alpha for implicit preference", TypeConverters.toFloat, 1.0),
    ("userCol", "column name for user ids", TypeConverters.toString, "user"),
    ("itemCol", "column name for item ids", TypeConverters.toString, "item"),
    ("ratingCol", "column name for ratings", TypeConverters.toString, "rating"),
    ("predictionCol", "prediction column name", TypeConverters.toString,
     "prediction"),
    ("nonnegative", "whether to use nonnegative constraint for least squares",
     TypeConverters.toBoolean, False),
    ("checkpointInterval", "checkpoint interval (>= 1), -1 disables",
     TypeConverters.toInt, 10),
    ("intermediateStorageLevel",
     "storage level for intermediate datasets (accepted for API parity; "
     "factors live in device HBM here)", TypeConverters.toString,
     "MEMORY_AND_DISK"),
    ("finalStorageLevel", "storage level for final factors (API parity)",
     TypeConverters.toString, "MEMORY_AND_DISK"),
    ("coldStartStrategy",
     "strategy for unknown/unfitted ids at predict time: 'nan' or 'drop'",
     TypeConverters.toString, "nan"),
    ("seed", "random seed", TypeConverters.toInt, 0),
    ("blockSize", "block size for blocked top-k scoring", TypeConverters.toInt,
     4096),
    ("solver", "'jax_tpu' (batched-Cholesky TPU core, the only backend here)",
     TypeConverters.toString, "jax_tpu"),
]


class _ALSParams(Params):
    def __init__(self):
        super().__init__()
        for name, doc, conv, default in _ALS_PARAMS:
            self._declareParam(name, doc, conv, default)

    def _validate(self):
        m = self.extractParamMap()
        get = lambda n: m[self.getParam(n)]  # noqa: E731
        if get("rank") <= 0:
            raise ValueError("rank must be > 0")
        if get("maxIter") < 0:
            raise ValueError("maxIter must be >= 0")
        if get("regParam") < 0:
            raise ValueError("regParam must be >= 0")
        if get("coldStartStrategy") not in ("nan", "drop"):
            raise ValueError("coldStartStrategy must be 'nan' or 'drop'")
        if get("solver") not in ("jax_tpu", "als"):
            raise ValueError("solver must be 'jax_tpu' or 'als'")
        for lvl in ("intermediateStorageLevel", "finalStorageLevel"):
            if get(lvl) not in _STORAGE_LEVELS:
                raise ValueError(f"{lvl}: unknown storage level {get(lvl)!r}")
        if get("checkpointInterval") == 0 or get("checkpointInterval") < -1:
            raise ValueError("checkpointInterval must be >= 1 or -1")
        if get("alpha") < 0:
            raise ValueError("alpha must be >= 0")
        if get("blockSize") < 1:
            raise ValueError("blockSize must be >= 1")


def recover_interrupted_overwrite(path):
    """If a previous ``.write().overwrite().save(path)`` crashed between
    its two renames, ``path`` is missing but the old save sits complete at
    ``path + '.overwritten.tmp'`` — move it back.  Called by both the
    writer and the load entry points so an intact copy on disk is never
    unreachable (code-review r2)."""
    import os

    aside = path.rstrip("/\\") + ".overwritten.tmp"
    if not os.path.exists(path) and os.path.exists(aside):
        os.rename(aside, path)


class MLWriter:
    """Writer handle giving the reference call shape
    ``instance.write().overwrite().save(path)`` (pyspark ``ml.util.MLWriter``
    — SURVEY.md §2.B11).  Without ``overwrite()``, saving onto an existing
    path raises, matching the reference semantics."""

    def __init__(self, instance):
        self._instance = instance
        self._shouldOverwrite = False

    def overwrite(self):
        self._shouldOverwrite = True
        return self

    def save(self, path):
        import os
        import shutil

        recover_interrupted_overwrite(path)
        if os.path.exists(path):
            if not self._shouldOverwrite:
                raise IOError(
                    f"path {path} already exists; use "
                    ".write().overwrite().save(path) to replace it")
            # write the new save to a sibling temp dir FIRST, then swap:
            # a _save_to failure (ENOSPC, bug) leaves the old save at
            # ``path`` completely untouched, and the only crash window is
            # between the two renames — where both copies still exist on
            # disk (same discipline as io.checkpoint's atomic swap).
            # (Writing into the old directory in place would leave stale
            # files when the save *kinds* differ — e.g. an estimator.json
            # landing next to an old model manifest.)
            base = path.rstrip("/\\")
            fresh = base + ".new.tmp"
            aside = base + ".overwritten.tmp"
            for tmp in (fresh, aside):
                if os.path.exists(tmp):
                    shutil.rmtree(tmp, ignore_errors=True)
            try:
                self._instance._save_to(fresh)
            except BaseException:
                shutil.rmtree(fresh, ignore_errors=True)
                raise
            os.rename(path, aside)
            os.rename(fresh, path)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            self._instance._save_to(path)


def _attach_accessors(cls, names):
    for name in names:
        cap = name[0].upper() + name[1:]

        def getter(self, _n=name):
            return self.getOrDefault(self.getParam(_n))

        def setter(self, value, _n=name):
            return self._set(**{_n: value})

        setattr(cls, f"get{cap}", getter)
        setattr(cls, f"set{cap}", setter)


class ALS(_ALSParams, Estimator):
    """ALS matrix-factorization Estimator (explicit + implicit feedback).

    Runtime-only (non-Param) knobs: ``mesh`` — a ``jax.sharding.Mesh`` to
    train sharded over devices (None = single device; ``numUserBlocks`` /
    ``numItemBlocks`` are then API-parity hints only); ``gatherStrategy`` —
    how sharded half-steps move the opposite factors: any row of
    ``tpu_als.parallel.trainer.GATHER_STRATEGIES`` (the one
    authoritative strategy table — this docstring deliberately does not
    restate it); default ``'all_gather'``; ``checkpointDir`` —
    where ``checkpointInterval`` writes resumable factor snapshots;
    ``resumeFrom`` — a checkpoint directory to warm-start from: ``fit``
    loads its factors + iteration counter and runs only the remaining
    iterations (failure recovery, SURVEY.md §5.3);
    ``fitCallback(iteration, U, V)`` — per-iteration observer (e.g.
    tpu_als.utils.observe.IterationLogger); in a multi-process fit the
    entity-space factors are gathered collectively every
    ``fitCallbackInterval`` iterations and the callback runs on process 0
    only (the gather is the cost — raise the interval to amortize it);
    ``dataMode`` — ``'replicated'`` (default: every process passes the
    SAME dataset to ``fit``) or ``'per_host'`` (every process passes its
    OWN disjoint split — e.g. one input file per pod host; the entity
    space is agreed via ``multihost.global_id_union`` and the triples are
    redistributed inside ``train_multihost``);
    ``cgIters`` — > 0 replaces the exact per-row solve with that many
    warm-started conjugate-gradient steps (inexact ALS): the r³
    factorization becomes a few batched MXU matvecs; 0 (default) keeps
    the exact batched Cholesky;
    ``cgMode`` — ``'matfree'`` (default: ``ops.solve.solve_cg_matfree``
    applies the normal equations through the gathered factor rows, never
    materializing the [n, r, r] tensor) or ``'dense'``
    (``ops.solve.solve_cg`` on the einsum-built tensor); the ring
    strategy always solves dense (its A accumulates across streamed
    shards);
    ``checkpointSharded`` — multi-process fits only: each process writes
    its own factor shards (``multihost.save_checkpoint_sharded``) instead
    of gathering full factors to process 0 per checkpoint — the O(N·r)
    cross-host gather disappears from the checkpoint path; resume reads
    the sharded directory transparently.  Single-process fits ignore the
    knob (they hold entity-space factors already);
    ``guardrails`` — numerical-health guardrails mode for this fit
    (``'off'``/``'warn'``/``'recover'``; ``None``, the default, inherits
    ``TPU_ALS_GUARDRAILS``): armed fits quarantine non-finite /
    out-of-range ratings instead of aborting, and ``'recover'`` adds the
    sentinel / adaptive-solve / rollback ladder — docs/resilience.md;
    ``elastic`` — single-process mesh fits: device loss becomes a
    rescheduling event instead of a crash.  A failed step is
    health-probed (``resilience.elastic``) into transient-retry vs
    ``DeviceLost``; on loss the mesh re-forms on the surviving devices
    and training resumes from the last atomic checkpoint (or from the
    seed-deterministic init when no ``checkpointDir`` is set).  Off by
    default — the detector adds nothing to the traced step either way
    (the ``elastic_disarmed`` contract) — docs/resilience.md.
    """

    def __init__(self, *, mesh=None, gatherStrategy="all_gather",
                 checkpointDir=None, resumeFrom=None,
                 fitCallback=None, fitCallbackInterval=1,
                 dataMode="replicated", cgIters=0, cgMode="matfree",
                 checkpointSharded=False, guardrails=None,
                 elastic=False, **kwargs):
        super().__init__()
        self.mesh = mesh
        self.elastic = bool(elastic)
        if guardrails is not None and guardrails not in ("off", "warn",
                                                         "recover"):
            raise ValueError(f"unknown guardrails mode {guardrails!r} "
                             "(expected 'off', 'warn' or 'recover')")
        # None = inherit TPU_ALS_GUARDRAILS / programmatic set_mode;
        # an explicit mode is scoped around this estimator's fit only
        self.guardrails = guardrails
        if int(cgIters) < 0:
            raise ValueError("cgIters must be >= 0 (0 = exact solve)")
        if cgMode not in ("matfree", "dense"):
            raise ValueError(f"unknown cgMode {cgMode!r} (expected "
                             "'matfree' or 'dense')")
        self.cgIters = int(cgIters)
        self.cgMode = cgMode
        # validate against THE strategy table (parallel.trainer owns it)
        from tpu_als.parallel.trainer import GATHER_STRATEGIES, strategy_help

        if gatherStrategy not in GATHER_STRATEGIES:
            raise ValueError(
                f"unknown gatherStrategy {gatherStrategy!r} (expected "
                f"one of {tuple(GATHER_STRATEGIES)}; {strategy_help()})")
        if dataMode not in ("replicated", "per_host"):
            raise ValueError(f"unknown dataMode {dataMode!r} (expected "
                             "'replicated' or 'per_host')")
        if int(fitCallbackInterval) < 1:
            raise ValueError("fitCallbackInterval must be >= 1")
        self.gatherStrategy = gatherStrategy
        self.checkpointDir = checkpointDir
        self.resumeFrom = resumeFrom
        self.fitCallback = fitCallback
        self.fitCallbackInterval = int(fitCallbackInterval)
        self.dataMode = dataMode
        self.checkpointSharded = bool(checkpointSharded)
        self.setParams(**kwargs)

    def setParams(self, **kwargs):
        unknown = [k for k in kwargs if not self.hasParam(k)]
        if unknown:
            raise TypeError(f"unknown param(s): {unknown}")
        return self._set(**kwargs)

    def _config(self):
        m = self.extractParamMap()
        get = lambda n: m[self.getParam(n)]  # noqa: E731
        return AlsConfig(
            rank=get("rank"),
            max_iter=get("maxIter"),
            reg_param=get("regParam"),
            implicit_prefs=get("implicitPrefs"),
            alpha=get("alpha"),
            nonnegative=get("nonnegative"),
            seed=get("seed") or 0,
            cg_iters=self.cgIters,
            cg_mode=self.cgMode,
        )

    def _extract_columns(self, frame):
        """(u_raw, i_raw, r, nonfinite_count) with the reference schema
        checks: integer ids, ratingCol='' meaning unit ratings.  The
        nan/inf count is RETURNED, not raised on: in a multi-process fit
        a data-dependent one-host abort before the first collective
        would strand the peers inside it, so fit raises single-process
        and defers to the collective check otherwise."""
        userCol, itemCol = self.getUserCol(), self.getItemCol()
        ratingCol = self.getRatingCol()
        for c in (userCol, itemCol):
            if c not in frame:
                raise ValueError(f"column {c!r} not in dataset "
                                 f"(columns: {frame.columns})")
            if not np.issubdtype(frame[c].dtype, np.integer):
                raise ValueError(
                    f"ALS only supports integer ids; column {c!r} has dtype "
                    f"{frame[c].dtype} (the reference API has the same "
                    "integer-range restriction). For raw string ids, index "
                    "them first — Pipeline(stages=[StringIndexer(inputCol="
                    f"{c!r}, outputCol='{c}_idx', handleInvalid='skip'), "
                    "ALS(...)]) mirrors the reference workflow "
                    "(docs/migration.md)")
        if ratingCol == "":
            # reference semantic: empty ratingCol means unit ratings
            r = np.ones(len(frame), dtype=np.float32)
        elif ratingCol in frame:
            r = np.asarray(frame[ratingCol], dtype=np.float32)
        else:
            raise ValueError(f"column {ratingCol!r} not in dataset "
                             f"(columns: {frame.columns}); set ratingCol='' "
                             "for unit ratings")
        # one nan/inf rating poisons the whole factorization through the
        # normal-equation sums (the strict CSV parser blocks this at
        # ingest; this guards direct API callers)
        return frame[userCol], frame[itemCol], r, int((~np.isfinite(r)).sum())

    def _fit(self, dataset):
        # fit()/fitMultiple() param-map overloads come from the shared
        # api.params.Estimator base (reference python/pyspark/ml/base.py)
        self._validate()
        _g = lambda n: self.getOrDefault(self.getParam(n))  # noqa: E731
        if _g("rank") >= 256 and _g("regParam") < 1e-4:
            # the round-5 conditioning study's measured boundary
            # (docs/conditioning_rank256.md): below reg 1e-4 the f32
            # normal equations lose their 3-significant-digit guarantee
            # under adversarially collinear gathers — and at reg=0 they
            # are outright singular for entities with degree < rank
            import warnings

            warnings.warn(
                f"regParam={_g('regParam')} at rank {_g('rank')} is "
                "below the measured float32 conditioning floor (1e-4) "
                "— see docs/conditioning_rank256.md", stacklevel=3)
        frame = as_frame(dataset)
        ratingCol = self.getRatingCol()
        u_raw, i_raw, r, nonfinite = self._extract_columns(frame)
        multiproc = False
        if self.mesh is not None:
            import jax

            multiproc = jax.process_count() > 1
        from tpu_als.resilience import guardrails as _guardrails

        gmode = (self.guardrails if self.guardrails is not None
                 else _guardrails.guardrails_mode())
        if not multiproc and gmode != "off":
            # guardrails armed: quarantine poisoned ratings instead of
            # aborting — the API-path mirror of stream_ingest's
            # poisoned-record sink (same invalid_rating_mask contract,
            # core.ratings; also catches huge-magnitude finite values)
            from tpu_als import obs
            from tpu_als.core.ratings import invalid_rating_mask

            bad = invalid_rating_mask(r)
            nbad = int(bad.sum())
            if nbad:
                keep = ~bad
                u_raw = np.asarray(u_raw)[keep]
                i_raw = np.asarray(i_raw)[keep]
                r = r[keep]
                obs.counter("ingest.quarantined_rows", nbad)
                obs.emit("ingest_quarantined", path="<api>", rows=nbad,
                         reasons={"malformed": 0, "nonfinite": nonfinite,
                                  "out_of_range": nbad - nonfinite},
                         sink=None)
        elif nonfinite and not multiproc:
            raise ValueError(
                f"ratingCol {ratingCol!r} contains {nonfinite} "
                "non-finite value(s) (nan/inf); clean the input "
                "before fit")

        if multiproc:
            # the FIRST collective of every multi-process fit, on every
            # configuration: a knob divergence must raise here instead
            # of pairing MISMATCHED collectives later (a distributed
            # hang or a cryptic gloo shape error)
            from tpu_als.api.fitting import (
                check_finite_ratings_collective,
                check_multiprocess_gate,
            )

            check_multiprocess_gate(self)
            # bad data on ANY host must raise on EVERY host (a
            # one-sided abort would strand the peers in the next
            # collective) — runs right after the gate, before any
            # data-derived collective
            check_finite_ratings_collective(nonfinite, ratingCol)
        if self.dataMode == "per_host":
            # every process holds a DIFFERENT split, so the entity space
            # must be agreed before anything derives from it (id maps →
            # partitions → layouts → init); union of per-host unique ids,
            # identical on every process.  Single-process this degenerates
            # to remap_ids (np.unique of the one split).
            import jax

            from tpu_als.parallel.multihost import global_id_union

            if jax.process_count() > 1 and self.mesh is None:
                # without a mesh, fit would fall into the single-device
                # branch and every process would "successfully" train on
                # only its local split
                raise ValueError(
                    "dataMode='per_host' in a multi-process deployment "
                    "requires mesh= (the per-host splits are combined by "
                    "the multi-process trainer; without a mesh each "
                    "process would silently fit only its own split)")
            user_map = IdMap(ids=global_id_union(u_raw))
            item_map = IdMap(ids=global_id_union(i_raw))
            u_idx = user_map.to_dense(u_raw)
            i_idx = item_map.to_dense(i_raw)
        else:
            u_idx, user_map = remap_ids(u_raw)
            i_idx, item_map = remap_ids(i_raw)
        cfg = self._config()
        # traffic observability is per-fit state (single-process mesh
        # path only — the multi-process builders live inside
        # train_multihost); cleared so a later fit on another path can't
        # report a stale number
        self.lastFitCommBytes = None
        self.lastFitStrategy = None

        init, start_iter = None, 0
        if self.resumeFrom is not None:
            manifest, c_uids, c_U, c_iids, c_V = load_factors(self.resumeFrom)
            if manifest.get("rank") != cfg.rank:
                raise ValueError(
                    f"resumeFrom checkpoint has rank {manifest.get('rank')}, "
                    f"estimator is configured with rank {cfg.rank}")
            if not (np.array_equal(c_uids, user_map.ids)
                    and np.array_equal(c_iids, item_map.ids)):
                raise ValueError("resumeFrom checkpoint id maps do not match "
                                 "the dataset being fit")
            # exact recovery requires identical solver hyperparameters too
            # (cgIters/cgMode change the trajectory: inexact ALS resumes
            # must continue with the same solver)
            ck = manifest.get("params", {})
            for name in ("regParam", "implicitPrefs", "alpha", "nonnegative",
                         "cgIters", "cgMode"):
                if name in ck:
                    mine = (getattr(self, name) if name.startswith("cg")
                            else self.getOrDefault(self.getParam(name)))
                    if ck[name] != mine:
                        raise ValueError(
                            f"resumeFrom checkpoint was trained with "
                            f"{name}={ck[name]!r}, estimator has {mine!r}; "
                            "resume cannot reproduce the original run")
            init = (c_U, c_V)
            start_iter = int(manifest.get("iteration") or 0)

        # scoping to the RESOLVED mode is a no-op when inheriting the
        # env/global setting and an override when guardrails= was given
        with _guardrails.scoped(gmode):
            if self.mesh is not None:
                import jax

                from tpu_als.api.fitting import (
                    fit_multiprocess,
                    fit_sharded,
                )

                mode_fit = (fit_multiprocess if jax.process_count() > 1
                            else fit_sharded)
                U, V = mode_fit(self, u_idx, i_idx, r, user_map, item_map,
                                cfg, init, start_iter)
            else:
                from tpu_als import obs

                callback = self._checkpoint_callback(user_map, item_map)
                with obs.span("train.block"):
                    ucsr = build_csr_buckets(u_idx, i_idx, r,
                                             len(user_map))
                    icsr = build_csr_buckets(i_idx, u_idx, r,
                                             len(item_map))
                with obs.span("train.fit"):
                    U, V = _train(ucsr, icsr, cfg, callback=callback,
                                  init=init, start_iter=start_iter)
                    U, V = np.asarray(U), np.asarray(V)

        return self._make_model(user_map, item_map, U, V)

    def _make_model(self, user_map, item_map, U, V):
        """Model assembly shared by ``fit`` and the multi-process CLI
        path (tpu_als.cli) — one place for the params snapshot."""
        return ALSModel(
            rank=self.getOrDefault(self.getParam("rank")),
            user_map=user_map, item_map=item_map,
            user_factors=U, item_factors=V,
            # records which solver produced the factors (trajectory-
            # changing knobs — same snapshot checkpoints persist)
            params=self._ckpt_params(),
            parent=self,
        )

    # -- estimator persistence (DefaultParamsWritable parity) -----------
    def write(self):
        return MLWriter(self)

    def save(self, path):
        """Params-only JSON save — the reference's ``DefaultParamsWritable``
        on the ALS estimator itself (SURVEY.md §2.B11).  Runtime-only knobs
        (mesh, callbacks, checkpoint dirs) are process-bound and not
        persisted."""
        self.write().save(path)

    def _save_to(self, path):
        import json
        import os

        os.makedirs(path, exist_ok=True)
        payload = {
            "class": "tpu_als.api.estimator.ALS",
            "paramMap": {p.name: v for p, v in self._paramMap.items()},
            "defaultParamMap": {p.name: v
                                for p, v in self._defaultParamMap.items()},
            "gatherStrategy": self.gatherStrategy,
            # algorithm-affecting runtime knobs travel with the estimator
            # (unlike process-bound ones: mesh, callbacks, dataMode)
            "cgIters": self.cgIters,
            "cgMode": self.cgMode,
        }
        tmp = os.path.join(path, "estimator.json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, "estimator.json"))

    @classmethod
    def load(cls, path):
        import json
        import os

        recover_interrupted_overwrite(path)
        with open(os.path.join(path, "estimator.json")) as f:
            meta = json.load(f)
        if meta.get("class") != "tpu_als.api.estimator.ALS":
            raise ValueError(
                f"{path} holds a {meta.get('class')!r} save, not an ALS "
                "estimator")
        est = cls(gatherStrategy=meta.get("gatherStrategy", "all_gather"),
                  cgIters=meta.get("cgIters", 0),
                  cgMode=meta.get("cgMode", "matfree"))
        # restore saved defaults too (DefaultParamsReader semantics): a
        # class default that changed after the save must not silently
        # apply to the loaded instance
        for name, v in meta.get("defaultParamMap", {}).items():
            est._defaultParamMap[est.getParam(name)] = v
        est.setParams(**meta.get("paramMap", {}))
        return est

    def _ckpt_params(self):
        """The params snapshot persisted with checkpoints and models —
        Param map plus the trajectory-changing runtime knobs, so the
        resume-compatibility check can reject a solver switch."""
        params = {p.name: v for p, v in self.extractParamMap().items()}
        params["cgIters"] = self.cgIters
        params["cgMode"] = self.cgMode
        return params

    def _save_checkpoint(self, user_map, item_map, iteration, U, V):
        import os

        save_factors(
            os.path.join(self.checkpointDir, "als_checkpoint"),
            user_map.ids, np.asarray(U), item_map.ids, np.asarray(V),
            params=self._ckpt_params(),
            iteration=iteration,
        )

    def _due(self, iteration):
        """(fitCallback due, checkpoint due) at this iteration — the ONE
        gating rule, consulted by the single-process callback and by the
        multi-process branch's gather decision (which must stay
        consistent with it: the gather only happens when something is
        due, and the callback then re-checks the same predicate)."""
        interval = self.getCheckpointInterval()
        due_cb = (self.fitCallback is not None
                  and iteration % self.fitCallbackInterval == 0)
        due_ck = (self.checkpointDir is not None and interval >= 1
                  and iteration % interval == 0)
        return due_cb, due_ck

    def _callback_due(self, iteration):
        """True when the per-iteration callback has any work at this
        iteration (fitCallback, checkpoint, or a pending preemption) —
        the gate fit_sharded uses to skip the slot→entity factor fetch
        on quiet iterations."""
        from tpu_als.resilience import preempt

        due_cb, due_ck = self._due(iteration)
        return due_cb or due_ck or preempt.pending(iteration)

    def _checkpoint_callback(self, user_map, item_map):
        from tpu_als.resilience import preempt

        ckpt = self.checkpointDir is not None \
            and self.getCheckpointInterval() >= 1
        if not ckpt and self.fitCallback is None \
                and not preempt.enabled():
            return None

        def cb(iteration, U, V):
            due_cb, due_ck = self._due(iteration)
            if due_cb:
                self.fitCallback(iteration, U, V)
            if due_ck:
                self._save_checkpoint(user_map, item_map, iteration, U, V)
            if preempt.pending(iteration):
                # the in-flight iteration is complete (we are at its
                # boundary): write the resume point, then stop with the
                # distinct exit status
                import os

                from tpu_als import obs

                path = None
                if self.checkpointDir is not None:
                    if not due_ck:  # don't rewrite an identical save
                        self._save_checkpoint(
                            user_map, item_map, iteration, U, V)
                    path = os.path.join(self.checkpointDir,
                                        "als_checkpoint")
                g = preempt.installed()
                signum = g.signum if g is not None else None
                obs.emit("preempted", iteration=iteration, signum=signum)
                raise preempt.Preempted(iteration, path, signum)

        return cb


_attach_accessors(ALS, [n for n, _, _, _ in _ALS_PARAMS])


class ALSModel:
    """Fitted model: factor matrices + id maps.  Mirrors
    ``pyspark.ml.recommendation.ALSModel`` (SURVEY.md §2.D)."""

    def __init__(self, rank, user_map, item_map, user_factors, item_factors,
                 params, parent=None):
        self.rank = rank
        self._user_map = user_map
        self._item_map = item_map
        self._U = np.asarray(user_factors, dtype=np.float32)
        self._V = np.asarray(item_factors, dtype=np.float32)
        self._params = dict(params)
        self.parent = parent

    # -- param passthroughs the reference model exposes ----------------
    def _get(self, name):
        return self._params[name]

    # the reference ALSModel exposes per-param setters/getters for the
    # serving-time knobs (pyspark ``ALSModel.setPredictionCol`` etc.);
    # generated below by _attach_model_accessors — the settable set is
    # exactly the knobs transform/recommend* consult at call time
    _MODEL_PARAMS = ("userCol", "itemCol", "predictionCol",
                     "coldStartStrategy", "blockSize")

    def _set(self, **kwargs):
        for name, v in kwargs.items():
            if name not in self._MODEL_PARAMS:
                raise TypeError(
                    f"{name!r} is not a settable model param "
                    f"(settable: {list(self._MODEL_PARAMS)})")
            if name == "coldStartStrategy" and v not in ("nan", "drop"):
                raise ValueError(
                    "coldStartStrategy must be 'nan' or 'drop'")
            self._params[name] = v
        return self

    @property
    def userFactors(self):
        """Frame(id, features) — entity ids are the original ids."""
        return ColumnarFrame({
            "id": self._user_map.ids,
            "features": _to_object_rows(self._U),
        })

    @property
    def itemFactors(self):
        return ColumnarFrame({
            "id": self._item_map.ids,
            "features": _to_object_rows(self._V),
        })

    # scoring chunk for transform: bounds the per-call gather at
    # ~chunk × rank device elements regardless of frame size, with ONE
    # jit specialization (the tail chunk pads with invalid ids)
    _TRANSFORM_CHUNK = 1 << 20

    # -- prediction ----------------------------------------------------
    def transform(self, dataset):
        frame = as_frame(dataset)
        userCol, itemCol = self._get("userCol"), self._get("itemCol")
        u = self._user_map.to_dense(frame[userCol])
        i = self._item_map.to_dense(frame[itemCol])
        Uj, Vj = jnp.asarray(self._U), jnp.asarray(self._V)
        B = self._TRANSFORM_CHUNK
        if len(u) <= B:
            preds = np.asarray(_predict_kernel(
                Uj, Vj, jnp.asarray(u), jnp.asarray(i),
                jnp.asarray(u >= 0), jnp.asarray(i >= 0),
            ), dtype=np.float32)
        else:
            preds = np.empty(len(u), dtype=np.float32)
            for s in range(0, len(u), B):
                ub = u[s:s + B]
                ib = i[s:s + B]
                n = len(ub)
                if n < B:  # pad the tail: one compiled shape for all
                    ub = np.pad(ub, (0, B - n), constant_values=-1)
                    ib = np.pad(ib, (0, B - n), constant_values=-1)
                preds[s:s + n] = np.asarray(_predict_kernel(
                    Uj, Vj, jnp.asarray(ub), jnp.asarray(ib),
                    jnp.asarray(ub >= 0), jnp.asarray(ib >= 0),
                ), dtype=np.float32)[:n]
        out = frame.withColumn(self._get("predictionCol"), preds)
        if self._get("coldStartStrategy") == "drop":
            out = out.filter(~np.isnan(preds))
        return out

    def predict(self, user, item):
        """Scalar prediction for one (user, item) pair (legacy surface)."""
        out = self.transform(ColumnarFrame({
            self._get("userCol"): np.asarray([user]),
            self._get("itemCol"): np.asarray([item]),
        }))
        return float(out[self._get("predictionCol")][0]) if len(out) else float("nan")

    # -- top-k recommendation ------------------------------------------
    # mesh/gatherStrategy are keyword-only additions on top of the
    # reference signatures: serve sharded over a jax.sharding.Mesh
    # (parallel/serve.py — catalog gathered or ring-streamed); the
    # default path is unchanged
    def recommendForAllUsers(self, numItems, *, mesh=None,
                             gatherStrategy="all_gather"):
        return self._recommend(self._U, self._user_map.ids, numItems,
                               users=True, mesh=mesh,
                               gatherStrategy=gatherStrategy)

    def recommendForAllItems(self, numUsers, *, mesh=None,
                             gatherStrategy="all_gather"):
        return self._recommend(self._V, self._item_map.ids, numUsers,
                               users=False, mesh=mesh,
                               gatherStrategy=gatherStrategy)

    def recommendForUserSubset(self, dataset, numItems, *, mesh=None,
                               gatherStrategy="all_gather"):
        ids = np.unique(as_frame(dataset)[self._get("userCol")])
        dense = self._user_map.to_dense(ids)
        keep = dense >= 0
        return self._recommend(self._U[dense[keep]], ids[keep], numItems,
                               users=True, mesh=mesh,
                               gatherStrategy=gatherStrategy)

    def recommendForItemSubset(self, dataset, numUsers, *, mesh=None,
                               gatherStrategy="all_gather"):
        ids = np.unique(as_frame(dataset)[self._get("itemCol")])
        dense = self._item_map.to_dense(ids)
        keep = dense >= 0
        return self._recommend(self._V[dense[keep]], ids[keep], numUsers,
                               users=False, mesh=mesh,
                               gatherStrategy=gatherStrategy)

    def _recommend(self, Q, q_ids, k, users, mesh=None,
                   gatherStrategy="all_gather"):
        """Blocked top-k: stream `blockSize` query rows at a time through the
        chunked GEMM+top_k kernel (the reference's blockify+crossJoin+queue
        path collapsed into one jitted scan — SURVEY.md §3.3).  With
        ``mesh``, the whole call runs sharded instead
        (parallel/serve.py): queries sharded over devices, catalog
        gathered or ring-streamed per ``gatherStrategy``."""
        other = self._V if users else self._U
        other_ids = self._item_map.ids if users else self._user_map.ids
        other_col = self._get("itemCol") if users else self._get("userCol")
        if other_col == "rating":
            # the struct dtype below would need two fields named 'rating'
            # (np.dtype raises a bare "duplicate field name") — surface
            # the actual conflict, and do it BEFORE the scoring loop so
            # a serving-scale call fails instantly (advisor r3)
            raise ValueError(
                f"{'itemCol' if users else 'userCol'}='rating' collides "
                "with the fixed 'rating' score field of the "
                "recommendations struct (reference schema); rename the "
                "column before calling recommendFor*")
        k = min(k, other.shape[0])
        if mesh is not None:
            import jax

            from tpu_als.parallel.serve import topk_sharded

            if jax.process_count() > 1:
                # topk_sharded returns GLOBAL arrays cross-process;
                # the id-join + frame assembly below needs host rows.
                # Refuse with direction instead of crashing on
                # np.asarray of non-addressable shards.
                raise ValueError(
                    "recommendFor*(mesh=...) supports single-process "
                    "meshes; in a multi-process deployment call "
                    "tpu_als.parallel.serve.topk_sharded directly and "
                    "read .addressable_shards per host")
            sc, ix = topk_sharded(Q, other, k, mesh,
                                  strategy=gatherStrategy)
            ids_out = other_ids[ix]
            scores_out = sc
        else:
            block = max(1, int(self._get("blockSize")))
            valid = jnp.ones(other.shape[0], dtype=bool)
            other_j = jnp.asarray(other)
            ids_out = np.empty((Q.shape[0], k), dtype=other_ids.dtype)
            scores_out = np.empty((Q.shape[0], k), dtype=np.float32)
            for s in range(0, Q.shape[0], block):
                sc, ix = topk_scores(
                    jnp.asarray(Q[s:s + block]), other_j, valid, k=k,
                    item_chunk=block,
                )
                ids_out[s:s + block] = other_ids[np.asarray(ix)]
                scores_out[s:s + block] = np.asarray(sc)
        # vectorized assembly (VERDICT r2 weak #5): the recommendations
        # column is one [n, k] structured array with the reference's struct
        # field names ((itemCol|userCol), 'rating') — column[row] is a
        # [k] record view whose elements unpack like (id, score) tuples,
        # so consumers iterate exactly as they did over the old per-row
        # list-of-tuples, without O(n·k) Python tuple construction on the
        # serving path (162k users × k=10 was ~1.6M tuples per call).
        recs = np.empty(ids_out.shape,
                        dtype=[(other_col, ids_out.dtype),
                               ("rating", np.float32)])
        recs[other_col] = ids_out
        recs["rating"] = scores_out
        key_col = self._get("userCol") if users else self._get("itemCol")
        return ColumnarFrame({key_col: q_ids, "recommendations": recs})

    def recommend_arrays(self, numItems, for_users=True, mesh=None,
                         gatherStrategy="all_gather"):
        """Dense variant of recommendForAll*: (query_ids, ids [n,k],
        scores [n,k]) as plain arrays — the TPU-friendly serving surface.

        ``mesh``: serve sharded over a ``jax.sharding.Mesh`` — query rows
        sharded across devices, and the opposite factor table either
        gathered (``gatherStrategy='all_gather'``) or ppermute-streamed
        (``'ring'``, for catalogs that don't fit one device's HBM) —
        the serving analog of the trainer's strategies
        (``parallel/serve.py``).
        """
        frame_ids = self._user_map.ids if for_users else self._item_map.ids
        Q = self._U if for_users else self._V
        other = self._V if for_users else self._U
        other_ids = self._item_map.ids if for_users else self._user_map.ids
        k = min(numItems, other.shape[0])
        if mesh is not None:
            import jax

            from tpu_als.parallel.serve import topk_sharded

            if jax.process_count() > 1:
                raise ValueError(
                    "recommend_arrays(mesh=...) supports single-process "
                    "meshes; in a multi-process deployment call "
                    "tpu_als.parallel.serve.topk_sharded directly and "
                    "read .addressable_shards per host")
            sc, ix = topk_sharded(Q, other, k, mesh,
                                  strategy=gatherStrategy)
        else:
            sc, ix = topk_scores(
                jnp.asarray(Q), jnp.asarray(other),
                jnp.ones(other.shape[0], bool), k=k,
            )
        return frame_ids, other_ids[np.asarray(ix)], np.asarray(sc)

    # -- persistence ----------------------------------------------------
    def save(self, path):
        """Equivalent to ``write().save(path)`` — raises if ``path`` exists
        (reference semantics); checkpointing during fit overwrites via
        ``io.checkpoint.save_factors`` directly."""
        self.write().save(path)

    def write(self):
        return MLWriter(self)

    def _save_to(self, path):
        save_factors(path, self._user_map.ids, self._U,
                     self._item_map.ids, self._V, params=self._params)

    @classmethod
    def load(cls, path):
        recover_interrupted_overwrite(path)
        manifest, u_ids, U, i_ids, V = load_factors(path)
        return cls(rank=manifest["rank"], user_map=IdMap(ids=u_ids),
                   item_map=IdMap(ids=i_ids), user_factors=U, item_factors=V,
                   params=manifest["params"])


def _attach_model_accessors(cls):
    for name in cls._MODEL_PARAMS:
        cap = name[0].upper() + name[1:]

        def getter(self, _n=name):
            return self._params[_n]

        def setter(self, value, _n=name):
            return self._set(**{_n: value})

        setattr(cls, f"get{cap}", getter)
        setattr(cls, f"set{cap}", setter)


_attach_model_accessors(ALSModel)


def _to_object_rows(mat):
    out = np.empty(mat.shape[0], dtype=object)
    for i in range(mat.shape[0]):
        out[i] = mat[i].copy()
    return out
