from tpu_als.api.estimator import ALS, ALSModel  # noqa: F401
from tpu_als.api.evaluation import (  # noqa: F401
    RankingEvaluator,
    RankingMetrics,
    RegressionMetrics,
    RegressionEvaluator,
)
from tpu_als.api.params import Param, Params, TypeConverters  # noqa: F401
from tpu_als.api.pipeline import (  # noqa: F401
    IndexToString,
    Pipeline,
    PipelineModel,
    StringIndexer,
    StringIndexerModel,
)
from tpu_als.api.tuning import (  # noqa: F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from tpu_als.api import legacy  # noqa: F401
