"""Background update loop: rating events → fold-in → incremental publish.

One thread owns the whole arrival-to-servable path so its latency is a
single measurable quantity:

1. **Admit.**  ``submit(user, item, rating)`` appends to a bounded
   queue; at capacity it sheds with the serving batcher's own typed
   :class:`~tpu_als.serving.batcher.Overloaded` (``live.shed`` counts
   it) — producers see the identical backpressure contract the request
   path uses.
2. **Accumulate.**  The loop gathers up to ``max_batch`` events or
   until the oldest has waited ``max_wait_ms`` (planner-resolved
   cadence, ``plan.resolve_live_cadence``), whichever first — the
   fold-in kernel's fixed cost amortizes over the batch.
3. **Quarantine.**  Poisoned events (non-finite or out-of-range
   ratings, ``core.ratings.invalid_rating_mask``) are dropped before
   they can reach the factors, through the SAME obs contract streaming
   ingest uses: one ``ingest_quarantined`` event + the
   ``ingest.quarantined_rows`` counter.
4. **Fold.**  ``FoldInServer.update`` solves the touched user rows
   (and ``update_items`` the touched item rows when ``fold_items`` is
   on — the path that exercises incremental index re-quantization).
5. **Publish.**  ``ServingEngine.publish_update`` swaps the new
   generation in atomically — retag for user-only batches, an
   O(touched) delta re-quantization for item batches — never a full
   O(catalog) rebuild while the live index is healthy.

Freshness (``live.freshness_seconds``) is per EVENT, arrival →
publish-visible, so the histogram's p99 is exactly the SLO quantity:
how stale can a rating be before it influences recommendations.  A
breach emits ``live_freshness_breach`` and dumps the updater's flight
ring (queue_wait/quarantine/foldin/publish spans per batch), so the
trail says WHERE the budget went — queued behind a slow fold-in, or a
compaction-heavy publish.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tpu_als import obs
from tpu_als.core.ratings import invalid_rating_mask
from tpu_als.obs import tracing
from tpu_als.obs.trace import FlightRecorder
from tpu_als.resilience import faults
from tpu_als.serving.batcher import Overloaded

# the per-batch span breakdown the updater's flight ring carries
# (source of truth in the stdlib-only schema module, where the jax-free
# static check pins it against the record's structural field names)
LIVE_SPAN_KEYS = obs.schema.LIVE_SPAN_KEYS


class LiveUpdater:
    """Continuous fold-in → publish over a :class:`FoldInServer` and a
    :class:`ServingEngine`.

    ``foldin`` wraps the model whose factors are updated; every publish
    pushes that model's current U/V into ``engine``.  ``fold_items``
    additionally solves the ITEM side of each batch (new/updated items
    become recommendable; their rows ride the index's delta segment).
    ``slo_s`` is the arrival → servable objective; None disables the
    breach trigger but freshness is always measured.

    ``tenant`` (default: the engine's own tenant) labels every live.*
    metric this loop writes and tags its events/flight records, so a
    freshness breach in a multi-tenant process names its tenant from
    the obs trail alone (docs/tenancy.md).
    """

    def __init__(self, engine, foldin, *, max_queue=4096,
                 max_batch=None, max_wait_ms=None, slo_s=None,
                 fold_items=False, flight_capacity=64, tenant=None):
        from tpu_als import plan as _plan

        cad = _plan.resolve_live_cadence()
        self.engine = engine
        self.foldin = foldin
        if tenant is None:
            tenant = getattr(engine, "tenant", None)
        self.tenant = str(tenant) if tenant is not None else None
        self._labels = {"tenant": self.tenant} if self.tenant else {}
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch if max_batch is not None
                             else cad["max_batch"])
        self.max_wait_s = float(max_wait_ms if max_wait_ms is not None
                                else cad["max_wait_ms"]) / 1e3
        self.slo_s = float(slo_s) if slo_s is not None else None
        self.fold_items = bool(fold_items)
        self.flight = FlightRecorder(flight_capacity,
                                     span_keys=LIVE_SPAN_KEYS,
                                     labels=self._labels)
        self._queue = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None

    # -- producer side ------------------------------------------------
    def submit(self, user, item, rating):
        """Admit one rating event (original user/item ids).  Raises
        :class:`Overloaded` when the queue is at capacity — the same
        typed shed the serving batcher raises, so producers share one
        backpressure contract.  Each admitted event is stamped with a
        root causal-trace context (``obs.tracing``; None disarmed) the
        loop carries through coalescing -> fold-in -> publish ->
        visibility, so a freshness breach is explainable per event."""
        t_arrival = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("LiveUpdater is stopped")
            if len(self._queue) >= self.max_queue:
                obs.counter("live.shed", **self._labels)
                tracing.start_trace("live.admit", tenant=self.tenant,
                                    status="shed")
                raise Overloaded(
                    f"live update queue at capacity ({self.max_queue})")
            ctx = tracing.start_trace("live.admit", tenant=self.tenant)
            self._queue.append((user, item, float(rating), t_arrival,
                                ctx))
            self._cond.notify()

    @property
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    # -- lifecycle ----------------------------------------------------
    def start(self):
        if self._thread is not None:
            raise RuntimeError("updater already started")
        self._thread = threading.Thread(
            target=self._run, name="tpu-als-live", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout_s=10.0):
        """Close admission, drain the queue, join the loop."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(drain_timeout_s)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- update loop --------------------------------------------------
    def _next_batch(self):
        """Block for the first event, then accumulate until ``max_batch``
        or the oldest event has waited ``max_wait_s``.  Returns None on
        an idle timeout (the loop re-checks for shutdown); a closed,
        non-empty queue drains immediately (no wait)."""
        with self._cond:
            if not self._queue:
                if self._closed:
                    return None
                self._cond.wait(0.05)
                if not self._queue:
                    return None
            t_oldest = self._queue[0][3]
            while (len(self._queue) < self.max_batch
                   and not self._closed):
                left = self.max_wait_s - (time.perf_counter() - t_oldest)
                if left <= 0:
                    break
                self._cond.wait(left)
            batch = self._queue[:self.max_batch]
            del self._queue[:self.max_batch]
            obs.gauge("live.queue_depth", len(self._queue),
                      **self._labels)
            return batch

    def _run(self):
        while True:
            batch = self._next_batch()
            if batch is None:
                with self._cond:
                    if self._closed and not self._queue:
                        return
                continue
            try:
                self._process(batch)
            except BaseException as e:  # noqa: BLE001 — loop must survive
                if not isinstance(e, faults.InjectedFault):
                    obs.emit("warning", what="live.update",
                             reason=f"{type(e).__name__}: {e}")

    def _process(self, batch):
        t0 = time.perf_counter()
        users = np.asarray([e[0] for e in batch])
        items = np.asarray([e[1] for e in batch])
        ratings = np.asarray([e[2] for e in batch], dtype=np.float32)
        arrivals = np.asarray([e[3] for e in batch])
        # chain the queue hop per event (its own wait, not the batch's)
        ctxs = [tracing.record_span(e[4], "live.queue",
                                    seconds=t0 - e[3])
                if e[4] is not None else None
                for e in batch]
        queue_wait = t0 - float(arrivals.min())

        # quarantine BEFORE the factors can see a poisoned value — the
        # streaming-ingest contract, same event + counter vocabulary
        bad = invalid_rating_mask(ratings)
        n_bad = int(bad.sum())
        if n_bad:
            nonfinite = int((~np.isfinite(ratings)).sum())
            obs.counter("ingest.quarantined_rows", n_bad)
            obs.emit("ingest_quarantined", path="live", rows=n_bad,
                     reasons={"nonfinite": nonfinite,
                              "out_of_range": n_bad - nonfinite},
                     **self._labels)
            keep = ~bad
            for c, dropped in zip(ctxs, bad):
                # a poisoned event's trail ENDS at quarantine — status
                # says so; the trace is complete, not dropped
                if dropped and c is not None:
                    tracing.record_span(c, "live.quarantine",
                                        status="quarantined")
            users, items = users[keep], items[keep]
            ratings, arrivals = ratings[keep], arrivals[keep]
            ctxs = [c for c, k in zip(ctxs, keep) if k]
        quarantine_s = time.perf_counter() - t0
        obs.histogram("live.batch_rows", len(ratings), **self._labels)
        if len(ratings) == 0:
            self.flight.record(
                "quarantined",
                {"queue_wait": queue_wait, "quarantine": quarantine_s})
            return

        p = self.foldin.model._params
        frame = {p["userCol"]: users, p["itemCol"]: items,
                 p["ratingCol"]: ratings}
        tf = time.perf_counter()
        touched_users = self.foldin.update(frame)
        touched_item_rows = None
        if self.fold_items:
            t_items = self.foldin.update_items(frame)
            touched_item_rows = self.foldin.model._item_map.to_dense(
                np.asarray(t_items))
        foldin_s = time.perf_counter() - tf
        ctxs = [tracing.record_span(c, "live.foldin", seconds=foldin_s)
                if c is not None else None for c in ctxs]

        tp = time.perf_counter()
        m = self.foldin.model
        seq, mode = self.engine.publish_update(
            m._U, m._V, touched_items=touched_item_rows, trace=ctxs)
        publish_s = time.perf_counter() - tp
        ctxs = [tracing.record_span(c, "live.publish",
                                    seconds=publish_s, seq=seq,
                                    mode=mode)
                if c is not None else None for c in ctxs]

        done = time.perf_counter()
        worst, worst_ctx = 0.0, None
        for a, c in zip(arrivals, ctxs):
            fr = done - float(a)
            obs.histogram("live.freshness_seconds", fr, **self._labels)
            # the terminal hop: this event's publish seq is now visible
            # to the score path; its seconds ARE the freshness sample
            if c is not None:
                tracing.record_span(c, "live.visible", seconds=fr,
                                    seq=seq)
            if fr > worst:
                worst, worst_ctx = fr, c
        touched = len(touched_users) + (
            len(touched_item_rows) if touched_item_rows is not None
            else 0)
        obs.emit("live_update", seq=seq, events=len(ratings),
                 touched=touched, mode=mode, **self._labels)
        self.flight.record(
            "ok",
            {"queue_wait": queue_wait, "quarantine": quarantine_s,
             "foldin": foldin_s, "publish": publish_s},
            e2e_seconds=worst, seq=seq, mode=mode,
            trace_ids=sorted({c.trace_id for c in ctxs
                              if c is not None}) or None)
        if self.slo_s is not None and worst > self.slo_s:
            obs.emit("live_freshness_breach", seq=seq,
                     freshness_seconds=worst, slo_s=self.slo_s,
                     trace_id=(worst_ctx.trace_id
                               if worst_ctx is not None else None),
                     **self._labels)
            self.flight.dump("freshness_breach")
