"""Continuous fold-in → publish: rating arrival to servable in seconds.

The live half of BASELINE config 3 ("micro-batches of new ratings →
incremental user-factor jit update") and ROADMAP item 3's freshness
target.  The pieces existed in isolation — ``stream/microbatch.py``
folds factors, ``serving/engine.py`` publishes atomically — and this
package closes the loop:

- :class:`~tpu_als.live.updater.LiveUpdater` — a background update
  loop behind a bounded admission queue of rating events (the
  batcher's deadline/shed vocabulary: a full queue raises the same
  typed ``Overloaded``), accumulating micro-batches under the
  planner's ``max_batch``/``max_wait_ms`` cadence, quarantining
  poisoned events (the ``ingest_quarantined`` contract), folding via
  ``FoldInServer``, and publishing through
  ``ServingEngine.publish_update`` — the O(touched rows) incremental
  path, never a full index rebuild.
- Freshness is MEASURED, not assumed: every event's arrival →
  servable latency (its fold-in's publish seq visible to the score
  path) lands in ``live.freshness_seconds``; an SLO breach dumps the
  updater's flight-recorder tail (queue_wait/quarantine/foldin/publish
  span breakdown) into the obs trail.

See docs/serving.md (freshness section) for the lifecycle and knobs,
and the ``continuous-freshness`` scenario for the end-to-end proof.
"""

from tpu_als.live.updater import LiveUpdater

__all__ = ["LiveUpdater"]
