"""Deterministic fault injection — the testable half of fault tolerance.

The reference stack's resilience story is exercised by Spark's own chaos
suites (executor kills in ``local-cluster`` masters, SURVEY.md §5.3); our
JAX port needs the same property: every failure path must be REACHABLE on
demand, deterministically, so a test can assert recovery instead of
hoping a flake exercises the handler.  This module is that switchboard.

Named fault points (the complete vocabulary — sites call
:func:`check` with one of these):

========================  ====================================================
``checkpoint.write``      inside ``io.checkpoint.save_factors``' write body,
                          before the atomic install (corrupt = torn npz)
``checkpoint.rename``     inside ``io.checkpoint.atomic_install``, in the
                          window between the two renames (crash mid-swap)
``ingest.read_chunk``     per chunk read in ``io.stream.stream_ingest``
                          (corrupt = bit-flipped chunk bytes)
``multihost.init``        inside ``parallel.multihost.init_distributed``'s
                          rendezvous attempt
``comm.ring_step``        per trainer iteration of the ring strategies
                          (host-level, around the jitted step; corrupt =
                          non-finite factors)
``serve.gather``          inside ``parallel.serve.topk_sharded``'s sharded
                          execute (corrupt = stale/lost factor shard)
``serving.publish``       inside ``serving.engine.ServingEngine.publish``
                          (corrupt = the new int8 index is tagged stale, so
                          every batch falls back to the exact path)
``serving.score``         per serving micro-batch, before scoring (corrupt =
                          treat the index as stale for this batch; raise =
                          the batch's tickets fail with the injected error)
``solve.gram``            per training iteration of ``core.als.train``
                          (host-level, after the jitted step — the
                          comm.ring_step pattern; corrupt = NaN-poison a
                          factor row, exactly what a blown Gram solve
                          leaves behind)
``ingest.record``         per parsed record in ``io.stream.stream_ingest``
                          — armed only (disarmed ingest never walks
                          records; corrupt = the record's rating column is
                          rewritten to ``nan`` pre-parse, a genuinely
                          poisoned text record for the quarantine path)
``mesh.device_lost``      per trainer iteration of the sharded strategies
                          (host-level, armed only, around the jitted
                          step — the comm.ring_step pattern; corrupt =
                          a device DIES: the elastic registry marks the
                          victim lost, so the health probe confirms a
                          dead peer; raise = a transient ICI hiccup —
                          the step fails once but every peer probes
                          healthy, so the detector retries in place)
========================  ====================================================

Spec grammar (``TPU_ALS_FAULT_SPEC`` env var, or :func:`install`)::

    SPEC  ::= RULE (';' RULE)*
    RULE  ::= POINT '=' MODE ('@' SCHED)?
    MODE  ::= 'raise' | 'corrupt' | 'hang:' SECONDS
    SCHED ::= 'once' | 'nth=' K | 'first=' N | 'every=' K
            | 'prob=' P (',seed=' S)?

Hit indices are 1-based per point.  ``once`` == ``nth=1`` (the default
schedule).  ``prob`` draws from a dedicated ``random.Random(seed)`` per
rule — the schedule is a pure function of (spec, hit index), never of
wall clock or global RNG state, so a failing chaos run replays exactly.

Modes at the site: ``raise`` raises :class:`InjectedFault` (an ``IOError``
subclass, so the retry policies treat it as transient I/O); ``hang:S``
sleeps S seconds then continues (a stall the caller's timeout must
catch); ``corrupt`` returns ``"corrupt"`` from :func:`check` and the
site applies its own corruption (torn file, flipped bytes, NaN factors).

When no spec is installed, :func:`check` is a single attribute load and
``None`` compare, and :func:`armed` lets trace-time call sites (the ring
step builder) skip wrapping entirely — traced jaxprs are byte-identical
to a build without this module.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

FAULT_POINTS = (
    "checkpoint.write",
    "checkpoint.rename",
    "ingest.read_chunk",
    "multihost.init",
    "comm.ring_step",
    "serve.gather",
    "serving.publish",
    "serving.score",
    "solve.gram",
    "ingest.record",
    "mesh.device_lost",
)

MODES = ("raise", "corrupt", "hang")

ENV_VAR = "TPU_ALS_FAULT_SPEC"


class InjectedFault(IOError):
    """Raised by an armed ``raise``-mode fault point.

    Subclasses ``IOError`` deliberately: the injected failure stands in
    for a transient I/O / RPC error, so the retry policies
    (tpu_als.resilience.retry) classify it as retryable without a
    special case at every call site."""

    def __init__(self, point, hit):
        super().__init__(
            f"injected fault at {point!r} (hit {hit}) — "
            f"{ENV_VAR} / tpu_als.resilience.faults.install")
        self.point = point
        self.hit = hit


class FaultSpecError(ValueError):
    """A malformed ``TPU_ALS_FAULT_SPEC`` string."""


class _Rule:
    __slots__ = ("point", "mode", "hang_seconds", "sched", "k",
                 "prob", "_rng", "hits", "fired")

    def __init__(self, point, mode, hang_seconds, sched, k, prob, seed):
        self.point = point
        self.mode = mode
        self.hang_seconds = hang_seconds
        self.sched = sched
        self.k = k
        self.prob = prob
        self._rng = random.Random(seed) if sched == "prob" else None
        self.hits = 0      # times the point was reached
        self.fired = 0     # times the fault actually triggered

    def due(self):
        """Advance the hit counter and decide whether this hit fires."""
        self.hits += 1
        if self.sched == "nth":
            hit = self.hits == self.k
        elif self.sched == "first":
            hit = self.hits <= self.k
        elif self.sched == "every":
            hit = self.hits % self.k == 0
        else:  # prob
            hit = self._rng.random() < self.prob
        if hit:
            self.fired += 1
        return hit


def _parse_rule(text):
    text = text.strip()
    point, sep, rest = text.partition("=")
    point = point.strip()
    if not sep or not rest:
        raise FaultSpecError(
            f"fault rule {text!r} is not POINT=MODE[@SCHED]")
    if point not in FAULT_POINTS:
        raise FaultSpecError(
            f"unknown fault point {point!r} (known: {list(FAULT_POINTS)})")
    mode_part, _, sched_part = rest.partition("@")
    mode_part = mode_part.strip()
    hang_seconds = 0.0
    if mode_part.startswith("hang:"):
        mode = "hang"
        try:
            hang_seconds = float(mode_part[len("hang:"):])
        except ValueError:
            raise FaultSpecError(
                f"hang mode needs 'hang:SECONDS', got {mode_part!r}")
        if hang_seconds < 0:
            raise FaultSpecError("hang seconds must be >= 0")
    elif mode_part in ("raise", "corrupt"):
        mode = mode_part
    else:
        raise FaultSpecError(
            f"unknown fault mode {mode_part!r} (known: raise, corrupt, "
            "hang:SECONDS)")
    sched, k, prob, seed = "nth", 1, 0.0, 0
    sched_part = sched_part.strip()
    if sched_part and sched_part != "once":
        key, _, val = sched_part.partition("=")
        key = key.strip()
        if key in ("nth", "first", "every"):
            sched = key
            try:
                k = int(val)
            except ValueError:
                raise FaultSpecError(
                    f"schedule {sched_part!r}: K must be an integer")
            if k < 1:
                raise FaultSpecError(f"schedule {sched_part!r}: K must "
                                     "be >= 1")
        elif key == "prob":
            sched = "prob"
            body, _, seed_part = val.partition(",")
            try:
                prob = float(body)
            except ValueError:
                raise FaultSpecError(
                    f"schedule {sched_part!r}: P must be a float")
            if not 0.0 <= prob <= 1.0:
                raise FaultSpecError("prob must be in [0, 1]")
            if seed_part:
                skey, _, sval = seed_part.partition("=")
                if skey.strip() != "seed":
                    raise FaultSpecError(
                        f"schedule {sched_part!r}: expected ',seed=S'")
                try:
                    seed = int(sval)
                except ValueError:
                    raise FaultSpecError(
                        f"schedule {sched_part!r}: seed must be an "
                        "integer")
        else:
            raise FaultSpecError(
                f"unknown schedule {sched_part!r} (known: once, nth=K, "
                "first=N, every=K, prob=P[,seed=S])")
    return _Rule(point, mode, hang_seconds, sched, k, prob, seed)


def parse_spec(spec):
    """Parse a spec string into ``{point: _Rule}``; raises
    :class:`FaultSpecError` on any malformed rule."""
    rules = {}
    for part in spec.split(";"):
        if not part.strip():
            continue
        rule = _parse_rule(part)
        if rule.point in rules:
            raise FaultSpecError(
                f"fault point {rule.point!r} appears twice in the spec")
        rules[rule.point] = rule
    if not rules:
        raise FaultSpecError(f"empty fault spec {spec!r}")
    return rules


# the installed rule table; None = disarmed (the common case — check()
# is then one load + compare).  A lock guards install/clear vs readers
# on other threads (FoldInServer, timeout threads); the armed fast path
# reads one reference without taking it.
_rules = None
_lock = threading.Lock()

# saved rule tables for push_spec/pop_spec (scoped arming windows — the
# scenario runner's per-phase specs and the soak chaos schedule)
_stack = []


def install(spec):
    """Arm the harness: ``spec`` is a grammar string or a pre-parsed
    ``{point: _Rule}``.  Replaces any previous installation."""
    global _rules
    rules = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    with _lock:
        _rules = rules
    return rules


def push_spec(spec):
    """Arm ``spec`` as a scoped OVERLAY over the current rule table and
    save the previous table for :func:`pop_spec`.

    Overlay semantics: points named by ``spec`` get fresh rules; every
    other armed point keeps its existing ``_Rule`` object (hit counters
    and all), so a chaos *window* can re-arm ``serving.publish`` while
    a scenario-level ``solve.gram`` rule stays live underneath.  LIFO:
    every ``push_spec`` must be paired with exactly one ``pop_spec`` —
    the scenario runner and the soak chaos scheduler both restore in a
    ``finally`` so a failing window never leaks its rules."""
    global _rules
    rules = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    with _lock:
        _stack.append(_rules)
        base = dict(_rules) if _rules else {}
        base.update(rules)
        _rules = base
    return rules


def pop_spec():
    """Restore the rule table saved by the matching :func:`push_spec`
    (``None`` restores the disarmed state).  Raises ``RuntimeError`` on
    an unbalanced pop — a silent no-op here would leave chaos armed."""
    global _rules
    with _lock:
        if not _stack:
            raise RuntimeError(
                "faults.pop_spec() without a matching push_spec()")
        _rules = _stack.pop()


def push_depth():
    """How many scoped specs are currently pushed (test/debug aid)."""
    with _lock:
        return len(_stack)


def install_from_env(environ=None):
    """Arm from ``TPU_ALS_FAULT_SPEC`` if set; no-op (and disarm) when
    unset.  Called once at import, callable again by tests."""
    spec = (environ if environ is not None else os.environ).get(ENV_VAR)
    if spec:
        return install(spec)
    clear()
    return None


def clear():
    """Disarm every fault point.  Also discards any scoped specs still
    pushed (a full disarm resets the push/pop stack — tests that clear
    in teardown must not hand stale saved tables to the next test)."""
    global _rules
    with _lock:
        _rules = None
        _stack.clear()


def active():
    """True when any fault point is armed."""
    return _rules is not None


def armed(point):
    """True when ``point`` specifically is armed — trace-time call sites
    use this to skip wrapping jitted code entirely when disarmed."""
    r = _rules
    return r is not None and point in r


def hits(point):
    """(times reached, times fired) for an armed point; (0, 0) when
    disarmed."""
    r = _rules
    if r is None or point not in r:
        return (0, 0)
    rule = r[point]
    return (rule.hits, rule.fired)


def check(point):
    """The fault point itself.  Returns ``None`` (continue normally) or
    ``"corrupt"`` (the caller must corrupt its artifact); raises
    :class:`InjectedFault` for raise mode; sleeps for hang mode.

    Disarmed cost: one module-attribute load and an ``is None`` test.
    """
    r = _rules
    if r is None:
        return None
    rule = r.get(point)
    if rule is None or not rule.due():
        return None
    _emit_fired(rule)
    if rule.mode == "raise":
        raise InjectedFault(point, rule.hits)
    if rule.mode == "hang":
        time.sleep(rule.hang_seconds)
        return None
    return "corrupt"


def _emit_fired(rule):
    """One ``fault_injected`` obs event per firing — but only when the
    obs module is already loaded (this module must stay importable from
    jax-free contexts like bench.py's pre-probe phase)."""
    obs = sys.modules.get("tpu_als.obs")
    if obs is None:
        return
    try:
        obs.emit("fault_injected", point=rule.point, mode=rule.mode,
                 hit=rule.hits)
    except Exception:
        pass  # chaos instrumentation must never mask the chaos itself


try:
    install_from_env()
except FaultSpecError as _e:
    # the import-time arm must not kill every importer of the package
    # with a traceback (pytest collection, library embedders) — but an
    # unparseable spec silently disarming chaos would be worse.  Leave
    # the harness DISARMED with a warning nobody can miss; the CLI front
    # door (cli._validate_fault_spec) re-parses and exits loudly with
    # the typed error before any command body runs.  Explicit
    # install_from_env()/install() calls still raise.
    import warnings as _warnings

    _warnings.warn(
        f"{ENV_VAR} is unparseable and was IGNORED (faults disarmed): "
        f"{_e}", RuntimeWarning)
