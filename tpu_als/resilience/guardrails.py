"""Numerical-health guardrails: divergence sentinels + bounded rollback.

The rest of :mod:`tpu_als.resilience` recovers from *process*-level
failures (kills, torn publishes, corrupt checkpoints); this module
guards against *numerical* failure — a NaN seeded into the factors, an
ill-conditioned per-row Gram system, a poisoned rating stream — which
would otherwise destroy a fit silently: ALS has no loss curve anyone
watches per step, and a non-finite factor row propagates through the
next normal-equation sum to every entity it touches.

Three layers, armed together by one mode knob
(``tpu_als train --guardrails off|warn|recover``, env
``TPU_ALS_GUARDRAILS``, or :func:`set_mode`):

- **Sentinels** — cheap on-device reductions over the factors
  (finiteness, factor-norm band, norm-trend growth), computed by one
  tiny jitted function per iteration and READ only at the existing
  iteration-boundary callback gate, so the armed cost is one small
  kernel plus one scalar sync per iteration and the production step's
  jaxpr is untouched.  Disarmed, the cost is one mode check per
  ``train()`` call — the jitted step is byte-identical (pinned in
  tests/test_guardrails.py, the perf/ne_audit.py discipline).
- **Adaptive solve** — ``recover`` mode rebuilds the step with
  ``AlsConfig.adaptive_solve=True``: residual-checked jitter escalation
  (base → 1e-4 → 1e-2) with a final CG fallback inside
  :func:`tpu_als.ops.solve.solve_spd`, inherited by every solve backend
  because it sits above the dispatch (the shared pre-regularization
  contract).
- **Rollback** — a rolling last-good factor snapshot (copied at each
  healthy boundary; the production step donates its inputs, so the
  snapshot must be a real copy).  On a trip in ``recover`` mode the
  iteration is retried from the snapshot with a seeded perturbation and
  a regularization bump; the budget reuses
  :class:`tpu_als.resilience.retry.RetryPolicy` (``max_attempts``
  rollbacks), after which the typed :class:`TrainDiverged` raises.
  ``warn`` mode only emits and keeps going.

Obs trail: every trip emits ``guardrail_tripped``; every rollback bumps
the ``train.rollbacks`` counter and emits ``train_rollback``.  The
ingest half of the guardrail story (poisoned-input quarantine) lives in
:mod:`tpu_als.io.stream` / :mod:`tpu_als.core.ratings`.

Deliberately importable without jax (the mode check runs in jax-free
contexts); jax is imported only once a Monitor actually runs.
"""

from __future__ import annotations

import contextlib
import os

from tpu_als.resilience.retry import RetryPolicy

MODES = ("off", "warn", "recover")

ENV_VAR = "TPU_ALS_GUARDRAILS"

# sentinel vocabulary (docs/resilience.md): the `sentinel` field of
# every guardrail_tripped event is one of these
SENTINELS = ("nonfinite", "norm_band", "trend")

# default thresholds.  Factor rows start unit-norm (core.als.init_factors)
# and a healthy explicit/implicit fit keeps row norms within a few orders
# of magnitude of the rating scale; 1e4 is far outside any converging
# trajectory while far inside f32 overflow.  The trend sentinel fires on a
# >10x global-norm jump between consecutive healthy iterations — ALS
# monotonically decreases its objective, so a norm explosion is the
# cheap, ratings-free proxy for an RMSE-trend reversal.
NORM_BAND_MAX = 1e4
TREND_FACTOR = 10.0

# recover-mode knobs: every rollback perturbs the snapshot by
# PERTURB_SCALE gaussian noise (seeded — replays exactly) and multiplies
# the effective regParam by REG_BUMP_FACTOR for the retried iterations.
PERTURB_SCALE = 1e-3
REG_BUMP_FACTOR = 10.0

# default rollback budget: 3 rollbacks, then TrainDiverged.  A
# RetryPolicy so call sites can override with the same vocabulary every
# other resilience site uses (delays are irrelevant — rollback retries
# immediately).
DEFAULT_ROLLBACK_POLICY = RetryPolicy(max_attempts=3, base_delay=0.0,
                                      jitter=0.0)


class TrainDiverged(ArithmeticError):
    """The rollback budget is exhausted and the fit still trips a
    sentinel — the run is numerically unrecoverable under the current
    config (raise regParam / jitter, or inspect the data)."""

    def __init__(self, iteration, rollbacks, sentinel):
        super().__init__(
            f"training diverged at iteration {iteration}: sentinel "
            f"{sentinel!r} still trips after {rollbacks} rollback(s) — "
            "rollback budget exhausted (see docs/resilience.md "
            "guardrails)")
        self.iteration = iteration
        self.rollbacks = rollbacks
        self.sentinel = sentinel


_mode = None   # explicit set_mode value; None -> consult the env var


def set_mode(mode):
    """Arm the guardrails programmatically (the estimator's
    ``guardrails=`` knob lands here)."""
    global _mode
    if mode not in MODES:
        raise ValueError(f"unknown guardrails mode {mode!r} "
                         f"(expected one of {MODES})")
    _mode = mode


def clear_mode():
    """Back to the environment default."""
    global _mode
    _mode = None


def guardrails_mode():
    """The effective mode: an explicit :func:`set_mode` wins, else the
    ``TPU_ALS_GUARDRAILS`` env var, else 'off'.  A garbage env value
    raises (silently disarming a guardrail would be worse)."""
    if _mode is not None:
        return _mode
    env = os.environ.get(ENV_VAR, "off") or "off"
    if env not in MODES:
        raise ValueError(f"{ENV_VAR}={env!r} is not a guardrails mode "
                         f"(expected one of {MODES})")
    return env


def armed():
    return guardrails_mode() != "off"


@contextlib.contextmanager
def scoped(mode):
    """Scoped arming for tests, scenarios, and the estimator fit."""
    global _mode
    prev = _mode
    set_mode(mode)
    try:
        yield
    finally:
        _mode = prev


_health_jit = None


def health_stats(U, V):
    """One jitted reduction over both factor matrices:
    ``[finite, max_row_norm_u, max_row_norm_v, global_fro_norm]`` as a
    length-4 f32 device array.  O(N·r) elementwise + reduce — trivial
    next to a half-step's gathers — and NOT read here: the caller syncs
    it at the iteration boundary."""
    global _health_jit
    if _health_jit is None:
        import jax
        import jax.numpy as jnp

        def h(U, V):
            finite = jnp.isfinite(U).all() & jnp.isfinite(V).all()
            un = jnp.sqrt(jnp.max(jnp.sum(U * U, axis=1)))
            vn = jnp.sqrt(jnp.max(jnp.sum(V * V, axis=1)))
            fro = jnp.sqrt(jnp.sum(U * U) + jnp.sum(V * V))
            return jnp.stack([finite.astype(jnp.float32), un, vn, fro])

        _health_jit = jax.jit(h)
    return _health_jit(U, V)


class Monitor:
    """Per-fit sentinel state + rollback machinery for the training loop
    (:func:`tpu_als.core.als.train` instantiates one when armed).

    The loop contract, per iteration: :meth:`keep_last_good` BEFORE the
    step (the step donates its inputs), :meth:`judge` on the outputs at
    the boundary, and — on a trip in recover mode — :meth:`rollback` to
    get perturbed last-good factors plus the bumped reg scale for the
    rebuilt step.
    """

    def __init__(self, cfg, mode, *, norm_band_max=NORM_BAND_MAX,
                 trend_factor=TREND_FACTOR, policy=None):
        if mode not in ("warn", "recover"):
            raise ValueError(f"Monitor mode must be 'warn' or 'recover', "
                             f"got {mode!r}")
        self.cfg = cfg
        self.mode = mode
        self.norm_band_max = float(norm_band_max)
        self.trend_factor = float(trend_factor)
        self.policy = policy if policy is not None \
            else DEFAULT_ROLLBACK_POLICY
        self.rollbacks = 0
        self.reg_scale = 1.0
        self._snap = None
        self._prev_fro = None

    def keep_last_good(self, U, V, retry=False):
        """Snapshot the pre-step factors (recover mode only; warn never
        rolls back so it never pays the copy).  ``retry=True`` marks a
        post-rollback attempt: the perturbed factors must NOT replace
        the clean snapshot they were derived from."""
        if self.mode != "recover" or retry:
            return
        import jax.numpy as jnp

        self._snap = (jnp.array(U, copy=True), jnp.array(V, copy=True))

    def judge(self, iteration, U, V):
        """Read the sentinels at the iteration boundary (the one host
        sync).  Returns the tripped sentinel name, or None when healthy;
        emits ``guardrail_tripped`` on a trip."""
        import numpy as np

        s = np.asarray(health_stats(U, V))
        finite = bool(s[0])
        row_norm = float(max(s[1], s[2]))
        fro = float(s[3])
        trip = None
        value = None
        if not finite:
            trip, value = "nonfinite", 0.0
        elif row_norm > self.norm_band_max:
            trip, value = "norm_band", row_norm
        elif (self._prev_fro is not None
                and fro > self.trend_factor * self._prev_fro):
            trip, value = "trend", fro / self._prev_fro
        if trip is None:
            self._prev_fro = fro
            return None
        from tpu_als import obs

        obs.emit("guardrail_tripped", iteration=int(iteration),
                 sentinel=trip, mode=self.mode, value=value)
        return trip

    def rollback(self, iteration, sentinel):
        """Bounded rollback-and-retry: restore the last-good snapshot
        with a seeded perturbation and bump the regularization.  Returns
        ``(U, V, reg_scale)``; raises :class:`TrainDiverged` once the
        policy's ``max_attempts`` rollbacks are spent (or when no
        healthy snapshot exists — a fit whose very first iteration
        diverges has nothing to roll back to)."""
        if self.rollbacks >= self.policy.max_attempts or self._snap is None:
            raise TrainDiverged(iteration, self.rollbacks, sentinel)
        self.rollbacks += 1
        self.reg_scale *= REG_BUMP_FACTOR
        import jax

        U0, V0 = self._snap
        # key is a pure function of (seed, iteration, attempt): a failing
        # recovery replays exactly, and consecutive rollbacks at one
        # iteration draw different noise
        key = jax.random.PRNGKey(
            (self.cfg.seed * 1_000_003 + iteration * 101 + self.rollbacks)
            & 0x7FFFFFFF)
        ku, kv = jax.random.split(key)
        U = U0 + PERTURB_SCALE * jax.random.normal(ku, U0.shape, U0.dtype)
        V = V0 + PERTURB_SCALE * jax.random.normal(kv, V0.shape, V0.dtype)
        from tpu_als import obs

        obs.counter("train.rollbacks", 1)
        obs.emit("train_rollback", iteration=int(iteration),
                 attempt=self.rollbacks, sentinel=sentinel,
                 reg_param=float(self.cfg.reg_param * self.reg_scale))
        return U, V, self.reg_scale
