"""Preemption-safe training: catch SIGTERM, checkpoint, exit cleanly.

TPU VMs (and any spot/preemptible capacity) get a SIGTERM with a short
grace window before the machine disappears.  The reference stack
survives this because a killed Spark executor's work is recomputed from
RDD lineage (SURVEY.md §5.3); we have no lineage, so the contract is:
finish the in-flight iteration, write an atomic checkpoint, and exit
with :data:`EXIT_PREEMPTED` so the orchestrator knows to reschedule
with ``--resume auto`` rather than report a failure.

The guard only *records* the signal; the trainer's per-iteration
callback polls :func:`pending` at iteration boundaries (factors are
only consistent between iterations — mid-step the donated buffers are
in flux).  Resume is bitwise-exact because the checkpoint carries the
iteration index and factors, and ALS iterations are deterministic given
those.

``TPU_ALS_PREEMPT_AT=N`` makes :func:`pending` fire at iteration N
without any signal — deterministic "preemption" for tests where real
kill timing races a fast CPU run.  A malformed value is a configuration
error, not a silent no-op: it raises the typed :class:`PreemptAtError`
at arm time (``PreemptionGuard.__enter__``) and at every poll, matching
the ``TPU_ALS_FAULT_SPEC`` fail-loud convention.
"""

from __future__ import annotations

import os
import signal
import threading

# distinct from generic failure (1) and the crash-test's os._exit(42)
EXIT_PREEMPTED = 43

ENV_PREEMPT_AT = "TPU_ALS_PREEMPT_AT"


class PreemptAtError(ValueError):
    """``TPU_ALS_PREEMPT_AT`` is set but not a positive integer.

    A deterministic-preemption knob that silently fails to fire is the
    worst kind of chaos tooling — the test passes because nothing was
    injected.  Fail loud instead, the ``TPU_ALS_FAULT_SPEC`` way."""


def preempt_at(environ=None):
    """The validated ``TPU_ALS_PREEMPT_AT`` value: ``None`` when unset
    or empty, the iteration as an int otherwise.  Raises
    :class:`PreemptAtError` on a malformed value."""
    at = (environ if environ is not None else os.environ).get(
        ENV_PREEMPT_AT)
    if not at:
        return None
    try:
        n = int(at)
    except ValueError:
        raise PreemptAtError(
            f"{ENV_PREEMPT_AT}={at!r} is not an integer — the "
            "deterministic preemption knob takes an iteration number "
            "(e.g. TPU_ALS_PREEMPT_AT=3)") from None
    if n < 1:
        raise PreemptAtError(
            f"{ENV_PREEMPT_AT}={at!r} must be >= 1 (iterations are "
            "1-based)")
    return n


class Preempted(SystemExit):
    """Raised (by the trainer callback) after the preemption checkpoint
    is safely on disk.  Subclasses SystemExit with code
    :data:`EXIT_PREEMPTED` so an unhandled escape still exits with the
    right status; ``checkpoint_path`` tells the handler where the
    resumable state landed (None if no checkpoint dir was configured)."""

    def __init__(self, iteration, checkpoint_path=None, signum=None):
        super().__init__(EXIT_PREEMPTED)
        self.iteration = iteration
        self.checkpoint_path = checkpoint_path
        self.signum = signum

    def __str__(self):
        where = self.checkpoint_path or "<no checkpoint dir>"
        return (f"preempted at iteration {self.iteration}; "
                f"state at {where}")


class PreemptionGuard:
    """Context manager that converts SIGTERM/SIGINT into a flag.

    Signal handlers can only be installed from the main thread; on any
    other thread (FoldInServer workers, test runners) the guard degrades
    to the ``TPU_ALS_PREEMPT_AT`` env knob only.  Handlers are restored
    on exit.  A second signal while the flag is already set re-raises
    the default behavior (the user pressing Ctrl-C twice really wants
    out *now*).
    """

    _active = None  # the currently installed guard, for pending()

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._signum = None
        self._saved = {}
        self._installed = False

    # -- signal plumbing -------------------------------------------------
    def _handler(self, signum, frame):
        if self._flag.is_set():
            # second signal: restore defaults and let it kill us
            self._restore()
            signal.raise_signal(signum)
            return
        self._signum = signum
        self._flag.set()

    def _restore(self):
        for s, old in self._saved.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        self._saved.clear()
        self._installed = False

    def __enter__(self):
        preempt_at()   # arm-time validation: fail loud, not silent
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._saved[s] = signal.signal(s, self._handler)
            self._installed = True
        PreemptionGuard._active = self
        return self

    def __exit__(self, *exc):
        if self._installed:
            self._restore()
        if PreemptionGuard._active is self:
            PreemptionGuard._active = None
        return False

    # -- queries ---------------------------------------------------------
    @property
    def signum(self):
        return self._signum

    def triggered(self):
        """True once a signal has been observed."""
        return self._flag.is_set()

    def trigger(self, signum=signal.SIGTERM):
        """Programmatic preemption (tests, simulated orchestrators)."""
        self._signum = signum
        self._flag.set()


def installed():
    """The active :class:`PreemptionGuard`, or None."""
    return PreemptionGuard._active


def enabled():
    """True when preemption handling is in play at all — a guard is
    installed or the deterministic test knob is set.  Trainers use this
    to decide whether their loop needs a preemption-aware callback."""
    return (PreemptionGuard._active is not None
            or preempt_at() is not None)


def pending(iteration=None):
    """Should training stop at this iteration boundary?

    True when the active guard has observed a signal, or when
    ``TPU_ALS_PREEMPT_AT`` equals ``iteration`` (the deterministic test
    knob).  Cheap enough to poll every iteration.
    """
    g = PreemptionGuard._active
    if g is not None and g.triggered():
        return True
    if iteration is not None:
        at = preempt_at()
        if at is not None and at == iteration:
            if g is not None:
                g.trigger()
            return True
    return False
