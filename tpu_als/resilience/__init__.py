"""Resilience subsystem: fault injection, retries, preemption safety.

Three jax-free modules (importable before jax, usable from bench.py's
pre-probe phase):

- :mod:`tpu_als.resilience.faults` — deterministic fault-injection
  harness behind the ``TPU_ALS_FAULT_SPEC`` env var; the named fault
  points every chaos test drives.
- :mod:`tpu_als.resilience.retry` — the one retry/backoff policy
  implementation (jittered exponential, per-attempt timeout, budget)
  used by multihost init, checkpoint I/O, stream ingest and bench.py.
- :mod:`tpu_als.resilience.preempt` — SIGTERM/SIGINT → graceful
  checkpoint-and-exit (:data:`EXIT_PREEMPTED`) for spot/preemptible
  capacity.
- :mod:`tpu_als.resilience.elastic` — device loss as a rescheduling
  event: a failed collective/ring step is health-probed (bounded retry
  backoff) into "transient, retry in place" vs the typed
  :class:`DeviceLost`, which the elastic fit loop turns into ring
  re-formation on the surviving mesh from the last atomic checkpoint.
  (Module-level jax-free; jax loads lazily inside the probe.)

Degraded-mode serving lives in :mod:`tpu_als.parallel.serve` (it needs
jax) but its typed error is re-exported here for one-stop handling.

See docs/resilience.md for the operator-facing story.
"""

from tpu_als.resilience.faults import (
    ENV_VAR as FAULT_SPEC_ENV,
    FAULT_POINTS,
    FaultSpecError,
    InjectedFault,
)
from tpu_als.resilience import faults
from tpu_als.resilience.elastic import (
    DeviceLost,
    ProbeFailed,
)
from tpu_als.resilience import elastic
from tpu_als.resilience.preempt import (
    EXIT_PREEMPTED,
    PreemptAtError,
    Preempted,
    PreemptionGuard,
)
from tpu_als.resilience import preempt
from tpu_als.resilience.retry import (
    AttemptTimeout,
    RetryExhausted,
    RetryPolicy,
    retry_call,
)

__all__ = [
    "AttemptTimeout",
    "DeviceLost",
    "EXIT_PREEMPTED",
    "FAULT_POINTS",
    "FAULT_SPEC_ENV",
    "FaultSpecError",
    "InjectedFault",
    "PreemptAtError",
    "Preempted",
    "PreemptionGuard",
    "ProbeFailed",
    "RetryExhausted",
    "RetryPolicy",
    "elastic",
    "faults",
    "preempt",
    "retry_call",
]
