"""Elastic mesh training: device loss becomes a rescheduling event.

The ring substrate addresses peers by logical device id and the
resilience stack already does preempt → atomic checkpoint → resume
(ROADMAP item 4); this module composes them.  A failed collective or
ring step is *classified* instead of aborting the run:

1. **Detect** — :func:`wrap_step` (installed by
   ``parallel.trainer.train_sharded`` when elastic training is on)
   catches the step failure on the host side, outside the traced graph,
   so the production step's jaxpr is byte-identical with the detector
   on or off (the ``elastic_disarmed`` contract in
   ``analysis/contracts.py``).
2. **Classify** — :func:`classify` health-probes every mesh device with
   a bounded :mod:`tpu_als.resilience.retry` backoff.  A peer that
   fails every probe attempt is DEAD (`RetryExhausted`); a step failure
   with every peer probing healthy is a transient ICI hiccup, retried
   in place up to ``max_transient`` times.
3. **Reschedule** — a dead peer surfaces as the typed
   :class:`DeviceLost`, which ``api.fitting.fit_sharded`` converts into
   a mesh reformation: quarantine the epoch, rebuild the mesh from the
   surviving logical device ids, re-derive the shard plan through the
   planner (the plan key carries the device count), reshard the factor
   tables from the last atomic checkpoint, and re-enter the (shrunk)
   ring at an iteration boundary — the PreemptionGuard discipline, so
   recovery is bitwise-reproducible from the checkpoint.

Deterministic injection: the ``mesh.device_lost`` fault point
(``TPU_ALS_FAULT_SPEC``).  ``corrupt`` mode kills a device — the
victim (``TPU_ALS_LOST_DEVICE``, default the highest logical id) is
marked lost in this module's registry, so the health probe confirms a
dead peer without real hardware dying; ``raise`` mode injects a step
failure with every peer healthy, exercising the transient-retry path.
The registry also lets CPU tests simulate loss directly
(:func:`mark_lost` / :func:`clear_lost`).

Module-level imports are stdlib + sibling resilience modules only; jax
loads lazily inside the probe so ``scenario list`` and the jax-free
tooling stay instant.
"""

from __future__ import annotations

import os
import sys
import threading

from tpu_als.resilience import faults
from tpu_als.resilience.retry import (
    RetryExhausted,
    RetryPolicy,
    retry_call,
)

#: logical device index (into the mesh's flat device order) that
#: ``mesh.device_lost`` corrupt mode kills; default: the last device.
ENV_LOST_DEVICE = "TPU_ALS_LOST_DEVICE"

FAULT_POINT = "mesh.device_lost"


class DeviceLost(RuntimeError):
    """A mesh peer is dead: the health probe exhausted its retry budget
    on the named logical device ids.  The elastic fit loop catches this
    and re-forms the ring on the survivors; without elastic training it
    propagates — device loss stays a hard failure unless opted into."""

    def __init__(self, lost, surviving=None, iteration=None):
        self.lost = tuple(int(d) for d in lost)
        self.surviving = surviving
        self.iteration = iteration
        super().__init__(
            f"device(s) {list(self.lost)} unreachable after probe "
            f"retries exhausted; {surviving} device(s) surviving")


class ProbeFailed(OSError):
    """One health-probe attempt against one device failed.  Subclasses
    ``OSError`` so the retry policy classifies it as transient — only
    a FULL budget of failed probes (``RetryExhausted``) marks the
    device dead."""


# -- simulated-loss registry -------------------------------------------------
# CPU tests (and the corrupt-mode fault point) mark devices lost here;
# the health probe consults it before touching real hardware, so the
# whole detect → classify → reform protocol is exercisable on an
# 8-device CPU mesh.

_lost = set()
_lock = threading.Lock()


def mark_lost(*device_ids):
    """Mark logical device ids as dead for the health probe."""
    with _lock:
        _lost.update(int(d) for d in device_ids)


def lost_devices():
    """Frozen snapshot of the simulated-lost logical device ids."""
    with _lock:
        return frozenset(_lost)


def clear_lost():
    """Forget every simulated loss (tests; between scenario phases)."""
    with _lock:
        _lost.clear()


def _victim_index(n_devices, environ=None):
    """Which flat mesh position corrupt mode kills: the validated
    ``TPU_ALS_LOST_DEVICE`` value, default the last position."""
    raw = (environ if environ is not None else os.environ).get(
        ENV_LOST_DEVICE)
    if not raw:
        return n_devices - 1
    try:
        idx = int(raw)
    except ValueError:
        raise ValueError(
            f"{ENV_LOST_DEVICE}={raw!r} is not an integer mesh "
            "position") from None
    if not 0 <= idx < n_devices:
        raise ValueError(
            f"{ENV_LOST_DEVICE}={idx} out of range for a "
            f"{n_devices}-device mesh")
    return idx


# -- health probe ------------------------------------------------------------


def default_probe_policy():
    """The bounded backoff that separates a hiccup from a corpse: a few
    fast attempts per device.  Deterministic-jitter under
    ``TPU_ALS_TRACE`` (RetryPolicy default), so a traced recovery
    replays its probe schedule byte-identically."""
    return RetryPolicy(max_attempts=3, base_delay=0.01, factor=2.0,
                       max_delay=0.25, jitter=0.25,
                       retry_on=(OSError, TimeoutError))


def _probe_device(device):
    """One probe attempt: a trivial round-trip computation pinned to
    ``device``.  Simulated-lost devices fail unconditionally; a real
    device that cannot complete the round-trip raises the retryable
    :class:`ProbeFailed`."""
    if int(device.id) in lost_devices():
        raise ProbeFailed(
            f"device {int(device.id)} is marked lost")
    import jax
    import jax.numpy as jnp

    try:
        x = jax.device_put(jnp.ones((8,), jnp.float32), device)
        ok = bool(jax.block_until_ready(x.sum()) == 8.0)
    except Exception as e:   # noqa: BLE001 — any failure is the signal
        raise ProbeFailed(
            f"device {int(device.id)} probe raised "
            f"{type(e).__name__}: {e}") from e
    if not ok:
        raise ProbeFailed(
            f"device {int(device.id)} returned a wrong probe value")


def classify(devices, policy=None):
    """Probe every device; returns the tuple of DEAD logical device ids
    (empty == the failure was transient).  Each device gets the full
    retry budget with backoff — the "is it a hiccup" question is asked
    ``max_attempts`` times per peer, never once."""
    from tpu_als import obs

    policy = policy or default_probe_policy()
    dead = []
    with obs.span("elastic.probe", devices=len(tuple(devices))):
        for d in devices:
            try:
                retry_call(_probe_device, d, policy=policy,
                           what=f"elastic.probe:d{int(d.id)}")
            except RetryExhausted:
                dead.append(int(d.id))
    return tuple(dead)


def surviving_devices(mesh):
    """The mesh's devices minus the simulated-lost set, in mesh order —
    the device list the re-formed mesh is built from."""
    lost = lost_devices()
    return [d for d in mesh.devices.flat if int(d.id) not in lost]


# -- the detector ------------------------------------------------------------


def _step_failure_types():
    """Exception classes a failed collective/ring step can surface as:
    the injected fault types plus, when jax is loaded, the XLA runtime
    error a REAL dead peer produces."""
    types = [faults.InjectedFault, ProbeFailed, OSError]
    jax_errors = getattr(sys.modules.get("jax"), "errors", None)
    for name in ("JaxRuntimeError", "XlaRuntimeError"):
        cls = getattr(jax_errors, name, None)
        if isinstance(cls, type) and cls not in types:
            types.append(cls)
    return tuple(types)


def wrap_step(step, mesh, policy=None, max_transient=2):
    """Host-level elastic detector around a jitted training step.

    Fires the ``mesh.device_lost`` fault point before each step
    (corrupt = kill the victim device and fail the step; raise = a
    transient failure with every peer healthy), then classifies any
    step failure via the health probe: dead peers raise
    :class:`DeviceLost`; transient failures are retried in place up to
    ``max_transient`` times with the probe policy's backoff.

    Purely host-side — the wrapped step's traced jaxpr is the raw
    step's, byte for byte (the ``elastic_disarmed`` contract).
    """
    from tpu_als import obs

    devices = tuple(mesh.devices.flat)
    policy = policy or default_probe_policy()
    failure_types = _step_failure_types()

    def elastic_step(U, V, *args):
        transient = 0
        while True:
            try:
                mode = faults.check("mesh.device_lost")
                if mode == "corrupt":
                    victim = devices[_victim_index(len(devices))]
                    mark_lost(int(victim.id))
                    raise ProbeFailed(
                        f"collective failed: peer {int(victim.id)} "
                        "unreachable (injected device loss)")
                return step(U, V, *args)
            except failure_types as e:
                with obs.span("elastic.classify"):
                    dead = classify(devices, policy=policy)
                if dead:
                    raise DeviceLost(
                        dead, surviving=len(devices) - len(dead)) from e
                transient += 1
                obs.emit("warning", what="elastic.transient",
                         reason=f"step failure with all peers healthy "
                                f"(attempt {transient}/{max_transient}):"
                                f" {type(e).__name__}: {e}")
                if transient > max_transient:
                    raise
                policy.sleep(policy.delay(transient - 1))

    return elastic_step
