"""Retry policies: jittered exponential backoff with timeouts + budgets.

The reference stack retries for free at the task level (Spark reruns a
failed task up to ``spark.task.maxFailures`` times from RDD lineage,
SURVEY.md §5.3); our JAX port has no task scheduler, so transient
failures — a flaky DCN rendezvous, a blip on the checkpoint filesystem,
a slow NFS read — must be retried at the call site.  This module is the
ONE implementation every site uses (multihost init, checkpoint save/load,
stream chunk reads, bench.py's backend probe), so retry semantics and
observability are identical everywhere.

Deliberately stdlib-only and jax-free: bench.py loads this file
standalone (``importlib`` on the file path) BEFORE anything imports jax,
because its backend probe must run in a subprocess with the parent
process still jax-clean.  Obs events are emitted only when
``tpu_als.obs`` is already in ``sys.modules`` — true for every in-library
call site, false for the standalone bench load (which passes its own
``on_attempt`` hook instead).
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time


class RetryExhausted(RuntimeError):
    """Every attempt failed.  ``last`` is the final exception,
    ``attempts`` how many were made."""

    def __init__(self, what, attempts, last):
        super().__init__(
            f"{what}: all {attempts} attempt(s) failed; last error: "
            f"{type(last).__name__}: {last}")
        self.what = what
        self.attempts = attempts
        self.last = last


class AttemptTimeout(TimeoutError):
    """One attempt exceeded the policy's per-call timeout.  The worker
    thread may still be running (Python cannot kill it); the attempt is
    abandoned and counted as failed."""


class RetryPolicy:
    """Backoff schedule + budgets.

    ``max_attempts``: total tries (1 = no retry).
    ``base_delay`` / ``factor`` / ``max_delay``: attempt k (0-based)
    sleeps ``min(max_delay, base_delay * factor**k)`` before attempt
    k+1, scaled by the jitter draw.  ``factor=1`` gives the constant
    wait bench.py's probe historically used.
    ``jitter``: fraction of the delay drawn uniformly in
    ``[1-jitter, 1+jitter]`` from a dedicated ``random.Random(seed)`` —
    deterministic per policy instance, never global RNG state.
    ``timeout``: per-attempt wall-clock budget; the attempt runs on a
    daemon thread and :class:`AttemptTimeout` counts as a failure (a
    HUNG call — a wedged collective, a dead NFS mount — becomes a
    retryable error instead of wedging the trainer).  ``None`` calls
    inline (zero thread overhead).
    ``retry_on``: exception classes that count as transient.  Anything
    else propagates immediately — a ``CheckpointCorrupt`` or
    ``ValueError`` is a fact about the data, not the weather.
    ``sleep``: injectable for tests.
    ``deterministic``: when True the jitter for attempt k is a pure
    function of ``(seed, k)`` — a fresh ``random.Random`` keyed on both
    — instead of a draw from the policy's stateful stream.  Two policies
    with the same seed then produce byte-identical schedules REGARDLESS
    of how many draws either has already made, so a traced run replays
    its retry timeline exactly.  ``None`` (the default) resolves from
    the ``TPU_ALS_TRACE`` env var at construction: tracing on means
    deterministic schedules.
    """

    def __init__(self, max_attempts=3, base_delay=0.05, factor=2.0,
                 max_delay=5.0, jitter=0.25, timeout=None,
                 retry_on=(OSError, TimeoutError), seed=0,
                 sleep=time.sleep, deterministic=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if base_delay < 0 or max_delay < 0:
            raise ValueError("delays must be >= 0")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.timeout = timeout
        self.retry_on = tuple(retry_on)
        self.seed = seed
        self.sleep = sleep
        if deterministic is None:
            deterministic = bool(os.environ.get("TPU_ALS_TRACE"))
        self.deterministic = bool(deterministic)
        self._rng = random.Random(seed)

    def delay(self, attempt):
        """Backoff before attempt ``attempt + 1`` (0-based), jittered."""
        d = min(self.max_delay, self.base_delay * self.factor ** attempt)
        if self.jitter:
            if self.deterministic:
                # int-mix the (seed, attempt) pair: stable across
                # processes (no hash salt) and a legal Random seed
                u = random.Random(
                    int(self.seed) * 1_000_003 + attempt).random()
            else:
                u = self._rng.random()
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return d


def _call_with_timeout(fn, args, kwargs, seconds, what):
    """Run ``fn`` on a daemon thread, bounding THIS caller's wait — the
    bench.py hang-isolation idiom, shared by every timed retry."""
    box = {}

    def run():
        try:
            box["v"] = fn(*args, **kwargs)
        except BaseException as e:  # re-raised on the caller's thread
            box["e"] = e

    t = threading.Thread(target=run, daemon=True, name=f"retry:{what}")
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise AttemptTimeout(
            f"{what}: attempt exceeded {seconds}s timeout")
    if "e" in box:
        raise box["e"]
    return box["v"]


def _obs():
    """tpu_als.obs, but ONLY if it is already imported (keeps this
    module loadable from jax-free contexts like bench.py)."""
    return sys.modules.get("tpu_als.obs")


def retry_call(fn, *args, policy=None, what=None, on_attempt=None,
               **kwargs):
    """Call ``fn(*args, **kwargs)`` under ``policy``.

    On each FAILED attempt emits a ``retry_attempt`` obs event and calls
    ``on_attempt(info_dict)`` if given (bench.py builds its provenance
    ``bench_retry`` JSONL rows from this hook).  When the budget is
    exhausted emits ``retry_exhausted`` and raises
    :class:`RetryExhausted` from the last error.
    """
    policy = policy or RetryPolicy()
    what = what or getattr(fn, "__name__", "call")
    last = None
    for attempt in range(policy.max_attempts):
        t0 = time.monotonic()
        try:
            if policy.timeout is not None:
                return _call_with_timeout(fn, args, kwargs,
                                          policy.timeout, what)
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            info = {
                "what": what,
                "attempt": attempt + 1,
                "attempts": policy.max_attempts,
                "elapsed_seconds": round(time.monotonic() - t0, 6),
                "reason": f"{type(e).__name__}: {e}",
            }
            obs = _obs()
            if obs is not None:
                try:
                    obs.emit("retry_attempt", **info)
                except Exception:
                    pass  # bookkeeping must never mask the retried call
            if on_attempt is not None:
                on_attempt(dict(info))
            if attempt + 1 < policy.max_attempts:
                policy.sleep(policy.delay(attempt))
    obs = _obs()
    if obs is not None:
        try:
            obs.emit("retry_exhausted", what=what,
                     attempts=policy.max_attempts,
                     reason=f"{type(last).__name__}: {last}")
        except Exception:
            pass
    raise RetryExhausted(what, policy.max_attempts, last) from last
