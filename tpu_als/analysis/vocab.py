"""Registry-driven literal-vocabulary checks (obs schema + fault points).

The single engine behind two front ends:

- ``scripts/check_obs_schema.py`` — the historical CLI, now a thin shim
  over this module (same diagnostics, same exit codes, same summary
  lines, so the smoke scripts and tests/test_obs.py are untouched);
- the linter's ``unregistered-name`` rule (:mod:`tpu_als.analysis.lint`),
  which reports the same diagnostics through the baseline/suppression
  machinery.

What it checks (verbatim from the PR 1/PR 3/PR 9 contracts): every
literal ``.counter( / .gauge( / .histogram( / .emit(`` call site and
read-side accessor must name a declared metric/event of the right kind;
non-literal names are violations for write methods outside
``tpu_als/obs/``; scenario ``Assertion(metric=/event=/num=/den=)``
literals and inline ``{"ts": ..., "type": ...}`` event dicts validate
against the same schema; ``faults.check/armed/hits`` literals and
``fault_spec=`` strings validate against ``FAULT_POINTS`` /
``parse_spec``.  The four ``plan_*`` events are additionally pinned as
a cross-process contract (declared AND emitted by the planner).

Deliberately jax-free: the registries — ``tpu_als/obs/schema.py`` and
``tpu_als/resilience/faults.py``, both stdlib-only — are loaded
STANDALONE by file path (the ``scripts/bench_gate.sh`` idiom), never
through the ``tpu_als`` package root, whose ``__init__`` imports jax.
That standalone loading is itself the fix for the linter's
``jaxfree-import`` finding on the pre-shim check_obs_schema.py, which
imported the package root and crashed with jax absent despite its
documented contract (pinned by a poisoned-jax test in
tests/test_analysis.py).
"""

from __future__ import annotations

import importlib.util
import os
import re
import sys

# tpu_als/analysis/vocab.py -> repo root
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# a counter/gauge/histogram/emit (write) or quantile/count/value (read
# accessor) call with either a literal first argument (named groups
# q/name) or anything else (group expr); longest alternatives first so
# 'histogram_quantile' never half-matches as 'histogram'
CALL_RE = re.compile(
    r"\.(?P<method>histogram_quantile|histogram_count|histogram"
    r"|counter_value|counter|gauge|emit)\(\s*"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<expr>[^)\s][^),]*))")

# accessor method -> the metric kind its name must be declared as; a
# non-literal name is allowed for these (read-only: can't mint a series)
ACCESSOR_KIND = {"histogram_quantile": "histogram",
                 "histogram_count": "histogram",
                 "counter_value": "counter"}

# scenario-spec literals: Assertion(metric=/event=/num=/den=) bind to
# the registry only at evaluation time — validate them where declared.
# "$key"-prefixed values resolve from scenario config, not the schema.
ASSERT_KW_RE = re.compile(
    r"\b(?P<kw>metric|event|num)\s*=\s*"
    r"(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)")
ASSERT_DEN_RE = re.compile(r"\bden\s*=\s*\((?P<body>[^)]*)\)")
_STR_RE = re.compile(r"['\"]([^'\"]+)['\"]")

# fault-point literals: consultation sites (check/armed/hits) must name
# a declared point; scenario fault_spec= strings (possibly implicit-
# concat inside parens) must survive parse_spec whole
FAULT_CALL_RE = re.compile(
    r"\bfaults\.(?P<method>check|armed|hits)\(\s*"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<expr>[^)\s][^),]*))")
FAULT_SPEC_RE = re.compile(
    r"\bfault_spec\s*=\s*(?P<body>\([^)]*\)|['\"][^'\"]*['\"])",
    re.DOTALL)

# same-line suppression, the linter's reasoned form only: this engine
# maps 1:1 onto the linter's `unregistered-name` rule, so a site the
# linter accepts as suppressed must not resurface via the
# check_obs_schema shim (a reason-less `tal: disable` stays flagged —
# the linter reports those as bad-suppression)
SUPPRESS_RE = re.compile(
    r"#\s*tal:\s*disable=(?P<rules>[A-Za-z0-9_,\-]+)\s*--\s*\S")

# causal-trace span literals: every start_trace/record_span call site
# must name a span declared in schema.TRACE_SPANS — same stance as the
# metric vocabulary, so `observe explain` trees never carry a hop name
# the docs table doesn't list.  record_span's first argument is the
# parent context (may span a newline), so skip one comma-delimited arg.
TRACE_START_RE = re.compile(
    r"\btracing\.start_trace\(\s*(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)")
TRACE_RECORD_RE = re.compile(
    r"\btracing\.record_span\(\s*[^,]+,\s*"
    r"(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)")

# inline event dicts: a line carrying both a "ts" key and a literal
# "type" value (the hand-built shape allowed where importing tpu_als is
# off-limits)
INLINE_RE = re.compile(r"['\"]type['\"]\s*:\s*['\"](?P<name>\w+)['\"]")
INLINE_TS_RE = re.compile(r"['\"]ts['\"]\s*:")

DEFAULT_ROOTS = ("tpu_als", "scripts", "bench.py")

# the execution planner's event vocabulary is a cross-process CONTRACT:
# the warm-start tests assert trails like "plan_cache_hit present,
# plan_probe absent" (and autotune_smoke asserts "plan_tuned on cold
# tune, absent on warm"), so a renamed/undeclared literal would
# silently void those assertions.  Pin all five here, over and above
# the generic call-site validation.
PLAN_EVENTS = ("plan_resolved", "plan_probe", "plan_cache_hit",
               "plan_cache_miss", "plan_tuned")

# the tenancy contract pins the LABEL vocabulary the same way: every
# serving.*/live.* series must declare the tenant label (the tenant-
# isolation scenario and serve-bench --tenants read per-tenant tails
# from exactly these names), and serving.publish_seconds must keep its
# historical "mode" dimension alongside tenant — dropping either key
# silently voids the per-tenant SLO assertions without failing a test
TENANT_PREFIXES = ("serving.", "live.")

# the elastic-training recovery trail is a cross-process contract too:
# the device-loss scenario (and any orchestrator watching events.jsonl)
# re-derives the loss -> reform -> resume tree from exactly these
# names, so a rename would green the scenario's zero-count assertions
# instead of failing them.  Pinned declared AND emitted, the PLAN_EVENTS
# discipline.
ELASTIC_EVENTS = ("device_lost", "mesh_reformed", "elastic_resume")
ELASTIC_SPANS = ("elastic.detect", "elastic.reform", "elastic.resume")
ELASTIC_FAULT_POINT = "mesh.device_lost"

SOAK_EVENTS = ("soak_start", "soak_window", "soak_injection",
               "soak_verdict")
SOAK_METRICS = (("soak.windows", "counter"),
                ("soak.injections", "counter"),
                ("soak.recoveries", "counter"),
                ("soak.window_seconds", "histogram"))


def _load_standalone(name, relpath, repo):
    """Load one stdlib-only registry module by file path, bypassing the
    ``tpu_als`` package root (whose ``__init__`` imports jax)."""
    path = os.path.join(repo, *relpath.split("/"))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_REGISTRY_CACHE = {}


def load_registries(repo=REPO):
    """Return ``(schema, faults)`` — the two vocabulary registries,
    loaded standalone (jax-free) and cached per repo root."""
    if repo not in _REGISTRY_CACHE:
        _REGISTRY_CACHE[repo] = (
            _load_standalone("_tal_obs_schema", "tpu_als/obs/schema.py",
                             repo),
            _load_standalone("_tal_faults", "tpu_als/resilience/faults.py",
                             repo),
        )
    return _REGISTRY_CACHE[repo]


def check_plan_vocabulary(repo=REPO):
    """The five plan_* events must be declared in the schema AND emitted
    by tpu_als/plan/planner.py (an emit that moved elsewhere without a
    declaration update fails the generic pass; a declaration whose emit
    vanished fails here)."""
    schema, _ = load_registries(repo)
    errors = []
    for name in PLAN_EVENTS:
        if name not in schema.EVENTS:
            errors.append(
                f"tpu_als/obs/schema.py: planner event {name!r} is not "
                "declared in EVENTS (the tpu_als.plan contract pins all "
                f"of {', '.join(PLAN_EVENTS)})")
    planner_py = os.path.join(repo, "tpu_als", "plan", "planner.py")
    if os.path.exists(planner_py):
        with open(planner_py, encoding="utf-8") as f:
            text = f.read()
        for name in PLAN_EVENTS:
            if f'"{name}"' not in text:
                errors.append(
                    f"tpu_als/plan/planner.py: never emits {name!r} — "
                    "the plan_* event trail is the warm-start test "
                    "contract (docs/planner.md)")
    return errors


def check_elastic_vocabulary(repo=REPO):
    """The elastic recovery-trail contract: the three elastic events
    declared in the schema AND emitted by the fit loop
    (tpu_als/api/fitting.py), the ``mesh.device_lost`` fault point
    declared AND consulted by the detector
    (tpu_als/resilience/elastic.py), the three ``elastic.*`` trace
    spans declared, and the ``train.reformations`` counter declared."""
    schema, faults = load_registries(repo)
    errors = []
    for name in ELASTIC_EVENTS:
        if name not in schema.EVENTS:
            errors.append(
                f"tpu_als/obs/schema.py: elastic event {name!r} is not "
                "declared in EVENTS (the device-loss recovery trail "
                f"pins all of {', '.join(ELASTIC_EVENTS)})")
    fitting_py = os.path.join(repo, "tpu_als", "api", "fitting.py")
    if os.path.exists(fitting_py):
        with open(fitting_py, encoding="utf-8") as f:
            text = f.read()
        for name in ELASTIC_EVENTS:
            if f'"{name}"' not in text:
                errors.append(
                    f"tpu_als/api/fitting.py: never emits {name!r} — "
                    "the recovery trail is the device-loss scenario's "
                    "contract (docs/resilience.md)")
    for name in ELASTIC_SPANS:
        if name not in getattr(schema, "TRACE_SPANS", ()):
            errors.append(
                f"tpu_als/obs/schema.py: trace span {name!r} is not "
                "declared in TRACE_SPANS (the elastic recovery hops)")
    if ELASTIC_FAULT_POINT not in faults.FAULT_POINTS:
        errors.append(
            "tpu_als/resilience/faults.py: fault point "
            f"{ELASTIC_FAULT_POINT!r} is not declared in FAULT_POINTS "
            "— deterministic device-loss injection is the elastic "
            "test surface")
    elastic_py = os.path.join(repo, "tpu_als", "resilience",
                              "elastic.py")
    if not os.path.exists(elastic_py):
        errors.append("tpu_als/resilience/elastic.py: missing (the "
                      "device-loss detector)")
    else:
        with open(elastic_py, encoding="utf-8") as f:
            if f'"{ELASTIC_FAULT_POINT}"' not in f.read():
                errors.append(
                    "tpu_als/resilience/elastic.py: never consults the "
                    f"declared {ELASTIC_FAULT_POINT!r} fault point")
    if schema.METRICS.get("train.reformations", ("",))[0] != "counter":
        errors.append(
            "tpu_als/obs/schema.py: METRICS['train.reformations'] must "
            "be a counter — the mesh-reformation tally "
            "(docs/observability.md)")
    return errors


def check_soak_vocabulary(repo=REPO):
    """The production-week contract: the four soak_* events declared in
    the schema AND emitted by the orchestrator
    (tpu_als/soak/orchestrator.py), the four soak.* metrics declared
    with their kinds, and the standalone judge
    (tpu_als/soak/verdict.py) free of tpu_als imports — the verdict
    must re-derive from events.jsonl on a machine with nothing but
    python installed (docs/soak.md)."""
    schema, _ = load_registries(repo)
    errors = []
    for name in SOAK_EVENTS:
        if name not in schema.EVENTS:
            errors.append(
                f"tpu_als/obs/schema.py: soak event {name!r} is not "
                "declared in EVENTS (the production-week trail pins "
                f"all of {', '.join(SOAK_EVENTS)})")
    orch_py = os.path.join(repo, "tpu_als", "soak", "orchestrator.py")
    if not os.path.exists(orch_py):
        errors.append("tpu_als/soak/orchestrator.py: missing (the "
                      "production-week driver)")
    else:
        with open(orch_py, encoding="utf-8") as f:
            text = f.read()
        for name in SOAK_EVENTS:
            if f'"{name}"' not in text:
                errors.append(
                    f"tpu_als/soak/orchestrator.py: never emits "
                    f"{name!r} — the soak trail is the verdict's only "
                    "input (docs/soak.md)")
    for name, kind in SOAK_METRICS:
        if schema.METRICS.get(name, ("",))[0] != kind:
            errors.append(
                f"tpu_als/obs/schema.py: METRICS[{name!r}] must be a "
                f"{kind} (the production-week soak tally)")
    verdict_py = os.path.join(repo, "tpu_als", "soak", "verdict.py")
    if os.path.exists(verdict_py):
        with open(verdict_py, encoding="utf-8") as f:
            vtext = f.read()
        if "import tpu_als" in vtext or "from tpu_als" in vtext:
            errors.append(
                "tpu_als/soak/verdict.py: imports tpu_als — the "
                "standalone judge must stay stdlib-only so the verdict "
                "re-derives from a copied run dir offline")
    return errors


def check_tenant_vocabulary(repo=REPO):
    """Every serving.*/live.* metric must declare the ``tenant`` label
    (schema.TENANT_LABELED), and ``serving.publish_seconds`` must keep
    its ``mode`` dimension — the multi-tenant obs contract
    (docs/tenancy.md)."""
    schema, _ = load_registries(repo)
    errors = []
    labels = getattr(schema, "LABELS", {})
    tenant_labeled = set(getattr(schema, "TENANT_LABELED", ()))
    for name in sorted(schema.METRICS):
        if name.startswith(TENANT_PREFIXES) \
                and name not in tenant_labeled:
            errors.append(
                f"tpu_als/obs/schema.py: metric {name!r} matches the "
                "tenant-attributed prefixes "
                f"({'/'.join(TENANT_PREFIXES)}) but does not declare "
                "the 'tenant' label key in LABELS — per-tenant SLO "
                "reads would silently return the cross-tenant series "
                "(docs/tenancy.md)")
    if "mode" not in labels.get("serving.publish_seconds", ()):
        errors.append(
            "tpu_als/obs/schema.py: LABELS['serving.publish_seconds'] "
            "must keep the 'mode' key — the publish-mode histogram "
            "(retag/delta/full) is the incremental-publish contract "
            "(docs/serving.md)")
    for name in tenant_labeled:
        if name not in schema.METRICS:
            errors.append(
                f"tpu_als/obs/schema.py: LABELS declares {name!r} but "
                "METRICS does not — a label table entry for an "
                "undeclared metric is dead vocabulary")
    # the flight ring stamps tenant (and trace ids) STRUCTURALLY on
    # every record; a span key colliding with a reserved record field
    # would silently overwrite the attribution
    reserved = set(getattr(schema, "FLIGHT_RESERVED", ())) \
        | {"tenant", "trace_id", "trace_ids"}
    for attr in ("SERVE_SPAN_KEYS", "LIVE_SPAN_KEYS"):
        overlap = sorted(set(getattr(schema, attr, ())) & reserved)
        if overlap:
            errors.append(
                f"tpu_als/obs/schema.py: {attr} overlaps the reserved "
                f"flight-record field names ({', '.join(overlap)}) — a "
                "span named like a structural field would overwrite the "
                "tenant/trace attribution on every record "
                "(docs/observability.md)")
    return errors


def check_trace_vocabulary(repo=REPO):
    """The causal-tracing contract: ``trace_span`` is declared with the
    six linkage fields ``observe explain`` rebuilds trees from, the span
    vocabulary is non-empty, the emitter (``obs/tracing.py``) writes the
    declared event type, and every declared span name is actually
    recorded somewhere under ``tpu_als/`` — dead vocabulary in the docs
    table is as misleading as an undeclared hop."""
    schema, _ = load_registries(repo)
    errors = []
    decl = schema.EVENTS.get("trace_span")
    if decl is None:
        errors.append(
            "tpu_als/obs/schema.py: event type 'trace_span' is not "
            "declared in EVENTS — the causal-tracing trail has no "
            "schema (docs/observability.md)")
    else:
        for k in ("trace_id", "span_id", "parent_id", "name", "status",
                  "seconds"):
            if k not in decl[0]:
                errors.append(
                    "tpu_als/obs/schema.py: EVENTS['trace_span'] is "
                    f"missing the {k!r} field — `observe explain` "
                    "links spans by exactly these keys")
    spans = getattr(schema, "TRACE_SPANS", ())
    if not spans:
        errors.append(
            "tpu_als/obs/schema.py: TRACE_SPANS is empty/missing — the "
            "span-name vocabulary is the explain trees' legend")
    tracing_py = os.path.join(repo, "tpu_als", "obs", "tracing.py")
    if not os.path.exists(tracing_py):
        errors.append("tpu_als/obs/tracing.py: missing (the trace_span "
                      "emitter)")
    else:
        with open(tracing_py, encoding="utf-8") as f:
            if '"trace_span"' not in f.read():
                errors.append(
                    "tpu_als/obs/tracing.py: never emits the declared "
                    "'trace_span' event type")
    used = set()
    for path in py_files([os.path.join(repo, "tpu_als")]):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for name in spans:
            if f'"{name}"' in text:
                used.add(name)
    for name in spans:
        if name not in used:
            errors.append(
                f"tpu_als/obs/schema.py: TRACE_SPANS declares {name!r} "
                "but no call site under tpu_als/ records it — dead "
                "vocabulary (remove it or record the hop)")
    return errors


def py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, _, files in os.walk(p):
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


_TENANT_KW_RE = re.compile(r"\btenant\s*=")


def _call_block(text, start):
    """The balanced ``(...)`` call text opening at/after ``start`` (the
    _assertion_blocks idiom; our call sites carry no parens inside their
    string literals)."""
    open_pos = text.find("(", start)
    if open_pos < 0:
        return ""
    depth = 0
    for i in range(open_pos, min(len(text), open_pos + 4000)):
        ch = text[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return text[open_pos:i + 1]
    return text[open_pos:open_pos + 4000]


def _assertion_blocks(text):
    """Yield (start_pos, block_text) for every ``Assertion(...)`` call,
    matched by paren balance (good enough for our code: no parens inside
    the string literals these blocks carry)."""
    for m in re.finditer(r"\bAssertion\s*\(", text):
        start = m.end() - 1
        depth = 0
        for i in range(start, min(len(text), start + 4000)):
            ch = text[i]
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    yield m.start(), text[start:i + 1]
                    break


def check_file(path, repo=REPO):
    """Return ``(lineno, message)`` pairs for every vocabulary violation
    in one file.  Messages carry their own ``rel:line`` prefix so the
    shim's output stays byte-compatible with the historical script."""
    schema, faults = load_registries(repo)
    errors = []
    with open(path, encoding="utf-8") as f:
        text = f.read()
    rel = os.path.relpath(path, repo)
    # the registry/schema themselves pass names through variables; the
    # analysis engine (this module + the linter) quotes call shapes in
    # docstrings and fixtures, so it gets the same exemption the old
    # check_obs_schema.py script gave itself
    in_obs = "tpu_als/obs/" in path.replace(os.sep, "/") \
        or "tpu_als/analysis/" in path.replace(os.sep, "/") \
        or path.replace(os.sep, "/").endswith("scripts/check_obs_schema.py")

    def line_of(pos):
        return text.count("\n", 0, pos) + 1

    lines = text.splitlines()

    def suppressed(lineno):
        if not 1 <= lineno <= len(lines):
            return False
        m = SUPPRESS_RE.search(lines[lineno - 1])
        return m is not None and "unregistered-name" in {
            r.strip() for r in m.group("rules").split(",")}

    def add(lineno, msg):
        if not suppressed(lineno):
            errors.append((lineno, msg))

    for m in CALL_RE.finditer(text):
        method, name = m.group("method"), m.group("name")
        lineno = line_of(m.start())
        where = f"{rel}:{lineno}"
        if name is None:
            if not in_obs and method not in ACCESSOR_KIND:
                add(lineno,
                    f"{where}: {method}() with a non-literal name "
                    f"({m.group('expr').strip()!r}) — the static check "
                    "cannot validate it; use a literal declared in "
                    "tpu_als.obs.schema")
            continue
        if method == "emit":
            if name not in schema.EVENTS:
                add(lineno,
                    f"{where}: emit of undeclared event type {name!r} "
                    "(declare it in tpu_als.obs.schema.EVENTS)")
        else:
            want_kind = ACCESSOR_KIND.get(method, method)
            decl = schema.METRICS.get(name)
            if decl is None:
                add(lineno,
                    f"{where}: {method} of undeclared metric {name!r} "
                    "(declare it in tpu_als.obs.schema.METRICS)")
            elif decl[0] != want_kind:
                add(lineno,
                    f"{where}: metric {name!r} is declared as a "
                    f"{decl[0]}, used as a {want_kind} ({method})")
            elif (method not in ACCESSOR_KIND and not in_obs
                  and name not in getattr(schema, "TENANT_LABELED", ())
                  and _TENANT_KW_RE.search(_call_block(text, m.start()))):
                add(lineno,
                    f"{where}: {method} of {name!r} passes a tenant= "
                    "label, but the metric does not declare the "
                    "'tenant' key in tpu_als.obs.schema.LABELS — the "
                    "write would raise at runtime (docs/tenancy.md)")

    for pos, block in _assertion_blocks(text):
        lineno = line_of(pos)
        where = f"{rel}:{lineno}"
        for m in ASSERT_KW_RE.finditer(block):
            kw, name = m.group("kw"), m.group("name")
            if name.startswith("$"):     # resolved from scenario config
                continue
            if kw == "event":
                if name not in schema.EVENTS:
                    add(lineno,
                        f"{where}: Assertion(event={name!r}) names an "
                        "undeclared event type (declare it in "
                        "tpu_als.obs.schema.EVENTS)")
            elif name not in schema.METRICS:
                add(lineno,
                    f"{where}: Assertion({kw}={name!r}) names an "
                    "undeclared metric (declare it in "
                    "tpu_als.obs.schema.METRICS)")
        for m in ASSERT_DEN_RE.finditer(block):
            for name in _STR_RE.findall(m.group("body")):
                if not name.startswith("$") \
                        and name not in schema.METRICS:
                    add(lineno,
                        f"{where}: Assertion(den=...) entry {name!r} is "
                        "not a declared metric (declare it in "
                        "tpu_als.obs.schema.METRICS)")

    if not in_obs:
        trace_spans = getattr(schema, "TRACE_SPANS", ())
        for regex in (TRACE_START_RE, TRACE_RECORD_RE):
            for m in regex.finditer(text):
                name = m.group("name")
                if name not in trace_spans:
                    lineno = line_of(m.start())
                    add(lineno,
                        f"{rel}:{lineno}: trace span {name!r} is not "
                        "declared in tpu_als.obs.schema.TRACE_SPANS — "
                        "explain trees must only carry documented hop "
                        "names")

    in_faults = in_obs or path.replace(os.sep, "/").endswith(
        "tpu_als/resilience/faults.py")
    for m in FAULT_CALL_RE.finditer(text) if not in_obs else ():
        method, name = m.group("method"), m.group("name")
        lineno = line_of(m.start())
        where = f"{rel}:{lineno}"
        if name is None:
            if not in_faults:
                add(lineno,
                    f"{where}: faults.{method}() with a non-literal "
                    f"point ({m.group('expr').strip()!r}) — the static "
                    "check cannot validate it; use a literal from "
                    "tpu_als.resilience.faults.FAULT_POINTS")
        elif name not in faults.FAULT_POINTS:
            add(lineno,
                f"{where}: faults.{method} of undeclared fault point "
                f"{name!r} (declare it in "
                "tpu_als.resilience.faults.FAULT_POINTS)")

    for m in FAULT_SPEC_RE.finditer(text) if not in_obs else ():
        lineno = line_of(m.start())
        where = f"{rel}:{lineno}"
        spec = "".join(_STR_RE.findall(m.group("body")))
        if not spec:
            continue                         # non-literal: runtime checks it
        try:
            faults.parse_spec(spec)
        except faults.FaultSpecError as e:
            add(lineno, f"{where}: fault_spec {spec!r} does not parse: "
                        f"{e}")

    for lineno, line in enumerate(text.splitlines(), 1):
        if not INLINE_TS_RE.search(line):
            continue
        for m in INLINE_RE.finditer(line):
            name = m.group("name")
            if name not in schema.EVENTS:
                add(lineno,
                    f"{rel}:{lineno}: inline event dict with undeclared "
                    f"type {name!r} (declare it in "
                    "tpu_als.obs.schema.EVENTS)")
    return errors


def main(argv=None):
    """CLI core shared with scripts/check_obs_schema.py: returns the
    historical exit code and prints the historical summary lines."""
    import argparse

    ap = argparse.ArgumentParser(
        description="statically validate observability call sites "
                    "against tpu_als.obs.schema")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="files/dirs to scan (default: tpu_als/, "
                         "scripts/, bench.py under the repo root)")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(REPO, p) for p in DEFAULT_ROOTS]
    errors = []
    if args.paths is None:          # fixture runs scan only their files
        errors.extend(check_plan_vocabulary())
        errors.extend(check_tenant_vocabulary())
        errors.extend(check_trace_vocabulary())
        errors.extend(check_elastic_vocabulary())
        errors.extend(check_soak_vocabulary())
    nfiles = 0
    for path in py_files(paths):
        nfiles += 1
        errors.extend(
            msg for _, msg in check_file(path))
    if errors:
        print("\n".join(errors), file=sys.stderr)
        print(f"check_obs_schema: {len(errors)} violation(s) in "
              f"{nfiles} files", file=sys.stderr)
        return 1
    print(f"check_obs_schema: OK ({nfiles} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
