"""Static analysis: tracer-safety linter + jaxpr-contract registry.

Three modules, layered by what they may import:

- :mod:`tpu_als.analysis.lint` — the AST linter (``tpu_als lint``).
  Deliberately jax-free, stdlib-only, and runnable standalone
  (``python tpu_als/analysis/lint.py``) so CI can lint before the
  accelerator stack even resolves.
- :mod:`tpu_als.analysis.vocab` — the obs/fault literal-vocabulary
  engine (the one registry-driven implementation behind both the
  linter's ``unregistered-name`` rule and the
  ``scripts/check_obs_schema.py`` shim).  Also jax-free: it loads
  ``tpu_als/obs/schema.py`` and ``tpu_als/resilience/faults.py`` by
  file path, never through the package root (which imports jax).
- :mod:`tpu_als.analysis.contracts` — the ``Contract(name, build,
  pin)`` manifest unifying the repo's jaxpr byte-identity and
  byte-count pins.  jax is imported lazily inside ``verify()`` so the
  module itself stays importable everywhere.

This ``__init__`` keeps imports lazy for the same reason: importing
``tpu_als.analysis`` must not be the thing that drags jax in.
"""

from __future__ import annotations

_SUBMODULES = ("contracts", "lint", "vocab")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
