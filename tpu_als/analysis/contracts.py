"""Unified jaxpr-contract registry: the repo's byte-level pins, by name.

Several subsystems carry the same load-bearing discipline — a claim about
the TRACED program, pinned byte-for-byte against the jaxpr rather than
against the claimant's own inputs:

- ``ne_audit``            — the einsum NE build materializes exactly one
  ``Vg = V[cols]`` gather; the gather-fused build traces NO HBM gather;
  the fused kernel's embedded CostEstimate equals the roofline's
  ``fused_ne_kernel_bytes`` at the kernel's padded shapes.
- ``fused_solve_audit``   — the whole-iteration fused kernel
  (``gather_solve``: gather → Gram → Cholesky → x) traces NO HBM gather,
  stamps a CostEstimate equal to the roofline's
  ``fused_solve_kernel_bytes``, and that stamp sits strictly below the
  gather-fused NE build plus the A/b HBM handoff it deletes.
- ``guardrails_disarmed`` — arming the divergence sentinels must not
  perturb the production step's traced graph (``str(jax.make_jaxpr)``
  byte-identity, armed vs disarmed).
- ``tracing_disarmed``    — arming causal tracing (``obs.tracing``)
  must not perturb the production step's traced graph either: trace
  context is host-side state on tickets/events, never a jit operand.
- ``plan_cache_off``      — ``TPU_ALS_PLAN_CACHE=off`` vs a warm cache
  dir resolves the byte-identical step jaxpr: the planner supplies probe
  verdicts, never a different program.
- ``comm_audit``          — the collective bytes the sharded step's
  jaxpr actually moves equal ``trainer.comm_bytes_per_iter``'s closed
  form exactly.
- ``live_delta_index``    — an incremental publish (delta segment of
  only the touched/appended rows, and its later compaction) returns
  top-k scores/indices BITWISE equal to a full ``build_index`` rebuild
  of the updated catalog, and compaction's arrays are byte-equal to
  the rebuild's (serving/index.py; not a jaxpr pin but the same
  discipline — an exactness claim re-verified by name).
- ``serve_comm_audit``    — the sharded serving fabric's in-kernel
  cross-shard merge moves exactly the remote-DMA bytes
  ``perf.roofline.serve_merge_remote_bytes`` prices, traces NO XLA
  gather/all_gather collectives and exactly one ``pallas_call``
  (per-shard candidate lists live only in kernel scratch), and its
  merged top-k is BITWISE equal to single-device
  ``chunked_topk_scores`` on an adversarial tie catalog.
- ``floor_audit``         — the committed autotune bank
  (``BENCH_autotune_cpu.json``): the tuned config is never slower than
  the hand-picked defaults, the banked ``model_seconds`` equals the
  ``fused_solve_kernel_bytes`` closed form re-derived at the banked
  config/shape, and the measured-vs-modeled ratio stays inside its
  band — so the roofline gap can never silently reopen in CI.

Before this registry the four pins lived in four test files with no
shared vocabulary; a kernel author adding a fifth had to rediscover the
idiom each time.  Here each pin is a ``Contract(name, build, pin)``:
``build()`` produces the traced artifact (jaxprs, byte counts),
``pin(artifact)`` asserts the invariant and returns a one-line verdict.
``tpu_als lint --contracts`` re-verifies all of them; ``--contract
<name>`` re-verifies one.  The authoritative (parameter-rich) versions
remain the provenance tests named on each contract — this registry is
the cheap, named, CI-gated re-verification at small shapes.

Import layering: this module imports only stdlib at module level; jax
and tpu_als subsystems load lazily inside each ``build``.  Contracts
assume a fresh process (the CLI / smoke-script invocation): process
state they must control (guardrails mode, the plan-cache env var, probe
caches) is saved and restored, but a caller that already armed a
subsystem mid-process may see spurious verdicts.  ``comm_audit`` and
``serve_comm_audit`` need a multi-device backend — start Python with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` on CPU.
"""

from __future__ import annotations

import dataclasses
import os
import time

__all__ = [
    "Contract", "ContractViolation", "Result",
    "get", "names", "verify", "verify_all",
]


class ContractViolation(AssertionError):
    """A pinned jaxpr-level invariant no longer holds."""


@dataclasses.dataclass(frozen=True)
class Result:
    name: str
    ok: bool
    detail: str


@dataclasses.dataclass(frozen=True)
class Contract:
    """One named, re-verifiable jaxpr pin.

    ``build``: () -> artifact (traces the program(s), counts bytes).
    ``pin``: artifact -> str (asserts; the returned string is the
    human verdict).  ``provenance``: the authoritative test that owns
    the full-strength version of this pin.
    """

    name: str
    build: "callable"
    pin: "callable"
    provenance: str

    def verify(self):
        t0 = time.perf_counter()
        try:
            detail = self.pin(self.build())
        except Exception as e:  # noqa: BLE001 — verdicts, not crashes
            return Result(self.name, False,
                          f"{type(e).__name__}: {e} [{self.provenance}]")
        dt = time.perf_counter() - t0
        return Result(self.name, True,
                      f"{detail} [{dt:.1f}s; {self.provenance}]")


def _require(cond, msg):
    if not cond:
        raise ContractViolation(msg)


# -- shared tiny problem (the guardrails/plan pin shapes) -------------------

def _tiny_csr(nU=60, nI=40, nnz=800, seed=0):
    import numpy as np

    from tpu_als.core.ratings import build_csr_buckets

    gen = np.random.default_rng(seed)
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4, chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4, chunk_elems=1 << 12)
    return ucsr, icsr


def _tiny_step_and_factors(cfg):
    import jax

    from tpu_als.core.als import init_factors, make_step

    ucsr, icsr = _tiny_csr()
    nU, nI = ucsr.num_rows, icsr.num_rows
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    ku, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
    U0 = init_factors(ku, nU, cfg.rank)
    V0 = init_factors(kv, nI, cfg.rank)
    return step, U0, V0, ucsr, icsr


# -- ne_audit ---------------------------------------------------------------

def _build_ne_audit():
    import numpy as np

    import jax.numpy as jnp

    from tpu_als.ops.pallas_gather_ne import (
        _tiles,
        gather_normal_eq_explicit,
    )
    from tpu_als.ops.solve import normal_eq_explicit
    from tpu_als.perf.ne_audit import gather_out_bytes, pallas_cost_bytes
    from tpu_als.perf.roofline import fused_ne_kernel_bytes

    n, w, r, N = 48, 40, 24, 300           # the provenance test's shapes
    rng = np.random.default_rng(7)
    V = jnp.asarray(rng.normal(size=(N, r)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
    mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))

    einsum = lambda V, c, v, m: normal_eq_explicit(V[c], v, m, 0.1)
    fused = lambda V, c, v, m: gather_normal_eq_explicit(
        V, c, v, m, 0.1, interpret=True)

    r_pad = max(128, -(-r // 128) * 128)
    tn, wc, w_pad = _tiles(r_pad, -(-w // 8) * 8)
    n_pad = -(-n // tn) * tn
    return {
        "vg_bytes": n * w * r * 4,
        "einsum_gather": gather_out_bytes(einsum, V, cols, vals, mask),
        "fused_gather": gather_out_bytes(fused, V, cols, vals, mask),
        "fused_cost": pallas_cost_bytes(fused, V, cols, vals, mask),
        "model_bytes": fused_ne_kernel_bytes(n_pad * w_pad, n_pad,
                                             r_pad, 4),
    }


def _pin_ne_audit(a):
    total, count = a["einsum_gather"]
    _require(count == 1 and total == a["vg_bytes"],
             f"einsum path traced {count} gather(s) writing {total} B, "
             f"expected exactly one writing {a['vg_bytes']} B (Vg)")
    _require(a["fused_gather"] == (0, 0),
             f"gather-fused path traced an HBM gather: "
             f"{a['fused_gather']} — Vg is being materialized")
    ctotal, ccount = a["fused_cost"]
    _require(ccount == 1 and ctotal == a["model_bytes"],
             f"fused CostEstimate {ctotal} B != fused_ne_kernel_bytes "
             f"{a['model_bytes']} B at padded shapes")
    return (f"einsum gather == Vg ({a['vg_bytes']} B), fused gather-free, "
            f"CostEstimate == model ({a['model_bytes']} B)")


# -- fused_solve_audit ------------------------------------------------------

def _build_fused_solve_audit():
    import numpy as np

    import jax.numpy as jnp

    from tpu_als.ops.pallas_gather_ne import (
        _tiles,
        _tiles_solve,
        gather_fused_solve_explicit,
        gather_normal_eq_explicit,
    )
    from tpu_als.perf.ne_audit import gather_out_bytes, pallas_cost_bytes
    from tpu_als.perf.roofline import fused_solve_kernel_bytes

    n, w, r, N = 48, 40, 24, 300           # the ne_audit contract's shapes
    rng = np.random.default_rng(7)
    V = jnp.asarray(rng.normal(size=(N, r)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
    mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))

    fsolve = lambda V, c, v, m: gather_fused_solve_explicit(
        V, c, v, m, 0.1, interpret=True)
    ne = lambda V, c, v, m: gather_normal_eq_explicit(
        V, c, v, m, 0.1, interpret=True)

    r_pad = max(128, -(-r // 128) * 128)
    w8 = -(-w // 8) * 8
    tn, _, w_pad = _tiles_solve(r_pad, w8)
    n_pad = -(-n // tn) * tn
    tn_ne, _, _ = _tiles(r_pad, w8)
    n_pad_ne = -(-n // tn_ne) * tn_ne
    # what the unfused gather_fused path moves ON TOP of its NE kernel:
    # A [n, r, r] + b [n, r] written to HBM, then read back by the
    # solver (the x write appears in both paths, so it cancels out of
    # the comparison)
    handoff = 2 * n_pad_ne * (r_pad * r_pad + r_pad) * 4
    return {
        "solve_gather": gather_out_bytes(fsolve, V, cols, vals, mask),
        "solve_cost": pallas_cost_bytes(fsolve, V, cols, vals, mask),
        "model_bytes": fused_solve_kernel_bytes(
            n_pad * w_pad, n_pad, r_pad, 4),
        "ne_cost": pallas_cost_bytes(ne, V, cols, vals, mask),
        "handoff": handoff,
    }


def _pin_fused_solve_audit(a):
    _require(a["solve_gather"] == (0, 0),
             f"whole-iteration fused path traced an HBM gather: "
             f"{a['solve_gather']} — Vg is being materialized")
    ctotal, ccount = a["solve_cost"]
    _require(ccount == 1 and ctotal == a["model_bytes"],
             f"fused-solve CostEstimate {ctotal} B != "
             f"fused_solve_kernel_bytes {a['model_bytes']} B at padded "
             f"shapes")
    ntotal, ncount = a["ne_cost"]
    _require(ncount == 1,
             f"NE comparator traced {ncount} pallas_call(s), expected 1")
    unfused = ntotal + a["handoff"]
    _require(ctotal < unfused,
             f"fused-solve bytes {ctotal} B not below the NE-build + "
             f"A/b handoff total {unfused} B — the fusion stopped "
             f"deleting traffic")
    drop = 100.0 * (1.0 - ctotal / unfused)
    return (f"gather-free, CostEstimate == model ({ctotal} B), "
            f"{drop:.0f}% below NE build + A/b handoff ({unfused} B)")


# -- guardrails_disarmed ----------------------------------------------------

def _build_guardrails_disarmed():
    import jax

    from tpu_als.core.als import AlsConfig
    from tpu_als.resilience import guardrails

    step, U0, V0, _, _ = _tiny_step_and_factors(
        AlsConfig(rank=4, max_iter=2))
    disarmed = str(jax.make_jaxpr(step)(U0, V0))
    with guardrails.scoped("recover"):
        armed = str(jax.make_jaxpr(step)(U0, V0))
    return {"disarmed": disarmed, "armed": armed}


def _pin_guardrails_disarmed(a):
    _require(a["disarmed"] == a["armed"],
             "arming guardrails changed the production step's jaxpr "
             f"({len(a['disarmed'])} vs {len(a['armed'])} chars) — the "
             "sentinels leaked into the traced graph")
    return f"armed == disarmed step jaxpr ({len(a['disarmed'])} chars)"


# -- tracing_disarmed -------------------------------------------------------

def _build_tracing_disarmed():
    import jax

    from tpu_als.core.als import AlsConfig
    from tpu_als.obs import tracing

    step, U0, V0, _, _ = _tiny_step_and_factors(
        AlsConfig(rank=4, max_iter=2))
    disarmed = str(jax.make_jaxpr(step)(U0, V0))
    with tracing.traced():
        armed = str(jax.make_jaxpr(step)(U0, V0))
    return {"disarmed": disarmed, "armed": armed}


def _pin_tracing_disarmed(a):
    _require(a["disarmed"] == a["armed"],
             "arming causal tracing changed the production step's jaxpr "
             f"({len(a['disarmed'])} vs {len(a['armed'])} chars) — trace "
             "context leaked into the traced graph (it must stay "
             "host-side: ids on tickets/events, never in jit)")
    return f"armed == disarmed step jaxpr ({len(a['disarmed'])} chars)"


# -- plan_cache_off ---------------------------------------------------------

def _build_plan_cache_off():
    import tempfile

    from tpu_als.core.als import AlsConfig
    from tpu_als.plan.cache import ENV_VAR
    from tpu_als.utils import platform

    import jax

    cfg = AlsConfig(rank=4, max_iter=2)
    saved = os.environ.get(ENV_VAR)
    try:
        with tempfile.TemporaryDirectory() as td:
            os.environ[ENV_VAR] = "off"
            platform.clear_probe_caches()
            step, U0, V0, _, _ = _tiny_step_and_factors(cfg)
            off = str(jax.make_jaxpr(step)(U0, V0))

            os.environ[ENV_VAR] = os.path.join(td, "armed")
            platform.clear_probe_caches()
            step, U0, V0, _, _ = _tiny_step_and_factors(cfg)
            armed = str(jax.make_jaxpr(step)(U0, V0))
    finally:
        if saved is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = saved
        platform.clear_probe_caches()
    return {"off": off, "armed": armed}


def _pin_plan_cache_off(a):
    _require(a["off"] == a["armed"],
             "arming the plan cache changed the step's jaxpr "
             f"({len(a['off'])} vs {len(a['armed'])} chars) — the "
             "planner steered the traced program, not just the probes")
    return f"cache-off == cache-armed step jaxpr ({len(a['off'])} chars)"


# -- comm_audit -------------------------------------------------------------

def _build_comm_audit():
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_als.core.als import AlsConfig
    from tpu_als.parallel.comm import shard_csr_grid
    from tpu_als.parallel.comm_audit import (
        collective_bytes,
        remote_dma_bytes,
    )
    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.mesh import AXIS, make_mesh
    from tpu_als.parallel.trainer import (
        comm_bytes_per_iter,
        make_ring_step,
        make_sharded_step,
        stacked_counts,
    )

    D = len(jax.devices())
    if D < 2:
        raise ContractViolation(
            "comm_audit needs a multi-device backend; start Python with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU")
    rank = 8
    gen = np.random.default_rng(3)
    nU, nI, nnz = 60, 40, 900
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = np.abs(gen.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    mesh = make_mesh(D)
    leading = NamedSharding(mesh, P(AXIS))
    U = jax.device_put(
        jnp.zeros((upart.padded_rows, rank), jnp.float32), leading)
    V = jax.device_put(
        jnp.zeros((ipart.padded_rows, rank), jnp.float32), leading)
    ub = jax.device_put(ush.device_buckets(), leading)
    ib = jax.device_put(ish.device_buckets(), leading)
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    step = make_sharded_step(mesh, ush, ish, cfg)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, axis_size=D)
    model = comm_bytes_per_iter("all_gather", upart, ipart, rank,
                                user_container=ush, item_container=ish,
                                implicit=True)

    # fused-comm ring (solve_backend='gather_fused_ring'): the inter-chip
    # bytes move as in-kernel remote DMAs — invisible to
    # collective_bytes, counted by remote_dma_bytes — and must equal the
    # model's gather_fused_ring closed form (perf.roofline
    # ring_remote_bytes per half-step), with NO ppermute left in the
    # traced step (the rotation migrated into the kernel)
    rank_ring = 128  # real lane width: the payload model is r_pad-exact
    ug = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    ig = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    cfg_ring = AlsConfig(rank=rank_ring, max_iter=1, reg_param=0.1,
                         implicit_prefs=True, alpha=4.0, seed=0,
                         solve_backend="gather_fused_ring")
    ring_step = make_ring_step(mesh, ug, ig, cfg_ring)
    Ur = jax.device_put(
        jnp.zeros((upart.padded_rows, rank_ring), jnp.float32), leading)
    Vr = jax.device_put(
        jnp.zeros((ipart.padded_rows, rank_ring), jnp.float32), leading)
    ubg = jax.device_put(ug.device_buckets(), leading)
    ibg = jax.device_put(ig.device_buckets(), leading)
    uc = jax.device_put(stacked_counts(upart, u, r, positive_only=True),
                        leading)
    ic = jax.device_put(stacked_counts(ipart, i, r, positive_only=True),
                        leading)
    ring_args = (Ur, Vr, ubg, ibg, uc, ic)
    ring_traced, _ = remote_dma_bytes(ring_step, *ring_args)
    _, ring_breakdown = collective_bytes(ring_step, *ring_args,
                                         axis_size=D)
    # the implicit=False form is the pure ring term; the implicit=True
    # delta is the psum(YtY) adder — pinned separately because they are
    # counted by different auditors (remote_dma_bytes vs collective_bytes)
    ring_model = comm_bytes_per_iter(
        "gather_fused_ring", upart, ipart, rank_ring,
        user_container=ug, item_container=ig, implicit=False)
    ring_model_psum = comm_bytes_per_iter(
        "gather_fused_ring", upart, ipart, rank_ring,
        user_container=ug, item_container=ig, implicit=True) - ring_model
    return {"traced": traced, "model": model, "breakdown": breakdown,
            "devices": D, "ring_traced": ring_traced,
            "ring_model": ring_model,
            "ring_psum_traced": ring_breakdown.get("psum", 0),
            "ring_psum_model": ring_model_psum,
            "ring_breakdown": ring_breakdown}


def _pin_comm_audit(a):
    _require(a["breakdown"].get("all_gather")
             and a["breakdown"].get("psum"),
             f"expected all_gather+psum collectives, traced "
             f"{sorted(a['breakdown'])}")
    _require(a["traced"] == a["model"],
             f"traced collective bytes {a['traced']} != "
             f"comm_bytes_per_iter model {a['model']} "
             f"(breakdown {a['breakdown']})")
    _require(a["ring_traced"] == a["ring_model"],
             f"traced in-kernel remote-DMA bytes {a['ring_traced']} != "
             f"comm_bytes_per_iter('gather_fused_ring') ring term "
             f"{a['ring_model']}")
    _require("ppermute" not in a["ring_breakdown"]
             and "all_gather" not in a["ring_breakdown"],
             "fused-comm ring step still traces XLA gather collectives "
             f"({sorted(a['ring_breakdown'])}) — the rotation did not "
             "move in-kernel")
    _require(a["ring_psum_traced"] == a["ring_psum_model"],
             f"fused-ring psum(YtY) bytes {a['ring_psum_traced']} != "
             f"model {a['ring_psum_model']}")
    return (f"traced == modeled collective bytes ({a['traced']} B/device "
            f"across {a['devices']} devices; fused-ring remote-DMA "
            f"{a['ring_traced']} B/device == closed form, no XLA gather "
            "collectives)")


# -- ring_substrate ---------------------------------------------------------

def _build_ring_substrate():
    import re
    from pathlib import Path

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from tpu_als.ops import pallas_gather_ne as pg
    from tpu_als.ops import pallas_topk as pt
    from tpu_als.ops import ring_buffer as rb

    # frozen twins of the PRE-extraction hand-rolled schedules (PR 14's
    # in-kernel loop in pallas_gather_ne; pallas_topk's per-grid-step
    # variant).  These are deliberate verbatim copies: the substrate
    # extraction claimed "byte-identical jaxpr modulo source locations",
    # and this contract is where that claim is load-bearing.
    def _frozen_pump(n_entries, make_copy, depth=None):
        if depth is None:
            depth = min(8, n_entries)  # inlined DMA_SLOTS=8, pre-extraction
        for s in range(depth):
            make_copy(s, s).start()

        def _body(e, carry):
            make_copy(e, e % depth).wait()

            @pl.when(e + depth < n_entries)
            def _next():
                make_copy(e + depth, e % depth).start()

            return carry

        jax.lax.fori_loop(0, n_entries, _body, 0)

    def _frozen_grid_pump(step, n_steps, make_copy, depth=2):
        @pl.when(step == 0)
        def _prime():
            make_copy(0, 0).start()

        make_copy(step, jax.lax.rem(step, depth)).wait()

        @pl.when(step + 1 < n_steps)
        def _next():
            make_copy(step + 1, jax.lax.rem(step + 1, depth)).start()

    def _norm(jaxpr):
        # source locations are the ONE documented difference between the
        # twin (defined here) and the substrate (defined in ring_buffer)
        return re.sub(r" at /[^,\s)]*", "", str(jaxpr))

    rng = np.random.default_rng(0)
    n, w, r = 24, 12, 8
    V = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, n, size=(5, w)).astype(np.int32))
    aw = jnp.ones((5, w), jnp.float32)
    bw = jnp.asarray(rng.normal(size=(5, w)).astype(np.float32))
    cw = jnp.ones((5, w), jnp.float32)
    U = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
    Vt = jnp.asarray(rng.normal(size=(1024, 16)).astype(np.float32))
    valid = jnp.ones(1024, bool)

    # trace the UNJITTED entry points (__wrapped__): pjit caches inner
    # jaxprs across calls, so the monkeypatched twin would be invisible
    # through the jit wrapper
    def traces():
        out = {
            "gather_gram": jax.make_jaxpr(
                lambda: pg.gather_gram.__wrapped__(
                    V, cols, aw, bw, two_sided=True, interpret=True))(),
            "gather_solve": jax.make_jaxpr(
                lambda: pg.gather_solve.__wrapped__(
                    V, cols, aw, bw, cw, two_sided=True, reg=0.1,
                    interpret=True))(),
            "topk": jax.make_jaxpr(
                lambda: pt.topk_scores_pallas.__wrapped__(
                    U, Vt, valid, 10, interpret=True))(),
        }
        return {k: _norm(v) for k, v in out.items()}

    current = traces()
    orig = rb.pump, rb.grid_pump
    rb.pump, rb.grid_pump = _frozen_pump, _frozen_grid_pump
    try:
        frozen = traces()
    finally:
        rb.pump, rb.grid_pump = orig

    # source scan: the substrate owns EVERY async-DMA descriptor.  A
    # private make_async_copy / make_async_remote_copy call site outside
    # ops/ring_buffer.py is a fourth hand-rolled double-buffer waiting to
    # drift.  Call syntax only — prose mentions in docstrings are fine.
    root = Path(pg.__file__).resolve().parents[1]
    call = re.compile(r"make_async(?:_remote)?_copy\s*\(")
    offenders = sorted(
        str(p.relative_to(root))
        for p in root.rglob("*.py")
        if p.name != "ring_buffer.py" and call.search(p.read_text())
    )
    return {"current": current, "frozen": frozen, "offenders": offenders}


def _pin_ring_substrate(a):
    for k, cur in a["current"].items():
        froz = a["frozen"][k]
        _require(cur == froz,
                 f"{k}: substrate-routed jaxpr differs from the frozen "
                 f"pre-extraction twin ({len(cur)} vs {len(froz)} chars "
                 "after source-location normalization) — the extraction "
                 "changed the emitted schedule")
    _require(not a["offenders"],
             "private async-DMA call sites outside ops/ring_buffer.py: "
             f"{a['offenders']}")
    sizes = ", ".join(f"{k} {len(v)}c" for k, v in a["current"].items())
    return (f"substrate pump == frozen hand-rolled twin ({sizes}); no "
            "async-DMA call sites outside ops/ring_buffer.py")


# -- live_delta_index -------------------------------------------------------

def _build_live_delta():
    import numpy as np

    from tpu_als.serving.index import build_index

    rng = np.random.default_rng(17)
    Ni, r, n, k, sk = 220, 8, 13, 5, 48
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    valid = rng.random(Ni) > 0.15
    U = rng.normal(size=(n, r)).astype(np.float32)
    base = build_index(V, item_valid=valid, shortlist_k=sk, seq=1)

    touched = rng.choice(Ni, 9, replace=False)
    Vn = np.concatenate(
        [V, rng.normal(size=(5, r)).astype(np.float32)])
    Vn[touched] = rng.normal(size=(9, r)).astype(np.float32)
    validn = np.concatenate([valid, np.ones(5, bool)])
    rows = np.concatenate([touched, np.arange(Ni, Ni + 5)])
    delta = base.with_updates(rows, Vn[rows], valid_rows=validn[rows],
                              seq=2)
    compacted = delta.compact(seq=3)
    ref = build_index(Vn, item_valid=validn, shortlist_k=sk, seq=2)
    return {"U": U, "k": k, "delta": delta, "compacted": compacted,
            "ref": ref, "touched": len(rows)}


def _pin_live_delta(a):
    import numpy as np

    s_r, ix_r = (np.asarray(x) for x in a["ref"].topk(a["U"], a["k"]))
    for which in ("delta", "compacted"):
        s, ix = (np.asarray(x) for x in a[which].topk(a["U"], a["k"]))
        _require(np.array_equal(s, s_r),
                 f"{which} top-k SCORES differ from the full rebuild "
                 "(the O(touched) incremental publish is not bitwise)")
        _require(np.array_equal(ix, ix_r),
                 f"{which} top-k INDICES differ from the full rebuild")
    for arr in ("V", "Vq", "sv", "valid"):
        _require(np.array_equal(np.asarray(getattr(a["compacted"], arr)),
                                np.asarray(getattr(a["ref"], arr))),
                 f"compacted index array {arr!r} differs bytewise from "
                 "a full rebuild — compaction re-quantized or dropped "
                 "rows")
    return (f"delta({a['touched']} touched rows) and compacted top-k "
            "bitwise == full rebuild; compacted arrays byte-equal")


# -- serve_comm_audit -------------------------------------------------------

def _build_serve_comm_audit():
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpu_als.ops.topk import chunked_topk_scores
    from tpu_als.parallel.comm_audit import (
        collective_bytes,
        remote_dma_bytes,
    )
    from tpu_als.parallel.mesh import make_mesh, replicated, shard_leading
    from tpu_als.parallel.serve import _build
    from tpu_als.perf.roofline import serve_merge_remote_bytes

    D = len(jax.devices())
    if D < 2:
        raise ContractViolation(
            "serve_comm_audit needs a multi-device backend; start Python "
            "with XLA_FLAGS=--xla_force_host_platform_device_count=8 on "
            "CPU")
    # integer-valued factors drawn from a tiny pool: duplicate rows
    # everywhere, so the catalog is ADVERSARIALLY tied and every f32
    # dot product is exact — bitwise equality is meaningful, not lucky
    rng = np.random.default_rng(23)
    n, Ni, r, k = 40, 87 * D, 32, 10
    pool = rng.integers(-3, 4, size=(7, r)).astype(np.float32)
    V = pool[rng.integers(0, 7, Ni)]
    U = rng.integers(-3, 4, size=(n, r)).astype(np.float32)
    valid = rng.random(Ni) < 0.9
    ni_loc = -(-Ni // D)
    dead = min(2, D - 1)
    valid[dead * ni_loc:(dead + 1) * ni_loc] = False  # all-invalid shard
    mesh = make_mesh(D)
    k_eff = min(k, Ni)
    tile_u = min(256, -(-n // 8) * 8)
    tile_i = min(512, -(-ni_loc // 128) * 128)
    f = _build(mesh, ni_loc, k_eff, min(k_eff, ni_loc), "merge_ring",
               8192, tile_u=tile_u, tile_i=tile_i, interpret=True)
    cap = D * ni_loc
    Vp = np.pad(V, ((0, cap - Ni), (0, 0)))
    validp = np.pad(valid, (0, cap - Ni))
    args = (jax.device_put(U, replicated(mesh)),
            jax.device_put(Vp, shard_leading(mesh)),
            jax.device_put(validp, shard_leading(mesh)))
    # the merge ring's schedule: one hop per (user tile, step), S-1
    # steps — the ``fires`` contract pinned in remote_dma_bytes' docs
    traced, _ = remote_dma_bytes(f, *args,
                                 fires=lambda g: g[0] * (D - 1))
    n_ut = -(-n // tile_u)
    model = serve_merge_remote_bytes(n_ut, D, tile_u)
    _, breakdown = collective_bytes(f, *args, axis_size=D)

    # per-shard candidate lists must exist ONLY in kernel scratch: the
    # traced program holds exactly one pallas_call and no HBM-level
    # gather/concat of per-shard top-k outputs feeding a host merge
    def count_pallas(jaxpr, acc=None):
        acc = [] if acc is None else acc
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                acc.append(eqn)
            for p in ("jaxpr", "call_jaxpr"):
                inner = eqn.params.get(p) if eqn.params else None
                if inner is not None:
                    count_pallas(getattr(inner, "jaxpr", inner), acc)
            for br in (eqn.params.get("branches", ())
                       if eqn.params else ()):
                count_pallas(getattr(br, "jaxpr", br), acc)
        return acc

    n_pallas = len(count_pallas(jax.make_jaxpr(f)(*args).jaxpr))
    s, ix = f(*args)
    ref_s, ref_i = chunked_topk_scores(jnp.asarray(U), jnp.asarray(V),
                                       jnp.asarray(valid), k_eff)
    return {"traced": traced, "model": model, "breakdown": breakdown,
            "devices": D, "n_pallas": n_pallas,
            "s": np.asarray(s), "ix": np.asarray(ix),
            "ref_s": np.asarray(ref_s), "ref_i": np.asarray(ref_i),
            "queries": n}


def _pin_serve_comm_audit(a):
    import numpy as np

    _require(a["traced"] == a["model"],
             f"traced in-kernel remote-DMA bytes {a['traced']} != "
             f"perf.roofline serve_merge_remote_bytes {a['model']}")
    _require(not a["breakdown"],
             "the fused serving path still traces XLA collectives "
             f"({sorted(a['breakdown'])}) — the cross-shard merge did "
             "not move in-kernel")
    _require(a["n_pallas"] == 1,
             f"expected exactly one pallas_call (merge in VMEM "
             f"scratch), traced {a['n_pallas']} — per-shard candidate "
             "lists are materializing outside the kernel")
    _require(np.array_equal(a["s"], a["ref_s"]),
             "merged top-k SCORES differ from single-device "
             "chunked_topk_scores on the tie catalog")
    _require(np.array_equal(a["ix"], a["ref_i"]),
             "merged top-k INDICES differ from single-device "
             "chunked_topk_scores — tie ORDER is not reproduced")
    return (f"in-kernel remote-DMA {a['traced']} B == closed form "
            f"across {a['devices']} shards; no XLA collectives; one "
            f"pallas_call; {a['queries']}-query top-k bitwise == "
            "single-device exact on an adversarial tie catalog")


# -- registry ---------------------------------------------------------------

# -- elastic_disarmed -------------------------------------------------------

def _build_elastic_disarmed():
    import os

    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_als.core.als import AlsConfig
    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.mesh import AXIS, make_mesh
    from tpu_als.parallel.trainer import make_sharded_step
    from tpu_als.resilience import elastic, faults

    D = min(2, len(jax.devices()))
    mesh = make_mesh(D)
    gen = np.random.default_rng(0)
    nU, nI, nnz = 24, 16, 200
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r)
    ish = shard_csr(ipart, upart, i, u, r)
    cfg = AlsConfig(rank=4, max_iter=2)
    leading = NamedSharding(mesh, P(AXIS))
    ub = jax.device_put(ush.device_buckets(), leading)
    ib = jax.device_put(ish.device_buckets(), leading)
    U0 = jax.device_put(
        np.zeros((upart.padded_rows, cfg.rank), np.float32), leading)
    V0 = jax.device_put(
        np.zeros((ipart.padded_rows, cfg.rank), np.float32), leading)

    step = make_sharded_step(mesh, ush, ish, cfg)
    disarmed = str(jax.make_jaxpr(step)(U0, V0, ub, ib))
    # arm the detector's fault point (a schedule that never fires, so
    # tracing completes) AND route tracing through the elastic wrapper —
    # exactly what train_sharded(elastic=True) installs
    spec_was = os.environ.get(faults.ENV_VAR)
    os.environ[faults.ENV_VAR] = "mesh.device_lost=raise@nth=999999"
    faults.install_from_env()
    try:
        wrapped = elastic.wrap_step(step, mesh)
        armed = str(jax.make_jaxpr(wrapped)(U0, V0, ub, ib))
    finally:
        if spec_was is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = spec_was
        faults.install_from_env()
    return {"disarmed": disarmed, "armed": armed}


def _pin_elastic_disarmed(a):
    _require(a["disarmed"] == a["armed"],
             "arming the elastic device-loss detector changed the "
             f"production step's jaxpr ({len(a['disarmed'])} vs "
             f"{len(a['armed'])} chars) — the detector must stay a "
             "host-level wrapper, never enter the traced graph")
    return ("elastic-armed wrapped step jaxpr == raw step jaxpr "
            f"({len(a['disarmed'])} chars)")


# -- floor_audit: the banked autotune A/B stays inside its roofline band ----

# the committed autotune bank this contract audits; an override root lets
# the red-path test (and a TPU re-bank rehearsal) point at a doctored copy
FLOOR_AUDIT_ROOT_ENV = "TPU_ALS_FLOOR_AUDIT_ROOT"
FLOOR_AUDIT_BANK = "BENCH_autotune_cpu.json"
# measured/modeled band for DEVICE-sourced banks: the headline sits ~24x
# off the revised roofline floor (ROADMAP), so 32x is the "gap silently
# reopened" tripwire; interpret-sourced banks only pin ratio > 1 (the
# CPU interpreter cannot beat the v5e closed-form floor)
FLOOR_BAND_ENV = "TPU_ALS_FLOOR_BAND"
DEFAULT_FLOOR_BAND = 32.0
# never-slower tolerance: one regress noise band (obs.regress default)
FLOOR_AUDIT_NOISE = 0.10


def _build_floor_audit():
    import json

    from tpu_als.perf import autotune

    root = os.environ.get(FLOOR_AUDIT_ROOT_ENV) or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..")
    path = os.path.join(os.path.abspath(root), FLOOR_AUDIT_BANK)
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    shape = doc["shape"]
    # re-derive the prediction from THE closed form at the banked config
    # and shapes — the bank's own model_seconds field is provenance, the
    # formula is authority (the ne_audit discipline applied to a bank)
    model_s = autotune.model_seconds(doc["config"], shape["rank"],
                                     shape["n"], shape["w"])
    try:
        band = float(os.environ.get(FLOOR_BAND_ENV, "")
                     or DEFAULT_FLOOR_BAND)
    except ValueError:
        band = DEFAULT_FLOOR_BAND
    return {"doc": doc, "model_s": model_s, "band": band,
            "path": os.path.basename(path)}


def _pin_floor_audit(a):
    doc, model_s, band = a["doc"], a["model_s"], a["band"]
    tuned_s = float(doc["tuned_seconds"])
    default_s = float(doc["default_seconds"])
    source = doc.get("source", "interpret")
    _require(tuned_s > 0 and default_s > 0 and model_s > 0,
             f"{a['path']}: non-positive timing "
             f"(tuned {tuned_s}, default {default_s}, model {model_s})")
    _require(tuned_s <= default_s * (1.0 + FLOOR_AUDIT_NOISE),
             f"{a['path']}: the banked tuned config is SLOWER than the "
             f"hand-picked defaults ({tuned_s:.6f}s vs {default_s:.6f}s, "
             f"tolerance {FLOOR_AUDIT_NOISE:.0%}) — the autotuner's "
             "never-slower acceptance rule is broken")
    banked_model = doc.get("model_seconds")
    if banked_model is not None:
        _require(abs(float(banked_model) - model_s)
                 <= 1e-6 * max(float(banked_model), model_s),
                 f"{a['path']}: banked model_seconds "
                 f"{float(banked_model):.3e} != fused_solve_kernel_bytes "
                 f"closed form {model_s:.3e} at the banked config/shape "
                 "— the bank drifted from the roofline model")
    ratio = tuned_s / model_s
    if source == "device":
        _require(0.9 <= ratio <= band,
                 f"{a['path']}: device measured/modeled ratio {ratio:.2f} "
                 f"outside [0.9, {band:g}] — the roofline gap silently "
                 "reopened (or the measurement beat physics); re-tune "
                 "and re-bank")
    else:
        _require(ratio > 1.0,
                 f"{a['path']}: interpret-mode measured/modeled ratio "
                 f"{ratio:.2f} <= 1 — the CPU interpreter cannot beat "
                 "the v5e HBM floor; the bank is doctored or mis-derived")
    speedup = default_s / tuned_s
    _require(abs(float(doc["value"]) - speedup)
             <= 1e-6 * max(float(doc["value"]), speedup),
             f"{a['path']}: banked speedup value {doc['value']} != "
             f"default_seconds/tuned_seconds {speedup:.6f}")
    return (f"banked {source} A/B: tuned {tuned_s:.4f}s <= default "
            f"{default_s:.4f}s (speedup {speedup:.2f}x), "
            f"measured/modeled {ratio:.1f} inside its band")


_REGISTRY = {
    c.name: c for c in (
        Contract("ne_audit", _build_ne_audit, _pin_ne_audit,
                 "tests/test_ne_audit.py, PR 6"),
        Contract("fused_solve_audit", _build_fused_solve_audit,
                 _pin_fused_solve_audit,
                 "tests/test_gather_solve.py, PR 14"),
        Contract("guardrails_disarmed", _build_guardrails_disarmed,
                 _pin_guardrails_disarmed,
                 "tests/test_guardrails.py::"
                 "test_disarmed_step_jaxpr_is_byte_identical, PR 8"),
        Contract("tracing_disarmed", _build_tracing_disarmed,
                 _pin_tracing_disarmed,
                 "tests/test_tracing.py::"
                 "test_tracing_disarmed_step_jaxpr_byte_identical, "
                 "PR 13"),
        Contract("plan_cache_off", _build_plan_cache_off,
                 _pin_plan_cache_off,
                 "tests/test_plan.py::"
                 "test_planner_off_training_step_jaxpr_byte_identical, "
                 "PR 9"),
        Contract("comm_audit", _build_comm_audit, _pin_comm_audit,
                 "tests/test_comm_audit.py, PR 6"),
        Contract("ring_substrate", _build_ring_substrate,
                 _pin_ring_substrate,
                 "tests/test_ring_substrate.py, PR 15"),
        Contract("live_delta_index", _build_live_delta, _pin_live_delta,
                 "tests/test_live.py, PR 11"),
        Contract("serve_comm_audit", _build_serve_comm_audit,
                 _pin_serve_comm_audit,
                 "tests/test_serve_fabric.py, PR 17"),
        Contract("elastic_disarmed", _build_elastic_disarmed,
                 _pin_elastic_disarmed,
                 "tests/test_resilience.py, PR 18"),
        Contract("floor_audit", _build_floor_audit, _pin_floor_audit,
                 "tests/test_autotune.py, PR 20"),
    )
}


def names():
    return tuple(_REGISTRY)


def get(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no contract named {name!r}; registered: "
            f"{', '.join(_REGISTRY)}") from None


def verify(name):
    return get(name).verify()


def verify_all(only=None):
    """Verify every registered contract (or the named subset), in
    registration order.  Unknown names in ``only`` are skipped here —
    the CLI reports them — so the return covers exactly the contracts
    that ran."""
    picked = [c for n, c in _REGISTRY.items()
              if only is None or n in set(only)]
    return [c.verify() for c in picked]
