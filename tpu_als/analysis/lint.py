#!/usr/bin/env python
"""Tracer-safety linter: the JAX/Pallas pitfalls that live in reviewer
memory, mechanized as ~a dozen named AST rules.

Why a bespoke linter: the invariants that keep six Pallas kernels and
the donation-based trainer step correct — no Python branching on traced
values, no host side effects or wall-clock/RNG at trace time, no reads
of donated buffers, no hardcoded precision downcasts, no literal names
bypassing the obs/fault registries, no per-call ``jax.jit`` that dodges
the PR 9 planner — are invisible to generic linters because they are
*tracing* semantics, not Python semantics.  PR 8's Monitor had to learn
the donated-snapshot rule from a real corruption; every future kernel
should inherit these checks for free instead (ROADMAP items 1–2).

Rules (slug = what you put in a suppression)::

    TAL000 parse-error           file does not parse
    TAL001 tracer-branch         if/while/assert on a traced value in traced code
    TAL002 host-side-effect      print/open/file I/O inside traced code
    TAL003 wallclock-rng         time.* / random.* / np.random / datetime in traced code
    TAL004 use-after-donation    read of a donated buffer after the donating call
    TAL005 dtype-drift           hardcoded low-precision downcast without a dtype gate
    TAL006 numpy-on-traced       np.* call on a traced array
    TAL007 unregistered-name     obs/fault literal bypassing the schema registries
    TAL008 bare-jit              jax.jit built per call inside a plain function body
    TAL009 magic-jitter          hardcoded 1e-6 jitter escaping DEFAULT_JITTER threading
    TAL010 jaxfree-import        'Deliberately jax-free' module imports jax / tpu_als
    TAL011 timer-brackets-span   perf_counter window brackets an obs.span enter/exit
    TAL012 bad-suppression       'tal: disable' without a reason / unknown rule

Suppression syntax (reason is MANDATORY — a suppression is a reviewed
decision, not an escape hatch)::

    something_flagged()  # tal: disable=bare-jit -- built once per fit, cached on self

A suppression comment on its own line applies to the next line.  The
checked-in ``lint_baseline.txt`` holds repo-wide accepted findings
(``path :: rule :: message`` per line) and is kept EMPTY by policy:
pre-existing findings get fixed or individually suppressed with a
reason at the site, so every new finding is a hard failure.

Deliberately jax-free and stdlib-only: runnable standalone
(``python tpu_als/analysis/lint.py``) without jax installed, and proven
so by a poisoned-jax subprocess test — the same discipline
tests/test_regress.py applies to the bench gate.  The sibling
``vocab.py`` engine (rule unregistered-name) is loaded by FILE PATH,
never through the ``tpu_als`` package root, whose ``__init__`` imports
jax.  ``--contracts`` is the one jax doorway: it imports
:mod:`tpu_als.analysis.contracts` and re-verifies the jaxpr pins.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import re
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
# tpu_als/analysis/lint.py -> repo root
REPO = os.path.dirname(os.path.dirname(HERE))

RULES = {
    "parse-error": ("TAL000", "file does not parse"),
    "tracer-branch": (
        "TAL001",
        "Python if/while/assert on a traced value inside traced code — "
        "trace-time freeze of one branch; use lax.cond/lax.select/pl.when"),
    "host-side-effect": (
        "TAL002",
        "host side effect inside traced code runs at trace time only "
        "(and never again from the compiled step); use jax.debug.print "
        "or a callback"),
    "wallclock-rng": (
        "TAL003",
        "wall-clock / host RNG inside traced code is baked in at trace "
        "time; fence outside the jit or use jax.random"),
    "use-after-donation": (
        "TAL004",
        "read of a buffer after it was donated to a jitted call — the "
        "backing memory is invalid; snapshot before the call (the PR 8 "
        "Monitor rule)"),
    "dtype-drift": (
        "TAL005",
        "hardcoded low-precision downcast with no dtype gate — restore "
        "the saved input dtype instead (ops/solve.py solve_spd gate)"),
    "numpy-on-traced": (
        "TAL006",
        "np.* call on a traced array forces a host round-trip or a "
        "trace error; use jnp"),
    "unregistered-name": (
        "TAL007",
        "obs metric/event/fault-point literal bypassing the schema "
        "registries"),
    "bare-jit": (
        "TAL008",
        "jax.jit built inside a plain function body recompiles per "
        "call; hoist to module scope, cache it, or route the dispatch "
        "decision through tpu_als.plan"),
    "magic-jitter": (
        "TAL009",
        "hardcoded 1e-6 jitter literal — thread "
        "tpu_als.ops.solve.DEFAULT_JITTER / AlsConfig.jitter instead"),
    "jaxfree-import": (
        "TAL010",
        "module declared 'Deliberately jax-free' imports jax or the "
        "tpu_als package (tpu_als/__init__ imports jax); load "
        "registries standalone by file path"),
    "timer-brackets-span": (
        "TAL011",
        "perf_counter window brackets an obs.span enter/exit, so span "
        "emission (JSONL writes) pollutes the measurement; start the "
        "clock inside the span"),
    "bad-suppression": (
        "TAL012",
        "'tal: disable' comment without a '-- reason' or naming an "
        "unknown rule"),
}

DEFAULT_ROOTS = ("tpu_als", "scripts", "bench.py")
BASELINE_DEFAULT = os.path.join(REPO, "lint_baseline.txt")

# jnp/np helpers whose results are static host values (safe to branch
# on) or dtype objects — calling them does NOT make a value traced
_LAUNDER_CALLS = {
    "issubdtype", "dtype", "result_type", "promote_types", "iinfo",
    "finfo", "shape", "ndim", "isdtype", "can_cast",
    # dtype constructors on static config values
    "float32", "float64", "float16", "bfloat16", "int8", "int16",
    "int32", "int64", "uint8", "uint32", "uint64", "bool_",
}
# attribute reads that launder taint (static metadata of an array)
_LAUNDER_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}
# method calls that pull a host value off a traced array on purpose
_LAUNDER_METHODS = {"item", "tolist"}

# call targets (resolved, dotted) that trace their function arguments
_TRACER_SUFFIXES = ("pallas_call", "shard_map")
_JAX_TRACERS = {
    "jax.jit", "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.lax.scan", "jax.lax.map",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.associative_scan", "jax.custom_vjp",
    "jax.custom_jvp",
}

_HOST_EFFECT_BUILTINS = {"print", "open", "input", "breakpoint"}
_HOST_EFFECT_MODULES = ("os.remove", "os.rename", "os.makedirs",
                        "shutil.", "sys.stdout", "sys.stderr",
                        "logging.")
_WALLCLOCK_MODULES = ("time.", "random.", "datetime.", "secrets.",
                      "uuid.", "numpy.random.")
# debug/callback escape hatches that are legitimate inside traced code
_TRACED_OK_CALLS = ("jax.debug.", "jax.experimental.io_callback",
                    "jax.pure_callback", "jax.experimental.pallas.debug_print")

_JAXFREE_CLAIM_RE = re.compile(
    r"(?i)\bdeliberately\s+(?:stdlib-only\s+and\s+)?jax[-\s]free\b")

_SUPPRESS_RE = re.compile(
    r"#\s*tal:\s*disable=(?P<rules>[A-Za-z0-9_,\-]+)"
    r"(?P<sep>\s*--\s*)?(?P<reason>.*)?$")


class Finding:
    __slots__ = ("path", "line", "rule", "msg")

    def __init__(self, path, line, rule, msg):
        self.path, self.line, self.rule, self.msg = path, line, rule, msg

    @property
    def key(self):
        return f"{self.path} :: {self.rule} :: {self.msg}"

    def render(self):
        tal = RULES[self.rule][0]
        return f"{self.path}:{self.line}: {self.rule} [{tal}]: {self.msg}"


def _dotted(node, aliases):
    """Resolve an Attribute/Name chain to a dotted path with import
    aliases expanded ('jnp.linalg.cholesky' -> 'jax.numpy.linalg.
    cholesky'); None for anything not a plain chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _const_names(node):
    """static_argnames value -> set of names."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


def _const_ints(node):
    """donate_argnums value -> tuple of ints."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _jit_call_info(call, aliases):
    """If ``call`` is ``jax.jit(...)`` or ``functools.partial(jax.jit,
    ...)``, return (inner_fn_node_or_None, donate, static); else None."""
    f = _dotted(call.func, aliases)
    inner = None
    if f == "jax.jit":
        inner = call.args[0] if call.args else None
    elif f in ("functools.partial", "partial") and call.args \
            and _dotted(call.args[0], aliases) == "jax.jit":
        inner = call.args[1] if len(call.args) > 1 else None
    else:
        return None
    donate, static = (), set()
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            donate = _const_ints(kw.value)
        elif kw.arg in ("static_argnames",):
            static = _const_names(kw.value)
        elif kw.arg in ("static_argnums",):
            static = set(_const_ints(kw.value))
    return inner, donate, static


class _ModuleIndex:
    """One parsed module: alias map, function table, traced set,
    donating-callable table."""

    def __init__(self, tree):
        self.tree = tree
        self.aliases = {}
        self.functions = {}          # simple name -> FunctionDef node
        self.parents = {}            # id(node) -> parent node
        self.traced = {}             # id(FunctionDef) -> reason str
        self.donating = {}           # callable name -> donated arg positions
        self.jit_aliases = {}        # name -> (donate, static) partial aliases
        self._index()

    def _index(self):
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        # module-level partial(jax.jit, ...) aliases (the als.py
        # ``_step_jit`` idiom)
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value, self.aliases)
                if info is not None and info[0] is None:
                    self.jit_aliases[node.targets[0].id] = \
                        (info[1], info[2])
        self._mark_traced()

    def _mark(self, fn_node, reason, donate=(), name=None):
        if id(fn_node) not in self.traced:
            self.traced[id(fn_node)] = reason
        if donate and name:
            self.donating[name] = donate

    def _mark_traced(self):
        # 1. decorators
        for fn in self.functions.values():
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    info = _jit_call_info(dec, self.aliases)
                    if info is not None:
                        self._mark(fn, "jit-decorated", info[1], fn.name)
                        continue
                    d = _dotted(dec.func, self.aliases)
                else:
                    d = _dotted(dec, self.aliases)
                if d == "jax.jit":
                    self._mark(fn, "jit-decorated", (), fn.name)
                elif d is not None and d in self.jit_aliases:
                    donate, _ = self.jit_aliases[d]
                    self._mark(fn, "jit-decorated", donate, fn.name)
        # 2. call sites: jax.jit(f, ...) and tracing consumers
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            info = _jit_call_info(node, self.aliases)
            if info is not None:
                inner, donate, _ = info
                if isinstance(inner, ast.Name) \
                        and inner.id in self.functions:
                    target = None
                    parent = self.parents.get(id(node))
                    if isinstance(parent, ast.Assign) \
                            and len(parent.targets) == 1 \
                            and isinstance(parent.targets[0], ast.Name):
                        target = parent.targets[0].id
                    self._mark(self.functions[inner.id], "jit-wrapped",
                               donate, target or inner.id)
                elif isinstance(inner, ast.Lambda):
                    pass          # no statements to lint in a lambda
                continue
            d = _dotted(node.func, self.aliases)
            if d is None:
                continue
            if d in _JAX_TRACERS or d.endswith(_TRACER_SUFFIXES):
                why = "pallas kernel" if d.endswith("pallas_call") \
                    else f"passed to {d.rsplit('.', 1)[-1]}"
                for arg in node.args:
                    if isinstance(arg, ast.Name) \
                            and arg.id in self.functions:
                        self._mark(self.functions[arg.id], why)
        # 3. propagate: nested defs + same-module callees of traced fns
        changed = True
        while changed:
            changed = False
            for fn in self.functions.values():
                if id(fn) not in self.traced:
                    continue
                for node in ast.walk(fn):
                    called = None
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and node is not fn:
                        if id(node) not in self.traced:
                            self.traced[id(node)] = "nested in traced"
                            changed = True
                        continue
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name):
                        called = node.func.id
                    if called and called in self.functions \
                            and id(self.functions[called]) \
                            not in self.traced:
                        self.traced[id(self.functions[called])] = \
                            f"called from traced code"
                        changed = True


class _Taint:
    """Array-taint for one traced function: a name is tainted when it
    was produced by a jax/jnp/lax call (or arithmetic/indexing on a
    tainted value).  Plain parameters are deliberately NOT tainted —
    branching on a static config param is the normal idiom; the bug this
    catches is branching on something the trace just computed."""

    def __init__(self, fn, aliases):
        self.aliases = aliases
        self.names = set()
        assigns = [n for n in ast.walk(fn)
                   if isinstance(n, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign, ast.For))]
        for _ in range(4):                       # tiny fixpoint
            before = len(self.names)
            for node in assigns:
                if isinstance(node, ast.For):
                    if self.tainted(node.iter):
                        self._add_targets(node.target)
                    continue
                value = node.value
                if value is not None and self.tainted(value):
                    tgt = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in tgt:
                        self._add_targets(t)
            if len(self.names) == before:
                break

    def _add_targets(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._add_targets(e)

    def tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _LAUNDER_ATTRS:
                return False
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            d = _dotted(node.func, self.aliases)
            if d is not None:
                leaf = d.rsplit(".", 1)[-1]
                if leaf in _LAUNDER_CALLS or leaf in _LAUNDER_METHODS:
                    return False
                if d.startswith(("jax.numpy.", "jax.lax.", "jax.nn.",
                                 "jax.scipy.", "jax.random.",
                                 "jax.image.")):
                    return True
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr not in _LAUNDER_METHODS \
                    and self.tainted(node.func.value):
                return True                       # method on tainted
            return any(self.tainted(a) for a in node.args)
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.tainted(node.left) \
                or any(self.tainted(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        return False


def _walk_own(fn):
    """Yield every AST node in ``fn``'s body WITHOUT descending into
    nested function definitions (those are linted as their own traced
    functions, so descending would double-report)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class FileLinter:
    def __init__(self, path, repo=REPO, vocab=None):
        self.path = path
        self.rel = os.path.relpath(path, repo).replace(os.sep, "/")
        self.repo = repo
        self.vocab = vocab
        self.findings = []
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()

    def add(self, line, rule, msg):
        self.findings.append(Finding(self.rel, line, rule, msg))

    # -- suppression comments ------------------------------------------
    def _suppressions(self):
        """Map line -> set(rule slugs) from ``# tal: disable=`` comments;
        malformed comments become bad-suppression findings."""
        by_line = {}
        for i, raw in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            reason = (m.group("reason") or "").strip()
            if not m.group("sep") or not reason:
                self.add(i, "bad-suppression",
                         "suppression without a reason — write "
                         "'# tal: disable=<rule> -- <why this is ok>'")
                continue
            unknown = sorted(r for r in rules if r not in RULES)
            if unknown:
                self.add(i, "bad-suppression",
                         f"unknown rule(s) {', '.join(unknown)} in "
                         "suppression (see tpu_als lint --rules)")
                rules -= set(unknown)
            target = i
            if raw.lstrip().startswith("#"):
                # own-line comment: applies to the next code line
                # (skipping blank/comment continuation lines)
                target = i + 1
                while target <= len(self.lines) and (
                        not self.lines[target - 1].strip()
                        or self.lines[target - 1].lstrip()
                        .startswith("#")):
                    target += 1
            by_line.setdefault(target, set()).update(rules)
        return by_line

    # -- the rules -----------------------------------------------------
    def run(self):
        suppressions = self._suppressions()
        try:
            tree = ast.parse(self.text)
        except SyntaxError as e:
            self.add(e.lineno or 1, "parse-error", str(e.msg))
            return self.findings
        idx = _ModuleIndex(tree)

        self._rule_jaxfree_import(tree, idx)
        self._rule_magic_jitter(tree, idx)
        self._rule_bare_jit(tree, idx)
        self._rule_timer_brackets_span(tree, idx)
        self._rule_use_after_donation(tree, idx)
        for fn in idx.functions.values():
            if id(fn) in idx.traced:
                self._traced_rules(fn, idx)
        if self.vocab is not None:
            for lineno, msg in self.vocab.check_file(self.path,
                                                     self.repo):
                prefix = f"{os.path.relpath(self.path, self.repo)}:{lineno}: "
                if msg.startswith(prefix):
                    msg = msg[len(prefix):]
                self.add(lineno, "unregistered-name", msg)

        kept = []
        for f in self.findings:
            if f.rule != "bad-suppression" \
                    and f.rule in suppressions.get(f.line, ()):
                continue
            kept.append(f)
        self.findings = kept
        return self.findings

    def _rule_jaxfree_import(self, tree, idx):
        head = self.text[:4000]
        if not _JAXFREE_CLAIM_RE.search(head):
            return
        for node in tree.body:
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    mods = ["." + (node.module or "")]
                elif node.module:
                    mods = [node.module]
            for mod in mods:
                if mod.split(".")[0] in ("jax", "tpu_als") \
                        or mod.startswith("."):
                    self.add(
                        node.lineno, "jaxfree-import",
                        f"module declares itself jax-free but imports "
                        f"{mod!r} at module level — importing any "
                        "tpu_als submodule executes tpu_als/__init__, "
                        "which imports jax; load the registry "
                        "standalone by file path instead "
                        "(scripts/bench_gate.sh idiom)")

    def _rule_magic_jitter(self, tree, idx):
        def is_magic(node):
            return isinstance(node, ast.Constant) \
                and node.value == 1e-6 and isinstance(node.value, float)

        def mentions_jitter(node):
            return (isinstance(node, ast.Name) and "jitter" in node.id) \
                or (isinstance(node, ast.Attribute)
                    and "jitter" in node.attr)

        msg = ("hardcoded 1e-6 jitter — use tpu_als.ops.solve."
               "DEFAULT_JITTER (or thread AlsConfig.jitter) so the one "
               "regularization knob stays one knob")
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                pos_named = args.posonlyargs + args.args
                for a, d in zip(pos_named[len(pos_named)
                                          - len(args.defaults):],
                                args.defaults):
                    if a.arg == "jitter" and is_magic(d):
                        self.add(d.lineno, "magic-jitter", msg)
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    if d is not None and a.arg == "jitter" \
                            and is_magic(d):
                        self.add(d.lineno, "magic-jitter", msg)
            elif isinstance(node, ast.keyword):
                if node.arg == "jitter" and is_magic(node.value):
                    self.add(node.value.lineno, "magic-jitter", msg)
            elif isinstance(node, ast.AnnAssign):
                # dataclass field: ``jitter: float = 1e-6``
                if isinstance(node.target, ast.Name) \
                        and "jitter" in node.target.id \
                        and node.value is not None \
                        and is_magic(node.value):
                    self.add(node.lineno, "magic-jitter", msg)
            elif isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(is_magic(s) for s in sides) \
                        and any(mentions_jitter(s) for s in sides):
                    self.add(node.lineno, "magic-jitter", msg)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Mult):
                for side, other in ((node.left, node.right),
                                    (node.right, node.left)):
                    if is_magic(side) and isinstance(other, ast.Call):
                        d = _dotted(other.func, idx.aliases) or ""
                        if d.rsplit(".", 1)[-1] == "eye":
                            self.add(node.lineno, "magic-jitter", msg)

    def _rule_bare_jit(self, tree, idx):
        decorator_ids = {id(n) for f in idx.functions.values()
                         for d in f.decorator_list
                         for n in ast.walk(d)}
        for fn in idx.functions.values():
            # build-once factories are the sanctioned idiom: the jit
            # happens once per construction, not per call
            if re.match(r"^_?(make|build|get)(_|$)", fn.name):
                continue
            if any(isinstance(s, ast.Global) for s in _walk_own(fn)):
                continue          # memoized module-global builder
            for node in _walk_own(fn):
                if id(node) in decorator_ids:
                    continue
                if isinstance(node, ast.Call) \
                        and _dotted(node.func, idx.aliases) == "jax.jit":
                    self.add(node.lineno, "bare-jit",
                             "jax.jit inside a function body compiles "
                             "per call — hoist to module scope, cache "
                             "in a module global, or resolve the "
                             "dispatch through tpu_als.plan")

    def _rule_timer_brackets_span(self, tree, idx):
        for fn in idx.functions.values():
            body_blocks = [fn.body]
            for node in ast.walk(fn):
                for field in ("body", "orelse", "finalbody"):
                    blk = getattr(node, field, None)
                    if isinstance(blk, list) and blk and node is not fn:
                        body_blocks.append(blk)
            for block in body_blocks:
                for prev, nxt in zip(block, block[1:]):
                    if not (isinstance(prev, ast.Assign)
                            and isinstance(prev.value, ast.Call)):
                        continue
                    d = _dotted(prev.value.func, idx.aliases) or ""
                    if not d.endswith(("perf_counter", "monotonic",
                                       "time.time")):
                        continue
                    if isinstance(nxt, ast.With) and any(
                            isinstance(item.context_expr, ast.Call)
                            and isinstance(item.context_expr.func,
                                           ast.Attribute)
                            and item.context_expr.func.attr == "span"
                            for item in nxt.items):
                        self.add(
                            prev.lineno, "timer-brackets-span",
                            "stage clock started before the obs.span "
                            "enter (and read after its exit) — the "
                            "span's own event emission lands in the "
                            "measured interval; move the perf_counter "
                            "read inside the span body")

    def _rule_use_after_donation(self, tree, idx):
        if not idx.donating:
            return

        def stores_of(stmt):
            out = set()
            tgts = []
            if isinstance(stmt, ast.Assign):
                tgts = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign,
                                   ast.For)):
                tgts = [stmt.target]
            elif isinstance(stmt, ast.With):
                tgts = [i.optional_vars for i in stmt.items
                        if i.optional_vars is not None]
            for t in tgts:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            return out

        def donated_in(stmt):
            out = []
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id in idx.donating:
                    for pos in idx.donating[node.func.id]:
                        if pos < len(node.args) \
                                and isinstance(node.args[pos], ast.Name):
                            out.append((node.args[pos].id,
                                        node.func.id, node.lineno))
            return out

        def check_loads(node, track):
            for n in ast.walk(node):
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, ast.Load) \
                        and n.id in track:
                    callee, at = track[n.id]
                    self.add(
                        n.lineno, "use-after-donation",
                        f"{n.id!r} was donated to {callee}() at "
                        f"line {at} — its buffer is gone; snapshot "
                        "before the donating call")
                    del track[n.id]              # report once per name

        def scan(block, track):
            for stmt in block:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                compound = isinstance(
                    stmt, (ast.If, ast.For, ast.While, ast.With,
                           ast.Try, ast.AsyncWith, ast.AsyncFor))
                if compound:
                    # header expressions only; the sub-blocks are
                    # scanned statement-by-statement below
                    for h in ([stmt.test] if hasattr(stmt, "test")
                              else [stmt.iter] if hasattr(stmt, "iter")
                              else [i.context_expr
                                    for i in getattr(stmt, "items", [])]):
                        check_loads(h, track)
                    for s in stores_of(stmt):
                        track.pop(s, None)
                    for field in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, field, None)
                        if sub:
                            scan(sub, track)
                    for h in getattr(stmt, "handlers", []) or []:
                        scan(h.body, track)
                    continue
                check_loads(stmt, track)
                stores = stores_of(stmt)
                for name, callee, at in donated_in(stmt):
                    if name not in stores:
                        track[name] = (callee, at)
                for s in stores:
                    track.pop(s, None)

        for fn in idx.functions.values():
            scan(fn.body, {})

    def _traced_rules(self, fn, idx):
        taint = _Taint(fn, idx.aliases)
        kernel = "pallas" in (idx.traced.get(id(fn)) or "")
        for node in _walk_own(fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and taint.tainted(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                fix = "pl.when / jnp.where" if kernel \
                    else "lax.cond / lax.while_loop / jnp.where"
                self.add(node.lineno, "tracer-branch",
                         f"Python `{kind}` on a traced value in traced "
                         f"function {fn.name!r} — the branch freezes at "
                         f"trace time (or raises); use {fix}")
            elif isinstance(node, ast.Assert) \
                    and taint.tainted(node.test):
                self.add(node.lineno, "tracer-branch",
                         f"`assert` on a traced value in traced "
                         f"function {fn.name!r} — raises a tracer "
                         "error; use checkify or assert static "
                         "metadata (shapes/dtypes) instead")
            elif isinstance(node, ast.IfExp) \
                    and taint.tainted(node.test):
                self.add(node.lineno, "tracer-branch",
                         f"conditional expression on a traced value "
                         f"in traced function {fn.name!r}; use "
                         "jnp.where / lax.select")
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func, idx.aliases)
            if d is None:
                continue
            if d.startswith(_TRACED_OK_CALLS):
                continue
            if d in _HOST_EFFECT_BUILTINS \
                    or d.startswith(_HOST_EFFECT_MODULES):
                self.add(node.lineno, "host-side-effect",
                         f"{d}() inside traced function "
                         f"{fn.name!r} runs at trace time only — "
                         "it will not fire per step; use "
                         "jax.debug.print / pl.debug_print / a "
                         "callback")
            elif d.startswith(_WALLCLOCK_MODULES):
                self.add(node.lineno, "wallclock-rng",
                         f"{d}() inside traced function "
                         f"{fn.name!r} is evaluated once at trace "
                         "time and baked into the jaxpr; move it "
                         "outside the traced region (or use "
                         "jax.random for randomness)")
            elif (d.startswith("numpy.")
                  and d.rsplit(".", 1)[-1] not in _LAUNDER_CALLS
                  and any(taint.tainted(a) for a in node.args)):
                self.add(node.lineno, "numpy-on-traced",
                         f"{d}() applied to a traced value in "
                         f"{fn.name!r} — numpy can't consume "
                         "tracers (ConcretizationTypeError) and "
                         "silently constant-folds otherwise; use "
                         "the jnp equivalent")
        self._rule_dtype_drift(fn, idx)

    def _rule_dtype_drift(self, fn, idx):
        consults_dtype = any(
            isinstance(n, ast.Attribute) and n.attr == "dtype"
            for n in _walk_own(fn))
        if consults_dtype:
            return            # gated like solve_spd: downcast is informed
        for node in _walk_own(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                continue
            arg = node.args[0]
            target = _dotted(arg, idx.aliases) or (
                arg.value if isinstance(arg, ast.Constant)
                and isinstance(arg.value, str) else "")
            if str(target).rsplit(".", 1)[-1] in ("bfloat16", "float16"):
                self.add(node.lineno, "dtype-drift",
                         f"unconditional downcast to {str(target).rsplit('.', 1)[-1]} "
                         f"in traced function {fn.name!r} with no "
                         ".dtype consultation — restore the saved "
                         "input dtype instead so f32 callers stay f32 "
                         "(ops/solve.py solve_spd gate is the idiom)")


# -- front end ---------------------------------------------------------

def _load_vocab():
    spec = importlib.util.spec_from_file_location(
        "_tal_vocab", os.path.join(HERE, "vocab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_baseline(path):
    keys = set()
    if path and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    keys.add(line)
    return keys


def lint_paths(paths, repo=REPO, with_vocab=True):
    """Lint files/dirs; returns (findings, nfiles)."""
    vocab = _load_vocab() if with_vocab else None
    findings, nfiles = [], 0
    for path in _py_files(paths):
        nfiles += 1
        findings.extend(FileLinter(path, repo, vocab).run())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, nfiles


def _py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, _, files in os.walk(p):
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpu_als lint",
        description="tracer-safety linter + contract verifier "
                    "(stdlib-only; --contracts needs jax)")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="files/dirs to lint (default: tpu_als/, "
                         "scripts/, bench.py)")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="baseline file of accepted findings "
                         "(default: lint_baseline.txt; 'none' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--contracts", action="store_true",
                    help="also re-verify the jaxpr contract registry "
                         "(imports jax; CPU-safe)")
    ap.add_argument("--contract", action="append", default=None,
                    help="verify only this named contract (repeatable; "
                         "implies --contracts)")
    args = ap.parse_args(argv)

    if args.rules:
        for slug, (tal, help_) in RULES.items():
            print(f"{tal}  {slug:22s} {help_}")
        return 0

    t0 = time.perf_counter()
    default_run = args.paths is None
    paths = args.paths if args.paths \
        else [os.path.join(REPO, p) for p in DEFAULT_ROOTS]
    findings, nfiles = lint_paths(paths)

    if default_run:
        # the plan_* vocabulary is a repo-level contract, not a
        # per-file property — only meaningful over the default roots
        vocab = _load_vocab()
        for msg in vocab.check_plan_vocabulary(REPO):
            path, _, rest = msg.partition(": ")
            findings.append(Finding(path, 1, "unregistered-name", rest))
        # same repo-level footing for the tenancy label contract: every
        # serving.*/live.* metric keeps its tenant dimension
        for msg in vocab.check_tenant_vocabulary(REPO):
            path, _, rest = msg.partition(": ")
            findings.append(Finding(path, 1, "unregistered-name", rest))
        # and the production-week soak trail: soak_* events declared
        # AND emitted, soak.* metric kinds, stdlib-only verdict
        for msg in vocab.check_soak_vocabulary(REPO):
            path, _, rest = msg.partition(": ")
            findings.append(Finding(path, 1, "unregistered-name", rest))

    baseline_path = None if args.baseline == "none" else args.baseline
    if args.write_baseline:
        with open(baseline_path or BASELINE_DEFAULT, "w",
                  encoding="utf-8") as f:
            f.write("# tpu_als lint baseline — accepted findings, one "
                    "'path :: rule :: message' per line.\n"
                    "# Policy: keep this EMPTY.  Fix findings or "
                    "suppress at the site with a reason\n"
                    "# ('# tal: disable=<rule> -- <why>').  See "
                    "docs/analysis.md.\n")
            for fd in findings:
                f.write(fd.key + "\n")
        print(f"tpu_als lint: wrote {len(findings)} finding(s) to "
              f"{baseline_path or BASELINE_DEFAULT}")
        return 0

    baseline = load_baseline(baseline_path)
    new = [f for f in findings if f.key not in baseline]
    matched = {f.key for f in findings if f.key in baseline}
    stale = baseline - matched
    for entry in sorted(stale):
        print(f"tpu_als lint: note: stale baseline entry (fixed? "
              f"remove it): {entry}", file=sys.stderr)

    rc = 0
    if new:
        for f in new:
            print(f.render(), file=sys.stderr)
        print(f"tpu_als lint: {len(new)} finding(s) in {nfiles} files",
              file=sys.stderr)
        rc = 1
    else:
        dt = time.perf_counter() - t0
        print(f"tpu_als lint: OK ({nfiles} files, "
              f"{len(matched)} baselined, {dt:.2f}s)")

    if args.contracts or args.contract:
        rc = max(rc, _run_contracts(args.contract))
    return rc


def _run_contracts(only=None):
    """Verify the jaxpr contract registry (the jax doorway)."""
    sys.path.insert(0, REPO)
    from tpu_als.analysis import contracts

    results = contracts.verify_all(only=only)
    bad = 0
    for r in results:
        status = "OK" if r.ok else "FAIL"
        print(f"contract {r.name}: {status} — {r.detail}")
        if not r.ok:
            bad += 1
    if only is not None:
        known = {r.name for r in results}
        missing = [n for n in only if n not in known]
        for n in missing:
            print(f"contract {n}: UNKNOWN (not registered)",
                  file=sys.stderr)
            bad += 1
    if bad:
        print(f"tpu_als lint --contracts: {bad} contract(s) failed",
              file=sys.stderr)
        return 1
    print(f"tpu_als lint --contracts: OK ({len(results)} verified)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
