"""Factor checkpointing + model persistence.

One format serves both roles the reference stack splits in two (SURVEY.md
§5.4): (a) training-time checkpoints for failure recovery — the analog of
ALS's ``checkpointInterval`` RDD-lineage cut, except ALS is a fixed-point
iteration so recovery is literally restart-from-factors; and (b) model
persistence — the analog of ``ALSModel.save`` (JSON metadata +
userFactors/itemFactors Parquet, SURVEY.md §2.B11), here a JSON manifest +
``.npz`` arrays (factors and original-id maps).

Integrity contract (the resilience layer's half of the story):

- ``save_factors`` records a blake2b digest of every data file in
  ``manifest["files"]`` and installs atomically (tmp → ``.old`` swap),
  so a *complete* generation exists at ``path`` or ``path + '.old'`` at
  every instant.
- ``load_factors`` verifies presence + digest of every manifest-listed
  file.  A torn or bit-rotted generation raises the typed
  :class:`CheckpointCorrupt` (never a raw numpy traceback), is moved
  aside to a ``.corrupt/`` quarantine sibling (preserved for forensics,
  out of the way of the next save), and the ``.old`` generation is
  loaded instead when it validates.
- ``discover_resume`` is the ``--resume auto`` entry point: newest
  *valid* generation under a checkpoint dir, quarantining invalid ones
  it encounters.

Transient I/O errors during save/load are retried under
``tpu_als.resilience.retry`` (CheckpointCorrupt is a fact about bytes,
not the weather, and is never retried).  Fault points
``checkpoint.write`` and ``checkpoint.rename`` let the chaos suite
exercise every branch above deterministically.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

import numpy as np

from tpu_als import obs
from tpu_als.resilience import faults
from tpu_als.resilience.retry import RetryPolicy, retry_call


class CheckpointCorrupt(ValueError):
    """A checkpoint directory failed validation: missing manifest,
    unparseable manifest, missing data file, or digest mismatch.
    ``path`` is the offending generation."""

    def __init__(self, path, reason):
        super().__init__(f"corrupt checkpoint at {path}: {reason}")
        self.path = str(path)
        self.reason = reason


# transient-I/O budget for checkpoint save/load; chaos tests swap in a
# fast policy via the retry_policy= parameters
_DEFAULT_RETRY = dict(max_attempts=3, base_delay=0.05, max_delay=1.0)


def _retry_policy(override):
    return override if override is not None \
        else RetryPolicy(**_DEFAULT_RETRY)


def _tree_bytes(path):
    total = 0
    for root, _, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


def _file_digest(path):
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()

# 1 = replicated layout (user_factors.npz / item_factors.npz);
# 2 = shard-per-process layout (user_shard_*.npz + slots.npz, written by
#     tpu_als.parallel.multihost.save_checkpoint_sharded).
# FORMAT_VERSION is the NEWEST layout this build reads: a build predating
# the sharded layout carries FORMAT_VERSION 1, so a sharded manifest's
# format_version 2 fails there with the designed "newer than this build"
# error instead of a bare FileNotFoundError.
REPLICATED_FORMAT = 1
SHARDED_FORMAT = 2
FORMAT_VERSION = 2


def atomic_install(tmp, path):
    """Install a fully-written ``tmp`` directory at ``path``: rename any
    old save aside, install, delete the old.  A complete save exists at
    ``path`` or ``path + '.old'`` at every instant; :func:`load_factors`
    falls back to ``.old`` if a crash hit the window between the renames.
    THE swap shared by both checkpoint formats — the ``.old`` contract
    must never diverge between them."""
    import shutil

    old = path + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    # fault point: a crash in the swap window leaves only .old on disk
    faults.check("checkpoint.rename")
    os.rename(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def save_factors(path, user_ids, user_factors, item_ids, item_factors,
                 params=None, iteration=None, extra=None,
                 retry_policy=None):
    """Write a checkpoint/model directory (atomic via tmp+rename).

    The whole write body is retried on transient I/O errors; it is
    idempotent across attempts (stale tmp dirs are removed, the install
    swap tolerates a pre-existing ``.old``).
    """
    import shutil

    t0 = time.perf_counter()
    tmp = path + ".tmp"
    nbytes_box = {}

    def _write():
        if os.path.exists(tmp):  # stale leftovers from a failed attempt
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, "user_factors.npz"),
                 ids=np.asarray(user_ids),
                 factors=np.asarray(user_factors))
        np.savez(os.path.join(tmp, "item_factors.npz"),
                 ids=np.asarray(item_ids),
                 factors=np.asarray(item_factors))
        files = {name: _file_digest(os.path.join(tmp, name))
                 for name in ("user_factors.npz", "item_factors.npz")}
        manifest = {
            "format_version": REPLICATED_FORMAT,
            "rank": int(np.asarray(user_factors).shape[1]),
            "num_users": int(np.asarray(user_factors).shape[0]),
            "num_items": int(np.asarray(item_factors).shape[0]),
            "iteration": iteration,
            "params": params or {},
            "extra": extra or {},
            "files": files,
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        # fault point: raise = transient write error (retried);
        # corrupt = torn npz slips past the writer, caught at load by
        # the digest check
        if faults.check("checkpoint.write") == "corrupt":
            target = os.path.join(tmp, "user_factors.npz")
            with open(target, "r+b") as f:
                f.truncate(max(0, os.path.getsize(target) // 2))
        nbytes_box["n"] = _tree_bytes(tmp)  # before the install renames
        atomic_install(tmp, path)

    retry_call(_write, policy=_retry_policy(retry_policy),
               what="checkpoint.save")
    dt = time.perf_counter() - t0
    nbytes = nbytes_box["n"]
    obs.histogram("checkpoint.save_seconds", dt)
    obs.counter("checkpoint.save_bytes", nbytes)
    obs.emit("checkpoint_save", path=str(path), seconds=round(dt, 6),
             bytes=nbytes, iteration=iteration)


def load_factors(path, retry_policy=None):
    """Read a checkpoint/model directory.

    Returns (manifest, user_ids, user_factors, item_ids, item_factors).
    Validates every manifest-listed file digest; a corrupt primary is
    quarantined to ``.corrupt/`` and the ``.old`` generation is loaded
    when it validates, else :class:`CheckpointCorrupt` propagates.
    """
    t0 = time.perf_counter()
    out = retry_call(_load_validated, path,
                     policy=_retry_policy(retry_policy),
                     what="checkpoint.load")
    dt = time.perf_counter() - t0
    nbytes = _tree_bytes(path)
    obs.histogram("checkpoint.load_seconds", dt)
    obs.counter("checkpoint.load_bytes", nbytes)
    obs.emit("checkpoint_load", path=str(path), seconds=round(dt, 6),
             bytes=nbytes)
    return out


def _read_manifest(path):
    mpath = os.path.join(path, "manifest.json")
    if not os.path.exists(mpath):
        raise CheckpointCorrupt(path, "missing manifest.json")
    try:
        with open(mpath) as f:
            return json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointCorrupt(path, f"unreadable manifest.json: {e}")


def validate_dir(path):
    """Manifest + digest check of one generation; returns the manifest
    or raises :class:`CheckpointCorrupt`.  Pre-digest manifests (no
    ``files`` key, e.g. sharded saves) get a presence-only check."""
    manifest = _read_manifest(path)
    files = manifest.get("files")
    if files is None:
        return manifest
    for name, digest in files.items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            raise CheckpointCorrupt(path, f"missing data file {name}")
        actual = _file_digest(fpath)
        if actual != digest:
            raise CheckpointCorrupt(
                path, f"digest mismatch for {name} "
                      f"(manifest {digest}, file {actual})")
    return manifest


def quarantine(path, reason):
    """Move a corrupt generation into a ``.corrupt/`` sibling directory
    (preserved for forensics, out of the next save's way).  Returns the
    quarantine destination, or None if the move itself failed."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    qdir = os.path.join(parent, ".corrupt")
    base = os.path.basename(path.rstrip(os.sep))
    try:
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, f"{base}.{int(time.time())}")
        n = 0
        while os.path.exists(dest):
            n += 1
            dest = os.path.join(qdir, f"{base}.{int(time.time())}.{n}")
        os.rename(path, dest)
    except OSError:
        return None
    obs.emit("checkpoint_quarantined", path=str(path), reason=reason,
             quarantined_to=dest)
    return dest


def _load_validated(path):
    primary, old = path, path + ".old"
    if not os.path.exists(os.path.join(primary, "manifest.json")) and \
            os.path.exists(os.path.join(old, "manifest.json")):
        # crash hit the save_factors swap window: only .old is complete
        return _load_dir(old, validate_dir(old))
    try:
        return _load_dir(primary, validate_dir(primary))
    except CheckpointCorrupt as e:
        # quarantine only dirs that ARE checkpoints with torn contents:
        # the atomic writer never installs a generation without its
        # manifest, so a manifest-less dir is some OTHER artifact (e.g.
        # an estimator save) passed by mistake — moving it aside would
        # destroy it
        if os.path.exists(os.path.join(primary, "manifest.json")):
            quarantine(primary, e.reason)
        if os.path.exists(os.path.join(old, "manifest.json")):
            return _load_dir(old, validate_dir(old))
        raise


def _load_dir(path, manifest):
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format_version']} is newer than "
            f"this build supports ({FORMAT_VERSION})"
        )
    if manifest.get("sharded"):
        # shard-per-process layout (multihost.save_checkpoint_sharded):
        # reassemble slot space from the per-position files, then map to
        # entity space through the saved slot arrays — same return
        # contract as the replicated format
        slots = np.load(os.path.join(path, "slots.npz"),
                        allow_pickle=False)
        rank = int(manifest["rank"])
        D = int(manifest["n_shards"])

        def side(name, rps, slot):
            full = np.zeros((D * rps, rank), dtype=np.float32)
            for pos in range(D):
                f = np.load(os.path.join(
                    path, f"{name}_shard_{pos:05d}.npz"),
                    allow_pickle=False)
                full[pos * rps:(pos + 1) * rps] = f["factors"]
            return full[slot]

        U = side("user", int(manifest["rows_per_shard_user"]),
                 slots["user_slot"])
        V = side("item", int(manifest["rows_per_shard_item"]),
                 slots["item_slot"])
        return manifest, slots["user_ids"], U, slots["item_ids"], V
    try:
        u = np.load(os.path.join(path, "user_factors.npz"),
                    allow_pickle=False)
        i = np.load(os.path.join(path, "item_factors.npz"),
                    allow_pickle=False)
        return manifest, u["ids"], u["factors"], i["ids"], i["factors"]
    except FileNotFoundError as e:
        raise CheckpointCorrupt(path, f"missing data file: {e}")
    except (ValueError, OSError, KeyError) as e:
        # a torn npz surfaces from numpy as ValueError/zipfile errors —
        # translate to the typed contract (pre-digest manifests only;
        # digest validation catches this first otherwise)
        raise CheckpointCorrupt(path, f"unreadable data file: {e}")


def discover_resume(checkpoint_dir):
    """``--resume auto``: newest valid checkpoint generation under
    ``checkpoint_dir``.

    Accepts either a directory that *is* a checkpoint (has
    manifest.json) or a training ``checkpointDir`` containing the
    estimator's ``als_checkpoint`` (+ ``.old``) generations.  Invalid
    generations encountered on the way are quarantined.  Returns the
    path to load, or None when nothing valid exists.
    """
    candidates = []
    if os.path.exists(os.path.join(checkpoint_dir, "manifest.json")):
        candidates.append(checkpoint_dir)
    else:
        for name in ("als_checkpoint", "als_checkpoint.old"):
            p = os.path.join(checkpoint_dir, name)
            if os.path.isdir(p):
                candidates.append(p)
    best, best_iter = None, None
    for p in candidates:
        try:
            manifest = validate_dir(p)
        except CheckpointCorrupt as e:
            if os.path.exists(os.path.join(p, "manifest.json")):
                quarantine(p, e.reason)  # torn checkpoint, not junk
            continue
        it = manifest.get("iteration")
        it = -1 if it is None else int(it)
        if best_iter is None or it > best_iter:
            best, best_iter = p, it
    return best
