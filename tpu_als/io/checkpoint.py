"""Factor checkpointing + model persistence.

One format serves both roles the reference stack splits in two (SURVEY.md
§5.4): (a) training-time checkpoints for failure recovery — the analog of
ALS's ``checkpointInterval`` RDD-lineage cut, except ALS is a fixed-point
iteration so recovery is literally restart-from-factors; and (b) model
persistence — the analog of ``ALSModel.save`` (JSON metadata +
userFactors/itemFactors Parquet, SURVEY.md §2.B11), here a JSON manifest +
``.npz`` arrays (factors and original-id maps).
"""

from __future__ import annotations

import json
import os

import numpy as np

FORMAT_VERSION = 1


def save_factors(path, user_ids, user_factors, item_ids, item_factors,
                 params=None, iteration=None, extra=None):
    """Write a checkpoint/model directory (atomic via tmp+rename)."""
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    np.savez(os.path.join(tmp, "user_factors.npz"),
             ids=np.asarray(user_ids), factors=np.asarray(user_factors))
    np.savez(os.path.join(tmp, "item_factors.npz"),
             ids=np.asarray(item_ids), factors=np.asarray(item_factors))
    manifest = {
        "format_version": FORMAT_VERSION,
        "rank": int(np.asarray(user_factors).shape[1]),
        "num_users": int(np.asarray(user_factors).shape[0]),
        "num_items": int(np.asarray(item_factors).shape[0]),
        "iteration": iteration,
        "params": params or {},
        "extra": extra or {},
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    # swap so a complete checkpoint exists at `path` or `path.old` at every
    # instant: rename old aside, install new, then delete old.  load_factors
    # falls back to `.old` if a crash hit the window between the renames.
    old = path + ".old"
    import shutil

    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def load_factors(path):
    """Read a checkpoint/model directory.

    Returns (manifest, user_ids, user_factors, item_ids, item_factors).
    """
    if not os.path.exists(os.path.join(path, "manifest.json")) and \
            os.path.exists(os.path.join(path + ".old", "manifest.json")):
        path = path + ".old"  # crash hit the save_factors swap window
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format_version']} is newer than "
            f"this build supports ({FORMAT_VERSION})"
        )
    u = np.load(os.path.join(path, "user_factors.npz"), allow_pickle=False)
    i = np.load(os.path.join(path, "item_factors.npz"), allow_pickle=False)
    return manifest, u["ids"], u["factors"], i["ids"], i["factors"]
