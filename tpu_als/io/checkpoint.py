"""Factor checkpointing + model persistence.

One format serves both roles the reference stack splits in two (SURVEY.md
§5.4): (a) training-time checkpoints for failure recovery — the analog of
ALS's ``checkpointInterval`` RDD-lineage cut, except ALS is a fixed-point
iteration so recovery is literally restart-from-factors; and (b) model
persistence — the analog of ``ALSModel.save`` (JSON metadata +
userFactors/itemFactors Parquet, SURVEY.md §2.B11), here a JSON manifest +
``.npz`` arrays (factors and original-id maps).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tpu_als import obs


def _tree_bytes(path):
    total = 0
    for root, _, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total

# 1 = replicated layout (user_factors.npz / item_factors.npz);
# 2 = shard-per-process layout (user_shard_*.npz + slots.npz, written by
#     tpu_als.parallel.multihost.save_checkpoint_sharded).
# FORMAT_VERSION is the NEWEST layout this build reads: a build predating
# the sharded layout carries FORMAT_VERSION 1, so a sharded manifest's
# format_version 2 fails there with the designed "newer than this build"
# error instead of a bare FileNotFoundError.
REPLICATED_FORMAT = 1
SHARDED_FORMAT = 2
FORMAT_VERSION = 2


def atomic_install(tmp, path):
    """Install a fully-written ``tmp`` directory at ``path``: rename any
    old save aside, install, delete the old.  A complete save exists at
    ``path`` or ``path + '.old'`` at every instant; :func:`load_factors`
    falls back to ``.old`` if a crash hit the window between the renames.
    THE swap shared by both checkpoint formats — the ``.old`` contract
    must never diverge between them."""
    import shutil

    old = path + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def save_factors(path, user_ids, user_factors, item_ids, item_factors,
                 params=None, iteration=None, extra=None):
    """Write a checkpoint/model directory (atomic via tmp+rename)."""
    import shutil

    t0 = time.perf_counter()
    tmp = path + ".tmp"
    if os.path.exists(tmp):  # stale leftovers from a crashed attempt
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "user_factors.npz"),
             ids=np.asarray(user_ids), factors=np.asarray(user_factors))
    np.savez(os.path.join(tmp, "item_factors.npz"),
             ids=np.asarray(item_ids), factors=np.asarray(item_factors))
    manifest = {
        "format_version": REPLICATED_FORMAT,
        "rank": int(np.asarray(user_factors).shape[1]),
        "num_users": int(np.asarray(user_factors).shape[0]),
        "num_items": int(np.asarray(item_factors).shape[0]),
        "iteration": iteration,
        "params": params or {},
        "extra": extra or {},
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    nbytes = _tree_bytes(tmp)  # before the install renames tmp away
    atomic_install(tmp, path)
    dt = time.perf_counter() - t0
    obs.histogram("checkpoint.save_seconds", dt)
    obs.counter("checkpoint.save_bytes", nbytes)
    obs.emit("checkpoint_save", path=str(path), seconds=round(dt, 6),
             bytes=nbytes, iteration=iteration)


def load_factors(path):
    """Read a checkpoint/model directory.

    Returns (manifest, user_ids, user_factors, item_ids, item_factors).
    """
    t0 = time.perf_counter()
    out = _load_factors(path)
    dt = time.perf_counter() - t0
    nbytes = _tree_bytes(path)
    obs.histogram("checkpoint.load_seconds", dt)
    obs.counter("checkpoint.load_bytes", nbytes)
    obs.emit("checkpoint_load", path=str(path), seconds=round(dt, 6),
             bytes=nbytes)
    return out


def _load_factors(path):
    if not os.path.exists(os.path.join(path, "manifest.json")) and \
            os.path.exists(os.path.join(path + ".old", "manifest.json")):
        path = path + ".old"  # crash hit the save_factors swap window
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format_version']} is newer than "
            f"this build supports ({FORMAT_VERSION})"
        )
    if manifest.get("sharded"):
        # shard-per-process layout (multihost.save_checkpoint_sharded):
        # reassemble slot space from the per-position files, then map to
        # entity space through the saved slot arrays — same return
        # contract as the replicated format
        slots = np.load(os.path.join(path, "slots.npz"),
                        allow_pickle=False)
        rank = int(manifest["rank"])
        D = int(manifest["n_shards"])

        def side(name, rps, slot):
            full = np.zeros((D * rps, rank), dtype=np.float32)
            for pos in range(D):
                f = np.load(os.path.join(
                    path, f"{name}_shard_{pos:05d}.npz"),
                    allow_pickle=False)
                full[pos * rps:(pos + 1) * rps] = f["factors"]
            return full[slot]

        U = side("user", int(manifest["rows_per_shard_user"]),
                 slots["user_slot"])
        V = side("item", int(manifest["rows_per_shard_item"]),
                 slots["item_slot"])
        return manifest, slots["user_ids"], U, slots["item_ids"], V
    u = np.load(os.path.join(path, "user_factors.npz"), allow_pickle=False)
    i = np.load(os.path.join(path, "item_factors.npz"), allow_pickle=False)
    return manifest, u["ids"], u["factors"], i["ids"], i["factors"]
