// bucketize: multi-threaded degree-bucketed padded-CSR builder.
//
// The native blocking engine of the framework: the TPU-first counterpart of
// the reference stack's rating-blocking machinery (Spark MLlib's
// RatingBlockBuilder / UncompressedInBlockSort / LocalIndexEncoder inside
// ml/recommendation/ALS.scala — SURVEY.md §2.B4), which runs as JVM task
// code over the shuffle.  Here blocking is a host-side preprocessing pass
// that lays COO ratings out as power-of-two-width padded CSR buckets
// (tpu_als/core/ratings.py documents the layout); this library does the two
// O(nnz) passes — per-entity counting and bucket fill — with threads, an
// order of magnitude faster than the numpy argsort path at ML-25M scale,
// and bit-identical to it (same bucket order, same within-row entry order).
//
// Build: g++ -O3 -shared -fPIC -pthread bucketize.cc -o libbucketize.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

void parallel_for(int64_t n, int n_threads,
                  const std::function<void(int64_t, int64_t)>& fn) {
  if (n_threads <= 1 || n < (1 << 16)) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// counts[e] = number of entries with rows[i] == e.  rows must be < num_rows.
void bucketize_count(const int64_t* rows, int64_t nnz, int64_t num_rows,
                     int64_t* counts, int n_threads) {
  std::memset(counts, 0, sizeof(int64_t) * num_rows);
  if (n_threads <= 1 || nnz < (1 << 18)) {
    for (int64_t i = 0; i < nnz; ++i) counts[rows[i]]++;
    return;
  }
  // per-thread partial counts, then reduce (counting over entries)
  std::vector<std::vector<int64_t>> partial(n_threads);
  std::vector<std::thread> ts;
  int64_t per = (nnz + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * per, hi = std::min(nnz, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([&, t, lo, hi] {
      partial[t].assign(num_rows, 0);
      for (int64_t i = lo; i < hi; ++i) partial[t][rows[i]]++;
    });
  }
  for (auto& t : ts) t.join();
  parallel_for(num_rows, n_threads, [&](int64_t lo, int64_t hi) {
    for (const auto& p : partial) {
      if (p.empty()) continue;
      for (int64_t e = lo; e < hi; ++e) counts[e] += p[e];
    }
  });
}

// Fill the bucket arenas.
//
//  rows/cols   [nnz] int64 COO
//  vals        [nnz] float
//  counts      [num_rows] from bucketize_count
//  ebucket     [num_rows] bucket index per entity (-1 = no ratings),
//              precomputed by the caller (tpu_als/io/fastbucket.py) with the
//              same width rule as the numpy path — single source of truth
//  per bucket b (nbuckets of them):
//    widths[b], rows_out[b] int32[nb_pad] (prefilled with num_rows),
//    cols/vals/mask arenas of [nb_pad * w], zero-prefilled by the caller.
//  scratch: elocal int32[num_rows], cursor int32[num_rows] zero-prefilled.
//
// Semantics match tpu_als.core.ratings.build_csr_buckets exactly: bucket
// rows ascend by entity id; entries within a row keep input order.
void bucketize_fill(const int64_t* rows, const int64_t* cols,
                    const float* vals, int64_t nnz, int64_t num_rows,
                    const int64_t* counts,
                    const int32_t* ebucket, int32_t nbuckets,
                    const int64_t* widths, int32_t** rows_out,
                    int32_t** cols_out, float** vals_out, float** mask_out,
                    int32_t* elocal, int32_t* cursor,
                    int n_threads) {
  // pass 1 (sequential over entities, ascending id = numpy bucket order):
  // assign every rated entity its local row and write rows_out
  std::vector<int64_t> fill(nbuckets, 0);
  for (int64_t e = 0; e < num_rows; ++e) {
    int32_t b = ebucket[e];
    if (b < 0) continue;
    elocal[e] = static_cast<int32_t>(fill[b]);
    rows_out[b][fill[b]++] = static_cast<int32_t>(e);
  }
  // pass 2 (parallel by entity range): scatter entries into the arenas;
  // each thread owns a disjoint entity range so cursor needs no atomics,
  // and scanning entries in input order preserves within-row entry order.
  // Ranges are balanced by entry mass (counts prefix), not entity count —
  // power-law degrees would otherwise starve most threads.
  int T = (nnz < (1 << 18)) ? 1 : std::max(1, n_threads);
  std::vector<int64_t> bound(T + 1, num_rows);
  bound[0] = 0;
  int64_t acc = 0, target = nnz / T + 1;
  for (int64_t e = 0, t = 1; e < num_rows && t < T; ++e) {
    acc += counts[e];
    if (acc >= t * target) bound[t++] = e + 1;
  }
  std::vector<std::thread> ts;
  for (int t = 0; t < T; ++t) {
    int64_t lo = bound[t], hi = bound[t + 1];
    if (lo >= hi) continue;
    auto work = [&, lo, hi] {
      for (int64_t i = 0; i < nnz; ++i) {
        int64_t e = rows[i];
        if (e < lo || e >= hi) continue;
        int32_t b = ebucket[e];
        int64_t w = widths[b];
        int64_t dst = static_cast<int64_t>(elocal[e]) * w + cursor[e]++;
        cols_out[b][dst] = static_cast<int32_t>(cols[i]);
        vals_out[b][dst] = vals[i];
        mask_out[b][dst] = 1.0f;
      }
    };
    if (T == 1) {
      work();
    } else {
      ts.emplace_back(work);
    }
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
