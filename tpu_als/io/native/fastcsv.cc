// fastcsv: multi-threaded MovieLens ratings parser.
//
// The native IO component of the framework (SURVEY.md §2.C5): where the
// reference stack leans on the JVM's native substrate (snappy/parquet JNI,
// netty) for data movement, the TPU framework's host-side ingest is this
// small C++ library — it parses `ratings.csv` (userId,movieId,rating,
// timestamp) or `u.data` (tab-separated) straight into preallocated numpy
// buffers, parallelized over byte ranges, ~an order of magnitude faster
// than python csv at ML-25M scale.  Bound via ctypes (no pybind11 in this
// image).
//
// Strictness contract (adversarial-ingest hardening, VERDICT r3 #8): every
// data line must be exactly `int<delim>int<delim>float<delim>int` with an
// optional trailing `\r` / spaces; empty lines (and `\r`-only lines) are
// skipped.  Anything else — quoted fields, missing fields, trailing junk,
// extra columns — makes fastcsv_parse return -2 so the Python wrapper can
// raise a clean error instead of a zero-filled row entering training.
// CRLF endings, a missing final newline, scientific-notation floats, and
// full-int64 ids are all accepted (the ids notably exceed the float64
// mantissa the numpy fallback rides through).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread fastcsv.cc -o libfastcsv.so

#include <atomic>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Span {
  const char* begin;
  const char* end;
  int64_t out_offset;  // first output row index for this span
};

// [b, eol) of one line with the trailing '\r' stripped; empty -> skip
inline const char* strip_eol(const char* b, const char* eol) {
  if (eol > b && eol[-1] == '\r') --eol;
  return eol;
}

// count NON-EMPTY lines in [b, e)
int64_t count_lines(const char* b, const char* e) {
  int64_t n = 0;
  while (b < e) {
    const char* p = static_cast<const char*>(memchr(b, '\n', e - b));
    const char* eol = p ? p : e;
    if (strip_eol(b, eol) > b) ++n;
    if (!p) break;
    b = p + 1;
  }
  return n;
}

// strict parse of one line body [p, eol): exactly 4 delimited fields.
// strtoll/strtof stop at the terminating '\n'/delim, and every field is
// bounds-checked against eol, so they never consume past the line.
// errno (thread-local) catches int64 overflow — an overflowing id would
// otherwise clamp to INT64_MAX and silently merge distinct entities —
// and std::isfinite rejects nan/inf ratings, which strtof accepts as
// valid spellings but which would poison the factor accumulation.
inline bool parse_fields(const char* p, const char* eol, char delim,
                         int64_t* u, int64_t* i, float* r, int64_t* t) {
  char* q;
  errno = 0;
  *u = strtoll(p, &q, 10);
  if (q == p || errno == ERANGE || q >= eol || *q != delim) return false;
  p = q + 1;
  *i = strtoll(p, &q, 10);
  if (q == p || errno == ERANGE || q >= eol || *q != delim) return false;
  p = q + 1;
  *r = strtof(p, &q);
  if (q == p || !std::isfinite(*r) || q >= eol || *q != delim)
    return false;
  p = q + 1;
  errno = 0;  // strtof sets ERANGE on float underflow (a legal rating)
  *t = strtoll(p, &q, 10);
  if (q == p || errno == ERANGE || q > eol) return false;
  for (p = q; p < eol && *p == ' '; ++p) {}
  return p == eol;
}

void parse_span(Span span, char delim, int64_t* users, int64_t* items,
                float* ratings, int64_t* ts, std::atomic<bool>* bad) {
  const char* p = span.begin;
  int64_t row = span.out_offset;
  while (p < span.end) {
    if (bad->load(std::memory_order_relaxed)) return;
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', span.end - p));
    const char* eol = strip_eol(p, nl ? nl : span.end);
    if (eol > p) {
      if (!parse_fields(p, eol, delim, &users[row], &items[row],
                        &ratings[row], &ts[row])) {
        bad->store(true, std::memory_order_relaxed);
        return;
      }
      ++row;
    }
    p = nl ? nl + 1 : span.end;
  }
}

}  // namespace

extern "C" {

// Count data lines (after skipping `skip_header` lines) of the buffer.
int64_t fastcsv_count(const char* buf, int64_t len, int skip_header) {
  const char* b = buf;
  const char* e = buf + len;
  for (int s = 0; s < skip_header && b < e; ++s) {
    const char* p = static_cast<const char*>(memchr(b, '\n', e - b));
    if (!p) return 0;
    b = p + 1;
  }
  return count_lines(b, e);
}

// Parse into preallocated arrays of length >= fastcsv_count(...).
// Returns rows written, -1 on a header error, -2 on a malformed data line.
int64_t fastcsv_parse(const char* buf, int64_t len, char delim,
                      int skip_header, int n_threads, int64_t* users,
                      int64_t* items, float* ratings, int64_t* ts) {
  const char* b = buf;
  const char* e = buf + len;
  for (int s = 0; s < skip_header && b < e; ++s) {
    const char* p = static_cast<const char*>(memchr(b, '\n', e - b));
    if (!p) return -1;
    b = p + 1;
  }
  if (n_threads < 1) n_threads = 1;

  // split [b, e) into n byte ranges aligned to line starts
  std::vector<Span> spans;
  int64_t chunk = (e - b) / n_threads + 1;
  const char* cur = b;
  while (cur < e) {
    const char* stop = cur + chunk < e ? cur + chunk : e;
    if (stop < e) {
      const char* nl = static_cast<const char*>(memchr(stop, '\n', e - stop));
      stop = nl ? nl + 1 : e;
    }
    spans.push_back({cur, stop, 0});
    cur = stop;
  }
  // prefix-sum line counts -> output offsets
  std::vector<int64_t> counts(spans.size());
  {
    std::vector<std::thread> th;
    for (size_t k = 0; k < spans.size(); ++k)
      th.emplace_back([&, k] { counts[k] = count_lines(spans[k].begin,
                                                       spans[k].end); });
    for (auto& t : th) t.join();
  }
  int64_t off = 0;
  for (size_t k = 0; k < spans.size(); ++k) {
    spans[k].out_offset = off;
    off += counts[k];
  }
  std::atomic<bool> bad{false};
  {
    std::vector<std::thread> th;
    for (auto& s : spans)
      th.emplace_back([&, s] { parse_span(s, delim, users, items,
                                          ratings, ts, &bad); });
    for (auto& t : th) t.join();
  }
  if (bad.load()) return -2;
  return off;
}

}  // extern "C"
