// streamcsv: chunked string-id ratings ingest with a persistent interner.
//
// The config-3 (Amazon-Reviews-2023-shaped) data plane: ratings files
// whose user/item ids are STRINGS at ~half-billion-row scale cannot take
// the fastcsv path (int ids, whole-file parse) — the id space has to be
// discovered while streaming, and no host may ever materialize the full
// rating set (SURVEY.md §5.7, VERDICT r4 next-round #4).  This library
// is the per-host half of that plane: the caller feeds it successive
// chunk buffers of its byte range (lines never split across calls — the
// Python reader re-stitches chunk-boundary partials), and it emits dense
// LOCAL int64 ids per row while growing two intern tables (user, item).
// After the stream ends the caller exports each table's keys in
// dense-id order and merges vocabularies across hosts (io/stream.py);
// the remap local->global is then one numpy gather per host.
//
// Strictness contract matches fastcsv.cc: every data line must be
// exactly `str<delim>str<delim>float` followed by (require_cols - 3)
// more non-validated fields; empty id fields, non-finite ratings,
// quoted fields (a '"' opening either id), and wrong column counts all
// return -2 so the Python wrapper raises instead of letting a merged or
// zero-filled row enter training.  CRLF and a missing final newline are
// accepted; empty lines are skipped.
//
// Interner: open-addressing table (FNV-1a 64) over a byte arena;
// indices, not pointers, so arena growth never invalidates keys.  One
// handle is single-threaded by design — per-host ingest is one stream.
//
// Build: g++ -O3 -shared -fPIC streamcsv.cc -o libstreamcsv.so

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

inline uint64_t fnv1a(const char* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t k = 0; k < n; ++k) {
    h ^= static_cast<unsigned char>(p[k]);
    h *= 1099511628211ull;
  }
  return h;
}

struct Interner {
  std::vector<char> arena;           // concatenated key bytes
  std::vector<int64_t> offsets{0};   // offsets[id] .. offsets[id+1]
  std::vector<int64_t> slots;        // open addressing: id+1, 0 = empty
  std::vector<uint64_t> hashes;      // hash per id (cheap rehash/probe)

  Interner() : slots(1 << 12, 0) {}

  int64_t size() const { return (int64_t)offsets.size() - 1; }

  void rehash() {
    std::vector<int64_t> ns(slots.size() * 2, 0);
    uint64_t mask = ns.size() - 1;
    for (int64_t id = 0; id < size(); ++id) {
      uint64_t j = hashes[id] & mask;
      while (ns[j]) j = (j + 1) & mask;
      ns[j] = id + 1;
    }
    slots.swap(ns);
  }

  int64_t intern(const char* p, int64_t n) {
    uint64_t h = fnv1a(p, n);
    uint64_t mask = slots.size() - 1;
    uint64_t j = h & mask;
    while (slots[j]) {
      int64_t id = slots[j] - 1;
      if (hashes[id] == h && offsets[id + 1] - offsets[id] == n &&
          memcmp(arena.data() + offsets[id], p, n) == 0)
        return id;
      j = (j + 1) & mask;
    }
    int64_t id = size();
    arena.insert(arena.end(), p, p + n);
    offsets.push_back((int64_t)arena.size());
    hashes.push_back(h);
    slots[j] = id + 1;
    if (size() * 10 >= (int64_t)slots.size() * 7) rehash();
    return id;
  }
};

struct Handle {
  Interner users, items;
};

// [b, eol) of one line with the trailing '\r' stripped
inline const char* strip_eol(const char* b, const char* eol) {
  if (eol > b && eol[-1] == '\r') --eol;
  return eol;
}

// one id field [p, *fe): ends at delim; empty or quoted -> malformed
inline bool take_id(const char* p, const char* eol, char delim,
                    const char** fe) {
  const char* d =
      static_cast<const char*>(memchr(p, delim, eol - p));
  if (!d || d == p || *p == '"') return false;
  *fe = d;
  return true;
}

}  // namespace

extern "C" {

void* sc_create() { return new Handle(); }

void sc_destroy(void* h) { delete static_cast<Handle*>(h); }

// Count non-empty lines of the buffer (chunk output sizing).
int64_t sc_count_lines(const char* buf, int64_t len) {
  int64_t n = 0;
  const char* b = buf;
  const char* e = buf + len;
  while (b < e) {
    const char* p = static_cast<const char*>(memchr(b, '\n', e - b));
    const char* eol = strip_eol(b, p ? p : e);
    if (eol > b) ++n;
    if (!p) break;
    b = p + 1;
  }
  return n;
}

// Parse one chunk of whole lines; rows land in out_* (length >= the
// chunk's sc_count_lines).  require_cols >= 3: total delimited fields
// per line (user, item, rating, then require_cols-3 ignored tails).
// Returns rows written, or -2 on the first malformed line.
int64_t sc_ingest(void* handle, const char* buf, int64_t len, char delim,
                  int require_cols, int64_t* out_u, int64_t* out_i,
                  float* out_r) {
  Handle* h = static_cast<Handle*>(handle);
  const char* p = buf;
  const char* e = buf + len;
  int64_t row = 0;
  while (p < e) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', e - p));
    const char* eol = strip_eol(p, nl ? nl : e);
    if (eol > p) {
      const char *ue, *ie;
      if (!take_id(p, eol, delim, &ue)) return -2;
      if (!take_id(ue + 1, eol, delim, &ie)) return -2;
      const char* rp = ie + 1;
      char* q;
      float r = strtof(rp, &q);
      if (q == rp || !std::isfinite(r)) return -2;
      // after the rating: either end-of-line (require_cols == 3) or
      // delim + exactly require_cols-4 more delims before eol
      int extra = require_cols - 3;
      if (extra == 0) {
        const char* t = q;
        while (t < eol && *t == ' ') ++t;
        if (t != eol) return -2;
      } else {
        if (q >= eol || *q != delim) return -2;
        const char* t = q;
        int seen = 0;  // delims from the one after rating onward
        while (t < eol) {
          const char* d =
              static_cast<const char*>(memchr(t, delim, eol - t));
          if (!d) break;
          ++seen;
          t = d + 1;
        }
        if (seen != extra) return -2;
      }
      out_u[row] = h->users.intern(p, ue - p);
      out_i[row] = h->items.intern(ue + 1, ie - (ue + 1));
      out_r[row] = r;
      ++row;
    }
    p = nl ? nl + 1 : e;
  }
  return row;
}

// which: 0 = users, 1 = items
int64_t sc_num_keys(void* handle, int which) {
  Handle* h = static_cast<Handle*>(handle);
  return (which ? h->items : h->users).size();
}

int64_t sc_key_bytes(void* handle, int which) {
  Handle* h = static_cast<Handle*>(handle);
  return (int64_t)(which ? h->items : h->users).arena.size();
}

// Export keys in dense-id order: out_bytes gets the concatenated arena
// (length sc_key_bytes), out_offsets gets size()+1 offsets.
void sc_export_keys(void* handle, int which, char* out_bytes,
                    int64_t* out_offsets) {
  Handle* h = static_cast<Handle*>(handle);
  Interner& t = which ? h->items : h->users;
  memcpy(out_bytes, t.arena.data(), t.arena.size());
  memcpy(out_offsets, t.offsets.data(),
         t.offsets.size() * sizeof(int64_t));
}

int64_t sc_max_key_len(void* handle, int which) {
  Handle* h = static_cast<Handle*>(handle);
  Interner& t = which ? h->items : h->users;
  int64_t m = 0;
  for (int64_t id = 0; id < t.size(); ++id) {
    int64_t n = t.offsets[id + 1] - t.offsets[id];
    if (n > m) m = n;
  }
  return m;
}

// Export keys as a dense [size, width] zero-padded matrix — one memcpy
// per key instead of one Python object per key, so the caller can view
// it as a numpy S(width) array and vectorize the cross-host merge.
void sc_export_keys_padded(void* handle, int which, int64_t width,
                           char* out) {
  Handle* h = static_cast<Handle*>(handle);
  Interner& t = which ? h->items : h->users;
  memset(out, 0, t.size() * width);
  for (int64_t id = 0; id < t.size(); ++id) {
    int64_t n = t.offsets[id + 1] - t.offsets[id];
    memcpy(out + id * width, t.arena.data() + t.offsets[id],
           n < width ? n : width);
  }
}

}  // extern "C"
