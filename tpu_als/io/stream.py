"""Chunked/streaming string-id ratings ingest — the config-3 data plane.

The Amazon-Reviews-2023-shaped workload (SURVEY.md §6 row 3) is a ratings
file with STRING user/item ids at a scale (~570M rows) where no single
host may materialize the whole rating set.  This module is the host-side
plane that feeds ``ALS(dataMode='per_host')``:

- :func:`stream_ingest` — ONE host's view: stream the host's byte range
  of the file in bounded chunks through the native interner
  (``native/streamcsv.cc``), producing dense local int64 ids + the local
  vocabulary in first-seen order.  Peak memory is one chunk buffer plus
  this host's output arrays — never the full file, never another host's
  rows.
- :func:`merge_vocabularies` — union per-host vocabularies into a global
  id space (lexicographic — a pure function of the label SET, so every
  host computes the identical map) and the per-host ``local id ->
  global id`` gathers.
- :func:`ingest_per_host` — the single-process harness that runs every
  host's stream (used by tests and the ingest benchmark; a real pod runs
  one :func:`stream_ingest` per process and exchanges only vocabularies,
  which are ~|distinct ids|, not ~|ratings|).

Byte-range protocol (the classic split-reader contract): host ``k`` owns
the lines whose first byte falls in its range.  A line straddling a range
boundary belongs to the host where it STARTS; the next host skips through
the first newline at-or-after its range start.  Chunk reads within a host
re-stitch the partial line left at each chunk's tail, so the native layer
only ever sees whole lines.

The string labels feed the standard indexer surface:
``StringIndexerModel.from_labels(decode_labels(user_labels))`` gives the
same transform/inverse path a small-data ``StringIndexer().fit`` would
(SURVEY.md §2.D pipeline parity), without ever running a full-file
``np.unique`` — labels stay as numpy bytes arrays until a consumer
actually needs Python strings.
"""

from __future__ import annotations

import ctypes
import os
import time

import numpy as np

from tpu_als import obs
from tpu_als.core.ratings import invalid_rating_mask
from tpu_als.io._native_build import build_native
from tpu_als.resilience import faults
from tpu_als.resilience.retry import RetryPolicy, retry_call

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "streamcsv.cc")
_LIB = os.path.join(_NATIVE_DIR, "libstreamcsv.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    build_native(_SRC, _LIB)
    lib = ctypes.CDLL(_LIB)
    lib.sc_create.restype = ctypes.c_void_p
    lib.sc_destroy.argtypes = [ctypes.c_void_p]
    lib.sc_count_lines.restype = ctypes.c_int64
    lib.sc_count_lines.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.sc_ingest.restype = ctypes.c_int64
    lib.sc_ingest.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_float)]
    lib.sc_num_keys.restype = ctypes.c_int64
    lib.sc_num_keys.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sc_key_bytes.restype = ctypes.c_int64
    lib.sc_key_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sc_export_keys.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64)]
    lib.sc_max_key_len.restype = ctypes.c_int64
    lib.sc_max_key_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sc_export_keys_padded.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_char_p]
    _lib = lib
    return lib


def host_byte_range(size, host_index, num_hosts):
    """Even byte split; the line-ownership protocol (see module doc)
    turns it into an exact, non-overlapping line split."""
    if not 0 <= host_index < num_hosts:
        raise ValueError(f"host_index {host_index} not in [0, {num_hosts})")
    per = size // num_hosts
    start = host_index * per
    end = size if host_index == num_hosts - 1 else (host_index + 1) * per
    return start, end


def _export_labels(lib, handle, which):
    """This host's vocabulary as a numpy ``S(width)`` array in dense-id
    order — no per-key Python objects (at ~1M distinct ids per host the
    decode loop would dominate the whole ingest)."""
    n = lib.sc_num_keys(handle, which)
    width = max(1, lib.sc_max_key_len(handle, which))
    out = np.empty(n, dtype=f"S{width}")
    if n:
        lib.sc_export_keys_padded(
            handle, which, width,
            out.ctypes.data_as(ctypes.c_char_p))
    return out


def decode_labels(labels):
    """Bytes vocabulary -> list[str] (for the StringIndexerModel surface
    and other user-facing label consumers; deliberately lazy — decoding
    a million labels costs more than parsing ten million rows)."""
    return [s.decode("utf-8") for s in labels.tolist()]


def _read_chunk(f, pos, want, policy):
    """One chunk read under the retry policy.  Each attempt seeks back
    to ``pos`` first, so a partially-consumed failed read never skips
    bytes.  Fault point ``ingest.read_chunk``: raise = transient read
    error (retried); corrupt = a stray newline tears a line mid-chunk
    (NUL would be skipped as padding by the native parser), which the
    strict parser rejects as a malformed line (typed ValueError, never
    silently-wrong rows)."""

    def _read():
        f.seek(pos)
        mode = faults.check("ingest.read_chunk")
        block = f.read(want)
        if mode == "corrupt" and block:
            buf = bytearray(block)
            buf[len(buf) // 2] = ord("\n")
            block = bytes(buf)
        return block

    return retry_call(_read, policy=policy, what="ingest.read_chunk")


class _Quarantine:
    """Poisoned-record sink for one :func:`stream_ingest` call
    (resilience guardrails).  Mirrors checkpoint's ``.corrupt/``
    convention: the bad records are moved ASIDE — appended verbatim to a
    sink file for forensics — not silently dropped, with one
    ``ingest.quarantined_rows`` counter bump and ONE
    ``ingest_quarantined`` event at end of call (the per-chunk obs cost
    discipline)."""

    REASONS = ("malformed", "nonfinite", "out_of_range")

    def __init__(self, sink):
        self.sink = str(sink)
        self.counts = dict.fromkeys(self.REASONS, 0)
        self._fh = None

    @property
    def total(self):
        return sum(self.counts.values())

    def _handle(self):
        if self._fh is None:
            d = os.path.dirname(self.sink)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.sink, "ab")
        return self._fh

    def line(self, raw, reason):
        """Quarantine one raw text line the parser rejected."""
        self.counts[reason] += 1
        self._handle().write(raw.rstrip(b"\n") + b"\n")

    def rows(self, u, i, r, reason):
        """Quarantine post-parse rows (non-finite / out-of-range rating
        values the parser accepted as text).  The original line is gone
        by now, so the sink gets a synthesized record."""
        self.counts[reason] += int(len(r))
        fh = self._handle()
        for uu, ii, rr in zip(u.tolist(), i.tolist(), r.tolist()):
            fh.write((f"# post-parse {reason}: local_u={uu} "
                      f"local_i={ii} rating={rr}\n").encode())

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _quarantine_sink(path, host_index, quarantine):
    """Resolve the sink file: ``True`` derives
    ``<path>.quarantine/host<k>.bad`` next to the input (the
    ``.corrupt/`` sibling convention); a path-like is used as-is."""
    if quarantine is True:
        return os.path.join(str(path) + ".quarantine",
                            f"host{int(host_index)}.bad")
    return os.fspath(quarantine)


def _poison_records(buf, delim):
    """``ingest.record`` fault point (armed only — disarmed ingest never
    walks records): ``corrupt`` rewrites the scheduled record's rating
    column to ``nan`` BEFORE parsing, so the injected poison is a
    genuinely malformed text record exercising the same quarantine path
    real stream corruption would."""
    d = delim.encode()[:1]
    out = []
    changed = False
    for line in buf.split(b"\n"):
        if line.strip() and faults.check("ingest.record") == "corrupt":
            cols = line.split(d)
            if len(cols) >= 3:
                cols[2] = b"nan"
                line = d.join(cols)
                changed = True
        out.append(line)
    return b"\n".join(out) if changed else buf


def stream_ingest(path, host_index=0, num_hosts=1, *, delim=",",
                  require_cols=3, skip_header=0, chunk_bytes=32 << 20,
                  retry_policy=None, quarantine=None):
    """Stream this host's byte range into (users, items, ratings, vocab).

    Returns ``(u_local, i_local, ratings, user_labels, item_labels)``
    where ``u_local``/``i_local`` are dense int64 ids into the label
    arrays (numpy ``S``-dtype, first-seen order within this host's
    stream; :func:`decode_labels` converts to ``list[str]`` on demand).

    ``require_cols`` is the exact delimited column count per line; the
    first three are ``user,item,rating`` and the rest are skipped
    unparsed (Amazon-2023 csv: ``user_id,parent_asin,rating,timestamp``
    -> ``require_cols=4``).  A malformed line raises ``ValueError`` (the
    fastcsv strictness contract: no silent zero/merged rows).

    ``quarantine`` (guardrails, docs/resilience.md): ``None`` keeps the
    strict contract above; ``True`` (sink at
    ``<path>.quarantine/host<k>.bad``) or an explicit sink path routes
    malformed lines and non-finite / out-of-range ratings to the sink
    instead of raising.  Bad lines re-run through the SAME native parser
    one line at a time (the parser is its own strictness oracle — no
    Python reimplementation to drift), so a poisoned record never
    changes which good records parse.  Quarantined lines still consume
    their owner's byte range, so the exactly-once split claims are
    untouched.  Cost: zero until a chunk actually fails the batch parse.
    """
    lib = _load()
    policy = retry_policy if retry_policy is not None \
        else RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=1.0)
    q = None if quarantine is None else _Quarantine(
        _quarantine_sink(path, host_index, quarantine))
    size = os.path.getsize(path)
    start, end = host_byte_range(size, host_index, num_hosts)
    handle = lib.sc_create()
    out_u, out_i, out_r = [], [], []
    t_start = time.perf_counter()
    stall = 0.0          # time blocked in file reads (vs parse/intern)
    nbytes = 0
    try:
        with open(path, "rb") as f:
            pos = start
            f.seek(pos)
            if start == end:
                pass  # degenerate split (more hosts than bytes): no rows
            elif start == 0:
                # the header belongs to whichever host owns byte 0 —
                # normally host 0, but in the degenerate split above the
                # LAST host can own (0, size) while earlier hosts are
                # empty (reviewer, round 5)
                for _ in range(skip_header):
                    header = f.readline()
                    pos += len(header)
            elif pos > 0:
                # a line straddling `start` belongs to the previous
                # host: skip through the first newline at-or-after start
                skipped = f.readline()
                pos += len(skipped)
            carry = b""
            while pos < end:
                want = min(chunk_bytes, end - pos)
                t_io = time.perf_counter()
                block = _read_chunk(f, pos, want, policy)
                stall += time.perf_counter() - t_io
                if not block:
                    break
                pos += len(block)
                nbytes += len(block)
                buf = carry + block
                cut = buf.rfind(b"\n")
                if cut < 0:
                    carry = buf
                    continue
                carry, buf = buf[cut + 1:], buf[:cut + 1]
                _ingest_chunk(lib, handle, buf, delim, require_cols,
                              out_u, out_i, out_r, path, q)
            # finish the line straddling `end` (ours: it starts in-range)
            # — or, when the range ends exactly at a line start, take the
            # next host's first line (it skips through its first newline,
            # so exactly-once either way).  `pos == end` excludes both a
            # skip that overshot the whole range (those lines belong to a
            # later host) and a degenerate empty range.
            tail = f.readline() if (start != end and pos == end
                                    and pos < size) else b""
            last = carry + tail
            if last.strip():
                _ingest_chunk(lib, handle, last, delim, require_cols,
                              out_u, out_i, out_r, path, q)
        user_labels = _export_labels(lib, handle, 0)
        item_labels = _export_labels(lib, handle, 1)
    finally:
        lib.sc_destroy(handle)
        if q is not None:
            q.close()
    cat = (lambda xs, dt: np.concatenate(xs) if xs
           else np.empty(0, dtype=dt))
    u_out = cat(out_u, np.int64)
    rows = int(len(u_out))
    seconds = time.perf_counter() - t_start
    # one counter set + ONE event per call — never per chunk: the
    # instrumented path must not scale its own cost with the file size
    obs.counter("ingest.rows", rows)
    obs.counter("ingest.bytes", nbytes)
    obs.counter("ingest.stall_seconds", stall)
    obs.emit("ingest", path=str(path), host_index=int(host_index),
             num_hosts=int(num_hosts), rows=rows, bytes=nbytes,
             seconds=round(seconds, 6), stall_seconds=round(stall, 6))
    if q is not None and q.total:
        obs.counter("ingest.quarantined_rows", q.total)
        obs.emit("ingest_quarantined", path=str(path), rows=int(q.total),
                 reasons=dict(q.counts), sink=q.sink,
                 host_index=int(host_index))
    return (u_out, cat(out_i, np.int64),
            cat(out_r, np.float32), user_labels, item_labels)


def _ingest_chunk(lib, handle, buf, delim, require_cols,
                  out_u, out_i, out_r, path, q=None):
    if faults.armed("ingest.record"):
        buf = _poison_records(buf, delim)
    n = lib.sc_count_lines(buf, len(buf))
    if n == 0:
        return
    u = np.empty(n, dtype=np.int64)
    i = np.empty(n, dtype=np.int64)
    r = np.empty(n, dtype=np.float32)
    wrote = lib.sc_ingest(
        handle, buf, len(buf), delim.encode()[0], require_cols,
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        i.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        r.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if wrote == -2:
        if q is None:
            raise ValueError(
                f"malformed ratings line in {path}: every data line must "
                f"be str{delim}str{delim}float with exactly "
                f"{require_cols} columns (no quotes; ids non-empty; "
                "rating finite)")
        u, i, r = _salvage_chunk(lib, handle, buf, delim, require_cols,
                                 q, path)
    elif wrote != n:
        raise IOError(f"streamcsv parsed {wrote} rows, expected {n}")
    if q is not None and len(r):
        # post-parse scrub: values the parser accepts as valid text but
        # the trainer must never see (huge-magnitude ratings; non-finite
        # if the parser's float accepts them)
        bad = invalid_rating_mask(r)
        if bad.any():
            nonfinite = ~np.isfinite(r)
            if (bad & nonfinite).any():
                q.rows(u[bad & nonfinite], i[bad & nonfinite],
                       r[bad & nonfinite], "nonfinite")
            oor = bad & ~nonfinite
            if oor.any():
                q.rows(u[oor], i[oor], r[oor], "out_of_range")
            keep = ~bad
            u, i, r = u[keep], i[keep], r[keep]
    out_u.append(u)
    out_i.append(i)
    out_r.append(r)


def _salvage_chunk(lib, handle, buf, delim, require_cols, q, path):
    """Per-line salvage of a chunk the batch parse rejected: each line
    re-runs through the SAME native parser (its own strictness oracle),
    rejected lines route to the quarantine sink.  Only ever runs on
    chunks that actually contain a bad line, so the healthy-stream cost
    is zero."""
    us, is_, rs = [], [], []
    u1 = np.empty(1, dtype=np.int64)
    i1 = np.empty(1, dtype=np.int64)
    r1 = np.empty(1, dtype=np.float32)
    for line in buf.split(b"\n"):
        if not line.strip():
            continue
        lbuf = line + b"\n"
        wrote = lib.sc_ingest(
            handle, lbuf, len(lbuf), delim.encode()[0], require_cols,
            u1.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            i1.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            r1.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if wrote == 1:
            us.append(int(u1[0]))
            is_.append(int(i1[0]))
            rs.append(float(r1[0]))
        else:
            q.line(line, "malformed")
    return (np.array(us, dtype=np.int64), np.array(is_, dtype=np.int64),
            np.array(rs, dtype=np.float32))


def merge_vocabularies(per_host_labels):
    """Union per-host vocabularies into one global id space.

    Inputs are the ``S``-dtype label arrays from :func:`stream_ingest`.
    Global order is LEXICOGRAPHIC (``np.unique`` over the stacked
    vocabularies — fully vectorized, and a pure function of the per-host
    vocabularies, so in a real deployment every process computes the
    identical mapping from the all-gathered small vocabularies).
    Returns ``(global_labels, remaps)`` where ``global_labels`` is an
    ``S``-dtype array and ``remaps[k][local_id] == global_id``.
    """
    arrays = [np.asarray(a, dtype="S") for a in per_host_labels]
    width = max([a.dtype.itemsize for a in arrays] + [1])
    stacked = np.concatenate([a.astype(f"S{width}") for a in arrays]) \
        if arrays else np.empty(0, dtype="S1")
    global_labels, inverse = np.unique(stacked, return_inverse=True)
    remaps, at = [], 0
    for a in arrays:
        remaps.append(inverse[at:at + len(a)].astype(np.int64))
        at += len(a)
    return global_labels, remaps


# Sentinel vocabulary entry carrying one host's byte-range claim through
# the vocab-union collective.  \x01 cannot appear in a parsed label (the
# streamer rejects control bytes via the malformed-line checks and NUL is
# the padding alphabet), sorts before every printable id, and survives
# np.unique — so the union itself transports the split agreement with
# zero extra collectives.
SPLIT_CLAIM_PREFIX = b"\x01split="


def split_claim(host_index, num_hosts):
    """This host's byte-range claim, to append to its LOCAL user
    vocabulary before :func:`~tpu_als.parallel.multihost.global_vocab_union`
    (or :func:`merge_vocabularies`)."""
    if not 0 <= int(host_index) < int(num_hosts):
        raise ValueError(f"host_index {host_index} not in [0, {num_hosts})")
    return SPLIT_CLAIM_PREFIX + b"%d/%d" % (int(host_index), int(num_hosts))


def _claim_mask(labels):
    """Boolean mask of split-claim sentinels in an ``S``-dtype array.
    S-dtype compare is whole-string, so test the prefix bytes directly."""
    width = max(labels.dtype.itemsize, 1)
    raw = labels.view(np.uint8).reshape(len(labels), width) \
        if len(labels) else np.zeros((0, width), np.uint8)
    npx = len(SPLIT_CLAIM_PREFIX)
    if width >= npx:
        return (raw[:, :npx] ==
                np.frombuffer(SPLIT_CLAIM_PREFIX, np.uint8)).all(axis=1)
    return np.zeros(len(labels), bool)


def strip_split_claims(labels):
    """Remove split-claim sentinels without enforcement — for harnesses
    that byte-split within ONE process (peer claims cannot arrive through
    a single-process union, so coverage is unverifiable there)."""
    labels = np.asarray(labels, dtype="S")
    return labels[~_claim_mask(labels)]


def validate_split_claims(labels):
    """Strip split claims from a unioned vocabulary and verify the hosts
    actually partitioned the file.

    Every host ran ``stream_ingest(path, h, H)`` believing some ``H``;
    :func:`host_byte_range` only partitions the file when every host used
    the SAME ``H`` and the indices cover ``0..H-1``.  A launch where one
    host was started with a stale ``--num-hosts`` silently double-reads
    or drops a byte range — the claims make that loud at vocabulary
    time, before any rating is trained on.

    Returns ``(clean_labels, num_hosts)``; raises ``ValueError`` on
    disagreeing ``num_hosts`` or missing byte ranges.  Identical claims
    collapse in the union, so two hosts claiming the same ``h/H`` are
    indistinguishable — but then some other range is missing, which IS
    caught (coverage), unless they also shadow a live host, in which
    case the ranges still partition and the data is still exactly-once.
    """
    labels = np.asarray(labels, dtype="S")
    is_claim = _claim_mask(labels)
    npx = len(SPLIT_CLAIM_PREFIX)
    claims = []
    for c in labels[is_claim]:
        body = bytes(c)[npx:]
        try:
            h, hh = body.split(b"/")
            claims.append((int(h), int(hh)))
        except ValueError:
            raise ValueError(f"corrupt split claim in vocabulary: {c!r}")
    if not claims:
        raise ValueError(
            "no split claims in the unioned vocabulary — every host must "
            "append split_claim(host_index, num_hosts) before the union")
    counts = {hh for _, hh in claims}
    if len(counts) > 1:
        raise ValueError(
            f"hosts disagree on num_hosts: claims {sorted(claims)} — the "
            "byte ranges do not partition the file (stale --num-hosts on "
            "some host?)")
    (H,) = counts
    got = {h for h, _ in claims}
    missing = sorted(set(range(H)) - got)
    if missing:
        raise ValueError(
            f"byte ranges {missing} of {H} have no ingest claim — those "
            "ratings were never read (host down or mis-indexed)")
    bad = sorted(h for h in got if not 0 <= h < H)
    if bad:
        raise ValueError(f"split claims {bad} out of range for "
                         f"num_hosts={H}")
    return labels[~is_claim], H


def ingest_per_host(path, num_hosts, *, delim=",", require_cols=3,
                    skip_header=0, chunk_bytes=32 << 20):
    """Run every host's stream (single-process harness) and return
    globally-consistent per-host COO splits.

    Returns ``(splits, user_labels, item_labels)`` with ``splits[k] =
    (u_gid, i_gid, ratings)`` — exactly what each process passes to
    ``ALS(dataMode='per_host').fit`` (ids already integer and globally
    agreed, so the estimator's id-union collective sees int64 arrays).
    """
    per_host = [stream_ingest(path, k, num_hosts, delim=delim,
                              require_cols=require_cols,
                              skip_header=skip_header,
                              chunk_bytes=chunk_bytes)
                for k in range(num_hosts)]
    user_labels, u_remaps = merge_vocabularies(
        [h[3] for h in per_host])
    item_labels, i_remaps = merge_vocabularies(
        [h[4] for h in per_host])
    splits = [(u_remaps[k][per_host[k][0]],
               i_remaps[k][per_host[k][1]],
               per_host[k][2]) for k in range(num_hosts)]
    return splits, user_labels, item_labels
