"""MovieLens loaders + a scale-faithful synthetic generator.

Covers the reference app's data-ingest step (SURVEY.md §2.A1): ml-100k
``u.data`` (tab-separated user/item/rating/ts), ml-1m/ml-10m
``ratings.dat`` (``'::'``-separated), and ml-latest/ml-25m
``ratings.csv`` (header ``userId,movieId,rating,timestamp``).  Since this
environment has no network, :func:`synthetic_movielens` generates
MovieLens-shaped data (power-law user/item degrees, 0.5–5.0 star ratings on
a planted low-rank structure) at any scale — it is what the benchmarks use,
with the real loaders available for when datasets are present on disk.
"""

from __future__ import annotations

import os

import numpy as np

from tpu_als.utils.frame import ColumnarFrame

# MovieLens-25M's published shape (users, items, ratings) — used by the
# benchmark harness to synthesize at the exact config-2 scale.
ML25M_SHAPE = (162_541, 59_047, 25_000_095)
ML100K_SHAPE = (943, 1_682, 100_000)


def load_movielens_100k(path):
    """Read ml-100k ``u.data`` (or a directory containing it)."""
    if os.path.isdir(path):
        path = os.path.join(path, "u.data")
    raw = np.loadtxt(path, dtype=np.int64, delimiter="\t")
    return ColumnarFrame({
        "user": raw[:, 0],
        "item": raw[:, 1],
        "rating": raw[:, 2].astype(np.float32),
        "timestamp": raw[:, 3],
    })


def load_movielens_dat(path):
    """Read ml-1m / ml-10m ``ratings.dat`` (or a directory containing it):
    ``UserID::MovieID::Rating::Timestamp``, no header; ml-10m ratings come
    in half-star steps, so the rating column is parsed as float.

    Vectorized: splitting ``a::b::c::d`` on single ``':'`` yields empty
    fields at odd positions, so ``usecols=(0, 2, 4, 6)`` reads the ``'::'``
    format exactly (the fields are bare numbers — no quoting or escapes in
    this format) and the 10M-row ml-10m file stays in numpy end-to-end
    instead of boxing 40M Python objects."""
    if os.path.isdir(path):
        path = os.path.join(path, "ratings.dat")
    try:
        raw = np.loadtxt(path, dtype=np.float64, delimiter=":",
                         usecols=(0, 2, 4, 6), ndmin=2)
    except (ValueError, IndexError) as e:
        raise ValueError(
            f"{path}: malformed ratings line ({e})") from None
    return ColumnarFrame({
        "user": raw[:, 0].astype(np.int64),
        "item": raw[:, 1].astype(np.int64),
        "rating": raw[:, 2].astype(np.float32),
        "timestamp": raw[:, 3].astype(np.int64),
    })


def load_movielens_csv(path):
    """Read a ``ratings.csv`` (ml-latest / ml-25m style, with header)."""
    if os.path.isdir(path):
        path = os.path.join(path, "ratings.csv")
    try:
        from tpu_als.io.fastcsv import load_ratings_csv

        u, i, r, t = load_ratings_csv(path)
    except (ImportError, OSError):
        raw = np.genfromtxt(path, delimiter=",", skip_header=1,
                            dtype=np.float64)
        u = raw[:, 0].astype(np.int64)
        i = raw[:, 1].astype(np.int64)
        r = raw[:, 2].astype(np.float32)
        t = raw[:, 3].astype(np.int64)
    return ColumnarFrame({"user": u, "item": i, "rating": r, "timestamp": t})


def load_movielens_movies(path):
    """Read movie metadata — the id→title table the reference app joins
    recommendations against (SURVEY.md §2.A5's human-readable output).

    Accepts all three MovieLens metadata formats, detected by filename:
    ml-100k ``u.item`` (``|``-separated, latin-1), ml-1m/ml-10m
    ``movies.dat`` (``'::'``-separated), ml-latest/ml-25m ``movies.csv``
    (quoted CSV with header).  A directory resolves to whichever of the
    three it contains.  Returns a ColumnarFrame with ``item`` (int64) and
    ``title`` (object) columns.
    """
    if os.path.isdir(path):
        for name in ("movies.csv", "movies.dat", "u.item"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"{path} contains none of movies.csv / movies.dat / u.item")
    base = os.path.basename(path)
    ids, titles = [], []
    if base.endswith(".csv"):
        import csv

        with open(path, newline="", encoding="utf-8") as f:
            reader = csv.reader(f)
            next(reader, None)  # header: movieId,title,genres
            for row in reader:
                if len(row) < 2:
                    continue
                ids.append(int(row[0]))
                titles.append(row[1])
    elif base.endswith(".dat"):
        # ml-10m ships movies.dat as UTF-8, ml-1m as latin-1: try strict
        # UTF-8 first (latin-1 would silently mojibake UTF-8 titles —
        # every byte sequence is valid latin-1), fall back for ml-1m
        try:
            text = open(path, encoding="utf-8").read()
        except UnicodeDecodeError:
            text = open(path, encoding="latin-1").read()
        for line in text.splitlines():
            parts = line.split("::")
            if len(parts) >= 2:
                ids.append(int(parts[0]))
                titles.append(parts[1])
    else:  # u.item
        with open(path, encoding="latin-1") as f:
            for line in f:
                parts = line.rstrip("\n").split("|")
                if len(parts) >= 2:
                    ids.append(int(parts[0]))
                    titles.append(parts[1])
    return ColumnarFrame({
        "item": np.asarray(ids, dtype=np.int64),
        "title": np.asarray(titles, dtype=object),
    })


def synthetic_movielens(num_users, num_items, num_ratings, seed=0,
                        rank=16, noise=0.3, user_power=0.9, item_power=1.1,
                        return_factors=False):
    """MovieLens-shaped synthetic ratings.

    Degrees follow truncated zipf-like power laws (users shallower than
    items, as in the real datasets); ratings are a planted rank-``rank``
    structure mapped to the 0.5..5.0 half-star grid.  Deterministic per seed.

    ``return_factors=True`` additionally returns the planted ``(Ustar,
    Vstar)`` — benchmarks use them to compute oracle ceilings (the best any
    model could score under a protocol), which is what makes absolute
    retrieval numbers on the synthetic interpretable.
    """
    rng = np.random.default_rng(seed)

    def power_law_ids(n_entities, n_draws, a):
        w = (np.arange(1, n_entities + 1, dtype=np.float64)) ** (-a)
        w /= w.sum()
        ids = rng.choice(n_entities, size=n_draws, p=w)
        # random relabeling so popularity isn't correlated with id order
        perm = rng.permutation(n_entities)
        return perm[ids]

    u = power_law_ids(num_users, num_ratings, user_power)
    i = power_law_ids(num_items, num_ratings, item_power)
    Ustar = rng.normal(0, 1.0, (num_users, rank)).astype(np.float32)
    Vstar = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank)).astype(np.float32)
    raw = np.einsum("nr,nr->n", Ustar[u], Vstar[i])
    raw = raw + noise * rng.normal(size=num_ratings).astype(np.float32)
    # squash to the 0.5..5.0 half-star grid with a MovieLens-like mean
    stars = np.clip(np.round((3.5 + 1.1 * raw) * 2) / 2, 0.5, 5.0)
    frame = ColumnarFrame({
        "user": u.astype(np.int64),
        "item": i.astype(np.int64),
        "rating": stars.astype(np.float32),
        "timestamp": rng.integers(1_000_000_000, 1_600_000_000,
                                  num_ratings),
    })
    if return_factors:
        return frame, Ustar, Vstar
    return frame
