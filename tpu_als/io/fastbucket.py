"""ctypes binding for the native bucketizer (lazy-built with g++).

Drop-in fast path for :func:`tpu_als.core.ratings.build_csr_buckets`: the
two O(nnz) blocking passes (per-entity counting, padded-bucket fill) run in
threaded C++ instead of numpy argsort machinery, producing bit-identical
buckets.  See native/bucketize.cc for the role this plays vs the reference
stack's JVM blocking code (SURVEY.md §2.B4).
"""

from __future__ import annotations

import ctypes
import os


import numpy as np

from tpu_als.io._native_build import build_native

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "bucketize.cc")
_LIB = os.path.join(_NATIVE_DIR, "libbucketize.so")

_lib = None
_load_failed = False
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)
_F32P = ctypes.POINTER(ctypes.c_float)


def load():
    global _lib
    if _lib is not None:
        return _lib
    build_native(_SRC, _LIB, extra_flags=("-pthread",))
    lib = ctypes.CDLL(_LIB)
    lib.bucketize_count.restype = None
    lib.bucketize_count.argtypes = [
        _I64P, ctypes.c_int64, ctypes.c_int64, _I64P, ctypes.c_int]
    lib.bucketize_fill.restype = None
    lib.bucketize_fill.argtypes = [
        _I64P, _I64P, _F32P, ctypes.c_int64, ctypes.c_int64,
        _I64P,
        _I32P, ctypes.c_int32, _I64P,
        ctypes.POINTER(_I32P), ctypes.POINTER(_I32P),
        ctypes.POINTER(_F32P), ctypes.POINTER(_F32P),
        _I32P, _I32P, ctypes.c_int]
    _lib = lib
    return lib


def available():
    global _load_failed
    if _load_failed:
        return False
    try:
        load()
        return True
    except (OSError, subprocess.CalledProcessError):
        _load_failed = True  # don't re-spawn a failing g++ per call
        return False


def counts(row_idx, num_rows, n_threads=None):
    """Per-entity rating counts (np.bincount equivalent).

    Bounds-checks the indices before handing them to C++ — out-of-range
    rows (e.g. the -1 'missing' sentinel from IdMap.to_dense) must raise
    like the numpy path, not corrupt the heap.
    """
    lib = load()
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    row_idx = np.ascontiguousarray(row_idx, dtype=np.int64)
    if len(row_idx):
        lo, hi = row_idx.min(), row_idx.max()
        if lo < 0 or hi >= num_rows:
            raise ValueError(
                f"row indices must be in [0, {num_rows}); got range "
                f"[{lo}, {hi}]")
    out = np.empty(num_rows, dtype=np.int64)
    lib.bucketize_count(
        row_idx.ctypes.data_as(_I64P), len(row_idx), num_rows,
        out.ctypes.data_as(_I64P), n_threads)
    return out


def fill_buckets(row_idx, col_idx, vals, num_rows, cnts, ebucket,
                 bucket_layout, n_threads=None):
    """Fill pre-sized bucket arrays.

    ebucket: [num_rows] int32 bucket index per entity, -1 for entities with
    no ratings — computed by the caller with the same width rule as the
    numpy path (single source of truth for bucket assignment).
    bucket_layout: list of (width, nb, nb_pad) ascending by width, with
    ``nb`` = rated entities of that width and ``nb_pad`` >= nb the padded
    row count.  Returns list of (rows, cols, vals, mask) numpy arrays.
    """
    lib = load()
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    row_idx = np.ascontiguousarray(row_idx, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float32)
    cnts = np.ascontiguousarray(cnts, dtype=np.int64)
    ebucket = np.ascontiguousarray(ebucket, dtype=np.int32)
    nnz = len(row_idx)
    widths = np.array([w for w, _, _ in bucket_layout], dtype=np.int64)

    out = []
    rows_ptrs = (_I32P * len(bucket_layout))()
    cols_ptrs = (_I32P * len(bucket_layout))()
    vals_ptrs = (_F32P * len(bucket_layout))()
    mask_ptrs = (_F32P * len(bucket_layout))()
    for b, (w, nb, nb_pad) in enumerate(bucket_layout):
        rows = np.full(nb_pad, num_rows, dtype=np.int32)
        cols = np.zeros((nb_pad, w), dtype=np.int32)
        v = np.zeros((nb_pad, w), dtype=np.float32)
        m = np.zeros((nb_pad, w), dtype=np.float32)
        out.append((rows, cols, v, m))
        rows_ptrs[b] = rows.ctypes.data_as(_I32P)
        cols_ptrs[b] = cols.ctypes.data_as(_I32P)
        vals_ptrs[b] = v.ctypes.data_as(_F32P)
        mask_ptrs[b] = m.ctypes.data_as(_F32P)

    elocal = np.empty(num_rows, dtype=np.int32)
    cursor = np.zeros(num_rows, dtype=np.int32)
    lib.bucketize_fill(
        row_idx.ctypes.data_as(_I64P), col_idx.ctypes.data_as(_I64P),
        vals.ctypes.data_as(_F32P), nnz, num_rows,
        cnts.ctypes.data_as(_I64P),
        ebucket.ctypes.data_as(_I32P), len(bucket_layout),
        widths.ctypes.data_as(_I64P),
        rows_ptrs, cols_ptrs, vals_ptrs, mask_ptrs,
        elocal.ctypes.data_as(_I32P),
        cursor.ctypes.data_as(_I32P), n_threads)
    return out
