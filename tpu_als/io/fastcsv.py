"""ctypes binding for the native fastcsv parser (lazy-built with g++).

pybind11 is not available in this image, so the Python↔C++ boundary is
ctypes over a tiny ``extern "C"`` surface; arrays are preallocated numpy
buffers written in place by the library.
"""

from __future__ import annotations

import ctypes
import mmap
import os

import numpy as np

from tpu_als.io._native_build import build_native

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "fastcsv.cc")
_LIB = os.path.join(_NATIVE_DIR, "libfastcsv.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    build_native(_SRC, _LIB, extra_flags=("-pthread",))
    lib = ctypes.CDLL(_LIB)
    lib.fastcsv_count.restype = ctypes.c_int64
    lib.fastcsv_count.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int]
    lib.fastcsv_parse.restype = ctypes.c_int64
    lib.fastcsv_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
    ]
    _lib = lib
    return lib


def load_ratings_csv(path, delim=",", skip_header=1, n_threads=None):
    """Parse a ratings file into (users, items, ratings, timestamps).

    Strict: a malformed data line (quoted fields, missing/extra columns,
    trailing junk — see native/fastcsv.cc) raises ``ValueError`` rather
    than letting a zero-filled row enter training.  ``ValueError`` is
    deliberately NOT an ``OSError``: callers with a numpy fallback
    (io.movielens) fall back on build/load problems, never on malformed
    content (the fallback would silently parse such rows as nan).
    """
    lib = _load()
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    size = os.path.getsize(path)
    if size == 0:
        return (np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, np.float32), np.empty(0, np.int64))
    use_mmap = size % mmap.PAGESIZE != 0
    with open(path, "rb") as f:
        # ACCESS_COPY: buffer-protocol-writable (ctypes.from_buffer needs
        # that) but copy-on-write — we never write, so reads are zero-copy.
        # Exception: a file of exactly page-multiple size with no final
        # newline would let strtoll touch the unmapped next page (the
        # parser reads a field up to its terminator); for that rare shape
        # read a heap copy with one byte of slack instead.
        if use_mmap:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
        else:  # one allocation, filled in place (no 2x transient peak)
            mm = bytearray(size + 1)
            f.readinto(memoryview(mm)[:size])
            mm[size] = 0x0A
        try:
            length = size if use_mmap else size + 1
            buf = (ctypes.c_char * length).from_buffer(mm)
            n = lib.fastcsv_count(buf, length, skip_header)
            users = np.empty(n, dtype=np.int64)
            items = np.empty(n, dtype=np.int64)
            ratings = np.empty(n, dtype=np.float32)
            ts = np.empty(n, dtype=np.int64)
            wrote = lib.fastcsv_parse(
                buf, length, delim.encode()[0], skip_header, n_threads,
                users.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                items.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ratings.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
        finally:
            del buf  # release the exported buffer before closing the mmap
            if use_mmap:
                mm.close()
    if wrote == -2:
        raise ValueError(
            f"malformed ratings line in {path}: every data line must be "
            f"int{delim}int{delim}float{delim}int (no quotes, no extra "
            "columns); empty lines are allowed")
    if wrote != n:
        raise IOError(f"fastcsv parsed {wrote} rows, expected {n} ({path})")
    return users, items, ratings, ts


def load_u_data(path, n_threads=None):
    """ml-100k ``u.data`` (tab-separated, no header)."""
    return load_ratings_csv(path, delim="\t", skip_header=0,
                            n_threads=n_threads)
