"""Shared lazy g++ build for the native IO libraries.

One implementation of the build-if-stale pattern (fastcsv, fastbucket,
streamcsv): compile to a private temp file and ``os.rename`` into place,
so two processes racing to build (e.g. both pod workers of
``examples/04`` starting on a clean checkout) can never dlopen a
partially written .so — rename is atomic within a directory, and the
loser's rename simply replaces the winner's identical artifact.
"""

from __future__ import annotations

import os
import subprocess
import tempfile


def build_native(src, lib, extra_flags=()):
    """Build ``src`` -> ``lib`` with g++ if missing or stale."""
    if (os.path.exists(lib)
            and os.path.getmtime(lib) >= os.path.getmtime(src)):
        return
    fd, tmp = tempfile.mkstemp(
        suffix=".so", prefix=os.path.basename(lib) + ".",
        dir=os.path.dirname(lib))
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", *extra_flags, src,
             "-o", tmp],
            check=True, capture_output=True)
        os.rename(tmp, lib)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
