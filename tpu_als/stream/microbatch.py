"""Streaming micro-batch driver: serve fold-in updates without a refit.

The capability the reference stack lacks (Spark MLlib requires a full refit
for new ratings — SURVEY.md §3.5), promised by the north-star (BASELINE.json
configs[3]: "hourly micro-batches of new ratings → incremental user-factor
jit update").  The server wraps a fitted ALSModel; each ``update`` call:

1. merges the batch with the per-user rating history it keeps (optional),
2. pads touched-user rows/widths to powers of two so repeated batches hit
   the jit cache (bounded compile count),
3. runs the jitted fold-in kernel against the fixed item factors,
4. writes the new rows into the model (appending brand-new users).

Item factors stay fixed during USER fold-ins (the standard fold-in
contract); the symmetric ``update_items`` folds new/updated ITEMS against
the fixed user factors, so both directions of catalog growth are served
between refits.
"""

from __future__ import annotations

import collections
import time

import numpy as np

import jax.numpy as jnp

from tpu_als import obs
from tpu_als.core.foldin import fold_in
from tpu_als.core.ratings import IdMap, _next_pow2
from tpu_als.ops.solve import compute_yty
from tpu_als.utils.frame import as_frame


def _pad_rows_pow2(F):
    """Pad a factor table to a power-of-two row count with zero rows.

    The fold-in kernel only ever GATHERS rows of the fixed side (by
    dense ids < the real row count) and, on the implicit path, reads
    ``F^T F`` — zero rows change neither.  Without this, every
    appended entity changes the table's leading dim and the jitted
    solve recompiles per micro-batch (a compile treadmill the live
    pipeline's freshness SLO cannot absorb); with it, compiles happen
    only at doublings."""
    n = int(F.shape[0])
    n_pad = _next_pow2(n)
    if n_pad == n:
        return F
    return jnp.concatenate(
        [F, jnp.zeros((n_pad - n, F.shape[1]), F.dtype)])


class FoldInServer:
    """Incremental user-factor updates against a fitted model."""

    def __init__(self, model, keep_history=True, stats_window=512):
        self.model = model
        self.keep_history = keep_history
        self._history = {}  # original user id -> (item_dense[], rating[])
        self._item_history = {}  # original item id -> (user_dense[], rating[])
        p = model._params
        self._reg = float(p.get("regParam", 0.1))
        self._implicit = bool(p.get("implicitPrefs", False))
        self._alpha = float(p.get("alpha", 1.0))
        self._nonnegative = bool(p.get("nonnegative", False))
        self._V = _pad_rows_pow2(jnp.asarray(model._V))
        self._YtY = compute_yty(self._V) if self._implicit else None
        # (batch_size, touched_users, latency_seconds) — bounded: a
        # long-lived live pipeline folds in forever, and the durable
        # record is the registered obs histograms, not this ring
        self.stats = collections.deque(maxlen=int(stats_window))

    def prewarm(self, rows=(256, 512, 1024), widths=(2, 4, 8, 16, 32),
                sides=("user",), growth=0):
        """Pre-compile the fold-in kernel for a (rows, width) shape grid.

        ``update`` pads batches to power-of-two shapes, so the jit cache
        is bounded — but each NEW shape still pays its compile at serving
        time, which is what dominates a latency benchmark's p95 early in
        a run (observed: p95 11x p50 on the first 30 batches).  Serving
        deployments call this once at startup with the shapes their
        batch size implies; entries are cached per process.

        ``sides`` picks the fold directions to compile ("user" solves
        against the item table, "item" against the user table — a live
        pipeline with ``fold_items`` needs both).  ``growth`` also
        compiles against the fixed table padded up that many extra
        doublings: a stream that appends entities eventually pushes the
        fixed side past its current pow2 pad, and that recompile should
        be paid here, not mid-stream against a freshness SLO.  Shapes
        shared between sides (equal table pads) hit the same jit-cache
        entry, so requesting both costs no duplicate compiles.
        """
        for side in sides:
            F0 = (self._V if side == "user"
                  else _pad_rows_pow2(jnp.asarray(self.model._U)))
            for g in range(int(growth) + 1):
                n_pad = int(F0.shape[0]) << g
                F = (F0 if g == 0 else jnp.concatenate(
                    [F0, jnp.zeros((n_pad - int(F0.shape[0]),
                                    F0.shape[1]), F0.dtype)]))
                YtY = compute_yty(F) if self._implicit else None
                for n in rows:
                    for w in widths:
                        fold_in(
                            F,
                            jnp.zeros((n, w), jnp.int32),
                            jnp.zeros((n, w), jnp.float32),
                            jnp.zeros((n, w), jnp.float32),
                            self._reg, implicit_prefs=self._implicit,
                            alpha=self._alpha,
                            nonnegative=self._nonnegative,
                            YtY=YtY,
                        ).block_until_ready()

    def update(self, batch):
        """Process one micro-batch frame (userCol/itemCol/ratingCol of the
        model).  Returns the original ids of the users whose factors moved.
        """
        return self._fold_batch(batch, items_side=False)

    def update_items(self, batch):
        """Symmetric fold-in for ITEMS: solve new/updated item factors
        against the (fixed) user factors — a brand-new item with a few
        ratings from known users becomes recommendable without a refit.
        The reference stack requires a full refit here too (SURVEY §3.5).

        Users unknown to the model are ignored (no factors to regress
        on — fold them in via ``update`` first).  After the write-back
        the server's cached serving-side V and YᵀY are refreshed, so
        subsequent USER fold-ins see the new items.  Returns the
        original ids of the items whose factors moved.
        """
        return self._fold_batch(batch, items_side=True)

    def _fold_batch(self, batch, items_side):
        """ONE shared mechanics path for both directions — known-side
        filter, per-entity grouping, history merge, pow2 padding, solve,
        write-back — parameterized by which side is being solved, so a
        fix to any of it cannot apply to one direction only."""
        t0 = time.perf_counter()
        frame = as_frame(batch)
        m = self.model
        p = m._params
        if items_side:
            solved_raw = np.asarray(frame[p["itemCol"]])
            fixed_raw = np.asarray(frame[p["userCol"]])
            fixed_map, history = m._user_map, self._item_history
        else:
            solved_raw = np.asarray(frame[p["userCol"]])
            fixed_raw = np.asarray(frame[p["itemCol"]])
            fixed_map, history = m._item_map, self._history
        r = np.asarray(frame[p["ratingCol"]], dtype=np.float32)

        # fixed-side entities never seen in training cannot contribute
        # (no factors to regress on); the reference would equally ignore
        # them until a refit
        fixed_dense = fixed_map.to_dense(fixed_raw)
        known = fixed_dense >= 0
        solved_raw = solved_raw[known]
        fixed_dense, r = fixed_dense[known], r[known]
        if len(solved_raw) == 0:
            return np.array([], dtype=np.int64)

        touched = np.unique(solved_raw)
        per = {e: ([], []) for e in touched}
        for e, f, v in zip(solved_raw, fixed_dense, r):
            per[e][0].append(f)
            per[e][1].append(v)
        if self.keep_history:
            for e in touched:
                hist = history.get(e)
                if hist is not None:
                    per[e] = (hist[0] + per[e][0], hist[1] + per[e][1])
                history[e] = per[e]

        # pad rows and width to powers of two -> bounded jit-cache entries
        n = len(touched)
        n_pad = _next_pow2(n)
        w = _next_pow2(max(len(v[0]) for v in per.values()))
        cols = np.zeros((n_pad, w), dtype=np.int32)
        vals = np.zeros((n_pad, w), dtype=np.float32)
        mask = np.zeros((n_pad, w), dtype=np.float32)
        for row, e in enumerate(touched):
            ff, vv = per[e]
            cols[row, :len(ff)] = ff
            vals[row, :len(ff)] = vv
            mask[row, :len(ff)] = 1.0

        if items_side:
            # the fixed side here is U, which user fold-ins may have
            # grown — read it live (one transfer per item batch; item
            # batches are the rare direction, so this stays off the
            # user hot path)
            F = _pad_rows_pow2(jnp.asarray(m._U))
            YtY = compute_yty(F) if self._implicit else None
        else:
            F, YtY = self._V, self._YtY
        x = np.asarray(fold_in(
            F, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            self._reg, implicit_prefs=self._implicit, alpha=self._alpha,
            nonnegative=self._nonnegative, YtY=YtY,
        ))[:n]

        self._write_back(touched, x, items_side)
        if items_side:
            # refresh the serving-side cache the USER fold-in path reads
            self._V = _pad_rows_pow2(jnp.asarray(m._V))
            if self._implicit:
                self._YtY = compute_yty(self._V)
        dt = time.perf_counter() - t0
        self.stats.append((len(solved_raw), n, dt))
        obs.histogram("foldin.update_seconds", dt,
                      side="item" if items_side else "user")
        obs.histogram("foldin.batch_rows", n,
                      side="item" if items_side else "user")
        obs.counter("foldin.ratings", len(solved_raw))
        return touched

    def _write_back(self, touched_raw_ids, new_rows, items_side=False):
        m = self.model
        map_attr = "_item_map" if items_side else "_user_map"
        fac_attr = "_V" if items_side else "_U"
        fac = getattr(m, fac_attr)
        if not fac.flags.writeable:  # np view of a jax array is read-only
            fac = fac.copy()
            setattr(m, fac_attr, fac)
        emap = getattr(m, map_attr)
        dense = emap.to_dense(touched_raw_ids)
        new_mask = dense < 0
        if new_mask.any():  # brand-new entities: extend map and factors
            new_ids = touched_raw_ids[new_mask]
            emap = IdMap(ids=np.concatenate([emap.ids, new_ids]))
            setattr(m, map_attr, emap)
            fac = np.concatenate(
                [fac, np.zeros((len(new_ids), fac.shape[1]),
                               dtype=fac.dtype)])
            setattr(m, fac_attr, fac)
            dense = emap.to_dense(touched_raw_ids)
        fac[dense] = new_rows

    def latency(self, q=0.5, skip_warmup=False):
        """Latency quantile over processed batches.  ``skip_warmup`` drops
        the first batch (jit compile) — what latency benchmarks want."""
        stats = list(self.stats)
        if skip_warmup:
            stats = stats[1:]
        lat = sorted(s[2] for s in stats)
        if not lat:
            return float("nan")
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    def p50_latency(self):
        return self.latency(0.5)
