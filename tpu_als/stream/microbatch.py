"""Streaming micro-batch driver: serve fold-in updates without a refit.

The capability the reference stack lacks (Spark MLlib requires a full refit
for new ratings — SURVEY.md §3.5), promised by the north-star (BASELINE.json
configs[3]: "hourly micro-batches of new ratings → incremental user-factor
jit update").  The server wraps a fitted ALSModel; each ``update`` call:

1. merges the batch with the per-user rating history it keeps (optional),
2. pads touched-user rows/widths to powers of two so repeated batches hit
   the jit cache (bounded compile count),
3. runs the jitted fold-in kernel against the fixed item factors,
4. writes the new rows into the model (appending brand-new users).

Item factors stay fixed between refits — the standard fold-in contract.
"""

from __future__ import annotations

import time

import numpy as np

import jax.numpy as jnp

from tpu_als.core.foldin import fold_in
from tpu_als.core.ratings import IdMap, _next_pow2
from tpu_als.ops.solve import compute_yty
from tpu_als.utils.frame import as_frame


class FoldInServer:
    """Incremental user-factor updates against a fitted model."""

    def __init__(self, model, keep_history=True):
        self.model = model
        self.keep_history = keep_history
        self._history = {}  # original user id -> (item_dense[], rating[])
        p = model._params
        self._reg = float(p.get("regParam", 0.1))
        self._implicit = bool(p.get("implicitPrefs", False))
        self._alpha = float(p.get("alpha", 1.0))
        self._nonnegative = bool(p.get("nonnegative", False))
        self._V = jnp.asarray(model._V)
        self._YtY = compute_yty(self._V) if self._implicit else None
        self.stats = []  # (batch_size, touched_users, latency_seconds)

    def prewarm(self, rows=(256, 512, 1024), widths=(2, 4, 8, 16, 32)):
        """Pre-compile the fold-in kernel for a (rows, width) shape grid.

        ``update`` pads batches to power-of-two shapes, so the jit cache
        is bounded — but each NEW shape still pays its compile at serving
        time, which is what dominates a latency benchmark's p95 early in
        a run (observed: p95 11x p50 on the first 30 batches).  Serving
        deployments call this once at startup with the shapes their
        batch size implies; entries are cached per process.
        """
        for n in rows:
            for w in widths:
                fold_in(
                    self._V,
                    jnp.zeros((n, w), jnp.int32),
                    jnp.zeros((n, w), jnp.float32),
                    jnp.zeros((n, w), jnp.float32),
                    self._reg, implicit_prefs=self._implicit,
                    alpha=self._alpha, nonnegative=self._nonnegative,
                    YtY=self._YtY,
                ).block_until_ready()

    def update(self, batch):
        """Process one micro-batch frame (userCol/itemCol/ratingCol of the
        model).  Returns the original ids of the users whose factors moved.
        """
        t0 = time.perf_counter()
        frame = as_frame(batch)
        p = self.model._params
        u_raw = np.asarray(frame[p["userCol"]])
        i_raw = np.asarray(frame[p["itemCol"]])
        r = np.asarray(frame[p["ratingCol"]], dtype=np.float32)

        # items never seen in training cannot contribute (no factors); the
        # reference would equally ignore them until a refit
        i_dense = self.model._item_map.to_dense(i_raw)
        known = i_dense >= 0
        u_raw, i_dense, r = u_raw[known], i_dense[known], r[known]
        if len(u_raw) == 0:
            return np.array([], dtype=np.int64)

        touched = np.unique(u_raw)
        per_user = {u: ([], []) for u in touched}
        for u, i, v in zip(u_raw, i_dense, r):
            per_user[u][0].append(i)
            per_user[u][1].append(v)
        if self.keep_history:
            for u in touched:
                hist = self._history.get(u)
                if hist is not None:
                    per_user[u] = (hist[0] + per_user[u][0],
                                   hist[1] + per_user[u][1])
                self._history[u] = per_user[u]

        # pad rows and width to powers of two -> bounded jit-cache entries
        n = len(touched)
        n_pad = _next_pow2(n)
        w = _next_pow2(max(len(v[0]) for v in per_user.values()))
        cols = np.zeros((n_pad, w), dtype=np.int32)
        vals = np.zeros((n_pad, w), dtype=np.float32)
        mask = np.zeros((n_pad, w), dtype=np.float32)
        for row, u in enumerate(touched):
            ii, vv = per_user[u]
            cols[row, :len(ii)] = ii
            vals[row, :len(ii)] = vv
            mask[row, :len(ii)] = 1.0

        x = np.asarray(fold_in(
            self._V, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            self._reg, implicit_prefs=self._implicit, alpha=self._alpha,
            nonnegative=self._nonnegative, YtY=self._YtY,
        ))[:n]

        self._write_back(touched, x)
        self.stats.append((len(u_raw), n, time.perf_counter() - t0))
        return touched

    def _write_back(self, touched_raw_ids, new_rows):
        m = self.model
        if not m._U.flags.writeable:  # np view of a jax array is read-only
            m._U = m._U.copy()
        dense = m._user_map.to_dense(touched_raw_ids)
        new_mask = dense < 0
        if new_mask.any():  # brand-new users: extend the map and the factors
            new_ids = touched_raw_ids[new_mask]
            m._user_map = IdMap(
                ids=np.concatenate([m._user_map.ids, new_ids]))
            m._U = np.concatenate(
                [m._U, np.zeros((len(new_ids), m._U.shape[1]),
                                dtype=m._U.dtype)])
            dense = m._user_map.to_dense(touched_raw_ids)
        m._U[dense] = new_rows

    def latency(self, q=0.5, skip_warmup=False):
        """Latency quantile over processed batches.  ``skip_warmup`` drops
        the first batch (jit compile) — what latency benchmarks want."""
        stats = self.stats[1:] if skip_warmup else self.stats
        lat = sorted(s[2] for s in stats)
        if not lat:
            return float("nan")
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    def p50_latency(self):
        return self.latency(0.5)
