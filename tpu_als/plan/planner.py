"""Cost-model-driven execution planner.

One resolve discipline for every dispatch decision in the stack — solve
backend, NE build path, top-k backend, gather strategy, serving bucket
plan, bench probe budget: **the roofline model proposes, a probe
confirms, and the verdict persists.**

Mechanics per component:

- The *plan key* is (device kind, jax version, rank/dtype, shape class,
  mesh shape) — everything a probe verdict can legitimately depend on.
- A warm cache entry (tpu_als.plan.cache) seeds the in-process probe
  registry (tpu_als.utils.platform) with the banked verdicts, so the
  existing probe walks — ``core.als.resolve_solve_path``,
  ``ops.solve.auto_solve_backend``, ``ops.topk`` — run as pure cache
  reads: zero probe executions, and the resolved path is byte-for-byte
  what a cold walk on the same key selects (the walk still computes the
  verdict; the cache only supplies the probe outcomes it would have
  measured).  ``plan_cache_hit`` is emitted, ``plan_probe`` is not —
  the cross-process warm-start test pins exactly that trail.
- A cold resolve emits ``plan_cache_miss``, runs the walk, emits one
  ``plan_probe`` per newly cached kernel verdict plus one for the walk
  itself, and banks the registry snapshot with full provenance (probe
  timings, ``banked_at``, the roofline model's proposal next to the
  probe's verdict).  Transient-failure verdicts are never banked
  (platform.snapshot_probes).
- ``TPU_ALS_PLAN_CACHE=off`` disarms everything: every consult returns
  immediately and the dispatch sites behave exactly as before the
  planner existed (tests pin the training-step jaxpr byte-identical).

Gather strategy is the one component whose verdict is always the
model's, never the bank's: it costs no probe, and in a multi-process
fit every host must reach the same answer even when their caches
disagree — a banked verdict steering collectives would be a
distributed hang waiting to happen.  The cache entry is provenance for
``plan show`` there, not authority.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone

from tpu_als import obs
from tpu_als.plan import cache as plan_cache

PlanCacheCorrupt = plan_cache.PlanCacheCorrupt

# auto-tune-on-miss opt-in: with TPU_ALS_AUTOTUNE=1 an armed resolve
# whose entry has no banked kernel config runs the measured-timing
# search (perf.autotune) and banks the winner; anything else keeps the
# hand-picked kernel constants — and with the gate off the dispatch
# sites never even consult the bank, so the training-step jaxpr stays
# byte-identical to the pre-autotune tree (tests pin this the
# plan_cache_off way)
AUTOTUNE_ENV = "TPU_ALS_AUTOTUNE"


def autotune_enabled():
    return os.environ.get(AUTOTUNE_ENV, "") == "1"

# tie-break preference when the comm model scores candidates equal — a
# SUBSET of parallel.trainer.GATHER_STRATEGIES (the authoritative
# table): all_to_all is excluded because its byte model needs built
# A2aCsr plans the planner doesn't have at pick time
GATHER_CANDIDATES = ("all_gather", "all_gather_chunked", "ring_overlap",
                     "ring")


def mode():
    """``"off"`` or the active cache directory."""
    return plan_cache.mode()


def armed():
    return plan_cache.mode() != "off"


def _now():
    return datetime.now(timezone.utc).isoformat(timespec="seconds")


def _device_kind():
    import jax

    try:
        d = jax.devices()[0]
        return f"{d.platform}:{d.device_kind}"
    except RuntimeError:
        return "unknown"


def shape_class(n_users=None, n_items=None, nnz=None):
    """Coarse log2 bucketing so near-identical problem sizes share a plan
    entry; ``"generic"`` when the resolve site has no shapes (the probe
    verdicts themselves key on rank/dtype only)."""
    if n_users is None and n_items is None and nnz is None:
        return "generic"

    def b(x):
        return "?" if not x else f"2^{int(math.log2(max(1, int(x))))}"

    return f"u{b(n_users)}.i{b(n_items)}.nnz{b(nnz)}"


def plan_key(*, rank, dtype, shape_class="generic", mesh_shape=None,
             device_count=None):
    # device_count is its own key component (default: the mesh_shape
    # product) so elastic reformation — same mesh RANK, fewer devices —
    # re-derives the shard plan instead of replaying a stale entry
    if device_count is None and mesh_shape:
        device_count = 1
        for n in mesh_shape:
            device_count *= int(n)
    return {
        "device_kind": _device_kind(),
        "jax_version": plan_cache._jax_version(),
        "rank": int(rank),
        "dtype": str(dtype),
        "shape_class": shape_class,
        "mesh_shape": list(mesh_shape) if mesh_shape else None,
        "device_count": int(device_count) if device_count else None,
    }


def _key_str(key):
    mesh = key.get("mesh_shape")
    dc = key.get("device_count")
    return (f"{key['device_kind']}|jax{key['jax_version']}"
            f"|r{key['rank']}|{key['dtype']}|{key['shape_class']}"
            f"|mesh{'x'.join(map(str, mesh)) if mesh else '-'}"
            f"|D{dc if dc else '-'}")


def _summ(resolved):
    if isinstance(resolved, dict):
        return str(resolved.get("resolved_solve_path", resolved))
    return str(resolved)


def _jsonable(x):
    import json

    return json.loads(json.dumps(x, default=str))


def _load_or_quarantine(key):
    """``(entry_or_None, miss_reason_or_None)`` — a corrupt entry is moved
    to ``.corrupt/`` (never crashed on, never trusted) and reads as a
    miss with reason ``"corrupt"`` so the walk reprobes."""
    try:
        return plan_cache.load_entry(key), None
    except PlanCacheCorrupt as e:
        qpath = plan_cache.quarantine(e.path, e.reason)
        obs.emit("warning", what="plan_cache",
                 reason=f"quarantined corrupt entry to {qpath}: {e.reason}")
        return None, "corrupt"


def _resolve_component(key, component, walk, *, model=None,
                       use_banked=False):
    """The shared resolve discipline.  On a cache hit the banked probe
    verdicts are seeded and ``walk()`` re-derives the plan from them
    (``use_banked=True`` trusts the banked resolved value instead —
    only for configuration-like components such as the bucket ladder).
    On a miss the walk runs cold, its probe spend is emitted, and the
    verdict + registry snapshot are banked with provenance."""
    from tpu_als.utils import platform

    entry, reason = _load_or_quarantine(key)
    if entry is not None and component in entry["components"]:
        seeded = platform.seed_probes(entry.get("probes") or {})
        obs.emit("plan_cache_hit", key=_key_str(key), component=component,
                 path=plan_cache.entry_path(key), seeded=seeded)
        resolved = (entry["components"][component]["resolved"]
                    if use_banked else walk())
        obs.emit("plan_resolved", key=_key_str(key), component=component,
                 source="cache", resolved=_summ(resolved))
        return resolved

    obs.emit("plan_cache_miss", key=_key_str(key), component=component,
             reason=(reason or "absent") if entry is None
             else "component_absent")
    before = {n: set(c) for n, c in platform.probe_caches().items()}
    t0 = time.perf_counter()
    resolved = walk()
    walk_s = time.perf_counter() - t0
    executed = []
    for name, c in platform.probe_caches().items():
        for k in c:
            if k in before.get(name, ()):
                continue
            m = c.meta.get(k, {})
            obs.emit("plan_probe", kernel=f"{name}:{k!r}",
                     outcome=bool(c[k]), seconds=m.get("seconds") or 0.0)
            executed.append(f"{name}:{k!r}")
    obs.emit("plan_probe", kernel=f"walk:{component}",
             outcome=_summ(resolved), seconds=walk_s)

    if entry is None:
        entry = {"schema_version": plan_cache.SCHEMA_VERSION,
                 "plan_key": key, "probes": {}, "components": {}}
    for name, outcomes in platform.snapshot_probes().items():
        entry["probes"].setdefault(name, {}).update(outcomes)
    entry["components"][component] = {
        "resolved": _jsonable(resolved),
        "provenance": {
            "banked_at": _now(),
            "walk_seconds": round(walk_s, 6),
            "probes_executed": executed,
            "probe_timings": _jsonable(platform.probe_timings()),
            "model": _jsonable(model) if model is not None else None,
        },
    }
    try:
        plan_cache.store_entry(key, entry)
    except OSError as e:
        obs.emit("warning", what="plan_cache",
                 reason=f"could not bank plan entry: {e}")
    obs.emit("plan_resolved", key=_key_str(key), component=component,
             source="probe", resolved=_summ(resolved))
    return resolved


# -- component resolvers (one per dispatch site) ------------------------


def resolve_training(*, rank, compute_dtype, label, walk):
    """Consulted by ``core.als.resolve_solve_path`` when armed.  ``walk``
    is the legacy probe walk (``_resolve_solve_path_walk``); its return
    dict is the verdict, warm or cold."""
    if not armed():
        return None
    key = plan_key(rank=rank, dtype=compute_dtype)
    return _resolve_component(key, f"training:{label}", walk,
                              model=training_model(rank, compute_dtype))


def training_model(rank, compute_dtype):
    """The roofline proposal for the training resolve: modeled NE-build
    HBM bytes of the gather-fused kernel vs the einsum build at the
    timing probe's shapes (perf.roofline closed forms), plus the solve
    preference ladder.  The probe walk confirms or overrules — both are
    banked so ``plan show`` can display prediction vs measured."""
    import importlib

    # perf.__init__ rebinds the package attribute 'roofline' to the
    # function, so attribute-style module imports resolve wrong here
    rl = importlib.import_module("tpu_als.perf.roofline")

    db = 2 if "bfloat16" in str(compute_dtype) else 4
    n, w = 2048, 256                 # faster_than_einsum's probe instance
    P = n * w
    fused = rl.fused_ne_kernel_bytes(P, n, rank, db)
    einsum = rl.einsum_ne_build_bytes(P, n, rank, db)
    return {
        "ne_bytes": {"gather_fused": fused, "einsum": einsum},
        "ne_proposal": "gather_fused" if fused < einsum else "einsum",
        "solve_preference": (["lanes"] if rank <= 128
                             else ["lanes_blocked"]) + ["pallas", "xla"],
    }


def resolve_topk(*, rank, k, walk):
    """Consulted by ``ops.topk.topk_scores`` (eager 'auto' dispatch) and
    by ``plan warm``; ``walk`` is ``ops.topk.auto_topk_backend``."""
    if not armed():
        return None
    key = plan_key(rank=rank, dtype="float32")
    model = {"proposal": "pallas" if int(k) <= 128 else "xla",
             "reason": "pallas top-k holds k<=128 in lanes; larger k "
                       "falls back to the chunked XLA path"}
    return _resolve_component(key, f"topk:k={int(k)}", walk, model=model)


def gather_model(*, n_users, n_items, rank, n_devices, implicit=False):
    """Closed-form per-device collective bytes for one full ALS iteration
    per candidate strategy (the balanced-shard, one-row-tile case of
    ``parallel.trainer.comm_bytes_per_iter``) and the ranked proposal."""
    D = max(1, int(n_devices))
    fb = 4 * int(rank)
    ru = -(-int(n_users) // D)
    ri = -(-int(n_items) // D)
    ag = (D - 1) * ri * fb + (D - 1) * ru * fb
    ring = D * ri * fb + D * ru * fb
    psum = 4 * (D - 1) / D * rank * rank * 4 if implicit else 0
    by = {"all_gather": ag + psum, "all_gather_chunked": ag + psum,
          "ring_overlap": ring + psum, "ring": ring + psum}
    proposal = min(GATHER_CANDIDATES, key=lambda s: by[s])
    return {"comm_bytes_per_iter": by, "proposal": proposal,
            "n_devices": D}


def resolve_gather_strategy(*, requested="auto", n_users, n_items, rank,
                            n_devices, implicit=False):
    """An explicit strategy passes through untouched.  ``"auto"`` is the
    comm model's pick — deterministic across hosts by construction (see
    module docstring: the bank is provenance here, never authority)."""
    if requested != "auto":
        return requested
    model = gather_model(n_users=n_users, n_items=n_items, rank=rank,
                         n_devices=n_devices, implicit=implicit)
    choice = model["proposal"]
    if armed():
        key = plan_key(
            rank=rank, dtype="float32",
            shape_class=shape_class(n_users=n_users, n_items=n_items),
            mesh_shape=(n_devices,))
        _resolve_component(key, f"gather:D={int(n_devices)}",
                           walk=lambda: choice, model=model)
    return choice


def _ladder_from_observed(observed):
    """Pow2-rounded quantile ladder from an observed request-size mix.

    One bucket per {p50, p90, p99, max} of the observed batch sizes,
    each rounded UP to the next power of two (one pinned executable per
    rung, pad waste bounded by 2x at every quantile the traffic
    actually hits).  Returns None when there is nothing to learn from.
    """
    from tpu_als.core.ratings import _next_pow2

    xs = sorted(int(s) for s in observed if int(s) > 0)
    if not xs:
        return None
    rungs = {int(_next_pow2(xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]))
             for q in (0.50, 0.90, 0.99, 1.0)}
    return tuple(sorted(rungs))


def resolve_serving_buckets(*, rank=0, requested=None, observed=None):
    """Serving batch-bucket ladder.  Explicit buckets pass through;
    ``observed`` (a sequence of served batch sizes, e.g. drained from
    the ``serving.batch_rows`` histogram after a bench run) derives a
    pow2 quantile ladder and re-banks it so later default resolutions
    inherit the measured mix; the bare default consults the bank (a
    previously recorded ladder wins) and falls back to
    ``serving.batcher.DEFAULT_BUCKETS``."""
    from tpu_als.serving.batcher import DEFAULT_BUCKETS

    if requested is not None:
        return tuple(int(b) for b in requested)
    if observed is not None:
        ladder = _ladder_from_observed(observed) or tuple(DEFAULT_BUCKETS)
        if armed():
            key = plan_key(rank=int(rank or 0), dtype="float32")
            entry, _ = _load_or_quarantine(key)
            if entry is None:
                entry = {"schema_version": plan_cache.SCHEMA_VERSION,
                         "plan_key": key, "probes": {}, "components": {}}
            entry["components"]["serving_buckets"] = {
                "resolved": [int(b) for b in ladder],
                "provenance": {
                    "banked_at": _now(),
                    "walk_seconds": 0.0,
                    "probes_executed": [],
                    "probe_timings": {},
                    "model": {"observed_n": len(list(observed)),
                              "reason": "pow2 quantile ladder "
                                        "(p50/p90/p99/max) from the "
                                        "observed request-size mix"},
                },
            }
            try:
                plan_cache.store_entry(key, entry)
            except OSError as e:
                obs.emit("warning", what="plan_cache",
                         reason=f"could not bank observed ladder: {e}")
            obs.emit("plan_resolved", key=_key_str(key),
                     component="serving_buckets", source="observed",
                     resolved=_summ(list(ladder)))
        return ladder
    if not armed():
        return tuple(DEFAULT_BUCKETS)
    key = plan_key(rank=int(rank or 0), dtype="float32")
    model = {"proposal": list(DEFAULT_BUCKETS),
             "reason": "geometric ladder bounds pad waste to ~4x worst "
                       "case while keeping one executable per bucket "
                       "(docs/serving.md)"}
    resolved = _resolve_component(key, "serving_buckets",
                                  walk=lambda: list(DEFAULT_BUCKETS),
                                  model=model, use_banked=True)
    return tuple(int(b) for b in resolved)


def resolve_kernel_config(*, rank, compute_dtype="float32", budget_s=None,
                          space=None, force=False, tune=None, timer=None,
                          n=256, w=64, k=3, seed=0):
    """The measured-timing autotune component (``"kernel_config"``):
    the fused-solve kernel knobs (panel / vmem_budget / max_wc / pump
    depth / factor-table dtype) resolved through the plan cache.

    Warm path: a banked, non-invalidated config returns as a pure cache
    read — ``plan_cache_hit`` + ``plan_resolved(source="cache")``, ZERO
    tuning executions (autotune_smoke pins the trail).  Cold path: only
    when tuning is requested (``tune=True``, the ``plan tune`` CLI, or
    the ``TPU_ALS_AUTOTUNE=1`` auto-tune-on-miss gate) the search runs
    (``perf.autotune.tune``), the winner is banked with measured-vs-
    modeled provenance, and ``plan_tuned`` +
    ``plan_resolved(source="measured")`` are emitted.  Returns None —
    "keep the hand-picked constants" — when disarmed, or when nothing
    is banked and tuning was not requested.

    The never-override rule: an ``interpret``-sourced verdict (CPU
    interpreter timings) never replaces a banked ``device`` (on-chip)
    measurement — the fresh result is discarded with a warning and the
    banked config stands, even under ``force``.
    """
    if not armed():
        return None
    if tune is None:
        tune = autotune_enabled()
    key = plan_key(rank=int(rank), dtype=str(compute_dtype))
    entry, _ = _load_or_quarantine(key)
    comp = (entry or {}).get("components", {}).get("kernel_config")
    prov = (comp or {}).get("provenance") or {}
    if comp is not None and not prov.get("invalidated") and not force:
        obs.emit("plan_cache_hit", key=_key_str(key),
                 component="kernel_config",
                 path=plan_cache.entry_path(key), seeded=0)
        obs.emit("plan_resolved", key=_key_str(key),
                 component="kernel_config", source="cache",
                 resolved=_summ(comp["resolved"]))
        return dict(comp["resolved"])
    if not tune:
        return dict(comp["resolved"]) if comp is not None \
            and not prov.get("invalidated") else None

    from tpu_als.perf import autotune

    obs.emit("plan_cache_miss", key=_key_str(key),
             component="kernel_config",
             reason="invalidated" if prov.get("invalidated")
             else ("forced" if (force and comp is not None)
                   else ("component_absent" if entry is not None
                         else "absent")))
    kwargs = dict(rank=int(rank), compute_dtype=str(compute_dtype),
                  space=space, timer=timer, n=n, w=w, k=k, seed=seed)
    if budget_s is not None:
        kwargs["budget_s"] = float(budget_s)
    verdict = autotune.tune(**kwargs)
    if prov.get("source") == "device" and verdict["source"] == "interpret":
        obs.emit("warning", what="plan_cache",
                 reason="interpret-mode autotune verdict discarded — the "
                        "banked on-chip kernel config stands "
                        "(never-override rule)")
        return dict(comp["resolved"])
    if entry is None:
        entry = {"schema_version": plan_cache.SCHEMA_VERSION,
                 "plan_key": key, "probes": {}, "components": {}}
    ratio = (verdict["measured_seconds"] / verdict["model_seconds"]
             if verdict["model_seconds"] else None)
    entry["components"]["kernel_config"] = {
        "resolved": _jsonable(verdict["config"]),
        "provenance": {
            "banked_at": _now(),
            "source": verdict["source"],
            "measured_seconds": verdict["measured_seconds"],
            "model_seconds": verdict["model_seconds"],
            "default_seconds": verdict["default_seconds"],
            "ratio": ratio,
            "tune_seconds": round(verdict["tune_seconds"], 6),
            "trials": len(verdict["trials"]),
            "walk_seconds": round(verdict["tune_seconds"], 6),
            "probes_executed": [],
            "model": {"shape": verdict["shape"],
                      "reason": "one-at-a-time measured search over "
                                "perf.autotune.SPACE; model_seconds is "
                                "the fused_solve_kernel_bytes closed "
                                "form at the winning config's padded "
                                "shapes"},
        },
    }
    try:
        plan_cache.store_entry(key, entry)
    except OSError as e:
        obs.emit("warning", what="plan_cache",
                 reason=f"could not bank tuned kernel config: {e}")
    obs.emit("plan_tuned", key=_key_str(key), component="kernel_config",
             source=verdict["source"], config=_jsonable(verdict["config"]),
             measured_seconds=verdict["measured_seconds"],
             model_seconds=verdict["model_seconds"])
    obs.emit("plan_resolved", key=_key_str(key), component="kernel_config",
             source="measured", resolved=_summ(verdict["config"]))
    return dict(verdict["config"])


def invalidate_kernel_config(*, rank, compute_dtype="float32",
                             reason="drift"):
    """The re-plan trigger: mark the banked kernel config stale (the
    measured/modeled ratio left its band — ``observe regress --trend``
    or the attribution gap table) so the next armed resolve re-tunes
    instead of riding it.  Returns True when an entry was invalidated."""
    if not armed():
        return False
    key = plan_key(rank=int(rank), dtype=str(compute_dtype))
    entry, _ = _load_or_quarantine(key)
    comp = (entry or {}).get("components", {}).get("kernel_config")
    if comp is None:
        return False
    prov = comp.setdefault("provenance", {})
    if prov.get("invalidated"):
        return False
    prov["invalidated"] = {"at": _now(), "reason": str(reason)}
    try:
        plan_cache.store_entry(key, entry)
    except OSError as e:
        obs.emit("warning", what="plan_cache",
                 reason=f"could not mark kernel config stale: {e}")
        return False
    obs.emit("warning", what="plan_cache",
             reason=f"kernel config invalidated ({reason}) — next armed "
                    "resolve re-tunes")
    return True


# live-pipeline cadence: micro-batch accumulation + index compaction.
# The defaults are the measured sweet spot on CPU (fold-in p50 82 ms
# amortizes over ~256 events; a quarter-catalog delta segment keeps the
# two-GEMM shortlist within noise of the base kernel).
DEFAULT_LIVE_CADENCE = {
    "max_batch": 256,
    "max_wait_ms": 50.0,
    "compact_delta_frac": 0.25,
    "compact_min_rows": 64,
}


def resolve_live_cadence(*, rank=0, requested=None):
    """Live fold-in → publish cadence: micro-batch bounds for the
    updater and the compaction threshold for the delta index.  Explicit
    cadence passes through; the default consults the bank (a recorded
    cadence for this device/rank wins) and falls back to
    ``DEFAULT_LIVE_CADENCE``."""
    if requested is not None:
        out = dict(DEFAULT_LIVE_CADENCE)
        out.update(requested)
    elif not armed():
        out = dict(DEFAULT_LIVE_CADENCE)
    else:
        key = plan_key(rank=int(rank or 0), dtype="float32")
        model = {"proposal": dict(DEFAULT_LIVE_CADENCE),
                 "reason": "accumulate ~max_batch events or max_wait_ms "
                           "(whichever first) per fold-in; compact the "
                           "delta segment past max(compact_min_rows, "
                           "compact_delta_frac * catalog) "
                           "(docs/serving.md)"}
        out = dict(_resolve_component(key, "live_cadence",
                                      walk=lambda: dict(
                                          DEFAULT_LIVE_CADENCE),
                                      model=model, use_banked=True))
    return {"max_batch": int(out["max_batch"]),
            "max_wait_ms": float(out["max_wait_ms"]),
            "compact_delta_frac": float(out["compact_delta_frac"]),
            "compact_min_rows": int(out["compact_min_rows"])}


def resolve_tenant_plan(*, rank, n_users=None, n_items=None,
                        requested_buckets=None, requested_cadence=None):
    """Per-tenant execution plan for the multi-tenant control plane:
    the serving bucket ladder + live cadence this tenant's engine and
    updater run with, plus the tenant's ``shape_class``.

    The bucket/cadence components key on (device, jax, rank, dtype) —
    deliberately NOT on the tenant's name — so every same-shaped tenant
    resolves to the SAME plan entry (one probe walk total, zero for
    warm caches) and, with equal buckets/rank/catalog shape-class,
    shares the process-global compiled scoring executables.  That
    compile sharing is what makes N tenants on one mesh cheaper than N
    processes (docs/tenancy.md).
    """
    sc = shape_class(n_users=n_users, n_items=n_items)
    return {
        "shape_class": sc,
        "buckets": resolve_serving_buckets(rank=rank,
                                           requested=requested_buckets),
        "cadence": resolve_live_cadence(rank=rank,
                                        requested=requested_cadence),
    }


def probe_budget_s(default_s):
    """Bench probe-budget suggestion; see
    ``plan.cache.suggested_probe_budget`` (bench.py loads that module
    standalone to stay jax-free)."""
    return plan_cache.suggested_probe_budget(default_s)


def clear():
    """Drop the on-disk entries AND the in-process probe registry (the
    ``plan clear`` CLI verb).  Returns the number of files removed."""
    from tpu_als.utils import platform

    n = plan_cache.clear()
    platform.clear_probe_caches()
    return n


# -- whole-plan assembly (CLI `plan warm` / `plan show`) ----------------


@dataclass(frozen=True)
class ExecutionPlan:
    """Everything the planner decides, assembled in one place."""

    key: dict
    solve: dict | None                # resolve_solve_path verdict dict
    topk_backend: str | None
    gather_strategy: str | None
    serving_buckets: tuple
    probe_budget_s: float
    probe_budget_reason: str
    notes: dict = field(default_factory=dict)
    kernel_config: dict | None = None  # tuned knobs (None = hand-picked)

    def summary(self):
        return {
            "key": _key_str(self.key),
            "resolved_solve_path": (self.solve or {}).get(
                "resolved_solve_path"),
            "topk_backend": self.topk_backend,
            "gather_strategy": self.gather_strategy,
            "serving_buckets": list(self.serving_buckets),
            "probe_budget_s": self.probe_budget_s,
            "probe_budget_reason": self.probe_budget_reason,
            "kernel_config": self.kernel_config,
        }


def resolve_execution_plan(*, rank=128, compute_dtype="float32",
                           solve_backend="auto", cg_iters=0,
                           cg_mode="dense", nonnegative=False, k=10,
                           n_users=None, n_items=None, n_devices=1,
                           default_probe_budget_s=600.0):
    """Resolve the full plan for one configuration — the ``plan warm``
    entry point.  Every component goes through its real dispatch-site
    walk (``resolve_solve_path`` consults the planner itself), so
    warming here is exactly the resolve training/serving will perform."""
    from tpu_als.core.als import AlsConfig, resolve_solve_path
    from tpu_als.ops.topk import auto_topk_backend

    cfg = AlsConfig(rank=int(rank), solve_backend=solve_backend,
                    cg_iters=int(cg_iters), cg_mode=cg_mode,
                    nonnegative=bool(nonnegative),
                    compute_dtype=compute_dtype)
    solve = resolve_solve_path(cfg, int(rank))
    if armed():
        topk = resolve_topk(rank=int(rank), k=int(k),
                            walk=lambda: auto_topk_backend(int(rank),
                                                           int(k)))
    else:
        topk = auto_topk_backend(int(rank), int(k))
    gather = None
    if n_devices and int(n_devices) > 1 and n_users and n_items:
        gather = resolve_gather_strategy(
            requested="auto", n_users=int(n_users), n_items=int(n_items),
            rank=int(rank), n_devices=int(n_devices))
    buckets = resolve_serving_buckets(rank=int(rank))
    # warm read always when armed; the measured search itself only runs
    # behind the TPU_ALS_AUTOTUNE=1 opt-in (resolve_kernel_config)
    kcfg = (resolve_kernel_config(rank=int(rank),
                                  compute_dtype=compute_dtype)
            if armed() else None)
    budget, why = plan_cache.suggested_probe_budget(default_probe_budget_s)
    return ExecutionPlan(
        key=plan_key(rank=int(rank), dtype=compute_dtype),
        solve=solve, topk_backend=topk, gather_strategy=gather,
        serving_buckets=buckets, probe_budget_s=budget,
        probe_budget_reason=why,
        notes={"mode": mode()},
        kernel_config=kcfg)
