"""Persistent autotune cache for the execution planner (stdlib-only).

One JSON file per plan key under the cache directory (default
``~/.cache/tpu_als/plan``, overridden by ``TPU_ALS_PLAN_CACHE``; the
literal value ``off`` disarms the planner entirely).  Each entry banks
the probe verdicts a cold resolve walked plus the resolved plan per
component, with full provenance — probe timings, ``banked_at``, the
roofline model's proposal next to what the probe measured — so the next
process on the same plan key seeds its probe registry from disk and
compiles the winning paths with zero probe executions.

Write discipline follows the checkpoint conventions (tpu_als/io/
checkpoint.py): writes go to a same-directory temp file and are
atomically renamed into place, and a corrupt or schema-mismatched file
is moved into a ``.corrupt/`` sibling (typed :class:`PlanCacheCorrupt`)
rather than crashed on or silently trusted — the planner treats a
quarantined entry as a cache miss and reprobes.

Deliberately jax-free: ``bench.py`` consults
:func:`suggested_probe_budget` via a standalone importlib load before
it is allowed to import jax (its subprocess backend probe must run
first), and ``scripts/plan_smoke.sh`` inspects entries the same way.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

SCHEMA_VERSION = 1

ENV_VAR = "TPU_ALS_PLAN_CACHE"
_OFF_VALUES = ("off", "0", "none", "disabled")

DEFAULT_DIR = os.path.join("~", ".cache", "tpu_als", "plan")


class PlanCacheCorrupt(ValueError):
    """A plan-cache entry that cannot be trusted: unparseable JSON, a
    schema version this build does not speak, or a payload whose shape
    fails validation.  Carries ``path`` and ``reason``; the planner
    quarantines the file and reprobes instead of propagating this."""

    def __init__(self, path, reason):
        super().__init__(f"plan cache entry {path}: {reason}")
        self.path = path
        self.reason = reason


def mode():
    """``"off"`` when the planner is disarmed, else the cache directory
    (absolute, user-expanded)."""
    raw = os.environ.get(ENV_VAR)
    if raw is not None and raw.strip().lower() in _OFF_VALUES:
        return "off"
    return os.path.abspath(os.path.expanduser(raw or DEFAULT_DIR))


def cache_dir():
    """The cache directory, or ``None`` when disarmed."""
    m = mode()
    return None if m == "off" else m


def key_digest(key):
    """Stable short digest of a plan-key dict (filename stem)."""
    blob = json.dumps(key, sort_keys=True, default=str).encode()
    return hashlib.blake2b(blob, digest_size=10).hexdigest()


def entry_path(key, root=None):
    root = root or cache_dir()
    if root is None:
        raise RuntimeError("plan cache is disarmed (TPU_ALS_PLAN_CACHE=off)")
    return os.path.join(root, f"plan_{key_digest(key)}.json")


def _validate(doc, path, key=None):
    if not isinstance(doc, dict):
        raise PlanCacheCorrupt(path, "entry is not a JSON object")
    ver = doc.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise PlanCacheCorrupt(
            path, f"schema_version {ver!r} != supported {SCHEMA_VERSION} "
                  "(written by a different build)")
    if not isinstance(doc.get("plan_key"), dict):
        raise PlanCacheCorrupt(path, "missing plan_key object")
    if key is not None and doc["plan_key"] != key:
        raise PlanCacheCorrupt(
            path, "plan_key mismatch (digest collision or edited file)")
    probes = doc.get("probes")
    if not isinstance(probes, dict):
        raise PlanCacheCorrupt(path, "missing probes object")
    for name, entries in probes.items():
        if not isinstance(entries, dict) or not all(
                isinstance(v, bool) for v in entries.values()):
            raise PlanCacheCorrupt(
                path, f"probe table {name!r} is not {{key: bool}}")
    comps = doc.get("components")
    if not isinstance(comps, dict):
        raise PlanCacheCorrupt(path, "missing components object")
    for cname, comp in comps.items():
        if not isinstance(comp, dict) or "resolved" not in comp:
            raise PlanCacheCorrupt(
                path, f"component {cname!r} carries no resolved plan")
        prov = comp.get("provenance")
        if not isinstance(prov, dict) or not prov.get("banked_at"):
            raise PlanCacheCorrupt(
                path, f"component {cname!r} is missing banked_at provenance")
    return doc


def load_entry(key, root=None):
    """Load and validate the entry for ``key``.  Returns ``None`` when no
    file exists; raises :class:`PlanCacheCorrupt` when the file exists
    but cannot be trusted (callers quarantine and treat as a miss)."""
    path = entry_path(key, root)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise PlanCacheCorrupt(path, f"unreadable JSON ({e})") from e
    return _validate(doc, path, key=key)


def store_entry(key, doc, root=None):
    """Atomically install ``doc`` as the entry for ``key`` (temp file in
    the same directory + rename, per the checkpoint conventions — a
    reader never sees a half-written entry)."""
    path = entry_path(key, root)
    _validate(doc, path, key=key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def quarantine(path, reason):
    """Move an untrusted entry into a ``.corrupt/`` sibling (timestamped,
    collision-suffixed) so the evidence survives while the planner
    reprobes.  Returns the quarantine path, or ``None`` if the file was
    already gone (lost race with another process)."""
    if not os.path.exists(path):
        return None
    qdir = os.path.join(os.path.dirname(path), ".corrupt")
    os.makedirs(qdir, exist_ok=True)
    base = f"{os.path.basename(path)}.{int(time.time())}"
    dest = os.path.join(qdir, base)
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = os.path.join(qdir, f"{base}.{n}")
    try:
        os.replace(path, dest)
    except OSError:
        return None
    with open(dest + ".reason", "w", encoding="utf-8") as f:
        f.write(f"{reason}\n")
    return dest


def list_entries(root=None):
    """Every entry in the cache dir: ``[(path, doc_or_error)]`` where the
    second element is the validated doc or a :class:`PlanCacheCorrupt`
    (``plan show`` renders both; nothing raises)."""
    root = root or cache_dir()
    out = []
    if root is None or not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if not (name.startswith("plan_") and name.endswith(".json")):
            continue
        path = os.path.join(root, name)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            out.append((path, _validate(doc, path)))
        except PlanCacheCorrupt as e:
            out.append((path, e))
        except (OSError, ValueError) as e:
            out.append((path, PlanCacheCorrupt(path, f"unreadable ({e})")))
    return out


def clear(root=None):
    """Delete every entry file (``.corrupt/`` evidence is kept).  Returns
    the number of entries removed."""
    root = root or cache_dir()
    n = 0
    if root is None or not os.path.isdir(root):
        return n
    for name in sorted(os.listdir(root)):
        if name.startswith("plan_") and name.endswith(".json"):
            try:
                os.remove(os.path.join(root, name))
                n += 1
            except OSError:
                pass
    return n


def _jax_version():
    """jax's installed version without importing jax (bench.py calls this
    before its subprocess backend probe is allowed to touch jax)."""
    try:
        from importlib import metadata
        return metadata.version("jax")
    except Exception:
        return "unknown"


def suggested_probe_budget(default_s, root=None):
    """Bench probe-budget suggestion: when the cache holds at least one
    valid entry banked under the currently installed jax version, the
    winning paths are known and compile immediately, so the TPU-ready
    probe envelope shrinks (to ``max(default/5, 120)`` seconds, capped by
    the default).  Disarmed, empty, or version-mismatched caches return
    the default unchanged.  jax-free by construction."""
    root = root if root is not None else cache_dir()
    if root is None:
        return float(default_s), "planner off"
    ver = _jax_version()
    warm = [p for p, doc in list_entries(root)
            if isinstance(doc, dict)
            and doc.get("plan_key", {}).get("jax_version") == ver]
    if not warm:
        return float(default_s), "no warm plan entries"
    budget = min(float(default_s), max(float(default_s) / 5.0, 120.0))
    return budget, (f"{len(warm)} warm plan entr"
                    f"{'y' if len(warm) == 1 else 'ies'} for jax {ver}")
