"""Execution planner: roofline-ranked, probe-confirmed, persisted.

``tpu_als.plan.planner`` resolves ExecutionPlan components for every
dispatch site in the stack; ``tpu_als.plan.cache`` is the on-disk,
schema-validated autotune cache behind it (jax-free — bench.py loads it
standalone).  See docs/planner.md.
"""

from tpu_als.plan.cache import PlanCacheCorrupt, SCHEMA_VERSION  # noqa: F401
from tpu_als.plan.planner import (  # noqa: F401
    AUTOTUNE_ENV,
    DEFAULT_LIVE_CADENCE,
    GATHER_CANDIDATES,
    ExecutionPlan,
    armed,
    autotune_enabled,
    clear,
    gather_model,
    invalidate_kernel_config,
    mode,
    plan_key,
    probe_budget_s,
    resolve_execution_plan,
    resolve_gather_strategy,
    resolve_kernel_config,
    resolve_live_cadence,
    resolve_serving_buckets,
    resolve_tenant_plan,
    resolve_topk,
    resolve_training,
    shape_class,
    training_model,
)
