"""tpu_als — a TPU-native recommender framework.

Reimplements the full capability surface of the reference repo
(``amy-leaf/Recommender-System-using-Apache-Spark-MLlib-``, a Spark MLlib ALS
recommender — see SURVEY.md; the reference mount was empty, so the spec is the
``pyspark.ml.recommendation.ALS`` stack it delegates to) as an idiomatic
JAX/XLA stack:

- factor matrices are sharded ``jax.Array``s on a named device mesh,
- each ALS half-step is one batched normal-equation build + Cholesky solve,
- the Spark shuffle is replaced by on-device collectives
  (``all_gather`` / ring ``ppermute``),
- new ratings fold in via a jitted incremental update instead of a refit.

Package map (SURVEY.md §7):
  ops/       batched numerics: normal equations, Cholesky/NNLS solves, top-k
  core/      ratings containers (bucketed padded CSR), ALS loop, fold-in
  parallel/  mesh helpers + gather strategies (replicate/all_gather/ring)
  api/       Param system, ALS Estimator / ALSModel, evaluators, tuning
  io/        MovieLens loaders, checkpoint/persistence
  stream/    micro-batch fold-in driver
  models/    two-tower retrieval model warm-started from ALS factors
"""

__version__ = "0.1.0"

from tpu_als.api.estimator import ALS, ALSModel  # noqa: F401
from tpu_als.api.pipeline import (  # noqa: F401
    IndexToString,
    Pipeline,
    PipelineModel,
    StringIndexer,
    StringIndexerModel,
)
from tpu_als.api.evaluation import (  # noqa: F401
    RankingEvaluator,
    RankingMetrics,
    RegressionMetrics,
    RegressionEvaluator,
)
from tpu_als.api.tuning import (  # noqa: F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from tpu_als.stream.microbatch import FoldInServer  # noqa: F401
from tpu_als.utils.frame import ColumnarFrame  # noqa: F401
