"""Pallas TPU kernel: fused normal-equation build + SPD solve.

The unfused half-step (tpu_als.core.als.local_half_step) runs three HBM
round-trips per chunk: the gathered factors ``Vg`` feed an einsum that
writes ``A [n, r, r]`` to HBM, and the solver reads ``A`` back.  At
ML-25M/rank-128 scale ``A`` is ~14 GB per iteration of pure HBM traffic.
This kernel accumulates ``A`` and ``b`` in VMEM scratch while the ``Vg``
blocks stream through, then factorizes and solves **in the same kernel
invocation** — ``A`` never exists in HBM.

Grid: ``(row_tiles, width_chunks)`` with the width dimension innermost; the
``[TN, r, r]`` accumulator persists across the width chunks of one row tile
(the standard Pallas revisiting pattern).  At the last width chunk the
ridge (weighted-λ: ``regParam · n_ratings``, matching the reference
solver's ``regParam * ne.k`` — Spark MLlib ``NormalEquation``/
``CholeskySolver``, SURVEY.md §2.B5), the empty-row identity guard, the
implicit-feedback YᵀY term, and the jitter are applied, and the blocked
Cholesky + substitution from tpu_als.ops.pallas_solve runs on the VMEM
accumulator.

Semantics match ``normal_eq_explicit`` / ``normal_eq_implicit`` +
``solve_spd`` exactly (same masking, same ridge, same empty-row contract).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops.pallas_solve import factorize, substitute
from tpu_als.ops.solve import DEFAULT_JITTER


def _fused_kernel(Vg_ref, vals_ref, mask_ref, YtY_ref, x_ref, S, LT, bacc,
                  cnt, *, r, panel, n_wc, implicit, alpha, reg, jitter):
    """One (row-tile, width-chunk) grid step.

    Vg_ref [TN, WC, r]; vals/mask [TN, WC]; YtY_ref [r, r] (zeros when
    explicit); x_ref [TN, r] (written at the last width chunk).
    Scratch: S/LT [TN, r, r]; bacc [TN, r]; cnt [TN, r] (the per-row rating
    count replicated across lanes — lane-uniform so the ridge/empty masks
    can read it without lane extraction).
    """
    j = pl.program_id(1)
    tn = Vg_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        S[:] = jnp.zeros_like(S)
        bacc[:] = jnp.zeros_like(bacc)
        cnt[:] = jnp.zeros_like(cnt)

    Vg = Vg_ref[:].astype(jnp.float32)
    v = vals_ref[:].astype(jnp.float32)
    m = mask_ref[:].astype(jnp.float32)
    if implicit:
        conf_m1 = alpha * jnp.abs(v) * m              # c - 1
        pref = (v > 0).astype(jnp.float32) * m
        Vw = Vg * conf_m1[..., None]
        contrib_b = ((1.0 + conf_m1) * pref)[..., None] * Vg
        rowcnt = jnp.sum(pref, axis=1)                # numExplicits
    else:
        Vw = Vg * m[..., None]
        Vg = Vw                                       # both sides masked
        contrib_b = (v * m)[..., None] * Vg
        rowcnt = jnp.sum(m, axis=1)
    # A += Σ_w Vw[t,w,:] Vg[t,w,:]ᵀ — one batched MXU contraction
    S[:] = S[:] + jax.lax.dot_general(
        Vw, Vg, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    bacc[:] = bacc[:] + jnp.sum(contrib_b, axis=1)
    cnt[:] = cnt[:] + rowcnt[:, None]                 # lane-uniform

    @pl.when(j == n_wc - 1)
    def _solve():
        ii = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 1)
        kk = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 2)
        diag = ii == kk
        c3 = cnt[:][:, None, :]                       # [TN, 1, r] broadcast
        A = S[:] + YtY_ref[:][None].astype(jnp.float32)
        A = jnp.where(diag, A + reg * c3 + jitter, A)
        # empty rows (count == 0): A := I so the factorization stays
        # finite; b is already 0 there so x = 0 — the solve_spd contract
        A = jnp.where(c3 <= 0.0, jnp.where(diag, 1.0 + jitter, 0.0), A)
        S[:] = A
        factorize(S, LT, tn=tn, r=r, panel=panel)
        x_ref[:] = substitute(LT, bacc[:], tn=tn, r=r, panel=panel)


def _tiles(r_pad, w, max_wc=256, budget_elems=1 << 18, panel=16):
    """(TN, WC, W_PAD): row tile, width chunk, (re)padded width.

    Mosaic constrains the LAST dimension of a block to be a multiple of
    128 or equal to the full array dimension; the width is the last dim of
    the 2-D vals/mask blocks ``[TN, WC]``, so WC must be the whole (padded)
    width or a 128-multiple dividing it — shrinking in 8-steps, as this
    did before round 2, compiles in interpret mode but is rejected by the
    real Mosaic lowering for any bucket whose width chunks below 128.
    VMEM must hold S + LT [TN, r, r] plus double-buffered Vg [TN, WC, r];
    when the width can no longer shrink, the ROW tile shrinks instead.
    """
    from tpu_als.ops.pallas_solve import _tile_n

    tn = _tile_n(r_pad, budget_elems)
    budget = 1 << 19
    if w <= max_wc:
        wc = w_pad = w
    else:
        w_pad = -(-w // 128) * 128
        wc = max_wc - (max_wc % 128)
        while wc > 128 and (tn * wc * r_pad > budget or w_pad % wc):
            wc -= 128
    while tn > 8 and tn * wc * r_pad > budget:
        tn //= 2
    # Mosaic allocates the kernel body's live temporaries ([TN, panel, r]
    # shaped, ~20 live at the factorization's deepest point) on the scoped
    # VMEM stack; _tile_n's budget only models the S/LT scratches, which
    # at small ranks lets TN grow until the stack blows the 16 MiB limit
    # (observed: rank 32, TN=256 → "scoped vmem limit exceeded by 7.88M").
    # Cap TN so TN·panel·r stays ≤ 2^17 elems at panel 32 — measured green
    # at ranks 32/64/128 on v5e; scale with the caller's actual panel.
    tn = min(tn, max(8, (1 << 17) // (max(panel, 32) * r_pad)))
    return tn, wc, w_pad


@functools.partial(
    jax.jit,
    static_argnames=("implicit", "alpha", "reg", "panel", "jitter",
                     "interpret"),
)
def fused_normal_solve(Vg, vals, mask, YtY=None, *, reg, implicit=False,
                       alpha=1.0, panel=16, jitter=DEFAULT_JITTER,
                       interpret=False):
    """x = (ΣvvᵀC + λnI [+ YᵀY])⁻¹ (ΣcCp) for every row, A never in HBM.

    Vg [N, w, r] gathered opposite factors; vals/mask [N, w]; YtY [r, r]
    required when ``implicit``.  Drop-in for normal_eq_* + solve_spd.
    """
    N, w, r = Vg.shape
    if implicit and YtY is None:
        raise ValueError("implicit fused solve requires YtY")
    r_pad = max(panel, -(-r // panel) * panel)
    tn, wc, w_pad = _tiles(r_pad, -(-w // 8) * 8, panel=panel)
    assert wc == w_pad or (wc % 128 == 0 and w_pad % wc == 0), (wc, w_pad)
    n_pad = -(-N // tn) * tn
    Vg = jnp.pad(Vg, ((0, n_pad - N), (0, w_pad - w), (0, r_pad - r)))
    vals = jnp.pad(vals, ((0, n_pad - N), (0, w_pad - w)))
    mask = jnp.pad(mask, ((0, n_pad - N), (0, w_pad - w)))
    w = w_pad
    YtY_p = (jnp.zeros((r_pad, r_pad), jnp.float32) if YtY is None
             else jnp.pad(YtY.astype(jnp.float32),
                          ((0, r_pad - r), (0, r_pad - r))))
    n_wc = w // wc

    kernel = functools.partial(
        _fused_kernel, r=r_pad, panel=panel, n_wc=n_wc,
        implicit=implicit, alpha=float(alpha), reg=float(reg),
        jitter=float(jitter),
    )
    x = pl.pallas_call(
        kernel,
        grid=(n_pad // tn, n_wc),
        in_specs=[
            pl.BlockSpec((tn, wc, r_pad), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r_pad, r_pad), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tn, r_pad), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(n_pad * (2 * w * r_pad * r_pad
                               + r_pad ** 3 / 3 + 2 * r_pad ** 2)),
            bytes_accessed=(n_pad * w * r_pad + 2 * n_pad * w
                            + n_pad * r_pad) * 4,
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(Vg, vals, mask, YtY_p)
    return x[:N, :r]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_fused")


def available(rank=128, panel=16):
    """Compile-and-run probe, cached per (padded rank, panel) — same
    contract as tpu_als.ops.pallas_solve.available.  The probe validates
    the kernel output against the unfused XLA path on a random instance,
    so a Mosaic miscompile producing finite-but-wrong values also fails."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = max(panel, -(-rank // panel) * panel)

    def probe():
        import numpy as np

        from tpu_als.ops.solve import normal_eq_explicit, solve_spd

        # shape chosen so the probe compiles the SAME program structure as
        # production: >= 2 row tiles and >= 2 width chunks, exercising the
        # scratch-accumulator revisiting across the inner grid dimension
        w = 64
        while True:
            tn, wc, w_pad = _tiles(r_pad, -(-w // 8) * 8, panel=panel)
            if w_pad // wc >= 2:
                break
            w *= 2
        n = 2 * tn
        rng = np.random.default_rng(0)
        Vg = jnp.asarray(
            rng.normal(size=(n, w, r_pad)).astype(np.float32)
            / np.sqrt(r_pad))
        vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
        mask = jnp.asarray(
            (rng.random((n, w)) < 0.8).astype(np.float32))
        # explicit variant
        x = fused_normal_solve(Vg, vals, mask, reg=0.1, panel=panel)
        A, b, count = normal_eq_explicit(Vg, vals * mask, mask, 0.1)
        ref = solve_spd(A, b, count, backend="xla")
        x.block_until_ready()
        if not np.allclose(np.asarray(x), np.asarray(ref), atol=1e-3,
                           rtol=1e-2):
            return False
        # implicit variant compiles a different kernel body (confidence /
        # preference / YtY path) — probe it independently
        from tpu_als.ops.solve import normal_eq_implicit

        iv = jnp.abs(vals) * jnp.asarray(
            np.sign(rng.normal(size=(n, w))).astype(np.float32))
        YtY = jnp.asarray(
            rng.normal(size=(r_pad, r_pad)).astype(np.float32))
        YtY = YtY @ YtY.T / r_pad
        xi = fused_normal_solve(Vg, iv, mask, YtY, reg=0.1, implicit=True,
                                alpha=4.0, panel=panel)
        Ai, bi, ci = normal_eq_implicit(Vg, iv * mask, mask, 0.1, 4.0, YtY)
        refi = solve_spd(Ai, bi, ci, backend="xla")
        xi.block_until_ready()
        return np.allclose(np.asarray(xi), np.asarray(refi), atol=1e-3,
                           rtol=1e-2)

    return probe_kernel(_AVAILABLE, (r_pad, panel), probe)
