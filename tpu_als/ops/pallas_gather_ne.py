"""Pallas TPU kernel: DMA-gather factor rows + fused Gram (normal-eq) build.

The unfused half-step (tpu_als.core.als.local_half_step) materializes the
gathered opposite factors ``Vg [n, w, r]`` in HBM: the XLA gather reads one
factor row per padded rating entry AND writes it into the gathered layout
(``2·P·r·db`` bytes), then the normal-equation einsum reads the whole thing
back (another ``P·r·db``).  At ML-25M/rank-128 that round-trip is the
co-dominant stage on the roofline floor (docs/roofline.md: gather_stream
95.76 ms + the einsum's re-read).  This kernel deletes it: the bucket's
``cols`` land in SMEM, each factor row is DMA-copied **directly from the
HBM-resident factor table** into a VMEM tile (double-buffered
``pltpu.make_async_copy``), and the Gram accumulation

    A = Σ_w  (aw·v) vᵀ        b = Σ_w  bw·v

runs on the VMEM tile as the rows stream through — ``Vg`` never exists in
HBM.  Each padded entry's factor row moves HBM→VMEM exactly once.

Two fusion depths share the DMA-gather front end:

* :func:`gather_gram` (``gather_normal_eq_*``) fuses ONLY gather + Gram
  build and writes ``A [n, r, r]`` / ``b [n, r]`` back to HBM; the
  ridge/YtY tail, the count, the empty-row guard and the SPD solve stay
  on the proven XLA / ``pallas_lanes`` paths (``tpu_als.ops.solve``).
* :func:`gather_solve` (``gather_fused_solve_*``) keeps going: the ridge/
  YtY/empty-guard tail and the blocked Cholesky + substitution from
  ``tpu_als.ops.pallas_solve`` run on the VMEM accumulator at the last
  width chunk, so ``A`` **never exists in HBM at all** — only ``x [n, r]``
  comes back.  This retires the old ``ops.pallas_fused`` attempt, which
  fused the same tail but still streamed an HBM-materialized ``Vg`` in
  (and whose per-column VPU recurrence made it 34× slower than
  einsum+lanes on v5e; the pallas_solve panel factorization used here
  does its trailing updates as batched MXU GEMMs).  Both depths are
  probe-gated independently — availability AND speed — so the planner
  picks the deepest fusion that actually wins on the local chip.

Numerics contract: :func:`gather_normal_eq_explicit` /
:func:`gather_normal_eq_implicit` are drop-in replacements for
``normal_eq_explicit(V[cols], …)`` / ``normal_eq_implicit(V[cols], …)``,
**bitwise at f32** for sublane-multiple widths that fit one width chunk
(every real bucket width — tpu_als.core.ratings.entity_widths only emits
%8==0 widths): the weights, the count, and the ridge/YtY tail are computed
by the *same* XLA expressions as the reference builders, and the in-kernel
contraction is the same ``dot_general`` the einsum lowers to, over the same
operands in the same dtypes (``compute_dtype=bfloat16`` flows through
unchanged — the table is gathered in the compute dtype, contractions
accumulate in f32 via ``preferred_element_type``).  Buckets whose padded
width spans several width chunks accumulate chunk-by-chunk, which matches
the einsum only to rounding (the property tests assert tight allclose
there, exact equality on single-chunk widths).

Grid: ``(row_tiles, width_chunks)``, width innermost; the ``[TN, r, r]``
accumulator persists across the width chunks of one row tile (the
standard Pallas revisiting pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops import ring_buffer as rb
from tpu_als.ops.solve import DEFAULT_JITTER, implicit_weights

# ring depth comes from the shared substrate (ops.ring_buffer) — kept as a
# module alias because the kernels' semaphore-ring scratch shapes and the
# ring_substrate contract both reference it
_DMA_SLOTS = rb.DMA_SLOTS


class TileBudgetError(ValueError):
    """The VMEM budget forces the fused-solve row tile below the
    panel-efficiency knee (TN < 8, a degenerate 1-row-tile grid whose
    factorization panels can no longer amortize their scoped-VMEM
    temporaries).  Raised instead of silently clamping — callers (the
    autotuner's search loop, or a hand-picked override) should treat the
    config as infeasible and widen ``vmem_budget`` or shrink ``panel``."""


def _gather_gram_kernel(cols_ref, aw_ref, bw_ref, V_hbm, A_ref, b_ref,
                        Vg, S, bacc, sem, *, n_wc, two_sided):
    """One (row-tile, width-chunk) grid step.

    cols_ref [TN, WC] (SMEM, scalar-readable DMA indices); aw/bw [TN, WC]
    (VMEM) — the A-side and b-side per-entry weights, precomputed by the
    wrappers with the reference builders' exact expressions; V_hbm [N, r]
    stays in HBM (``memory_space=ANY``).  Scratch: Vg [TN, WC, r] (the
    VMEM landing tile — the only place the gathered rows ever exist),
    S [TN, r, r] / bacc [TN, r] f32 accumulators, sem: DMA semaphore ring.

    two_sided=True applies ``aw`` to BOTH contraction operands (the
    explicit builder's ``Vm = Vg·mask`` on each side); False applies it to
    one side (the implicit builder's ``conf_m1·Vg`` against raw ``Vg``).
    """
    j = pl.program_id(1)
    tn, wc = cols_ref.shape
    n_e = tn * wc

    @pl.when(j == 0)
    def _init():
        S[:] = jnp.zeros_like(S)
        bacc[:] = jnp.zeros_like(bacc)

    def _copy(e, slot):
        t = e // wc
        k = e % wc
        return rb.local_copy(
            V_hbm.at[cols_ref[t, k]], Vg.at[t, k], sem.at[slot])

    # the substrate's multiple-buffering schedule: prime the ring, then
    # wait entry e / start entry e+depth into the slot e just vacated
    rb.pump(n_e, _copy)

    Vg_t = Vg[:]
    aw = aw_ref[:]
    Vw = Vg_t * aw[..., None]
    # same batched contraction the reference einsums lower to, accumulated
    # chunk-by-chunk in f32
    S[:] = S[:] + jax.lax.dot_general(
        Vw, Vw if two_sided else Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    bacc[:] = bacc[:] + jax.lax.dot_general(
        bw_ref[:], Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_wc - 1)
    def _emit():
        A_ref[:] = S[:]
        b_ref[:] = bacc[:]


def _tiles(r_pad, w8, max_wc=256):
    """(TN, WC, W_PAD) for a bucket of (8-padded) width ``w8``.

    Mosaic constrains the LAST dim of a block to be a 128-multiple or the
    full array dim — the width is the last dim of the [TN, WC] cols/aw/bw
    blocks, so WC is the whole padded width or a 128-multiple dividing it
    (the pallas_fused lesson: 8-step shrinking passes interpret mode but
    fails the real lowering).  TN is bounded by the VMEM working set
    (S accumulator + the Vg landing tile + pipelined aw/bw blocks) and by
    the SMEM cols block (TN·WC int32 scalars).
    """
    if w8 <= max_wc:
        wc = w_pad = w8
    else:
        w_pad = -(-w8 // 128) * 128
        wc = max_wc - (max_wc % 128)
        while wc > 128 and w_pad % wc:
            wc -= 128
    tn = 256
    while tn > 8 and tn * (r_pad * r_pad + 3 * wc * r_pad) > (1 << 21):
        tn //= 2
    while tn > 8 and tn * wc > (1 << 13):
        tn //= 2
    return tn, wc, w_pad


@functools.partial(jax.jit, static_argnames=("two_sided", "interpret"))
def gather_gram(V, cols, aw, bw, *, two_sided, interpret=False):
    """Raw fused gather+Gram: ``S[i] = Σ_k aw[i,k]·v[i,k] v[i,k]ᵀ`` (both
    sides weighted when ``two_sided``), ``b[i] = Σ_k bw[i,k]·v[i,k]`` with
    ``v[i,k] = V[cols[i,k]]`` — the rows DMA'd straight from the
    HBM-resident ``V``, never materialized as an [n, w, r] intermediate.

    V [N, r] (any float dtype — bf16 halves the dominant HBM stream);
    cols [n, w] int32; aw/bw [n, w].  Returns (S [n, r, r] f32, b [n, r]
    f32).  The ridge/YtY/count tail lives in the gather_normal_eq_*
    wrappers so it stays bitwise-identical to ``normal_eq_*``.
    """
    N, r = V.shape
    n, w = cols.shape
    # rows are DMA'd as whole [r_pad] slices: pad the table's lane dim to
    # a 128 multiple once (a no-op at the rank-128 headline)
    r_pad = max(128, -(-r // 128) * 128)
    tn, wc, w_pad = _tiles(r_pad, -(-w // 8) * 8)
    assert wc == w_pad or (wc % 128 == 0 and w_pad % wc == 0), (wc, w_pad)
    n_pad = -(-n // tn) * tn
    V_p = jnp.pad(V, ((0, 0), (0, r_pad - r)))
    # padding slots index row 0 with zero weight — contributes nothing
    cols_p = jnp.pad(cols.astype(jnp.int32),
                     ((0, n_pad - n), (0, w_pad - w)))
    aw_p = jnp.pad(aw, ((0, n_pad - n), (0, w_pad - w)))
    bw_p = jnp.pad(bw, ((0, n_pad - n), (0, w_pad - w)))
    n_wc = w_pad // wc

    from tpu_als.perf.roofline import fused_ne_kernel_bytes

    db = jnp.dtype(V.dtype).itemsize
    kernel = functools.partial(
        _gather_gram_kernel, n_wc=n_wc, two_sided=two_sided)
    S, b = pl.pallas_call(
        kernel,
        grid=(n_pad // tn, n_wc),
        in_specs=[
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((tn, r_pad, r_pad), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, r_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, r_pad, r_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tn, wc, r_pad), V.dtype),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((rb.dma_slots(tn * wc),)),
        ],
        # bytes = THE roofline fused-stage model (perf.roofline) at the
        # kernel's padded shapes — tests/test_ne_audit.py extracts this
        # from the traced jaxpr and pins it to the model, the same way
        # test_comm_audit.py pins collective bytes
        cost_estimate=pl.CostEstimate(
            flops=int(2.0 * n_pad * w_pad * r_pad * (r_pad + 1)),
            bytes_accessed=fused_ne_kernel_bytes(
                n_pad * w_pad, n_pad, r_pad, db),
            transcendentals=0,
        ),
        interpret=interpret,
    )(cols_p, aw_p, bw_p, V_p)
    return S[:n, :r, :r], b[:n, :r]


def gather_normal_eq_explicit(V, cols, vals, mask, reg, *, interpret=False):
    """Fused-gather drop-in for ``normal_eq_explicit(V[cols], vals, mask,
    reg)`` — same returns ``(A, b, count)``, bitwise at f32 (module
    docstring), without ever materializing ``V[cols]`` in HBM.

    The weights and the ridge tail are the reference builder's exact
    expressions; only the gather+contraction runs in the kernel.
    """
    aw = mask
    bw = vals * mask
    S, b = gather_gram(V, cols, aw, bw, two_sided=True, interpret=interpret)
    count = jnp.sum(mask, axis=-1)
    r = V.shape[-1]
    eye = jnp.eye(r, dtype=S.dtype)
    A = S + (reg * count)[:, None, None] * eye
    return A, b, count


def gather_normal_eq_implicit(V, cols, vals, mask, reg, alpha, YtY, *,
                              interpret=False):
    """Fused-gather drop-in for ``normal_eq_implicit(V[cols], vals, mask,
    reg, alpha, YtY)`` — same returns ``(A, b, count)``, bitwise at f32.

    Confidence/preference come from the shared :func:`implicit_weights`
    (the one site normal_eq_implicit and solve_cg_matfree also use), the
    YtY + weighted-λ tail is the reference builder's exact expression.
    """
    conf_m1, pref = implicit_weights(vals, mask, alpha)
    aw = conf_m1
    bw = (1.0 + conf_m1) * pref * mask
    S, b = gather_gram(V, cols, aw, bw, two_sided=False,
                       interpret=interpret)
    count = jnp.sum(pref * mask, axis=-1)
    r = V.shape[-1]
    eye = jnp.eye(r, dtype=S.dtype)
    A = S + YtY[None] + (reg * count)[:, None, None] * eye
    return A, b, count


# --------------------------------------------------------------------------
# whole-iteration fusion: gather -> Gram -> ridge/YtY tail -> Cholesky solve
# --------------------------------------------------------------------------

def _gather_solve_kernel(cols_ref, aw_ref, bw_ref, cw_ref, YtY_ref, V_hbm,
                         x_ref, Vg, S, LT, bacc, cnt, sem, *, n_wc,
                         two_sided, panel, reg, jitter, depth=None):
    """One (row-tile, width-chunk) grid step of the fully fused half-step.

    Same DMA-gather + Gram front end as :func:`_gather_gram_kernel`, plus
    ``cw_ref [TN, WC]`` — the per-entry COUNT weights (explicit: the mask;
    implicit: ``pref·mask``), accumulated lane-uniform into ``cnt`` so the
    weighted ridge and the empty-row guard can apply in-kernel.  At the
    last width chunk the ridge/YtY/jitter tail (the ``solve_spd``
    pre-regularization, verbatim) is applied to the VMEM accumulator and
    the blocked Cholesky + substitution from ``tpu_als.ops.pallas_solve``
    produce ``x_ref [TN, r]`` — ``A`` is never written to HBM.
    """
    j = pl.program_id(1)
    tn, wc = cols_ref.shape
    r = S.shape[-1]
    n_e = tn * wc

    @pl.when(j == 0)
    def _init():
        S[:] = jnp.zeros_like(S)
        bacc[:] = jnp.zeros_like(bacc)
        cnt[:] = jnp.zeros_like(cnt)

    def _copy(e, slot):
        t = e // wc
        k = e % wc
        return rb.local_copy(
            V_hbm.at[cols_ref[t, k]], Vg.at[t, k], sem.at[slot])

    rb.pump(n_e, _copy, depth=depth)

    Vg_t = Vg[:]
    aw = aw_ref[:]
    Vw = Vg_t * aw[..., None]
    S[:] = S[:] + jax.lax.dot_general(
        Vw, Vw if two_sided else Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    bacc[:] = bacc[:] + jax.lax.dot_general(
        bw_ref[:], Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    cnt[:] = cnt[:] + jnp.sum(
        cw_ref[:], axis=1).astype(jnp.float32)[:, None]  # lane-uniform

    @pl.when(j == n_wc - 1)
    def _solve():
        from tpu_als.ops.pallas_solve import factorize, substitute

        ii = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 1)
        kk = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 2)
        diag = ii == kk
        c3 = cnt[:][:, None, :]                       # [TN, 1, r] broadcast
        # the reference builders compute ``reg * count`` in the weight
        # dtype, so a bf16 run's ridge is bf16-rounded; ``.astype`` pairs
        # get elided inside a jitted kernel (XLA excess precision), so the
        # rounding must be the explicit reduce_precision op — identity at
        # f32 (nmant=23), bf16-RN otherwise.  Without it the fused diagonal
        # sits ~0.4% of λ·n off the unfused path's at bf16.
        fi = jnp.finfo(cw_ref.dtype)
        reg_w = jnp.asarray(reg, cw_ref.dtype).astype(jnp.float32)
        ridge = jax.lax.reduce_precision(
            jax.lax.reduce_precision(c3, fi.nexp, fi.nmant) * reg_w,
            fi.nexp, fi.nmant)
        A = S[:] + YtY_ref[:][None].astype(jnp.float32)
        A = jnp.where(diag, A + ridge + jitter, A)
        # empty rows (count == 0): A := I so the factorization stays
        # finite; b is already 0 there so x = 0 — the solve_spd contract
        A = jnp.where(c3 <= 0.0, jnp.where(diag, 1.0 + jitter, 0.0), A)
        S[:] = A
        factorize(S, LT, tn=tn, r=r, panel=panel)
        x_ref[:] = substitute(LT, bacc[:], tn=tn, r=r, panel=panel)


def _tiles_solve(r_pad, w8, panel=16, max_wc=256, vmem_budget=1 << 17):
    """(TN, WC, W_PAD) for the fused-solve kernel: the gather kernel's
    tiling, shrunk further for the second [TN, r, r] scratch (LT) and
    capped so the factorization's scoped-VMEM stack (the ~20 live
    [TN, panel, r] temporaries at its deepest point — the pallas_fused
    round's measured overflow at rank 32 / TN 256) stays under the 16 MiB
    limit.  TN stays a sublane (8) multiple.

    ``vmem_budget`` is the factorization-stack element budget the cap is
    derived from (historically the hard-coded ``1 << 17``; now an
    autotuner knob).  A budget that forces the cap below the sublane
    minimum (TN < 8) is a degenerate grid, not a smaller tile — raise
    :class:`TileBudgetError` instead of silently clamping to 8 rows of a
    tile the factorization can't panel efficiently."""
    tn, wc, w_pad = _tiles(r_pad, w8, max_wc)
    while tn > 8 and tn * (2 * r_pad * r_pad + 3 * wc * r_pad) > (1 << 21):
        tn //= 2
    cap = int(vmem_budget) // (max(panel, 32) * r_pad)
    if cap < 8:
        raise TileBudgetError(
            f"vmem_budget {vmem_budget} caps the fused-solve row tile at "
            f"{cap} rows for r_pad={r_pad} panel={panel} — below the "
            f"8-row panel-efficiency knee; raise vmem_budget to at least "
            f"{8 * max(panel, 32) * r_pad} or shrink panel")
    tn = min(tn, cap)
    tn = max(8, (tn // 8) * 8)
    return tn, wc, w_pad


@functools.partial(jax.jit, static_argnames=("two_sided", "reg", "jitter",
                                             "panel", "max_wc",
                                             "vmem_budget", "depth",
                                             "interpret"))
def gather_solve(V, cols, aw, bw, cw, YtY=None, *, two_sided, reg,
                 jitter=DEFAULT_JITTER, panel=16, max_wc=256,
                 vmem_budget=1 << 17, depth=None, interpret=False):
    """Whole-iteration fused half-step core: DMA-gather ``V[cols]`` rows
    straight into VMEM, accumulate the weighted Gram, apply the ridge/YtY/
    empty-guard tail and solve — returns ``x [n, r]`` f32 only.  Neither
    the gathered rows nor the normal-equation matrices ever touch HBM.

    V [N, r] (any float dtype — bf16 halves the dominant HBM stream);
    cols [n, w] int32; aw/bw/cw [n, w] (A-side, b-side and count weights —
    the wrappers compute them with the reference builders' exact
    expressions).  ``reg``/``jitter`` are static floats baked into the
    kernel tail (the ``solve_spd`` pre-regularization, applied in VMEM).

    ``panel``/``max_wc``/``vmem_budget``/``depth`` are the autotuner's
    tiling knobs (perf.autotune); their defaults ARE the historical
    hand-picked constants, so an untuned call traces byte-identically to
    the pre-knob kernel.  ``depth=None`` keeps the substrate's own
    multiple-buffering depth (``ring_buffer.dma_slots``).
    """
    N, r = V.shape
    n, w = cols.shape
    r_pad = max(128, -(-r // 128) * 128)
    if r_pad % panel:
        raise ValueError(f"panel {panel} must divide padded rank {r_pad}")
    tn, wc, w_pad = _tiles_solve(r_pad, -(-w // 8) * 8, panel=panel,
                                 max_wc=max_wc, vmem_budget=vmem_budget)
    assert wc == w_pad or (wc % 128 == 0 and w_pad % wc == 0), (wc, w_pad)
    n_pad = -(-n // tn) * tn
    V_p = jnp.pad(V, ((0, 0), (0, r_pad - r)))
    # padding slots index row 0 with zero weight — contributes nothing;
    # padded batch rows have count 0 and hit the empty-row guard (x = 0)
    cols_p = jnp.pad(cols.astype(jnp.int32),
                     ((0, n_pad - n), (0, w_pad - w)))
    aw_p = jnp.pad(aw, ((0, n_pad - n), (0, w_pad - w)))
    bw_p = jnp.pad(bw, ((0, n_pad - n), (0, w_pad - w)))
    cw_p = jnp.pad(cw, ((0, n_pad - n), (0, w_pad - w)))
    YtY_p = (jnp.zeros((r_pad, r_pad), jnp.float32) if YtY is None
             else jnp.pad(YtY.astype(jnp.float32),
                          ((0, r_pad - r), (0, r_pad - r))))
    n_wc = w_pad // wc

    from tpu_als.perf.roofline import fused_solve_kernel_bytes

    db = jnp.dtype(V.dtype).itemsize
    eff_depth = (None if depth is None
                 else max(1, min(int(depth), rb.dma_slots(tn * wc))))
    kernel = functools.partial(
        _gather_solve_kernel, n_wc=n_wc, two_sided=two_sided, panel=panel,
        reg=float(reg), jitter=float(jitter), depth=eff_depth)
    x = pl.pallas_call(
        kernel,
        grid=(n_pad // tn, n_wc),
        in_specs=[
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r_pad, r_pad), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((tn, r_pad), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((tn, wc, r_pad), V.dtype),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((rb.dma_slots(tn * wc),)),
        ],
        # bytes = THE roofline fused-solve model (perf.roofline) at the
        # kernel's padded shapes — the fused_solve_audit contract
        # (analysis/contracts.py) extracts this from the traced jaxpr and
        # pins it to the closed form, the test_ne_audit.py pattern
        cost_estimate=pl.CostEstimate(
            flops=int(2.0 * n_pad * w_pad * r_pad * (r_pad + 1)
                      + n_pad * (r_pad ** 3 / 3 + 2 * r_pad ** 2)),
            bytes_accessed=fused_solve_kernel_bytes(
                n_pad * w_pad, n_pad, r_pad, db),
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(cols_p, aw_p, bw_p, cw_p, YtY_p, V_p)
    return x[:n, :r]


def gather_fused_solve_explicit(V, cols, vals, mask, reg, *,
                                jitter=DEFAULT_JITTER, panel=16, max_wc=256,
                                vmem_budget=1 << 17, depth=None,
                                interpret=False):
    """Fused-gather drop-in for ``normal_eq_explicit(V[cols], …)`` +
    ``solve_spd`` — returns ``x`` only; A/b/Vg never exist in HBM.  The
    weights are the reference builder's exact expressions; the ridge/
    empty-guard tail runs in-kernel with the same arithmetic.  The tiling
    knobs default to the historical constants (see :func:`gather_solve`)."""
    aw = mask
    bw = vals * mask
    cw = mask
    return gather_solve(V, cols, aw, bw, cw, two_sided=True,
                        reg=float(reg), jitter=jitter, panel=panel,
                        max_wc=max_wc, vmem_budget=vmem_budget, depth=depth,
                        interpret=interpret)


def gather_fused_solve_implicit(V, cols, vals, mask, reg, alpha, YtY, *,
                                jitter=DEFAULT_JITTER, panel=16, max_wc=256,
                                vmem_budget=1 << 17, depth=None,
                                interpret=False):
    """Fused-gather drop-in for ``normal_eq_implicit(V[cols], …)`` +
    ``solve_spd`` — returns ``x`` only.  Confidence/preference come from
    the shared :func:`implicit_weights`; the YtY + weighted-λ tail applies
    in-kernel to the VMEM accumulator."""
    conf_m1, pref = implicit_weights(vals, mask, alpha)
    aw = conf_m1
    bw = (1.0 + conf_m1) * pref * mask
    cw = pref * mask
    return gather_solve(V, cols, aw, bw, cw, YtY, two_sided=False,
                        reg=float(reg), jitter=jitter, panel=panel,
                        max_wc=max_wc, vmem_budget=vmem_budget, depth=depth,
                        interpret=interpret)


# --------------------------------------------------------------------------
# Fused-comm ring: the whole-iteration kernel UNDER shard_map, with the
# inter-chip factor rotation moved INSIDE the kernel as a
# make_async_remote_copy ring (solve_backend="gather_fused_ring").
# --------------------------------------------------------------------------

# collective_id for the ring kernel's barrier semaphore (compiled path
# only); any process-unique small int works — it namespaces the barrier
# across distinct collective kernels, and this repo has exactly one
_RING_COLLECTIVE_ID = 7


def _gather_solve_ring_kernel(cols_ref, aw_ref, bw_ref, cw_ref, YtY_ref,
                              V_hbm, x_ref, buf0, buf1, Vg, S, LT, bacc,
                              cnt, sem, send_sem, recv_sem, ack_sem, *,
                              axis_name, n_shards, n_wc, two_sided, panel,
                              reg, jitter, sync, depth=None):
    """One (row-tile, ring-step, width-chunk) grid cell of the fused-comm
    half-step.  Grid dims ``(i, t, j)``: per row tile ``i``, ring step
    ``t`` streams source shard ``(me - t) % S`` — held in ``V_hbm`` at
    ``t == 0`` and in the substrate's two HBM landing buffers
    ``buf0``/``buf1`` (parity ``t % 2``) afterwards — while the remote
    copy forwarding the held shard to the RIGHT neighbor is in flight
    under the same gather/Gram front end as :func:`_gather_solve_kernel`.
    The weight blocks arrive pre-rotated by the wrapper (leading axis
    ``t`` indexes the shard held at step ``t``), so the accumulation is
    just the fused-solve kernel's, once per shard; the ridge/YtY/
    empty-guard tail and the blocked Cholesky solve run at the last
    ``(t, j)`` cell exactly as in the single-device kernel — at
    ``n_shards == 1`` the ring degenerates to :func:`_gather_solve_kernel`
    bitwise (no sends trace at all).

    ``sync`` (compiled path only — interpret mode emulates devices
    sequentially, so it validates the schedule and the numerics but NOT
    race-freedom, and remote ``semaphore_signal`` is not implemented by
    the interpreter): two extra arms close the two real-hardware races of
    a 2-buffer ring —

    * **ack backpressure**: my step-``t`` send lands in the right
      neighbor's ``buf[t % 2]``, which that neighbor reads as ``cur`` at
      step ``t - 1``; a sender running one step ahead would clobber it.
      After consuming ``cur(t)`` each receiver signals its LEFT
      neighbor's ``ack_sem`` (steps ``t <= S - 3`` — one ack per gated
      send), and every send at ``t >= 1`` waits one ack first.
    * **pass barrier**: row tile ``i + 1`` restarts the ring at ``t = 0``
      targeting ``buf0`` while a slower neighbor may still be reading its
      pass-``i`` buffers; each pass opens with a neighbor barrier on the
      ``collective_id``-scoped barrier semaphore.
    """
    t = pl.program_id(1)
    j = pl.program_id(2)
    _, tn, wc = cols_ref.shape
    r = S.shape[-1]
    n_e = tn * wc

    if n_shards > 1:
        me = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(me + 1, n_shards)
        left = jax.lax.rem(me + n_shards - 1, n_shards)
        odd = jax.lax.rem(t, 2) == 1

        if sync:
            @pl.when((t == 0) & (j == 0))
            def _pass_barrier():
                bar = pltpu.get_barrier_semaphore()
                pltpu.semaphore_signal(
                    bar, 1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_signal(
                    bar, 1, device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_wait(bar, 2)

            @pl.when((t >= 1) & (t <= n_shards - 2) & (j == 0))
            def _ack_gate():
                pltpu.semaphore_wait(ack_sem, 1)

        # forward cur(t) to the right neighbor's landing buffer for step
        # t+1 (parity (t+1)%2 == destination buf[t%2]... the dst of step
        # t's send IS what the neighbor reads as cur(t+1)); three static
        # source variants because cur(t) is V_hbm / buf0 / buf1
        @pl.when((t == 0) & (j == 0))
        def _send_home():
            rb.remote_copy(V_hbm, buf0, send_sem, recv_sem, right).start()

        @pl.when((t >= 1) & (t <= n_shards - 2) & odd & (j == 0))
        def _send_odd():
            rb.remote_copy(buf0, buf1, send_sem, recv_sem, right).start()

        @pl.when((t >= 1) & (t <= n_shards - 2) & ~odd & (j == 0))
        def _send_even():
            rb.remote_copy(buf1, buf0, send_sem, recv_sem, right).start()

    @pl.when((t == 0) & (j == 0))
    def _init():
        S[:] = jnp.zeros_like(S)
        bacc[:] = jnp.zeros_like(bacc)
        cnt[:] = jnp.zeros_like(cnt)

    def _gather_from(src):
        def _copy(e, slot):
            tt = e // wc
            k = e % wc
            return rb.local_copy(
                src.at[cols_ref[0, tt, k]], Vg.at[tt, k], sem.at[slot])

        rb.pump(n_e, _copy, depth=depth)

    if n_shards == 1:
        _gather_from(V_hbm)
    else:
        @pl.when(t == 0)
        def _g_home():
            _gather_from(V_hbm)

        @pl.when((t >= 1) & odd)
        def _g_odd():
            _gather_from(buf0)

        @pl.when((t >= 1) & ~odd)
        def _g_even():
            _gather_from(buf1)

    Vg_t = Vg[:]
    aw = aw_ref[0]
    Vw = Vg_t * aw[..., None]
    S[:] = S[:] + jax.lax.dot_general(
        Vw, Vw if two_sided else Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    bacc[:] = bacc[:] + jax.lax.dot_general(
        bw_ref[0], Vg_t,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    cnt[:] = cnt[:] + jnp.sum(
        cw_ref[0], axis=1).astype(jnp.float32)[:, None]  # lane-uniform

    if n_shards > 1:
        @pl.when((t <= n_shards - 2) & (j == n_wc - 1))
        def _drain():
            # retire my send and the incoming shard (recv_sem is signaled
            # by the LEFT neighbor's symmetric send) before step t+1
            # reads the landing buffer; all variants share one shape, so
            # one canonical descriptor waits both semaphores
            d = rb.remote_copy(buf0, buf1, send_sem, recv_sem, right)
            d.wait_send()
            d.wait_recv()

        if sync:
            @pl.when((t <= n_shards - 3) & (j == n_wc - 1))
            def _ack_left():
                # cur(t) fully consumed (the last width chunk's pump has
                # retired) — free the left neighbor's next gated send
                pltpu.semaphore_signal(
                    ack_sem, 1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

    @pl.when((t == n_shards - 1) & (j == n_wc - 1))
    def _solve():
        from tpu_als.ops.pallas_solve import factorize, substitute

        ii = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 1)
        kk = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 2)
        diag = ii == kk
        c3 = cnt[:][:, None, :]                       # [TN, 1, r] broadcast
        # same explicit weight-dtype rounding as _gather_solve_kernel —
        # see the comment there (bitwise ridge parity with the reference
        # builders at bf16)
        fi = jnp.finfo(cw_ref.dtype)
        reg_w = jnp.asarray(reg, cw_ref.dtype).astype(jnp.float32)
        ridge = jax.lax.reduce_precision(
            jax.lax.reduce_precision(c3, fi.nexp, fi.nmant) * reg_w,
            fi.nexp, fi.nmant)
        A = S[:] + YtY_ref[:][None].astype(jnp.float32)
        A = jnp.where(diag, A + ridge + jitter, A)
        A = jnp.where(c3 <= 0.0, jnp.where(diag, 1.0 + jitter, 0.0), A)
        S[:] = A
        factorize(S, LT, tn=tn, r=r, panel=panel)
        x_ref[:] = substitute(LT, bacc[:], tn=tn, r=r, panel=panel)


def gather_solve_ring(V_shard, cols, aw, bw, cw, YtY=None, *, two_sided,
                      reg, axis_name=None, jitter=DEFAULT_JITTER, panel=16,
                      max_wc=256, vmem_budget=1 << 17, depth=None,
                      interpret=False):
    """Fused-comm half-step core (inside ``shard_map``): one kernel call
    per bucket runs the WHOLE distributed iteration — the inter-chip ring
    rotation (``make_async_remote_copy``), the DMA row gather, the Gram
    accumulation across all ``S`` source shards, and the ridge/YtY/solve
    tail — overlapped on the substrate's shared double buffers.  Returns
    ``x [n, r]`` f32; neither the rotated shards (beyond the two ``[per,
    r]`` HBM landing buffers) nor A/b ever exist as XLA values.

    V_shard [per, r]: THIS device's shard of the opposite factors (compute
    dtype).  cols/aw/bw/cw [S, n, w]: the RingCsr bucket's shard-local
    column ids and weights, source-shard-major and UNROTATED — the wrapper
    rotates the leading axis by ``(me - t) % S`` so block ``t`` always
    weighs the shard held at ring step ``t``.  ``axis_name`` names the
    mesh axis (required when ``S > 1``).

    Off-TPU pass ``interpret=True`` (the forced-host-device CPU mesh):
    numerics and schedule are exercised, the hardware-race arms (ack
    backpressure + pass barrier, see the kernel docstring) compile only
    on real meshes.
    """
    per, r = V_shard.shape
    n_shards, n, w = cols.shape
    r_pad = max(128, -(-r // 128) * 128)
    if r_pad % panel:
        raise ValueError(f"panel {panel} must divide padded rank {r_pad}")
    tn, wc, w_pad = _tiles_solve(r_pad, -(-w // 8) * 8, panel=panel,
                                 max_wc=max_wc, vmem_budget=vmem_budget)
    assert wc == w_pad or (wc % 128 == 0 and w_pad % wc == 0), (wc, w_pad)
    n_pad = -(-n // tn) * tn
    V_p = jnp.pad(V_shard, ((0, 0), (0, r_pad - r)))

    if n_shards > 1:
        if axis_name is None:
            raise ValueError("axis_name is required when n_shards > 1")
        me = jax.lax.axis_index(axis_name)
        src_order = jnp.mod(
            me - jnp.arange(n_shards, dtype=jnp.int32), n_shards)

        def _rot(x):
            return jnp.take(x, src_order, axis=0)
    else:
        def _rot(x):
            return x

    def _prep(x):
        # padding slots index row 0 with zero weight; padded batch rows
        # have count 0 and hit the empty-row guard (x = 0) — the
        # gather_solve contract
        return jnp.pad(_rot(x), ((0, 0), (0, n_pad - n), (0, w_pad - w)))

    cols_p = _prep(cols.astype(jnp.int32))
    aw_p = _prep(aw)
    bw_p = _prep(bw)
    cw_p = _prep(cw)
    YtY_p = (jnp.zeros((r_pad, r_pad), jnp.float32) if YtY is None
             else jnp.pad(YtY.astype(jnp.float32),
                          ((0, r_pad - r), (0, r_pad - r))))
    n_wc = w_pad // wc
    n_rt = n_pad // tn

    from tpu_als.perf.roofline import fused_ring_kernel_bytes, \
        ring_remote_bytes

    db = jnp.dtype(V_shard.dtype).itemsize
    sync = not interpret and n_shards > 1
    eff_depth = (None if depth is None
                 else max(1, min(int(depth), rb.dma_slots(tn * wc))))
    kernel = functools.partial(
        _gather_solve_ring_kernel, axis_name=axis_name, n_shards=n_shards,
        n_wc=n_wc, two_sided=two_sided, panel=panel, reg=float(reg),
        jitter=float(jitter), sync=sync, depth=eff_depth)
    x = pl.pallas_call(
        kernel,
        grid=(n_rt, n_shards, n_wc),
        in_specs=[
            pl.BlockSpec((1, tn, wc), lambda i, t, j: (t, i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, tn, wc), lambda i, t, j: (t, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn, wc), lambda i, t, j: (t, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn, wc), lambda i, t, j: (t, i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r_pad, r_pad), lambda i, t, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((tn, r_pad), lambda i, t, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        scratch_shapes=[
            pltpu.ANY((per, r_pad), V_shard.dtype),   # buf0 (HBM landing)
            pltpu.ANY((per, r_pad), V_shard.dtype),   # buf1
            pltpu.VMEM((tn, wc, r_pad), V_shard.dtype),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.VMEM((tn, r_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((rb.dma_slots(tn * wc),)),
            pltpu.SemaphoreType.DMA,      # send
            pltpu.SemaphoreType.DMA,      # recv
            pltpu.SemaphoreType.REGULAR,  # ack (sync arm only)
        ],
        # bytes = THE roofline fused-comm model (perf.roofline): the
        # fused-solve stream plus the in-kernel remote-DMA ring payload —
        # the extended comm_audit contract (analysis/contracts.py)
        # extracts both from the traced kernel and pins them to the
        # closed forms
        cost_estimate=pl.CostEstimate(
            flops=int(2.0 * n_pad * n_shards * w_pad * r_pad * (r_pad + 1)
                      + n_pad * (r_pad ** 3 / 3 + 2 * r_pad ** 2)),
            bytes_accessed=fused_ring_kernel_bytes(
                n_pad * n_shards * w_pad, n_pad, r_pad, db,
                ring_remote_bytes(n_rt, n_shards, per, r_pad, db)),
            transcendentals=n_pad * r_pad,
        ),
        compiler_params=(
            pltpu.TPUCompilerParams(collective_id=_RING_COLLECTIVE_ID)
            if sync else None),
        interpret=interpret,
    )(cols_p, aw_p, bw_p, cw_p, YtY_p, V_p)
    return x[:n, :r]


def gather_fused_ring_explicit(V_shard, cols, vals, mask, reg, *,
                               axis_name=None, jitter=DEFAULT_JITTER,
                               panel=16, max_wc=256, vmem_budget=1 << 17,
                               depth=None, interpret=False):
    """Fused-comm drop-in for one explicit ring half-step: the reference
    builders' exact weight expressions over the UNROTATED [S, n, w] bucket
    arrays, then one :func:`gather_solve_ring` call.  At ``S == 1`` this
    is :func:`gather_fused_solve_explicit` bitwise (same kernel body, no
    sends)."""
    aw = mask
    bw = vals * mask
    cw = mask
    return gather_solve_ring(V_shard, cols, aw, bw, cw, two_sided=True,
                             reg=float(reg), axis_name=axis_name,
                             jitter=jitter, panel=panel, max_wc=max_wc,
                             vmem_budget=vmem_budget, depth=depth,
                             interpret=interpret)


def gather_fused_ring_implicit(V_shard, cols, vals, mask, reg, alpha, YtY,
                               *, axis_name=None, jitter=DEFAULT_JITTER,
                               panel=16, max_wc=256, vmem_budget=1 << 17,
                               depth=None, interpret=False):
    """Fused-comm drop-in for one implicit ring half-step — weights from
    the shared :func:`implicit_weights`, YtY + weighted-λ tail in-kernel."""
    conf_m1, pref = implicit_weights(vals, mask, alpha)
    aw = conf_m1
    bw = (1.0 + conf_m1) * pref * mask
    cw = pref * mask
    return gather_solve_ring(V_shard, cols, aw, bw, cw, YtY,
                             two_sided=False, reg=float(reg),
                             axis_name=axis_name, jitter=jitter,
                             panel=panel, max_wc=max_wc,
                             vmem_budget=vmem_budget, depth=depth,
                             interpret=interpret)


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_gather_ne")
_FASTER = _probe_cache("pallas_gather_ne_speed")


def available(rank=128, compute_dtype="float32"):
    """Compile-and-validate probe, cached per (padded rank, dtype) — the
    probe_kernel contract (off-TPU → False; a Mosaic rejection caches
    False so callers stay on the einsum path).  Validates BOTH kernel
    variants (explicit/two-sided and implicit/one-sided compile different
    bodies) against the unfused builders on a multi-row-tile,
    multi-width-chunk instance, so a miscompile producing finite-but-wrong
    values also fails."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = max(128, -(-rank // 128) * 128)
    cdt = str(compute_dtype)

    def probe():
        import numpy as np

        from tpu_als.ops.solve import normal_eq_explicit, normal_eq_implicit

        dt = jnp.dtype(cdt)
        # >= 2 row tiles and >= 2 width chunks: exercise the accumulator
        # revisiting across the inner grid dim and the DMA ring reuse
        w = 256
        while True:
            tn, wc, w_pad = _tiles(r_pad, w)
            if w_pad // wc >= 2:
                break
            w *= 2
        n, N = 2 * tn, 3 * tn
        rng = np.random.default_rng(0)
        V = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32)
                        / np.sqrt(rank)).astype(dt)
        cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
        vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
        mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))
        tol = dict(atol=1e-3, rtol=1e-2)
        A, b, c = gather_normal_eq_explicit(
            V, cols, vals.astype(dt), mask.astype(dt), 0.1)
        Ar, br, cr = normal_eq_explicit(
            V[cols], vals.astype(dt), mask.astype(dt), 0.1)
        A.block_until_ready()
        if not (np.allclose(np.asarray(A), np.asarray(Ar), **tol)
                and np.allclose(np.asarray(b), np.asarray(br), **tol)):
            return False
        YtY = jnp.asarray(rng.normal(size=(rank, rank)).astype(np.float32))
        YtY = YtY @ YtY.T / rank
        Ai, bi, ci = gather_normal_eq_implicit(
            V, cols, vals.astype(dt), mask.astype(dt), 0.1, 4.0, YtY)
        Air, bir, cir = normal_eq_implicit(
            V[cols], vals.astype(dt), mask.astype(dt), 0.1, 4.0, YtY)
        Ai.block_until_ready()
        return bool(np.allclose(np.asarray(Ai), np.asarray(Air), **tol)
                    and np.allclose(np.asarray(bi), np.asarray(bir), **tol))

    return probe_kernel(_AVAILABLE, (r_pad, cdt), probe)


def faster_than_einsum(rank=128, compute_dtype="float32", n=2048, w=256,
                       reps=3):
    """Timing probe: True only when the fused kernel BEATS the XLA
    gather+einsum build on a representative bucket — the auto path
    selects the kernel on this outcome, never on availability alone
    (the fused_pallas lesson: available ≠ faster).  Cached per process
    via probe_kernel (off-TPU → False)."""
    from tpu_als.utils.platform import fence, probe_kernel

    r_pad = max(128, -(-rank // 128) * 128)
    cdt = str(compute_dtype)

    def probe():
        import time

        import numpy as np

        from tpu_als.ops.solve import normal_eq_explicit

        if not available(rank, cdt):
            return False
        dt = jnp.dtype(cdt)
        rng = np.random.default_rng(0)
        N = 4 * n
        V = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32)
                        / np.sqrt(rank)).astype(dt)
        cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
        vals = jnp.asarray(rng.normal(size=(n, w)).astype(dt))
        mask = jnp.asarray((rng.random((n, w)) < 0.9).astype(dt))

        @jax.jit
        def fused(V, cols, vals, mask):
            return gather_normal_eq_explicit(V, cols, vals, mask, 0.1)

        @jax.jit
        def einsum(V, cols, vals, mask):
            return normal_eq_explicit(V[cols], vals, mask, 0.1)

        def best(f):
            fence(f(V, cols, vals, mask)[0])  # compile + warm
            t = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fence(f(V, cols, vals, mask)[0])
                t.append(time.perf_counter() - t0)
            return min(t)

        return best(fused) < best(einsum)

    return probe_kernel(_FASTER, ("speed", r_pad, cdt, n, w), probe)


_SOLVE_AVAILABLE = _probe_cache("pallas_gather_solve")
_SOLVE_FASTER = _probe_cache("pallas_gather_solve_speed")


def solve_available(rank=128, compute_dtype="float32"):
    """Compile-and-validate probe for the whole-iteration fused kernel,
    cached per (padded rank, dtype) — same contract as :func:`available`.
    Validates BOTH variants (explicit and implicit compile different
    bodies) against the unfused builders + ``solve_spd`` on a
    multi-row-tile, multi-width-chunk instance."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = max(128, -(-rank // 128) * 128)
    cdt = str(compute_dtype)

    def probe():
        import numpy as np

        from tpu_als.ops.solve import (normal_eq_explicit,
                                       normal_eq_implicit, solve_spd)

        dt = jnp.dtype(cdt)
        w = 256
        while True:
            tn, wc, w_pad = _tiles_solve(r_pad, w)
            if w_pad // wc >= 2:
                break
            w *= 2
        n, N = 2 * tn, 3 * tn
        rng = np.random.default_rng(0)
        V = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32)
                        / np.sqrt(rank)).astype(dt)
        cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
        vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
        mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))
        tol = dict(atol=1e-3, rtol=1e-2)
        x = gather_fused_solve_explicit(
            V, cols, vals.astype(dt), mask.astype(dt), 0.1)
        A, b, c = normal_eq_explicit(
            V[cols], vals.astype(dt), mask.astype(dt), 0.1)
        ref = solve_spd(A, b, c, backend="xla")
        x.block_until_ready()
        if not np.allclose(np.asarray(x), np.asarray(ref), **tol):
            return False
        YtY = jnp.asarray(rng.normal(size=(rank, rank)).astype(np.float32))
        YtY = YtY @ YtY.T / rank
        xi = gather_fused_solve_implicit(
            V, cols, vals.astype(dt), mask.astype(dt), 0.1, 4.0, YtY)
        Ai, bi, ci = normal_eq_implicit(
            V[cols], vals.astype(dt), mask.astype(dt), 0.1, 4.0, YtY)
        refi = solve_spd(Ai, bi, ci, backend="xla")
        xi.block_until_ready()
        return bool(np.allclose(np.asarray(xi), np.asarray(refi), **tol))

    return probe_kernel(_SOLVE_AVAILABLE, (r_pad, cdt), probe)


def solve_faster_than_unfused(rank=128, compute_dtype="float32", n=2048,
                              w=256, reps=3):
    """Timing probe: True only when the whole-iteration fused kernel
    BEATS the current best unfused composition (the gather-Gram kernel
    when IT probes faster, else the XLA gather+einsum, followed by
    ``solve_spd(backend='auto')``) on a representative bucket — the
    fused_pallas lesson (available ≠ faster) applied to the deeper
    fusion.  Cached per process via probe_kernel (off-TPU → False)."""
    from tpu_als.utils.platform import fence, probe_kernel

    r_pad = max(128, -(-rank // 128) * 128)
    cdt = str(compute_dtype)

    def probe():
        import time

        import numpy as np

        from tpu_als.ops.solve import normal_eq_explicit, solve_spd

        if not solve_available(rank, cdt):
            return False
        dt = jnp.dtype(cdt)
        rng = np.random.default_rng(0)
        N = 4 * n
        V = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32)
                        / np.sqrt(rank)).astype(dt)
        cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
        vals = jnp.asarray(rng.normal(size=(n, w)).astype(dt))
        mask = jnp.asarray((rng.random((n, w)) < 0.9).astype(dt))
        use_gather_ne = faster_than_einsum(rank, cdt, n=n, w=w, reps=reps)

        @jax.jit
        def fused(V, cols, vals, mask):
            return gather_fused_solve_explicit(V, cols, vals, mask, 0.1)

        @jax.jit
        def unfused(V, cols, vals, mask):
            if use_gather_ne:
                A, b, c = gather_normal_eq_explicit(V, cols, vals, mask,
                                                    0.1)
            else:
                A, b, c = normal_eq_explicit(V[cols], vals, mask, 0.1)
            return solve_spd(A, b, c)

        def best(f):
            fence(f(V, cols, vals, mask))  # compile + warm
            t = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fence(f(V, cols, vals, mask))
                t.append(time.perf_counter() - t0)
            return min(t)

        return best(fused) < best(unfused)

    return probe_kernel(_SOLVE_FASTER, ("speed", r_pad, cdt, n, w), probe)


_RING_AVAILABLE = _probe_cache("pallas_gather_ring")


def ring_available(rank=128, compute_dtype="float32", n_shards=None):
    """Compile-and-validate probe for the fused-comm ring kernel ON THE
    LIVE MESH, cached per (padded rank, dtype, n_shards) — the gate
    ``trainer.make_ring_step`` consults before adopting
    ``solve_backend='gather_fused_ring'`` on hardware.

    Unlike the single-device probes this one executes a COLLECTIVE (the
    in-kernel remote-DMA ring under ``shard_map`` over the first
    ``n_shards`` local devices), so its verdict is only meaningful for
    the mesh it ran on — the cache key carries ``n_shards``, and the
    planner's persistence layer (utils.platform.snapshot_probes) may bank
    it like any other probe because the CONSUMER re-validates shape: a
    banked verdict for a different shard count is a cache miss, never a
    steer.  Validates explicit AND implicit variants against the
    single-device whole-iteration kernel on the concatenated global
    column space.  Off-TPU → False (the CPU path doesn't need it: the
    interpret-mode kernel is dispatched unconditionally there).
    """
    from tpu_als.utils.platform import probe_kernel

    if n_shards is None:
        n_shards = jax.device_count()
    r_pad = max(128, -(-rank // 128) * 128)
    cdt = str(compute_dtype)

    def probe():
        import functools as ft

        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from tpu_als.parallel.mesh import shard_map

        if jax.device_count() < n_shards:
            return False
        S = n_shards
        ax = "ring_probe"
        mesh = Mesh(np.array(jax.devices()[:S]), (ax,))
        dt = jnp.dtype(cdt)
        rng = np.random.default_rng(0)
        tn, _, _ = _tiles_solve(r_pad, 16)
        per, n, w = 64, tn + 8, 16  # ragged: one partial kernel row tile
        V = jnp.asarray(rng.normal(size=(S * per, rank))
                        .astype(np.float32) / np.sqrt(rank)).astype(dt)
        cols = rng.integers(0, per, size=(S, S, n, w)).astype(np.int32)
        vals = rng.normal(size=(S, S, n, w)).astype(np.float32)
        mask = (rng.random(size=(S, S, n, w)) < 0.8).astype(np.float32)
        YtY = np.asarray(V.astype(jnp.float32).T @ V.astype(jnp.float32))

        @jax.jit
        @ft.partial(shard_map, mesh=mesh,
                    in_specs=(P(ax), P(ax), P(ax), P(ax), P()),
                    out_specs=(P(ax), P(ax)), check_vma=False)
        def run(V_shard, c, v, m, yty):
            xe = gather_fused_ring_explicit(
                V_shard, c[0], v[0].astype(dt), m[0].astype(dt), 0.1,
                axis_name=ax)
            xi = gather_fused_ring_implicit(
                V_shard, c[0], v[0].astype(dt), m[0].astype(dt), 0.1,
                4.0, yty, axis_name=ax)
            return xe[None], xi[None]

        xe, xi = run(V, jnp.asarray(cols), jnp.asarray(vals),
                     jnp.asarray(mask), jnp.asarray(YtY))
        xe.block_until_ready()
        xe, xi = np.asarray(xe), np.asarray(xi)
        tol = dict(atol=1e-3, rtol=1e-2)
        for d in range(S):
            gc = np.concatenate([cols[d, s] + s * per for s in range(S)],
                                axis=1)
            gv = np.concatenate([vals[d, s] for s in range(S)], axis=1)
            gm = np.concatenate([mask[d, s] for s in range(S)], axis=1)
            re_ = gather_fused_solve_explicit(
                V, jnp.asarray(gc), jnp.asarray(gv).astype(dt),
                jnp.asarray(gm).astype(dt), 0.1)
            ri = gather_fused_solve_implicit(
                V, jnp.asarray(gc), jnp.asarray(gv).astype(dt),
                jnp.asarray(gm).astype(dt), 0.1, 4.0, jnp.asarray(YtY))
            if not (np.allclose(xe[d], np.asarray(re_), **tol)
                    and np.allclose(xi[d], np.asarray(ri), **tol)):
                return False
        return True

    return probe_kernel(_RING_AVAILABLE, (r_pad, cdt, n_shards), probe)
