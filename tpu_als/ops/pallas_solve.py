"""Pallas TPU kernel: batched SPD solve (blocked Cholesky + substitution).

The ALS half-step ends with x = A⁻¹b for hundreds of thousands of small SPD
systems (rank×rank, one per entity).  XLA lowers ``jnp.linalg.cholesky`` /
``triangular_solve`` on TPU as column-sequential panel algorithms over HBM
operands — for [221k, 128, 128] batches that serial chain dominates the
whole training iteration.  This kernel keeps a tile of matrices resident in
VMEM and factorizes them there:

  * right-looking blocked Cholesky, panel width P: the within-panel rank-1
    updates are VPU work on a [TN, r, P] panel block, the trailing update is
    ONE batched [TN,r,P]x[TN,P,r] MXU contraction per panel;
  * forward/backward substitution vectorized over the batch dim.

Everything is masked static-shape arithmetic — no data-dependent control
flow.  Replaces the per-entity LAPACK ``dppsv`` of the reference stack
(Spark MLlib ``CholeskySolver``, SURVEY.md §2.B5/C1) at the opposite end of
the batching spectrum: one kernel, every entity at once.

Contract matches tpu_als.ops.solve.solve_spd: caller pre-regularizes A
(jitter + empty-row identity guard); rows with b = 0 solve to x = 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _chol_solve_kernel(A_ref, b_ref, x_ref, S, *, r, panel):
    """One batch tile: factorize A (in VMEM scratch S) and solve.

    A_ref [TN, r, r]; b_ref [TN, r]; x_ref [TN, r]; S [TN, r, r] scratch.
    """
    S[:] = A_ref[:]
    tn = A_ref.shape[0]
    row_i = jax.lax.broadcasted_iota(jnp.int32, (tn, r, 1), 1)
    prow = jax.lax.broadcasted_iota(jnp.int32, (tn, r, panel), 1)
    pcol = jax.lax.broadcasted_iota(jnp.int32, (tn, r, panel), 2)

    def do_panel(pi, _):
        p = pi * panel
        blk = S[:, :, pl.ds(p * 1, panel)]  # [TN, r, panel]

        # [r, P] selector picking rows p..p+P-1 (one-hot matmul: dynamic
        # lane-offset slicing is not a thing on TPU, a tiny MXU dot is)
        sel = (
            jax.lax.broadcasted_iota(jnp.int32, (r, panel), 0)
            == p + jax.lax.broadcasted_iota(jnp.int32, (r, panel), 1)
        ).astype(jnp.float32)

        def do_col(jj, blk):
            j = p + jj
            onecol = pcol == jj
            onerow_j = prow == j
            # d = sqrt(A[j,j]); column j scaled by 1/d, zeroed above row j
            col = jnp.sum(jnp.where(onecol, blk, 0.0), axis=2)  # [TN, r]
            d2 = jnp.sum(jnp.where(onerow_j[:, :, 0:1] & onecol, blk, 0.0),
                         axis=(1, 2))  # [TN]
            inv = jax.lax.rsqrt(jnp.maximum(d2, 1e-30))  # [TN]
            ncol = col * inv[:, None]
            ncol = jnp.where(row_i[:, :, 0] >= j, ncol, 0.0)
            # rank-1 update of the panel columns right of j (VPU):
            #   blk[:, :, k] -= ncol * L[p+k, j],  L[p+k, j] = ncol[p:p+P]
            ncol_panel = jnp.dot(ncol, sel,
                                 preferred_element_type=jnp.float32)
            upd = ncol[:, :, None] * ncol_panel[:, None, :]
            blk = jnp.where(pcol > jj, blk - upd, blk)
            # write the finished column back into the panel block
            blk = jnp.where(onecol, ncol[:, :, None], blk)
            return blk

        blk = jax.lax.fori_loop(0, panel, do_col, blk)
        # L panel, zeroed above the diagonal (per-column global row >= col)
        Lp = jnp.where(prow >= p + pcol, blk, 0.0)
        S[:, :, pl.ds(p * 1, panel)] = Lp
        # trailing update (MXU): S[:, :, k] -= sum_j Lp[:, :, j] Lp[:, k, j]
        # for k >= p+panel (mask; rows above the diagonal become garbage the
        # later panels never read)
        upd = jax.lax.dot_general(
            Lp, Lp, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [TN, r, r]
        col_k = jax.lax.broadcasted_iota(jnp.int32, (tn, r, r), 2)
        S[:] = jnp.where(col_k >= p + panel, S[:] - upd, S[:])
        return 0

    jax.lax.fori_loop(0, r // panel, do_panel, 0)

    # ---- forward substitution: L y = b ----
    ridx = jax.lax.broadcasted_iota(jnp.int32, (tn, r), 1)

    def fwd(j, res):
        onej = ridx == j
        colj = S[:, :, pl.ds(j * 1, 1)][:, :, 0]  # [TN, r] (zero above j)
        d = jnp.sum(jnp.where(onej, colj, 0.0), axis=1)  # L[j,j]
        yj = jnp.sum(jnp.where(onej, res, 0.0), axis=1) / d
        # subtract yj * L[:, j] from the remaining rows (> j)
        res = jnp.where(ridx > j, res - yj[:, None] * colj, res)
        # store yj at position j
        res = jnp.where(onej, yj[:, None], res)
        return res

    y = jax.lax.fori_loop(0, r, fwd, b_ref[:])

    # ---- backward substitution: Lᵀ x = y ----
    def bwd(t, res):
        j = r - 1 - t
        onej = ridx == j
        colj = S[:, :, pl.ds(j * 1, 1)][:, :, 0]
        d = jnp.sum(jnp.where(onej, colj, 0.0), axis=1)
        xj = jnp.sum(jnp.where(onej, res, 0.0), axis=1) / d
        # (Lᵀ)[i, j] = L[j, i] → subtract xj * L[j, :] from rows < j
        rowj = jnp.sum(
            jnp.where(row_i == j, S[:], 0.0), axis=1
        )  # [TN, r] row j of L (zero right of j)
        res = jnp.where(ridx < j, res - xj[:, None] * rowj, res)
        res = jnp.where(onej, xj[:, None], res)
        return res

    x_ref[:] = jax.lax.fori_loop(0, r, bwd, y)


def _tile_n(r_pad, budget_elems=1 << 21):
    """Batch-tile so the [TN, r, r] scratch stays within ~8 MB of VMEM."""
    tn = max(8, budget_elems // (r_pad * r_pad))
    return 1 << (tn.bit_length() - 1)


@functools.partial(jax.jit, static_argnames=("panel", "interpret"))
def spd_solve_pallas(A, b, panel=32, interpret=False):
    """Batched SPD solve x = A⁻¹ b.  A [N, r, r] f32, b [N, r] f32.

    Caller must pre-regularize A (SPD with jitter; identity for empty rows)
    — same contract as the XLA path in tpu_als.ops.solve.solve_spd.
    """
    N, r = b.shape
    r_pad = max(panel, -(-r // panel) * panel)
    tn = _tile_n(r_pad)
    n_pad = -(-N // tn) * tn
    eye_tail = jnp.eye(r_pad, dtype=jnp.float32)[None, :, :]
    Ap = jnp.pad(A, ((0, n_pad - N), (0, r_pad - r), (0, r_pad - r)))
    # padded diagonal (both the rank padding and the batch padding) = I so
    # the factorization stays finite; padded b = 0 → padded x = 0
    diag_fix = jnp.where(
        (jax.lax.broadcasted_iota(jnp.int32, (1, r_pad, r_pad), 1) >= r)
        | (jnp.arange(n_pad)[:, None, None] >= N),
        eye_tail, 0.0,
    )
    Ap = Ap + diag_fix
    bp = jnp.pad(b, ((0, n_pad - N), (0, r_pad - r)))

    kernel = functools.partial(_chol_solve_kernel, r=r_pad, panel=panel)
    x = pl.pallas_call(
        kernel,
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((tn, r_pad, r_pad), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, r_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tn, r_pad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tn, r_pad, r_pad), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(n_pad * (r_pad ** 3 / 3 + 2 * r_pad ** 2)),
            bytes_accessed=(n_pad * r_pad * r_pad + 2 * n_pad * r_pad) * 4,
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(Ap, bp)
    return x[:N, :r]
