"""Pallas TPU kernel: batched SPD solve (blocked Cholesky + substitution).

The ALS half-step ends with x = A⁻¹b for hundreds of thousands of small SPD
systems (rank×rank, one per entity).  XLA lowers ``jnp.linalg.cholesky`` /
``triangular_solve`` on TPU as column-sequential panel algorithms over HBM
operands — for [221k, 128, 128] batches that serial chain dominates the
whole training iteration.  This kernel keeps a tile of matrices resident in
VMEM and factorizes them there.

Mosaic (the Pallas TPU compiler) cannot slice the lane (last) dimension at
offsets that are not multiples of 128, so the kernel never slices lanes:

  * panels are **rows** of the working matrix (sublane dimension, static
    offsets from a Python-unrolled panel loop) — valid because right-looking
    Cholesky keeps the trailing submatrix symmetric, so a column panel of
    the trailing block equals its row panel;
  * single columns are extracted with iota masks + reductions, and panel
    (lane-window) extraction uses one-hot selector matmuls on the MXU;
  * the factor is written to a second scratch as **Lᵀ** (column j of L
    stored as row j), so forward/backward substitution also read rows.

Within-panel rank-1 updates are VPU work on a [TN, P, r] row panel; the
trailing update is ONE batched [TN,P,r]ᵀ[TN,P,r] MXU contraction per panel.
Everything is masked static-shape arithmetic — no data-dependent control
flow.  Replaces the per-entity LAPACK ``dppsv`` of the reference stack
(Spark MLlib ``CholeskySolver``, SURVEY.md §2.B5/C1) at the opposite end of
the batching spectrum: one kernel, every entity at once.

Contract matches tpu_als.ops.solve.solve_spd: caller pre-regularizes A
(jitter + empty-row identity guard); rows with b = 0 solve to x = 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# All dots inside the factorization/substitution run at HIGHEST precision:
# the MXU's DEFAULT f32 path is a single bf16 pass (~4e-3 relative), and
# that error COMPOUNDS through the Cholesky recurrence — measured ~1e-2
# relative solve error on well-conditioned rank-128 systems, which is what
# made available()'s comparison against the XLA lowering fail on real
# hardware in round 1.  HIGHEST (multi-pass f32 emulation) restores ~1e-6.
# The dots here are a small fraction of kernel time (the column loops are
# VPU-bound), so the cost is negligible.
_PREC = jax.lax.Precision.HIGHEST


def _chol_solve_kernel(A_ref, b_ref, x_ref, S, LT, *, r, panel):
    """One batch tile: factorize A and solve.

    A_ref [TN, r, r]; b_ref [TN, r]; x_ref [TN, r].
    S  [TN, r, r] scratch: the symmetric trailing matrix (rows above the
       current panel become stale garbage — never read again).
    LT [TN, r, r] scratch: LT[t, j, i] = L[i, j] (column j of L on row j).
    """
    S[:] = A_ref[:]
    tn = A_ref.shape[0]
    factorize(S, LT, tn=tn, r=r, panel=panel)
    x_ref[:] = substitute(LT, b_ref[:], tn=tn, r=r, panel=panel)


def factorize(S, LT, *, tn, r, panel):
    """In-VMEM blocked Cholesky: S (symmetric input, destroyed) → LT holds
    Lᵀ.  Shared by the standalone solver and the fused normal-eq kernel."""
    n_panels = r // panel

    lane = jax.lax.broadcasted_iota(jnp.int32, (tn, r), 1)          # [TN, r]
    sub_p = jax.lax.broadcasted_iota(jnp.int32, (tn, panel, r), 1)  # k index
    lane_p = jax.lax.broadcasted_iota(jnp.int32, (tn, panel, r), 2)
    sel_r = jax.lax.broadcasted_iota(jnp.int32, (r, panel), 0)
    sel_p = jax.lax.broadcasted_iota(jnp.int32, (r, panel), 1)

    def selector(p):
        """One-hot [r, P]: sel[c, k] = (c == p + k).  Static p."""
        return (sel_r == p + sel_p).astype(jnp.float32)

    # ---- factorization: right-looking blocked Cholesky ----
    for pi in range(n_panels):
        p = pi * panel
        sel = selector(p)
        # row panel of the (symmetric) trailing matrix == column panel,
        # transposed: blkT[t, k, i] = A_trail[i, p+k]
        blkT = S[:, p:p + panel, :]

        def do_col(jj, blkT, p=p, sel=sel):
            j = p + jj
            col = jnp.sum(jnp.where(sub_p == jj, blkT, 0.0), axis=1)  # [TN,r]
            d2 = jnp.sum(jnp.where(lane == j, col, 0.0), axis=1)
            inv = jax.lax.rsqrt(jnp.maximum(d2, 1e-30))
            ncol = jnp.where(lane >= j, col * inv[:, None], 0.0)
            # ncol at the panel's own lanes, via one-hot MXU dot
            npanel = jnp.dot(ncol, sel, preferred_element_type=jnp.float32, precision=_PREC)
            upd = npanel[:, :, None] * ncol[:, None, :]       # [TN, P, r]
            blkT = jnp.where(sub_p > jj, blkT - upd, blkT)
            blkT = jnp.where(sub_p == jj, ncol[:, None, :], blkT)
            return blkT

        blkT = jax.lax.fori_loop(0, panel, do_col, blkT)
        # zero above the diagonal: L[i, p+k] lives at lane i >= p+k
        LpT = jnp.where(lane_p >= p + sub_p, blkT, 0.0)
        LT[:, p:p + panel, :] = LpT
        if pi + 1 < n_panels:
            # trailing update (MXU): S[t,i,i'] -= Σ_k L[i,p+k] L[i',p+k]
            upd = jax.lax.dot_general(
                LpT, LpT, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32, precision=_PREC,
            )  # [TN, r, r]
            S[:] = S[:] - upd


def substitute(LT, b, *, tn, r, panel):
    """Solve L Lᵀ x = b given LT (= Lᵀ) in VMEM; returns x [TN, r]."""
    n_panels = r // panel

    lane = jax.lax.broadcasted_iota(jnp.int32, (tn, r), 1)          # [TN, r]
    aidx = jax.lax.broadcasted_iota(jnp.int32, (tn, panel), 1)      # [TN, P]
    g_sub = jax.lax.broadcasted_iota(jnp.int32, (tn, panel, panel), 1)
    g_lane = jax.lax.broadcasted_iota(jnp.int32, (tn, panel, panel), 2)
    sel_r = jax.lax.broadcasted_iota(jnp.int32, (r, panel), 0)
    sel_p = jax.lax.broadcasted_iota(jnp.int32, (r, panel), 1)

    def selector(p):
        return (sel_r == p + sel_p).astype(jnp.float32)

    # ---- forward substitution: L y = b (panel-blocked, row reads) ----
    res = b
    for pi in range(n_panels):
        p = pi * panel
        sel = selector(p)
        LpT = LT[:, p:p + panel, :]             # LpT[t,k,i] = L[i, p+k]
        # diag block via one-hot MXU: G[t,k,a] = L[p+a, p+k]
        G = jnp.dot(
            LpT.reshape(tn * panel, r), sel,
            preferred_element_type=jnp.float32, precision=_PREC,
        ).reshape(tn, panel, panel)
        rhs = jnp.dot(res, sel, preferred_element_type=jnp.float32, precision=_PREC)  # [TN,P]

        def fwd_col(jj, rhs, G=G):
            # column jj of the diag block, indexed by row a: G[t, jj, a]
            colj = jnp.sum(jnp.where(g_sub == jj, G, 0.0), axis=1)
            d = jnp.sum(jnp.where(aidx == jj, colj, 0.0), axis=1)
            yj = jnp.sum(jnp.where(aidx == jj, rhs, 0.0), axis=1) / d
            rhs = jnp.where(aidx > jj, rhs - yj[:, None] * colj, rhs)
            rhs = jnp.where(aidx == jj, yj[:, None], rhs)
            return rhs

        y_p = jax.lax.fori_loop(0, panel, fwd_col, rhs)     # [TN, P]
        # apply to lanes below the panel: upd[t,i] = Σ_k y[t,k] L[i, p+k]
        upd = jnp.sum(y_p[:, :, None] * LpT, axis=1)        # [TN, r]
        y_full = jnp.dot(y_p, sel.T, preferred_element_type=jnp.float32, precision=_PREC)
        res = jnp.where(lane >= p + panel, res - upd, res)
        res = jnp.where((lane >= p) & (lane < p + panel), y_full, res)

    # ---- backward substitution: Lᵀ x = y (LT rows ARE Lᵀ rows) ----
    for pi in range(n_panels - 1, -1, -1):
        p = pi * panel
        sel = selector(p)
        UpT = LT[:, p:p + panel, :]             # UpT[t,k,i] = Lᵀ[p+k, i]
        # contributions of already-solved lanes (>= p+P)
        xm = jnp.where(lane >= p + panel, res, 0.0)
        contrib = jnp.sum(UpT * xm[:, None, :], axis=2)     # [TN, P]
        rhs = jnp.dot(res, sel, preferred_element_type=jnp.float32, precision=_PREC) - contrib
        G = jnp.dot(
            UpT.reshape(tn * panel, r), sel,
            preferred_element_type=jnp.float32, precision=_PREC,
        ).reshape(tn, panel, panel)             # G[t,k,a] = Lᵀ[p+k, p+a]

        def bwd_col(tt, rhs, G=G):
            jj = panel - 1 - tt
            # column jj of the diag block, indexed by row k: G[t, k, jj]
            colj = jnp.sum(jnp.where(g_lane == jj, G, 0.0), axis=2)
            d = jnp.sum(jnp.where(aidx == jj, colj, 0.0), axis=1)
            xj = jnp.sum(jnp.where(aidx == jj, rhs, 0.0), axis=1) / d
            rhs = jnp.where(aidx < jj, rhs - xj[:, None] * colj, rhs)
            rhs = jnp.where(aidx == jj, xj[:, None], rhs)
            return rhs

        x_p = jax.lax.fori_loop(0, panel, bwd_col, rhs)
        x_full = jnp.dot(x_p, sel.T, preferred_element_type=jnp.float32, precision=_PREC)
        res = jnp.where((lane >= p) & (lane < p + panel), x_full, res)

    return res


def _tile_n(r_pad, budget_elems=1 << 19):
    """Batch-tile so each [TN, r, r] VMEM buffer stays within ~2 MB: the
    A block is double-buffered by the pipeline and there are two scratches,
    so ~4 such buffers must fit the default 16 MiB scoped-VMEM limit."""
    tn = max(8, budget_elems // (r_pad * r_pad))
    return 1 << (tn.bit_length() - 1)


@functools.partial(jax.jit, static_argnames=("panel", "interpret"))
def spd_solve_pallas(A, b, panel=16, interpret=False):
    """Batched SPD solve x = A⁻¹ b.  A [N, r, r] f32, b [N, r] f32.

    Caller must pre-regularize A (SPD with jitter; identity for empty rows)
    — same contract as the XLA path in tpu_als.ops.solve.solve_spd.
    """
    if panel % 8:
        raise ValueError("panel must be a multiple of 8 (TPU sublane tile)")
    N, r = b.shape
    r_pad = max(panel, -(-r // panel) * panel)
    tn = _tile_n(r_pad)
    n_pad = -(-N // tn) * tn
    eye_tail = jnp.eye(r_pad, dtype=jnp.float32)[None, :, :]
    Ap = jnp.pad(A, ((0, n_pad - N), (0, r_pad - r), (0, r_pad - r)))
    # padded diagonal (both the rank padding and the batch padding) = I so
    # the factorization stays finite; padded b = 0 → padded x = 0
    diag_fix = jnp.where(
        (jax.lax.broadcasted_iota(jnp.int32, (1, r_pad, r_pad), 1) >= r)
        | (jnp.arange(n_pad)[:, None, None] >= N),
        eye_tail, 0.0,
    )
    Ap = Ap + diag_fix
    bp = jnp.pad(b, ((0, n_pad - N), (0, r_pad - r)))

    kernel = functools.partial(_chol_solve_kernel, r=r_pad, panel=panel)
    x = pl.pallas_call(
        kernel,
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((tn, r_pad, r_pad), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, r_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tn, r_pad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, r_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tn, r_pad, r_pad), jnp.float32),
                        pltpu.VMEM((tn, r_pad, r_pad), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(n_pad * (r_pad ** 3 / 3 + 2 * r_pad ** 2)),
            bytes_accessed=(n_pad * r_pad * r_pad + 2 * n_pad * r_pad) * 4,
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(Ap, bp)
    return x[:N, :r]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_solve")  # (r_pad, panel) -> bool


def available(rank=128, panel=16):
    """True when the kernel actually compiles AND runs on the local TPU's
    Mosaic version **at this rank** — probed once per process per padded
    rank with a tiny instance (VMEM budgets and Mosaic lowering both depend
    on the rank, so a rank-128 success must not green-light rank 384).
    Off-TPU this is False; use ``interpret=True`` there.
    solve_spd(backend='auto') consults this so a Mosaic regression degrades
    to the XLA lowering instead of crashing training.
    """
    from tpu_als.utils.platform import probe_kernel

    r_pad = max(panel, -(-rank // panel) * panel)

    def probe():
        # validates a random well-conditioned SPD batch against the XLA
        # lowering, through the same solve_spd() entry production uses —
        # a Mosaic miscompile producing finite-but-wrong values fails here
        # (identity-only probes do not exercise the factorization
        # arithmetic; same standard as pallas_gather_ne.solve_available)
        import numpy as np

        from tpu_als.ops.solve import DEFAULT_JITTER, solve_spd

        n, r = 8, r_pad
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, r, r)).astype(np.float32) / np.sqrt(r)
        A = jnp.asarray(
            M @ np.swapaxes(M, 1, 2)
            + 0.5 * np.eye(r, dtype=np.float32)[None])
        b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
        # mirror solve_spd's pre-regularization, but call the kernel
        # directly so the probe compiles the SAME panel it green-lights
        x = spd_solve_pallas(A + DEFAULT_JITTER * jnp.eye(r), b,
                             panel=panel)
        x.block_until_ready()
        ref = solve_spd(A, b, jnp.ones((n,), jnp.float32), backend="xla")
        return np.allclose(np.asarray(x), np.asarray(ref), atol=1e-3,
                           rtol=1e-2)

    return probe_kernel(_AVAILABLE, (r_pad, panel), probe)
