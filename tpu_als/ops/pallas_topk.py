"""Pallas TPU kernel: fused GEMM + running top-k for recommendation serving.

The XLA path (tpu_als.ops.topk) streams item tiles through an einsum and
folds each tile into a running ``jax.lax.top_k`` — but XLA cannot fuse the
top-k into the matmul, so every [users, item_chunk] score tile makes a round
trip through HBM.  At ML-25M serving scale (160k users x 60k items) that is
~40 GB of score traffic for ~2.5 GFLOP of useful ranking work: purely
bandwidth-bound.

This kernel keeps the running (scores, ids) top-k block resident in VMEM
across the item-tile grid dimension (the output-revisiting pattern), computes
each [TU, TI] score tile on the MXU, and merges it in-register with k rounds
of vectorized argmax-extraction on the VPU.  Scores never touch HBM; HBM
traffic drops to the factor matrices themselves plus the [users, k] result.

The item factor table stays HBM-resident (``memory_space=ANY``) and its
tiles stream into a 2-slot VMEM ring via the shared double-buffer substrate
(:mod:`tpu_als.ops.ring_buffer`): :func:`ring_buffer.grid_pump` waits tile
``j`` and puts tile ``j+1``'s DMA in flight under tile ``j``'s GEMM+merge —
the same slot/semaphore discipline as ``pallas_gather_ne``'s row gather,
stated once.  (Under BlockSpec auto-pipelining the compiler ran an
equivalent schedule; owning the copy makes the kernel's HBM stream explicit
and substrate-audited — bytes and numerics are unchanged.)

Replaces the reference stack's ``recommendForAll`` (blockify + crossJoin +
per-block GEMM + BoundedPriorityQueue merge across a shuffle,
``mllib/.../recommendation/MatrixFactorizationModel.scala`` — SURVEY.md §3.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops import ring_buffer as rb

NEG_INF = -3.4e38

# lane width: the merge buffer reserves one lane-tile for the carried best-k
LANES = 128


def _topk_kernel(U_ref, V_hbm, valid_ref, out_s_ref, out_i_ref, Vt, sem,
                 *, k, tile_i, n_ti):
    """One (user-tile, item-tile) grid cell.

    U_ref   [TU, r]      resident user factor tile
    V_hbm   [Ni, r]      the HBM-resident item factor table (``ANY``)
    valid_ref [1, TI]    1.0 = rankable item, 0.0 = padding/cold
    out_s/out_i [TU, LANES]  running best (revisited across the item grid
                         dim; only the first k lanes are meaningful)
    Vt [2, TI, r] / sem: the substrate's 2-slot item-tile ring — slot
    ``j%2`` holds this step's tile while ``j+1``'s DMA is in flight.
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_s_ref[:] = jnp.full_like(out_s_ref, NEG_INF)
        out_i_ref[:] = jnp.zeros_like(out_i_ref)

    def _copy(e, slot):
        return rb.local_copy(
            V_hbm.at[pl.ds(e * tile_i, tile_i)], Vt.at[slot], sem.at[slot])

    rb.grid_pump(j, n_ti, _copy)

    tu = U_ref.shape[0]
    # [TU, TI] score tile on the MXU, streamed from the slot just waited
    scores = jax.lax.dot_general(
        U_ref[:], Vt[jax.lax.rem(j, 2)],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.where(valid_ref[0, :][None, :] > 0, scores, NEG_INF)
    ids = jax.lax.broadcasted_iota(jnp.int32, (tu, tile_i), 1) + j * tile_i

    # merge buffer: [TU, TI + LANES] = new tile ++ carried best
    merged_s = jnp.concatenate([scores, out_s_ref[:]], axis=1)
    merged_i = jnp.concatenate([ids, out_i_ref[:]], axis=1)

    # k rounds of argmax-extract (VPU): descending, first-index tie-break —
    # carried best sits at high columns so fresh (lower-id) entries win ties
    # the same way a single global top_k would only for distinct scores;
    # callers should not rely on tie order (the XLA path doesn't either).
    def extract(jj, carry):
        ms, mi, bs, bi = carry
        col = jnp.argmax(ms, axis=1)  # [TU]
        hit = (
            jax.lax.broadcasted_iota(jnp.int32, ms.shape, 1)
            == col[:, None]
        )
        val = jnp.max(ms, axis=1)  # [TU]
        idx = jnp.sum(jnp.where(hit, mi, 0), axis=1)  # [TU]
        onecol = (
            jax.lax.broadcasted_iota(jnp.int32, bs.shape, 1) == jj
        )
        bs = jnp.where(onecol, val[:, None], bs)
        bi = jnp.where(onecol, idx[:, None], bi)
        ms = jnp.where(hit, NEG_INF, ms)
        return ms, mi, bs, bi

    best_s = jnp.full_like(out_s_ref, NEG_INF)
    best_i = jnp.zeros_like(out_i_ref)
    _, _, best_s, best_i = jax.lax.fori_loop(
        0, k, extract, (merged_s, merged_i, best_s, best_i)
    )
    out_s_ref[:] = best_s
    out_i_ref[:] = best_i


@functools.partial(
    jax.jit, static_argnames=("k", "tile_u", "tile_i", "interpret")
)
def topk_scores_pallas(U, V, item_valid, k, tile_u=256, tile_i=512,
                       interpret=False):
    """Top-k items per user row.  Same contract as
    :func:`tpu_als.ops.topk.chunked_topk_scores`: U [n, r], V [Ni, r],
    item_valid [Ni] bool; returns (scores [n, k], indices [n, k]) sorted
    descending.  ``k`` must be <= 128 (one lane tile carries the best list).
    """
    if k > LANES:
        raise ValueError(f"pallas top-k supports k <= {LANES}, got {k}")
    n, r = U.shape
    Ni = V.shape[0]

    n_pad = -(-n // tile_u) * tile_u
    i_pad = -(-Ni // tile_i) * tile_i
    r_pad = -(-r // LANES) * LANES
    Up = jnp.pad(U.astype(jnp.float32), ((0, n_pad - n), (0, r_pad - r)))
    Vp = jnp.pad(V.astype(jnp.float32), ((0, i_pad - Ni), (0, r_pad - r)))
    validp = jnp.pad(
        item_valid.astype(jnp.float32), (0, i_pad - Ni)
    ).reshape(1, i_pad)

    grid = (n_pad // tile_u, i_pad // tile_i)
    kernel = functools.partial(_topk_kernel, k=k, tile_i=tile_i,
                               n_ti=i_pad // tile_i)
    out_s, out_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_u, r_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, tile_i), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_u, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_u, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, tile_i, r_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * i_pad * r_pad,
            bytes_accessed=(n_pad * r_pad + i_pad * r_pad + 2 * n_pad * LANES)
            * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(Up, Vp, validp)
    return out_s[:n, :k], out_i[:n, :k]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_topk")


def available(rank=128, k=10):
    """Compile-and-run probe, cached per (padded rank, k) — the kernel
    instantiation depends on both (k is a static loop bound; the rank sets
    the lane padding), so a verdict for one shape must not green-light
    another.  Validated against the XLA scan path, same contract as the
    solver kernels' ``available()``: a Mosaic regression (compile failure
    OR finite-but-wrong output) makes serving degrade to the XLA scan."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = -(-max(1, rank) // LANES) * LANES
    k = min(k, LANES)

    def probe():
        import numpy as np

        from tpu_als.ops.topk import chunked_topk_scores

        rng = np.random.default_rng(0)
        # >= 2 user tiles and >= 2 item tiles so the output-revisiting
        # merge across the item grid dimension is exercised
        n, ni, r = 2 * 256, 2 * 512, r_pad
        U = (rng.normal(size=(n, r)) / np.sqrt(r)).astype(np.float32)
        V = (rng.normal(size=(ni, r)) / np.sqrt(r)).astype(np.float32)
        valid = jnp.asarray(np.ones(ni, bool))
        s, i = topk_scores_pallas(jnp.asarray(U), jnp.asarray(V), valid, k)
        rs, _ = chunked_topk_scores(jnp.asarray(U), jnp.asarray(V), valid, k)
        s.block_until_ready()
        s, i, rs = np.asarray(s), np.asarray(i), np.asarray(rs)
        # score VALUES must match the XLA scan; exact index equality is not
        # required (fp accumulation-order near-ties may rank-swap on a
        # healthy kernel) — instead the returned ids must reproduce the
        # returned scores under an independent host-side dot
        host = np.einsum("nr,nkr->nk", U, V[i])
        return (np.allclose(s, rs, atol=1e-4)
                and np.allclose(host, s, atol=1e-3))

    return probe_kernel(_AVAILABLE, (r_pad, k), probe)
