"""Pallas TPU kernel: fused GEMM + running top-k for recommendation serving.

The XLA path (tpu_als.ops.topk) streams item tiles through an einsum and
folds each tile into a running ``jax.lax.top_k`` — but XLA cannot fuse the
top-k into the matmul, so every [users, item_chunk] score tile makes a round
trip through HBM.  At ML-25M serving scale (160k users x 60k items) that is
~40 GB of score traffic for ~2.5 GFLOP of useful ranking work: purely
bandwidth-bound.

This kernel keeps the running (scores, ids) top-k block resident in VMEM
across the item-tile grid dimension (the output-revisiting pattern), computes
each [TU, TI] score tile on the MXU, and merges it in-register with k rounds
of vectorized argmax-extraction on the VPU.  Scores never touch HBM; HBM
traffic drops to the factor matrices themselves plus the [users, k] result.

The item factor table stays HBM-resident (``memory_space=ANY``) and its
tiles stream into a 2-slot VMEM ring via the shared double-buffer substrate
(:mod:`tpu_als.ops.ring_buffer`): :func:`ring_buffer.grid_pump` waits tile
``j`` and puts tile ``j+1``'s DMA in flight under tile ``j``'s GEMM+merge —
the same slot/semaphore discipline as ``pallas_gather_ne``'s row gather,
stated once.  (Under BlockSpec auto-pipelining the compiler ran an
equivalent schedule; owning the copy makes the kernel's HBM stream explicit
and substrate-audited — bytes and numerics are unchanged.)

Replaces the reference stack's ``recommendForAll`` (blockify + crossJoin +
per-block GEMM + BoundedPriorityQueue merge across a shuffle,
``mllib/.../recommendation/MatrixFactorizationModel.scala`` — SURVEY.md §3.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops import ring_buffer as rb

NEG_INF = -3.4e38

# lane width: the merge buffer reserves one lane-tile for the carried best-k
LANES = 128


def _topk_kernel(U_ref, V_hbm, valid_ref, out_s_ref, out_i_ref, Vt, sem,
                 *, k, tile_i, n_ti):
    """One (user-tile, item-tile) grid cell.

    U_ref   [TU, r]      resident user factor tile
    V_hbm   [Ni, r]      the HBM-resident item factor table (``ANY``)
    valid_ref [1, TI]    1.0 = rankable item, 0.0 = padding/cold
    out_s/out_i [TU, LANES]  running best (revisited across the item grid
                         dim; only the first k lanes are meaningful)
    Vt [2, TI, r] / sem: the substrate's 2-slot item-tile ring — slot
    ``j%2`` holds this step's tile while ``j+1``'s DMA is in flight.
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_s_ref[:] = jnp.full_like(out_s_ref, NEG_INF)
        out_i_ref[:] = jnp.zeros_like(out_i_ref)

    def _copy(e, slot):
        return rb.local_copy(
            V_hbm.at[pl.ds(e * tile_i, tile_i)], Vt.at[slot], sem.at[slot])

    rb.grid_pump(j, n_ti, _copy)

    tu = U_ref.shape[0]
    # [TU, TI] score tile on the MXU, streamed from the slot just waited
    scores = jax.lax.dot_general(
        U_ref[:], Vt[jax.lax.rem(j, 2)],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.where(valid_ref[0, :][None, :] > 0, scores, NEG_INF)
    ids = jax.lax.broadcasted_iota(jnp.int32, (tu, tile_i), 1) + j * tile_i

    # merge buffer: [TU, TI + LANES] = new tile ++ carried best
    merged_s = jnp.concatenate([scores, out_s_ref[:]], axis=1)
    merged_i = jnp.concatenate([ids, out_i_ref[:]], axis=1)

    # k rounds of argmax-extract (VPU): descending, first-index tie-break —
    # carried best sits at high columns so fresh (lower-id) entries win ties
    # the same way a single global top_k would only for distinct scores;
    # callers should not rely on tie order (the XLA path doesn't either).
    def extract(jj, carry):
        ms, mi, bs, bi = carry
        col = jnp.argmax(ms, axis=1)  # [TU]
        hit = (
            jax.lax.broadcasted_iota(jnp.int32, ms.shape, 1)
            == col[:, None]
        )
        val = jnp.max(ms, axis=1)  # [TU]
        idx = jnp.sum(jnp.where(hit, mi, 0), axis=1)  # [TU]
        onecol = (
            jax.lax.broadcasted_iota(jnp.int32, bs.shape, 1) == jj
        )
        bs = jnp.where(onecol, val[:, None], bs)
        bi = jnp.where(onecol, idx[:, None], bi)
        ms = jnp.where(hit, NEG_INF, ms)
        return ms, mi, bs, bi

    best_s = jnp.full_like(out_s_ref, NEG_INF)
    best_i = jnp.zeros_like(out_i_ref)
    _, _, best_s, best_i = jax.lax.fori_loop(
        0, k, extract, (merged_s, merged_i, best_s, best_i)
    )
    out_s_ref[:] = best_s
    out_i_ref[:] = best_i


@functools.partial(
    jax.jit, static_argnames=("k", "tile_u", "tile_i", "interpret")
)
def topk_scores_pallas(U, V, item_valid, k, tile_u=256, tile_i=512,
                       interpret=False):
    """Top-k items per user row.  Same contract as
    :func:`tpu_als.ops.topk.chunked_topk_scores`: U [n, r], V [Ni, r],
    item_valid [Ni] bool; returns (scores [n, k], indices [n, k]) sorted
    descending.  ``k`` must be <= 128 (one lane tile carries the best list).
    """
    if k > LANES:
        raise ValueError(f"pallas top-k supports k <= {LANES}, got {k}")
    n, r = U.shape
    Ni = V.shape[0]

    n_pad = -(-n // tile_u) * tile_u
    i_pad = -(-Ni // tile_i) * tile_i
    r_pad = -(-r // LANES) * LANES
    Up = jnp.pad(U.astype(jnp.float32), ((0, n_pad - n), (0, r_pad - r)))
    Vp = jnp.pad(V.astype(jnp.float32), ((0, i_pad - Ni), (0, r_pad - r)))
    validp = jnp.pad(
        item_valid.astype(jnp.float32), (0, i_pad - Ni)
    ).reshape(1, i_pad)

    grid = (n_pad // tile_u, i_pad // tile_i)
    kernel = functools.partial(_topk_kernel, k=k, tile_i=tile_i,
                               n_ti=i_pad // tile_i)
    out_s, out_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_u, r_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, tile_i), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_u, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_u, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, tile_i, r_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * i_pad * r_pad,
            bytes_accessed=(n_pad * r_pad + i_pad * r_pad + 2 * n_pad * LANES)
            * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(Up, Vp, validp)
    return out_s[:n, :k], out_i[:n, :k]


# collective_id for the serving merge ring — distinct from the training
# ring's _RING_COLLECTIVE_ID (pallas_gather_ne) so a pod running both
# kernels never aliases their barrier semaphores
_MERGE_COLLECTIVE_ID = 8


def _stable_extract(ms, mi, k, tu):
    """k rounds of argmax-extraction reproducing ``jax.lax.top_k``'s
    STABLE order bitwise: descending values, first-column tie-break.

    Unlike :func:`_topk_kernel`'s extract (which retires taken slots to
    ``NEG_INF`` and so re-picks sentinel columns arbitrarily), taken
    slots retire to ``-inf`` — strictly below the ``NEG_INF`` sentinel —
    so successive argmaxes select distinct earliest-untaken columns the
    same way a stable sort would, sentinels included.  This is what lets
    the cross-shard merge promise BITWISE equality (scores AND ids)
    with :func:`tpu_als.ops.topk.chunked_topk_scores`; callers place the
    carried best at LOW columns (earliest-seen wins ties, the chunked
    scan's ``[best_s, scores]`` order).
    """
    def extract(jj, carry):
        ms, mi, bs, bi = carry
        col = jnp.argmax(ms, axis=1)  # first max column per row
        hit = (
            jax.lax.broadcasted_iota(jnp.int32, ms.shape, 1)
            == col[:, None]
        )
        val = jnp.max(ms, axis=1)
        idx = jnp.sum(jnp.where(hit, mi, 0), axis=1)
        onecol = (
            jax.lax.broadcasted_iota(jnp.int32, bs.shape, 1) == jj
        )
        bs = jnp.where(onecol, val[:, None], bs)
        bi = jnp.where(onecol, idx[:, None], bi)
        ms = jnp.where(hit, -jnp.inf, ms)
        return ms, mi, bs, bi

    bs = jnp.full((tu, LANES), NEG_INF, jnp.float32)
    bi = jnp.zeros((tu, LANES), jnp.int32)
    _, _, bs, bi = jax.lax.fori_loop(0, k, extract, (ms, mi, bs, bi))
    return bs, bi


def _topk_merge_ring_kernel(U_ref, V_hbm, valid_ref, out_s_ref, out_i_ref,
                            Vt, coll, sem, send_sem, recv_sem, *, k, tile_i,
                            n_ti, axis_name, n_shards, ni_loc, sync):
    """One (user-tile, phase) grid cell of the cross-shard serving merge.

    Grid dims ``(i, p)`` with ``p`` ranging over ``n_ti + S`` phases:

    * ``p < n_ti`` — score item tile ``p`` of THIS device's catalog shard
      against the replicated query tile (the :func:`_topk_kernel` GEMM +
      merge, streamed through the substrate's 2-slot VMEM ring) into the
      running best refs; ids are globalized as ``me * ni_loc + local``.
      At the last tile the finished local candidate set is packed into
      ``coll[me]`` — scores in lanes ``[0, LANES)``, ids bitcast to f32
      in lanes ``[LANES, 2·LANES)``.
    * ``n_ti <= p < n_ti + S - 1`` — ring hop ``h = p - n_ti + 1``: send
      the set SOURCED from shard ``(me - h + 1) % S`` (received last hop;
      own set at ``h = 1``) to the right neighbor's same ``coll`` slot as
      one ``remote_copy``, and retire this hop's send + the incoming set
      from the left.  Slot identity is keyed on the SOURCE shard, so
      sender and receiver agree and every slot is written exactly once
      per pass — no ack backpressure is needed (each hop's send reads the
      slot the previous hop's ``wait_recv`` retired, so no device can run
      ahead within a pass), only the pass barrier below.
    * ``p == n_ti + S - 1`` — merge ``coll[0..S-1]`` in shard order with
      :func:`_stable_extract` (carried-at-low-columns), which makes the
      result bitwise-equal to ``chunked_topk_scores`` over the
      concatenated global catalog, tie-break included.

    Per-shard candidate lists exist only in the ``coll`` VMEM scratch —
    never as an XLA value in HBM (the ``serve_comm_audit`` contract pins
    this, plus the remote-DMA byte count, against the roofline closed
    form).  ``sync`` (compiled path only): pass barrier at ``p == 0`` on
    the ``collective_id``-scoped barrier semaphore — tile ``i + 1``
    repacks ``coll[me]`` while a slower neighbor may still be merging
    pass ``i``.  At ``n_shards == 1`` the ring degenerates to the packed
    local set (no sends trace at all).
    """
    p = pl.program_id(1)
    tu = U_ref.shape[0]

    if n_shards > 1:
        me = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(me + 1, n_shards)
        left = jax.lax.rem(me + n_shards - 1, n_shards)

        if sync:
            @pl.when(p == 0)
            def _pass_barrier():
                bar = pltpu.get_barrier_semaphore()
                pltpu.semaphore_signal(
                    bar, 1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_signal(
                    bar, 1, device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_wait(bar, 2)
    else:
        me = jnp.int32(0)

    @pl.when(p == 0)
    def _init():
        out_s_ref[:] = jnp.full_like(out_s_ref, NEG_INF)
        out_i_ref[:] = jnp.zeros_like(out_i_ref)

    @pl.when(p < n_ti)
    def _score():
        def _copy(e, slot):
            return rb.local_copy(
                V_hbm.at[pl.ds(e * tile_i, tile_i)], Vt.at[slot],
                sem.at[slot])

        rb.grid_pump(p, n_ti, _copy)

        scores = jax.lax.dot_general(
            U_ref[:], Vt[jax.lax.rem(p, 2)],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        scores = jnp.where(valid_ref[0, :][None, :] > 0, scores, NEG_INF)
        ids = (jax.lax.broadcasted_iota(jnp.int32, (tu, tile_i), 1)
               + me * ni_loc + p * tile_i)

        # carried best at LOW columns — the chunked scan's stable order
        bs, bi = _stable_extract(
            jnp.concatenate([out_s_ref[:], scores], axis=1),
            jnp.concatenate([out_i_ref[:], ids], axis=1), k, tu)
        out_s_ref[:] = bs
        out_i_ref[:] = bi

        @pl.when(p == n_ti - 1)
        def _pack():
            packed = jnp.concatenate(
                [bs, jax.lax.bitcast_convert_type(bi, jnp.float32)],
                axis=1)
            coll[pl.ds(me, 1)] = packed[None]

    if n_shards > 1:
        @pl.when((p >= n_ti) & (p < n_ti + n_shards - 1))
        def _hop():
            h = p - n_ti + 1
            sl = jax.lax.rem(me + n_shards - h + 1, n_shards)
            d = rb.remote_copy(coll.at[sl], coll.at[sl], send_sem,
                               recv_sem, right)
            d.start()
            # retire my send and the incoming set from the LEFT (lands in
            # slot (me - h) % S, which the next hop forwards) — all hops
            # share one payload shape, so the descriptor waits both
            d.wait_send()
            d.wait_recv()

    @pl.when(p == n_ti + n_shards - 1)
    def _merge():
        bs = jnp.full((tu, LANES), NEG_INF, jnp.float32)
        bi = jnp.zeros((tu, LANES), jnp.int32)
        for s in range(n_shards):  # static: shard order == ascending ids
            bs, bi = _stable_extract(
                jnp.concatenate([bs, coll[s, :, :LANES]], axis=1),
                jnp.concatenate(
                    [bi, jax.lax.bitcast_convert_type(
                        coll[s, :, LANES:], jnp.int32)], axis=1),
                k, tu)
        out_s_ref[:] = bs
        out_i_ref[:] = bi


def topk_merge_ring(U, V_loc, item_valid_loc, k, *, axis_name=None,
                    n_shards=1, ni_loc=None, tile_u=256, tile_i=512,
                    interpret=False):
    """Cross-shard top-k serving core (inside ``shard_map``): ONE kernel
    call per device scores the replicated query rows against this
    device's catalog shard and merges the per-shard candidate sets
    in-kernel over ``make_async_remote_copy`` hops on the ring substrate.
    Per-shard candidate lists never materialize in HBM — the only
    cross-device traffic is the packed ``[TU, 2·LANES]`` running set,
    ``S - 1`` hops per user tile (``perf.roofline.serve_merge_remote_bytes``
    is the closed form; the ``serve_comm_audit`` contract pins the traced
    kernel against it).

    U [n, r] REPLICATED queries; V_loc [ni_loc, r] / item_valid_loc
    [ni_loc] THIS device's shard (``ni_loc`` is the uniform shard stride;
    pass it explicitly if ``V_loc`` arrives pre-padded).  Returns
    (scores [n, k], ids [n, k]) replicated, bitwise-equal to
    ``chunked_topk_scores`` on the concatenated catalog — tie-break
    included (the stable-extract merge; see ``_stable_extract``) —
    whenever the score values themselves are reproducible across the two
    contraction shapes (exact at integer-valued factors; the contract's
    adversarial-tie corpus).  Off-TPU pass ``interpret=True``: numerics
    and schedule are exercised; the pass-barrier arm compiles only on
    real meshes.
    """
    if k > LANES:
        raise ValueError(f"pallas top-k supports k <= {LANES}, got {k}")
    if n_shards > 1 and axis_name is None:
        raise ValueError("axis_name is required when n_shards > 1")
    n, r = U.shape
    ni = V_loc.shape[0]
    if ni_loc is None:
        ni_loc = ni

    n_pad = -(-n // tile_u) * tile_u
    i_pad = -(-ni // tile_i) * tile_i
    r_pad = -(-r // LANES) * LANES
    Up = jnp.pad(U.astype(jnp.float32), ((0, n_pad - n), (0, r_pad - r)))
    Vp = jnp.pad(V_loc.astype(jnp.float32),
                 ((0, i_pad - ni), (0, r_pad - r)))
    validp = jnp.pad(
        item_valid_loc.astype(jnp.float32), (0, i_pad - ni)
    ).reshape(1, i_pad)

    n_ti = i_pad // tile_i
    n_ut = n_pad // tile_u
    grid = (n_ut, n_ti + n_shards)
    sync = not interpret and n_shards > 1
    kernel = functools.partial(
        _topk_merge_ring_kernel, k=k, tile_i=tile_i, n_ti=n_ti,
        axis_name=axis_name, n_shards=n_shards, ni_loc=ni_loc, sync=sync)

    from tpu_als.perf.roofline import serve_merge_remote_bytes

    out_s, out_i = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_u, r_pad), lambda i, p: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            # hop/merge phases revisit the last tile's block (clamped
            # index map) — only scoring phases read it
            pl.BlockSpec((1, tile_i),
                         lambda i, p: (0, jnp.minimum(p, n_ti - 1)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_u, LANES), lambda i, p: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_u, LANES), lambda i, p: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, LANES), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, tile_i, r_pad), jnp.float32),   # item-tile ring
            # per-source-shard packed candidate sets: scores ++ bitcast
            # ids; 2·LANES·TU·S·4 B (256 KiB at S=8, TU=128) — the VMEM
            # cost of never spilling the lists to HBM
            pltpu.VMEM((n_shards, tile_u, 2 * LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,      # send
            pltpu.SemaphoreType.DMA,      # recv
        ],
        # bytes = the single-device top-k stream plus THE roofline
        # serving-merge ring payload (perf.roofline) — serve_comm_audit
        # extracts the remote-DMA component from the traced kernel and
        # pins it to the closed form
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * i_pad * r_pad,
            bytes_accessed=(n_pad * r_pad + i_pad * r_pad
                            + 2 * n_pad * LANES) * 4
            + serve_merge_remote_bytes(n_ut, n_shards, tile_u),
            transcendentals=0,
        ),
        compiler_params=(
            pltpu.TPUCompilerParams(collective_id=_MERGE_COLLECTIVE_ID)
            if sync else None),
        interpret=interpret,
    )(Up, Vp, validp)
    return out_s[:n, :k], out_i[:n, :k]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_topk")


def available(rank=128, k=10):
    """Compile-and-run probe, cached per (padded rank, k) — the kernel
    instantiation depends on both (k is a static loop bound; the rank sets
    the lane padding), so a verdict for one shape must not green-light
    another.  Validated against the XLA scan path, same contract as the
    solver kernels' ``available()``: a Mosaic regression (compile failure
    OR finite-but-wrong output) makes serving degrade to the XLA scan."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = -(-max(1, rank) // LANES) * LANES
    k = min(k, LANES)

    def probe():
        import numpy as np

        from tpu_als.ops.topk import chunked_topk_scores

        rng = np.random.default_rng(0)
        # >= 2 user tiles and >= 2 item tiles so the output-revisiting
        # merge across the item grid dimension is exercised
        n, ni, r = 2 * 256, 2 * 512, r_pad
        U = (rng.normal(size=(n, r)) / np.sqrt(r)).astype(np.float32)
        V = (rng.normal(size=(ni, r)) / np.sqrt(r)).astype(np.float32)
        valid = jnp.asarray(np.ones(ni, bool))
        s, i = topk_scores_pallas(jnp.asarray(U), jnp.asarray(V), valid, k)
        rs, _ = chunked_topk_scores(jnp.asarray(U), jnp.asarray(V), valid, k)
        s.block_until_ready()
        s, i, rs = np.asarray(s), np.asarray(i), np.asarray(rs)
        # score VALUES must match the XLA scan; exact index equality is not
        # required (fp accumulation-order near-ties may rank-swap on a
        # healthy kernel) — instead the returned ids must reproduce the
        # returned scores under an independent host-side dot
        host = np.einsum("nr,nkr->nk", U, V[i])
        return (np.allclose(s, rs, atol=1e-4)
                and np.allclose(host, s, atol=1e-3))

    return probe_kernel(_AVAILABLE, (r_pad, k), probe)


_MERGE_AVAILABLE = _probe_cache("pallas_topk_merge_ring")


def merge_ring_available(rank=128, k=10, n_shards=None):
    """Compile-and-validate probe for the cross-shard merge kernel ON THE
    LIVE MESH, cached per (padded rank, k, n_shards) — the gate
    ``parallel.serve.topk_sharded`` / ``ServingEngine`` consult before
    adopting ``serve_backend='merge_ring'`` on hardware.

    Same discipline as ``pallas_gather_ne.ring_available``: the probe
    executes a COLLECTIVE (the in-kernel candidate-set ring under
    ``shard_map``), so its verdict is only meaningful for the mesh it ran
    on — the cache key carries ``n_shards`` and the CONSUMER re-validates
    shape, so a banked verdict for a different shard count is a cache
    miss, never a steer.  Validates against the single-device
    ``chunked_topk_scores`` on the concatenated catalog.  Off-TPU →
    False (the CPU path doesn't need it: the interpret-mode kernel is
    dispatched by tests/contracts explicitly, and CPU serving uses the
    compiled XLA sharded path).
    """
    from tpu_als.utils.platform import probe_kernel

    if n_shards is None:
        n_shards = jax.device_count()
    r_pad = -(-max(1, rank) // LANES) * LANES
    k = min(k, LANES)

    def probe():
        import functools as ft

        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from tpu_als.ops.topk import chunked_topk_scores
        from tpu_als.parallel.mesh import shard_map

        if jax.device_count() < n_shards:
            return False
        S = n_shards
        ax = "merge_probe"
        mesh = Mesh(np.array(jax.devices()[:S]), (ax,))
        rng = np.random.default_rng(0)
        # integer-valued factors: scores are exact in f32, so equality
        # with the XLA scan is bitwise — ties included (duplicated rows)
        per, n = 96, 40
        base = rng.integers(-3, 4, size=(7, r_pad)).astype(np.float32)
        V = base[rng.integers(0, 7, size=S * per)]
        U = rng.integers(-3, 4, size=(n, r_pad)).astype(np.float32)
        valid = rng.random(S * per) < 0.9

        @jax.jit
        @ft.partial(shard_map, mesh=mesh,
                    in_specs=(P(), P(ax), P(ax)), out_specs=(P(), P()),
                    check_vma=False)
        def run(Uq, V_shard, valid_shard):
            return topk_merge_ring(
                Uq, V_shard, valid_shard, k, axis_name=ax, n_shards=S,
                tile_u=8 * (-(-n // 8)), tile_i=128)

        from tpu_als.parallel.mesh import shard_leading

        spec = shard_leading(mesh)
        s, ix = run(jnp.asarray(U),
                    jax.device_put(V, spec),
                    jax.device_put(valid, spec))
        s.block_until_ready()
        rs, rix = chunked_topk_scores(
            jnp.asarray(U), jnp.asarray(V), jnp.asarray(valid),
            min(k, S * per))
        return (np.array_equal(np.asarray(s), np.asarray(rs))
                and np.array_equal(np.asarray(ix), np.asarray(rix)))

    return probe_kernel(_MERGE_AVAILABLE, (r_pad, k, n_shards), probe)
