"""The ONE double-buffer ring substrate: slots, semaphore discipline, copies.

Three hand-rolled double-buffer schedules grew up independently — the
HBM→VMEM ``make_async_copy`` ring inside ``ops.pallas_gather_ne``'s kernels,
the ppermute-under-einsum rotation in ``parallel.comm.ring_half_step`` and
the block-gather prefetch in ``parallel.comm.chunked_gather_half_step`` —
each re-stating the same discipline: a fixed ring of slots, *start* entry
``e+depth`` into the slot entry ``e`` just vacated, *wait* before reading.
This module is that discipline stated once, at both levels where it occurs:

**In-kernel (Pallas)** — descriptors + pumps over DMA semaphore rings:

- :func:`local_copy` / :func:`remote_copy`: the two copy descriptors.  A
  slot's copy is *local* (HBM→VMEM ``make_async_copy``, one DMA semaphore)
  or *remote* (inter-chip ``make_async_remote_copy``, send/recv semaphore
  pair, ``LOGICAL`` device ids — the form that lowers on hardware meshes
  AND emulates under ``interpret=True`` on forced-host-device CPU meshes;
  ``MESH`` tuple ids do not interpret on jax 0.4.37).
- :func:`pump`: the multiple-buffering schedule inside one grid step
  (``ops.pallas_gather_ne``'s row-gather front end, the remote tile stream
  of the fused-comm ring kernel).
- :func:`grid_pump`: the same schedule unrolled *across* grid steps, for
  kernels whose natural chunk is one grid iteration (``ops.pallas_topk``
  streams one item tile per step).

**XLA-level (inside shard_map)** — the identical start/consume/wait shape
with collectives as the "DMA":

- :func:`rotate_stream`: ring rotation (``ppermute``) with the optional
  one-in-flight overlap slot — ``ring_half_step``'s schedule.
- :func:`prefetch_stream`: indexed fetches (``all_gather`` of block ``c``)
  with the next fetch issued under the current consume —
  ``chunked_gather_half_step``'s schedule.

The ``ring_substrate`` contract (analysis/contracts.py) pins that routing
``pallas_gather_ne`` through :func:`pump` emits a **byte-identical** jaxpr
to the pre-extraction hand-rolled loop, and that no private
``make_async_copy`` / ``make_async_remote_copy`` call sites survive outside
this module.
"""

from __future__ import annotations

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# outstanding-DMA ring depth: row copies are small (r·db bytes, 512 B at
# rank 128 f32), so several must be in flight to hide per-descriptor
# latency; 8 is comfortably below the DMA queue depth
DMA_SLOTS = 8


def dma_slots(n_entries):
    """Slot-ring depth for a pump over ``n_entries`` copies (never more
    slots than entries — each primed slot must map to a distinct entry)."""
    return min(DMA_SLOTS, n_entries)


def local_copy(src, dst, sem):
    """Local async-DMA descriptor (HBM↔VMEM): start/wait via ``sem``."""
    return pltpu.make_async_copy(src, dst, sem)


def remote_copy(src, dst, send_sem, recv_sem, device_id):
    """Inter-device RDMA descriptor: ``src`` here → ``dst`` on the logical
    device ``device_id``; symmetric SPMD rings wait their own incoming via
    ``.wait_recv()`` on the same descriptor (``dst`` names the local
    landing buffer, ``recv_sem`` is signaled by the neighbor's send).

    ``LOGICAL`` scalar ids on purpose — see the module docstring.
    """
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send_sem, recv_sem=recv_sem,
        device_id=device_id, device_id_type=pltpu.DeviceIdType.LOGICAL)


def pump(n_entries, make_copy, depth=None):
    """The multiple-buffering schedule: prime ``depth`` copies, then wait
    entry ``e`` / start entry ``e+depth`` into the slot ``e`` just vacated.

    ``make_copy(entry, slot)`` returns a started-able descriptor
    (:func:`local_copy` / :func:`remote_copy` over the caller's refs and
    semaphore ring); callers read the landed data after pump returns (the
    last ``depth`` waits retire in entry order).  The emitted op sequence
    is EXACTLY the pre-extraction hand-rolled loop of
    ``pallas_gather_ne`` — the ``ring_substrate`` contract pins the jaxpr
    byte-for-byte, so think twice before "improving" this function.
    """
    if depth is None:
        depth = dma_slots(n_entries)
    for s in range(depth):
        make_copy(s, s).start()

    def _pump(e, carry):
        make_copy(e, e % depth).wait()

        @pl.when(e + depth < n_entries)
        def _next():
            make_copy(e + depth, e % depth).start()

        return carry

    jax.lax.fori_loop(0, n_entries, _pump, 0)


def grid_pump(step, n_steps, make_copy, depth=2):
    """:func:`pump` unrolled across a Pallas grid dimension: call once per
    grid step with ``step = pl.program_id(dim)``; the chunk landed by the
    previous step's start is waited here while ``step+1``'s copy is put in
    flight under this step's compute.  Slots (and their semaphores) must
    persist across steps, i.e. live in ``scratch_shapes``.

    ``make_copy(entry, slot)`` as in :func:`pump`, but both arguments are
    traced scalars (use ``.at[pl.ds(...)]`` descriptors).
    """
    @pl.when(step == 0)
    def _prime():
        make_copy(0, 0).start()

    make_copy(step, jax.lax.rem(step, depth)).wait()

    @pl.when(step + 1 < n_steps)
    def _next():
        make_copy(step + 1, jax.lax.rem(step + 1, depth)).start()


def rotate_stream(n_steps, rotate, consume, buf, carry, overlap=False):
    """XLA-level ring rotation (inside ``shard_map``): consume the held
    buffer each step, rotate every step — after ``n_steps`` rotations the
    buffer is home, so the next pass starts clean.

    ``overlap=True`` is the one-in-flight slot: the rotation for step
    ``t+1`` is issued *before* step ``t``'s consume, so XLA's latency-
    hiding scheduler keeps one async collective-permute under the compute.
    Bytes moved, rotation count and numerics are identical either way.

    ``rotate(buf) -> buf'``; ``consume(t, buf, carry) -> carry``.
    Returns ``(buf, carry)``.
    """
    for t in range(n_steps):
        if overlap:
            nxt = rotate(buf)
            carry = consume(t, buf, carry)
            buf = nxt
        else:
            carry = consume(t, buf, carry)
            buf = rotate(buf)
    return buf, carry


def prefetch_stream(n_steps, fetch, consume, carry):
    """XLA-level indexed prefetch (inside ``shard_map``): fetch block 0,
    then each step issues block ``c+1``'s fetch *before* consuming block
    ``c`` — one async fetch in flight under the compute, the chunked
    all_gather schedule.

    ``fetch(c) -> buf``; ``consume(c, buf, carry) -> carry``.
    """
    nxt = fetch(0)
    for c in range(n_steps):
        cur = nxt
        if c + 1 < n_steps:
            nxt = fetch(c + 1)
        carry = consume(c, cur, carry)
    return carry
