"""Pallas TPU kernel: batch-in-lanes Cholesky for ranks ABOVE 128.

The lanes kernel (tpu_als.ops.pallas_lanes) holds its whole ``[r, r, 128]``
working set in VMEM — 8 MiB at r=128, structurally capped there: r=256
would need 32 MiB against the 16 MiB limit (SURVEY.md §7 hard-part 2; the
rank-256 Amazon config, BASELINE.json configs[2], is exactly this shape).

This module extends the layout past 128 with an **out-of-core blocked
factorization** (VERDICT r3 #4): the matrix is tiled into 128×128 blocks;
one block at a time streams through the same ``[128, 128, LANES]``
lane-major VMEM working set; the factor is written back OVER the input in
HBM (``input_output_aliases`` — no second [N, r, r] allocation, which at
the rank-256 bench shape is gigabytes); and cross-block corrections
stream already-factored panels back from HBM in ``[panel, 128, LANES]``
slices.  Peak VMEM ≈ 8 MiB (block) + 2 × 0.5 MiB (stream buffers) —
independent of rank.

Right-looking block algorithm, all in the kernel's transposed layout
``S[col, row, lane]`` (column j of every lane's matrix is a leading-axis
slice, exactly as in pallas_lanes):

  for k in 0..nb:                      # nb = r_pad / 128 diagonal blocks
    W <- A[k,k];  W -= Σ_{m<k} L[k,m]·L[k,m]ᵀ   (streamed panels)
    factor W (panelized lanes recurrence);  L[k,k] <- W
    for i in k+1..nb:                  # blocks below the diagonal
      W <- A[i,k];  W -= Σ_{m<k} L[i,m]·L[k,m]ᵀ (two streams)
      W <- W · L[k,k]⁻ᵀ                (streamed right-looking tri-solve)
      L[i,k] <- W

The kernel factors ONLY (no substitution phases): the two triangular
substitutions are r² work that XLA's batched ``solve_triangular`` handles
well on the MXU — it is the r³ *factorization* whose XLA lowering is
column-sequential and slow (BASELINE.md round-2 ablation: the solve was
92% of the iteration before the first kernel).  Replaces the reference
stack's per-entity LAPACK ``dppsv`` at ranks the flat kernel cannot reach.

On-chip timing vs tpu_als.ops.pallas_solve at rank 256 is measured by
scripts/rank256_proxy.py (queued in the tunnel sweep); until a chip run
says otherwise the auto dispatch prefers this kernel above 128 because it
keeps the lanes layout's defining property — no cross-lane reductions or
selector matmuls in the serial chain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops.ring_buffer import local_copy

LANES = 128
BLOCK = 128
PANEL = 8

# see pallas_lanes._PREC — bf16 single-pass MXU error compounds through
# the Cholesky recurrence; HIGHEST keeps the GEMM rungs at f32 fidelity
_PREC = jax.lax.Precision.HIGHEST


def _chol_blocked_kernel(A_ref, out_ref, W, Bs, Cs, sem, *, nb, panel, mxu):
    """Factor one lane-group of ``nb·128``-rank matrices, blockwise.

    A_ref/out_ref [G, r_pad, r_pad, LANES] in HBM, ALIASED (the factor
    overwrites A).  Layout: [g, col, row, lane].  W [B, B, LANES] is the
    active block; Bs/Cs [panel, B, LANES] are streamed factor panels.
    After the kernel, blocks on/below the diagonal hold L (diag blocks
    with exact zeros above their diagonal); blocks ABOVE the diagonal
    still hold input values — callers take ``tril``.
    """
    g = pl.program_id(0)
    B = BLOCK
    sub = jax.lax.broadcasted_iota(jnp.int32, (B, LANES), 0)

    def dma(src, dst):
        cp = local_copy(src, dst, sem)
        cp.start()
        cp.wait()

    def blk(ref, cb, rb):
        """[B, B, LANES] block view: column-block cb, row-block rb."""
        return ref.at[g, cb * B:(cb + 1) * B, rb * B:(rb + 1) * B]

    def fused_outer(S1, S2):
        """Σ_cc S1[cc] ⊗ S2[cc] over the panel axis -> [B, B, LANES].

        ``mxu=True`` runs it as ONE lane-batched rank-``panel`` GEMM
        (per lane a [B, panel]·[panel, B] MXU contraction — the Schur
        corrections are where the blocked algorithm's r³/3 FLOPs live,
        so this is the whole-kernel lever); False is the VPU broadcast
        sweep the probe ladder falls back to.
        """
        if mxu:
            upd = jax.lax.dot_general(
                S1[:], S2[:],
                dimension_numbers=(((0,), (0,)), ((2,), (2,))),
                preferred_element_type=jnp.float32, precision=_PREC,
            )  # [LANES, B, B]
            return jnp.transpose(upd, (1, 2, 0))
        upd = S1[0][:, None, :] * S2[0][None, :, :]
        for cc in range(1, panel):
            upd = upd + S1[cc][:, None, :] * S2[cc][None, :, :]
        return upd

    def factor_active():
        """Panelized lanes Cholesky of W in place (pallas_lanes
        panel_step, with Bs as the panel scratch)."""
        def panel_step(ip, _):
            base = ip * panel
            for jj in range(panel):
                j = base + jj
                cj = W[j]
                for kk in range(jj):
                    Lk = Bs[kk]
                    lkj = jnp.sum(jnp.where(sub == j, Lk, 0.0), axis=0)
                    cj = cj - Lk * lkj[None, :]
                d = jnp.sum(jnp.where(sub == j, cj, 0.0), axis=0)
                inv = jax.lax.rsqrt(jnp.maximum(d, 1e-30))
                Bs[jj] = jnp.where(sub >= j, cj * inv[None, :], 0.0)
            W[:] = W[:] - fused_outer(Bs, Bs)
            for jj in range(panel):
                W[base + jj] = Bs[jj]
            return 0

        jax.lax.fori_loop(0, B // panel, panel_step, 0, unroll=False)

    for k in range(nb):
        # ---- diagonal block: Schur corrections, then factorize ----
        dma(blk(A_ref, k, k), W)
        for m in range(k):
            for c0 in range(0, B, panel):
                dma(out_ref.at[g, m * B + c0:m * B + c0 + panel,
                               k * B:(k + 1) * B], Bs)
                W[:] = W[:] - fused_outer(Bs, Bs)
        factor_active()
        dma(W, blk(out_ref, k, k))

        # ---- blocks below: corrections, then L[i,k] = A[i,k]·L[k,k]⁻ᵀ ----
        for i in range(k + 1, nb):
            dma(blk(A_ref, k, i), W)
            for m in range(k):
                for c0 in range(0, B, panel):
                    sl = slice(m * B + c0, m * B + c0 + panel)
                    dma(out_ref.at[g, sl, k * B:(k + 1) * B], Bs)
                    dma(out_ref.at[g, sl, i * B:(i + 1) * B], Cs)
                    W[:] = W[:] - fused_outer(Bs, Cs)
            # right-looking triangular solve against streamed L[k,k]:
            # finalize the panel's columns left-looking (corrections from
            # columns inside the panel), then ONE fused update of all
            # later columns
            for c0 in range(0, B, panel):
                dma(out_ref.at[g, k * B + c0:k * B + c0 + panel,
                               k * B:(k + 1) * B], Bs)
                for jj in range(panel):
                    j = c0 + jj
                    cj = W[j]
                    for mm in range(jj):
                        # L_kk[j, c0+mm]: row j of the streamed column
                        lmj = jnp.sum(jnp.where(sub == j, Bs[mm], 0.0),
                                      axis=0)
                        cj = cj - W[c0 + mm] * lmj[None, :]
                    d = jnp.sum(jnp.where(sub == j, Bs[jj], 0.0), axis=0)
                    W[j] = cj / jnp.maximum(d, 1e-30)[None, :]
                # later columns a > c0+panel-1: W[a] -= Σ_jj
                # L_kk[a, c0+jj] · W[c0+jj]; panel rows ≤ c0+panel-1 are
                # zeroed so within-panel columns (already final) and
                # earlier columns receive nothing
                upd = None
                for jj in range(panel):
                    Bm = jnp.where(sub > c0 + panel - 1, Bs[jj], 0.0)
                    term = Bm[:, None, :] * W[c0 + jj][None, :, :]
                    upd = term if upd is None else upd + term
                W[:] = W[:] - upd
            dma(W, blk(out_ref, k, i))


@functools.partial(jax.jit, static_argnames=("panel", "mxu", "interpret"))
def chol_lanes_blocked(A, panel=None, mxu=False, interpret=False):
    """Batched lower-Cholesky factor L of SPD ``A`` [N, r, r] f32, via the
    blocked out-of-core lanes kernel.  Caller pre-regularizes A (jitter +
    identity for empty rows), same contract as the flat kernel.

    ``panel``: factor/stream panel width (must divide BLOCK=128; None =
    PANEL).  Exposed so scripts/kernel_lab.py can tune it on chip the
    same way the flat kernel's DEFAULT_PANEL was tuned.  ``mxu``: run the
    streamed Schur corrections as lane-batched MXU GEMMs (fused_outer) —
    pass ``selected_mxu(rank)`` so only a probe-validated variant
    engages."""
    if panel is None:
        panel = PANEL
    if BLOCK % panel:
        raise ValueError(f"panel {panel} must divide {BLOCK}")
    N, r = A.shape[0], A.shape[-1]
    nb = -(-r // BLOCK)
    r_pad = nb * BLOCK
    n_pad = -(-N // LANES) * LANES
    Ap = jnp.pad(A, ((0, n_pad - N), (0, r_pad - r), (0, r_pad - r)))
    # identity on padded rows/cols keeps the factorization finite there
    eye_tail = jnp.eye(r_pad, dtype=jnp.float32)[None]
    diag_fix = jnp.where(
        (jax.lax.broadcasted_iota(jnp.int32, (1, r_pad, r_pad), 1) >= r)
        | (jnp.arange(n_pad)[:, None, None] >= N),
        eye_tail, 0.0)
    Ap = Ap + diag_fix

    G = n_pad // LANES
    At = jnp.transpose(Ap.reshape(G, LANES, r_pad, r_pad), (0, 3, 2, 1))
    kernel = functools.partial(_chol_blocked_kernel, nb=nb, panel=panel,
                               mxu=mxu)
    Lt = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((G, r_pad, r_pad, LANES),
                                       jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((BLOCK, BLOCK, LANES), jnp.float32),
            pltpu.VMEM((panel, BLOCK, LANES), jnp.float32),
            pltpu.VMEM((panel, BLOCK, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={0: 0},
        cost_estimate=pl.CostEstimate(
            flops=int(n_pad * r_pad ** 3 / 3),
            bytes_accessed=int(n_pad * r_pad * r_pad * 4 * (nb + 2)),
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(At)
    # [G, col, row, lane] -> [N, row, col]; blocks above the diagonal
    # still hold input values (never written) -> tril
    L = jnp.transpose(Lt, (0, 3, 2, 1)).reshape(n_pad, r_pad, r_pad)
    return jnp.tril(L[:N, :r, :r])


@functools.partial(jax.jit, static_argnames=("panel", "mxu", "interpret"))
def spd_solve_lanes_blocked(A, b, panel=None, mxu=False, interpret=False):
    """Batched SPD solve x = A⁻¹b for ranks > 128: blocked lanes
    factorization + XLA batched triangular substitutions (r² work the
    MXU handles; only the r³ factorization needed a kernel)."""
    L = chol_lanes_blocked(A, panel=panel, mxu=mxu, interpret=interpret)
    y = jax.scipy.linalg.solve_triangular(L, b[..., None], lower=True)
    return jax.scipy.linalg.solve_triangular(L, y, lower=True,
                                             trans=1)[..., 0]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_lanes_blocked")  # r_pad -> bool
_MXU = {}  # r_pad -> bool: MXU fused_outer variant validated by probe


def selected_mxu(rank):
    """Whether the probe validated the MXU trailing-update variant at
    this rank (False until ``available`` has run; the VPU sweep is the
    conservative default)."""
    r_pad = -(-rank // BLOCK) * BLOCK
    return _MXU.get(r_pad, False)


def supported_rank(rank):
    """This kernel exists for ranks the flat lanes layout cannot hold;
    the streamed working set is rank-independent, so any rank above 128
    is structurally fine (padding rounds to 128-block multiples)."""
    return rank > 128


def available(rank=256):
    """True when the kernel compiles AND matches the XLA lowering on a
    random SPD batch at this rank on the local Mosaic (same standard as
    the other solve kernels)."""
    from tpu_als.utils.platform import probe_kernel

    if not supported_rank(rank):
        return False
    r_pad = -(-rank // BLOCK) * BLOCK

    def probe():
        import numpy as np

        from tpu_als.ops.solve import DEFAULT_JITTER, solve_spd

        n, r = LANES + 8, r_pad  # 2 lane groups + batch padding
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, r, r)).astype(np.float32) / np.sqrt(r)
        A = jnp.asarray(
            M @ np.swapaxes(M, 1, 2)
            + 0.5 * np.eye(r, dtype=np.float32)[None])
        b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
        ref = solve_spd(A, b, jnp.ones((n,), jnp.float32), backend="xla")
        # Ladder: the MXU fused_outer first (lane-batched GEMM Schur
        # corrections), then the VPU sweep.  A Mosaic that rejects the
        # minormost-batch dot_general falls to the proven rung.
        for mx in (True, False):
            try:
                x = spd_solve_lanes_blocked(
                    A + DEFAULT_JITTER * jnp.eye(r), b, mxu=mx)
                x.block_until_ready()
                if np.allclose(np.asarray(x), np.asarray(ref),
                               atol=1e-3, rtol=1e-2):
                    _MXU[r_pad] = mx
                    return True
            except Exception as e:
                from tpu_als.utils.platform import classify_probe_error

                if classify_probe_error(e) != "kernel":
                    raise
        return False

    return probe_kernel(_AVAILABLE, r_pad, probe)
