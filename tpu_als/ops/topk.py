"""Chunked GEMM + running top-k — the recommendation serving kernel.

Replaces the reference stack's ``recommendForAll`` path (blockify both factor
sets, crossJoin all block pairs, per-pair BLAS3 GEMM, per-row
``BoundedPriorityQueue`` merge across a shuffle — SURVEY.md §3.3) with a
single jitted scan: stream item-factor tiles through an MXU GEMM against the
resident user block and fold each tile's scores into a running
``jax.lax.top_k``.  No queues, no shuffle, no host round-trips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# plain python float: creating a jnp scalar here would initialize the JAX
# backend as an import side effect
NEG_INF = -3.4e38


def topk_validity(scores):
    """Bool mask of the slots in a top-k result that hold a REAL score.

    When fewer than ``k`` items are valid (sparse ``item_valid``, a
    catalog smaller than ``k``, or all-False validity), the surplus
    slots carry the ``NEG_INF`` sentinel with arbitrary indices —
    callers must trim with this mask before surfacing results.  Works
    on the output of :func:`chunked_topk_scores`, the sharded
    ``parallel.serve.topk_sharded``, and the int8 index
    (``serving.index``): all three fill invalid slots with the same
    sentinel constant.
    """
    return scores > NEG_INF


@functools.partial(jax.jit, static_argnames=("k", "item_chunk"))
def chunked_topk_scores(U, V, item_valid, k, item_chunk=8192):
    """Top-k items per user row of ``U``.

    U [n, r]; V [Ni, r]; item_valid [Ni] bool (False rows never recommended —
    padding rows and cold items).  Returns (scores [n, k], indices [n, k]).

    When a row has fewer than ``k`` valid items the remaining slots
    hold the ``NEG_INF`` sentinel score with MEANINGLESS indices (the
    running-merge init state) — apply :func:`topk_validity` to the
    scores to know which slots are real.
    """
    n, r = U.shape
    Ni = V.shape[0]
    nchunks = -(-Ni // item_chunk)
    pad = nchunks * item_chunk - Ni
    Vp = jnp.pad(V, ((0, pad), (0, 0)))
    validp = jnp.pad(item_valid, (0, pad)).astype(jnp.bool_)
    Vc = Vp.reshape(nchunks, item_chunk, r)
    validc = validp.reshape(nchunks, item_chunk)
    base = jnp.arange(nchunks, dtype=jnp.int32) * item_chunk

    init_s = jnp.full((n, k), NEG_INF, dtype=jnp.float32)
    init_i = jnp.zeros((n, k), dtype=jnp.int32)

    def step(carry, chunk):
        best_s, best_i = carry
        Vt, valid, off = chunk
        scores = jnp.einsum(
            "nr,cr->nc", U, Vt, preferred_element_type=jnp.float32
        )
        scores = jnp.where(valid[None, :], scores, NEG_INF)
        ids = off + jnp.arange(Vt.shape[0], dtype=jnp.int32)
        cat_s = jnp.concatenate([best_s, scores], axis=1)
        cat_i = jnp.concatenate([best_i, jnp.broadcast_to(ids, (n, Vt.shape[0]))], axis=1)
        new_s, sel = jax.lax.top_k(cat_s, k)
        new_i = jnp.take_along_axis(cat_i, sel, axis=1)
        return (new_s, new_i), None

    (best_s, best_i), _ = jax.lax.scan(step, (init_s, init_i), (Vc, validc, base))
    return best_s, best_i


def auto_topk_backend(rank, k):
    """The 'auto' probe walk: the fused Pallas kernel only on TPU, only
    for lane-sized k, and only after its compile-and-run probe passes —
    a Mosaic regression degrades to the scan instead of crashing
    serving.  Shared by :func:`topk_scores` and the execution planner
    (tpu_als.plan), so the warm-cache verdict and the cold walk cannot
    drift."""
    from tpu_als.ops import pallas_topk
    from tpu_als.utils.platform import on_tpu

    return ("pallas" if (on_tpu() and k <= 128
                         and pallas_topk.available(rank, k))
            else "xla")


def topk_scores(U, V, item_valid, k, item_chunk=8192, backend="auto"):
    """Top-k dispatch: the fused Pallas kernel on TPU (scores never touch
    HBM — tpu_als.ops.pallas_topk), the XLA scan elsewhere.

    backend: 'auto' (the :func:`auto_topk_backend` walk; when called
    EAGERLY with the planner armed the verdict goes through
    tpu_als.plan — a warm cache answers with zero probe executions —
    while a call under an ambient jit trace skips the planner's disk
    I/O and walks the in-process caches as before) | 'pallas' | 'xla'.
    """
    if backend == "auto":
        rank = U.shape[1]
        tracing = isinstance(U, jax.core.Tracer) \
            or isinstance(V, jax.core.Tracer)
        if not tracing:
            from tpu_als import plan as _plan

            if _plan.armed():
                backend = _plan.resolve_topk(
                    rank=rank, k=k,
                    walk=lambda: auto_topk_backend(rank, k))
        if backend == "auto":
            backend = auto_topk_backend(rank, k)
    if backend == "pallas":
        from tpu_als.ops.pallas_topk import topk_scores_pallas

        return topk_scores_pallas(U, V, item_valid, k)
    return chunked_topk_scores(U, V, item_valid, k, item_chunk=item_chunk)
