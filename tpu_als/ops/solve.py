"""Batched normal-equation build + least-squares solves — the numerics core.

This replaces the reference stack's per-row scalar path (Spark MLlib's
``NormalEquation`` accumulating ``A += x xᵀ`` one rating at a time via BLAS
``dspr``, then one LAPACK ``dppsv`` packed-Cholesky call *per entity row* —
canonical upstream ``mllib/src/main/scala/org/apache/spark/ml/recommendation/
ALS.scala``, ``NormalEquation`` / ``CholeskySolver`` / ``NNLSSolver``;
SURVEY.md §2.B5) with one **batched** einsum + Cholesky over every row of a
shard at once, which is the shape the TPU MXU wants: a handful of large
contractions instead of millions of rank-2 BLAS calls.

The solver family, exact → inexact: batched Cholesky (:func:`solve_spd`,
kernel-accelerated via tpu_als.ops.pallas_*), fixed-sweep NNLS
(:func:`solve_nnls`), and warm-started Jacobi-CG for inexact ALS —
:func:`solve_cg` on the built tensor, :func:`solve_cg_matfree` applying
the operator straight through the gathered factor rows.

The *build* side has the same exact/fused split: the einsum builds here
consume a materialized ``Vg`` gathered by XLA, while
:mod:`tpu_als.ops.pallas_gather_ne` DMA-gathers factor rows from the
HBM-resident table directly into the Gram accumulation (``Vg`` never
touches HBM — ~59% fewer modeled NE-build bytes at the headline shape,
see docs/roofline.md). Its wrappers reuse this module's weighting
expressions verbatim (:func:`implicit_weights`, the ``reg·count`` ridge)
so the fused build is bitwise-equal to :func:`normal_eq_explicit` /
:func:`normal_eq_implicit` at f32 in the single-width-chunk regime.

Shapes use the padded-CSR convention from :mod:`tpu_als.core.ratings`:

  ``Vg``   [n, w, r]  gathered opposite-side factor rows per entity
  ``vals`` [n, w]     ratings (0 in padding slots)
  ``mask`` [n, w]     1.0 for real entries, 0.0 for padding
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# The ONE default Tikhonov jitter, threaded everywhere a solve can be
# reached: every solver signature below, the Pallas probe matrices, the
# fused-kernel default, fold-in, and ``AlsConfig.jitter`` all reference
# this name.  A literal 1e-6 anywhere else is a lint finding
# (magic-jitter, tpu_als/analysis/lint.py): a drifted copy means the
# attribution twin or a probe solves a DIFFERENTLY-regularized system
# than the production step and the bitwise-equivalence pins lie.
DEFAULT_JITTER = 1e-6

# The adaptive-solve escalation ladder (resilience guardrails, docs/
# resilience.md): rungs are ABSOLUTE jitter levels tried above the
# configured base jitter, in order, before the CG fallback.  Residuals
# are judged against _ADAPTIVE_TOL relative to ||b|| — loose enough that
# a healthy f32 Cholesky always clears it on the first rung (the armed
# overhead is then one residual matvec), tight enough that a
# numerically-singular factorization (NaN/Inf backsubstitution, or a
# wildly wrong x from a near-zero pivot) fails it.
ADAPTIVE_JITTER_RUNGS = (1e-4, 1e-2)
_ADAPTIVE_TOL = 1e-2


class SolveUnstable(ArithmeticError):
    """Every rung of the adaptive solve ladder failed — the per-row
    system is beyond what jitter escalation and the CG fallback can
    stabilize (typed so callers distinguish 'the data is numerically
    hostile' from a programming error)."""

    def __init__(self, bad_rows, total_rows):
        super().__init__(
            f"adaptive SPD solve failed on {bad_rows} of {total_rows} "
            f"rows after jitter escalation {ADAPTIVE_JITTER_RUNGS} and "
            "the CG fallback — the Gram systems are numerically "
            "unsalvageable (see docs/resilience.md guardrails)")
        self.bad_rows = bad_rows
        self.total_rows = total_rows


def normal_eq_explicit(Vg, vals, mask, reg):
    """Normal equations for explicit-feedback ALS (ALS-WR weighting).

    For each entity u with rated factor rows ``v_k`` and ratings ``r_k``:

        A_u = Σ_k v_k v_kᵀ + λ·n_u·I        b_u = Σ_k r_k v_k

    λ is scaled by the per-entity rating count ``n_u`` — the "weighted-λ"
    scheme Spark ALS uses (``regParam * ne.k`` in the reference stack's solver,
    SURVEY.md §2.B5), which makes regParam roughly scale-free in dataset size.

    Returns ``(A [n,r,r], b [n,r], count [n])``.
    """
    Vm = Vg * mask[..., None]
    # Σ v vᵀ over the w axis. One MXU-friendly contraction for all n rows.
    A = jnp.einsum("nwr,nws->nrs", Vm, Vm, preferred_element_type=jnp.float32)
    b = jnp.einsum("nw,nwr->nr", vals * mask, Vg, preferred_element_type=jnp.float32)
    count = jnp.sum(mask, axis=-1)
    r = Vg.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    A = A + (reg * count)[:, None, None] * eye
    return A, b, count


def implicit_weights(vals, mask, alpha):
    """Hu–Koren–Volinsky weighting: ``(c − 1, preference)``.

    THE shared formula consumed by the dense normal-equation build
    (:func:`normal_eq_implicit`) and the matrix-free CG operator
    (:func:`solve_cg_matfree`) — one site, so the two solvers cannot
    drift on the confidence/preference semantics.
    """
    conf_m1 = alpha * jnp.abs(vals) * mask          # c − 1, 0 in padding
    pref = (vals > 0).astype(vals.dtype)
    return conf_m1, pref


def normal_eq_implicit(Vg, vals, mask, reg, alpha, YtY):
    """Normal equations for implicit-feedback ALS (Hu–Koren–Volinsky).

    Confidence ``c_k = 1 + α·|r_k|``, preference ``p_k = 1 if r_k > 0 else 0``.
    Using the YᵀY trick (SURVEY.md §3.1 — the reference stack computes YtY
    once per half-step via ``treeAggregate``; here it's one einsum + psum):

        A_u = YᵀY + Σ_k (c_k − 1) v_k v_kᵀ + λ·n_u·I
        b_u = Σ_k c_k p_k v_k

    Negative ratings contribute confidence but preference 0, and — matching
    the reference solver's ``numExplicits`` — only ratings > 0 count toward
    the λ·n regularization scaling.

    Returns ``(A [n,r,r], b [n,r], count [n])``.
    """
    conf_m1, pref = implicit_weights(vals, mask, alpha)
    A = jnp.einsum(
        "nw,nwr,nws->nrs", conf_m1, Vg, Vg, preferred_element_type=jnp.float32
    )
    b = jnp.einsum(
        "nw,nwr->nr", (1.0 + conf_m1) * pref * mask, Vg,
        preferred_element_type=jnp.float32,
    )
    count = jnp.sum(pref * mask, axis=-1)
    r = Vg.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    A = A + YtY[None] + (reg * count)[:, None, None] * eye
    return A, b, count


def compute_yty(V):
    """YᵀY over all (valid) factor rows; invalid rows must be zero.

    [N, r] -> [r, r].  Under ``shard_map`` callers ``psum`` the result over the
    mesh axis — the analog of the reference stack's ``treeAggregate``.
    """
    return jnp.einsum("nr,ns->rs", V, V, preferred_element_type=jnp.float32)


def auto_solve_backend(rank):
    """THE preference-ordered probe walk for the SPD solve — the single
    source of truth shared by ``solve_spd``'s 'auto' branch,
    ``prewarm_solve``, and ``resolve_solve_path`` (core/als.py), so the
    prewarmed probes are exactly the ones the dispatch consults.

    Returns 'lanes' | 'lanes_blocked' | 'pallas' | 'xla'.  Each Pallas
    kernel engages only after its compile-and-validate probe passes on
    the local Mosaic (probes are cached per process).  'lanes' owns
    ranks <= 128 (whole working set VMEM-resident); 'lanes_blocked' owns
    ranks above (same layout, 128-blocks streamed out-of-core —
    tpu_als.ops.pallas_lanes_blocked; rank-256 config-3 path).
    """
    from tpu_als.ops import pallas_lanes, pallas_lanes_blocked, pallas_solve
    from tpu_als.utils.platform import on_tpu

    if not on_tpu():
        return "xla"
    if pallas_lanes.available(rank):
        return "lanes"
    if pallas_lanes_blocked.available(rank):
        return "lanes_blocked"
    if pallas_solve.available(rank):
        return "pallas"
    return "xla"


def prewarm_solve(rank):
    """Run the solve-kernel probes EAGERLY for this rank (cached per
    process).  Anything that jit-traces a path reaching
    ``solve_spd(backend='auto')`` must probe eagerly first: a probe cannot
    execute inside a trace (tpu_als.utils.platform.probe_kernel degrades
    that trace to the fallback path without caching), and the jit cache
    would then pin the slow path for the compiled step's lifetime.
    Callers: ``fold_in`` and ``scripts/ablate.py`` directly; the training
    step builders (``make_step`` and the tpu_als.parallel.trainer
    builders) get the same effect through their eager
    ``resolve_solve_path`` call — all of them walk the same
    :func:`auto_solve_backend` probe order.
    """
    auto_solve_backend(rank)


def _dispatch_spd(A, b, backend):
    """One batched Cholesky solve of the (already pre-regularized) A —
    the backend dispatch shared by the plain and adaptive solve_spd
    paths, so every escalation rung runs on the SAME kernel the plain
    solve would."""
    if backend == "lanes":
        from tpu_als.ops import pallas_lanes

        # forced-lanes path: validate the panel width on this Mosaic first
        # (cached per process; free after an eager prewarm).  Without this,
        # selected_panel(r) returns DEFAULT_PANEL when available() never
        # ran, and the panel=8 fused trailing update's extra [panel, r,
        # LANES] scratch could hit a VMEM/Mosaic failure the auto path's
        # probe-and-fallback would have avoided (ADVICE r2).  When the
        # probe could NOT validate a width (off-TPU, probe failure, or
        # probe-inside-trace degrade), run the rank-1 recurrence (panel=1)
        # — never an unvalidated fused update.
        r = A.shape[-1]
        ok = pallas_lanes.available(r)
        panel = pallas_lanes.selected_panel(r) if ok else 1
        mxu = pallas_lanes.selected_mxu(r) if ok else False
        return pallas_lanes.spd_solve_lanes(A, b, panel=panel, mxu=mxu)
    if backend == "lanes_blocked":
        from tpu_als.ops import pallas_lanes_blocked

        # same discipline as lanes: the MXU trailing update engages only
        # after the probe validated it on this Mosaic
        r = A.shape[-1]
        mxu = (pallas_lanes_blocked.selected_mxu(r)
               if pallas_lanes_blocked.available(r) else False)
        return pallas_lanes_blocked.spd_solve_lanes_blocked(A, b, mxu=mxu)
    if backend == "pallas":
        from tpu_als.ops.pallas_solve import spd_solve_pallas

        return spd_solve_pallas(A, b)
    L = jnp.linalg.cholesky(A)
    y = jax.scipy.linalg.solve_triangular(L, b[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(
        L, y, lower=True, trans=1
    )[..., 0]
    return x


def solve_spd(A, b, count, jitter=DEFAULT_JITTER, backend="auto",
              adaptive=False):
    """Batched SPD solve via Cholesky: x = A⁻¹ b for each row.

    Rows with ``count == 0`` (entities with no ratings in this shard — padding
    rows or cold entities) get A replaced by I so the factorization stays
    finite; their b is 0 so the solution is exactly 0.  This is the batched
    equivalent of the reference solver's per-row ``dppsv`` (SURVEY.md §2.C1).

    backend: 'auto' routes, in preference order, to (1) the batch-in-lanes
    Pallas kernel (tpu_als.ops.pallas_lanes — the serial Cholesky
    recurrence vectorized across 128 matrices in the lane dimension;
    measured 2.2x the blocked kernel at rank 128 on v5e, rank <= 128
    only), (2) the out-of-core blocked lanes kernel for ranks above 128
    (tpu_als.ops.pallas_lanes_blocked — same layout, 128-blocks streamed
    through VMEM, substitutions on XLA), (3) the VMEM blocked-Cholesky
    kernel (tpu_als.ops.pallas_solve, any rank), (4) the XLA
    cholesky/triangular_solve lowering — whose column-sequential HBM
    passes are the training-loop bottleneck at six-figure batch sizes.
    Each kernel engages only when its compile-and-validate probe passes
    on the local Mosaic version.  'lanes' / 'lanes_blocked' / 'pallas' /
    'xla' force a specific path.

    ``adaptive=True`` (the guardrails recover path, docs/resilience.md):
    the empty-row identity guard and ``jitter`` pre-regularization apply
    as always, then the solution is RESIDUAL-CHECKED — rows whose
    relative residual fails escalate through ADAPTIVE_JITTER_RUNGS
    re-solves and finally a Jacobi-CG fallback, all under one
    ``lax.cond`` so the healthy common case pays only the residual
    matvec.  Escalation happens at THIS layer, above the backend
    dispatch, so the xla / pallas_lanes / gather_fused paths all inherit
    it.  A row the full ladder cannot save keeps its (non-finite or
    residual-failing) CG answer — the host-side verdict and the typed
    :class:`SolveUnstable` live in :func:`solve_spd_checked` and the
    training sentinels (raising is impossible inside a trace).
    """
    if A.dtype == jnp.bfloat16:
        # no bf16 Cholesky lowering (and an 8-bit mantissa is hopeless for
        # a factorization anyway): solve in f32, hand back bf16.  The
        # Python-level dtype gate leaves the f32 training trace untouched.
        return solve_spd(A.astype(jnp.float32), b.astype(jnp.float32),
                         count, jitter=jitter, backend=backend,
                         adaptive=adaptive).astype(jnp.bfloat16)
    r = A.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    empty = (count <= 0)[:, None, None]
    A0 = jnp.where(empty, eye, A)
    A = A0 + jitter * eye
    if backend == "auto":
        backend = auto_solve_backend(r)
    if backend not in ("lanes", "lanes_blocked", "pallas", "xla"):
        raise ValueError(f"unknown solve backend {backend!r} (expected "
                         "'auto', 'lanes', 'lanes_blocked', 'pallas' or "
                         "'xla')")
    if not adaptive:
        return _dispatch_spd(A, b, backend)

    def _row_ok(x, Areg):
        res = jnp.einsum("nrs,ns->nr", Areg, x,
                         preferred_element_type=jnp.float32) - b
        rnorm = jnp.linalg.norm(res, axis=-1)
        bnorm = jnp.linalg.norm(b, axis=-1)
        finite = jnp.all(jnp.isfinite(x), axis=-1)
        return finite & (rnorm <= _ADAPTIVE_TOL * (bnorm + 1.0))

    x0 = _dispatch_spd(A, b, backend)
    ok0 = _row_ok(x0, A)

    def _escalate(x_first):
        xs, oks = x_first, ok0
        for rung in ADAPTIVE_JITTER_RUNGS:
            Ar = A0 + rung * eye
            xr = _dispatch_spd(Ar, b, backend)
            xs = jnp.where(oks[:, None], xs, xr)
            oks = oks | _row_ok(xr, Ar)
        # final rung: fixed-iteration Jacobi-CG on the heaviest-jittered
        # system — factorization-free, so a Cholesky that breaks down on
        # every rung still gets a descent answer
        Ac = A0 + ADAPTIVE_JITTER_RUNGS[-1] * eye
        diag = jnp.diagonal(Ac, axis1=-2, axis2=-1)

        def matvec(p):
            return jnp.einsum("nrs,ns->nr", Ac, p,
                              preferred_element_type=jnp.float32)

        warm = jnp.where(jnp.isfinite(xs), xs, 0.0)
        xc = pcg(matvec, b, diag, x0=warm, iters=min(2 * r, 32))
        return jnp.where(oks[:, None], xs, xc)

    return jax.lax.cond(jnp.all(ok0), lambda x: x, _escalate, x0)


def solve_spd_checked(A, b, count, jitter=DEFAULT_JITTER, backend="auto"):
    """Eager adaptive solve with a host-side verdict: runs the full
    escalation ladder and raises the typed :class:`SolveUnstable` when
    rows remain non-finite or residual-failing after every rung — the
    'all rungs fail' contract a jitted caller cannot enforce itself."""
    x = solve_spd(A, b, count, jitter=jitter, backend=backend,
                  adaptive=True)
    r = A.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    empty = (count <= 0)[:, None, None]
    A0 = jnp.where(empty, eye, A)
    # a row is salvaged if its answer satisfies ANY rung's system: a row
    # solved cleanly at base jitter must not be judged against the
    # heaviest-rung regularization it never needed
    ok = jnp.zeros(x.shape[0], dtype=bool)
    bnorm = jnp.linalg.norm(b, axis=-1)
    for rung in (jitter,) + ADAPTIVE_JITTER_RUNGS:
        res = jnp.einsum("nrs,ns->nr", A0 + rung * eye, x,
                         preferred_element_type=jnp.float32) - b
        ok = ok | (jnp.linalg.norm(res, axis=-1)
                   <= _ADAPTIVE_TOL * (bnorm + 1.0))
    bad = ~(jnp.all(jnp.isfinite(x), axis=-1) & ok)
    nbad = int(jnp.sum(bad))
    if nbad:
        raise SolveUnstable(nbad, int(x.shape[0]))
    return x


def pcg(matvec, b, diag, x0=None, iters=3):
    """Generic batched Jacobi-preconditioned CG, fixed iterations.

    ``matvec``: callable [n, r] -> [n, r] applying the (batched) SPD
    operator; ``diag`` [n, r]: its diagonal (the Jacobi preconditioner).
    Shared engine of :func:`solve_cg` (dense A) and the matrix-free
    half-step path (tpu_als.core.als.local_half_step), which applies A
    through the gathered factor rows without ever materializing the
    [n, r, r] tensor.
    """
    x = jnp.zeros_like(b) if x0 is None else x0.astype(b.dtype)
    res = b - matvec(x)
    z = res / diag
    p = z
    rz = jnp.einsum("nr,nr->n", res, z)

    def body(_, carry):
        x, res, p, rz = carry
        Ap = matvec(p)
        denom = jnp.einsum("nr,nr->n", p, Ap)
        alpha = rz / jnp.maximum(denom, 1e-30)
        x = x + alpha[:, None] * p
        res = res - alpha[:, None] * Ap
        z = res / diag
        rz_new = jnp.einsum("nr,nr->n", res, z)
        beta = rz_new / jnp.maximum(rz, 1e-30)
        p = z + beta[:, None] * p
        return x, res, p, rz_new

    x, _, _, _ = jax.lax.fori_loop(0, iters, body, (x, res, p, rz))
    return x


def solve_cg(A, b, count, x0=None, iters=3, jitter=DEFAULT_JITTER):
    """Batched Jacobi-preconditioned conjugate gradient, fixed iterations.

    The Takács–Pilászy approach for ALS (Applications of the conjugate
    gradient method for implicit feedback collaborative filtering, 2011):
    instead of factorizing each A (r³/3 serial-recurrence work — the
    measured 80% of the on-chip iteration, VPU-bound at ~1% MFU), run a
    few CG steps whose cost is one batched matvec each
    (``einsum('nrs,ns->nr')`` — a [n, r, r] × [n, r] contraction the MXU
    executes at high utilization).  With ``x0`` warm-started from the
    previous ALS iterate the outer fixed-point iteration converges to the
    same solution (inexact ALS): each half-step only needs to reduce the
    residual below the progress the outer loop makes, which 2-3 steps do.

    Same contract as :func:`solve_spd`: rows with ``count <= 0`` get
    A := I, and since their b is 0 the first CG step lands exactly on
    x = 0 even from a nonzero warm start (α = 1, residual −x₀) — cold
    entities keep the zero-factor semantic.

    Fixed ``iters`` keeps the trip count static for XLA (same stance as
    the fixed-sweep NNLS, SURVEY.md §7 hard-part 4).
    """
    r = A.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    empty = (count <= 0)[:, None, None]
    A = jnp.where(empty, eye, A) + jitter * eye
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)          # Jacobi precond

    def matvec(p):
        return jnp.einsum("nrs,ns->nr", A, p,
                          preferred_element_type=jnp.float32)

    return pcg(matvec, b, diag, x0=x0, iters=iters)


def solve_cg_matfree(Vg, vals, mask, reg, implicit=False, alpha=1.0,
                     YtY=None, x0=None, iters=3, jitter=DEFAULT_JITTER):
    """Matrix-free inexact solve: warm-started Jacobi-CG where A is
    applied THROUGH the gathered factor rows —

        A·p = YtY·p + Vgᵀ((c−1) ⊙ (Vg·p)) + (λn + jitter)·p

    — so the [n, r, r] normal-equation tensor is never materialized: the
    NE einsum and A's HBM round-trips both disappear; what remains per CG
    step is two nnz-proportional contractions the MXU runs well.

    ``Vg`` may be reduced precision (bfloat16): the big tensor stays
    narrow in HBM while every reduction and every Krylov intermediate
    accumulates in f32 (mixed-dtype einsums promote — the dense path
    builds A once with f32 accumulation, and this path must not add
    per-iteration bf16 rounding the dense path doesn't have).

    Same weighting formulas as the dense build (:func:`implicit_weights`,
    the ``numExplicits`` count rule) and same cold-row contract as
    :func:`solve_spd`: rows with count 0 act as A := I, b = 0, landing
    exactly on x = 0 from any warm start.
    """
    dt = Vg.dtype
    mA = mask.astype(dt)
    vA = vals.astype(dt)
    if implicit:
        w_conf, pref = implicit_weights(vA, mA, alpha)
        rhs = jnp.einsum("nw,nwr->nr", (1.0 + w_conf) * pref * mA, Vg,
                         preferred_element_type=jnp.float32)
        count = jnp.sum(pref.astype(jnp.float32) * mask, axis=-1)
    else:
        w_conf = mA
        rhs = jnp.einsum("nw,nwr->nr", vA * mA, Vg,
                         preferred_element_type=jnp.float32)
        count = jnp.sum(mask, axis=-1)
    rhs = rhs.astype(jnp.float32)
    w32 = w_conf.astype(jnp.float32)
    ridge = (reg * count + jitter)[:, None]
    empty = (count <= 0)[:, None]
    diag = jnp.einsum("nw,nwr->nr", w_conf, Vg * Vg,
                      preferred_element_type=jnp.float32) + ridge
    YtYf = YtY.astype(jnp.float32) if implicit else None
    if YtYf is not None:
        diag = diag + jnp.diagonal(YtYf)[None, :]
    diag = jnp.where(empty, 1.0, diag)

    def matvec(p):
        # mixed-dtype einsums: p/t stay f32, only Vg is (possibly) bf16
        t = jnp.einsum("nwr,nr->nw", Vg, p,
                       preferred_element_type=jnp.float32)
        mv = jnp.einsum("nw,nwr->nr", w32 * t, Vg,
                        preferred_element_type=jnp.float32)
        mv = mv + ridge * p
        if YtYf is not None:
            mv = mv + p @ YtYf
        # empty rows (chunk padding / cold entities): A := I so CG lands
        # exactly on x = 0 (their b is 0)
        return jnp.where(empty, p, mv)

    return pcg(matvec, rhs, diag, x0=x0, iters=iters)


@functools.partial(jax.jit, static_argnames=("sweeps", "jitter"))
def solve_nnls(A, b, count, sweeps=32, jitter=DEFAULT_JITTER):
    """Batched nonnegative least squares via cyclic coordinate descent.

    Replaces the reference stack's projected-CG ``NNLSSolver``
    (``mllib/.../optimization/NNLS.scala``, SURVEY.md §2.B5) with a
    fixed-iteration, jittable scheme: for SPD A, cyclic CD on
    ½xᵀAx − bᵀx subject to x ≥ 0 converges monotonically; a fixed number of
    sweeps keeps shapes/trip-counts static for XLA (SURVEY.md §7 hard-part 4).
    """
    r = A.shape[-1]
    eye = jnp.eye(r, dtype=A.dtype)
    empty = (count <= 0)[:, None, None]
    A = jnp.where(empty, eye, A) + jitter * eye
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)  # [n, r]

    x0 = jnp.zeros_like(b)

    def sweep(x, _):
        def coord(j, x):
            # residual_j = (A x - b)_j ; x_j <- max(0, x_j - residual_j / A_jj)
            Ax_j = jnp.einsum("nr,nr->n", A[:, j, :], x)
            xj = jnp.maximum(0.0, x[:, j] - (Ax_j - b[:, j]) / diag[:, j])
            return x.at[:, j].set(xj)

        x = jax.lax.fori_loop(0, r, coord, x)
        return x, None

    x, _ = jax.lax.scan(sweep, x0, None, length=sweeps)
    return x
