from tpu_als.ops.solve import (  # noqa: F401
    normal_eq_explicit,
    normal_eq_implicit,
    solve_spd,
    solve_nnls,
    compute_yty,
)
from tpu_als.ops.topk import chunked_topk_scores  # noqa: F401
