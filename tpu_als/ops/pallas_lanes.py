"""Pallas TPU kernel: batched SPD solve with the BATCH dimension in lanes.

Second-generation layout for the ALS solve (see tpu_als.ops.pallas_solve
for the first): instead of tiling matrices over the batch dimension and
running the Cholesky recurrence with masked lane reductions and one-hot
MXU extractions, this kernel lays the working set out as ``S[a, b, t] =
A_t[b, a]`` with ``t`` (the matrix index) in the 128-wide LANE dimension.
The serial column recurrence then vectorizes across 128 matrices at once
and every per-column step becomes a *static sublane slice*:

  * column ``j`` of all 128 matrices is ``S[j]`` — a [r, 128] slice, no
    masked reduction;
  * the pivot ``d = S[j, j]`` is a [128] vector — no lane extraction;
  * the rank-1 trailing update is one broadcast multiply-subtract over
    ``[r, r, 128]`` — no one-hot selector matmuls.

The trade: a plain MXU matmul cannot batch over lanes, so the original
trailing update ran on the VPU at r³ (vs the blocked scheme's r³/3 + MXU
panels).  What the layout buys is the removal of every cross-lane
reduction and selector dot from the serial chain — which is what actually
bounds the first-generation kernel (measured: its runtime is invariant to
the batch-tile size, so it is latency-, not throughput-, bound).

Third-generation refinement (``mxu=True``): the serial chain keeps the
lanes layout, but the rank-``panel`` trailing update — the only O(r²·P)
dense block, and the part that swept all of S per panel on the VPU — is
re-expressed as ONE lane-batched ``dot_general`` (batch dim = lanes,
contraction over the panel axis): per lane, an honest [r, P]·[P, r] GEMM
the MXU runs as a systolic pass.  The cost is two in-register layout
rotations around the GEMM (batch-leading in, lane-trailing out); whether
that trade wins on the local Mosaic is exactly what the ``available()``
probe ladder decides — the MXU panel is tried first and the VPU panel /
rank-1 recurrences remain the validated fallbacks, so a Mosaic that
rejects (or mis-lowers) minormost-batch contractions degrades instead of
crashing.

Substitution uses the same layout: y and x live as [r, 128] panels and
each forward/backward step is a [128]-wide vector operation.

Same contract as ``spd_solve_pallas``: caller pre-regularizes A (jitter +
empty-row identity guard); rows with b = 0 solve to x = 0.  Replaces the
reference stack's per-entity LAPACK ``dppsv`` (Spark MLlib
``CholeskySolver``, SURVEY.md §2.B5/C1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_als.ops.ring_buffer import local_copy

LANES = 128

# MXU contractions inside the factorization run at HIGHEST precision: the
# default f32 path is a single bf16 pass whose ~4e-3 relative error
# COMPOUNDS through the Cholesky recurrence (the pallas_solve round-1
# lesson) — HIGHEST restores ~1e-6 and the GEMM is a small fraction of
# kernel time next to the serial column chain.
_PREC = jax.lax.Precision.HIGHEST


def _chol_lanes_kernel(A_ref, b_ref, x_ref, S, Pn, sem, *, r, panel, mxu):
    """One lane-group: factorize 128 matrices and solve.

    A_ref [G, r, r, LANES] stays in HBM (``memory_space=ANY``) with
    A_ref[g, a, b, t] = A_t[b, a] (column-major per matrix so column j is
    a leading-axis slice); the kernel DMAs group ``g`` straight into the
    working scratch ``S`` [r, r, LANES] — at r=128 the group is 8 MB, so a
    pipelined (double-buffered) input block plus the scratch would blow
    the 16 MiB VMEM limit, and the copy (~10 µs at HBM bandwidth) is
    negligible against the factorization anyway.  b_ref / x_ref
    [1, r, LANES].  After the loop S[j] holds column j of L (entries above
    the diagonal zeroed).

    ``panel`` > 1 runs the recurrence in panels of that many columns:
    left-looking factorization of the panel against the scratch ``Pn``
    [panel, r, LANES], then ONE fused rank-``panel`` trailing update pass
    over S instead of ``panel`` rank-1 passes.  The update is what bounds
    this kernel (it sweeps all of S per column), so its VMEM traffic —
    and the kernel's runtime — drops by ~``panel``×.  panel=1 is the
    original rank-1 recurrence.

    ``mxu=True`` additionally moves that trailing update off the VPU: the
    rank-``panel`` correction ``upd[a, b, t] = Σ_k Pn[k, a, t]·Pn[k, b, t]``
    is one ``dot_general`` with the LANE axis as the batch dimension —
    per lane a [r, panel]·[panel, r] GEMM, i.e. 128 MXU passes per panel
    instead of an O(r²·panel·LANES) VPU broadcast sweep.  The serial
    panel factorization (the latency-bound part the lanes layout exists
    for) is unchanged.
    """
    g = pl.program_id(0)
    cp = local_copy(A_ref.at[g], S, sem)
    cp.start()
    cp.wait()
    sub = jax.lax.broadcasted_iota(jnp.int32, (r, LANES), 0)  # row index b

    def col(j, _):
        cj = S[j]                                   # [r, LANES]
        d = jnp.sum(jnp.where(sub == j, cj, 0.0), axis=0)     # pivot [LANES]
        inv = jax.lax.rsqrt(jnp.maximum(d, 1e-30))
        ncol = jnp.where(sub >= j, cj * inv[None, :], 0.0)    # L[:, j]
        # trailing rank-1 update, unmasked over the column axis: ncol is
        # zero above row j, so columns a < j receive no update, and
        # columns a <= j are never read again anyway — skipping the
        # where-mask pass is free
        S[:] = S[:] - ncol[:, None, :] * ncol[None, :, :]
        # column j itself was hit by the update (a == j); store the factor
        S[j] = ncol
        return 0

    def panel_step(ip, _):
        base = ip * panel
        # left-looking factorization of the panel columns: corrections
        # from columns inside the panel come from Pn (their trailing
        # update hasn't been applied to S yet)
        for jj in range(panel):
            j = base + jj
            cj = S[j]
            for kk in range(jj):
                Lk = Pn[kk]
                lkj = jnp.sum(jnp.where(sub == j, Lk, 0.0), axis=0)
                cj = cj - Lk * lkj[None, :]
            d = jnp.sum(jnp.where(sub == j, cj, 0.0), axis=0)
            inv = jax.lax.rsqrt(jnp.maximum(d, 1e-30))
            Pn[jj] = jnp.where(sub >= j, cj * inv[None, :], 0.0)
        # one fused rank-`panel` trailing update.  Columns a < base are
        # untouched (factor columns are zero above their pivot row); the
        # panel's own columns ARE hit...
        if mxu:
            # lane-batched GEMM: upd[t, a, b] = Σ_k Pn[k,a,t]·Pn[k,b,t]
            # — per lane an [r, panel]·[panel, r] MXU contraction; the
            # transpose back to the [a, b, t] working layout is the
            # price of admission the probe ladder adjudicates
            upd = jax.lax.dot_general(
                Pn[:], Pn[:],
                dimension_numbers=(((0,), (0,)), ((2,), (2,))),
                preferred_element_type=jnp.float32, precision=_PREC,
            )  # [LANES, r, r]
            S[:] = S[:] - jnp.transpose(upd, (1, 2, 0))
        else:
            upd = Pn[0][:, None, :] * Pn[0][None, :, :]
            for kk in range(1, panel):
                upd = upd + Pn[kk][:, None, :] * Pn[kk][None, :, :]
            S[:] = S[:] - upd
        # ...and restored, same trick as the rank-1 recurrence above
        for jj in range(panel):
            S[base + jj] = Pn[jj]
        return 0

    if panel > 1:
        jax.lax.fori_loop(0, r // panel, panel_step, 0, unroll=False)
    else:
        jax.lax.fori_loop(0, r, col, 0, unroll=False)

    # forward substitution L y = b: y_j = (b_j - Σ_{k<j} L[j,k] y_k)/L[j,j]
    def fwd(j, res):
        cj = S[j]                                   # column j of L [r, LANES]
        d = jnp.sum(jnp.where(sub == j, cj, 0.0), axis=0)
        yj = jnp.sum(jnp.where(sub == j, res, 0.0), axis=0) / d
        # subtract y_j * L[b, j] from all later rows b > j
        res = jnp.where(sub > j, res - yj[None, :] * cj, res)
        res = jnp.where(sub == j, yj[None, :], res)
        return res

    y = jax.lax.fori_loop(0, r, fwd, b_ref[0], unroll=False)

    # backward substitution Lᵀ x = y: x_j = (y_j - Σ_{k>j} L[k,j] x_k)/L[j,j]
    def bwd(t, res):
        j = r - 1 - t
        cj = S[j]
        d = jnp.sum(jnp.where(sub == j, cj, 0.0), axis=0)
        # Σ_{k>j} L[k, j] x_k: column j of L holds exactly those entries
        s = jnp.sum(jnp.where(sub > j, cj * res, 0.0), axis=0)
        xj = (jnp.sum(jnp.where(sub == j, res, 0.0), axis=0) - s) / d
        res = jnp.where(sub == j, xj[None, :], res)
        return res

    x_ref[0] = jax.lax.fori_loop(0, r, bwd, y, unroll=False)


# default trailing-update panel width for the VPU update; chosen on v5e
# (scripts/kernel_lab.py sweep at the headline shape) — see available()
# which validates the configured width on the local Mosaic before the
# kernel engages
DEFAULT_PANEL = 8
# default panel width for the MXU (lane-batched GEMM) trailing update:
# wider panels amortize the two layout rotations around the GEMM and keep
# the [r, panel] operand a full systolic pass; 32 balances that against
# the left-looking panel factorization's O(panel²) serial work
DEFAULT_MXU_PANEL = 32


@functools.partial(jax.jit, static_argnames=("panel", "mxu", "interpret"))
def spd_solve_lanes(A, b, panel=None, mxu=False, interpret=False):
    """Batched SPD solve x = A⁻¹ b.  A [N, r, r] f32, b [N, r] f32.

    Drop-in for ``spd_solve_pallas``; transposes to the lanes layout on
    device (one XLA transpose each way, fused into neighbours where
    possible).  ``panel``: trailing-update panel width (must divide the
    padded rank; None = the variant default, capped to the padded rank).
    ``mxu``: run the trailing update as a lane-batched MXU GEMM instead
    of the VPU broadcast sweep — pass ``selected_mxu(rank)`` so only a
    probe-validated variant engages (the auto dispatch in
    tpu_als.ops.solve does).
    """
    N, r = b.shape
    r_pad = -(-r // 8) * 8
    if panel is None:
        panel = DEFAULT_MXU_PANEL if mxu else DEFAULT_PANEL
    panel = min(panel, r_pad)
    while r_pad % panel:
        panel -= 1
    n_pad = -(-N // LANES) * LANES
    eye_tail = jnp.eye(r_pad, dtype=jnp.float32)[None, :, :]
    Ap = jnp.pad(A, ((0, n_pad - N), (0, r_pad - r), (0, r_pad - r)))
    diag_fix = jnp.where(
        (jax.lax.broadcasted_iota(jnp.int32, (1, r_pad, r_pad), 1) >= r)
        | (jnp.arange(n_pad)[:, None, None] >= N),
        eye_tail, 0.0,
    )
    Ap = Ap + diag_fix
    bp = jnp.pad(b, ((0, n_pad - N), (0, r_pad - r)))

    # [N, b, a] -> [G, a, b, t]: column-major per matrix, batch in lanes
    G = n_pad // LANES
    At = jnp.transpose(
        Ap.reshape(G, LANES, r_pad, r_pad), (0, 3, 2, 1))
    bt = jnp.transpose(bp.reshape(G, LANES, r_pad), (0, 2, 1))

    kernel = functools.partial(_chol_lanes_kernel, r=r_pad, panel=panel,
                               mxu=mxu)
    xt = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, r_pad, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r_pad, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((G, r_pad, LANES), jnp.float32),
        scratch_shapes=[pltpu.VMEM((r_pad, r_pad, LANES), jnp.float32),
                        pltpu.VMEM((max(panel, 1), r_pad, LANES),
                                   jnp.float32),
                        pltpu.SemaphoreType.DMA],
        cost_estimate=pl.CostEstimate(
            flops=int(n_pad * (r_pad ** 3 + 4 * r_pad ** 2)),
            bytes_accessed=(n_pad * r_pad * r_pad + 2 * n_pad * r_pad) * 4,
            transcendentals=n_pad * r_pad,
        ),
        interpret=interpret,
    )(At, bt)
    x = jnp.transpose(xt, (0, 2, 1)).reshape(n_pad, r_pad)
    return x[:N, :r]


from tpu_als.utils.platform import probe_cache as _probe_cache

_AVAILABLE = _probe_cache("pallas_lanes")  # r_pad -> bool, once per process
_PANEL = {}      # r_pad -> panel width that validated on this Mosaic
_MXU = {}        # r_pad -> True when the MXU trailing update validated


def selected_panel(rank):
    """Panel width ``available()`` validated for this rank (DEFAULT_PANEL
    until a probe has run)."""
    r_pad = -(-rank // 8) * 8
    return _PANEL.get(r_pad, DEFAULT_PANEL)


def selected_mxu(rank):
    """True when ``available()`` validated the MXU (lane-batched GEMM)
    trailing update for this rank on the local Mosaic; False until a
    probe has run — an unvalidated MXU update never engages (the same
    discipline as selected_panel)."""
    r_pad = -(-rank // 8) * 8
    return _MXU.get(r_pad, False)


def supported_rank(rank):
    """VMEM feasibility: the [r, r, LANES] scratch must fit alongside the
    b/x blocks — r_pad = 128 uses 8 MiB of the 16 MiB scoped limit; the
    next multiple of 8 over 128 is already pushing 10+ MiB with DMA
    staging.  Ranks above 128 are owned by the out-of-core blocked
    variant of this layout (tpu_als.ops.pallas_lanes_blocked), with
    tpu_als.ops.pallas_solve as the probe fallback."""
    r_pad = -(-rank // 8) * 8
    return r_pad <= 128


def available(rank=128):
    """True when the kernel compiles AND produces correct results on the
    local TPU at this rank — validated against the XLA lowering on a
    random SPD batch (same standard as pallas_solve.available)."""
    from tpu_als.utils.platform import probe_kernel

    r_pad = -(-rank // 8) * 8
    if not supported_rank(rank):
        return False

    def probe():
        import numpy as np

        from tpu_als.ops.solve import DEFAULT_JITTER, solve_spd

        n, r = LANES + 8, r_pad  # force 2 lane groups + batch padding
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, r, r)).astype(np.float32) / np.sqrt(r)
        A = jnp.asarray(
            M @ np.swapaxes(M, 1, 2)
            + 0.5 * np.eye(r, dtype=np.float32)[None])
        b = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
        ref = solve_spd(A, b, jnp.ones((n,), jnp.float32), backend="xla")
        # MXU panel GEMM first (the rank-k trailing update on the
        # systolic array), then the VPU panel sweep, then rank-1 — each
        # rung a strictly simpler lowering, so whatever this Mosaic
        # version rejects degrades one rung instead of losing the kernel
        for p, mx in ((DEFAULT_MXU_PANEL, True), (DEFAULT_PANEL, False),
                      (1, False)):
            try:
                x = spd_solve_lanes(A + DEFAULT_JITTER * jnp.eye(r), b,
                                    panel=p, mxu=mx)
                x.block_until_ready()
                ok = np.allclose(np.asarray(x), np.asarray(ref), atol=1e-3,
                                 rtol=1e-2)
            except Exception as e:
                from tpu_als.utils.platform import classify_probe_error

                if classify_probe_error(e) != "kernel":
                    # transient tunnel drop -> probe_kernel's retry;
                    # tracer leak -> probe_kernel degrades WITHOUT
                    # caching instead of pinning False
                    raise
                ok = False
            if ok:
                _PANEL[r_pad] = min(p, r_pad)
                _MXU[r_pad] = mx
                return True
        return False

    return probe_kernel(_AVAILABLE, r_pad, probe)
