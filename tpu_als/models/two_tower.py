"""Two-tower neural retrieval warm-started from ALS factors.

BASELINE.json config 5 (stretch): "Two-tower neural retrieval warm-started
from ALS factors — stretch ALS backend into learned embeddings".  The
reference stack has no neural models; this extends the framework beyond
parity: user/item embedding tables initialized from the fitted ALS factor
matrices, a small MLP tower per side, trained with in-batch sampled-softmax
(the standard retrieval objective) under optax, everything jitted.

Scoring shares the serving path with ALS: tower outputs are plain [N, d]
matrices, so ``chunked_topk_scores`` serves both model families.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
import optax

from tpu_als.ops.topk import chunked_topk_scores


@dataclass(frozen=True)
class TwoTowerConfig:
    embed_dim: int = 32
    hidden: tuple = (64,)
    out_dim: int = 32
    learning_rate: float = 1e-3
    batch_size: int = 4096
    epochs: int = 5
    temperature: float = 0.1
    seed: int = 0
    # logQ sampled-softmax correction: in-batch negatives are sampled with
    # probability proportional to item popularity, which biases the softmax
    # against popular items; subtracting log q(item) from each candidate
    # logit (the standard dual-encoder correction) removes the bias.  On
    # power-law data this is the difference between learning preferences
    # and learning an inverted-popularity table.
    popularity_correction: bool = True
    # learning-rate multiplier for the embedding TABLES only (towers
    # always train at learning_rate).  The warm-start preservation knob:
    # 0.0 freezes ALS-warm-started tables outright (only the towers
    # adapt), values in (0, 1) slow table drift so few-epoch training
    # can't wash out the CF signal it started from.  1.0 = one optimizer
    # for everything (identical to the pre-knob behavior).
    embed_lr_scale: float = 1.0


def init_params(key, num_users, num_items, cfg: TwoTowerConfig,
                als_user_factors=None, als_item_factors=None):
    """Embedding tables (ALS warm start when factors are given — padded or
    truncated to ``embed_dim``) + per-side MLP towers."""

    def embed(k, n, warm):
        e = 0.05 * jax.random.normal(k, (n, cfg.embed_dim), dtype=jnp.float32)
        if warm is not None:
            warm = jnp.asarray(warm, dtype=jnp.float32)
            r = min(warm.shape[1], cfg.embed_dim)
            e = e.at[:, :r].set(warm[:, :r])
        return e

    def mlp(k, dims):
        layers = []
        n_layers = len(dims) - 1
        for li, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
            k, kw = jax.random.split(k)
            w = jax.random.normal(kw, (din, dout)) * jnp.sqrt(2.0 / din)
            if li == n_layers - 1:
                # zero-init the final layer: with the residual connection in
                # _tower the towers start as the identity, so an ALS warm
                # start is exact at epoch 0 and training only refines it
                w = jnp.zeros_like(w)
            layers.append({"w": w, "b": jnp.zeros(dout)})
        return layers

    ku, ki, kmu, kmi = jax.random.split(key, 4)
    dims = (cfg.embed_dim,) + tuple(cfg.hidden) + (cfg.out_dim,)
    return {
        "user_embed": embed(ku, num_users, als_user_factors),
        "item_embed": embed(ki, num_items, als_item_factors),
        "user_tower": mlp(kmu, dims),
        "item_tower": mlp(kmi, dims),
    }


def _tower(layers, x):
    h = x
    for i, lyr in enumerate(layers):
        h = h @ lyr["w"] + lyr["b"]
        if i + 1 < len(layers):
            h = jax.nn.relu(h)
    if h.shape[-1] == x.shape[-1]:
        h = h + x  # residual: identity at init (final layer is zero-init)
    return h / jnp.maximum(jnp.linalg.norm(h, axis=-1, keepdims=True), 1e-6)


def user_repr(params, u_idx):
    return _tower(params["user_tower"], params["user_embed"][u_idx])


def item_repr(params, i_idx):
    return _tower(params["item_tower"], params["item_embed"][i_idx])


def in_batch_softmax_loss(params, u_idx, i_idx, weights, temperature,
                          log_q=None):
    """Sampled softmax with in-batch negatives: every other item in the
    batch is a negative for each (user, item) positive.

    ``log_q`` [num_items]: log of each item's sampling probability (its
    empirical share of training interactions).  When given, candidate
    logits are corrected by −log q(item) so popularity-proportional
    in-batch sampling doesn't bias scores (standard logQ correction).
    """
    zu = user_repr(params, u_idx)
    zi = item_repr(params, i_idx)
    logits = (zu @ zi.T) / temperature
    if log_q is not None:
        logits = logits - log_q[i_idx][None, :]
    labels = jnp.arange(zu.shape[0])
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(weights), 1e-6)


def train_two_tower(u_idx, i_idx, num_users, num_items,
                    cfg: TwoTowerConfig = TwoTowerConfig(),
                    als_user_factors=None, als_item_factors=None,
                    weights=None, callback=None):
    """Train on positive (user, item) interactions.  Returns params."""
    u_idx = np.asarray(u_idx)
    i_idx = np.asarray(i_idx)
    n = len(u_idx)
    weights = (np.ones(n, dtype=np.float32) if weights is None
               else np.asarray(weights, dtype=np.float32))

    key = jax.random.PRNGKey(cfg.seed)
    key, kinit = jax.random.split(key)
    params = init_params(kinit, num_users, num_items, cfg,
                         als_user_factors, als_item_factors)
    if cfg.embed_lr_scale == 1.0:
        tx = optax.adam(cfg.learning_rate)
    else:
        emb_tx = (optax.set_to_zero() if cfg.embed_lr_scale == 0.0
                  else optax.adam(cfg.learning_rate * cfg.embed_lr_scale))
        tx = optax.multi_transform(
            {"embed": emb_tx, "tower": optax.adam(cfg.learning_rate)},
            param_labels=lambda p: {
                "user_embed": "embed", "item_embed": "embed",
                "user_tower": jax.tree.map(lambda _: "tower",
                                           p["user_tower"]),
                "item_tower": jax.tree.map(lambda _: "tower",
                                           p["item_tower"]),
            })
    opt_state = tx.init(params)

    log_q = None
    if cfg.popularity_correction:
        log_q = jnp.asarray(
            log_popularity(np.bincount(i_idx, minlength=num_items)),
            dtype=jnp.float32)

    @jax.jit
    def step(params, opt_state, ub, ib, wb):
        loss, grads = jax.value_and_grad(in_batch_softmax_loss)(
            params, ub, ib, wb, cfg.temperature, log_q)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    bs = min(cfg.batch_size, n)
    steps_per_epoch = max(1, n // bs)
    rng = np.random.default_rng(cfg.seed)
    for epoch in range(cfg.epochs):
        perm = rng.permutation(n)
        losses = []
        for s in range(steps_per_epoch):
            sel = perm[s * bs:(s + 1) * bs]
            if len(sel) < bs:  # keep shapes static for the jit cache
                sel = np.concatenate([sel, perm[:bs - len(sel)]])
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(u_idx[sel]),
                jnp.asarray(i_idx[sel]), jnp.asarray(weights[sel]))
            losses.append(float(loss))
        if callback is not None:
            callback(epoch + 1, float(np.mean(losses)), params)
    return params


def ban_lists(users, train_u, train_i, user_batch):
    """Partition each eval user's train items into user batches — the
    filtered protocol's exclusion machinery, shared by :func:`recall_at_k`
    and the benchmark's oracle ceiling (bench.py) so the two metrics can
    never drift onto different protocols.

    ``users`` must be sorted (np.unique output).  Returns ``(tpos, tit,
    bounds)``: train positions into ``users`` (stable-sorted), their item
    ids, and ``bounds[bi]:bounds[bi+1]`` slicing batch ``bi``'s bans
    (rows re-base as ``tpos - bi*user_batch``).
    """
    tu = np.asarray(train_u)
    ti = np.asarray(train_i)
    keep = np.isin(tu, users)
    tpos = np.searchsorted(users, tu[keep])
    tit = np.asarray(ti[keep])
    order = np.argsort(tpos, kind="stable")
    tpos, tit = tpos[order], tit[order]
    bounds = np.searchsorted(
        tpos, np.arange(0, len(users) + user_batch, user_batch))
    return tpos, tit, bounds


def log_popularity(item_counts):
    """Add-1-smoothed log empirical item popularity, ``log q(item)``.

    THE shared formula behind three sites that must agree exactly: the
    training logQ correction (:func:`train_two_tower`), the serving prior
    (:func:`serving_bias` — which exists to add back precisely what
    training removed), and the benchmark's Bayes oracle ceiling
    (bench.py).  A divergence between them would silently break the
    'serving = oracle form' premise.
    """
    counts = np.asarray(item_counts, dtype=np.float64)
    q = (counts + 1.0) / (counts.sum() + len(counts))
    return np.log(q)


def serving_bias(item_counts, temperature):
    """Popularity prior for serving: ``temperature · log q(item)``.

    The towers are TRAINED with the logQ correction (preference scores,
    popularity removed), but when the target distribution is itself
    popularity-biased — like this protocol's test draws, and like most
    real recommendation traffic — the optimal serving score adds the
    popularity prior back: ``score/T + log q``, exactly the form of the
    benchmark's Bayes oracle.  Returned pre-scaled by ``temperature`` so
    it can be passed as ``recall_at_k(..., item_bias=...)`` where scores
    are raw (un-tempered) cosines.
    """
    return (temperature * log_popularity(item_counts)).astype(np.float32)


@functools.partial(jax.jit, static_argnames=("k",))
def _banned_topk(zu_b, zi, ban_rows, ban_cols, bias, k):
    """Top-k over all items with (row, col) score entries banned.  Padding
    bans carry row == batch size (out of bounds -> scatter-dropped).
    ``bias`` [num_items] is added to every user's scores."""
    scores = jnp.einsum("nr,cr->nc", zu_b, zi,
                        preferred_element_type=jnp.float32)
    scores = scores + bias[None, :]
    scores = scores.at[ban_rows, ban_cols].set(-3.4e38, mode="drop")
    return jax.lax.top_k(scores, k)[1]


def recall_at_k(params, eval_u, eval_i, k=10, item_chunk=8192,
                exclude=None, user_batch=2048, item_bias=None):
    """Fraction of held-out (user, item) pairs whose item appears in the
    user's top-k retrieval — the config-5 metric.

    ``exclude``: optional ``(train_u, train_i)`` interaction arrays.  When
    given, each user's *training* items are removed from their candidate
    set before the top-k (the standard filtered/leave-out protocol): a
    trained model correctly ranks the items it was trained on first, so
    unfiltered top-k slots are occupied by train positives and held-out
    recall is pinned near the random floor regardless of model quality.

    ``item_bias`` [num_items]: optional additive per-item score bias —
    :func:`serving_bias` restores the popularity prior the logQ-corrected
    training removed.
    """
    eval_u = np.asarray(eval_u)
    eval_i = np.asarray(eval_i)
    num_items = params["item_embed"].shape[0]
    users, inv = np.unique(eval_u, return_inverse=True)
    zi = item_repr(params, jnp.arange(num_items))

    if exclude is None and item_bias is None:
        zu = user_repr(params, jnp.asarray(users))
        _, topk = chunked_topk_scores(
            zu, zi, jnp.ones(num_items, bool), k=k, item_chunk=item_chunk)
        topk = np.asarray(topk)
        hits = (topk[inv] == eval_i[:, None]).any(axis=1)
        return float(hits.mean())
    if exclude is None:
        exclude = (np.empty(0, np.int64), np.empty(0, np.int64))

    # bound the [user_batch, num_items] device score tensor to ~256 MB f32
    # (an explicitly small user_batch is honored — tests use it to cover
    # the multi-batch ban partitioning)
    user_batch = min(user_batch, max(64, (1 << 26) // max(num_items, 1)))

    bias = (jnp.zeros(num_items, jnp.float32) if item_bias is None
            else jnp.asarray(item_bias, dtype=jnp.float32))
    nb = len(users)
    topk = np.zeros((nb, k), dtype=np.int32)
    tpos_s, tit_s, bounds = ban_lists(users, exclude[0], exclude[1],
                                      user_batch)
    max_bans = int((bounds[1:] - bounds[:-1]).max()) if nb else 0
    # one padded size for all batches: a single jit specialization, and
    # the ban lists move to device as indices (two int32 vectors), not a
    # dense [user_batch, num_items] host bool matrix
    max_bans = max(1, 1 << (max_bans - 1).bit_length()) if max_bans else 1
    for bi, s in enumerate(range(0, nb, user_batch)):
        e = min(s + user_batch, nb)
        ub = users[s:e]
        if len(ub) < user_batch:  # static shapes for the jit cache
            ub = np.pad(ub, (0, user_batch - len(ub)))
        lo, hi = bounds[bi], bounds[bi + 1]
        rows = np.full(max_bans, user_batch, np.int32)  # pad -> row OOB
        cols = np.zeros(max_bans, np.int32)
        rows[: hi - lo] = tpos_s[lo:hi] - s
        cols[: hi - lo] = tit_s[lo:hi]
        zu_b = user_repr(params, jnp.asarray(ub))
        topk[s:e] = np.asarray(_banned_topk(
            zu_b, zi, jnp.asarray(rows), jnp.asarray(cols), bias,
            k))[: e - s]
    hits = (topk[inv] == eval_i[:, None]).any(axis=1)
    return float(hits.mean())


def save_two_tower(path, params, cfg: TwoTowerConfig, num_users,
                   num_items):
    """Persist a trained tower model: config + entity counts as JSON, the
    params pytree as one npz (leaves in ``tree_flatten`` order).  Same
    atomic-directory discipline as io.checkpoint (the reference's model
    persistence analog, SURVEY.md §2.B11, for the config-5 model)."""
    import json
    import os
    from dataclasses import asdict

    from tpu_als.io.checkpoint import atomic_install

    leaves, _ = jax.tree_util.tree_flatten(params)
    tmp = path + ".tmp"
    import shutil

    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "params.npz"),
             **{f"leaf_{k}": np.asarray(v) for k, v in enumerate(leaves)})
    with open(os.path.join(tmp, "two_tower.json"), "w") as f:
        json.dump({"class": "tpu_als.models.two_tower",
                   "config": asdict(cfg),
                   "num_users": int(num_users),
                   "num_items": int(num_items),
                   "n_leaves": len(leaves)}, f, indent=2)
    atomic_install(tmp, path)


def load_two_tower(path):
    """Restore ``(params, cfg, num_users, num_items)`` saved by
    :func:`save_two_tower`.  The pytree structure is rebuilt from a
    skeleton ``init_params`` with the saved config, so leaf order is
    stable by construction; shapes are verified leaf-by-leaf."""
    import json
    import os

    with open(os.path.join(path, "two_tower.json")) as f:
        meta = json.load(f)
    if meta.get("class") != "tpu_als.models.two_tower":
        raise ValueError(f"{path} holds a {meta.get('class')!r} save, "
                         "not a two-tower model")
    c = dict(meta["config"])
    c["hidden"] = tuple(c["hidden"])
    cfg = TwoTowerConfig(**c)
    num_users, num_items = meta["num_users"], meta["num_items"]
    skeleton = init_params(jax.random.PRNGKey(0), num_users, num_items,
                           cfg)
    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    if meta["n_leaves"] != len(leaves):
        raise ValueError(
            f"saved model has {meta['n_leaves']} leaves; this build's "
            f"structure has {len(leaves)} — config/version mismatch")
    dat = np.load(os.path.join(path, "params.npz"), allow_pickle=False)
    loaded = []
    for k, sk in enumerate(leaves):
        leaf = jnp.asarray(dat[f"leaf_{k}"])
        if leaf.shape != sk.shape:
            raise ValueError(
                f"leaf {k}: saved shape {leaf.shape} != expected "
                f"{sk.shape} (num_users/num_items/config mismatch)")
        loaded.append(leaf)
    return (jax.tree_util.tree_unflatten(treedef, loaded), cfg,
            num_users, num_items)
