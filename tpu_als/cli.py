"""Command-line entry points: train / evaluate / recommend / foldin-bench.

The reference app layer is a runnable script (SURVEY.md §2.A); this CLI is
that surface for the TPU framework:

    python -m tpu_als.cli train --data ml-100k:/path/u.data --rank 16 \\
        --max-iter 10 --output /tmp/model
    python -m tpu_als.cli train --data synthetic:10000x2000x500000 ...
    (data specs: ml-100k:PATH | csv:PATH | dat:PATH | stream:PATH |
     synthetic:UxIxN; stream: = STRING-id csv with header, byte-range
     streamed — under --per-host-data each pod host reads only its own
     range of the ONE shared file and ids are agreed collectively)
    python -m tpu_als.cli evaluate --model /tmp/model --data ...
    python -m tpu_als.cli recommend --model /tmp/model --users 1,2,3 --k 10
    python -m tpu_als.cli foldin-bench --model /tmp/model
"""

from __future__ import annotations

import argparse
import json
import math
import sys

import numpy as np


def _vocab_lookup(labels, g):
    """Positions of ``labels`` in the sorted vocabulary ``g`` plus a
    known-mask, width-normalized once per array (shared by the eval and
    fold-in loaders — one definition, reviewer r5)."""
    import numpy as np

    w = max(labels.dtype.itemsize, g.dtype.itemsize, 1)
    lw = labels.astype(f"S{w}")
    gw = g.astype(f"S{w}")
    pos = np.searchsorted(gw, lw)
    known = np.zeros(len(labels), dtype=bool)
    inb = pos < len(g)
    known[inb] = gw[pos[inb]] == lw[inb]
    return pos, known


def _load_stream(path, host_index=0, num_hosts=1, vocab=None):
    """config-3-scale loader (``stream:PATH``): STRING-id ratings csv
    (``user_id,item_id,rating,timestamp`` with a header — the
    Amazon-2023 shape) streamed through the bounded-memory byte-range
    reader; ids densified into the globally-agreed (lexicographic)
    entity space.  Multi-process: each host streams only its byte range
    and the vocabularies are agreed with one collective — no ``{proc}``
    file splits needed.  Returns ``(frame, user_labels, item_labels)``
    (labels are numpy ``S``-dtype arrays, saved beside the model).

    ``vocab``: optional ``(user_labels, item_labels)`` from a trained
    model's ``stream_labels.npz`` sidecar.  Eval/serving data MUST be
    densified in the MODEL's id space — re-deriving a vocabulary from
    the eval file would silently score user b with user a's factors
    (reviewer, round 5).  Rows whose labels the model never saw are
    dropped (the cold-start ``'drop'`` semantics) with a stderr count.
    """
    import numpy as np

    from tpu_als.io.stream import (
        split_claim,
        strip_split_claims,
        stream_ingest,
        validate_split_claims,
    )
    from tpu_als.parallel.multihost import global_vocab_union
    from tpu_als.utils.frame import ColumnarFrame

    u_loc, i_loc, r, ul, il = stream_ingest(
        path, host_index, num_hosts, require_cols=4, skip_header=1)

    if vocab is None:
        # ride this host's byte-range claim through the user-vocab union
        # so a stale --num-hosts on any host fails HERE, not as silently
        # double-read/dropped ratings (io/stream.validate_split_claims)
        import jax

        claim = np.array([split_claim(host_index, num_hosts)])
        w = max(ul.dtype.itemsize, claim.dtype.itemsize, 1)
        claimed = np.concatenate([ul.astype(f"S{w}"), claim.astype(f"S{w}")])
        union = global_vocab_union(claimed)
        if jax.process_count() >= num_hosts:
            g_ul, _ = validate_split_claims(union)
        else:
            # single-process harness byte-splitting for a larger host
            # count: peer claims cannot arrive through a local union, so
            # coverage is unverifiable — strip without enforcement
            g_ul = strip_split_claims(union)
        g_il = global_vocab_union(il)
        u = np.searchsorted(g_ul, ul)[u_loc]
        i = np.searchsorted(g_il, il)[i_loc]
    else:
        g_ul, g_il = vocab
        pu, ku = _vocab_lookup(ul, g_ul)
        pi, ki = _vocab_lookup(il, g_il)
        keep = ku[u_loc] & ki[i_loc]
        dropped = int(len(u_loc) - keep.sum())
        if dropped:
            print(f"stream eval: dropped {dropped:,}/{len(u_loc):,} "
                  "rows with user/item ids unknown to the model",
                  file=sys.stderr)
        u = pu[u_loc][keep]
        i = pi[i_loc][keep]
        r = r[keep]
    return (ColumnarFrame({"user": u, "item": i, "rating": r}),
            g_ul, g_il)


def _load_train_data(args, pid=0, pcount=1):
    """The one stream-aware loader both train paths share (reviewer,
    round 5 — the spec dispatch must not live in three places).
    Returns ``(frame, stream_labels_or_None)``.

    ``stream:`` byte-range policy: a ``{proc}`` placeholder means the
    files are ALREADY per-host splits, so each host streams its whole
    expanded file (byte-splitting on top would silently drop
    (pcount-1)/pcount of every split); otherwise ``--per-host-data``
    byte-splits the one shared file, and replicated mode streams it
    whole on every host.  Vocabularies are agreed collectively in every
    multi-process case.

    ``{proc}`` expands ONLY under a real multi-process deployment: a
    single process expanding it to 0 would silently train on 1/N of the
    data where the literal path used to fail loudly (reviewer r5)."""
    spec = (args.data.replace("{proc}", str(pid)) if pcount > 1
            else args.data)
    kind, _, arg = spec.partition(":")
    if kind != "stream":
        return _load_data(spec), None
    if spec != args.data:
        host, hosts = 0, 1     # per-host FILES: stream each one whole
    elif getattr(args, "per_host_data", False):
        host, hosts = pid, pcount
    else:
        host, hosts = 0, 1
    frame, g_ul, g_il = _load_stream(arg, host, hosts)
    return frame, (g_ul, g_il)


def _model_vocab(model_dir):
    import os

    import numpy as np

    side = os.path.join(model_dir, "stream_labels.npz")
    if not os.path.exists(side):
        raise SystemExit(
            "stream: eval data needs the model's stream_labels.npz "
            "sidecar (present when the model was trained with "
            "--data stream:...); this model has none")
    z = np.load(side)
    return z["users"], z["items"]


def _load_eval_data(spec, model_dir):
    """Eval/serving-side loader: a ``stream:`` spec is densified in the
    MODEL's id space via its ``stream_labels.npz`` sidecar."""
    kind, _, arg = spec.partition(":")
    if kind != "stream":
        return _load_data(spec)
    frame, _, _ = _load_stream(arg, vocab=_model_vocab(model_dir))
    return frame


def _load_foldin_data(spec, model_dir, new_side):
    """Fold-in loader: the whole POINT of fold-in is ids the model has
    never seen, so the ``new_side`` ("user" for --foldin-data, "item"
    for --foldin-items-data) maps known labels through the sidecar and
    assigns FRESH dense ids (after the model's space, first-seen order)
    to new ones; the opposite side must be known (its factors do the
    folding) and unknown rows there are dropped with a count.

    Returns ``(frame, new_labels)`` — new_labels[j] is the original
    string id behind dense id ``len(model_side) + j``.
    """
    import numpy as np

    kind, _, arg = spec.partition(":")
    if kind != "stream":
        return _load_data(spec), []
    g_ul, g_il = _model_vocab(model_dir)
    from tpu_als.io.stream import stream_ingest
    from tpu_als.utils.frame import ColumnarFrame

    u_loc, i_loc, r, ul, il = stream_ingest(
        arg, require_cols=4, skip_header=1)

    pu, ku = _vocab_lookup(ul, g_ul)
    pi, ki = _vocab_lookup(il, g_il)
    # the keep-filter (opposite side known) runs FIRST: a new-side
    # entity whose every row is dropped must get NO fresh id — a fresh
    # id without a folded factor row would later resolve in --users and
    # serve a row the FoldInServer never solved (reviewer r5)
    if new_side == "user":
        keep = ki[i_loc]
        loc, base, labels_side = u_loc, g_ul, ul
        pos = pu
        unknown = ~ku
    else:
        keep = ku[u_loc]
        loc, base, labels_side = i_loc, g_il, il
        pos = pi
        unknown = ~ki
    surviving = np.zeros(len(labels_side), dtype=bool)
    surviving[np.unique(loc[keep])] = True
    fresh = unknown & surviving
    pos[fresh] = len(base) + np.arange(int(fresh.sum()))
    new_labels = [s.decode() for s in labels_side[fresh].tolist()]
    dropped = int(len(u_loc) - keep.sum())
    if dropped:
        opp = "item" if new_side == "user" else "user"
        print(f"stream fold-in: dropped {dropped:,}/{len(u_loc):,} "
              f"rows with {opp} ids unknown to the model (the known "
              f"{opp} factors are what fold the new {new_side}s in)",
              file=sys.stderr)
    frame = ColumnarFrame({"user": pu[u_loc][keep],
                           "item": pi[i_loc][keep], "rating": r[keep]})
    if new_labels:
        print(f"stream fold-in: {len(new_labels)} new {new_side} ids "
              f"-> dense {len(g_ul if new_side == 'user' else g_il)}+"
              f" (first-seen): {new_labels[:5]}"
              f"{'...' if len(new_labels) > 5 else ''}",
              file=sys.stderr)
    return frame, new_labels


def _save_stream_labels(out_dir, user_labels, item_labels):
    """Sidecar mapping dense ids -> original string ids, next to the
    model manifest (the stream loader's analog of persisting the fitted
    StringIndexerModels)."""
    import os

    import numpy as np

    np.savez(os.path.join(out_dir, "stream_labels.npz"),
             users=user_labels, items=item_labels)


def _load_data(spec):
    from tpu_als.io.movielens import (
        load_movielens_100k,
        load_movielens_csv,
        load_movielens_dat,
        synthetic_movielens,
    )

    kind, _, arg = spec.partition(":")
    if kind == "ml-100k":
        return load_movielens_100k(arg)
    if kind == "csv":
        return load_movielens_csv(arg)
    if kind == "dat":
        return load_movielens_dat(arg)
    if kind == "stream":
        return _load_stream(arg)[0]
    if kind == "synthetic":
        nu, ni, nnz = (int(x) for x in arg.split("x"))
        return synthetic_movielens(nu, ni, nnz)
    raise SystemExit(f"unknown data spec {spec!r} "
                     "(use ml-100k:PATH | csv:PATH | dat:PATH (ml-1m/10m "
                     "ratings.dat) | stream:PATH (string-id csv with "
                     "header, streamed) | synthetic:UxIxN)")


def _train_probe(train, test, max_rows=100_000):
    """Held-out (u_idx, i_idx, rating) triple in the DENSE id space the
    fitted model will use (``remap_ids`` over the train columns — the
    same first-seen order ``fit`` derives), for per-iteration probe RMSE.
    Test rows whose user/item never appears in train are dropped (they
    have no factors to score with); the probe is subsampled to a bounded
    size so the per-iteration host transfer stays O(1) in dataset size.
    Returns None when nothing survives."""
    from tpu_als.core.ratings import remap_ids

    if not len(test):
        return None
    _, umap = remap_ids(np.asarray(train["user"]))
    _, imap = remap_ids(np.asarray(train["item"]))
    u = umap.to_dense(np.asarray(test["user"]))
    i = imap.to_dense(np.asarray(test["item"]))
    keep = (u >= 0) & (i >= 0)
    u, i = u[keep], i[keep]
    r = np.asarray(test["rating"], dtype=np.float32)[keep]
    if not len(u):
        return None
    if len(u) > max_rows:
        step = len(u) // max_rows + 1
        u, i, r = u[::step], i[::step], r[::step]
    return u, i, r


def _iteration_cb(logger):
    """Wrap an IterationLogger so each record also lands in the metrics
    registry as an ``iteration`` event (what ``observe summarize`` reads)."""
    from tpu_als import obs

    def cb(iteration, U, V):
        logger(iteration, U, V)
        rec = logger.records[-1]
        obs.emit("iteration",
                 **{k: v for k, v in rec.items() if k != "tag"})
    return cb


def _resolve_resume(args):
    """``--resume PATH`` loads that checkpoint; ``--resume auto``
    discovers the newest VALID generation under --checkpoint-dir
    (digest-checked, corrupt generations quarantined, ``.old``
    considered) and starts fresh when none exists."""
    resume = getattr(args, "resume", None)
    if not resume:
        return None
    if resume != "auto":
        return resume
    if not getattr(args, "checkpoint_dir", None):
        raise SystemExit("--resume auto needs --checkpoint-dir (it "
                         "searches that directory for the newest valid "
                         "checkpoint)")
    from tpu_als.io.checkpoint import discover_resume

    path = discover_resume(args.checkpoint_dir)
    if path is None:
        print("--resume auto: no valid checkpoint under "
              f"{args.checkpoint_dir}; starting from scratch",
              file=sys.stderr)
    else:
        print(f"--resume auto: resuming from {path}", file=sys.stderr)
    return path


def cmd_train(args):
    from tpu_als import ALS, RegressionEvaluator, obs
    from tpu_als.resilience import preempt
    from tpu_als.utils.observe import IterationLogger

    # resolve the multi-process branch BEFORE loading data: every pod host
    # runs this same command, and _train_multiprocess does its own load —
    # loading here first would double the host I/O and peak memory
    mesh = None
    if args.devices != 1:
        import jax

        from tpu_als.parallel.mesh import make_mesh
        from tpu_als.parallel.multihost import init_distributed

        init_distributed()  # no-op single-process; DCN rendezvous on pods
        if jax.process_count() > 1:
            return _train_multiprocess(args)
        # make_mesh raises when the request exceeds visible devices
        mesh = make_mesh(None if args.devices == 0 else args.devices)
    if args.per_host_data:
        raise SystemExit(
            "--per-host-data is multi-process only (each process loads "
            "its own split); launch under a JAX distributed rendezvous "
            "with --devices 0 — single-process runs load one dataset")
    with obs.span("data.load"):
        frame, stream_labels = _load_train_data(args)
    train, test = frame.randomSplit([1 - args.holdout, args.holdout],
                                    seed=args.seed)
    # per-iteration logging when asked for (--log-file) OR when a metrics
    # run dir is live (--output/--obs-dir): the run dir's iteration
    # events are what `observe summarize` renders as the convergence
    # table, so an observed run always records them
    logger = fit_cb = None
    if args.log_file or obs.active():
        logger = IterationLogger(
            probe=_train_probe(train, test), path=args.log_file,
            stream=sys.stderr if args.log_file else None)
        fit_cb = _iteration_cb(logger)
    als = ALS(rank=args.rank, maxIter=args.max_iter, regParam=args.reg_param,
              implicitPrefs=args.implicit, alpha=args.alpha,
              nonnegative=args.nonnegative, seed=args.seed,
              coldStartStrategy="drop", fitCallback=fit_cb,
              mesh=mesh, gatherStrategy=args.gather_strategy,
              cgIters=args.cg_iters,
              checkpointDir=args.checkpoint_dir,
              checkpointInterval=args.checkpoint_interval,
              resumeFrom=_resolve_resume(args),
              guardrails=args.guardrails,
              elastic=getattr(args, "elastic", False))
    print(f"training on {len(train):,} ratings "
          f"({len(test):,} held out)", file=sys.stderr)
    try:
        # SIGTERM/SIGINT: finish the in-flight iteration, checkpoint,
        # exit with the distinct EXIT_PREEMPTED status (resume with
        # `--resume auto`)
        with preempt.PreemptionGuard():
            if args.profile_dir:
                from tpu_als.utils.observe import trace

                with trace(args.profile_dir):
                    model = als.fit(train)
                print(f"profiler trace written to {args.profile_dir}",
                      file=sys.stderr)
            else:
                model = als.fit(train)
    except preempt.Preempted as p:
        print(f"preempted — {p}; rerun with --resume auto to continue",
              file=sys.stderr)
        raise  # SystemExit(EXIT_PREEMPTED); obs still finalizes in main
    finally:
        if logger is not None:
            logger.close()
    if getattr(als, "lastFitCommBytes", None):
        print(f"collective traffic: {als.lastFitCommBytes / 1e6:.3g} "
              f"MB/device/iteration ({als.lastFitStrategy})",
              file=sys.stderr)
    if len(test):
        rmse = RegressionEvaluator(labelCol="rating").evaluate(
            model.transform(test))
        print(json.dumps({"holdout_rmse": round(rmse, 4)}))
    if args.output:
        # CLI --output semantics: replace (atomically) — a rerun must not
        # crash after the whole training finished
        model.write().overwrite().save(args.output)
        if stream_labels is not None:
            _save_stream_labels(args.output, *stream_labels)
        print(f"model saved to {args.output}", file=sys.stderr)
    return model


def _train_multiprocess(args):
    """Multi-process training path (every pod host runs the same command).

    Convention: every host loads ``--data`` and calls the same
    ``ALS(mesh=...).fit`` — its multi-process branch blocks only the
    shards each host's devices own and trains with cross-host
    collectives.  Default is a replicated load (every host reads the same
    file); with ``--per-host-data`` each host reads its OWN split — any
    ``{proc}`` placeholder in the spec expands to the process index (e.g.
    ``csv:/data/part-{proc}.csv``) and the Estimator runs in
    ``dataMode='per_host'``.  ``--log-file`` logs from process 0 (the
    per-iteration probe gathers factors collectively).  Process 0
    evaluates the holdout (its local split in per-host mode) and saves
    the model.
    """
    import contextlib

    import jax

    from tpu_als import RegressionEvaluator
    from tpu_als.api.estimator import ALS
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.utils.observe import IterationLogger

    pid, pcount = jax.process_index(), jax.process_count()
    visible = len(jax.devices())
    if args.devices not in (0, visible):
        raise SystemExit(
            f"--devices {args.devices} under {pcount} processes: the "
            f"multi-process path always uses the full deployment "
            f"({visible} devices); pass --devices 0")

    spec = args.data.replace("{proc}", str(pid))
    if (args.per_host_data and args.data == spec and pcount > 1
            and spec.partition(":")[0] != "stream"):
        # a stream: spec needs no placeholder — it splits by byte range
        print(f"[proc {pid}] warning: --per-host-data without a {{proc}} "
              "placeholder in --data — every host loads the same path "
              "(valid only for host-LOCAL disks holding different "
              "splits; identical content is rejected at train time)",
              file=sys.stderr)
    frame, stream_labels = _load_train_data(args, pid, pcount)
    # the split seed is deliberately IDENTICAL across hosts: per-host
    # data is disjoint anyway, and a per-pid seed would decorrelate the
    # splits of an accidentally-shared file, defeating the trainer's
    # duplicated-content rejection (code-review r3)
    train, test = frame.randomSplit([1 - args.holdout, args.holdout],
                                    seed=args.seed)
    mesh = make_mesh()  # global mesh over every host's devices
    # a non-None fitCallback must be passed on EVERY process (the
    # per-iteration factor gather it triggers is collective); only
    # process 0's is ever invoked, so peers get an inert stand-in rather
    # than an IterationLogger that would open the shared log file
    logger = fit_cb = None
    if args.log_file:
        if pid == 0:
            logger = IterationLogger(path=args.log_file)
            fit_cb = _iteration_cb(logger)
        else:
            fit_cb = (lambda iteration, U, V: None)
    print(f"[proc {pid}/{pcount}] training {len(train):,} ratings "
          f"({'per-host' if args.per_host_data else 'replicated'} load) "
          f"over {mesh.devices.size} devices", file=sys.stderr)
    from tpu_als.resilience import preempt

    als = ALS(rank=args.rank, maxIter=args.max_iter,
              regParam=args.reg_param, implicitPrefs=args.implicit,
              alpha=args.alpha, nonnegative=args.nonnegative,
              seed=args.seed, coldStartStrategy="drop", mesh=mesh,
              gatherStrategy=args.gather_strategy, fitCallback=fit_cb,
              dataMode="per_host" if args.per_host_data else "replicated",
              cgIters=args.cg_iters,
              checkpointDir=args.checkpoint_dir,
              checkpointInterval=args.checkpoint_interval,
              resumeFrom=_resolve_resume(args),
              guardrails=args.guardrails,
              elastic=getattr(args, "elastic", False))
    ctx = contextlib.nullcontext()
    if args.profile_dir:
        from tpu_als.utils.observe import trace

        ctx = trace(f"{args.profile_dir}/proc{pid}")
    try:
        # the preemption decision is collective inside fit: a signal on
        # ANY host checkpoints and stops EVERY process at the same
        # iteration boundary
        with preempt.PreemptionGuard(), ctx:
            # fit's multi-process branch: per-host blocking, cross-host
            # collectives, replicated model on every host
            model = als.fit(train)
    except preempt.Preempted as p:
        print(f"[proc {pid}] preempted — {p}; rerun with --resume auto",
              file=sys.stderr)
        raise
    finally:
        if logger is not None:
            logger.close()

    if pid != 0:
        return None
    if len(test):
        rmse = RegressionEvaluator(labelCol="rating").evaluate(
            model.transform(test))
        print(json.dumps({"holdout_rmse": round(rmse, 4)}))
    if args.output:
        model.write().overwrite().save(args.output)
        if stream_labels is not None:
            _save_stream_labels(args.output, *stream_labels)
        print(f"model saved to {args.output}", file=sys.stderr)
    return model


def _load_model_any(path):
    """Load an ALSModel save, or fall back to a PipelineModel save (a
    user who persisted the whole fitted pipeline evaluates it with the
    same command).  Returns (model, is_pipeline)."""
    import os

    from tpu_als import ALSModel, PipelineModel

    if os.path.exists(os.path.join(path, "pipeline.json")):
        return PipelineModel.load(path), True
    return ALSModel.load(path), False


def cmd_evaluate(args):
    from tpu_als import RegressionEvaluator

    model, is_pipeline = _load_model_any(args.model)
    if is_pipeline and args.ranking_k > 0:
        raise SystemExit(
            "--ranking-k needs an ALSModel save (the ranking protocol "
            "runs recommendForUserSubset on raw ids); evaluate the "
            "pipeline's ALS stage directly, or drop --ranking-k for "
            "regression metrics through the full pipeline")
    frame = _load_eval_data(args.data, args.model)
    out = model.transform(frame)
    result = {}
    for metric in ("rmse", "mae", "r2"):
        ev = RegressionEvaluator(labelCol="rating", metricName=metric)
        v = ev.evaluate(out)
        # None, not NaN (every row unservable → all-NaN predictions):
        # json.dumps would emit the non-standard `NaN` token
        result[metric] = round(v, 4) if math.isfinite(v) else None
    if args.ranking_k > 0:
        # retrieval-quality protocol (SURVEY §2.B7): per test user,
        # ground truth = their test items rated >= --positive-threshold;
        # predictions = the model's top-k.  Vectorized top-k once for
        # the evaluated users, then the reference RankingMetrics math.
        from tpu_als.api.evaluation import RankingMetrics
        from tpu_als.utils.frame import ColumnarFrame

        k = args.ranking_k
        p = model._params
        u = np.asarray(frame[p["userCol"]])
        i = np.asarray(frame[p["itemCol"]])
        pos = np.asarray(frame[p["ratingCol"]],
                         np.float32) >= args.positive_threshold
        truth = {}
        for uu, ii in zip(u[pos], i[pos]):
            truth.setdefault(int(uu), set()).add(int(ii))
        users = np.array(sorted(truth), dtype=u.dtype)
        recs = model.recommendForUserSubset(
            ColumnarFrame({p["userCol"]: users}), k)
        key = recs.columns[0]
        pairs = [
            ([int(iid) for iid, _ in recs["recommendations"][row]],
             truth[int(recs[key][row])])
            for row in range(len(recs))
        ]
        # test users the model cannot serve (absent from training) are
        # filtered out by recommendForUserSubset; the reference protocol
        # scores them as an EMPTY prediction list (zero contribution),
        # not as excluded — dropping them silently would bias every
        # ranking metric upward whenever the split has cold users
        served = {int(recs[key][row]) for row in range(len(recs))}
        cold = [uu for uu in truth if uu not in served]
        pairs.extend(([], truth[uu]) for uu in cold)
        rm = RankingMetrics(pairs)
        result.update({
            f"precision_at_{k}": round(rm.precisionAt(k), 4),
            f"recall_at_{k}": round(rm.recallAt(k), 4),
            "map": round(rm.meanAveragePrecision, 4),
            f"ndcg_at_{k}": round(rm.ndcgAt(k), 4),
            "ranking_users": len(pairs),
            "ranking_users_cold": len(cold),
        })
    print(json.dumps(result))


def cmd_recommend(args):
    from tpu_als.utils.frame import ColumnarFrame

    model, is_pipeline = _load_model_any(args.model)
    if is_pipeline:
        raise SystemExit(
            f"{args.model} holds a PipelineModel save; `recommend` "
            "serves an ALSModel (its ids are the raw id space). Load "
            "the pipeline in Python and serve its ALS stage "
            "(PipelineModel.load(path).stages[-1]), mapping indices "
            "back with IndexToString — see "
            "examples/02_pipeline_string_ids.py")
    new_user_labels, new_item_labels = [], []
    if (getattr(args, "foldin_data", None)
            or getattr(args, "foldin_items_data", None)):
        # the full serving flow in one command (SURVEY.md §3.5): fold the
        # new ratings into the loaded model, then recommend — new users
        # (and, via the symmetric item direction, new items) become
        # recommendable without a refit
        from tpu_als.stream.microbatch import FoldInServer

        srv = FoldInServer(model)
        if getattr(args, "foldin_items_data", None):
            batch, new_item_labels = _load_foldin_data(
                args.foldin_items_data, args.model, "item")
            touched = srv.update_items(batch)
            print(f"folded in {len(batch)} ratings touching "
                  f"{len(touched)} items", file=sys.stderr)
        if getattr(args, "foldin_data", None):
            batch, new_user_labels = _load_foldin_data(
                args.foldin_data, args.model, "user")
            touched = srv.update(batch)
            print(f"folded in {len(batch)} ratings touching "
                  f"{len(touched)} users", file=sys.stderr)
    titles = None
    if getattr(args, "titles", None):
        from tpu_als.io.movielens import load_movielens_movies

        t = load_movielens_movies(args.titles)
        titles = dict(zip(t["item"].tolist(), t["title"].tolist()))
    devices = getattr(args, "devices", 1)
    if devices < 0:
        raise SystemExit(f"--devices must be >= 0, got {devices}")
    mesh = None
    if devices != 1:
        # serving sharded over the mesh — applies to the subset path
        # too (the catalog side is what outgrows one device's HBM);
        # make_mesh raises when the request exceeds visible devices
        from tpu_als.parallel.mesh import make_mesh

        mesh = make_mesh(devices if devices > 0 else None)
    strategy = getattr(args, "gather_strategy", "all_gather")
    stream_names = None   # (user dense->label, item labels) for output
    if args.users:
        toks = args.users.split(",")
        try:
            ids = np.array([int(x) for x in toks])
        except ValueError:
            # string ids: resolve via the stream-trained model's label
            # sidecar, plus any users just folded in this invocation
            g_ul, g_il = _model_vocab(args.model)
            index = {s.decode(): k for k, s in enumerate(g_ul.tolist())}
            for j, lab in enumerate(new_user_labels):
                index.setdefault(lab, len(g_ul) + j)

            def resolve(t):
                if t not in index:
                    raise SystemExit(
                        f"unknown user id {t!r} (not in the model's "
                        "stream_labels sidecar nor in --foldin-data)")
                return index[t]

            ids = np.array([resolve(t) for t in toks])
            stream_names = ({v: k for k, v in index.items()}, g_il)
        recs = model.recommendForUserSubset(
            ColumnarFrame({model._params["userCol"]: ids}), args.k,
            mesh=mesh, gatherStrategy=strategy)
    else:
        recs = model.recommendForAllUsers(args.k, mesh=mesh,
                                          gatherStrategy=strategy)
    key = recs.columns[0]
    limit = args.limit if args.limit > 0 else len(recs)
    for row in range(min(limit, len(recs))):
        out = {"user": int(recs[key][row]),
               "items": [[int(i), round(float(s), 4)]
                         for i, s in recs["recommendations"][row]]}
        if stream_names is not None:
            rev_u, g_il = stream_names

            def item_name(i):
                if i < len(g_il):
                    return g_il[i].decode()
                j = i - len(g_il)   # freshly folded-in item this call
                return (new_item_labels[j]
                        if j < len(new_item_labels) else None)

            out["user_id"] = rev_u.get(int(recs[key][row]))
            out["item_ids"] = [item_name(int(i))
                               for i, _ in recs["recommendations"][row]]
        if titles is not None:
            out["titles"] = [titles.get(int(i))
                             for i, _ in recs["recommendations"][row]]
        print(json.dumps(out))


def cmd_tune(args):
    """Grid search over rank/regParam with CrossValidator — the reference
    app layer's tuning step (SURVEY.md §2.A6) as a CLI command."""
    from tpu_als import ALS, RegressionEvaluator
    from tpu_als.api.tuning import CrossValidator, ParamGridBuilder

    frame, stream_labels = _load_train_data(args)
    als = ALS(maxIter=args.max_iter, implicitPrefs=args.implicit,
              alpha=args.alpha, seed=args.seed, coldStartStrategy="drop",
              cgIters=args.cg_iters)
    gb = (ParamGridBuilder()
          .addGrid(als.rank, [int(x) for x in args.ranks.split(",")])
          .addGrid(als.regParam,
                   [float(x) for x in args.reg_params.split(",")]))
    if args.alphas:
        # regParam and alpha are traced through the compiled step
        # (core/als.py), so widening the grid over them adds fit time
        # but NO extra compiles at fixed rank
        gb = gb.addGrid(als.alpha,
                        [float(x) for x in args.alphas.split(",")])
    grid = gb.build()
    cv = CrossValidator(
        estimator=als,
        estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(labelCol="rating"),
        numFolds=args.folds,
        seed=args.seed,
    )
    cv_model = cv.fit(frame)
    best = cv_model.bestModel
    out = {
        "best_rank": int(best._params["rank"]),
        "best_regParam": float(best._params["regParam"]),
        "avg_metrics": [round(float(m), 4) for m in cv_model.avgMetrics],
        "grid_size": len(grid),
    }
    if args.alphas:
        out["best_alpha"] = float(best._params["alpha"])
    print(json.dumps(out))
    if args.output:
        cv_model.write().overwrite().save(args.output)
        if stream_labels is not None:
            _save_stream_labels(args.output, *stream_labels)
        print(f"best model saved to {args.output}", file=sys.stderr)


def cmd_foldin_bench(args):
    import time

    from tpu_als import ALSModel
    from tpu_als.stream.microbatch import FoldInServer
    from tpu_als.utils.frame import ColumnarFrame

    model = ALSModel.load(args.model)
    srv = FoldInServer(model)
    rng = np.random.default_rng(0)
    item_ids = model._item_map.ids
    p = model._params
    base_user = int(model._user_map.ids.max()) + 1
    for b in range(args.batches):
        n = args.batch_size
        batch = ColumnarFrame({
            p["userCol"]: rng.integers(base_user, base_user + 1000, n),
            p["itemCol"]: rng.choice(item_ids, n),
            p["ratingCol"]: rng.uniform(0.5, 5.0, n).astype(np.float32),
        })
        t0 = time.perf_counter()
        srv.update(batch)
        if b == 0:
            print(f"warmup batch: {time.perf_counter()-t0:.3f}s",
                  file=sys.stderr)
    print(json.dumps({
        "metric": "foldin_p50_latency",
        "value": round(srv.latency(0.5, skip_warmup=True), 4),
        "unit": "seconds",
        "batches": args.batches,
        "batch_size": args.batch_size,
    }))


def _serve_bench_tenants(args):
    """The ``--tenants N`` branch: N same-shaped models behind one
    :class:`MultiTenantEngine`, equal open-loop load per tenant, judged
    per tenant from the LABELED obs series.

    Headline metric is ``tenancy_worst_p99_ms`` — the worst per-tenant
    e2e p99 — and ``slo_met`` requires BOTH every tenant's p99 within
    ``--slo-ms`` AND the weighted goodput fairness ratio (max/min of
    served-rows-per-weight) within ``--fairness-bound``: a report where
    one tenant starves is a failing report even if the aggregate tail
    looks healthy.  ``--update-qps > 0`` gives every tenant its own
    live fold-in stream (per-tenant publish-mode histograms in the
    report).  Same-shaped tenants share compiled executables — warmup
    cost is paid once, not N times (docs/tenancy.md).
    """
    import datetime as _dt
    import threading
    import time

    from tpu_als import obs
    from tpu_als.tenancy import (MultiTenantEngine, TenantOverloaded,
                                 TenantSpec)

    if args.tenants < 2:
        raise SystemExit("serve-bench: --tenants needs >= 2")
    rng = np.random.default_rng(args.seed)
    names = [f"t{i}" for i in range(args.tenants)]
    weights = ([float(w) for w in args.tenant_weights.split(",")]
               if args.tenant_weights else [1.0] * args.tenants)
    if len(weights) != args.tenants:
        raise SystemExit("serve-bench: --tenant-weights needs exactly "
                         f"{args.tenants} comma-separated weights")
    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)

    eng = MultiTenantEngine()
    factors = {}
    for name, w in zip(names, weights):
        U = rng.normal(size=(args.users, args.rank)).astype(np.float32)
        V = rng.normal(size=(args.items, args.rank)).astype(np.float32)
        factors[name] = (U, V)
        eng.add_tenant(
            TenantSpec(name=name, weight=w, k=args.k,
                       shortlist_k=args.shortlist_k, buckets=buckets,
                       max_queue=args.max_queue,
                       max_wait_s=args.max_wait_ms / 1e3,
                       default_deadline_s=(args.deadline_ms / 1e3
                                           if args.deadline_ms
                                           else None),
                       slo_s=args.slo_ms / 1e3),
            U, V, quantize=not args.exact)
    with obs.span("serve_bench.warmup"):
        # tenant 0 pays the compiles; the rest hit the process-global
        # cache (same shape-class, same rank)
        eng.warmup()

    updaters = {}
    if args.update_qps > 0:
        from tpu_als.api.estimator import ALSModel
        from tpu_als.core.ratings import IdMap, _next_pow2
        from tpu_als.stream.microbatch import FoldInServer

        with obs.span("serve_bench.live_prewarm"):
            for name in names:
                U, V = factors[name]
                model = ALSModel(
                    args.rank, IdMap(ids=np.arange(args.users)),
                    IdMap(ids=np.arange(args.items)), U.copy(),
                    V.copy(),
                    {"userCol": "user", "itemCol": "item",
                     "ratingCol": "rating", "regParam": 0.05,
                     "implicitPrefs": False, "alpha": 1.0,
                     "nonnegative": False})
                srv = FoldInServer(model, keep_history=False)
                upd = eng.attach_live(
                    name, srv, max_batch=args.update_max_batch,
                    max_wait_ms=args.update_max_wait_ms,
                    slo_s=args.freshness_slo_ms / 1e3)
                if name == names[0]:
                    ladder = tuple(sorted(
                        {_next_pow2(max(1, upd.max_batch >> s))
                         for s in range(upd.max_batch.bit_length())}))
                    srv.prewarm(rows=ladder, widths=(1, 2),
                                sides=("user",))
                updaters[name] = upd

    per_qps = args.qps / args.tenants
    n_req = max(1, int(per_qps * args.duration))
    path = "exact" if args.exact else "int8"
    print(f"serve-bench: {args.tenants} tenants x {n_req} requests at "
          f"{per_qps:g} rps each over {args.duration:g}s ({path} path, "
          f"{args.items:,} items, rank {args.rank})", file=sys.stderr)

    shed = {name: 0 for name in names}

    def _drive(name, seed):
        trng = np.random.default_rng(seed)
        uids = trng.integers(0, args.users, n_req)
        tickets = []
        t0 = time.perf_counter()
        for j in range(n_req):
            delay = (t0 + j / per_qps) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                tickets.append(eng.submit(name, int(uids[j])))
            except TenantOverloaded:
                shed[name] += 1
        for t in tickets:
            try:
                t.result(timeout=max(5.0, 10 * args.slo_ms / 1e3))
            except Exception:   # noqa: BLE001 — counted from obs below
                pass

    def _drive_updates(name, seed):
        urng = np.random.default_rng(seed)
        n_upd = max(1, int(args.update_qps / args.tenants
                           * args.duration))
        uu = urng.integers(0, args.users, n_upd)
        ii = urng.integers(0, args.items, n_upd)
        rr = urng.uniform(0.5, 5.0, n_upd).astype(np.float32)
        tu = time.perf_counter()
        for j in range(n_upd):
            delay = (tu + j / (args.update_qps / args.tenants)
                     - time.perf_counter())
            if delay > 0:
                time.sleep(delay)
            try:
                updaters[name].submit(int(uu[j]), int(ii[j]),
                                      float(rr[j]))
            except Exception:   # noqa: BLE001 — live.shed counts it
                pass

    eng.start()
    try:
        with obs.span("serve_bench.drive"):
            threads = [threading.Thread(
                target=_drive, args=(name, args.seed + 100 + i),
                name=f"serve-bench-{name}")
                for i, name in enumerate(names)]
            threads += [threading.Thread(
                target=_drive_updates, args=(name, args.seed + 200 + i),
                name=f"serve-bench-upd-{name}")
                for i, name in enumerate(updaters)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            deadline = time.perf_counter() + 30.0
            while (any(u.queue_depth for u in updaters.values())
                   and time.perf_counter() < deadline):
                time.sleep(0.02)
    finally:
        eng.stop()

    per_tenant, worst_p99, modes_all = {}, 0.0, {}
    goodput = []
    events = obs.default_registry()._events
    for name, w in zip(names, weights):
        p50 = obs.histogram_quantile("serving.e2e_seconds", 0.5,
                                     tenant=name)
        p99 = obs.histogram_quantile("serving.e2e_seconds", 0.99,
                                     tenant=name)
        scored = obs.histogram_count("serving.e2e_seconds", tenant=name)
        if scored == 0:
            raise SystemExit(f"serve-bench: tenant {name!r} completed "
                             "no request — its histogram is empty")
        shed_obs = obs.counter_value("serving.shed", tenant=name)
        admitted = obs.counter_value("serving.requests", tenant=name)
        assert shed[name] == shed_obs, (name, shed[name], shed_obs)
        served = obs.counter_value("tenancy.served_rows", tenant=name)
        goodput.append(served / w)
        modes = {}
        for e in events:
            if (e.get("type") == "live_update"
                    and e.get("tenant") == name):
                modes[e["mode"]] = modes.get(e["mode"], 0) + 1
        for m, c in modes.items():
            modes_all[m] = modes_all.get(m, 0) + c
        worst_p99 = max(worst_p99, p99)
        per_tenant[name] = {
            "p50_ms": round(p50 * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3),
            "slo_met": bool(p99 * 1e3 <= args.slo_ms),
            "scored": int(scored),
            "shed_rate": (round(shed_obs / (admitted + shed_obs), 4)
                          if admitted + shed_obs else 0.0),
            "served_rows": int(served),
            "weight": w,
            **({"publish_modes": modes} if modes else {}),
        }
    fairness = (max(goodput) / min(goodput)) if min(goodput) else None
    all_in_slo = all(t["slo_met"] for t in per_tenant.values())
    # Fairness is a CONTENTION property: weighted goodput (served/weight)
    # can only equalize when the scheduler actually arbitrates.  An
    # unsaturated bench serves every tenant's full demand, so unequal
    # weights read as an "unfair" ratio while nobody was refused
    # anything — judge the ratio only when some tenant shed (always
    # report it).
    contended = any(shed[name] > 0 for name in names)
    fair_ok = (not contended or (fairness is not None
                                 and fairness <= args.fairness_bound))
    result = {
        "metric": "tenancy_worst_p99_ms",
        "value": round(worst_p99 * 1e3, 3),
        "unit": "ms",
        "slo_ms": args.slo_ms,
        "fairness_ratio": (round(fairness, 3)
                           if fairness is not None else None),
        "fairness_bound": args.fairness_bound,
        "fairness_judged": contended,
        "slo_met": bool(all_in_slo and fairness is not None
                        and fair_ok),
        "tenants": per_tenant,
        "shape_classes": {k: sorted(v) for k, v in
                          eng.registry.shape_classes().items()},
        **({"publish_modes": modes_all} if modes_all else {}),
        "config": {
            "path": path, "tenants": args.tenants,
            "tenant_weights": weights, "users": args.users,
            "items": args.items, "rank": args.rank, "k": args.k,
            "shortlist_k": args.shortlist_k, "qps": args.qps,
            "qps_per_tenant": per_qps, "duration_s": args.duration,
            "max_queue": args.max_queue,
            "max_wait_ms": args.max_wait_ms,
            "deadline_ms": args.deadline_ms,
            "update_qps": args.update_qps,
        },
    }
    print(json.dumps(result))
    if args.bench_json:
        with open(args.bench_json, "w") as f:
            json.dump({
                **result,
                "banked_by": "tpu_als serve-bench --tenants",
                "banked_at": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(timespec="seconds"),
            }, f, indent=2)
            f.write("\n")
        print(f"result banked to {args.bench_json}", file=sys.stderr)
    return result


def cmd_serve_bench(args):
    """Open-loop serving latency benchmark: synthetic factors, a fixed
    request rate for a fixed window, p50/p99/shed-rate read back from
    the obs histograms and judged against ``--slo-ms``.

    Open-loop means arrivals are scheduled by the clock, not by
    completions — the honest load model for online serving (a closed
    loop self-throttles and hides queueing collapse).  Results can be
    banked as ``BENCH_serve_*.json`` with the same ``banked_at``
    provenance stamp bench.py uses (``--bench-json``).

    ``--update-qps > 0`` additionally drives the LIVE pipeline
    (tpu_als/live/) during the window: a concurrent rating-event
    stream through a LiveUpdater — fold-in, incremental publish,
    freshness measured per event — and the report's headline metric
    becomes ``live_freshness_p99_ms`` judged against
    ``--freshness-slo-ms``, with an O(touched)-vs-O(catalog)
    publish-cost probe (min-of-3, device-fenced) alongside.

    ``--tenants N`` switches to the multi-tenant variant: N same-shaped
    models behind one MultiTenantEngine, judged per tenant
    (see :func:`_serve_bench_tenants`).
    """
    import datetime as _dt
    import threading
    import time

    from tpu_als import obs
    from tpu_als.serving import Overloaded, ServingEngine

    if args.tenants:
        return _serve_bench_tenants(args)

    rng = np.random.default_rng(args.seed)
    U = rng.normal(size=(args.users, args.rank)).astype(np.float32)
    V = rng.normal(size=(args.items, args.rank)).astype(np.float32)
    # no --buckets: the execution planner supplies the ladder (a banked
    # plan for this device/jax key, else the DEFAULT_BUCKETS walk)
    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)
    mesh = None
    if args.mesh_devices:
        from tpu_als.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh_devices)
    engine = ServingEngine(
        k=args.k, buckets=buckets, shortlist_k=args.shortlist_k,
        mesh=mesh, serve_backend=args.serve_backend,
        max_queue=args.max_queue, max_wait_s=args.max_wait_ms / 1e3,
        default_deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms else None),
        # the SLO is also the flight-recorder breach trigger: a request
        # slower than this dumps the last N per-request traces as
        # flight_record events (docs/observability.md)
        slo_s=args.slo_ms / 1e3)
    engine.publish(U, V, quantize=not args.exact)
    with obs.span("serve_bench.warmup"):
        engine.warmup()

    updater, model, upd_stats = None, None, {"shed": 0}
    if args.update_qps > 0:
        from tpu_als.api.estimator import ALSModel
        from tpu_als.core.ratings import IdMap, _next_pow2
        from tpu_als.live import LiveUpdater
        from tpu_als.stream.microbatch import FoldInServer

        model = ALSModel(
            args.rank, IdMap(ids=np.arange(args.users)),
            IdMap(ids=np.arange(args.items)), U.copy(), V.copy(),
            {"userCol": "user", "itemCol": "item",
             "ratingCol": "rating", "regParam": 0.05,
             "implicitPrefs": False, "alpha": 1.0,
             "nonnegative": False})
        # keep_history=False: widths stay the per-batch multiplicity
        # (1-2), so the prewarm grid below covers every shape the
        # stream can produce — a history merge would grow widths over
        # the window and pay compiles against the freshness SLO
        srv = FoldInServer(model, keep_history=False)
        updater = LiveUpdater(
            engine, srv, max_batch=args.update_max_batch,
            max_wait_ms=args.update_max_wait_ms,
            slo_s=args.freshness_slo_ms / 1e3,
            fold_items=args.update_items)
        ladder = tuple(sorted({_next_pow2(max(1, updater.max_batch >> s))
                               for s in range(updater.max_batch.bit_length())}))
        with obs.span("serve_bench.live_prewarm"):
            srv.prewarm(
                rows=ladder, widths=(1, 2),
                sides=(("user", "item") if args.update_items
                       else ("user",)))
            if args.update_items and not args.exact:
                # each event touches one item, so the stream can never
                # grow the delta segment past its own event count —
                # compile the (bucket, delta-pad) serve executables up
                # to that bound now, not on the request path
                engine.warmup_live(max_delta_rows=max(
                    1, int(args.update_qps * args.duration)))

    path = "exact" if args.exact else "int8"
    n_req = max(1, int(args.qps * args.duration))
    print(f"serve-bench: {n_req} requests at {args.qps:g} rps over "
          f"{args.duration:g}s ({path} path, "
          f"{args.items:,} items, rank {args.rank})", file=sys.stderr)
    foldin_ids = rng.random(n_req) < args.foldin_frac
    uids = rng.integers(0, args.users, n_req)

    upd_thread = None
    if updater is not None:
        n_upd = max(1, int(args.update_qps * args.duration))
        upd_u = rng.integers(0, args.users, n_upd)
        upd_i = rng.integers(0, args.items, n_upd)
        upd_r = rng.uniform(0.5, 5.0, n_upd).astype(np.float32)
        upd_r[rng.random(n_upd) < args.update_poison_frac] = np.nan
        print(f"serve-bench: +{n_upd} rating events at "
              f"{args.update_qps:g}/s (live fold-in → publish, "
              f"freshness SLO {args.freshness_slo_ms:g}ms)",
              file=sys.stderr)

        def _drive_updates():
            tu = time.perf_counter()
            for j in range(n_upd):
                delay = tu + j / args.update_qps - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    updater.submit(int(upd_u[j]), int(upd_i[j]),
                                   float(upd_r[j]))
                except Overloaded:
                    upd_stats["shed"] += 1

        updater.start()
        upd_thread = threading.Thread(
            target=_drive_updates, name="serve-bench-updates")

    tickets, shed = [], 0
    engine.start()
    try:
        with obs.span("serve_bench.drive"):
            # pacing epoch starts inside the span: the span-enter
            # emission must not make request 0 late against its target
            if upd_thread is not None:
                upd_thread.start()
            t0 = time.perf_counter()
            for j in range(n_req):
                target = t0 + j / args.qps
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                payload = (U[uids[j]] if foldin_ids[j]
                           else int(uids[j]))
                try:
                    tickets.append(engine.submit(payload))
                except Overloaded:
                    shed += 1
            for t in tickets:
                try:
                    t.result(timeout=max(5.0, 10 * args.slo_ms / 1e3))
                except Exception:
                    pass   # expired/failed requests are counted below
            if upd_thread is not None:
                upd_thread.join()
                # freshness is judged on a DRAINED queue: every event
                # that was admitted must reach a publish before the
                # histograms are read
                updater.stop(drain_timeout_s=max(
                    30.0, 10 * args.freshness_slo_ms / 1e3))
    finally:
        if updater is not None:
            updater.stop()
        engine.stop()

    p50 = obs.histogram_quantile("serving.e2e_seconds", 0.5)
    p99 = obs.histogram_quantile("serving.e2e_seconds", 0.99)
    scored = obs.histogram_count("serving.e2e_seconds")
    admitted = obs.counter_value("serving.requests")
    shed_obs = obs.counter_value("serving.shed")
    expired = obs.counter_value("serving.expired")
    attempted = admitted + shed_obs
    if scored == 0:
        raise SystemExit("serve-bench: no request completed — the "
                         "latency histograms are empty")
    assert shed == shed_obs, (shed, shed_obs)  # driver and obs agree
    result = {
        "metric": "serve_e2e_p99_ms",
        "value": round(p99 * 1e3, 3),
        "unit": "ms",
        "slo_ms": args.slo_ms,
        "slo_met": bool(p99 * 1e3 <= args.slo_ms),
        "p50_ms": round(p50 * 1e3, 3),
        "shed_rate": round(shed_obs / attempted, 4) if attempted else 0.0,
        "expired": int(expired),
        "scored": int(scored),
        "queue_wait_p99_ms": round(
            obs.histogram_quantile("serving.enqueue_seconds", 0.99) * 1e3,
            3),
        "flight_records": sum(
            1 for e in obs.default_registry()._events
            if e.get("type") == "flight_record"),
        "config": {
            "path": path, "users": args.users, "items": args.items,
            "rank": args.rank, "k": args.k,
            "shortlist_k": args.shortlist_k, "qps": args.qps,
            "duration_s": args.duration,
            "buckets": list(engine.batcher.buckets),
            "max_queue": args.max_queue, "max_wait_ms": args.max_wait_ms,
            "deadline_ms": args.deadline_ms,
            "foldin_frac": args.foldin_frac,
        },
    }
    if mesh is not None:
        result["backend"] = engine._backend
        result["config"]["mesh_devices"] = int(args.mesh_devices)
        result["config"]["serve_backend"] = args.serve_backend
    # feed the OBSERVED request-size mix back into the planner: the
    # batch_rows histogram's {p50,p90,p99,max}, weight-reconstructed
    # into a sample so the planner's own quantiles land on the same
    # rungs, become the banked pow2 ladder for this device/rank key
    # (quantiles are bucketed UPPER bounds — the derived ladder can
    # only over-provision, never undersize a bucket)
    if obs.histogram_count("serving.batch_rows"):
        from tpu_als import plan

        bq = [obs.histogram_quantile("serving.batch_rows", q)
              for q in (0.5, 0.9, 0.99, 1.0)]
        sample = ([bq[0]] * 50 + [bq[1]] * 40 + [bq[2]] * 9 + [bq[3]])
        result["derived_buckets"] = list(plan.resolve_serving_buckets(
            rank=args.rank, observed=sample))
    if updater is not None:
        from tpu_als.serving import build_index

        fr_p50 = obs.histogram_quantile("live.freshness_seconds", 0.5)
        fr_p99 = obs.histogram_quantile("live.freshness_seconds", 0.99)
        fr_n = obs.histogram_count("live.freshness_seconds")
        if fr_n == 0:
            raise SystemExit("serve-bench: no update event reached a "
                             "publish — the freshness histogram is "
                             "empty")
        modes = {}
        for e in obs.default_registry()._events:
            if e.get("type") == "live_update":
                modes[e["mode"]] = modes.get(e["mode"], 0) + 1

        # publish-cost probe: the incremental path must price as
        # O(touched rows), not O(catalog).  min-of-3 with device
        # fencing (rep 1 eats any quantize compile), same touched-row
        # count a steady-state micro-batch produces.
        probe = {}
        idx = engine.published_index
        if idx is not None:
            Vcur = np.asarray(model._V, dtype=np.float32)
            pr = np.arange(min(64, idx.n_items), dtype=np.int64)
            vr = np.ascontiguousarray(Vcur[pr])

            def _min3(fn):
                best = float("inf")
                for _ in range(3):
                    tp = time.perf_counter()
                    fn().block_until_ready()
                    best = min(best, time.perf_counter() - tp)
                return best

            d_s = _min3(lambda: idx.with_updates(
                pr, vr, seq=idx.seq + 1))
            f_s = _min3(lambda: build_index(
                Vcur, shortlist_k=idx.shortlist_k))
            probe = {
                "publish_delta_ms": round(d_s * 1e3, 3),
                "publish_full_ms": round(f_s * 1e3, 3),
                "publish_speedup": round(f_s / d_s, 2) if d_s else None,
                "probe_rows": int(pr.size),
                "catalog_rows": int(idx.n_items),
            }

        result.update({
            "metric": "live_freshness_p99_ms",
            "value": round(fr_p99 * 1e3, 3),
            "slo_ms": args.freshness_slo_ms,
            "slo_met": bool(fr_p99 * 1e3 <= args.freshness_slo_ms),
            "p50_ms": round(fr_p50 * 1e3, 3),
            "serve": {
                "p99_ms": round(p99 * 1e3, 3),
                "p50_ms": round(p50 * 1e3, 3),
                "slo_ms": args.slo_ms,
                "slo_met": bool(p99 * 1e3 <= args.slo_ms),
            },
            "live": {
                "events_scored": int(fr_n),
                "updates_shed": int(upd_stats["shed"]),
                "quarantined_rows": int(
                    obs.counter_value("ingest.quarantined_rows")),
                "publish_modes": modes,
                **probe,
            },
        })
        result["config"].update({
            "update_qps": args.update_qps,
            "update_items": bool(args.update_items),
            "update_poison_frac": args.update_poison_frac,
            "update_max_batch": updater.max_batch,
            "update_max_wait_ms": updater.max_wait_s * 1e3,
        })
    print(json.dumps(result))
    if args.bench_json:
        # same provenance contract as bench.py's banked variants: an
        # absolute UTC stamp, never a relative phrase
        with open(args.bench_json, "w") as f:
            json.dump({
                **result,
                "banked_by": "tpu_als serve-bench",
                "banked_at": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(timespec="seconds"),
            }, f, indent=2)
            f.write("\n")
        print(f"result banked to {args.bench_json}", file=sys.stderr)
    return result


def cmd_tt_train(args):
    """Train the two-tower retrieval model (BASELINE config 5) from a
    ratings file: ALS warm start (unless --cold), filtered-recall holdout
    report, persisted towers."""
    from tpu_als.core.als import AlsConfig, train as als_train
    from tpu_als.core.ratings import build_csr_buckets, remap_ids
    from tpu_als.models.two_tower import (
        TwoTowerConfig,
        recall_at_k,
        save_two_tower,
        train_two_tower,
    )

    frame = _load_data(args.data)
    u_raw = np.asarray(frame["user"])
    i_raw = np.asarray(frame["item"])
    r = np.asarray(frame["rating"], dtype=np.float32)
    u, umap = remap_ids(u_raw)
    i, imap = remap_ids(i_raw)
    nU, nI = len(umap), len(imap)
    pos = r >= args.positive_threshold
    u, i, r = u[pos], i[pos], r[pos]
    rng = np.random.default_rng(args.seed)
    test = rng.random(len(u)) < args.holdout
    ut, it_ = u[test], i[test]
    u2, i2 = u[~test], i[~test]

    warm_kw = {}
    if not args.cold:
        als_cfg = AlsConfig(rank=args.als_rank, max_iter=args.als_iters,
                            reg_param=0.005, implicit_prefs=True,
                            alpha=20.0, seed=args.seed)
        ucsr = build_csr_buckets(u2, i2, r[~test], nU)
        icsr = build_csr_buckets(i2, u2, r[~test], nI)
        U, V = als_train(ucsr, icsr, als_cfg)
        warm_kw = {"als_user_factors": np.asarray(U),
                   "als_item_factors": np.asarray(V)}
        print("ALS warm-start factors trained", file=sys.stderr)

    cfg = TwoTowerConfig(embed_dim=args.embed_dim, out_dim=args.embed_dim,
                         epochs=args.epochs, seed=args.seed)
    params = train_two_tower(u2, i2, nU, nI, cfg, **warm_kw)
    # None, not NaN: json.dumps would emit the non-standard `NaN` token
    # that strict parsers (jq etc.) reject
    rec = (round(recall_at_k(params, ut, it_, k=args.k, exclude=(u2, i2)),
                 4) if len(ut) else None)
    out = {"filtered_recall_at_%d" % args.k: rec,
           "train_pairs": int(len(u2)), "test_pairs": int(len(ut)),
           "users": nU, "items": nI, "epochs": cfg.epochs,
           "warm_start": not args.cold}
    if args.output:
        save_two_tower(args.output, params, cfg, nU, nI)
        out["saved"] = args.output
    print(json.dumps(out))


def cmd_observe(args):
    """Inspect a run directory written by the other subcommands — the
    analog of pointing the Spark UI at an event-log directory — or run
    one of the measurement-side tools: ``roofline`` (the analytical
    per-stage floor), ``attribution`` (measured per-stage seconds
    joined against that floor), ``regress`` (the bench-series gate)."""
    if args.action == "regress":
        from tpu_als.obs import regress as regress_mod

        result = regress_mod.check(args.root, noise=args.noise,
                                   strict=args.strict, trend=args.trend,
                                   trend_window=args.trend_window)
        if args.as_json:
            print(json.dumps(result))
        else:
            print(regress_mod.render(result))
        if result["exit_code"]:
            raise SystemExit(result["exit_code"])
        return result

    if args.action == "attribution":
        from tpu_als import obs
        from tpu_als.core.als import AlsConfig
        from tpu_als.core.ratings import build_csr_buckets, remap_ids
        from tpu_als.perf.attribution import (
            attribution_report,
            measure_attributed,
            render_attribution,
        )
        from tpu_als.perf.roofline import roofline

        if args.obs_dir:
            from tpu_als import obs as _obs

            _obs.configure(args.obs_dir,
                           config={k: v for k, v in vars(args).items()
                                   if k != "fn"})
        frame = _load_data(args.data)
        u, _ = remap_ids(np.asarray(frame["user"]))
        i, _ = remap_ids(np.asarray(frame["item"]))
        r = np.asarray(frame["rating"], dtype=np.float32)
        nU, nI = int(u.max()) + 1, int(i.max()) + 1
        ucsr = build_csr_buckets(u, i, r, nU)
        icsr = build_csr_buckets(i, u, r, nI)
        cfg = AlsConfig(rank=args.rank, implicit_prefs=not args.explicit,
                        reg_param=args.reg, alpha=args.alpha,
                        compute_dtype=args.dtype,
                        solve_backend=args.solve_backend)
        measured = measure_attributed(ucsr, icsr, cfg, iters=args.iters,
                                      warmup=args.warmup)
        path = measured["resolved_solve_path"]
        ne_path = ("gather_fused_solve" if path == "gatherfused_solve"
                   else "gather_fused" if path.startswith("gatherfused")
                   else "einsum")
        rl = roofline(nU, nI, len(r), args.rank, dtype=args.dtype,
                      implicit=not args.explicit, ne_path=ne_path,
                      user_counts=ucsr.counts, item_counts=icsr.counts)
        rep = attribution_report(measured, rl)
        obs.emit("attribution", stages=rep["rows"],
                 wall_s_per_iter=rep["wall_s_per_iter"],
                 coverage=rep["coverage"],
                 resolved_solve_path=rep["resolved_solve_path"],
                 config=rl["config"])
        if args.as_json:
            print(json.dumps(rep))
        else:
            print(render_attribution(rep))
        if args.obs_dir:
            obs.finalize()
            obs.deconfigure()
        return rep

    if args.action == "roofline":
        from tpu_als.perf.roofline import (
            HEADLINE,
            HEADLINE_MEASURED_S_PER_ITER,
            render,
            roofline,
        )

        kwargs = dict(
            n_users=args.users, n_items=args.items, nnz=args.ratings,
            rank=args.rank, dtype=args.dtype,
            implicit=not args.explicit,
            padding_waste=args.padding_waste, devices=args.devices,
            strategy=args.strategy,
            tiles_user=args.tiles, tiles_item=args.tiles,
            ne_path=args.ne_path,
        )
        measured = args.measured_s_per_iter
        if measured is None and kwargs == dict(
                HEADLINE, strategy=None, tiles_user=1, tiles_item=1,
                ne_path="einsum"):
            # the measured point belongs to the einsum-path headline; a
            # --ne-path gather_fused render shows the revised floor
            # without pretending the old measurement sits on it
            measured = HEADLINE_MEASURED_S_PER_ITER
        report_d = roofline(**kwargs, measured_s_per_iter=measured)
        if args.as_json:
            print(json.dumps(report_d))
        else:
            print(render(report_d))
        return

    if args.action == "explain":
        from tpu_als.obs import explain as explain_mod

        try:
            print(explain_mod.explain(args.run_dir, trace=args.trace,
                                      breach=args.breach))
        except (FileNotFoundError, ValueError) as err:
            raise SystemExit(str(err))
        except BrokenPipeError:
            # `observe explain RUN | head` closing the pipe early is
            # normal; point stdout at devnull so the interpreter's
            # exit-time flush doesn't raise a second time
            import os

            os.dup2(os.open(os.devnull, os.O_WRONLY),
                    sys.stdout.fileno())
        return

    from tpu_als.obs import report

    try:
        if args.action == "summarize":
            print(report.cmd_summarize(args.run_dir, as_json=args.as_json,
                                       since=args.since,
                                       window=args.window))
        else:
            print(report.cmd_tail(args.run_dir, n=args.lines,
                                  event=args.event, tenant=args.tenant,
                                  trace=args.trace))
    except (FileNotFoundError, ValueError) as err:
        raise SystemExit(str(err))


def cmd_scenario(args):
    """Run (or list) a production-day scenario — composed chaos over
    train + serve + stream with hard assertions judged from the obs
    trail (tpu_als.scenario; docs/scenarios.md)."""
    from tpu_als import scenario

    if args.action == "list":
        for name in scenario.names():
            spec = scenario.SCENARIOS[name]
            chaos = f"  [faults: {spec.fault_spec}]" if spec.fault_spec \
                else ""
            print(f"{name}{chaos}")
            print(f"    {' '.join(spec.doc.split())}")
            for p in spec.phases:
                print(f"      - {p.name}: {p.doc}")
        return

    try:
        spec = scenario.get_scenario(args.name)
    except scenario.UnknownScenario as e:
        print(f"tpu_als scenario: {e}", file=sys.stderr)
        raise SystemExit(2) from e
    overrides = {"slo_ms": args.slo_ms,
                 "freshness_slo_ms": args.freshness_slo_ms,
                 "seed": args.seed}
    try:
        result = scenario.run_scenario(spec, config=overrides)
    except scenario.PhaseFailed as e:
        # harness breakage (a phase body raised), as opposed to a judged
        # assertion failure — still one clean line, still non-zero
        print(f"tpu_als scenario: {e}", file=sys.stderr)
        raise SystemExit(1) from e
    print(scenario.render_result(result))
    if args.as_json:
        print(json.dumps(result, default=str))
    if args.bench_json:
        scenario.bank_result(result, args.bench_json)
        print(f"banked {args.bench_json}", file=sys.stderr)
    if not result["passed"]:
        raise SystemExit(1)


def cmd_soak(args):
    """Run the production-week soak (tpu_als.soak): seeded zipfian/
    diurnal traffic over a multi-tenant fleet with live fold-in and
    periodic refit, under the declarative chaos schedule; exit 0 only
    when the SLO verdict passes.  The verdict re-derives offline from
    the run dir alone: ``python tpu_als/soak/verdict.py <obs-dir>``."""
    from tpu_als.soak import chaos, orchestrator, traffic

    cfg = traffic.TrafficConfig(
        seed=args.seed, windows=args.windows, window_s=args.window_s,
        base_qps=args.base_qps, update_qps=args.update_qps,
        poison_frac=args.poison_frac)
    schedule = chaos.default_schedule(
        cfg.windows, victim=cfg.tenants[0][0],
        subprocesses=not args.no_subprocess_chaos)
    if args.plan:
        print(f"{cfg.windows} windows x {cfg.window_s}s "
              f"(~{cfg.windows * cfg.window_s / 60.0:.2f} scheduled "
              f"minutes), tenants "
              + ", ".join(f"{n}:{w:g}" for n, w in cfg.tenants))
        print(schedule.describe())
        return
    result = orchestrator.run_soak(
        cfg, schedule, rank=args.rank, refit_every=args.refit_every,
        judge_config={"slo_ms": args.slo_ms,
                      "freshness_slo_ms": args.freshness_slo_ms,
                      "fairness_max": args.fairness_max,
                      "shed_max": args.shed_max})
    print(orchestrator.render(result))
    if args.as_json:
        print(json.dumps(result, default=str))
    if args.bench_json:
        orchestrator.bank_result(result, args.bench_json)
        print(f"banked {args.bench_json}", file=sys.stderr)
    if not result["passed"]:
        raise SystemExit(1)


def _validate_fault_spec():
    """Fail LOUDLY (typed one-liner, exit 2) on an unparseable
    ``TPU_ALS_FAULT_SPEC`` before any command body imports the faults
    module — whose import-time ``install_from_env()`` would otherwise
    surface the same mistake as a raw traceback mid-command."""
    import os

    spec = os.environ.get("TPU_ALS_FAULT_SPEC", "").strip()
    if not spec:
        return
    try:
        # the import itself arms (and validates) the env spec
        from tpu_als.resilience import faults

        faults.parse_spec(spec)
    except ValueError as e:   # FaultSpecError subclasses ValueError
        print(f"tpu_als: FaultSpecError: TPU_ALS_FAULT_SPEC is "
              f"unparseable: {e}", file=sys.stderr)
        raise SystemExit(2) from e


def cmd_plan(args):
    """Execution-planner verbs (docs/planner.md): ``show`` renders the
    persistent autotune cache (mode, entries, provenance — corrupt
    files included, flagged); ``warm`` resolves the full ExecutionPlan
    for one configuration eagerly (cold: probes run and the verdicts
    bank; warm: zero probe executions) and prints it with the resolve
    wall-clock; ``tune`` runs the measured-timing kernel autotune
    (cold: real kernel timings bank; warm: pure cache read with zero
    tuning executions; ``--force`` re-tunes, ``--bank-out`` writes the
    regress/floor_audit direct bank); ``clear`` drops the on-disk
    entries and the in-process probe registry."""
    import time

    from tpu_als import plan as plan_pkg
    from tpu_als.plan import cache as plan_cache

    if args.plan_cmd == "show":
        entries = []
        for path, doc in plan_cache.list_entries():
            if isinstance(doc, dict):
                comps = {}
                for name, comp in doc["components"].items():
                    prov = comp["provenance"]
                    comps[name] = {
                        "resolved": comp["resolved"],
                        "banked_at": prov["banked_at"],
                        "walk_seconds": prov.get("walk_seconds"),
                        "probes_executed": prov.get("probes_executed"),
                        "model": prov.get("model"),
                    }
                    # the model-vs-measured column the re-plan loop
                    # reads: present on measured-timing components
                    # (kernel_config), rendered from the provenance the
                    # cache already banks
                    if prov.get("measured_seconds") is not None:
                        comps[name]["model_vs_measured"] = {
                            "prediction_s": prov.get("model_seconds"),
                            "measured_s": prov.get("measured_seconds"),
                            "ratio": prov.get("ratio"),
                            "source": prov.get("source"),
                            "tuned_config": comp["resolved"],
                            "invalidated": prov.get("invalidated"),
                        }
                entries.append({"path": path, "plan_key": doc["plan_key"],
                                "probes": doc["probes"],
                                "components": comps})
            else:                       # PlanCacheCorrupt — show, don't die
                entries.append({"path": path, "corrupt": str(doc)})
        print(json.dumps({"mode": plan_pkg.mode(),
                          "cache_dir": plan_cache.cache_dir(),
                          "entries": entries}, indent=2, default=str))
        return

    if args.plan_cmd == "warm":
        t0 = time.perf_counter()
        ep = plan_pkg.resolve_execution_plan(
            rank=args.rank, compute_dtype=args.dtype,
            solve_backend=args.solve_backend, cg_iters=args.cg_iters,
            k=args.k, n_users=args.users, n_items=args.items,
            n_devices=args.devices)
        out = ep.summary()
        out["resolve_seconds"] = round(time.perf_counter() - t0, 4)
        out["mode"] = plan_pkg.mode()
        print(json.dumps(out, default=str))
        return out

    if args.plan_cmd == "tune":
        if not plan_pkg.armed():
            print(json.dumps({"error": "plan cache is off "
                              "(TPU_ALS_PLAN_CACHE=off) — nothing to "
                              "tune against"}))
            raise SystemExit(2)
        space = None
        if args.space is not None:
            try:
                space = json.loads(args.space)
            except json.JSONDecodeError as e:
                print(f"tpu_als: --space is not valid JSON: {e}",
                      file=sys.stderr)
                raise SystemExit(2) from e
        t0 = time.perf_counter()
        config = plan_pkg.resolve_kernel_config(
            rank=args.rank, compute_dtype=args.dtype, tune=True,
            force=args.force, budget_s=args.budget_s, space=space,
            n=args.n, w=args.w, k=args.reps, seed=args.seed)
        key = plan_pkg.plan_key(rank=int(args.rank),
                                dtype=str(args.dtype))
        entry = plan_cache.load_entry(key)
        comp = (entry or {}).get("components", {}).get("kernel_config")
        prov = (comp or {}).get("provenance") or {}
        out = {"mode": plan_pkg.mode(), "config": config,
               "provenance": prov,
               "resolve_seconds": round(time.perf_counter() - t0, 4)}
        if args.bank_out is not None and prov:
            bank = {"metric": "autotune_fused_solve_speedup_"
                              + ("cpu" if prov["source"] == "interpret"
                                 else "tpu"),
                    "value": (prov["default_seconds"]
                              / prov["measured_seconds"]),
                    "unit": "x",
                    "kernel": "gather_solve",
                    "source": prov["source"],
                    "config": comp["resolved"],
                    "default_seconds": prov["default_seconds"],
                    "tuned_seconds": prov["measured_seconds"],
                    "model_seconds": prov["model_seconds"],
                    "tune_seconds": prov["tune_seconds"],
                    "shape": prov["model"]["shape"],
                    "banked_at": prov["banked_at"]}
            with open(args.bank_out, "w") as f:
                json.dump(bank, f, indent=2)
                f.write("\n")
            out["bank_out"] = args.bank_out
        print(json.dumps(out, default=str))
        return out

    if args.plan_cmd == "clear":
        root = plan_cache.cache_dir()
        n = plan_pkg.clear()
        print(json.dumps({"cleared_entries": n, "cache_dir": root}))
        return


def cmd_lint(args):
    """Delegate to the analysis linter (docs/analysis.md), rebuilding
    its argv — the engine owns the argument semantics and the direct
    ``python tpu_als/analysis/lint.py`` invocation (jax-free) must stay
    the single source of truth for both."""
    from tpu_als.analysis import lint as _lint

    argv = []
    if args.paths is not None:
        argv += ["--paths", *args.paths]
    if args.baseline is not None:
        argv += ["--baseline", args.baseline]
    if args.write_baseline:
        argv.append("--write-baseline")
    if args.rules:
        argv.append("--rules")
    if args.contracts:
        argv.append("--contracts")
    for name in args.contract or ():
        argv += ["--contract", name]
    return _lint.main(argv)


def main(argv=None):
    # choices + help for every strategy flag come from THE table in
    # parallel.trainer (running `python -m tpu_als.cli` already paid the
    # package import, so this is free here)
    from tpu_als.parallel.trainer import (EXECUTABLE_STRATEGIES,
                                          GATHER_STRATEGIES, strategy_help)

    ap = argparse.ArgumentParser(prog="tpu_als")
    sub = ap.add_subparsers(dest="cmd", required=True)

    # every run-producing subcommand can write a metrics/events run dir;
    # default (when only --output is given) is <output>/obs
    obs_common = argparse.ArgumentParser(add_help=False)
    obs_common.add_argument(
        "--obs-dir", default=None,
        help="write metrics/tracing events for this run here "
             "(default: <--output>/obs when --output is set; "
             "inspect with `tpu_als observe summarize DIR`)")

    t = sub.add_parser("train", help="fit an ALS model",
                       parents=[obs_common])
    t.add_argument("--data", required=True)
    t.add_argument("--rank", type=int, default=10)
    t.add_argument("--max-iter", type=int, default=10)
    t.add_argument("--reg-param", type=float, default=0.1)
    t.add_argument("--implicit", action="store_true")
    t.add_argument("--alpha", type=float, default=1.0)
    t.add_argument("--nonnegative", action="store_true")
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--holdout", type=float, default=0.2)
    t.add_argument("--output", default=None)
    t.add_argument("--log-file", default=None,
                   help="write per-iteration JSON log lines here")
    t.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the fit "
                        "(TensorBoard/Perfetto-readable)")
    t.add_argument("--devices", type=int, default=1,
                   help="train sharded over N devices (0 = all visible; "
                        "1 = single device, the default)")
    t.add_argument("--gather-strategy", default="all_gather",
                   choices=list(GATHER_STRATEGIES),
                   help="how sharded half-steps move the opposite factors "
                        "(authoritative table: parallel.trainer."
                        f"GATHER_STRATEGIES — {strategy_help()})")
    t.add_argument("--per-host-data", action="store_true",
                   help="multi-process only: each process loads its OWN "
                        "--data split ('{proc}' in the spec expands to "
                        "the process index) instead of a replicated load")
    t.add_argument("--cg-iters", type=int, default=0,
                   help="> 0: inexact ALS — warm-started CG solve with "
                        "this many steps per half-step (0 = exact "
                        "batched Cholesky)")
    t.add_argument("--checkpoint-dir", default=None,
                   help="write atomic factor checkpoints under this "
                        "directory every --checkpoint-interval "
                        "iterations (also the preemption save target: "
                        "SIGTERM checkpoints here and exits 43)")
    t.add_argument("--checkpoint-interval", type=int, default=10,
                   help="iterations between checkpoints (with "
                        "--checkpoint-dir)")
    t.add_argument("--resume", default=None, metavar="PATH|auto",
                   help="warm-start from a checkpoint: a directory "
                        "path, or 'auto' to discover the newest VALID "
                        "generation under --checkpoint-dir (corrupt "
                        "generations are quarantined to .corrupt/)")
    t.add_argument("--guardrails", default=None,
                   choices=("off", "warn", "recover"),
                   help="numerical-health guardrails (docs/resilience.md):"
                        " 'warn' reads divergence sentinels each "
                        "iteration and emits guardrail_tripped events; "
                        "'recover' adds adaptive solve-jitter escalation "
                        "and bounded rollback from the last-good factor "
                        "snapshot; default inherits TPU_ALS_GUARDRAILS "
                        "(unset = off)")
    t.add_argument("--elastic", action="store_true",
                   help="elastic mesh training (needs --devices > 1): "
                        "device loss becomes a rescheduling event — a "
                        "failed step is health-probed, the mesh re-forms "
                        "on the surviving devices and training resumes "
                        "from the last atomic checkpoint "
                        "(docs/resilience.md)")
    t.set_defaults(fn=cmd_train)

    e = sub.add_parser("evaluate", help="score a dataset with a saved model",
                       parents=[obs_common])
    e.add_argument("--model", required=True)
    e.add_argument("--data", required=True)
    e.add_argument("--ranking-k", type=int, default=0,
                   help="> 0: also report precision/recall@k, MAP, and "
                        "NDCG@k (test items rated >= --positive-threshold "
                        "are the per-user ground truth)")
    e.add_argument("--positive-threshold", type=float, default=3.5)
    e.set_defaults(fn=cmd_evaluate)

    r = sub.add_parser("recommend", help="top-k recommendations",
                       parents=[obs_common])
    r.add_argument("--model", required=True)
    r.add_argument("--users", default=None,
                   help="comma-separated original user ids (default: all)")
    r.add_argument("--k", type=int, default=10)
    r.add_argument("--limit", type=int, default=20,
                   help="max users to print (0 = all)")
    r.add_argument("--foldin-data", default=None,
                   help="ratings (csv:path / ml-100k:path) to fold into "
                        "the user factors before recommending — serves "
                        "new ratings/users without a refit")
    r.add_argument("--foldin-items-data", default=None,
                   help="ratings whose ITEMS are folded in against the "
                        "fixed user factors (new catalog entries served "
                        "without a refit); applied before --foldin-data")
    r.add_argument("--titles", default=None,
                   help="movie metadata path (u.item / movies.dat / "
                        "movies.csv, or their directory): join titles "
                        "into the output")
    r.add_argument("--devices", type=int, default=1,
                   help="serve all-users top-k sharded over N devices "
                        "(0 = all visible; 1 = single device)")
    r.add_argument("--gather-strategy", default="all_gather",
                   choices=["all_gather", "ring"],
                   help="sharded serving: gather the catalog once, or "
                        "ring-stream shards (catalog larger than one "
                        "device's HBM)")
    r.set_defaults(fn=cmd_recommend)

    g = sub.add_parser("tune", help="cross-validated grid search",
                       parents=[obs_common])
    g.add_argument("--data", required=True)
    g.add_argument("--ranks", default="8,16,32",
                   help="comma-separated rank grid")
    g.add_argument("--reg-params", default="0.01,0.05,0.1",
                   help="comma-separated regParam grid")
    g.add_argument("--max-iter", type=int, default=10)
    g.add_argument("--folds", type=int, default=3)
    g.add_argument("--implicit", action="store_true")
    g.add_argument("--alpha", type=float, default=1.0)
    g.add_argument("--alphas", default=None,
                   help="comma-separated alpha grid (implicit feedback); "
                        "alpha is traced, so the wider grid costs no "
                        "extra compiles")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--output", default=None,
                   help="save the best model here")
    g.add_argument("--cg-iters", type=int, default=0,
                   help="> 0: inexact-ALS CG solve for every grid fit "
                        "(k x numFolds fits amortize the speedup)")
    g.set_defaults(fn=cmd_tune)

    tt = sub.add_parser("tt-train",
                        help="train + persist the two-tower retrieval "
                             "model (ALS warm start by default)",
                        parents=[obs_common])
    tt.add_argument("--data", required=True)
    tt.add_argument("--output", default=None,
                    help="save the trained towers here")
    tt.add_argument("--epochs", type=int, default=5)
    tt.add_argument("--embed-dim", type=int, default=32)
    tt.add_argument("--als-rank", type=int, default=32)
    tt.add_argument("--als-iters", type=int, default=8)
    tt.add_argument("--cold", action="store_true",
                    help="skip the ALS warm start")
    tt.add_argument("--holdout", type=float, default=0.1)
    tt.add_argument("--positive-threshold", type=float, default=3.5)
    tt.add_argument("--k", type=int, default=10)
    tt.add_argument("--seed", type=int, default=0)
    tt.set_defaults(fn=cmd_tt_train)

    sb = sub.add_parser(
        "serve-bench",
        help="open-loop serving latency benchmark against an SLO "
             "(micro-batched engine, int8 index unless --exact)",
        parents=[obs_common])
    sb.add_argument("--users", type=int, default=20_000)
    sb.add_argument("--items", type=int, default=50_000)
    sb.add_argument("--rank", type=int, default=64)
    sb.add_argument("--k", type=int, default=10)
    sb.add_argument("--shortlist-k", type=int, default=64,
                    help="int8 shortlist rescored exactly in f32 "
                         "(>= items makes the match unconditional)")
    sb.add_argument("--exact", action="store_true",
                    help="skip the int8 index; score every request on "
                         "the exact chunked kernel")
    sb.add_argument("--qps", type=float, default=200.0,
                    help="open-loop arrival rate (requests/second)")
    sb.add_argument("--duration", type=float, default=5.0,
                    help="measured window in seconds")
    sb.add_argument("--slo-ms", type=float, default=50.0,
                    help="end-to-end p99 target the report is judged "
                         "against")
    sb.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline; requests that exceed it "
                         "while queued fail instead of being scored")
    sb.add_argument("--max-queue", type=int, default=1024,
                    help="admission-queue depth beyond which requests "
                         "are shed (typed Overloaded)")
    sb.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="micro-batch coalescing window")
    sb.add_argument("--buckets", default=None,
                    help="comma-separated padded batch sizes (one "
                         "compiled program each); default: the "
                         "execution planner's bucket plan (a banked "
                         "ladder for this device, else 8,32,128)")
    sb.add_argument("--foldin-frac", type=float, default=0.0,
                    help="fraction of requests carrying a fold-in "
                         "factor row instead of a user id")
    sb.add_argument("--mesh-devices", type=int, default=0,
                    help="> 0 serves from a device mesh of this many "
                         "shards: the catalog lives shard-resident "
                         "(never committed whole to one device) and "
                         "scoring runs the sharded fabric "
                         "(docs/serving.md)")
    sb.add_argument("--serve-backend", default="auto",
                    choices=("auto", "local", "sharded", "merge_ring"),
                    help="scoring backend on the mesh: sharded int8 "
                         "fan-out, the in-kernel merge-ring top-k, or "
                         "auto (probe-gated); local ignores the mesh")
    sb.add_argument("--update-qps", type=float, default=0.0,
                    help="concurrent rating-event rate through the "
                         "live fold-in → publish pipeline; >0 makes "
                         "the headline metric live_freshness_p99_ms")
    sb.add_argument("--freshness-slo-ms", type=float, default=5000.0,
                    help="arrival → servable p99 target for the live "
                         "stream (breach dumps the updater's flight "
                         "ring)")
    sb.add_argument("--update-poison-frac", type=float, default=0.0,
                    help="fraction of update events with a non-finite "
                         "rating — must be quarantined, never folded")
    sb.add_argument("--update-items", action="store_true",
                    help="also fold the ITEM side of each micro-batch "
                         "(exercises the index's incremental delta "
                         "re-quantization)")
    sb.add_argument("--update-max-batch", type=int, default=None,
                    help="live micro-batch cap (default: the "
                         "planner's live cadence)")
    sb.add_argument("--update-max-wait-ms", type=float, default=None,
                    help="live micro-batch deadline (default: the "
                         "planner's live cadence)")
    sb.add_argument("--tenants", type=int, default=0,
                    help=">= 2 runs the multi-tenant variant: N "
                         "same-shaped models behind one "
                         "MultiTenantEngine, equal open-loop load per "
                         "tenant, headline tenancy_worst_p99_ms judged "
                         "per tenant plus a goodput fairness ratio "
                         "(docs/tenancy.md)")
    sb.add_argument("--tenant-weights", default=None,
                    help="comma-separated fair-share weights, one per "
                         "tenant (default: all 1.0); the fairness "
                         "ratio is computed on served rows per weight")
    sb.add_argument("--fairness-bound", type=float, default=1.5,
                    help="max/min weighted-goodput ratio above which "
                         "the multi-tenant report fails its SLO")
    sb.add_argument("--seed", type=int, default=0)
    sb.add_argument("--bench-json", default=None, metavar="PATH",
                    help="also bank the result JSON (with banked_at "
                         "provenance) here, e.g. BENCH_serve_cpu.json")
    sb.set_defaults(fn=cmd_serve_bench)

    sc = sub.add_parser(
        "scenario",
        help="scripted production-day scenarios: composed chaos over "
             "train + serve + stream, judged by hard assertions "
             "evaluated from the obs trail (docs/scenarios.md)")
    scsub = sc.add_subparsers(dest="action", required=True)
    scr = scsub.add_parser(
        "run", help="run one named scenario; exit 0 only if every "
                    "assertion holds", parents=[obs_common])
    scr.add_argument("name",
                     help="scenario name (see `tpu_als scenario list`)")
    scr.add_argument("--slo-ms", type=float, default=None,
                     help="override the latency-SLO bound scenarios "
                          "judge p99 against (traffic-spike)")
    scr.add_argument("--freshness-slo-ms", type=float, default=None,
                     help="override the rating-arrival -> servable "
                          "bound (cold-start)")
    scr.add_argument("--seed", type=int, default=None,
                     help="override the scenario's default seed")
    scr.add_argument("--bench-json", default=None, metavar="PATH",
                     help="also bank the result JSON (with banked_at "
                          "provenance) here, e.g. "
                          "BENCH_scenario_traffic-spike.json")
    scr.add_argument("--json", dest="as_json", action="store_true",
                     help="also print the result as one JSON object")
    scr.set_defaults(fn=cmd_scenario)
    scl = scsub.add_parser(
        "list", help="list the scenarios, their chaos and their phases")
    scl.set_defaults(fn=cmd_scenario, obs_dir=None)

    sk = sub.add_parser(
        "soak",
        help="the production week at compressed timescale: synthetic "
             "zipfian/diurnal traffic drives multi-tenant serve + live "
             "fold-in + refit under a chaos schedule; exit 0 only when "
             "the SLO verdict passes (tpu_als.soak; docs/soak.md)",
        parents=[obs_common])
    sk.add_argument("--windows", type=int, default=8,
                    help="soak windows (the compressed week's length)")
    sk.add_argument("--window-s", type=float, default=3.0,
                    help="wall seconds per window")
    sk.add_argument("--base-qps", type=float, default=40.0,
                    help="serve queries/sec at the diurnal mean")
    sk.add_argument("--update-qps", type=float, default=25.0,
                    help="rating arrivals/sec at the diurnal mean")
    sk.add_argument("--poison-frac", type=float, default=0.02,
                    help="per-event probability a rating arrives "
                         "poisoned (nan -> quarantine path)")
    sk.add_argument("--seed", type=int, default=17,
                    help="traffic seed; (seed, schedule) replays the "
                         "whole workload byte-for-byte")
    sk.add_argument("--rank", type=int, default=8)
    sk.add_argument("--refit-every", type=int, default=3,
                    help="periodic refit-and-republish cadence, in "
                         "windows (0 disables; chaos refits still run)")
    sk.add_argument("--no-subprocess-chaos", action="store_true",
                    help="drop the CLI-child injections (preempt, "
                         "device loss) for a fast in-process soak")
    sk.add_argument("--slo-ms", type=float, default=None,
                    help="serve p99 bound for victim-free tenants")
    sk.add_argument("--freshness-slo-ms", type=float, default=None,
                    help="rating-arrival -> servable p99 bound")
    sk.add_argument("--fairness-max", type=float, default=None,
                    help="max/min answered-rate ratio across tenants")
    sk.add_argument("--shed-max", type=float, default=None,
                    help="shed/offered ceiling over the whole soak")
    sk.add_argument("--plan", action="store_true",
                    help="print the chaos schedule and exit (no soak)")
    sk.add_argument("--bench-json", default=None, metavar="PATH",
                    help="bank the verdict (survived-minutes headline, "
                         "tz-aware banked_at) here, e.g. "
                         "BENCH_soak_cpu.json")
    sk.add_argument("--json", dest="as_json", action="store_true",
                    help="also print the result as one JSON object")
    sk.set_defaults(fn=cmd_soak)

    f = sub.add_parser("foldin-bench", help="fold-in latency micro-benchmark",
                       parents=[obs_common])
    f.add_argument("--model", required=True)
    f.add_argument("--batches", type=int, default=20)
    f.add_argument("--batch-size", type=int, default=512)
    f.set_defaults(fn=cmd_foldin_bench)

    o = sub.add_parser("observe",
                       help="inspect a run directory's metrics/events")
    osub = o.add_subparsers(dest="action", required=True)
    os1 = osub.add_parser("summarize",
                          help="per-phase timings, per-iteration RMSE, "
                               "comm-bytes gauges, throughput")
    os1.add_argument("run_dir",
                     help="run dir (--output / --obs-dir of a past run)")
    os1.add_argument("--json", dest="as_json", action="store_true",
                     help="emit the summary as one JSON object")
    os1.add_argument("--since", type=float, default=None, metavar="S",
                     help="only events at/after S seconds into the "
                          "trail (relative to its first event)")
    os1.add_argument("--window", default=None, metavar="A:B",
                     help="only events in [A, B) seconds into the "
                          "trail (either side may be empty) — slice a "
                          "soak trail per chaos window")
    os1.set_defaults(fn=cmd_observe)
    os2 = osub.add_parser("tail", help="print the last N raw events")
    os2.add_argument("run_dir")
    os2.add_argument("-n", "--lines", type=int, default=20)
    os2.add_argument("--event", default=None, metavar="TYPE",
                     help="only events of this type (e.g. flight_record, "
                          "scenario_assert) — the last N AFTER filtering")
    os2.add_argument("--tenant", default=None, metavar="NAME",
                     help="only events labeled tenant=NAME — the last N "
                          "AFTER filtering")
    os2.add_argument("--trace", default=None, metavar="ID",
                     help="only events of one causal trace (trace_id "
                          "match, or membership in an event's trace_ids)")
    os2.set_defaults(fn=cmd_observe)
    os3 = osub.add_parser(
        "roofline",
        help="analytical per-stage bytes/FLOPs floor for one ALS "
             "iteration (defaults: THE headline config, with its "
             "measured point; see docs/roofline.md)")
    from tpu_als.perf.roofline import HEADLINE as _RL_HEADLINE

    os3.add_argument("--users", type=int, default=_RL_HEADLINE["n_users"])
    os3.add_argument("--items", type=int, default=_RL_HEADLINE["n_items"])
    os3.add_argument("--ratings", type=int, default=_RL_HEADLINE["nnz"])
    os3.add_argument("--rank", type=int, default=_RL_HEADLINE["rank"])
    os3.add_argument("--dtype", default=_RL_HEADLINE["dtype"],
                     choices=["float32", "bfloat16"])
    os3.add_argument("--explicit", action="store_true",
                     help="explicit feedback (default: implicit)")
    os3.add_argument("--padding-waste", type=float,
                     default=_RL_HEADLINE["padding_waste"],
                     help="padded_nnz / nnz of the built containers")
    os3.add_argument("--devices", type=int,
                     default=_RL_HEADLINE["devices"])
    os3.add_argument("--strategy", default=None,
                     choices=list(EXECUTABLE_STRATEGIES),
                     help="price the collective stage too (sharded; "
                          "table: parallel.trainer.GATHER_STRATEGIES)")
    os3.add_argument("--tiles", type=int, default=1,
                     help="row-tile count (ring/chunked strategies "
                          "re-stream the opposite factors per tile)")
    os3.add_argument("--ne-path", default="einsum",
                     choices=["einsum", "gather_fused",
                              "gather_fused_solve"],
                     help="normal-equation build to price: the unfused "
                          "gather+einsum round-trip, or the DMA-gather "
                          "fused kernel (ops/pallas_gather_ne — factor "
                          "rows read once, Vg never in HBM)")
    os3.add_argument("--measured-s-per-iter", type=float, default=None,
                     help="overlay a measured point (default: the "
                          "headline 1.184 when the config is untouched)")
    os3.add_argument("--json", dest="as_json", action="store_true")
    os3.set_defaults(fn=cmd_observe)
    os4 = osub.add_parser(
        "attribution",
        help="MEASURE where an iteration's seconds go: fence-timed "
             "per-stage seconds joined against the roofline floor "
             "(the measured counterpart of `observe roofline`)")
    os4.add_argument("--data", default="synthetic:943x1682x100000",
                     help="same specs as train --data; default is the "
                          "ml-100k shape synthetically (CPU-friendly); "
                          "use ml-100k:PATH for the real ratings")
    os4.add_argument("--rank", type=int, default=16)
    os4.add_argument("--iters", type=int, default=3,
                     help="fence-timed iterations (after --warmup "
                          "compile-absorbing ones)")
    os4.add_argument("--warmup", type=int, default=1)
    os4.add_argument("--explicit", action="store_true",
                     help="explicit feedback (default: implicit)")
    os4.add_argument("--dtype", default="float32",
                     choices=["float32", "bfloat16"])
    os4.add_argument("--reg", type=float, default=0.1)
    os4.add_argument("--alpha", type=float, default=1.0)
    os4.add_argument("--solve-backend", default="auto",
                     choices=["auto", "unfused", "gather_fused",
                              "gather_fused_solve"],
                     help="exact paths only (the CG ablations have no "
                          "decomposed twin)")
    os4.add_argument("--obs-dir", default=None, metavar="DIR",
                     help="also write the stage histograms + "
                          "attribution event as a run dir")
    os4.add_argument("--json", dest="as_json", action="store_true")
    os4.set_defaults(fn=cmd_observe)
    os5 = osub.add_parser(
        "regress",
        help="bench regression gate over the committed BENCH_*/"
             "MULTICHIP_* series: regressions beyond a noise band, "
             "value:null banks, missing banked_at provenance; typed "
             "exit code (1=regression 2=null 3=provenance)")
    os5.add_argument("root", nargs="?", default=".",
                     help="directory holding the bench artifacts "
                          "(default: cwd)")
    os5.add_argument("--noise", type=float, default=0.10,
                     help="relative band a latest-vs-best-prior move "
                          "must exceed to count as a regression")
    os5.add_argument("--strict", action="store_true",
                     help="historical nulls/unparseable rounds become "
                          "errors instead of warnings")
    os5.add_argument("--trend", action="store_true",
                     help="also fit the last --trend-window rounds of "
                          "each series and fail on sustained drift in "
                          "the worse direction beyond the noise band "
                          "(catches a slow slide the latest-vs-best "
                          "check misses)")
    os5.add_argument("--trend-window", type=int, default=5,
                     metavar="N",
                     help="rounds in the trend fit (needs >= 3 "
                          "effective points; default 5)")
    os5.add_argument("--json", dest="as_json", action="store_true")
    os5.set_defaults(fn=cmd_observe)
    os6 = osub.add_parser(
        "explain",
        help="reconstruct a request/event's full causal tree (admit -> "
             "queue -> round -> score / fold-in -> publish -> visible) "
             "from the trail's trace_span events; --breach last starts "
             "from the latest freshness/SLO breach")
    os6.add_argument("run_dir",
                     help="run dir / obs dir / events.jsonl path")
    os6.add_argument("--trace", default=None, metavar="ID",
                     help="render one trace's tree")
    os6.add_argument("--breach", default=None, choices=("last",),
                     help="start from the trail's last breach event and "
                          "render the trace it names")
    os6.set_defaults(fn=cmd_observe)

    pl = sub.add_parser(
        "plan",
        help="execution planner: inspect, warm, or clear the "
             "persistent autotune cache (docs/planner.md; "
             "TPU_ALS_PLAN_CACHE overrides the location, 'off' "
             "disarms)")
    plsub = pl.add_subparsers(dest="plan_cmd", required=True)
    pls = plsub.add_parser(
        "show", help="render the cache: mode, entries, per-component "
                     "provenance (corrupt files flagged, not fatal)")
    pls.set_defaults(fn=cmd_plan, obs_dir=None)
    plw = plsub.add_parser(
        "warm", parents=[obs_common],
        help="resolve the full ExecutionPlan for one configuration "
             "eagerly — cold resolves probe and bank, warm resolves "
             "answer from the cache with zero probe executions")
    plw.add_argument("--rank", type=int, default=128)
    plw.add_argument("--dtype", default="float32",
                     choices=["float32", "bfloat16"])
    plw.add_argument("--solve-backend", default="auto",
                     choices=["auto", "unfused", "gather_fused",
                              "gather_fused_solve"])
    plw.add_argument("--cg-iters", type=int, default=0)
    plw.add_argument("--k", type=int, default=10,
                     help="serving top-k (the pallas_topk probe keys "
                          "on it)")
    plw.add_argument("--users", type=int, default=None,
                     help="with --items and --devices > 1: also "
                          "resolve the gather strategy for this shape")
    plw.add_argument("--items", type=int, default=None)
    plw.add_argument("--devices", type=int, default=1)
    plw.set_defaults(fn=cmd_plan)
    plt = plsub.add_parser(
        "tune", parents=[obs_common],
        help="measured-timing kernel autotune at one shape class — "
             "cold: times real kernels min-of-k and banks the winner "
             "into the plan entry; warm: reads the banked config with "
             "zero tuning executions (--force re-tunes)")
    plt.add_argument("--rank", type=int, default=128)
    plt.add_argument("--dtype", default="float32",
                     choices=["float32", "bfloat16"])
    plt.add_argument("--budget-s", type=float, default=None,
                     help="wall-clock tuning budget in seconds; the "
                          "trial loop stops when exceeded (default: "
                          "120)")
    plt.add_argument("--space", default=None,
                     help="JSON dict restricting the search space, "
                          "e.g. '{\"depth\": [2, 8]}' — unknown knobs "
                          "are a typed error")
    plt.add_argument("--n", type=int, default=256,
                     help="timing-harness item count")
    plt.add_argument("--w", type=int, default=64,
                     help="timing-harness gather width")
    plt.add_argument("--reps", type=int, default=3,
                     help="min-of-k repetitions per trial")
    plt.add_argument("--seed", type=int, default=0)
    plt.add_argument("--force", action="store_true",
                     help="re-tune even when a valid banked config "
                          "exists (device-sourced banks still refuse "
                          "interpret-mode overwrites)")
    plt.add_argument("--bank-out", default=None,
                     help="also write a BENCH-style direct bank "
                          "(regress/floor_audit format) to this path")
    plt.set_defaults(fn=cmd_plan)
    plc = plsub.add_parser(
        "clear", help="drop the on-disk entries and the in-process "
                      "probe registry (.corrupt/ evidence is kept)")
    plc.set_defaults(fn=cmd_plan, obs_dir=None)

    ln = sub.add_parser(
        "lint",
        help="tracer-safety linter + jaxpr contract registry "
             "(docs/analysis.md; the AST pass is stdlib-only, "
             "--contracts re-verifies the byte pins)")
    ln.add_argument("--paths", nargs="*", default=None,
                    help="files/dirs to lint (default: tpu_als/, "
                         "scripts/, bench.py)")
    ln.add_argument("--baseline", default=None,
                    help="baseline file of accepted findings "
                         "(default: lint_baseline.txt; 'none' disables)")
    ln.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file")
    ln.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ln.add_argument("--contracts", action="store_true",
                    help="also re-verify every registered jaxpr "
                         "contract (guardrails_disarmed, plan_cache_off, "
                         "ne_audit, comm_audit)")
    ln.add_argument("--contract", action="append", default=None,
                    help="verify only this named contract (repeatable; "
                         "implies --contracts)")
    ln.set_defaults(fn=cmd_lint)

    args = ap.parse_args(argv)
    _validate_fault_spec()
    if getattr(args, "nonnegative", False) and \
            getattr(args, "cg_iters", 0) > 0:
        # solver precedence is nonnegative (NNLS) > cg (core/als.py);
        # refusing beats silently running the exact NNLS path under a
        # CG label (same stance as scripts/ablate.py's fused+cg guard)
        ap.error("--cg-iters cannot be combined with --nonnegative "
                 "(the NNLS solver takes precedence and the CG request "
                 "would be silently ignored)")
    if args.cmd in ("observe", "lint"):
        return args.fn(args)  # read-only commands must not write a run dir

    from tpu_als import obs

    run_dir = args.obs_dir
    if run_dir is None and getattr(args, "output", None):
        import os

        run_dir = os.path.join(args.output, "obs")
    if run_dir is not None:
        obs.configure(
            run_dir,
            config={k: v for k, v in vars(args).items() if k != "fn"},
            argv=list(argv) if argv is not None else sys.argv[1:])
        obs.emit("command", cmd=args.cmd,
                 argv=list(argv) if argv is not None else sys.argv[1:])
    try:
        with obs.span("cli." + args.cmd):
            return args.fn(args)
    finally:
        if run_dir is not None:
            # AFTER the command body: a train --output save atomically
            # REPLACES the output dir, so the run dir under it must be
            # written once the model is installed, not before.
            # deconfigure so a process issuing several commands (tests,
            # notebooks) never writes a later command's events here
            out = obs.finalize()
            obs.deconfigure()
            if out is not None:
                print(f"run metrics written to {out} "
                      f"(tpu_als observe summarize {out})",
                      file=sys.stderr)


if __name__ == "__main__":
    # several commands return report objects for in-process callers;
    # only integer returns are exit codes (lint findings, contract fails)
    _rc = main()
    sys.exit(_rc if isinstance(_rc, int) else 0)
