"""The ALS training engine: jitted half-steps over bucketed padded CSR.

This is the TPU-native replacement for the reference stack's ``computeFactors``
loop (Spark MLlib ``ml/recommendation/ALS.scala`` — SURVEY.md §3.1): where
Spark runs, per iteration, two RDD shuffles moving factor messages between
user-blocks and item-blocks and then per-row scalar solves inside tasks, here
each half-step is one jitted function: gather the opposite factor rows per
degree-bucket, build all normal equations with one einsum per bucket, and
solve them with one batched Cholesky (or fixed-sweep NNLS) per chunk.

Single-device and sharded training share :func:`local_half_step`; the sharded
path (tpu_als.parallel.trainer) wraps it in ``shard_map`` with an
``all_gather`` of the opposite factor shard in place of the shuffle.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace as _dc_replace

import jax
import jax.numpy as jnp

from tpu_als.core.ratings import trainer_chunk

from tpu_als.ops.solve import (
    DEFAULT_JITTER,
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_cg,
    solve_cg_matfree,
    solve_nnls,
    solve_spd,
)


@dataclass(frozen=True)
class AlsConfig:
    """Algorithm knobs.  Names/defaults mirror the Estimator params (§2.D)."""

    rank: int = 10
    max_iter: int = 10
    reg_param: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 1.0
    nonnegative: bool = False
    seed: int = 0
    nnls_sweeps: int = 32
    compute_dtype: str = "float32"  # or "bfloat16" for the A/b einsums
    # 'auto': normal equations + the fastest healthy Pallas solve —
    # batch-in-lanes (tpu_als.ops.pallas_lanes, rank <= 128, 2.2x the
    # blocked kernel on v5e) then blocked Cholesky (pallas_solve), else
    # the XLA cholesky lowering.  On the NE-build side, 'auto'
    # additionally upgrades the gather+einsum build to the DMA-gather
    # fused kernel (tpu_als.ops.pallas_gather_ne — factor rows stream
    # HBM→VMEM once, Vg never materialized), and beyond that to the
    # WHOLE-ITERATION fused kernel (gather → Gram → ridge/YtY tail →
    # in-VMEM Cholesky solve; A never exists in HBM), each step only
    # when BOTH its compile-and-validate probe AND its timing probe beat
    # the shallower path on this chip (available ≠ faster: the
    # pallas_fused lesson — its HBM-streamed Vg + per-column VPU solve
    # measured 34x slower than einsum+lanes on v5e, and it is retired).
    # 'gather_fused' forces the DMA-gather NE kernel,
    # 'gather_fused_solve' forces the whole-iteration kernel (both run
    # interpret-mode off-TPU, so CPU tests exercise them); 'unfused'
    # forces the plain einsum path (NNLS always uses unfused).
    # 'gather_fused_ring' forces the fused-COMM kernel under the ring
    # strategies: the inter-chip factor rotation runs as a
    # make_async_remote_copy ring INSIDE the whole-iteration kernel
    # (ops.pallas_gather_ne.gather_solve_ring) — explicit knob + an
    # availability probe on the live mesh, never a banked verdict (the
    # multi-host safety rule: banked outcomes must not steer
    # collectives).  On the local/all_gather paths it degrades to
    # 'gather_fused_solve' (an S=1 ring IS that kernel, bitwise)
    solve_backend: str = "auto"
    # > 0: replace the exact per-row factorization with that many
    # warm-started Jacobi-CG steps (ops.solve) — inexact ALS.
    # The solve cost drops from r³/3 serial-recurrence work to cg_iters
    # batched MXU matvecs; the warm start is the previous ALS iterate, so
    # the outer fixed-point loop converges to the same solution.
    # Precedence: nonnegative (NNLS) > forced fused backends > cg_iters.
    cg_iters: int = 0
    # 'matfree' (default): apply A through the gathered factor rows —
    # A·p = YtY·p + Vgᵀ((c−1) ⊙ (Vg·p)) + λn·p — so the [n, r, r]
    # normal-equation tensor is NEVER built (kills both the NE einsum and
    # A's HBM round-trips).  'dense': build A once, run CG on it (the
    # A/B partner; also what the ring strategy always uses — its A is
    # accumulated across streamed shards, which a matvec can't replay
    # without re-streaming the ring per CG step).
    cg_mode: str = "matfree"
    # THE solve pre-regularization floor: the absolute jitter added to
    # every per-row Gram matrix before factorization (ops.solve — one
    # knob for solve_spd / solve_cg / solve_cg_matfree / solve_nnls, and
    # the base rung of the adaptive escalation ladder).  Static: a
    # different jitter is a different compiled step.
    jitter: float = DEFAULT_JITTER
    # residual-checked jitter escalation + CG fallback inside solve_spd
    # (ops.solve ADAPTIVE_JITTER_RUNGS).  OFF by default — the plain
    # step's jaxpr must stay byte-identical; the guardrails 'recover'
    # mode (resilience.guardrails) flips it on for its own step build.
    adaptive_solve: bool = False


def resolve_solve_path(cfg: AlsConfig, rank, matfree_capable=True):
    """Which solve path the probes actually select for this config — the
    single source of truth for both the half-step dispatch and the
    benchmark's attribution fields.  When the execution planner is armed
    (TPU_ALS_PLAN_CACHE != 'off', the default) the resolve goes through
    tpu_als.plan: a warm cache entry for this (device, jax, rank, dtype)
    key seeds the probe registry so the walk below runs with ZERO probe
    executions; a cold resolve runs the walk and banks its verdicts.
    Either way the verdict is computed by :func:`_resolve_solve_path_walk`
    — the planner supplies probe outcomes, never a different answer — and
    with the planner off this is exactly the pre-planner behavior
    (tests/test_plan.py pins the training-step jaxpr byte-identical)."""
    from tpu_als import plan as _plan

    if _plan.armed():
        label = (f"solve={cfg.solve_backend},cg={cfg.cg_iters},"
                 f"mode={cfg.cg_mode},nonneg={int(cfg.nonnegative)},"
                 f"matfree={int(matfree_capable)}")
        resolved = _plan.resolve_training(
            rank=rank, compute_dtype=cfg.compute_dtype, label=label,
            walk=lambda: _resolve_solve_path_walk(cfg, rank,
                                                  matfree_capable))
        if resolved is not None:
            return resolved
    return _resolve_solve_path_walk(cfg, rank, matfree_capable)


def _tuned_kernel_kwargs(cfg: AlsConfig, rank):
    """``(kernel_kwargs, table_dtype)`` from the banked autotune config,
    or ``({}, None)`` — the untuned fallback.  STRICTLY gated on the
    planner being armed AND ``TPU_ALS_AUTOTUNE=1``: with the gate off
    nothing is consulted and the fused-solve call sites receive no
    extra kwargs, so the training-step jaxpr stays byte-identical to
    the pre-autotune tree (tests pin this the plan_cache_off way).
    ``table_dtype`` is the tuned factor-table residency dtype (the bf16
    knob); None means "keep cfg.compute_dtype"."""
    from tpu_als import plan as _plan

    if not (_plan.armed() and _plan.autotune_enabled()):
        return {}, None
    kcfg = _plan.resolve_kernel_config(rank=int(rank),
                                       compute_dtype=cfg.compute_dtype)
    if not kcfg:
        return {}, None
    kwargs = {"panel": int(kcfg["panel"]), "max_wc": int(kcfg["max_wc"]),
              "vmem_budget": int(kcfg["vmem_budget"]),
              "depth": int(kcfg["depth"])}
    tdt = str(kcfg.get("dtype") or cfg.compute_dtype)
    return kwargs, (None if tdt == str(cfg.compute_dtype) else tdt)


def _resolve_solve_path_walk(cfg: AlsConfig, rank, matfree_capable=True):
    """The probe walk behind :func:`resolve_solve_path` (VERDICT r1 weak
    #3: record *resolved* backends, not requested ones).

    Returns a dict with ``resolved_solve_path`` ∈ {'einsum+nnls',
    'gatherfused_solve' (the whole-iteration fused kernel — no '+'
    solver suffix because the solve happens in-kernel),
    'matfree_cg{n}_warmstart' (inexact ALS, no NE einsum;
    n = cfg.cg_iters), 'einsum+cg{n}_warmstart' (inexact ALS on the
    einsum-built A), 'einsum+pallas_lanes',
    'einsum+pallas_lanes_blocked' (out-of-core lanes, ranks > 128),
    'einsum+pallas_cholesky', 'einsum+xla_cholesky'} plus the raw probe
    outcomes.  The NE-build prefix flips from 'einsum' to 'gatherfused'
    (e.g. 'gatherfused+pallas_lanes') when solve_backend='gather_fused'
    forces the DMA-gather kernel, or — under 'auto' — when its
    compile-and-validate probe AND its beats-the-einsum timing probe
    both pass (tpu_als.ops.pallas_gather_ne); 'auto' further upgrades
    to 'gatherfused_solve' when the whole-iteration kernel's own
    validate + timing probes beat the best unfused composition.

    ``matfree_capable=False``: the caller's half-step cannot apply A
    matrix-free (the ring strategy — its A is accumulated across
    streamed shards) — cg_mode='matfree' then RESOLVES to the dense CG
    label, because that is what executes.
    """
    from tpu_als.ops import pallas_lanes, pallas_solve
    from tpu_als.ops.solve import auto_solve_backend
    from tpu_als.utils.platform import on_tpu

    tpu = on_tpu()
    # probe lazily: only the branches that consume a probe outcome run it
    # (each probe compiles+executes a kernel on TPU); None = not probed
    solve_ok = lanes_ok = blocked_ok = gather_ok = gsolve_ok = None
    if cfg.nonnegative:
        path = "einsum+nnls"
    elif cfg.solve_backend == "gather_fused_solve":
        # forced whole-iteration fusion: no probe — dispatch would ignore
        # its outcome, and the probe costs a Mosaic compile+execute on
        # every resolve.  Off-TPU the kernel runs in interpret mode.
        path = "gatherfused_solve"
    elif cfg.solve_backend == "gather_fused_ring":
        # forced fused-comm ring: the ring strategies move the rotation
        # in-kernel (comm.ring_fused_half_step); the local/all_gather
        # paths treat this as gather_fused_solve (the S=1 degenerate
        # ring, bitwise the same kernel body).  The on-mesh availability
        # probe (pallas_gather_ne.ring_available) gates the SHARDED
        # dispatch at step-build time, not here — resolve runs per
        # process and must not execute collectives.
        path = "gatherfused_ring"
    elif cfg.solve_backend == "gather_fused":
        # forced DMA-gather NE build; the solve still walks the probe
        # order (the kernel writes A/b, the solve stays on lanes/xla).
        # Off-TPU the kernel runs in interpret mode, so no gate here.
        base = {
            "lanes": "einsum+pallas_lanes",
            "lanes_blocked": "einsum+pallas_lanes_blocked",
            "pallas": "einsum+pallas_cholesky",
            "xla": "einsum+xla_cholesky",
        }[auto_solve_backend(rank)]
        path = "gatherfused" + base[len("einsum"):]
    elif cfg.cg_iters > 0:
        # inexact ALS: no factorization, no Pallas kernel, no probe —
        # matfree applies A through the factor rows (no NE einsum at
        # all); dense runs the matvecs on the einsum-built A
        path = (f"matfree_cg{cfg.cg_iters}_warmstart"
                if cfg.cg_mode == "matfree" and matfree_capable
                else f"einsum+cg{cfg.cg_iters}_warmstart")
    else:
        # the same probe walk solve_spd's dispatch runs — prewarming here
        # IS the prewarm contract; the re-reads below are cache hits
        path = {
            "lanes": "einsum+pallas_lanes",
            "lanes_blocked": "einsum+pallas_lanes_blocked",
            "pallas": "einsum+pallas_cholesky",
            "xla": "einsum+xla_cholesky",
        }[auto_solve_backend(rank)]
        from tpu_als.ops import pallas_lanes_blocked

        lanes_ok = bool(tpu and pallas_lanes.available(rank))
        blocked_ok = (None if lanes_ok
                      else bool(tpu and pallas_lanes_blocked.available(rank)))
        solve_ok = (None if (lanes_ok or blocked_ok)
                    else bool(tpu and pallas_solve.available(rank)))
        if cfg.solve_backend == "auto":
            # NE-build upgrade: the DMA-gather kernel replaces the
            # gather+einsum build ONLY when it validates AND measures
            # faster than the einsum path on this chip (both probes
            # cached per process; off-TPU both return False, so CPU runs
            # keep the einsum path under 'auto')
            from tpu_als.ops import pallas_gather_ne

            gather_ok = bool(
                tpu and pallas_gather_ne.available(rank, cfg.compute_dtype)
                and pallas_gather_ne.faster_than_einsum(
                    rank, cfg.compute_dtype))
            if gather_ok:
                path = "gatherfused" + path[len("einsum"):]
            # deepest fusion last: the whole-iteration kernel replaces
            # NE build AND solve only when it validates AND measures
            # faster than the best unfused composition (which the speed
            # probe itself picks via faster_than_einsum)
            gsolve_ok = bool(
                tpu
                and pallas_gather_ne.solve_available(rank,
                                                     cfg.compute_dtype)
                and pallas_gather_ne.solve_faster_than_unfused(
                    rank, cfg.compute_dtype))
            if gsolve_ok:
                path = "gatherfused_solve"
    return {
        "solve_backend_requested": cfg.solve_backend,
        "gather_ne_probe": gather_ok,
        "gather_solve_probe": gsolve_ok,
        "pallas_lanes_probe": lanes_ok,
        "pallas_lanes_blocked_probe": blocked_ok,
        "pallas_solve_probe": solve_ok,
        "resolved_solve_path": path,
        "on_tpu": tpu,
    }


def init_factors(key, num_rows, rank, dtype=jnp.float32):
    """Seeded init: unit-norm gaussian rows, like the reference stack's
    XORShiftRandom + normalize init (SURVEY.md §3.1 ``initialize``)."""
    x = jax.random.normal(key, (num_rows, rank), dtype=jnp.float32)
    nrm = jnp.linalg.norm(x, axis=1, keepdims=True)
    return (x / jnp.maximum(nrm, 1e-12)).astype(dtype)


def local_half_step(V_full, buckets, num_rows, cfg: AlsConfig, YtY=None,
                    chunk_elems=1 << 19, prev=None, reg=None, alpha=None):
    """Solve all rows of one side given the full opposite factor matrix.

    V_full [N_opposite, r]; buckets: list[Bucket] (device arrays); returns
    new factors [num_rows, r].  Everything static-shaped; per bucket the rows
    are processed in scan chunks so the gathered [chunk, w, r] tensor stays
    within the HBM budget set by ``chunk_elems`` — pass the value the buckets
    were built with (``CsrBuckets.chunk_elems``) so row padding divides the
    chunk exactly.

    ``prev`` [num_rows, r]: the solved side's CURRENT factors — the warm
    start for the inexact-ALS CG path (``cfg.cg_iters > 0``); ignored by
    the exact solvers.

    ``reg``: overrides ``cfg.reg_param``, and may be a TRACED scalar —
    the single-device step passes it dynamically so configs differing
    only in regParam share one compiled executable (a CrossValidator
    regParam grid then compiles once per rank instead of once per cell).
    The whole-iteration fused branch ('gatherfused_solve') keeps the
    static ``cfg.reg_param``/``cfg.alpha`` (its Pallas tail bakes them
    into the kernel; make_step keeps them in the jit cache key there).
    """
    if reg is None:
        reg = cfg.reg_param
    if alpha is None:
        alpha = cfg.alpha
    r = V_full.shape[-1]
    cdt = jnp.dtype(cfg.compute_dtype)
    # cast ONCE before the gathers: the gather reads padded_nnz × r elements
    # (>> N × r), so under bfloat16 casting first halves the dominant HBM
    # stream; casting after the gather would move f32 bytes and only shrink
    # the einsum inputs
    V_comp = V_full.astype(cdt)
    out = jnp.zeros((num_rows, r), dtype=jnp.float32)

    if cfg.solve_backend not in ("auto", "unfused", "gather_fused",
                                 "gather_fused_solve",
                                 "gather_fused_ring"):
        raise ValueError(
            f"unknown solve_backend {cfg.solve_backend!r} (expected "
            "'auto', 'unfused', 'gather_fused', 'gather_fused_solve' or "
            "'gather_fused_ring')")
    resolved = resolve_solve_path(cfg, r)
    # DMA-gather fused NE build (ops.pallas_gather_ne): the factor rows
    # stream HBM→VMEM inside the kernel, so the Vg = V_comp[c] gather
    # below never runs and the [chunk, w, r] intermediate never exists —
    # trainer_chunk drops it from the memory model (fused_gather=True).
    # 'gatherfused_solve' goes further: the ridge/YtY tail and the
    # Cholesky solve also run in-kernel, so A/b never exist in HBM.
    # Off-TPU the kernels run in interpret mode (CPU tier-1 exercises
    # them).
    # 'gatherfused_ring' on this LOCAL path is the S=1 degenerate ring —
    # the same whole-iteration kernel body, bitwise — so it shares the
    # gsolve dispatch (the in-kernel rotation only exists under the ring
    # strategies' shard_map; comm.ring_fused_half_step owns that case)
    gsolve = resolved["resolved_solve_path"] in ("gatherfused_solve",
                                                 "gatherfused_ring")
    gather = resolved["resolved_solve_path"].startswith("gatherfused+")
    gather_interpret = not resolved["on_tpu"]
    # banked autotune knobs for the fused-solve kernel ({} unless armed
    # AND TPU_ALS_AUTOTUNE=1 — the byte-identical-jaxpr-off contract);
    # a tuned table dtype overrides the kernel's stream dtype only
    tuned_kw, tuned_dt = (_tuned_kernel_kwargs(cfg, r) if gsolve
                          else ({}, None))
    kdt = jnp.dtype(tuned_dt) if tuned_dt else cdt
    cg = (cfg.cg_iters > 0 and not cfg.nonnegative
          and not (gather or gsolve))
    if cfg.cg_mode not in ("matfree", "dense"):
        raise ValueError(f"unknown cg_mode {cfg.cg_mode!r} "
                         "(expected 'matfree' or 'dense')")
    matfree = cg and cfg.cg_mode == "matfree"

    for b in buckets:
        nb, w = b.cols.shape
        chunk = trainer_chunk(nb, w, r, chunk_elems,
                              fused_gather=gather or gsolve)
        nchunks = nb // chunk
        cols = b.cols.reshape(nchunks, chunk, w)
        vals = b.vals.reshape(nchunks, chunk, w)
        mask = b.mask.reshape(nchunks, chunk, w)
        rows = b.rows.reshape(nchunks, chunk)

        def solve_chunk(args):
            c, v, m, rw = args
            if gsolve:
                from tpu_als.ops.pallas_gather_ne import (
                    gather_fused_solve_explicit,
                    gather_fused_solve_implicit,
                )

                # whole-iteration fusion: gather, Gram, ridge/YtY tail
                # AND the blocked Cholesky solve in one kernel — only x
                # comes back; A/b/Vg never exist in HBM.  reg/alpha/
                # jitter are STATIC here (the Pallas tail bakes them in;
                # make_step keeps them in the cache key for this path).
                with jax.named_scope("gather_fused_solve"):
                    if cfg.implicit_prefs:
                        return gather_fused_solve_implicit(
                            V_comp.astype(kdt), c, v.astype(kdt),
                            m.astype(kdt),
                            cfg.reg_param, cfg.alpha,
                            YtY.astype(jnp.float32),
                            jitter=cfg.jitter, **tuned_kw,
                            interpret=gather_interpret)
                    return gather_fused_solve_explicit(
                        V_comp.astype(kdt), c, v.astype(kdt),
                        m.astype(kdt),
                        cfg.reg_param, jitter=cfg.jitter, **tuned_kw,
                        interpret=gather_interpret)
            if gather:
                from tpu_als.ops.pallas_gather_ne import (
                    gather_normal_eq_explicit,
                    gather_normal_eq_implicit,
                )

                # fused DMA-gather + Gram build: A/b come straight off
                # the HBM-resident V_comp; semantics are bitwise the
                # normal_eq_* path (same weights/ridge/YtY/count — the
                # empty-row guard stays in solve_spd, as always)
                with jax.named_scope("gather_fused_ne"):
                    if cfg.implicit_prefs:
                        A, rhs, count = gather_normal_eq_implicit(
                            V_comp, c, v.astype(cdt), m.astype(cdt),
                            reg, alpha, YtY.astype(jnp.float32),
                            interpret=gather_interpret)
                    else:
                        A, rhs, count = gather_normal_eq_explicit(
                            V_comp, c, v.astype(cdt), m.astype(cdt),
                            reg, interpret=gather_interpret)
                with jax.named_scope("solve"):
                    return solve_spd(A.astype(jnp.float32),
                                     rhs.astype(jnp.float32), count,
                                     jitter=cfg.jitter,
                                     adaptive=cfg.adaptive_solve)
            with jax.named_scope("gather_factors"):
                Vg = V_comp[c]
            # warm start for the inexact (CG) solvers: the solved side's
            # current rows.  Padding rows (index num_rows) clip to a real
            # row's stale value, but their count is 0 so CG drives them
            # to 0 and the scatter drops them anyway.  One site for both
            # CG modes so their trajectories cannot diverge.
            x0 = None
            if cg and prev is not None:
                x0 = prev.astype(jnp.float32)[jnp.clip(rw, 0, num_rows - 1)]
            if matfree:
                # matrix-free inexact solve (ops.solve.solve_cg_matfree):
                # A applied through Vg — neither the NE einsum nor the
                # [chunk, r, r] tensor ever exists
                with jax.named_scope("cg_matfree"):
                    return solve_cg_matfree(
                        Vg, v, m, reg,
                        implicit=cfg.implicit_prefs, alpha=alpha,
                        YtY=YtY, x0=x0, iters=cfg.cg_iters,
                        jitter=cfg.jitter)
            with jax.named_scope("normal_eq"):
                if cfg.implicit_prefs:
                    A, rhs, count = normal_eq_implicit(
                        Vg, v.astype(cdt), m.astype(cdt), reg,
                        alpha, YtY.astype(jnp.float32),
                    )
                else:
                    A, rhs, count = normal_eq_explicit(
                        Vg, v.astype(cdt), m.astype(cdt), reg
                    )
            A = A.astype(jnp.float32)
            rhs = rhs.astype(jnp.float32)
            with jax.named_scope("solve"):
                if cfg.nonnegative:
                    return solve_nnls(A, rhs, count, sweeps=cfg.nnls_sweeps,
                                      jitter=cfg.jitter)
                if cg:
                    return solve_cg(A, rhs, count, x0=x0,
                                    iters=cfg.cg_iters, jitter=cfg.jitter)
                return solve_spd(A, rhs, count, jitter=cfg.jitter,
                                 adaptive=cfg.adaptive_solve)

        if nchunks == 1:
            x = solve_chunk((cols[0], vals[0], mask[0], rows[0]))
            xs = x[None]
        else:
            xs = jax.lax.map(solve_chunk, (cols, vals, mask, rows))
        # padding rows carry index num_rows -> out of bounds -> dropped
        out = out.at[b.rows].set(
            xs.reshape(nb, r), mode="drop", unique_indices=True
        )
    return out


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "num_users", "num_items",
                     "user_chunk_elems", "item_chunk_elems"),
    donate_argnums=(0, 1))
def _step_jit(U, V, ub, ib, reg, alpha, *, cfg, num_users, num_items,
              user_chunk_elems, item_chunk_elems):
    """THE jitted full ALS iteration — module-level, so its jit cache is
    keyed on (static config, array shapes) and SHARED across fits.
    ``reg`` and ``alpha`` are traced scalars: estimators differing only
    in regParam/alpha reuse one compiled executable (see make_step)."""
    if cfg.implicit_prefs:
        YtY_u = compute_yty(U)
        V = local_half_step(U, ib, num_items, cfg, YtY_u,
                            item_chunk_elems, prev=V, reg=reg, alpha=alpha)
        YtY_v = compute_yty(V)
        U = local_half_step(V, ub, num_users, cfg, YtY_v,
                            user_chunk_elems, prev=U, reg=reg, alpha=alpha)
    else:
        V = local_half_step(U, ib, num_items, cfg,
                            chunk_elems=item_chunk_elems, prev=V, reg=reg)
        U = local_half_step(V, ub, num_users, cfg,
                            chunk_elems=user_chunk_elems, prev=U, reg=reg)
    return U, V


def make_step(user_buckets, item_buckets, num_users, num_items, cfg: AlsConfig,
              user_chunk_elems=1 << 19, item_chunk_elems=1 << 19):
    """Build the jitted full ALS iteration (item half-step then user
    half-step, the reference stack's order — SURVEY.md §3.1).

    The rating buckets are passed to the jitted function as *arguments*, not
    closure captures: a closed-over device array is baked into the HLO as a
    constant, which at ML-25M scale means shipping ~1 GB of rating data
    inside the compile payload (and re-compiling whenever the data changes).
    As arguments they stay on device and the compiled step is reusable.

    regParam AND alpha enter the compiled step as TRACED scalars and are
    stripped from the static cache key (along with max_iter/seed, which
    the step body never reads), so a tuning grid over regParam/alpha at
    fixed rank/data compiles ONCE instead of once per grid cell — the
    recompile tax on a CrossValidator was ~30s × cells on a v5e.  The
    whole-iteration fused config ('gatherfused_solve') keeps both static
    (its Pallas tail bakes them into the kernel).
    """
    # probe the solve kernels EAGERLY: a probe firing inside the jit trace
    # below cannot run (and the jit cache would pin the fallback path for
    # the step's lifetime) — see ops.solve.prewarm_solve
    resolved = resolve_solve_path(cfg, cfg.rank)
    if resolved["resolved_solve_path"] == "gatherfused_solve":
        # the whole-iteration kernel bakes reg/alpha into its Pallas tail
        # (static lowering) — keep them in the cache key so two regParams
        # compile two steps instead of sharing a wrong executable
        cfg_key = _dc_replace(cfg, max_iter=0, seed=0)
    else:
        cfg_key = _dc_replace(cfg, reg_param=0.0, alpha=0.0,
                              max_iter=0, seed=0)
    reg = jnp.float32(cfg.reg_param)
    alpha = jnp.float32(cfg.alpha)

    def step(U, V):
        return _step_jit(U, V, user_buckets, item_buckets, reg, alpha,
                         cfg=cfg_key, num_users=num_users,
                         num_items=num_items,
                         user_chunk_elems=user_chunk_elems,
                         item_chunk_elems=item_chunk_elems)

    return step


def train(user_csr, item_csr, cfg: AlsConfig, callback=None, init=None,
          start_iter=0):
    """Single-device ALS training loop.

    ``user_csr``: CsrBuckets keyed by user (cols = item idx) — solves U.
    ``item_csr``: CsrBuckets keyed by item (cols = user idx) — solves V.
    ``callback(iteration, U, V)`` runs between iterations (logging,
    checkpointing); the per-iteration compute itself is one jitted call with
    zero host round-trips inside.

    ``init``: optional ``(U0, V0)`` warm start — the failure-recovery path
    (SURVEY.md §5.3): ALS is a fixed-point iteration, so resuming from a
    checkpoint's factors at ``start_iter`` reproduces the uninterrupted run
    exactly.  Runs the remaining ``cfg.max_iter - start_iter`` iterations.
    """
    num_users = user_csr.num_rows
    num_items = item_csr.num_rows
    if init is not None:
        U = jnp.asarray(init[0], dtype=jnp.float32)
        V = jnp.asarray(init[1], dtype=jnp.float32)
    else:
        key = jax.random.PRNGKey(cfg.seed)
        ku, kv = jax.random.split(key)
        U = init_factors(ku, num_users, cfg.rank)
        V = init_factors(kv, num_items, cfg.rank)

    ub = jax.device_put(user_csr.device_buckets())
    ib = jax.device_put(item_csr.device_buckets())
    step = make_step(ub, ib, num_users, num_items, cfg,
                     user_csr.chunk_elems, item_csr.chunk_elems)
    # stage attribution (obs/trace.py): armed via TPU_ALS_STAGE_ATTRIBUTION
    # or obs.trace.enable_stage_attribution(), the fused step above is
    # replaced by its decomposed fence-timed twin and per-stage seconds
    # land in train.stage_seconds histograms.  Disarmed (the default),
    # this one boolean check per train() call is the entire cost — the
    # jitted step is untouched (pinned in tests/test_attribution.py).
    from tpu_als.obs.trace import stage_attribution_armed

    if stage_attribution_armed():
        from tpu_als.perf.attribution import make_attributed_step

        step = make_attributed_step(ub, ib, num_users, num_items, cfg,
                                    user_csr.chunk_elems,
                                    item_csr.chunk_elems)

    # numerical-health guardrails (resilience/guardrails.py): armed via
    # --guardrails warn|recover / TPU_ALS_GUARDRAILS.  Same discipline as
    # stage attribution above — disarmed, this one mode check is the
    # entire cost and the jitted step is byte-identical (pinned in
    # tests/test_guardrails.py).  Armed, sentinels are a SEPARATE small
    # jitted reduction read at the callback boundary; the production
    # step is never modified.  'recover' additionally builds its step
    # with the adaptive solve ladder so ill-conditioned Gram rows heal
    # in-device before a sentinel ever has to trip.
    from tpu_als.resilience import faults
    from tpu_als.resilience.guardrails import Monitor, guardrails_mode

    gmode = guardrails_mode()
    monitor = None
    if gmode != "off":
        monitor = Monitor(cfg, gmode)
        if gmode == "recover" and not stage_attribution_armed():
            step = make_step(ub, ib, num_users, num_items,
                             _dc_replace(cfg, adaptive_solve=True),
                             user_csr.chunk_elems, item_csr.chunk_elems)
    gram_fault = faults.armed("solve.gram")

    it = start_iter
    retry = False
    while it < cfg.max_iter:
        if monitor is not None:
            monitor.keep_last_good(U, V, retry=retry)
        U, V = step(U, V)
        if gram_fault and faults.check("solve.gram") == "corrupt":
            # chaos hook: poison one factor row post-step, host-level —
            # exactly what a blown Gram solve leaves behind
            U = U.at[0].set(jnp.nan)
        if monitor is not None:
            trip = monitor.judge(it + 1, U, V)
            if trip is not None and monitor.mode == "recover":
                U, V, reg_scale = monitor.rollback(it + 1, trip)
                # rebuild with bumped reg: reg_param is a TRACED scalar
                # stripped from the jit cache key (make_step docstring),
                # so this is a cache hit, not a recompile
                step = make_step(
                    ub, ib, num_users, num_items,
                    _dc_replace(cfg, adaptive_solve=True,
                                reg_param=cfg.reg_param * reg_scale),
                    user_csr.chunk_elems, item_csr.chunk_elems)
                retry = True
                continue
        if (monitor is not None and retry and monitor.mode == "recover"
                and monitor.reg_scale != 1.0):
            # the reg bump is TRANSIENT: the retried iteration cleared,
            # so drop back to the configured regularization — a
            # permanent bump would quietly change the model the user
            # asked for (also a jit cache hit, same as above)
            monitor.reg_scale = 1.0
            step = make_step(ub, ib, num_users, num_items,
                             _dc_replace(cfg, adaptive_solve=True),
                             user_csr.chunk_elems, item_csr.chunk_elems)
        retry = False
        it += 1
        if callback is not None:
            callback(it, U, V)
    return U, V


@jax.jit
def predict(U, V, u_idx, i_idx, u_valid, i_valid):
    """Gather-dot scoring: the TPU replacement for the reference stack's two
    distributed hash joins in ``ALSModel.transform`` (SURVEY.md §3.2).

    Out-of-range / cold ids (valid mask False) yield NaN — the
    ``coldStartStrategy='nan'`` semantic; 'drop' filters host-side.
    """
    u = jnp.clip(u_idx, 0, U.shape[0] - 1)
    i = jnp.clip(i_idx, 0, V.shape[0] - 1)
    scores = jnp.einsum("nr,nr->n", U[u], V[i])
    ok = (
        u_valid & i_valid
        & (u_idx >= 0) & (u_idx < U.shape[0])
        & (i_idx >= 0) & (i_idx < V.shape[0])
    )
    return jnp.where(ok, scores, jnp.nan)
