"""Ratings containers: id remapping + bucketed, padded CSR shards.

This is the TPU-native replacement for the reference stack's blocking
machinery (Spark MLlib's ``RatingBlock``/``InBlock``/``OutBlock``/
``LocalIndexEncoder`` inside ``ml/recommendation/ALS.scala`` — SURVEY.md
§2.B4): where Spark compresses ratings into a ``numUserBlocks ×
numItemBlocks`` grid of CSC-like structures and shuffles factor messages
between them, we lay ratings out as **statically-shaped, degree-bucketed,
padded CSR** resident in HBM, so every ALS half-step is a fixed set of
gather→einsum→cholesky calls with no dynamic shapes (SURVEY.md §7 hard-part 1:
"raggedness on a static-shape machine").

Bucketing: entity rows are grouped by rating count into power-of-two width
buckets (width = next_pow2(count), floored at ``min_width``), each padded to
its width.  Power-law degree skew therefore costs at most 2× padding per row
instead of max-degree× padding for a single rectangle.

All structures here are host-side numpy; the trainer moves them to device
once (the "pulled … into device-sharded CSR blocks once" step of the
north-star in BASELINE.json).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

# the shared rating-sanity bound for poisoned-input quarantine
# (resilience/guardrails): any |rating| above this is treated as data
# corruption, not signal.  Real rating scales are O(1)-O(100); implicit
# confidence counts can be large but a value past 1e6 overwhelms the f32
# normal-equation accumulators (r^2 terms reach 1e12) and is always a
# poisoned record in practice.
RATING_ABS_MAX = 1e6


def invalid_rating_mask(r, max_abs=RATING_ABS_MAX):
    """Boolean mask of ratings that must be quarantined: non-finite or
    magnitude above ``max_abs``.  numpy-only — shared by the streaming
    ingest quarantine (io.stream) and the estimator's input scrub
    (api.estimator), so both sides of the guardrail agree on what
    'poisoned' means."""
    r = np.asarray(r)
    return ~np.isfinite(r) | (np.abs(r) > max_abs)


class Bucket(NamedTuple):
    """One fixed-width padded CSR bucket.  A pytree of arrays.

    rows [nb]      entity index per row; padding rows hold ``oob_row`` (one
                   past the last valid index) so factor scatters can use
                   ``mode='drop'`` instead of a mask.
    cols [nb, w]   opposite-entity indices (0 in padding slots)
    vals [nb, w]   ratings (0 in padding slots)
    mask [nb, w]   1.0 real / 0.0 padding
    """

    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    mask: np.ndarray

    @property
    def width(self):
        # last axis so the property also holds for stacked [..., nb, w]
        # bucket arrays (tpu_als.parallel.data / .comm)
        return self.cols.shape[-1]


@dataclass
class CsrBuckets:
    """All buckets for one side (users or items) of one shard."""

    buckets: list  # list[Bucket], ascending width
    num_rows: int  # entity count this shard (valid scatter targets)
    counts: np.ndarray  # [num_rows] rating count per entity
    nnz: int
    chunk_elems: int  # scan-chunk budget the padding was built for; the
    # trainer must chunk with this same value (rows are pre-padded to it)

    @property
    def padded_nnz(self):
        return sum(b.mask.size for b in self.buckets)

    def device_buckets(self):
        """Buckets as a plain list of NamedTuples (already a pytree)."""
        return list(self.buckets)


@dataclass
class IdMap:
    """Dense-index ↔ original-id mapping, persisted with the model.

    The reference stack requires ids to fit in int range and keeps them as-is
    (SURVEY.md §7 hard-part 5); we additionally densify to 0..N-1 so factor
    matrices are plain arrays.  ``ids[dense] == original``.
    """

    ids: np.ndarray  # [n] original ids, position = dense index

    def __post_init__(self):
        self._lookup = None

    def __len__(self):
        return len(self.ids)

    def to_dense(self, original, missing=-1):
        """Map original ids -> dense indices; unseen ids -> ``missing``."""
        original = np.asarray(original)
        if self._lookup is None:
            order = np.argsort(self.ids, kind="stable")
            self._lookup = (self.ids[order], order)
        sorted_ids, order = self._lookup
        pos = np.searchsorted(sorted_ids, original)
        pos = np.clip(pos, 0, len(sorted_ids) - 1)
        hit = sorted_ids[pos] == original
        return np.where(hit, order[pos], missing).astype(np.int64)

    def to_original(self, dense):
        return self.ids[np.asarray(dense)]


def remap_ids(raw):
    """Densify one id column.  Returns (dense_idx [n], IdMap)."""
    raw = np.asarray(raw)
    uniq, inv = np.unique(raw, return_inverse=True)
    return inv.astype(np.int64), IdMap(ids=uniq)


def _next_pow2(x):
    return 1 << int(max(0, int(np.ceil(np.log2(max(1, x))))))


def entity_widths(counts, min_width, growth=2.0):
    """Bucket width per entity, floored at ``min_width``.  The single
    source of truth for bucket assignment — the numpy and native blocking
    paths both call this.

    growth=2.0 (default): next power of two — worst-case 2× padding.
    growth=1.5: adds the 0.75·2^k rungs that are multiples of 8
    (…, 24, 48, 96, 192, …), cutting worst-case padding to ~1.5× at the
    cost of ~1.4× more bucket specializations.  The 8-multiple restriction
    keeps every width a TPU sublane multiple (the fused kernel and the
    sharded stackers rely on it).
    """
    counts = np.maximum(np.asarray(counts, dtype=np.int64), 1)
    w = np.maximum(
        min_width, 1 << np.ceil(np.log2(counts)).astype(np.int64)
    )
    if growth < 2.0:
        w34 = (3 * w) // 4
        ok = (w34 >= counts) & (w34 >= min_width) & (w34 % 8 == 0)
        w = np.where(ok, w34, w)
    return w


def scan_chunk(nb, width, chunk_elems):
    """Builder-side rows-per-scan-step for a bucket of ``nb`` rows of
    ``width``.  Always a power of two, so the trainer can halve it freely
    (any smaller power of two still divides the padded row count) when the
    rank makes the per-row normal-equation tensor, not the gathered factors,
    the dominant intermediate.  Builders pad row counts up to a multiple.

    The chunk is additionally capped at ~``nb``/16 (floored at 64 rows):
    pad-to-chunk costs up to ``chunk - 1`` fully-computed phantom rows, so
    a chunk near ``nb`` (the old single-chunk regime) could double a
    bucket's work at small scale, while ≥16 scan steps keep the padding
    under ~6-12% for the cost of amortized extra launches.  The trainer's
    re-derivation (:func:`trainer_chunk`) provably lands on the same chunk
    for the padded count — and its gcd fallback covers any drift.
    """
    cap = max(1, chunk_elems // width)
    cap = 1 << (cap.bit_length() - 1)  # floor to power of two
    full = 1 << max(0, nb - 1).bit_length()  # ceil to power of two
    tgt = max(64, 1 << max(0, -(-nb // 16) - 1).bit_length())
    return max(1, min(cap, full, tgt))


def padded_bucket_rows(nb, width, chunk_elems):
    """Bucket row count padded to its scan chunk — THE pairing every
    builder must use identically (numpy/native blocking, the sharded
    stacker, and the multi-host layout agreement all call this; a drifted
    copy would make hosts disagree on global bucket shapes)."""
    chunk = scan_chunk(nb, width, chunk_elems)
    return -(-nb // chunk) * chunk


def trainer_chunk(nb_padded, width, rank, chunk_elems, mem_elems=1 << 28,
                  fused_gather=False):
    """Trainer-side chunk: the builder chunk, halved until the largest
    per-chunk intermediate — max(Vg [chunk,w,r], A [chunk,r,r]) — fits in
    ``mem_elems`` elements (default 2^28 f32 elems = 1 GiB).

    ``fused_gather=True``: the DMA-gather NE kernel
    (tpu_als.ops.pallas_gather_ne) never materializes Vg in HBM — only
    the A tensor bounds the chunk, so wide buckets keep the builder
    chunk instead of halving it ``width/rank``-fold.

    The gcd fallback only defends against buckets built with a different
    ``chunk_elems`` (degrades throughput, never correctness).
    """
    c = scan_chunk(nb_padded, width, chunk_elems)
    big = rank if fused_gather else max(width, rank)
    while c > 1 and c * rank * big > mem_elems:
        c //= 2
    if nb_padded % c:
        c = math.gcd(nb_padded, c)
    return c


def build_csr_buckets(
    row_idx,
    col_idx,
    vals,
    num_rows,
    min_width=8,
    chunk_elems=1 << 19,
    dtype=np.float32,
    native=None,
    width_growth=2.0,
):
    """Build degree-bucketed padded CSR from COO triples.

    Duplicate (row, col) entries are kept as-is (they contribute twice, same
    as duplicate ratings fed to the reference stack's blocking).

    Rows per bucket are padded to a multiple of the bucket's scan chunk
    (:func:`scan_chunk` — a power of two bounded by ``chunk_elems // width``
    and by the bucket's row count) so the trainer can reshape to
    [nchunks, chunk, w] without tracing-time pads, halving the chunk if the
    rank demands it; padding rows carry ``rows == num_rows`` (out-of-bounds
    ⇒ scatter-dropped).

    ``native``: True forces the threaded C++ bucketizer
    (tpu_als.io.fastbucket — bit-identical output), False forces numpy,
    None (default) uses C++ when the library builds and f32 ratings are
    requested.
    """
    if native or native is None:
        from tpu_als.io import fastbucket

        ok = dtype == np.float32 and fastbucket.available()
        if native and not ok:
            raise RuntimeError(
                "native bucketizer requires float32 vals and a working g++")
        if ok:
            return _build_csr_buckets_native(
                row_idx, col_idx, vals, num_rows, min_width, chunk_elems,
                width_growth)
    row_idx = np.asarray(row_idx, dtype=np.int64)
    col_idx = np.asarray(col_idx, dtype=np.int64)
    vals = np.asarray(vals, dtype=dtype)
    nnz = len(row_idx)
    counts = np.bincount(row_idx, minlength=num_rows).astype(np.int64)

    order = np.argsort(row_idx, kind="stable")
    s_rows = row_idx[order]
    s_cols = col_idx[order]
    s_vals = vals[order]

    uniq, starts, ucounts = np.unique(s_rows, return_index=True, return_counts=True)
    # per-entry: rank of its row among unique rows, and offset within the row
    entry_rank = np.repeat(np.arange(len(uniq)), ucounts)
    entry_off = np.arange(nnz) - starts[entry_rank]

    widths = entity_widths(ucounts, min_width, width_growth)
    buckets = []
    for w in sorted(set(widths.tolist())):
        sel_rows = np.flatnonzero(widths == w)  # indices into uniq
        nb = len(sel_rows)
        nb_pad = padded_bucket_rows(nb, w, chunk_elems)
        rows = np.full(nb_pad, num_rows, dtype=np.int32)
        rows[:nb] = uniq[sel_rows]
        cols = np.zeros((nb_pad, w), dtype=np.int32)
        v = np.zeros((nb_pad, w), dtype=dtype)
        m = np.zeros((nb_pad, w), dtype=dtype)
        # local row position within this bucket for each selected unique row
        local = np.full(len(uniq), -1, dtype=np.int64)
        local[sel_rows] = np.arange(nb)
        emask = local[entry_rank] >= 0
        er = local[entry_rank[emask]]
        eo = entry_off[emask]
        cols[er, eo] = s_cols[emask]
        v[er, eo] = s_vals[emask]
        m[er, eo] = 1.0
        buckets.append(Bucket(rows=rows, cols=cols, vals=v, mask=m))

    return CsrBuckets(
        buckets=buckets,
        num_rows=num_rows,
        counts=counts,
        nnz=nnz,
        chunk_elems=chunk_elems,
    )


def _build_csr_buckets_native(row_idx, col_idx, vals, num_rows, min_width,
                              chunk_elems, width_growth=2.0):
    """Threaded C++ blocking path — same output as the numpy path above."""
    from tpu_als.io import fastbucket

    row_idx = np.asarray(row_idx, dtype=np.int64)
    counts = fastbucket.counts(row_idx, num_rows)
    w_all = entity_widths(counts, min_width, width_growth)
    rated = counts > 0
    layout = []
    bucket_widths = sorted(set(w_all[rated].tolist()))
    for w in bucket_widths:
        nb = int((rated & (w_all == w)).sum())
        layout.append((int(w), nb, padded_bucket_rows(nb, w, chunk_elems)))
    # per-entity bucket index (exact width match; -1 for unrated entities)
    ebucket = np.searchsorted(
        np.asarray(bucket_widths, dtype=np.int64), w_all
    ).astype(np.int32)
    ebucket[~rated] = -1
    raw = fastbucket.fill_buckets(
        row_idx, col_idx, vals, num_rows, counts, ebucket, layout)
    buckets = [Bucket(rows=r, cols=c, vals=v, mask=m) for r, c, v, m in raw]
    return CsrBuckets(
        buckets=buckets,
        num_rows=num_rows,
        counts=counts,
        nnz=len(row_idx),
        chunk_elems=chunk_elems,
    )
