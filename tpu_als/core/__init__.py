from tpu_als.core.ratings import (  # noqa: F401
    Bucket,
    CsrBuckets,
    IdMap,
    build_csr_buckets,
    remap_ids,
)
from tpu_als.core.als import AlsConfig, train, predict  # noqa: F401
from tpu_als.core.foldin import fold_in  # noqa: F401
