"""Jitted incremental fold-in: update touched user factors without a refit.

The reference stack has no streaming path — Spark MLlib requires a full refit
when new ratings arrive (SURVEY.md §3.5).  The north-star (BASELINE.json
configs[3]) replaces that with the standard ALS fold-in: for each touched
user u with rating rows against the *fixed* item factors V,

    u* = (VᵤᵀCᵤVᵤ + λ·n·I)⁻¹ VᵤᵀCᵤp(u)

— exactly one batched half-step restricted to the touched rows, served as a
single jitted kernel.  Shapes are padded to power-of-two (rows and width) by
the stream driver so repeated micro-batches hit the jit cache.
"""

from __future__ import annotations

import functools

import jax

from tpu_als.ops.solve import (
    DEFAULT_JITTER,
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_nnls,
    solve_spd,
)


def fold_in(
    V,
    cols,
    vals,
    mask,
    reg_param,
    implicit_prefs=False,
    alpha=1.0,
    nonnegative=False,
    nnls_sweeps=32,
    YtY=None,
    jitter=DEFAULT_JITTER,
):
    """Solve factors for a batch of touched entities against fixed ``V``.

    cols/vals/mask: [n, w] padded CSR rows (same convention as
    tpu_als.core.ratings).  Returns new factors [n, rank].

    Eager wrapper: probes the solve kernels before tracing (a probe inside
    the jit trace cannot run and would pin the fallback path into the jit
    cache — ops.solve.prewarm_solve), then dispatches to the jitted body.
    """
    from tpu_als.ops.solve import prewarm_solve

    if not nonnegative:
        prewarm_solve(V.shape[-1])
    return _fold_in_jit(V, cols, vals, mask, reg_param,
                        implicit_prefs=implicit_prefs, alpha=alpha,
                        nonnegative=nonnegative, nnls_sweeps=nnls_sweeps,
                        YtY=YtY, jitter=jitter)


@functools.partial(
    jax.jit,
    static_argnames=("implicit_prefs", "nonnegative", "nnls_sweeps", "jitter"),
)
def _fold_in_jit(
    V,
    cols,
    vals,
    mask,
    reg_param,
    implicit_prefs=False,
    alpha=1.0,
    nonnegative=False,
    nnls_sweeps=32,
    YtY=None,
    jitter=DEFAULT_JITTER,
):
    Vg = V[cols]
    if implicit_prefs:
        if YtY is None:
            YtY = compute_yty(V)
        A, b, count = normal_eq_implicit(Vg, vals, mask, reg_param, alpha, YtY)
    else:
        A, b, count = normal_eq_explicit(Vg, vals, mask, reg_param)
    if nonnegative:
        return solve_nnls(A, b, count, sweeps=nnls_sweeps, jitter=jitter)
    return solve_spd(A, b, count, jitter=jitter)
