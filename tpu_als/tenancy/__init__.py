"""Multi-tenant model control plane: many models on one mesh.

``tpu_als.tenancy.registry`` holds tenant identity — each tenant owns a
full single-tenant serving stack (engine, int8 index, optional live
updater) with namespaced publish seq-spaces and tenant-labeled obs.
``tpu_als.tenancy.scheduler`` is the shared admission front door: one
:class:`MultiTenantEngine` with weighted fair-share scheduling, typed
per-tenant shedding (:class:`TenantOverloaded`) and per-batch fault
isolation.  See docs/tenancy.md.
"""

from tpu_als.tenancy.registry import (  # noqa: F401
    GUARDRAIL_MODES,
    DuplicateTenant,
    TenancyError,
    Tenant,
    TenantRegistry,
    TenantSpec,
    UnknownTenant,
)
from tpu_als.tenancy.scheduler import (  # noqa: F401
    FairShareScheduler,
    MultiTenantEngine,
    TenantOverloaded,
)
