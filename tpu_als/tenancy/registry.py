"""Tenant registry: N independent model universes in one process.

Each registered tenant owns the full single-tenant serving stack —
its own :class:`~tpu_als.serving.engine.ServingEngine` (factors, int8
candidate index, admission queue, flight recorder, SLO) and optionally
its own fold-in + :class:`~tpu_als.live.LiveUpdater` pipeline — so the
isolation properties the single-tenant pieces already prove carry over
verbatim:

- **Namespaced seq-spaces.**  Publish sequence numbers live on the
  tenant's engine; tenant A's torn publish can tag only A's index
  stale.  There is no shared generation state to corrupt.
- **Per-tenant budgets.**  Queue depth (``max_queue``), coalescing
  window, deadlines and the latency SLO are all per-tenant knobs on
  the tenant's own batcher/engine; one tenant's overload raises
  :class:`~tpu_als.tenancy.scheduler.TenantOverloaded` naming that
  tenant and sheds only its requests.
- **Attributable obs.**  The engine/updater are constructed with
  ``tenant=<name>``, so every ``serving.*``/``live.*`` series, every
  ``serving_publish``/``live_update`` event and every flight-recorder
  dump carries the tenant — a breach in the shared process is
  attributable from the trail alone.

What IS shared is deliberate: the planner's plan cache (bucket ladder
and live cadence key on device/rank/dtype, not tenant name) and JAX's
process-global compile cache — same-shaped tenants reuse one set of
compiled scoring executables (``plan.resolve_tenant_plan``), the
compile-sharing win that makes N tenants on one mesh cheaper than N
processes.  See docs/tenancy.md.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field

from tpu_als import obs

# tenant names become metric label values and event fields; keep them
# to a slug so downstream tooling (PromQL selectors, file names) never
# needs quoting or escaping
_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,31}$")

GUARDRAIL_MODES = ("off", "abort", "recover")


class TenancyError(RuntimeError):
    """Base class for control-plane failures."""


class UnknownTenant(TenancyError):
    """An operation named a tenant nobody registered; carries
    ``available`` so every surface can list what IS registered."""

    def __init__(self, name, available):
        self.name = name
        self.available = tuple(available)
        super().__init__(
            f"unknown tenant {name!r} (registered: "
            f"{', '.join(self.available) or '<none>'})")


class DuplicateTenant(TenancyError):
    """``register`` was called twice for one name — tenant identity is
    the isolation boundary, so silently replacing a live engine would
    strand its in-flight tickets."""

    def __init__(self, name):
        self.name = name
        super().__init__(f"tenant {name!r} is already registered "
                         "(remove it first)")


@dataclass(frozen=True)
class TenantSpec:
    """Declarative per-tenant serving contract.

    ``weight`` is the fair-share scheduling weight (a weight-2 tenant
    is entitled to twice the served rows of a weight-1 tenant under
    contention); the queue/deadline/SLO fields are the tenant's own
    admission budgets, applied to ITS engine only.  ``buckets=None``
    resolves through the planner per shape-class
    (``plan.resolve_tenant_plan``).  ``guardrail_mode`` is the
    training-side posture the tenant's re-fits run under
    (``resilience.guardrails.scoped``).
    """

    name: str
    weight: float = 1.0
    k: int = 10
    shortlist_k: int = 64
    buckets: tuple = None
    max_queue: int = 1024
    max_wait_s: float = 0.002
    default_deadline_s: float = None
    slo_s: float = None
    freshness_slo_s: float = None
    fold_items: bool = False
    guardrail_mode: str = "abort"
    flight_capacity: int = 64

    def __post_init__(self):
        if not _NAME_RE.match(self.name or ""):
            raise ValueError(
                f"tenant name {self.name!r} must match "
                f"{_NAME_RE.pattern} (it becomes a metric label value)")
        if not self.weight > 0:
            raise ValueError(f"tenant {self.name!r}: weight must be "
                             f"> 0, got {self.weight}")
        if self.guardrail_mode not in GUARDRAIL_MODES:
            raise ValueError(
                f"tenant {self.name!r}: guardrail_mode "
                f"{self.guardrail_mode!r} not in {GUARDRAIL_MODES}")


@dataclass
class Tenant:
    """One admitted tenant: its spec, its engine, and (when live
    updates are attached) its fold-in pipeline.  ``shape_class`` is the
    planner bucketing its plan resolved under — tenants sharing it (at
    equal rank) share compiled executables."""

    spec: TenantSpec
    engine: object
    shape_class: str = "generic"
    foldin: object = None
    updater: object = None
    served_rows: int = 0            # scheduler-maintained goodput
    vtime: float = field(default=0.0, repr=False)   # fair-share clock

    @property
    def name(self):
        return self.spec.name


class TenantRegistry:
    """The control plane's source of truth: name -> :class:`Tenant`.

    ``register`` builds the tenant's engine (tenant-labeled), resolves
    its plan per shape-class, and performs the tenant's FIRST atomic
    publish — a tenant is never registered without a servable model.
    Thread-safe; the scheduler iterates a snapshot.
    """

    def __init__(self):
        self._tenants = {}
        self._reserved = set()
        self._lock = threading.Lock()

    # -- membership ---------------------------------------------------
    def register(self, spec, U, V, *, item_valid=None, quantize=True):
        """Admit one tenant and publish its initial factors.  Returns
        the :class:`Tenant`.  Raises :class:`DuplicateTenant` on a name
        collision, ``ValueError`` on a malformed spec.

        Publish-before-visible: the name is only *reserved* while the
        engine is built and its first generation published; the tenant
        enters the registry (scheduler snapshots, ``get``/``remove``)
        strictly AFTER the publish succeeds.  A reader can therefore
        never observe a registered tenant without a servable model, and
        a failed publish leaves nothing behind but a released
        reservation — no zombie tenant to ``remove``."""
        import numpy as np

        from tpu_als import plan as _plan
        from tpu_als.serving.engine import ServingEngine

        with self._lock:
            if spec.name in self._tenants or spec.name in self._reserved:
                raise DuplicateTenant(spec.name)
            self._reserved.add(spec.name)
        engine = None
        try:
            U = np.asarray(U, dtype=np.float32)
            V = np.asarray(V, dtype=np.float32)
            tplan = _plan.resolve_tenant_plan(
                rank=U.shape[1], n_users=U.shape[0], n_items=V.shape[0],
                requested_buckets=spec.buckets)
            engine = ServingEngine(
                k=spec.k, buckets=tplan["buckets"],
                shortlist_k=spec.shortlist_k, max_queue=spec.max_queue,
                max_wait_s=spec.max_wait_s,
                default_deadline_s=spec.default_deadline_s,
                slo_s=spec.slo_s, flight_capacity=spec.flight_capacity,
                tenant=spec.name)
            engine.publish(U, V, item_valid=item_valid,
                           quantize=quantize)
            tenant = Tenant(spec=spec, engine=engine,
                            shape_class=tplan["shape_class"])
        except BaseException:
            if engine is not None:
                engine.stop()
            with self._lock:
                self._reserved.discard(spec.name)
            raise
        with self._lock:
            self._reserved.discard(spec.name)
            self._tenants[spec.name] = tenant
            n_now = len(self._tenants)
        obs.gauge("tenancy.tenants", n_now)
        obs.emit("tenant_registered", tenant=spec.name,
                 users=int(U.shape[0]), items=int(V.shape[0]),
                 shape_class=tenant.shape_class,
                 weight=spec.weight)
        return tenant

    def attach_live(self, name, foldin, **updater_kwargs):
        """Wire a live fold-in → publish pipeline onto a registered
        tenant: its own :class:`LiveUpdater` over ``foldin``, labeled
        with the tenant's name (the updater is created but NOT started
        — lifecycle belongs to the caller/engine front door)."""
        from tpu_als.live import LiveUpdater

        tenant = self.get(name)
        if tenant.updater is not None:
            raise TenancyError(
                f"tenant {name!r} already has a live updater attached")
        updater_kwargs.setdefault("fold_items", tenant.spec.fold_items)
        if tenant.spec.freshness_slo_s is not None:
            updater_kwargs.setdefault("slo_s",
                                      tenant.spec.freshness_slo_s)
        tenant.foldin = foldin
        tenant.updater = LiveUpdater(tenant.engine, foldin,
                                     tenant=name, **updater_kwargs)
        return tenant.updater

    def remove(self, name):
        """Deregister a tenant: stop its updater and engine, drop the
        reference (releasing its device buffers)."""
        with self._lock:
            tenant = self._tenants.pop(name, None)
            n_now = len(self._tenants)
        if tenant is None:
            raise UnknownTenant(name, self.names())
        if tenant.updater is not None:
            tenant.updater.stop()
        tenant.engine.stop()
        obs.gauge("tenancy.tenants", n_now)
        obs.emit("tenant_removed", tenant=name)
        return tenant

    # -- lookup -------------------------------------------------------
    def get(self, name):
        with self._lock:
            tenant = self._tenants.get(name)
        if tenant is None:
            raise UnknownTenant(name, self.names())
        return tenant

    def names(self):
        with self._lock:
            return tuple(self._tenants)

    def tenants(self):
        """Snapshot of the registered tenants (safe to iterate while
        register/remove proceed on other threads)."""
        with self._lock:
            return tuple(self._tenants.values())

    def __len__(self):
        with self._lock:
            return len(self._tenants)

    def __contains__(self, name):
        with self._lock:
            return name in self._tenants

    def shape_classes(self):
        """shape_class -> tenant names, the compile-sharing report."""
        out = {}
        for t in self.tenants():
            out.setdefault(t.shape_class, []).append(t.name)
        return out
