"""Fair-share admission front door: one scheduler, N tenant engines.

The single-tenant :class:`ServingEngine` pairs one background thread
with one queue.  N tenants could run N threads, but then the OS
scheduler — not the control plane — decides who gets the device under
contention, and a flooded tenant's thread can starve its neighbors'
score calls.  Instead the :class:`MultiTenantEngine` runs ONE scheduler
thread over every tenant's queue and makes the sharing policy explicit:

- **Stride (weighted fair-share) scheduling.**  Each tenant carries a
  virtual time advanced by ``served_rows / weight`` whenever one of its
  micro-batches is scored; each round the backlogged tenant with the
  minimum virtual time is served next.  Over any contention window a
  tenant's share of served rows converges to ``weight / Σweights`` —
  a flooding tenant cannot buy more than its share, it can only fill
  its own queue and shed.
- **Typed per-tenant shedding.**  Admission rides each tenant's own
  bounded :class:`MicroBatcher`; at capacity the submit raises
  :class:`TenantOverloaded` (an :class:`Overloaded` subclass naming
  the tenant), counted into ``serving.shed{tenant=...}``.  A neighbor
  with a drained queue is untouched.
- **Fault isolation per batch.**  A tenant batch that raises (an
  injected ``serving.score`` fault, a poisoned model) fails only that
  batch's tickets (the single-engine ``_run`` contract) and counts
  ``tenancy.batch_errors{tenant=...}``; the scheduler round continues
  with the next tenant.
- **Lazy virtual-time admission.**  A tenant that joins (or idles) is
  admitted at the CURRENT minimum virtual time, not zero — otherwise
  a newcomer would monopolize the mesh "catching up" on time it never
  queued for (the classic stride-scheduler join rule).

See docs/tenancy.md for the policy walkthrough and the
``tenant-isolation`` scenario for the proof under faults.
"""

from __future__ import annotations

import threading

from tpu_als import obs
from tpu_als.obs import tracing
from tpu_als.resilience import faults
from tpu_als.serving.batcher import Overloaded
from tpu_als.tenancy.registry import TenantRegistry, TenantSpec

__all__ = ["FairShareScheduler", "MultiTenantEngine",
           "TenantOverloaded"]


class TenantOverloaded(Overloaded):
    """One tenant's admission queue is at capacity.  Subclasses the
    serving :class:`Overloaded` so existing back-off handlers keep
    working; carries ``tenant`` so load balancers shed per tenant, not
    per process."""

    def __init__(self, tenant, message):
        self.tenant = tenant
        super().__init__(f"tenant {tenant!r}: {message}")


class FairShareScheduler:
    """Stride scheduling over the registry's tenants.

    Pure policy, no threads: :meth:`pick` selects the backlogged tenant
    with minimum virtual time (ties break by name, deterministically);
    :meth:`charge` advances the served tenant's clock by
    ``rows / weight``.  Virtual times live on the :class:`Tenant`
    records, so the goodput accounting and the policy state are one
    structure; the scheduler itself carries only the global virtual
    clock (the vtime of the last tenant it picked) and the set of
    tenants active in the previous round.
    """

    def __init__(self):
        self._clock = 0.0
        self._active = set()

    def pick(self, backlogged):
        """The next tenant to serve among ``backlogged`` (non-empty).
        A tenant entering the rotation — newly registered, or returning
        from idle — is floored to the global virtual clock first:
        joining (or sitting idle) must not bank retroactive share.
        Tenants that stayed in the rotation keep their earned deficit
        untouched, so weighted shares hold exactly under contention."""
        for t in backlogged:
            if t.name not in self._active and t.vtime < self._clock:
                t.vtime = self._clock
        self._active = {t.name for t in backlogged}
        chosen = min(backlogged, key=lambda t: (t.vtime, t.name))
        self._clock = max(self._clock, chosen.vtime)
        return chosen

    def charge(self, tenant, rows):
        tenant.vtime += rows / tenant.spec.weight
        tenant.served_rows += rows
        obs.counter("tenancy.served_rows", rows, tenant=tenant.name)


class MultiTenantEngine:
    """Many models behind one admission front door.

    ``submit``/``recommend`` take the tenant name first; publishes and
    live updates are delegated to the named tenant's own engine/updater
    (seq-spaces stay per-tenant).  One scheduler thread drives every
    tenant's batcher through :class:`FairShareScheduler`; the per-batch
    serve path is the single-tenant ``ServingEngine.serve_batch``,
    unchanged — this class adds policy, not scoring.
    """

    def __init__(self, registry=None, idle_wait_s=0.05):
        self.registry = registry if registry is not None \
            else TenantRegistry()
        self.scheduler = FairShareScheduler()
        self.idle_wait_s = float(idle_wait_s)
        self._round = 0      # monotonic fair-share pick counter (traced)
        self._work = threading.Event()
        self._stopping = threading.Event()
        self._thread = None

    # -- tenant lifecycle ---------------------------------------------
    def add_tenant(self, spec, U, V, **publish_kwargs):
        """Register a tenant (see :meth:`TenantRegistry.register`);
        ``spec`` may be a :class:`TenantSpec` or a plain name."""
        if isinstance(spec, str):
            spec = TenantSpec(name=spec)
        return self.registry.register(spec, U, V, **publish_kwargs)

    def remove_tenant(self, name):
        return self.registry.remove(name)

    def attach_live(self, name, foldin, **updater_kwargs):
        """Attach and START the tenant's live fold-in pipeline (the
        front door owns running tenants' lifecycles)."""
        updater = self.registry.attach_live(name, foldin,
                                            **updater_kwargs)
        updater.start()
        return updater

    def tenant(self, name):
        return self.registry.get(name)

    # -- per-tenant model lifecycle -----------------------------------
    def publish(self, name, U, V, **kwargs):
        """Atomic publish into ONE tenant's seq-space."""
        return self.registry.get(name).engine.publish(U, V, **kwargs)

    def publish_update(self, name, U, V, **kwargs):
        """Incremental (fold-in) publish into one tenant's seq-space;
        returns ``(seq, mode)``."""
        return self.registry.get(name).engine.publish_update(
            U, V, **kwargs)

    def published_seq(self, name):
        return self.registry.get(name).engine.published_seq

    def warmup(self, name=None):
        """Compile the scoring executables (one tenant, or all).
        Same-shaped tenants hit JAX's process-global compile cache
        after the first — the compile-sharing win ``resolve_tenant_
        plan`` keys for."""
        tenants = ([self.registry.get(name)] if name is not None
                   else self.registry.tenants())
        for t in tenants:
            t.engine.warmup()

    # -- request path -------------------------------------------------
    def submit(self, name, payload, k=None, deadline_s=None):
        """Admit one request for ``name``; returns its ticket.  Raises
        :class:`UnknownTenant` for an unregistered name and
        :class:`TenantOverloaded` when THAT tenant's queue is full —
        the refusal never touches a neighbor's budget."""
        tenant = self.registry.get(name)
        try:
            ticket = tenant.engine.submit(payload, k=k,
                                          deadline_s=deadline_s)
        except Overloaded as e:
            raise TenantOverloaded(name, str(e)) from None
        self._work.set()
        return ticket

    def recommend(self, name, payload, k=None, deadline_s=None,
                  timeout=None):
        """Submit + block: ``(scores, indices)`` for one request."""
        return self.submit(name, payload, k=k,
                           deadline_s=deadline_s).result(timeout)

    # -- scheduler loop -----------------------------------------------
    def start(self):
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stopping.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpu-als-tenancy", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout_s=10.0):
        """Stop every tenant's updater, close every admission queue,
        drain in-flight batches, join the scheduler."""
        for t in self.registry.tenants():
            if t.updater is not None:
                t.updater.stop()
            t.engine.batcher.close()
        self._stopping.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(drain_timeout_s)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _backlogged(self):
        return [t for t in self.registry.tenants()
                if t.engine.batcher.depth() > 0]

    def _run(self):
        while True:
            served = self._drain_round()
            if not served:
                if self._stopping.is_set() and not self._backlogged():
                    return
                self._work.wait(self.idle_wait_s)
                self._work.clear()

    def _drain_round(self):
        """Serve until every queue is empty, one fair-share pick per
        micro-batch.  Returns whether anything was served."""
        served_any = False
        while True:
            backlogged = self._backlogged()
            if not backlogged:
                return served_any
            tenant = self.scheduler.pick(backlogged)
            # timeout=0: we just saw depth > 0; a race to empty simply
            # returns None and the round re-checks the backlog
            batch = tenant.engine.batcher.next_batch(timeout=0)
            if not batch:
                continue
            served_any = True
            # link every ticket's trail to the fair-share pick that
            # drained it: a request slow because ANOTHER tenant held
            # the rounds is explainable from this hop alone
            self._round += 1
            for t in batch:
                if t.trace is not None:
                    t.trace = tracing.record_span(
                        t.trace, "tenancy.round", round=self._round,
                        batch_rows=len(batch))
            try:
                tenant.engine.serve_batch(batch)
            except BaseException as e:  # noqa: BLE001 — isolate the tenant
                # the single-engine _run contract, scoped to ONE
                # tenant: its undone tickets fail, its error is
                # counted against it, and the round moves on — a
                # neighbor's batch never sees this exception (the
                # tenant label rides the engine ring structurally)
                for t in batch:
                    if not t.done():
                        t.fail(e)
                        if t.trace is not None:
                            t.trace = tracing.record_span(
                                t.trace, "serve.score", status="failed",
                                error=type(e).__name__)
                        tenant.engine.flight.record(
                            "failed",
                            {"admission": t.t_admit,
                             "queue_wait": (t.t_dequeue - t.t_submit
                                            if t.t_dequeue else None)},
                            error=type(e).__name__,
                            trace_id=(t.trace.trace_id
                                      if t.trace is not None else None))
                obs.counter("tenancy.batch_errors", tenant=tenant.name)
                if not isinstance(e, faults.InjectedFault):
                    obs.emit("warning", what="tenancy.batch",
                             reason=f"tenant {tenant.name!r}: "
                                    f"{type(e).__name__}: {e}")
            self.scheduler.charge(tenant, len(batch))
