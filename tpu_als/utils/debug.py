"""Numerical-safety tooling (SURVEY.md §5.2).

The reference stack gets race freedom structurally (JVM memory safety +
immutable RDD lineage) and numerical issues surface as NaN RMSE printouts.
JAX's functional purity gives the same structural race freedom; this module
adds the active checks:

  * :func:`debug_mode` — context manager enabling ``jax_debug_nans`` (and
    optionally disabling jit) so the first NaN-producing primitive raises
    with a usable stack instead of poisoning the factors silently.
  * :func:`checked_predict` — ``checkify``-wrapped scoring kernel that turns
    out-of-range id gathers into reported errors instead of clamped reads
    (the production ``predict`` clamps + masks to NaN; this is the test-mode
    oracle that the masking is actually hiding nothing).
  * :func:`assert_all_finite` — host-side factor audit for callbacks.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.experimental import checkify

import numpy as np


@contextmanager
def debug_mode(nans=True, disable_jit=False):
    """Enable fail-fast numerics for the enclosed block.

    ``nans=True`` makes any primitive producing NaN raise immediately
    (re-running the offending op un-jitted for a precise traceback);
    ``disable_jit=True`` additionally runs everything op-by-op.
    """
    prev_nans = jax.config.jax_debug_nans
    try:
        if nans:
            jax.config.update("jax_debug_nans", True)
        if disable_jit:
            with jax.disable_jit():
                yield
        else:
            yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)


def _predict_checked(U, V, u_idx, i_idx):
    checkify.check(jnp.all(u_idx >= 0), "negative user index")
    checkify.check(jnp.all(u_idx < U.shape[0]),
                   "user index out of range")
    checkify.check(jnp.all(i_idx >= 0), "negative item index")
    checkify.check(jnp.all(i_idx < V.shape[0]),
                   "item index out of range")
    return jnp.einsum("nr,nr->n", U[u_idx], V[i_idx])


_checked_predict = checkify.checkify(jax.jit(_predict_checked))


def checked_predict(U, V, u_idx, i_idx):
    """Gather-dot scoring with hard index-bounds checks.

    Returns the scores; raises ``checkify.JaxRuntimeError`` on any
    out-of-range id.  Use in tests/debugging; the production path
    (tpu_als.core.als.predict) masks invalid ids to NaN instead.
    """
    err, out = _checked_predict(U, V, jnp.asarray(u_idx), jnp.asarray(i_idx))
    err.throw()
    return out


def assert_all_finite(iteration, U, V):
    """Fit-callback form: raise if any factor entry is non-finite."""
    for name, X in (("U", U), ("V", V)):
        bad = ~np.isfinite(np.asarray(X))
        if bad.any():
            raise FloatingPointError(
                f"non-finite {name} factors at iteration {iteration}: "
                f"{int(bad.sum())} entries (first row "
                f"{int(np.argwhere(bad)[0][0])})")
