"""Observability: structured training logs + profiling hooks.

The reference stack's observability is the Spark UI / SparkListener event
bus / Codahale metrics sinks (SURVEY.md §5.1/§5.5).  The TPU-native
equivalents here:

- :class:`IterationLogger` — a ``callback`` for the training loops that
  emits one structured JSON line per iteration (iteration, wall time,
  probe RMSE, factor norms) to a file and/or stderr, the analog of
  per-stage metrics.  The process-wide metrics/event registry lives in
  :mod:`tpu_als.obs`; this logger is the per-fit convergence view.
- :func:`trace` — context manager over ``jax.profiler.trace`` producing a
  TensorBoard/Perfetto trace of the jitted steps (the analog of the Spark
  UI's stage timeline).
- ``jax.named_scope`` annotations are applied inside the half-step phases
  so traces show gather/normal-eq/solve spans.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time

import numpy as np


class IterationLogger:
    """Per-iteration structured logging; usable as ``train(callback=...)``.

    probe: optional (u_idx, i_idx, ratings) triple of dense indices — RMSE
    on it is logged each iteration (the convergence signal the reference
    app reads off its evaluator).

    Usable as a context manager (``with IterationLogger(path=p) as log:``);
    the file is opened lazily on the first record, so constructing a
    logger that never fires touches no filesystem state.
    """

    def __init__(self, probe=None, stream=sys.stderr, path=None, tag="als"):
        self.probe = probe
        self.stream = stream
        self.path = path
        self.tag = tag
        self._t_last = self._t0 = time.perf_counter()
        self._file = None
        self._closed = False
        self.records = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __call__(self, iteration, U, V):
        now = time.perf_counter()
        rec = {
            "tag": self.tag,
            "iteration": int(iteration),
            "seconds": round(now - self._t_last, 4),
            "total_seconds": round(now - self._t0, 4),
            "u_norm": float(np.linalg.norm(np.asarray(U)) /
                            max(1, U.shape[0]) ** 0.5),
            "v_norm": float(np.linalg.norm(np.asarray(V)) /
                            max(1, V.shape[0]) ** 0.5),
        }
        self._t_last = now
        if self.probe is not None:
            u, i, r = self.probe
            pred = np.einsum("nr,nr->n", np.asarray(U)[u], np.asarray(V)[i])
            rec["probe_rmse"] = float(np.sqrt(np.mean((pred - r) ** 2)))
        self.records.append(rec)
        line = json.dumps(rec)
        if self.stream is not None:
            print(line, file=self.stream, flush=True)
        if self.path is not None and not self._closed:
            if self._file is None:
                self._file = open(self.path, "a")
            self._file.write(line + "\n")
            self._file.flush()

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._file is not None:
            self._file.close()
            self._file = None


_trace_active = False


def _trace_warn(what, reason):
    """Record a degraded-profiling condition without killing the run: one
    structured warning event (when a registry is live) + a stderr line."""
    from tpu_als import obs

    obs.emit("warning", what=what, reason=str(reason))
    print(f"observe.trace: {what}: {reason}", file=sys.stderr)


@contextlib.contextmanager
def trace(logdir):
    """Profile a block into ``logdir`` (TensorBoard / Perfetto readable) —
    usage: ``with observe.trace('/tmp/trace'): step(U, V)``.

    Degrades to a no-op (with a ``warning`` event) instead of raising
    when a trace is already active in this process or the profiler
    cannot start — a failed profiling request must never take down the
    training run it was meant to observe.
    """
    global _trace_active

    if _trace_active:
        _trace_warn("trace_skipped",
                    "a profiler trace is already active in this process")
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception as err:
        _trace_warn("trace_unavailable", err)
        yield
        return
    _trace_active = True
    try:
        yield
    finally:
        _trace_active = False
        try:
            jax.profiler.stop_trace()
        except Exception as err:
            _trace_warn("trace_stop_failed", err)
